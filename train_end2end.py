#!/usr/bin/env python
"""End-to-end structure training driver — the runnable realization of the
reference's train_end2end.py design sketch (which crashes as written;
SURVEY.md S2.5). Full pipeline: trunk -> distogram -> MDS -> sidechains ->
SE(3) refine -> Kabsch/RMSD loss, compiled as one program.

Usage:
  python train_end2end.py data.crop_len=64 model.depth=1 train.num_steps=1000
"""

import sys

import alphafold2_tpu
from alphafold2_tpu.config import Config, DataConfig, ModelConfig, parse_cli


def main(argv):
    alphafold2_tpu.setup_platform()  # AF2TPU_PLATFORM=cpu to force host
    from alphafold2_tpu.parallel.distributed import initialize

    initialize()  # multi-host process group (no-op single-process)
    base = Config(
        model=ModelConfig(dim=256, depth=1),
        data=DataConfig(crop_len=64),  # distogram runs over 3L atom tokens
    )
    cfg = parse_cli(argv, base)
    print("config:", cfg.to_json())
    from alphafold2_tpu.train.end2end import train_end2end

    train_end2end(cfg)


if __name__ == "__main__":
    main(sys.argv[1:])
