"""Fused flash-attention path for the dense axial/cross attention hot loops.

The axial trunk's row/column passes materialize (B*N, H, N, N) logits in the
naive formulation — at crop 384 that dominates HBM traffic. On TPU this
module routes dense attention through the Pallas flash-attention kernels
shipped with JAX (``jax.experimental.pallas.ops.tpu.flash_attention`` —
fused QK^T/softmax/AV with full custom-VJP backward), so the N^2 attention
matrix never hits HBM. Padding masks are expressed as segment ids (valid=1,
pad=0: cross-segment pairs are masked inside the kernel).

Used automatically by :class:`ops.attention.Attention` on TPU backends for
the un-tied paths, including KV-compressed cross-attention (the kernel
sees the already-compressed k/v and the pooled mask); everything falls
back to the jnp dense path off-TPU or if the kernel rejects the shape
(trace-time validation is caught and logged once).
"""

from __future__ import annotations

import warnings
from typing import Optional

import jax
import jax.numpy as jnp

_WARNED = set()


def warn_once(key: str, message: str) -> None:
    """De-duplicated warning — trace-time fallbacks fire per call site but
    should reach the user once (shared by the flash and sparse modules)."""
    if key not in _WARNED:
        _WARNED.add(key)
        warnings.warn(message)


def flash_available() -> bool:
    return jax.default_backend() == "tpu"


def flash_attention(
    q: jnp.ndarray,  # (B, H, Nq, D)
    k: jnp.ndarray,  # (B, H, Nk, D)
    v: jnp.ndarray,
    q_mask: Optional[jnp.ndarray] = None,  # (B, Nq) bool
    kv_mask: Optional[jnp.ndarray] = None,  # (B, Nk) bool
    sm_scale: float = 1.0,
) -> Optional[jnp.ndarray]:
    """Fused attention via the stock Pallas TPU kernel.

    Returns None when the kernel cannot take this call (wrong backend or
    shape constraints) — the caller falls back to the dense jnp path.
    """
    if not flash_available():
        return None
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        SegmentIds,
        flash_attention as _fa,
    )

    b, h, nq, d = q.shape
    nk = k.shape[2]

    # short sequences (BOTH axes < one 128 block) use the dense path by
    # design: at these sizes the dense attention matrix is trivially small
    # and the kernel's MIN_BLOCK_SIZE tiling overhead dominates. With one
    # long axis (e.g. N^2 queries against a compressed context) the fused
    # path still pays off — the short axis is padded up to a block below.
    if nq < 128 and nk < 128:
        return None

    # the kernel's block verification requires both sequence axes divisible
    # by the 128-lane block (e.g. compressed-KV cross-attention lengths
    # rarely are): pad with mask-excluded positions and slice the output
    pad_q = (-nq) % 128
    pad_k = (-nk) % 128
    need_segments = (
        q_mask is not None or kv_mask is not None or pad_q or pad_k
    )
    segment_ids = None
    if need_segments:
        qs = (
            q_mask.astype(jnp.int32)
            if q_mask is not None
            else jnp.ones((b, nq), jnp.int32)
        )
        ks = (
            kv_mask.astype(jnp.int32)
            if kv_mask is not None
            else jnp.ones((b, nk), jnp.int32)
        )
        if pad_q:
            qs = jnp.pad(qs, ((0, 0), (0, pad_q)))
        if pad_k:
            ks = jnp.pad(ks, ((0, 0), (0, pad_k)))
        segment_ids = SegmentIds(q=qs, kv=ks)
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    try:
        out = _fa(q, k, v, segment_ids=segment_ids, sm_scale=sm_scale)
    except (ValueError, NotImplementedError) as e:
        warn_once(
            str(e)[:80],
            f"flash attention unavailable for shape q={q.shape} "
            f"k={k.shape}: {e}; using dense attention",
        )
        return None
    return out[:, :, :nq] if pad_q else out
