"""KernelPolicy: one switchboard for every attention kernel in the tree.

Before this module each kernel had its own ad-hoc gate — the stock flash
wrapper keyed on ``use_flash``/backend, the block-sparse Pallas kernel on
``SparseAttention.use_pallas``/``config.backend``, and the new fused
tied-row/axial kernels would have added a third convention. Now ONE policy
object answers "which implementation serves this attention shape", selected
per process (``AF2TPU_KERNELS``), per engine (``ServeConfig.kernels``) or
per trace (:func:`use_kernel_policy`), and its identity threads into serve
compile records, bench records and the regression gate's comparability
check — a kernel change is a visible key, never silent drift.

Policy fields and choices (every field defaults to ``"auto"``):

- ``tied_row``: ``auto`` | ``pallas`` | ``dense`` — the tied-row MSA
  attention path in ``Attention.__call__``. ``auto`` = the fused Pallas
  kernel (ops/pallas/tied_row.py) on TPU backends, dense einsum elsewhere.
- ``axial``: ``auto`` | ``pallas`` | ``stock`` | ``dense`` — the per-device
  attended-axis pass of the grid-native axial attention
  (``Attention.grid_axial`` / ``AxialAttention``). ``auto`` keeps the
  proven chain (stock jax flash kernel on TPU, chunked/dense off-TPU);
  ``pallas`` selects the in-repo fused kernel (ops/pallas/axial.py) —
  compiled on TPU, interpret-mode elsewhere.
- ``flash``: ``auto`` | ``on`` | ``off`` — the stock-kernel fast path for
  the flat dense/cross attention in ``Attention.__call__`` (the existing
  ``use_flash=None`` auto policy; an explicit module-level ``use_flash``
  bool still wins for back-compat).
- ``block_sparse``: ``auto`` | ``pallas`` | ``jnp`` | ``splash`` — the
  ``SparseAttention`` backend. Explicit ``use_pallas`` bools and a
  non-"auto" ``BlockSparseConfig.backend`` still win (they are reviewed
  per-module choices); the policy refines the remaining auto case.

Spec syntax (env var and ``ServeConfig.kernels``)::

    AF2TPU_KERNELS="tied_row=pallas,axial=pallas"
    AF2TPU_KERNELS="flash=off,block_sparse=jnp"

Consulted at TRACE time only — like ``parallel.sharding.active_mesh``, the
policy is part of the program being built, so engines activate it around
``.lower()`` and bake the resolved description into the executable's cache
key and compile record.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from contextlib import contextmanager
from typing import Optional

_CHOICES = {
    "tied_row": ("auto", "pallas", "dense"),
    "axial": ("auto", "pallas", "stock", "dense"),
    "flash": ("auto", "on", "off"),
    "block_sparse": ("auto", "pallas", "jnp", "splash"),
}

ENV_VAR = "AF2TPU_KERNELS"


@dataclasses.dataclass(frozen=True)
class KernelPolicy:
    """Which implementation serves each attention shape (see module doc)."""

    tied_row: str = "auto"
    axial: str = "auto"
    flash: str = "auto"
    block_sparse: str = "auto"

    def __post_init__(self):
        for field, choices in _CHOICES.items():
            value = getattr(self, field)
            if value not in choices:
                raise ValueError(
                    f"kernel policy {field}={value!r}; choices: {choices}"
                )

    def describe(self) -> str:
        """Stable short identity for records/keys: the non-default fields
        as ``field=value`` comma-joined, or ``"auto"`` when fully default —
        mirrors ``describe_mesh``'s empty-when-absent convention so records
        without any policy override stay comparable to old baselines."""
        parts = [
            f"{f}={getattr(self, f)}"
            for f in _CHOICES
            if getattr(self, f) != "auto"
        ]
        return ",".join(parts) if parts else "auto"


def parse_policy(spec: Optional[str]) -> KernelPolicy:
    """``"tied_row=pallas,axial=dense"`` -> KernelPolicy. Empty/None/"auto"
    -> the all-auto policy. Unknown fields or values raise (a typo'd kernel
    selection must be loud, not a silent fallback to stock XLA)."""
    if not spec or spec.strip() == "auto":
        return KernelPolicy()
    fields: dict = {}
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        name, sep, value = item.partition("=")
        if not sep or name not in _CHOICES:
            raise ValueError(
                f"bad kernel policy entry {item!r} in {spec!r}; known "
                f"fields: {sorted(_CHOICES)}"
            )
        fields[name] = value.strip()
    return KernelPolicy(**fields)


class _ThreadState(threading.local):
    policy: Optional[KernelPolicy] = None


_STATE = _ThreadState()
_ENV_CACHE: dict = {}


def policy_from_env() -> KernelPolicy:
    spec = os.environ.get(ENV_VAR, "")
    hit = _ENV_CACHE.get(spec)
    if hit is None:
        hit = _ENV_CACHE[spec] = parse_policy(spec)
    return hit


def current_policy() -> KernelPolicy:
    """The active policy on this (tracing) thread: an explicit
    :func:`use_kernel_policy` context wins, else the process-wide
    ``AF2TPU_KERNELS`` env policy (all-auto when unset)."""
    pol = _STATE.policy
    return pol if pol is not None else policy_from_env()


@contextmanager
def use_kernel_policy(policy: Optional[KernelPolicy]):
    """Activate ``policy`` for traces on this thread (None = no-op). The
    serve engine wraps its AOT ``.lower()`` in this so per-engine kernel
    choice composes with the env default."""
    if policy is None:
        yield
        return
    prev = _STATE.policy
    _STATE.policy = policy
    try:
        yield
    finally:
        _STATE.policy = prev


# ------------------------------------------------------------- resolution


def resolve_tied_row(policy: Optional[KernelPolicy] = None) -> str:
    """"pallas" | "dense" for the tied-row MSA path. auto -> the fused
    kernel on TPU (the trunk hot path this policy exists to fuse), dense
    elsewhere (the CPU-mesh serve/train graphs — and their committed
    contract fingerprints — stay byte-identical unless opted in)."""
    import jax

    choice = (policy or current_policy()).tied_row
    if choice == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "dense"
    return choice


def resolve_axial(policy: Optional[KernelPolicy] = None) -> str:
    """"pallas" | "stock" | "dense" for the grid-axial per-device pass.
    auto -> "stock" (the existing flash-on-TPU / chunked-off-TPU chain);
    "pallas" opts into the in-repo fused kernel."""
    choice = (policy or current_policy()).axial
    return "stock" if choice == "auto" else choice


def resolve_flash(policy: Optional[KernelPolicy] = None) -> bool:
    """Whether the flat dense paths may try the stock flash kernel (the
    ``use_flash=None`` auto case). "on" still requires a TPU backend —
    the wrapper declines and falls back off-TPU exactly as before."""
    from alphafold2_tpu.ops.flash import flash_available

    choice = (policy or current_policy()).flash
    if choice == "off":
        return False
    return flash_available()


def resolve_block_sparse(policy: Optional[KernelPolicy] = None) -> str:
    """"pallas" | "jnp" | "splash" for SparseAttention's remaining auto
    case (explicit use_pallas / config.backend win upstream of this)."""
    import jax

    choice = (policy or current_policy()).block_sparse
    if choice == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "jnp"
    return choice
