"""Fused tied-row MSA attention (MSA-Transformer style) as a Pallas kernel.

Tied-row attention shares ONE attention matrix across all R MSA rows:

    dots[b, h, i, j] = sum_r q[b, r, i, h, :] . k[b, r, j, h, :]
    out[b, r, i, h, :] = sum_j softmax(dots)[b, h, i, j] * v[b, r, j, h, :]

The dense path (ops/attention.py tied branch) materializes the full
(B, H, N, N) logits. The fused form rests on an algebraic identity: the
row sum in the logits is a single contraction over a fused (row, head_dim)
feature axis —

    dots[b, h, i, j] = <q'[b, h, i, :], k'[b, h, j, :]>,
    q'[b, h, i, (r, d)] = q[b, r, i, h, d]

— and the output is likewise one P @ V' with V' fused the same way. Tied
attention IS flash attention with head dim R*D, so this module folds the
row axis into the feature axis (two linear relayouts, nothing quadratic)
and runs the shared online-softmax kernels of :mod:`axial` with the tie
scale pre-applied to q. The N^2 logits stay in VMEM; HBM traffic is
O(R * N * D) instead of O(H * N^2).

Masking matches the dense tied path's abstention semantics: the caller
pre-zeroes padded (row, position) q/k/v entries (they abstain from the
shared logit sum exactly), passes the SHARED column mask as ``kv_mask``
(masked columns get NEG_INF bias) and the voting-row count as
``tie_scale`` — a traced per-batch array folded into q before the kernel,
so no scalar plumbing reaches the kernel. Masked queries produce zeros
(flash convention; the dense path gives them uniform attention — padded
rows are downstream-masked everywhere this runs).

VMEM bound: the fused feature axis R*D must fit a (128, R*D) f32 tile 4x
over (q/k/v/acc) — R*D <= ~4096 covers every MSA depth this model admits
(constants.MAX_NUM_MSA rows at dim_head 64 is what a caller could ask
for; the serve/train configs sit far below it).

Gradient support comes through :func:`axial.fused_attention`'s custom VJP;
the fold/unfold relayouts are plain differentiable jnp ops. Oracle-diff
(values and grads, masked + padded + ragged-row cases) in
tests/test_pallas_kernels.py; Mosaic-lowered by analysis/lowering.py.
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp

from alphafold2_tpu.ops.pallas.axial import fused_attention


def tied_row_attention(
    q: jnp.ndarray,  # (B, R, Nq, H, D) — padded entries pre-zeroed
    k: jnp.ndarray,  # (B, R, Nk, H, D)
    v: jnp.ndarray,
    q_mask: Optional[jnp.ndarray] = None,  # (B, Nq) SHARED query mask
    kv_mask: Optional[jnp.ndarray] = None,  # (B, Nk) SHARED column mask
    sm_scale: float = 1.0,
    tie_scale: Union[None, float, jnp.ndarray] = None,  # None -> R**-0.5;
    # or a per-batch voting-row scale, any shape broadcastable to (B,)
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Fused tied-row attention; returns (B, R, Nq, H, D).

    Exactly the dense tied contraction of ops/attention.py (one attention
    matrix per (batch, head), r^-0.5-style tie scaling) computed without
    materializing the (B, H, Nq, Nk) logits in HBM."""
    b, r, nq, h, d = q.shape
    if tie_scale is None:
        tie_scale = r**-0.5
    scale = jnp.asarray(tie_scale, jnp.float32).reshape(b, 1, 1, 1, 1) \
        if getattr(tie_scale, "ndim", 0) else jnp.float32(tie_scale)
    # pre-scale q: the kernel runs with sm_scale baked statically, and the
    # (possibly traced, per-batch) tie scale folds in here — mathematically
    # identical since the logits are linear in q
    q = (q.astype(jnp.float32) * scale).astype(q.dtype)

    def fold(t):  # (B, R, N, H, D) -> (B, H, N, R*D)
        n = t.shape[2]
        return jnp.transpose(t, (0, 3, 2, 1, 4)).reshape(b, h, n, r * d)

    out = fused_attention(
        fold(q), fold(k), fold(v),
        q_mask=q_mask, kv_mask=kv_mask, sm_scale=sm_scale,
        interpret=interpret,
    )  # (B, H, Nq, R*D)
    out = out.reshape(b, h, nq, r, d)
    return jnp.transpose(out, (0, 3, 2, 1, 4))  # (B, R, Nq, H, D)
