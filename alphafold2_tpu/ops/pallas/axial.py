"""Pallas TPU kernel: fused dense (flash) attention for the trunk hot paths.

The trunk's real FLOPs live in two dense attention shapes — the axial
row/col passes over the N^2 pair grid and tied-row MSA attention — and both
lowered to stock XLA dense attention (full (.., Nq, Nk) logits in HBM)
everywhere the stock jax kernel was not available. This module is the
in-repo fused answer, same proven idioms as ``block_sparse.py``:

- grid = (batch*heads, q_blocks, kv_blocks); online-softmax (flash)
  accumulation in VMEM scratch across the innermost kv axis, f32
  accumulators, bf16-friendly inputs; the output q-block is revisited and
  finalized on the last kv block. Nothing quadratic ever hits HBM.
- key-padding mask rides as a sublane-replicated (B, _SUB, Nk) f32 additive
  bias streamed per KV block (the Mosaic-tiling idiom block_sparse proved);
  row stats (lse, dsum) are lane-replicated (bh, n, _LANES) tensors.
- fused flash-style backward (custom VJP): dq accumulates over kv blocks,
  dk/dv over q blocks, probabilities recomputed from q/k and the saved
  logsumexp — the standard flash schedule, no quadratic residuals.
- ``interpret`` defaults to on off-TPU, so the same kernels run (slowly
  but exactly) on the CPU mesh and oracle-diff in CI.

The tied-row MSA kernel (``tied_row.py``) reuses these kernels through an
algebraic reduction: the tied logit sum over rows is one contraction over a
fused (row, head_dim) feature axis, so the D dimension here may be R*D.

Selected via :mod:`alphafold2_tpu.ops.kernels` (``KernelPolicy`` /
``AF2TPU_KERNELS``); validated against the dense jnp oracle (values and
grads, masked + padded + odd lengths) in tests/test_pallas_kernels.py and
Mosaic-lowered pre-hardware by ``analysis/lowering.py``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from alphafold2_tpu.ops.pallas.block_sparse import (
    NEG_INF,
    _LANES,
    _SUB,
    _rep_rows,
)

# q/kv tile edge: one Mosaic lane width. Arrays pad up to a multiple (the
# padded keys are excluded via the additive bias, padded query rows are
# sliced back off), exactly the policy ops/flash.py applies to the stock
# kernel, so any length — compressed-KV, odd crops — takes the fused path.
BLOCK = 128


def _fwd_core(
    q_ref,  # (1, block_q, d)
    k_ref,  # (1, block_k, d) — the a-th KV block
    v_ref,  # (1, block_k, d)
    bias_ref,  # (1, _SUB, block_k) f32 additive key bias (0 / NEG_INF)
    o_ref,  # (1, block_q, d)
    lse_ref,  # (1, block_q, _LANES) lane-replicated logsumexp, or None
    m_scr,  # (block_q, 1) f32 running max
    l_scr,  # (block_q, 1) f32 running sum
    acc_scr,  # (block_q, d) f32 accumulator
    *,
    scale: float,
):
    a = pl.program_id(2)
    num_a = pl.num_programs(2)

    @pl.when(a == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    dots = (
        jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        * scale
    )  # (block_q, block_k)
    dots = dots + bias_ref[0][:1, :]

    m_prev = m_scr[:]
    m_new = jnp.maximum(m_prev, jnp.max(dots, axis=-1, keepdims=True))
    p = jnp.exp(dots - m_new)
    alpha = jnp.exp(m_prev - m_new)
    l_scr[:] = alpha * l_scr[:] + jnp.sum(p, axis=-1, keepdims=True)
    acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_scr[:] = m_new

    @pl.when(a == num_a - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        if lse_ref is not None:
            lse_ref[0] = jnp.broadcast_to(
                m_scr[:] + jnp.log(l), lse_ref.shape[1:]
            )


def _kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref, m_scr, l_scr,
            acc_scr, *, scale: float):
    _fwd_core(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref, m_scr, l_scr,
              acc_scr, scale=scale)


def _kernel_no_lse(q_ref, k_ref, v_ref, bias_ref, o_ref, m_scr, l_scr,
                   acc_scr, *, scale: float):
    # inference/no-grad variant: skips the 128x-replicated lse HBM write
    _fwd_core(q_ref, k_ref, v_ref, bias_ref, o_ref, None, m_scr, l_scr,
              acc_scr, scale=scale)


def _dq_kernel(
    q_ref,  # (1, block_q, d)
    g_ref,  # (1, block_q, d) upstream cotangent dO
    lse_ref,  # (1, block_q, _LANES) lane-replicated
    dsum_ref,  # (1, block_q, _LANES) lane-replicated D = rowsum(dO * O)
    k_ref,  # (1, block_k, d) — the a-th KV block
    v_ref,  # (1, block_k, d)
    bias_ref,  # (1, _SUB, block_k)
    dq_ref,  # (1, block_q, d) out
    dq_scr,  # (block_q, d) f32
    *,
    scale: float,
):
    a = pl.program_id(2)
    num_a = pl.num_programs(2)

    @pl.when(a == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q, g, k, v = q_ref[0], g_ref[0], k_ref[0], v_ref[0]
    dots = (
        jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        * scale
        + bias_ref[0][:1, :]
    )
    p = jnp.exp(dots - _rep_rows(lse_ref[0], dots.shape[1]))
    dp = jax.lax.dot_general(
        g, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - _rep_rows(dsum_ref[0], dp.shape[1]))
    dq_scr[:] = dq_scr[:] + scale * jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(a == num_a - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(
    k_ref,  # (1, block_k, d) this KV block
    v_ref,  # (1, block_k, d)
    bias_ref,  # (1, _SUB, block_k) additive key bias for this KV block
    q_ref,  # (1, block_q, d) — the a-th attending Q block
    g_ref,  # (1, block_q, d)
    lse_ref,  # (1, block_q, _LANES)
    dsum_ref,  # (1, block_q, _LANES)
    dk_ref,  # (1, block_k, d) out
    dv_ref,  # (1, block_k, d) out
    dk_scr,  # (block_k, d) f32
    dv_scr,  # (block_k, d) f32
    *,
    scale: float,
):
    a = pl.program_id(2)
    num_a = pl.num_programs(2)

    @pl.when(a == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    k, v, q, g = k_ref[0], v_ref[0], q_ref[0], g_ref[0]
    dots = (
        jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        * scale
        + bias_ref[0][:1, :]
    )  # (block_q, block_k)
    p = jnp.exp(dots - _rep_rows(lse_ref[0], dots.shape[1]))
    dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
        p.astype(g.dtype), g, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dp = jax.lax.dot_general(
        g, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - _rep_rows(dsum_ref[0], dp.shape[1]))
    dk_scr[:] = dk_scr[:] + scale * jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(a == num_a - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("block_q", "block_k", "scale", "interpret", "with_lse"),
)
def _run(q, k, v, bias8, block_q, block_k, scale, interpret, with_lse):
    bh, nq, d = q.shape
    nk = k.shape[1]
    b = bias8.shape[0]
    heads = bh // b
    grid = (bh, nq // block_q, nk // block_k)

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh_, qi, a: (bh_, qi, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh_, qi, a: (bh_, a, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh_, qi, a: (bh_, a, 0)),
        pl.BlockSpec(
            (1, _SUB, block_k),
            lambda bh_, qi, a, h=heads: (bh_ // h, 0, a),
        ),
    ]
    out_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh_, qi, a: (bh_, qi, 0)),
    ] + ([
        pl.BlockSpec((1, block_q, _LANES), lambda bh_, qi, a: (bh_, qi, 0)),
    ] if with_lse else [])
    out_shape = [jax.ShapeDtypeStruct((bh, nq, d), q.dtype)] + (
        [jax.ShapeDtypeStruct((bh, nq, _LANES), jnp.float32)]
        if with_lse else []
    )
    kernel = functools.partial(
        _kernel if with_lse else _kernel_no_lse, scale=scale
    )
    res = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, bias8)
    return (res[0], res[1]) if with_lse else (res[0], None)


@functools.partial(
    jax.jit, static_argnames=("block_q", "block_k", "scale", "interpret")
)
def _run_dq(q, g, lse_l, dsum_l, k, v, bias8, block_q, block_k, scale,
            interpret):
    bh, nq, d = q.shape
    nk = k.shape[1]
    b = bias8.shape[0]
    heads = bh // b
    grid = (bh, nq // block_q, nk // block_k)
    return pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh_, qi, a: (bh_, qi, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh_, qi, a: (bh_, qi, 0)),
            pl.BlockSpec(
                (1, block_q, _LANES), lambda bh_, qi, a: (bh_, qi, 0)
            ),
            pl.BlockSpec(
                (1, block_q, _LANES), lambda bh_, qi, a: (bh_, qi, 0)
            ),
            pl.BlockSpec((1, block_k, d), lambda bh_, qi, a: (bh_, a, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, qi, a: (bh_, a, 0)),
            pl.BlockSpec(
                (1, _SUB, block_k),
                lambda bh_, qi, a, h=heads: (bh_ // h, 0, a),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, block_q, d), lambda bh_, qi, a: (bh_, qi, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((bh, nq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, g, lse_l, dsum_l, k, v, bias8)


@functools.partial(
    jax.jit, static_argnames=("block_q", "block_k", "scale", "interpret")
)
def _run_dkv(k, v, bias8, q, g, lse_l, dsum_l, block_q, block_k, scale,
             interpret):
    bh, nk, d = k.shape
    nq = q.shape[1]
    b = bias8.shape[0]
    heads = bh // b
    grid = (bh, nk // block_k, nq // block_q)
    return pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh_, kj, a: (bh_, kj, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, kj, a: (bh_, kj, 0)),
            pl.BlockSpec(
                (1, _SUB, block_k),
                lambda bh_, kj, a, h=heads: (bh_ // h, 0, kj),
            ),
            pl.BlockSpec((1, block_q, d), lambda bh_, kj, a: (bh_, a, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh_, kj, a: (bh_, a, 0)),
            pl.BlockSpec(
                (1, block_q, _LANES), lambda bh_, kj, a: (bh_, a, 0)
            ),
            pl.BlockSpec(
                (1, block_q, _LANES), lambda bh_, kj, a: (bh_, a, 0)
            ),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh_, kj, a: (bh_, kj, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, kj, a: (bh_, kj, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, nk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, nk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(k, v, bias8, q, g, lse_l, dsum_l)


def _pad_seq(t, axis: int, pad: int):
    if pad == 0:
        return t
    widths = [(0, 0)] * t.ndim
    widths[axis] = (0, pad)
    return jnp.pad(t, widths)


def fused_attention(
    q: jnp.ndarray,  # (B, H, Nq, D)
    k: jnp.ndarray,  # (B, H, Nk, D)
    v: jnp.ndarray,
    q_mask: Optional[jnp.ndarray] = None,  # (B, Nq) bool valid-query
    kv_mask: Optional[jnp.ndarray] = None,  # (B, Nk) bool valid-key
    sm_scale: float = 1.0,
    interpret: Optional[bool] = None,
    block_q: int = BLOCK,
    block_k: int = BLOCK,
) -> jnp.ndarray:
    """Fused flash attention, differentiable (fused custom-VJP backward).

    Same contract as ``ops.flash.flash_attention`` / ``ops.chunked``:
    masked keys are excluded exactly (additive NEG_INF bias before the
    online max); masked queries produce zeros (the flash SegmentIds
    convention — padded rows are downstream-masked everywhere this runs).
    Sequence axes pad up to the 128-lane block and the output is sliced
    back. ``interpret=None`` compiles on TPU and interprets elsewhere."""
    b, h, nq, d = q.shape
    nk = k.shape[2]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_q = min(block_q, max(8, -(-nq // 8) * 8)) if interpret else block_q
    block_k = min(block_k, max(8, -(-nk // 8) * 8)) if interpret else block_k
    pad_q = (-nq) % block_q
    pad_k = (-nk) % block_k
    if pad_k and kv_mask is None:
        kv_mask = jnp.ones((b, nk), dtype=bool)

    qp = _pad_seq(q, 2, pad_q)
    kp = _pad_seq(k, 2, pad_k)
    vp = _pad_seq(v, 2, pad_k)
    nqp, nkp = nq + pad_q, nk + pad_k
    if kv_mask is not None:
        kv_pad = _pad_seq(kv_mask, 1, pad_k)  # pads with False = excluded
        bias = jnp.where(kv_pad, 0.0, NEG_INF).astype(jnp.float32)
    else:
        bias = jnp.zeros((b, nkp), dtype=jnp.float32)
    bias8 = jnp.broadcast_to(bias[:, None, :], (b, _SUB, nkp))

    bh = b * h
    qf = qp.reshape(bh, nqp, d)
    kf = kp.reshape(bh, nkp, d)
    vf = vp.reshape(bh, nkp, d)

    @jax.custom_vjp
    def attend(qf, kf, vf, bias8):
        out, _ = _run(
            qf, kf, vf, bias8, block_q, block_k, sm_scale, interpret, False
        )
        return out

    def attend_fwd(qf, kf, vf, bias8):
        out, lse = _run(
            qf, kf, vf, bias8, block_q, block_k, sm_scale, interpret, True
        )
        return out, (qf, kf, vf, bias8, out, lse)

    def attend_bwd(res, g):
        qf, kf, vf, bias8, out, lse = res
        dsum = jnp.sum(
            out.astype(jnp.float32) * g.astype(jnp.float32), axis=-1
        )
        dsum_l = jnp.broadcast_to(dsum[..., None], (bh, nqp, _LANES))
        dq = _run_dq(
            qf, g, lse, dsum_l, kf, vf, bias8, block_q, block_k, sm_scale,
            interpret,
        )
        dk, dv = _run_dkv(
            kf, vf, bias8, qf, g, lse, dsum_l, block_q, block_k, sm_scale,
            interpret,
        )
        return dq, dk, dv, None

    attend.defvjp(attend_fwd, attend_bwd)
    out = attend(qf, kf, vf, bias8).reshape(b, h, nqp, d)[:, :, :nq]
    if q_mask is not None:
        out = jnp.where(q_mask[:, None, :, None], out, 0)
    return out


def axial_attn_fn(sm_scale: float, interpret: Optional[bool] = None):
    """An ``attn_fn`` hook for the (possibly 2D-sharded) axial passes
    (parallel.grid_parallel._attend_last_grid_axis): row-flattened
    ``(B*R, H, N, D)`` q/k/v and a ``(B*R, N)`` mask in, attended values in
    the same layout out — the per-device computation after the all-to-all
    gather runs this module's fused kernel instead of dense attention."""

    def attn_fn(q2, k2, v2, m2):
        return fused_attention(
            q2, k2, v2, q_mask=m2, kv_mask=m2, sm_scale=sm_scale,
            interpret=interpret,
        )

    attn_fn.accepts = lambda bsz, h, n: True
    return attn_fn
