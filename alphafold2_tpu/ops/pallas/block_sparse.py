"""Pallas TPU kernel: block-sparse flash attention.

The TPU-native replacement for the reference's DeepSpeed Triton block-sparse
kernels (reference alphafold2.py:195-209,234; compiled by
install_deepspeed.sh with DS_BUILD_SPARSE_ATTN=1). Design:

- grid = (batch*heads, q_blocks, active_kv_slots); the per-row active-block
  index lists (from ops/sparse.py:active_indices) ride in as scalar prefetch,
  so the kernel DMAs exactly the KV blocks the layout names — compute and
  HBM traffic are O(N * active * block), never O(N^2).
- online-softmax (flash) accumulation in VMEM scratch across the innermost
  grid axis, f32 accumulators, bf16-friendly inputs; the output q-block is
  revisited and finalized on the last active slot.
- padding-mask bias is an f32 input streamed per KV block; invalid (padded)
  layout slots contribute -inf via the prefetched valid flags.

Validated against the gather-based jnp oracle and dense attention in
tests/test_sparse.py (interpret mode on CPU; compiled on TPU).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# Mosaic tiling: the last two dims of every block must be (8k, 128k) or
# equal the full array dims. Row-wise stats (lse, dsum) therefore ride as
# (bh, n, _LANES) lane-replicated tensors and the per-key bias as a
# (b, _SUB, n) sublane-replicated tensor — the same idiom as the in-tree
# jax.experimental.pallas.ops.tpu.flash_attention (l/m stored with a
# MIN_BLOCK_SIZE=128 trailing dim). A bare (bh, n) with block (1, block)
# fails the compiled lowering (sublane dim 1), which interpret mode never
# surfaces.
_LANES = 128
_SUB = 8


def _rep_rows(stat, width):
    """(block, _LANES) lane-replicated row stat -> (block, width), matching
    a (block_q, width) logits tile. Every lane holds the row value, so
    slicing or tiling both preserve semantics."""
    lanes = stat.shape[-1]
    if width == lanes:
        return stat
    if width < lanes:
        return stat[:, :width]
    reps = -(-width // lanes)
    return jnp.tile(stat, (1, reps))[:, :width]


def _fwd_core(
    idx_ref,  # scalar prefetch: (nb, A) int32 active block ids
    valid_ref,  # scalar prefetch: (nb, A) int32 validity flags
    q_ref,  # (1, block, d)
    k_ref,  # (1, block, d) — the a-th active KV block for this q row
    v_ref,  # (1, block, d)
    kmask_ref,  # (1, _SUB, block) f32 additive key-padding bias (0/NEG_INF)
    o_ref,  # (1, block, d)
    lse_ref,  # (1, block, _LANES) f32 lane-replicated logsumexp, or None
    m_scr,  # (block, 1) f32 running max
    l_scr,  # (block, 1) f32 running sum
    acc_scr,  # (block, d) f32 accumulator
    *,
    scale: float,
):
    a = pl.program_id(2)
    num_a = pl.num_programs(2)
    qi = pl.program_id(1)

    @pl.when(a == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    dots = (
        jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        * scale
    )  # (block, block)

    valid_bias = jnp.where(valid_ref[qi, a] > 0, 0.0, NEG_INF)
    dots = dots + kmask_ref[0][:1, :] + valid_bias

    m_prev = m_scr[:]  # (block, 1)
    m_new = jnp.maximum(m_prev, jnp.max(dots, axis=-1, keepdims=True))
    p = jnp.exp(dots - m_new)  # (block, block)
    alpha = jnp.exp(m_prev - m_new)  # (block, 1)
    l_scr[:] = alpha * l_scr[:] + jnp.sum(p, axis=-1, keepdims=True)
    acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_scr[:] = m_new

    @pl.when(a == num_a - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        if lse_ref is not None:
            # logsumexp per q row, lane-replicated for the backward kernels
            lse_ref[0] = jnp.broadcast_to(
                m_scr[:] + jnp.log(l), lse_ref.shape[1:]
            )


def _kernel(idx_ref, valid_ref, q_ref, k_ref, v_ref, kmask_ref, o_ref,
            lse_ref, m_scr, l_scr, acc_scr, *, scale: float):
    _fwd_core(idx_ref, valid_ref, q_ref, k_ref, v_ref, kmask_ref, o_ref,
              lse_ref, m_scr, l_scr, acc_scr, scale=scale)


def _kernel_no_lse(idx_ref, valid_ref, q_ref, k_ref, v_ref, kmask_ref,
                   o_ref, m_scr, l_scr, acc_scr, *, scale: float):
    # forward-only variant: no (bh, n, _LANES) lse output allocated or
    # written — inference/no-grad calls skip that 128x-replicated HBM write
    _fwd_core(idx_ref, valid_ref, q_ref, k_ref, v_ref, kmask_ref, o_ref,
              None, m_scr, l_scr, acc_scr, scale=scale)


def _dq_kernel(
    idx_ref,  # scalar prefetch: (nb, A) active kv-block ids per q block
    valid_ref,  # scalar prefetch: (nb, A)
    q_ref,  # (1, block, d)
    g_ref,  # (1, block, d) upstream cotangent dO for this q block
    lse_ref,  # (1, block, _LANES) f32 logsumexp per q row (lane-replicated)
    dsum_ref,  # (1, block, _LANES) f32 D = rowsum(dO * O) (lane-replicated)
    k_ref,  # (1, block, d) a-th active kv block
    v_ref,  # (1, block, d)
    kmask_ref,  # (1, _SUB, block) f32 additive key bias
    dq_ref,  # (1, block, d) out
    dq_scr,  # (block, d) f32 accumulator
    *,
    scale: float,
):
    a = pl.program_id(2)
    num_a = pl.num_programs(2)
    qi = pl.program_id(1)

    @pl.when(a == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q, g, k, v = q_ref[0], g_ref[0], k_ref[0], v_ref[0]
    valid_bias = jnp.where(valid_ref[qi, a] > 0, 0.0, NEG_INF)
    dots = (
        jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        * scale
        + kmask_ref[0][:1, :]
        + valid_bias
    )
    # (block, block) normalized probs
    p = jnp.exp(dots - _rep_rows(lse_ref[0], dots.shape[1]))
    dp = jax.lax.dot_general(
        g, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - _rep_rows(dsum_ref[0], dp.shape[1]))
    dq_scr[:] = dq_scr[:] + scale * jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(a == num_a - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(
    idx_ref,  # scalar prefetch: (nbk, At) active Q-block ids per kv block
    valid_ref,  # scalar prefetch: (nbk, At)
    k_ref,  # (1, block, d) this kv block
    v_ref,  # (1, block, d)
    kmask_ref,  # (1, _SUB, block) f32 additive key bias for this kv block
    q_ref,  # (1, block, d) a-th attending q block
    g_ref,  # (1, block, d)
    lse_ref,  # (1, block, _LANES) lane-replicated
    dsum_ref,  # (1, block, _LANES) lane-replicated
    dk_ref,  # (1, block, d) out
    dv_ref,  # (1, block, d) out
    dk_scr,  # (block, d) f32
    dv_scr,  # (block, d) f32
    *,
    scale: float,
):
    a = pl.program_id(2)
    num_a = pl.num_programs(2)
    kj = pl.program_id(1)

    @pl.when(a == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    k, v, q, g = k_ref[0], v_ref[0], q_ref[0], g_ref[0]
    valid_bias = jnp.where(valid_ref[kj, a] > 0, 0.0, NEG_INF)
    dots = (
        jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        * scale
        + kmask_ref[0][:1, :]
        + valid_bias
    )
    # (block_q, block_k)
    p = jnp.exp(dots - _rep_rows(lse_ref[0], dots.shape[1]))
    dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
        p.astype(g.dtype), g, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dp = jax.lax.dot_general(
        g, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - _rep_rows(dsum_ref[0], dp.shape[1]))
    dk_scr[:] = dk_scr[:] + scale * jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(a == num_a - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("block_size", "interpret", "with_lse")
)
def _run(q, k, v, kmask8, idx, valid, block_size, interpret, with_lse=True):
    # the kernel is layout-agnostic: idx/valid ride in as runtime
    # scalar-prefetch operands, so distinct layouts with the same shapes
    # share one compilation
    bh, n, d = q.shape
    nb = n // block_size
    A = idx.shape[1]
    b = kmask8.shape[0]
    heads = bh // b

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, nb, A),
        in_specs=[
            pl.BlockSpec(
                (1, block_size, d), lambda bh_, qi, a, idx_, val_: (bh_, qi, 0)
            ),
            pl.BlockSpec(
                (1, block_size, d),
                lambda bh_, qi, a, idx_, val_: (bh_, idx_[qi, a], 0),
            ),
            pl.BlockSpec(
                (1, block_size, d),
                lambda bh_, qi, a, idx_, val_: (bh_, idx_[qi, a], 0),
            ),
            pl.BlockSpec(
                (1, _SUB, block_size),
                lambda bh_, qi, a, idx_, val_, h=heads:
                (bh_ // h, 0, idx_[qi, a]),
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, block_size, d), lambda bh_, qi, a, idx_, val_: (bh_, qi, 0)
            ),
        ] + ([
            pl.BlockSpec(
                (1, block_size, _LANES),
                lambda bh_, qi, a, idx_, val_: (bh_, qi, 0),
            ),
        ] if with_lse else []),
        scratch_shapes=[
            pltpu.VMEM((block_size, 1), jnp.float32),
            pltpu.VMEM((block_size, 1), jnp.float32),
            pltpu.VMEM((block_size, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _kernel if with_lse else _kernel_no_lse, scale=d**-0.5
    )
    out_shape = [jax.ShapeDtypeStruct((bh, n, d), q.dtype)] + (
        [jax.ShapeDtypeStruct((bh, n, _LANES), jnp.float32)]
        if with_lse else []
    )
    res = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(idx, valid, q, k, v, kmask8)
    return (res[0], res[1]) if with_lse else (res[0], None)


@functools.partial(jax.jit, static_argnames=("block_size", "interpret"))
def _run_dq(q, g, lse_l, dsum_l, k, v, kmask8, idx, valid, block_size,
            interpret):
    bh, n, d = q.shape
    nb = n // block_size
    A = idx.shape[1]
    b = kmask8.shape[0]
    heads = bh // b

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, nb, A),
        in_specs=[
            pl.BlockSpec((1, block_size, d),
                         lambda bh_, qi, a, idx_, val_: (bh_, qi, 0)),
            pl.BlockSpec((1, block_size, d),
                         lambda bh_, qi, a, idx_, val_: (bh_, qi, 0)),
            pl.BlockSpec((1, block_size, _LANES),
                         lambda bh_, qi, a, idx_, val_: (bh_, qi, 0)),
            pl.BlockSpec((1, block_size, _LANES),
                         lambda bh_, qi, a, idx_, val_: (bh_, qi, 0)),
            pl.BlockSpec((1, block_size, d),
                         lambda bh_, qi, a, idx_, val_: (bh_, idx_[qi, a], 0)),
            pl.BlockSpec((1, block_size, d),
                         lambda bh_, qi, a, idx_, val_: (bh_, idx_[qi, a], 0)),
            pl.BlockSpec((1, _SUB, block_size),
                         lambda bh_, qi, a, idx_, val_, h=heads:
                         (bh_ // h, 0, idx_[qi, a])),
        ],
        out_specs=pl.BlockSpec((1, block_size, d),
                               lambda bh_, qi, a, idx_, val_: (bh_, qi, 0)),
        scratch_shapes=[pltpu.VMEM((block_size, d), jnp.float32)],
    )
    kernel = functools.partial(_dq_kernel, scale=d**-0.5)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((bh, n, d), q.dtype),
        interpret=interpret,
    )(idx, valid, q, g, lse_l, dsum_l, k, v, kmask8)


@functools.partial(jax.jit, static_argnames=("block_size", "interpret"))
def _run_dkv(k, v, kmask8, q, g, lse_l, dsum_l, idx_t, valid_t,
             block_size, interpret):
    bh, n, d = q.shape
    nbk = n // block_size
    At = idx_t.shape[1]
    b = kmask8.shape[0]
    heads = bh // b

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, nbk, At),
        in_specs=[
            pl.BlockSpec((1, block_size, d),
                         lambda bh_, kj, a, idx_, val_: (bh_, kj, 0)),
            pl.BlockSpec((1, block_size, d),
                         lambda bh_, kj, a, idx_, val_: (bh_, kj, 0)),
            pl.BlockSpec((1, _SUB, block_size),
                         lambda bh_, kj, a, idx_, val_, h=heads:
                         (bh_ // h, 0, kj)),
            pl.BlockSpec((1, block_size, d),
                         lambda bh_, kj, a, idx_, val_: (bh_, idx_[kj, a], 0)),
            pl.BlockSpec((1, block_size, d),
                         lambda bh_, kj, a, idx_, val_: (bh_, idx_[kj, a], 0)),
            pl.BlockSpec((1, block_size, _LANES),
                         lambda bh_, kj, a, idx_, val_: (bh_, idx_[kj, a], 0)),
            pl.BlockSpec((1, block_size, _LANES),
                         lambda bh_, kj, a, idx_, val_: (bh_, idx_[kj, a], 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_size, d),
                         lambda bh_, kj, a, idx_, val_: (bh_, kj, 0)),
            pl.BlockSpec((1, block_size, d),
                         lambda bh_, kj, a, idx_, val_: (bh_, kj, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_size, d), jnp.float32),
            pltpu.VMEM((block_size, d), jnp.float32),
        ],
    )
    kernel = functools.partial(_dkv_kernel, scale=d**-0.5)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bh, n, d), k.dtype),
            jax.ShapeDtypeStruct((bh, n, d), v.dtype),
        ],
        interpret=interpret,
    )(idx_t, valid_t, k, v, kmask8, q, g, lse_l, dsum_l)


def _prep(q, mask, layout):
    from alphafold2_tpu.ops.sparse import active_indices

    b, h, n, d = q.shape
    idx, valid, _ = active_indices(layout)
    idx_j = jnp.asarray(idx, dtype=jnp.int32)
    valid_j = jnp.asarray(valid, dtype=jnp.int32)
    if mask is None:
        kmask_bias = jnp.zeros((b, n), dtype=jnp.float32)
    else:
        kmask_bias = jnp.where(mask, 0.0, NEG_INF).astype(jnp.float32)
    # sublane-replicated once here; every kernel takes it in this layout
    kmask8 = jnp.broadcast_to(kmask_bias[:, None, :], (b, _SUB, n))
    return idx_j, valid_j, kmask8


def pallas_block_sparse_attention(
    q: jnp.ndarray,  # (B, H, N, D)
    k: jnp.ndarray,
    v: jnp.ndarray,
    layout: np.ndarray,  # (nb, nb) bool, static
    block_size: int,
    mask: Optional[jnp.ndarray] = None,  # (B, N) bool
    interpret: Optional[bool] = None,
    return_lse: bool = False,
):
    """Flash block-sparse attention over a static layout. Same contract as
    ops.sparse.block_sparse_attention; ``return_lse=True`` additionally
    returns the per-row logsumexp (B, H, N) for the backward kernels."""
    b, h, n, d = q.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    idx_j, valid_j, kmask8 = _prep(q, mask, layout)

    qf = q.reshape(b * h, n, d)
    kf = k.reshape(b * h, n, d)
    vf = v.reshape(b * h, n, d)
    out, lse = _run(
        qf, kf, vf, kmask8, idx_j, valid_j, block_size, interpret,
        with_lse=return_lse,
    )
    out = out.reshape(b, h, n, d)
    if return_lse:
        # lane 0 of the lane-replicated (bh, n, _LANES) internal layout
        return out, lse[..., 0].reshape(b, h, n)
    return out


def pallas_block_sparse_attention_bwd(
    q: jnp.ndarray,  # (B, H, N, D)
    k: jnp.ndarray,
    v: jnp.ndarray,
    out: jnp.ndarray,  # forward output (for D = rowsum(dO * O))
    lse: jnp.ndarray,  # (B, H, N) from the forward
    g: jnp.ndarray,  # upstream cotangent dO
    layout: np.ndarray,
    block_size: int,
    mask: Optional[jnp.ndarray] = None,
    interpret: Optional[bool] = None,
):
    """Fused flash-style backward: dq over the row-wise active lists, dk/dv
    over the column-wise (transposed-layout) lists. Nothing quadratic is
    materialized; probabilities are recomputed from q/k and the saved
    logsumexp (the standard flash-attention backward schedule)."""
    b, h, n, d = q.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    idx_j, valid_j, kmask8 = _prep(q, mask, layout)
    # column-wise active lists: which q blocks attend each kv block
    from alphafold2_tpu.ops.sparse import active_indices

    idx_t_np, valid_t_np, _ = active_indices(np.asarray(layout).T)
    idx_t = jnp.asarray(idx_t_np, dtype=jnp.int32)
    valid_t = jnp.asarray(valid_t_np, dtype=jnp.int32)

    qf = q.reshape(b * h, n, d)
    kf = k.reshape(b * h, n, d)
    vf = v.reshape(b * h, n, d)
    gf = g.reshape(b * h, n, d)
    of = out.reshape(b * h, n, d)
    # lane-replicate the row stats ONCE for both backward kernels (the
    # forward's replicated lse was sliced to lane 0 at the public boundary)
    bh = b * h
    lse_l = jnp.broadcast_to(
        lse.reshape(bh, n)[..., None], (bh, n, _LANES)
    )
    dsum = jnp.sum(of.astype(jnp.float32) * gf.astype(jnp.float32), axis=-1)
    dsum_l = jnp.broadcast_to(dsum[..., None], (bh, n, _LANES))

    dq = _run_dq(
        qf, gf, lse_l, dsum_l, kf, vf, kmask8, idx_j, valid_j, block_size,
        interpret,
    )
    dk, dv = _run_dkv(
        kf, vf, kmask8, qf, gf, lse_l, dsum_l, idx_t, valid_t, block_size,
        interpret,
    )
    return (
        dq.reshape(b, h, n, d),
        dk.reshape(b, h, n, d),
        dv.reshape(b, h, n, d),
    )
