"""Pallas TPU kernel: block-sparse flash attention.

The TPU-native replacement for the reference's DeepSpeed Triton block-sparse
kernels (reference alphafold2.py:195-209,234; compiled by
install_deepspeed.sh with DS_BUILD_SPARSE_ATTN=1). Design:

- grid = (batch*heads, q_blocks, active_kv_slots); the per-row active-block
  index lists (from ops/sparse.py:active_indices) ride in as scalar prefetch,
  so the kernel DMAs exactly the KV blocks the layout names — compute and
  HBM traffic are O(N * active * block), never O(N^2).
- online-softmax (flash) accumulation in VMEM scratch across the innermost
  grid axis, f32 accumulators, bf16-friendly inputs; the output q-block is
  revisited and finalized on the last active slot.
- padding-mask bias is an f32 input streamed per KV block; invalid (padded)
  layout slots contribute -inf via the prefetched valid flags.

Validated against the gather-based jnp oracle and dense attention in
tests/test_sparse.py (interpret mode on CPU; compiled on TPU).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _kernel(
    idx_ref,  # scalar prefetch: (nb, A) int32 active block ids
    valid_ref,  # scalar prefetch: (nb, A) int32 validity flags
    q_ref,  # (1, block, d)
    k_ref,  # (1, block, d) — the a-th active KV block for this q row
    v_ref,  # (1, block, d)
    kmask_ref,  # (1, block) f32 additive key-padding bias (0 or NEG_INF)
    o_ref,  # (1, block, d)
    m_scr,  # (block, 1) f32 running max
    l_scr,  # (block, 1) f32 running sum
    acc_scr,  # (block, d) f32 accumulator
    *,
    scale: float,
):
    a = pl.program_id(2)
    num_a = pl.num_programs(2)
    qi = pl.program_id(1)

    @pl.when(a == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    dots = (
        jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        * scale
    )  # (block, block)

    valid_bias = jnp.where(valid_ref[qi, a] > 0, 0.0, NEG_INF)
    dots = dots + kmask_ref[0][None, :] + valid_bias

    m_prev = m_scr[:]  # (block, 1)
    m_new = jnp.maximum(m_prev, jnp.max(dots, axis=-1, keepdims=True))
    p = jnp.exp(dots - m_new)  # (block, block)
    alpha = jnp.exp(m_prev - m_new)  # (block, 1)
    l_scr[:] = alpha * l_scr[:] + jnp.sum(p, axis=-1, keepdims=True)
    acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_scr[:] = m_new

    @pl.when(a == num_a - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_size", "interpret"))
def _run(q, k, v, kmask_bias, idx, valid, block_size, interpret):
    # the kernel is layout-agnostic: idx/valid ride in as runtime
    # scalar-prefetch operands, so distinct layouts with the same shapes
    # share one compilation
    bh, n, d = q.shape
    nb = n // block_size
    A = idx.shape[1]
    b = kmask_bias.shape[0]
    heads = bh // b

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, nb, A),
        in_specs=[
            pl.BlockSpec(
                (1, block_size, d), lambda bh_, qi, a, idx_, val_: (bh_, qi, 0)
            ),
            pl.BlockSpec(
                (1, block_size, d),
                lambda bh_, qi, a, idx_, val_: (bh_, idx_[qi, a], 0),
            ),
            pl.BlockSpec(
                (1, block_size, d),
                lambda bh_, qi, a, idx_, val_: (bh_, idx_[qi, a], 0),
            ),
            pl.BlockSpec(
                (1, block_size),
                lambda bh_, qi, a, idx_, val_, h=heads: (bh_ // h, idx_[qi, a]),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, block_size, d), lambda bh_, qi, a, idx_, val_: (bh_, qi, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((block_size, 1), jnp.float32),
            pltpu.VMEM((block_size, 1), jnp.float32),
            pltpu.VMEM((block_size, d), jnp.float32),
        ],
    )
    kernel = functools.partial(_kernel, scale=d**-0.5)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((bh, n, d), q.dtype),
        interpret=interpret,
    )(idx, valid, q, k, v, kmask_bias)


def pallas_block_sparse_attention(
    q: jnp.ndarray,  # (B, H, N, D)
    k: jnp.ndarray,
    v: jnp.ndarray,
    layout: np.ndarray,  # (nb, nb) bool, static
    block_size: int,
    mask: Optional[jnp.ndarray] = None,  # (B, N) bool
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Flash block-sparse attention over a static layout. Same contract as
    ops.sparse.block_sparse_attention."""
    from alphafold2_tpu.ops.sparse import active_indices

    b, h, n, d = q.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    idx, valid, _ = active_indices(layout)
    idx_j = jnp.asarray(idx, dtype=jnp.int32)
    valid_j = jnp.asarray(valid, dtype=jnp.int32)

    if mask is None:
        kmask_bias = jnp.zeros((b, n), dtype=jnp.float32)
    else:
        kmask_bias = jnp.where(mask, 0.0, NEG_INF).astype(jnp.float32)

    qf = q.reshape(b * h, n, d)
    kf = k.reshape(b * h, n, d)
    vf = v.reshape(b * h, n, d)
    out = _run(qf, kf, vf, kmask_bias, idx_j, valid_j, block_size, interpret)
    return out.reshape(b, h, n, d)
