"""Block-sparse attention: sparsity config, layout, gather-based jnp impl.

Replaces the reference's DeepSpeed ``SparseSelfAttention`` +
``VariableSparsityConfig`` Triton/CUDA path (reference alphafold2.py:184-239;
built by install_deepspeed.sh) with a TPU-native design:

- :class:`BlockSparseConfig` — variable sparsity layout abstraction: local
  sliding-window blocks, global blocks (first rows+columns dense), and
  seeded random blocks per row — the same layout family as DeepSpeed's
  VariableSparsityConfig (block=16, num_random_blocks=seq_len/block/4 default,
  bidirectional; reference alphafold2.py:198-206).
- :func:`block_sparse_attention` — gather-based jnp implementation: for each
  query block, gather its active KV blocks (static layout -> static gather
  indices baked at trace time) and attend only over those. Compute is
  O(N * active_blocks * block) rather than O(N^2); runs on any backend and
  is the oracle for the Pallas kernel.
- :class:`SparseAttention` — drop-in module matching :class:`Attention`'s
  call surface for the self-attention case (the reference's sparse path is
  self-attn only and incompatible with tied rows, alphafold2.py:193).
- the Pallas TPU kernel lives in ops/pallas/block_sparse.py; it is selected
  with ``use_pallas=True`` (or on TPU backends) and validated against the
  jnp implementation — including the dense-layout == dense-attention
  differential test (tests/test_sparse.py).

Unlike the reference, a caller-supplied mask composes with padding instead of
being overwritten (alphafold2.py:222 clobbers it — SURVEY.md S2.5), and
there is no dead dense-dots compute (alphafold2.py:228).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from alphafold2_tpu.ops.attention import MASK_VALUE, grid_axial_project_attend
from alphafold2_tpu.ops.flash import warn_once


@dataclasses.dataclass(frozen=True)
class BlockSparseConfig:
    """Variable block-sparsity layout (bidirectional).

    block_size: attention block edge (reference default 16; use 128 on TPU
    for lane alignment). num_local_blocks: sliding window width in blocks.
    num_global_blocks: leading blocks attending/attended densely.
    num_random_blocks: extra random blocks per query row; None -> the
    reference's default seq_len/block/4 (alphafold2.py:198).
    """

    block_size: int = 16
    num_local_blocks: int = 4
    num_global_blocks: int = 1
    num_random_blocks: Optional[int] = None
    seed: int = 0
    # kernel backend: "auto" = in-repo Pallas kernels on TPU / jnp gather
    # elsewhere (the long-standing behavior); "pallas" / "jnp" force those;
    # "splash" = the stock jax splash-attention kernel over the same layout
    # (schedules only the layout's active blocks; fused custom-VJP backward)
    backend: str = "auto"

    def resolve_random(self, seq_len: int) -> int:
        if self.num_random_blocks is not None:
            return self.num_random_blocks
        return max(seq_len // self.block_size // 4, 0)

    def layout(self, seq_len: int) -> np.ndarray:
        """(num_blocks, num_blocks) bool — True where a block attends."""
        if seq_len % self.block_size != 0:
            raise ValueError(
                f"seq_len {seq_len} must be a multiple of block_size "
                f"{self.block_size}"
            )
        nb = seq_len // self.block_size
        lay = np.zeros((nb, nb), dtype=bool)
        # local sliding window
        half = self.num_local_blocks // 2
        for i in range(nb):
            lo = max(0, i - half)
            hi = min(nb, i + max(self.num_local_blocks - half, 1))
            lay[i, lo:hi] = True
        # global blocks: first G rows and columns fully dense
        g = min(self.num_global_blocks, nb)
        lay[:g, :] = True
        lay[:, :g] = True
        # seeded random blocks per row
        r = min(self.resolve_random(seq_len), nb)
        if r > 0:
            rng = np.random.default_rng(self.seed)
            for i in range(nb):
                lay[i, rng.choice(nb, size=r, replace=False)] = True
        return lay


def active_indices(layout: np.ndarray) -> tuple[np.ndarray, np.ndarray, int]:
    """Pack the layout into per-row active-block index lists.

    Returns (indices (nb, max_active) int32, valid (nb, max_active) bool,
    max_active). Rows with fewer active blocks are padded with index 0 and
    valid=False — static shapes for the gather.
    """
    nb = layout.shape[0]
    counts = layout.sum(-1)
    max_active = int(counts.max()) if nb else 0
    idx = np.zeros((nb, max_active), dtype=np.int32)
    valid = np.zeros((nb, max_active), dtype=bool)
    for i in range(nb):
        a = np.nonzero(layout[i])[0]
        idx[i, : len(a)] = a
        valid[i, : len(a)] = True
    return idx, valid, max_active


def block_sparse_attention(
    q: jnp.ndarray,  # (B, H, N, D)
    k: jnp.ndarray,
    v: jnp.ndarray,
    layout: np.ndarray,  # (nb, nb) bool, static
    block_size: int,
    mask: Optional[jnp.ndarray] = None,  # (B, N) bool key-side padding mask
) -> jnp.ndarray:
    """Gather-based block-sparse attention, numerically == dense attention
    restricted to the layout's blocks. Scale is applied inside."""
    b, h, n, d = q.shape
    nb = n // block_size
    idx, valid, max_active = active_indices(layout)
    idx_j = jnp.asarray(idx)  # (nb, A)
    valid_j = jnp.asarray(valid)

    scale = d**-0.5
    qb = q.reshape(b, h, nb, block_size, d)
    kb = k.reshape(b, h, nb, block_size, d)
    vb = v.reshape(b, h, nb, block_size, d)

    # gather active KV blocks per query block: (B, H, nb, A, block, d)
    kg = jnp.take(kb, idx_j.reshape(-1), axis=2).reshape(
        b, h, nb, max_active, block_size, d
    )
    vg = jnp.take(vb, idx_j.reshape(-1), axis=2).reshape(
        b, h, nb, max_active, block_size, d
    )

    dots = jnp.einsum("bhnqd,bhnakd->bhnqak", qb, kg) * scale

    # mask: invalid (padding) active slots + key padding mask
    am = valid_j[None, None, :, None, :, None]
    if mask is not None:
        mb = mask.reshape(b, nb, block_size)  # (B, nb, block)
        mg = jnp.take(mb, idx_j.reshape(-1), axis=1).reshape(
            b, nb, max_active, block_size
        )
        am = am & mg[:, None, :, None, :, :]
    dots = jnp.where(am, dots, MASK_VALUE)

    flat = dots.reshape(b, h, nb, block_size, max_active * block_size)
    attn = jax.nn.softmax(flat.astype(jnp.float32), axis=-1).astype(q.dtype)
    attn = attn.reshape(b, h, nb, block_size, max_active, block_size)
    out = jnp.einsum("bhnqak,bhnakd->bhnqd", attn, vg)
    return out.reshape(b, h, n, d)


def block_sparse_attention_pallas(
    q, k, v, layout: np.ndarray, block_size: int, mask=None, interpret=None
):
    """Pallas forward + fused Pallas backward.

    ``pallas_call`` kernels carry no autodiff rule, so this wrapper supplies
    one: the forward kernel additionally emits the per-row logsumexp, and
    the backward runs two flash-style kernels — dq over the row-wise active
    lists, dk/dv over the transposed (column-wise) lists — recomputing
    probabilities from q/k and the saved logsumexp. Nothing quadratic is
    saved or materialized in either direction. Gradient parity with the
    gather-based jnp oracle is proven in tests/test_sparse.py.

    ``interpret``: None = compiled on TPU, interpret elsewhere (the kernel
    default); the lowering gate (scripts/check_tpu_lowering.py) forces
    False to exercise the Mosaic pipeline off-hardware.
    """

    @jax.custom_vjp
    def f(q, k, v, mask):
        from alphafold2_tpu.ops.pallas.block_sparse import (
            pallas_block_sparse_attention,
        )

        return pallas_block_sparse_attention(
            q, k, v, layout, block_size, mask=mask, interpret=interpret
        )

    def fwd(q, k, v, mask):
        from alphafold2_tpu.ops.pallas.block_sparse import (
            pallas_block_sparse_attention,
        )

        out, lse = pallas_block_sparse_attention(
            q, k, v, layout, block_size, mask=mask, return_lse=True,
            interpret=interpret,
        )
        return out, (q, k, v, out, lse, mask)

    def bwd(res, g):
        q, k, v, out, lse, mask = res
        from alphafold2_tpu.ops.pallas.block_sparse import (
            pallas_block_sparse_attention_bwd,
        )

        dq, dk, dv = pallas_block_sparse_attention_bwd(
            q, k, v, out, lse, g, layout, block_size, mask=mask,
            interpret=interpret,
        )
        return dq, dk, dv, None

    f.defvjp(fwd, bwd)
    return f(q, k, v, mask)


@functools.lru_cache(maxsize=1)
def _block_layout_mask_cls():
    """The splash Mask subclass, built once (its base class lives inside
    the lazily-imported splash module). Module-level caching keeps mask
    equality/hashing stable across _splash_kernel calls — a per-call class
    would break __eq__'s isinstance against previously built masks."""
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_mask as sm,
    )

    class _BlockLayoutMask(sm.Mask):
        """Element-level view of a block-level layout, evaluated lazily:
        __getitem__ maps the requested element indices to layout blocks,
        touching only the requested chunk — nothing O(n^2) is ever
        materialized, at any sequence length (ADVICE r2)."""

        def __init__(self, layout: np.ndarray, block_size: int):
            self._layout = layout
            self._bs = block_size

        @property
        def shape(self):
            return (
                self._layout.shape[0] * self._bs,
                self._layout.shape[1] * self._bs,
            )

        def __getitem__(self, idx) -> np.ndarray:
            if not isinstance(idx, tuple) or len(idx) != 2:
                raise NotImplementedError(f"unsupported mask index {idx!r}")
            r = np.arange(self.shape[0])[idx[0]] // self._bs
            c = np.arange(self.shape[1])[idx[1]] // self._bs
            # dispatch on the ORIGINAL index types, not the resolved
            # arrays (ADVICE r3): numpy gives slice-involved indexing
            # outer-product semantics but array+array element-wise
            # *paired/broadcast* semantics, and a dense ndarray mask would
            # honor both — np.ix_ on a resolved integer-array pair would
            # silently return an outer-product block of the wrong shape
            # and values.
            if not isinstance(idx[0], slice) and not isinstance(idx[1], slice):
                return self._layout[r, c]  # paired/broadcast
            if r.ndim == 1 and c.ndim == 1:
                return self._layout[np.ix_(r, c)]  # outer product
            return self._layout[r, c]  # scalar-involved: broadcast

        def __eq__(self, other):
            if not isinstance(other, _BlockLayoutMask):
                return NotImplemented
            return self._bs == other._bs and np.array_equal(
                self._layout, other._layout
            )

        def __hash__(self):
            return hash(
                (type(self).__name__, self._bs, self._layout.tobytes())
            )

    return _BlockLayoutMask


@functools.lru_cache(maxsize=32)
def _splash_kernel(layout_bytes: bytes, nb: int, block_size: int, heads: int,
                   interpret: bool):
    """Build (and cache) a splash MHA kernel for a static block layout —
    mask preprocessing (MaskInfo construction) is trace-time work worth
    doing once per (layout, heads) rather than per call. The mask is
    served lazily from the (nb, nb) block layout via _block_layout_mask_cls
    (no dense element-level materialization)."""
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
        splash_attention_mask as sm,
    )

    layout = np.frombuffer(layout_bytes, dtype=bool).reshape(nb, nb)
    mask_cls = _block_layout_mask_cls()
    mh = sm.MultiHeadMask([mask_cls(layout, block_size)] * heads)
    return sk.make_splash_mha(
        mh, head_shards=1, q_seq_shards=1, interpret=interpret
    )


def block_sparse_attention_splash(
    q, k, v, layout: np.ndarray, block_size: int, mask=None
):
    """The stock jax splash-attention kernel over the same static layout —
    an alternative TPU backend to the in-repo Pallas kernels (fused
    forward + custom-VJP backward, schedules only the layout's active
    blocks). Padding composes via segment ids (valid=1, pad=0). Output at
    PADDED query rows is unspecified and differs from the jnp oracle —
    downstream masking makes those rows irrelevant (the loss excludes
    masked pairs), and valid-region parity (values and grads) is proven in
    interpret mode in tests/test_sparse.py."""
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
    )

    b, h, n, d = q.shape
    if n % 128 != 0:
        # the splash kernel's q/kv block size is 128: shorter/unaligned
        # sequences fall back to the gather oracle (same contract as
        # ops/flash.py — warn once, never crash training)
        warn_once(
            f"splash_unaligned_{n}",
            f"splash backend needs seq_len % 128 == 0, got {n}; "
            "falling back to the jnp gather implementation",
        )
        return block_sparse_attention(q, k, v, layout, block_size, mask=mask)
    if jax.default_backend() != "tpu":
        warn_once(
            "splash_interpret",
            "splash backend off-TPU runs the kernel in Pallas interpret "
            "mode (orders of magnitude slower) — fine for tests, wrong "
            "for real runs; use backend=\"auto\" or \"jnp\" off-TPU",
        )
    nb = layout.shape[0]
    kernel = _splash_kernel(
        np.ascontiguousarray(layout).tobytes(), nb, block_size, h,
        jax.default_backend() != "tpu",
    )
    seg = None
    if mask is not None:
        m = mask.astype(jnp.int32)
        seg = sk.SegmentIds(q=m, kv=m)
    out = jax.vmap(kernel)(q * (d**-0.5), k, v, segment_ids=seg)
    return out.astype(q.dtype)


class SparseAttention(nn.Module):
    """Block-sparse multi-head self-attention (drop-in for Attention).

    Pads the sequence to a block multiple (composing with, not clobbering,
    any caller mask) and slices the padding back off. ``seq_len`` bounds the
    allowed input length (reference alphafold2.py:194,215).
    """

    dim: int
    heads: int = 8
    dim_head: int = 64
    dropout: float = 0.0
    seq_len: Optional[int] = None
    config: BlockSparseConfig = BlockSparseConfig()
    use_pallas: Optional[bool] = None  # None -> Pallas kernel on TPU backends
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        inner = self.heads * self.dim_head
        self.to_q = nn.Dense(inner, use_bias=False, dtype=self.dtype)
        self.to_kv = nn.Dense(inner * 2, use_bias=False, dtype=self.dtype)
        self.to_out = nn.Dense(self.dim, dtype=self.dtype)
        self.out_dropout = nn.Dropout(self.dropout)

    def _impl(self):
        backend = getattr(self.config, "backend", "auto")
        # precedence: the explicit use_pallas bool (predates config.backend,
        # wins for back-compat) > a non-"auto" config.backend (a reviewed
        # per-module choice) > the KernelPolicy switchboard (ops/kernels.py
        # — one env var / ServeConfig field selects every kernel in the
        # tree consistently, and its identity rides in serve records)
        impls = {
            "jnp": block_sparse_attention,
            "pallas": block_sparse_attention_pallas,
            "splash": block_sparse_attention_splash,
        }
        if backend != "auto" and backend not in impls:
            raise ValueError(
                f"unknown sparse backend {backend!r}; have "
                f"{['auto', *impls]}"
            )
        if self.use_pallas is not None:
            return (
                block_sparse_attention_pallas
                if self.use_pallas
                else block_sparse_attention
            )
        if backend != "auto":
            return impls[backend]
        from alphafold2_tpu.ops.kernels import resolve_block_sparse

        return impls[resolve_block_sparse()]

    def grid_axial(self, x, mask=None, attend_axis: int = 2,
                   sharded: bool = True):
        """Block-sparse self-attention along ONE axis of a (B, H, W, D) grid
        2D-sharded over a (dp, spr, spc) mesh: after the all-to-all gathers
        the full attended axis per device, the local pass runs this module's
        block-sparse kernel instead of dense attention — O(N * active_blocks
        * block) logits per device, which is what makes 768+-crop grids fit
        (parallel/grid_parallel.py)."""
        h, dh = self.heads, self.dim_head
        n_att = x.shape[attend_axis]
        bs = self.config.block_size
        if n_att % bs != 0:
            raise ValueError(
                f"grid-sharded sparse attention needs the attended axis "
                f"({n_att}) to be a multiple of block_size ({bs})"
            )
        if self.seq_len is not None and n_att > self.seq_len:
            raise ValueError(
                f"attended axis {n_att} exceeds max_seq_len {self.seq_len}"
            )
        layout = self.config.layout(n_att)
        impl = self._impl()

        def attn_fn(q2, k2, v2, m2):
            return impl(q2, k2, v2, layout, bs, mask=m2)

        return grid_axial_project_attend(
            self.to_q, self.to_kv, self.to_out, h, dh,
            x, mask, attend_axis, attn_fn, sharded,
        )

    def __call__(
        self,
        x,
        context=None,
        mask=None,
        context_mask=None,
        tie_dim=None,
        deterministic: bool = True,
    ):
        if context is not None:
            raise ValueError("sparse attention is self-attention only")
        if tie_dim is not None:
            raise ValueError(
                "sparse attention is not compatible with tying of row "
                "attention"
            )
        b, n, _ = x.shape
        if self.seq_len is not None and n > self.seq_len:
            raise ValueError(
                f"sequence length {n} exceeds max_seq_len {self.seq_len}"
            )
        h, dh = self.heads, self.dim_head
        inner = h * dh
        bs = self.config.block_size
        pad = (-n) % bs
        if pad:
            x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        padded_n = n + pad
        if mask is None:
            mask = jnp.ones((b, n), dtype=bool)
        if pad:
            mask = jnp.pad(mask, ((0, 0), (0, pad)))

        q = self.to_q(x)
        k, v = jnp.split(self.to_kv(x), 2, axis=-1)

        def heads_first(t):
            return jnp.moveaxis(t.reshape(b, padded_n, h, dh), 2, 1)

        q, k, v = heads_first(q), heads_first(k), heads_first(v)
        layout = self.config.layout(padded_n)
        out = self._impl()(q, k, v, layout, bs, mask=mask)

        out = jnp.moveaxis(out, 1, 2).reshape(b, padded_n, inner)
        out = self.to_out(out)
        out = self.out_dropout(out, deterministic=deterministic)
        return out[:, :n]
