"""Exact chunked (online-softmax) attention for long-chain serving off-TPU.

The flash kernels (ops/flash.py) keep the N^2 attention matrix out of HBM,
but they are TPU-only — on the CPU mesh (and any backend without the Pallas
kernels) the dense jnp path materializes the full (B, H, Nq, Nk) logits.
At the serve ladder's long-chain rungs that is fatal: bucket 512 elongates
to N = 1536 pair tokens, and the N^2-query cross-attention alone would
build a ~50 GB logits tensor. This module is the backend-agnostic answer:
the classic two-level streaming formulation (Rabe & Staats; the same
recurrence the flash kernels hard-code) as plain jnp + ``lax.scan``:

- queries are processed in blocks (``lax.map`` — sequential, so only one
  block's intermediates are ever live);
- keys/values are streamed in chunks with a running (max, denominator,
  numerator) carry — softmax renormalized online, so the result is the
  EXACT dense softmax up to float reassociation (~1e-6), not an
  approximation;
- masking matches the dense path bit-for-bit in semantics: masked keys get
  ``MASK_VALUE`` logits *before* the online max, so fully-masked rows
  degrade to the same uniform attention the dense softmax produces.

Peak memory is O(q_chunk * kv_chunk) per (batch, head) instead of
O(Nq * Nk). ``should_chunk`` is the one routing policy: dense below
``CHUNK_THRESHOLD`` logits elements (the small-shape graphs — and their
committed contract fingerprints — stay byte-identical), chunked above.
"""

from __future__ import annotations

import os
from typing import Optional

import jax.numpy as jnp
from jax import lax

# logits elements (batch * heads * Nq * Nk) above which attention streams
# through the chunked path: 2**28 elements is ~1 GiB of f32 logits, past
# any shape the single-device serve/train flagships produce — their graphs
# (and the committed graph_contracts.json fingerprints) are unchanged.
CHUNK_THRESHOLD = int(os.environ.get("AF2TPU_ATTN_CHUNK_THRESHOLD", 2**28))

# per-tile logits budget (elements): chunk sizes adapt so one
# (batch*heads, q_chunk, kv_chunk) tile stays ~64 MiB of f32 whatever the
# batch dim is — the grid-sharded axial passes carry the row axis in batch
# (hundreds of rows), the flat cross-attention carries batch=B*heads only
TILE_ELEMENTS = int(os.environ.get("AF2TPU_ATTN_TILE_ELEMENTS", 2**24))

MASK_VALUE = -1e9  # keep in sync with ops.attention.MASK_VALUE


def _auto_chunk(batch_heads: int, n: int) -> int:
    """Largest power-of-two chunk (>=128, <=4096) whose tile fits the
    element budget for this batch size."""
    c = 4096
    while c > 128 and batch_heads * c * c > TILE_ELEMENTS:
        c //= 2
    return min(c, max(128, n))


def should_chunk(batch_heads: int, nq: int, nk: int) -> bool:
    """True when the dense (batch*heads, Nq, Nk) logits tensor is past the
    streaming threshold. All inputs are trace-time constants, so the
    decision is static per executable shape."""
    if CHUNK_THRESHOLD <= 0:
        return False
    return int(batch_heads) * int(nq) * int(nk) >= CHUNK_THRESHOLD


def _pad_axis(t, axis: int, pad: int, value=0):
    if pad == 0:
        return t
    widths = [(0, 0)] * t.ndim
    widths[axis] = (0, pad)
    return jnp.pad(t, widths, constant_values=value)


def chunked_attention(
    q: jnp.ndarray,  # (B, H, Nq, D)
    k: jnp.ndarray,  # (B, H, Nk, D)
    v: jnp.ndarray,
    q_mask: Optional[jnp.ndarray] = None,  # (B, Nq) bool valid-query
    kv_mask: Optional[jnp.ndarray] = None,  # (B, Nk) bool valid-key
    sm_scale: float = 1.0,
    q_chunk: Optional[int] = None,
    kv_chunk: Optional[int] = None,
) -> jnp.ndarray:
    """Exact attention with streamed logits; same contract as
    ops.flash.flash_attention (which it mirrors off-TPU) except it always
    succeeds. Masked queries produce zeros (the flash SegmentIds
    convention); masked keys are excluded exactly as the dense path's
    additive MASK_VALUE bias. Chunk sizes default to the largest tile
    within ``TILE_ELEMENTS`` for this batch*heads."""
    b, h, nq, d = q.shape
    nk = k.shape[2]
    q_chunk = min(q_chunk or _auto_chunk(b * h, nq), nq)
    kv_chunk = min(kv_chunk or _auto_chunk(b * h, nk), nk)
    pad_q = (-nq) % q_chunk
    pad_k = (-nk) % kv_chunk

    if pad_k and kv_mask is None:
        kv_mask = jnp.ones((b, nk), dtype=bool)
    q = _pad_axis(q, 2, pad_q)
    k = _pad_axis(k, 2, pad_k)
    v = _pad_axis(v, 2, pad_k)
    if kv_mask is not None:
        kv_mask = _pad_axis(kv_mask, 1, pad_k, value=False)
    if q_mask is not None:
        q_mask = _pad_axis(q_mask, 1, pad_q, value=False)
    nq_p, nk_p = nq + pad_q, nk + pad_k
    n_qb, n_kb = nq_p // q_chunk, nk_p // kv_chunk

    # kv chunks as scan inputs: (n_kb, B, H, kv_chunk, D)
    k_s = jnp.moveaxis(k.reshape(b, h, n_kb, kv_chunk, d), 2, 0)
    v_s = jnp.moveaxis(v.reshape(b, h, n_kb, kv_chunk, d), 2, 0)
    if kv_mask is not None:
        m_s = jnp.moveaxis(kv_mask.reshape(b, n_kb, kv_chunk), 1, 0)
    else:
        m_s = None

    def q_block(args):
        q_blk = args[0]

        def kv_step(carry, chunk):
            m_run, l_run, acc = carry
            if m_s is not None:
                k_c, v_c, km_c = chunk
            else:
                k_c, v_c = chunk
                km_c = None
            logits = (
                jnp.einsum("bhqd,bhkd->bhqk", q_blk, k_c).astype(jnp.float32)
                * sm_scale
            )
            if km_c is not None:
                logits = jnp.where(
                    km_c[:, None, None, :], logits, MASK_VALUE
                )
            m_new = jnp.maximum(m_run, logits.max(axis=-1))
            p = jnp.exp(logits - m_new[..., None])
            rescale = jnp.exp(m_run - m_new)
            l_new = l_run * rescale + p.sum(axis=-1)
            acc_new = acc * rescale[..., None] + jnp.einsum(
                "bhqk,bhkd->bhqd", p, v_c.astype(jnp.float32)
            )
            return (m_new, l_new, acc_new), None

        init = (
            jnp.full((b, h, q_chunk), -jnp.inf, jnp.float32),
            jnp.zeros((b, h, q_chunk), jnp.float32),
            jnp.zeros((b, h, q_chunk, d), jnp.float32),
        )
        xs = (k_s, v_s) if m_s is None else (k_s, v_s, m_s)
        (m_run, l_run, acc), _ = lax.scan(kv_step, init, xs)
        out = acc / jnp.maximum(l_run, 1e-30)[..., None]
        if len(args) > 1:  # masked queries emit zeros (flash convention)
            out = jnp.where(args[1][:, None, :, None], out, 0.0)
        return out.astype(q.dtype)

    # lax.map over query blocks: sequential, one block live at a time
    q_b = jnp.moveaxis(q.reshape(b, h, n_qb, q_chunk, d), 2, 0)
    if q_mask is not None:
        qm_b = jnp.moveaxis(q_mask.reshape(b, n_qb, q_chunk), 1, 0)
        xs_q = (q_b, qm_b)
    else:
        xs_q = (q_b,)
    out = lax.map(q_block, xs_q)  # (n_qb, B, H, q_chunk, D)
    out = jnp.moveaxis(out, 0, 2).reshape(b, h, nq_p, d)
    return out[:, :, :nq]


def chunked_attn_fn(sm_scale: float):
    """An ``attn_fn`` hook for the grid-sharded axial passes
    (parallel.grid_parallel._attend_last_grid_axis): takes row-flattened
    ``(B*R, H, N, D)`` q/k/v and a ``(B*R, N)`` key mask, returns the
    attended values in the same layout — or None (trace-time decline) when
    the dense logits are below the streaming threshold, keeping small
    shapes on the dense path."""

    def attn_fn(q2, k2, v2, m2):
        bsz, h, n, _ = q2.shape
        if not should_chunk(bsz * h, n, n):
            return None
        return chunked_attention(
            q2, k2, v2, q_mask=None, kv_mask=m2, sm_scale=sm_scale
        )

    # shape-only pre-probe (grid_parallel._attend_last_grid_axis): lets
    # the caller skip even the row-flattening ops when this hook would
    # decline, so small-shape jaxprs stay byte-identical to the
    # no-hook form
    attn_fn.accepts = lambda bsz, h, n: should_chunk(bsz * h, n, n)
    return attn_fn


def online_softmax_update(m_run, l_run, accs, logits, values):
    """One streaming-softmax accumulation step shared with consumers that
    fold extra per-edge aggregations into the same normalizer (the SE(3)
    refiner's vector updates): given this chunk's ``logits``
    (..., q, kchunk) f32 and a list of ``values`` each (..., q, kchunk, *),
    rescales the running (max, denom, numerators) and returns the updated
    carry. All numerators share the softmax normalizer ``l_run``."""
    m_new = jnp.maximum(m_run, logits.max(axis=-1))
    p = jnp.exp(logits - m_new[..., None])
    rescale = jnp.exp(m_run - m_new)
    l_new = l_run * rescale + p.sum(axis=-1)
    new_accs = []
    for acc, val in zip(accs, values):
        extra = val.ndim - p.ndim
        w = p.reshape(p.shape + (1,) * extra)
        r = rescale.reshape(rescale.shape + (1,) * (acc.ndim - rescale.ndim))
        new_accs.append(acc * r + (w * val).sum(axis=p.ndim - 1))
    return m_new, l_new, new_accs
