"""Dense attention primitives: FeedForward (GEGLU), Attention, AxialAttention.

TPU-native re-design of reference ``alphafold2_pytorch/alphafold2.py``:

- :class:`FeedForward`   <- alphafold2.py:53-74 (GEGLU + projections)
- :class:`Attention`     <- alphafold2.py:78-182 (self/cross, tied-row,
  memory-compressed KV)
- :class:`AxialAttention`<- alphafold2.py:241-287

Design (not a port):
- The reference flattens the pair map to an N^2 token stream and re-views it
  inside every axial block (alphafold2.py:472,259). Here the pair rep is a
  (B, H, W, D) grid end-to-end; the axial passes are plain batched attention
  with the non-attended axis folded into batch — static reshapes XLA removes.
- Row/column attention passes use one shared q/k/v projection applied to the
  whole grid once (the reference projects separately inside each of the two
  Attention submodules; two projections are kept for parameter parity of the
  two axes, but each is applied to a (B*, n, d) view with no copies).
- Tied-row attention (MSA-Transformer style) is a single einsum contracting
  the row axis with the extra r^-0.5 scale (alphafold2.py:151) — XLA fuses it;
  under a mesh the row axis can be sharded and the logits psum'd
  (see parallel/).
- Memory-compressed cross-attention KV downsampling (alphafold2.py:100-137)
  uses a strided grouped conv (lax.conv via nn.Conv, feature_group_count =
  heads) with sum-pooled masks.
- Masking is additive (large negative) with mask combination OR-free:
  ``mask[..., :, None] & context_mask[..., None, :]``; the tied-row path
  additionally zeroes padded q/k/v entries so they abstain from the shared
  (row-summed) logits exactly.
- Compute dtype is configurable (bfloat16 on TPU); params stay float32.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

MASK_VALUE = -1e9


def grid_axial_project_attend(
    to_q, to_kv, to_out, heads, dim_head, x, mask, attend_axis, attn_fn,
    sharded,
):
    """Shared grid_axial body for Attention and SparseAttention: pointwise
    q/kv projections on the (possibly sharded) grid, one axial pass with
    the module's fused per-device kernel, output projection.

    ``sharded=True`` runs the pass as an explicit shard_map over an active
    (dp, spr, spc) mesh — correct ONLY for arrays laid out P(dp, spr, spc)
    (the pair stream under grid_parallel). ``sharded=False`` runs the
    meshless grid-native formulation; under jit, GSPMD handles whatever
    sharding the array actually has (e.g. the MSA stream)."""
    from alphafold2_tpu.parallel.grid_parallel import grid_axial_attention
    from alphafold2_tpu.parallel.sharding import active_mesh

    b, gh, gw, _ = x.shape
    q = to_q(x).reshape(b, gh, gw, heads, dim_head)
    k, v = jnp.split(to_kv(x), 2, axis=-1)
    k = k.reshape(b, gh, gw, heads, dim_head)
    v = v.reshape(b, gh, gw, heads, dim_head)
    out = grid_axial_attention(
        q, k, v, mask=mask, mesh=active_mesh() if sharded else None,
        attend_axis=attend_axis, attn_fn=attn_fn,
    )
    return to_out(out.reshape(b, gh, gw, heads * dim_head))


class FeedForward(nn.Module):
    """GEGLU feedforward: Linear(d -> 2*mult*d) -> gated GELU -> Linear(mult*d -> d).

    ``gelu_exact``: the reference's torch ``F.gelu`` is the exact erf form
    (alphafold2.py:57); jax defaults to the tanh approximation, which is
    the faster choice on TPU and stays the default here — the flag exists
    so matched head-to-heads can eliminate the one remaining systematic
    functional divergence from the reference block.
    """

    dim: int
    mult: int = 4
    dropout: float = 0.0
    gelu_exact: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        inner = self.dim * self.mult
        h = nn.Dense(inner * 2, dtype=self.dtype, name="wi")(x)
        h, gates = jnp.split(h, 2, axis=-1)
        h = h * jax.nn.gelu(gates, approximate=not self.gelu_exact)
        h = nn.Dropout(self.dropout)(h, deterministic=deterministic)
        return nn.Dense(self.dim, dtype=self.dtype, name="wo")(h)


class Attention(nn.Module):
    """Multi-head attention with cross-attention, tied-row, and KV-compression.

    Feature parity with reference alphafold2.py:78-182:
    - ``context``/``context_mask`` for cross-attention
    - ``tie_dim``: fold a leading row axis (input (B*R, N, D)) into one shared
      attention matrix with r^-0.5 scaling. Unlike the reference (which
      forbids padding under tied rows, alphafold2.py:147-149), masks are
      handled here: padded (row, position) entries abstain from the shared
      logits and the row-count scale counts only voting rows. This equals
      attention on the cropped array when rows agree on masked positions
      (column padding — what MSA length padding is — and fully-masked
      rows); genuinely ragged per-row masks degrade gracefully (masked
      entries abstain) but have no cropped-array equivalent
    - ``compress_ratio`` > 1: strided grouped-conv KV compression (cross only)
    """

    dim: int
    heads: int = 8
    dim_head: int = 64
    dropout: float = 0.0
    compress_ratio: int = 1
    context_parallel: Optional[str] = None  # None | "ring" | "ulysses"
    use_flash: Optional[bool] = None  # None -> fused Pallas kernel on TPU
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        inner = self.heads * self.dim_head
        self.to_q = nn.Dense(inner, use_bias=False, dtype=self.dtype)
        self.to_kv = nn.Dense(inner * 2, use_bias=False, dtype=self.dtype)
        self.to_out = nn.Dense(self.dim, dtype=self.dtype)
        self.attn_dropout = nn.Dropout(self.dropout)
        if self.compress_ratio > 1:
            self.kv_compress = nn.Conv(
                inner,
                kernel_size=(self.compress_ratio,),
                strides=(self.compress_ratio,),
                feature_group_count=self.heads,
                padding="VALID",
                dtype=self.dtype,
            )

    def _use_flash(self) -> bool:
        """One place for the None -> auto-on-TPU flash policy (both the flat
        __call__ path and grid_axial consult it). The explicit module-level
        bool wins; the None case defers to the KernelPolicy switchboard
        (ops/kernels.py — AF2TPU_KERNELS / ServeConfig.kernels)."""
        if self.use_flash is None:
            from alphafold2_tpu.ops.kernels import resolve_flash

            return resolve_flash()
        return self.use_flash

    def grid_axial(self, x, mask=None, attend_axis: int = 2,
                   sharded: bool = True):
        """Self-attention along ONE axis of a (B, H, W, D) grid. With
        ``sharded=True`` and an active (dp, spr, spc) mesh the grid is
        2D-sharded (parallel/grid_parallel.py): projections are pointwise
        and run on the local shard; the attended axis is gathered by an
        all-to-all inside the primitive. On TPU the per-device
        attended-axis pass runs the fused flash kernel (falling back to
        exact dense attention); no tied rows / compression / broadcast
        context here."""
        dh = self.dim_head
        from alphafold2_tpu.ops.kernels import resolve_axial

        impl = resolve_axial()
        if impl == "pallas":
            # the in-repo fused kernel (ops/pallas/axial.py): compiled on
            # TPU, interpret-mode (exact, slow) elsewhere — selected only
            # by explicit KernelPolicy, never silently
            from alphafold2_tpu.ops.pallas.axial import axial_attn_fn

            attn_fn = axial_attn_fn(dh**-0.5)
        elif impl == "dense":
            attn_fn = None  # debug escape: plain per-device dense attention
        elif self._use_flash():
            from alphafold2_tpu.ops.flash import flash_attention

            def attn_fn(q2, k2, v2, m2):
                return flash_attention(
                    q2, k2, v2, q_mask=m2, kv_mask=m2, sm_scale=dh**-0.5
                )
        else:
            # off-TPU long-chain path: exact streamed attention once the
            # per-device logits would cross the chunk threshold; declines
            # (returns None) below it so small shapes stay dense
            from alphafold2_tpu.ops.chunked import chunked_attn_fn

            attn_fn = chunked_attn_fn(dh**-0.5)

        return grid_axial_project_attend(
            self.to_q, self.to_kv, self.to_out, self.heads, dh,
            x, mask, attend_axis, attn_fn, sharded,
        )

    def __call__(
        self,
        x,
        context=None,
        mask=None,
        context_mask=None,
        tie_dim: Optional[int] = None,
        deterministic: bool = True,
    ):
        h, dh = self.heads, self.dim_head
        inner = h * dh
        has_context = context is not None
        ctx = context if has_context else x

        q = self.to_q(x)
        k, v = jnp.split(self.to_kv(ctx), 2, axis=-1)

        if self.compress_ratio > 1:
            if not has_context:
                raise ValueError(
                    "KV compression is for cross-attention only"
                )
            ratio = self.compress_ratio
            j = k.shape[-2]
            pad = (-j) % ratio
            if pad:
                k = jnp.pad(k, ((0, 0), (0, pad), (0, 0)))
                v = jnp.pad(v, ((0, 0), (0, pad), (0, 0)))
            k = self.kv_compress(k)
            v = self.kv_compress(v)
            if context_mask is not None:
                cm = context_mask
                if pad:
                    cm = jnp.pad(cm, ((0, 0), (0, pad)))
                cm = cm.reshape(cm.shape[0], -1, ratio).sum(-1) > 0
                context_mask = cm
            elif pad:
                cm = jnp.pad(
                    jnp.ones((ctx.shape[0], j), dtype=bool), ((0, 0), (0, pad))
                )
                context_mask = cm.reshape(cm.shape[0], -1, ratio).sum(-1) > 0

        def split_heads(t):
            return t.reshape(*t.shape[:-1], h, dh)

        q, k, v = split_heads(q), split_heads(k), split_heads(v)  # (B, n, h, dh)
        scale = dh**-0.5

        # Fused-kernel gate for the paths below: tied rows keep their
        # bespoke dense contraction, and attention-weight dropout needs
        # materialized probabilities. KV compression composes with the
        # fused kernels — by this point k/v/context_mask are already the
        # compressed versions, and at large crops the fused path is what
        # keeps the (N^2 queries x compressed keys) logits out of HBM.
        fused_ok = tie_dim is None and (self.dropout == 0.0 or deterministic)
        kv_mask = context_mask
        if kv_mask is None and not has_context:
            kv_mask = mask

        def heads_first(t):
            return jnp.moveaxis(t, -2, 1)

        def project_out(out):  # (B, H, n, dh) -> (B, n, dim)
            out = jnp.moveaxis(out, 1, -2).reshape(*x.shape[:-1], inner)
            return self.to_out(out)

        # context-parallel path: exact attention with the sequence axis
        # sharded over the mesh's sp axis (ring ppermute or Ulysses
        # all-to-all — parallel/seq_parallel.py), when a mesh is active.
        # (compression is excluded here: the compressed KV length no longer
        # matches the sequence-parallel shard layout)
        if (
            self.context_parallel is not None
            and fused_ok
            and self.compress_ratio == 1
        ):
            from alphafold2_tpu.parallel.seq_parallel import (
                SEQ_AXIS_NAME,
                sequence_parallel_attention,
            )
            from alphafold2_tpu.parallel.sharding import active_mesh

            mesh = active_mesh()
            if mesh is not None and SEQ_AXIS_NAME in mesh.axis_names:
                out = sequence_parallel_attention(
                    heads_first(q),
                    heads_first(k),
                    heads_first(v),
                    mask=kv_mask,
                    mesh=mesh,
                    impl=self.context_parallel,
                )  # (B, H, n, dh)
                return project_out(out)

        # fused flash-attention path (TPU): the (n, n) attention matrix stays
        # in VMEM instead of HBM.
        if self._use_flash() and fused_ok:
            from alphafold2_tpu.ops.flash import flash_attention

            out = flash_attention(
                heads_first(q),
                heads_first(k),
                heads_first(v),
                q_mask=mask,
                kv_mask=kv_mask,
                sm_scale=scale,
            )
            if out is not None:
                return project_out(out)

        # exact streamed attention off-TPU once the dense logits would
        # cross the chunk threshold (ops/chunked.py): the long-chain serve
        # buckets' N^2-query cross-attention would otherwise materialize
        # tens of GB. Below the threshold the dense path (and its
        # committed graph fingerprints) is untouched.
        if fused_ok:
            from alphafold2_tpu.ops.chunked import (
                chunked_attention,
                should_chunk,
            )

            if should_chunk(q.shape[0] * h, q.shape[1], k.shape[1]):
                out = chunked_attention(
                    heads_first(q),
                    heads_first(k),
                    heads_first(v),
                    q_mask=mask,
                    kv_mask=kv_mask,
                    sm_scale=scale,
                )
                return project_out(out)

        if tie_dim is not None:
            # (B*R, n, h, d) -> (B, R, n, h, d); one attention matrix per (B, h)
            r = tie_dim
            q, k, v = (t.reshape(-1, r, *t.shape[1:]) for t in (q, k, v))
            tie_scale = r**-0.5
            kv_side = context_mask if has_context else mask
            if mask is not None or kv_side is not None:
                # The reference hard-asserts tied rows never see padding
                # (alphafold2.py:147-149). Here padding is exact instead:
                # each padded (row, position) ABSTAINS from the shared
                # logits (its q/k zeroed) and from the per-row output (its
                # v zeroed), the row-count scale uses the number of rows
                # that actually vote, and the softmax sees the shared
                # column mask. For column padding (every row masks the same
                # positions — what MSA length padding is) this equals
                # attention on the cropped array; fully-masked rows are
                # likewise exact (they abstain entirely). Query and kv
                # sides are masked independently so tied cross-attention
                # (broadcast context) works too.
                bt, n, j = q.shape[0], q.shape[2], k.shape[2]
                qr = (
                    mask.reshape(bt, r, n)
                    if mask is not None
                    else jnp.ones((bt, r, n), dtype=bool)
                )
                kr = (
                    kv_side.reshape(bt, r, j)
                    if kv_side is not None
                    else jnp.ones((bt, r, j), dtype=bool)
                )
                q = jnp.where(qr[..., None, None], q, 0)
                k = jnp.where(kr[..., None, None], k, 0)
                v = jnp.where(kr[..., None, None], v, 0)
                # a row votes in the logit sum iff it has both a valid
                # query and a valid key position
                n_rows = jnp.maximum((qr.any(-1) & kr.any(-1)).sum(-1), 1)
                tie_scale = (
                    n_rows.astype(jnp.float32) ** -0.5
                )[:, None, None, None].astype(self.dtype)
                # shared masks for the softmax below (batch dim B, not B*R)
                mask = qr.any(1)
                context_mask = kr.any(1) if has_context else None

            # fused tied-row kernel (ops/pallas/tied_row.py, selected by
            # the KernelPolicy switchboard): the shared (B, H, n, j) logits
            # stay in VMEM via the fused (row, head_dim) contraction; the
            # abstention masking and voting-row tie scale above are already
            # applied, so the kernel sees exactly the dense inputs. Active
            # attention-weight dropout keeps the dense path (it needs
            # materialized probabilities).
            from alphafold2_tpu.ops.kernels import resolve_tied_row

            if resolve_tied_row() == "pallas" and (
                self.dropout == 0.0 or deterministic
            ):
                from alphafold2_tpu.ops.pallas.tied_row import (
                    tied_row_attention,
                )

                km = context_mask if has_context else mask
                out = tied_row_attention(
                    q, k, v, q_mask=mask, kv_mask=km,
                    sm_scale=scale, tie_scale=tie_scale,
                )  # (B, R, n, h, dh)
                out = out.reshape(-1, *out.shape[2:])
                out = out.reshape(*out.shape[:-2], inner)
                return self.to_out(out)
            dots = jnp.einsum("brihd,brjhd->bhij", q, k) * scale * tie_scale
        else:
            dots = jnp.einsum("bihd,bjhd->bhij", q, k) * scale

        if mask is not None or context_mask is not None:
            i, j = dots.shape[-2], dots.shape[-1]
            b = dots.shape[0]
            qm = mask if mask is not None else jnp.ones((1, i), dtype=bool)
            if context_mask is not None:
                km = context_mask
            elif not has_context and mask is not None:
                km = mask
            else:
                km = jnp.ones((1, j), dtype=bool)
            pair = qm[:, None, :, None] & km[:, None, None, :]
            dots = jnp.where(pair, dots, MASK_VALUE)

        attn = jax.nn.softmax(dots.astype(jnp.float32), axis=-1).astype(self.dtype)
        attn = self.attn_dropout(attn, deterministic=deterministic)

        if tie_dim is not None:
            out = jnp.einsum("bhij,brjhd->brihd", attn, v)
            out = out.reshape(-1, *out.shape[2:])
        else:
            out = jnp.einsum("bhij,bjhd->bihd", attn, v)

        out = out.reshape(*out.shape[:-2], inner)
        return self.to_out(out)


class AxialAttention(nn.Module):
    """Factorized attention over a 2D grid: column pass + row pass, summed.

    Operates directly on (B, H, W, D) (+ optional (B, H, W) mask), unlike the
    reference which round-trips through a flat (B, H*W, D) stream
    (alphafold2.py:256-287). An optional cross-attention ``context``
    (B, Nc, D) is broadcast to every row/column. ``tie_row_attn`` ties the row
    (height) pass across rows — used for the MSA grid where H = num
    alignments. ``sparse_attn`` swaps the column/row attention for
    block-sparse attention (ops/sparse.py).
    """

    dim: int
    heads: int = 8
    dim_head: int = 64
    dropout: float = 0.0
    tie_row_attn: bool = False
    sparse_attn: bool = False
    seq_len: Optional[int] = None  # static max length for sparse block layout
    sparse_config: Optional[object] = None  # ops.sparse.BlockSparseConfig
    sparse_use_pallas: Optional[bool] = None  # None -> auto (Pallas on TPU)
    use_flash: Optional[bool] = None  # dense path: fused kernel on TPU
    grid_parallel: bool = False  # 2D-sharded passes over a (dp, spr, spc) mesh
    grid_native: bool = True  # grid-layout self-attn passes (no pair-map
    # transpose materialization); False forces the flat (B*, n, d) route
    dtype: jnp.dtype = jnp.float32

    def _attn_cls(self, name):
        if self.sparse_attn:
            from alphafold2_tpu.ops.sparse import BlockSparseConfig, SparseAttention

            return SparseAttention(
                dim=self.dim,
                heads=self.heads,
                dim_head=self.dim_head,
                dropout=self.dropout,
                seq_len=self.seq_len,
                config=self.sparse_config or BlockSparseConfig(),
                use_pallas=self.sparse_use_pallas,
                dtype=self.dtype,
                name=name,
            )
        return Attention(
            dim=self.dim,
            heads=self.heads,
            dim_head=self.dim_head,
            dropout=self.dropout,
            use_flash=self.use_flash,
            dtype=self.dtype,
            name=name,
        )

    @nn.compact
    def __call__(
        self,
        x,
        mask=None,
        context=None,
        context_mask=None,
        deterministic: bool = True,
    ):
        b, height, w, d = x.shape
        attn_width = self._attn_cls("attn_width")
        attn_height = self._attn_cls("attn_height")

        grid_mesh_active = False
        if self.grid_parallel:
            from alphafold2_tpu.parallel.grid_parallel import ROW_AXIS_NAME
            from alphafold2_tpu.parallel.sharding import active_mesh

            mesh = active_mesh()
            if mesh is not None and ROW_AXIS_NAME in mesh.axis_names:
                if context is not None or self.tie_row_attn:
                    raise ValueError(
                        "grid_parallel axial attention is self-attention "
                        "only (no broadcast context, no tied rows — "
                        "neither occurs on the pair stream)"
                    )
                grid_mesh_active = True

        # Grid route: q/kv/out projections stay pointwise on the
        # (B, H, W, D) grid — the flat route instead materializes a
        # transpose of the whole pair map for the column pass, a full extra
        # HBM round-trip per axial block. Each pass runs the module's fused
        # per-device kernel (flash / block-sparse); with grid_parallel and
        # an active (dp, spr, spc) mesh it is the explicit 2D-sharded
        # shard_map pass. Constraints: self-attention only, untied, no
        # active attention-weight dropout (the fused kernels never
        # materialize probabilities), and block-aligned axes for sparse
        # layouts. grid_native=False is a debug escape back to the flat
        # route — but never under an active grid mesh, where the flat
        # route's transpose of the 2D-sharded pair map would be a silent
        # memory/perf cliff.
        grid_ok = (
            (self.grid_native or grid_mesh_active)
            and context is None
            and not self.tie_row_attn
            and (self.dropout == 0.0 or deterministic)
        )
        if grid_ok and self.sparse_attn:
            from alphafold2_tpu.ops.sparse import BlockSparseConfig

            bs = (self.sparse_config or BlockSparseConfig()).block_size
            aligned = height % bs == 0 and w % bs == 0
            if grid_mesh_active and not aligned:
                # meshless flat sparse pads unaligned crops, but there is
                # no sharded flat route — refuse rather than silently
                # running unsharded at the crop sizes grid_parallel targets
                raise ValueError(
                    f"grid_parallel sparse attention needs block-aligned "
                    f"grid axes: ({height}, {w}) vs block_size {bs}; pad "
                    "the crop or change sparse_config.block_size"
                )
            grid_ok = aligned
        if grid_ok:
            # attn_width attends within columns (over rows, axis 1),
            # attn_height within rows (over columns, axis 2). Only the
            # grid_parallel pair stream is laid out P(dp, spr, spc) —
            # everything else (e.g. the MSA grid) must NOT enter the
            # explicit shard_map and relies on GSPMD instead.
            sharded = grid_mesh_active
            w_out = attn_width.grid_axial(
                x, mask=mask, attend_axis=1, sharded=sharded
            )
            h_out = attn_height.grid_axial(
                x, mask=mask, attend_axis=2, sharded=sharded
            )
            return w_out + h_out

        def broadcast_ctx(n_batch):
            if context is None:
                return {}
            nc = context.shape[1]
            c = jnp.broadcast_to(
                context[:, None], (b, n_batch // b, nc, context.shape[-1])
            ).reshape(n_batch, nc, context.shape[-1])
            cm = None
            if context_mask is not None:
                cm = jnp.broadcast_to(
                    context_mask[:, None], (b, n_batch // b, nc)
                ).reshape(n_batch, nc)
            return {"context": c, "context_mask": cm}

        # column pass: attend over the height axis within each column
        w_x = jnp.swapaxes(x, 1, 2).reshape(b * w, height, d)
        w_mask = (
            jnp.swapaxes(mask, 1, 2).reshape(b * w, height) if mask is not None else None
        )
        w_out = attn_width(
            w_x, mask=w_mask, deterministic=deterministic, **broadcast_ctx(b * w)
        )
        w_out = jnp.swapaxes(w_out.reshape(b, w, height, d), 1, 2)

        # row pass: attend over the width axis within each row (optionally tied)
        h_x = x.reshape(b * height, w, d)
        h_mask = mask.reshape(b * height, w) if mask is not None else None
        tie = {"tie_dim": height} if self.tie_row_attn else {}
        h_out = attn_height(
            h_x, mask=h_mask, deterministic=deterministic, **broadcast_ctx(b * height), **tie
        )
        h_out = h_out.reshape(b, height, w, d)

        return w_out + h_out
