from alphafold2_tpu.ops.attention import Attention, AxialAttention, FeedForward
