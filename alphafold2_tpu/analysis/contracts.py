"""Graph contracts (layer 3): jaxpr fingerprints diffed in CI.

``observe/regress.py`` gates *runtime* perf against a committed baseline;
this module does the same for *graph shape*. Every registered executable
(:mod:`targets`) gets a fingerprint — primitive op counts, equation count,
baked-const footprint, flat input signature, input treedef, donation map —
committed as ``graph_contracts.json``. CI recomputes and diffs: silent
graph bloat (a remat dropped, an attention path duplicated, a new host
callback) or a new recompile key (input signature / treedef change) fails
the build with a readable per-primitive diff instead of surfacing weeks
later as an unexplained TPU slowdown.

Fingerprints are exact, not thresholded: a jaxpr is deterministic for a
given jax version, so ANY drift is either intentional (re-baseline with
``--update``) or a regression. Baselines are keyed by ``jax.__version__``;
a version mismatch reports ``stale-baseline`` (rc 0, loudly) rather than
failing on upstream tracing changes the repo does not control.

CLI::

    JAX_PLATFORMS=cpu python -m alphafold2_tpu.analysis.contracts --check
    JAX_PLATFORMS=cpu python -m alphafold2_tpu.analysis.contracts --update

Exit codes for ``--check``: 0 contracts hold (or stale baseline),
1 drift, 2 usage error / missing baseline.

Re-baselining policy: ``--update`` after an INTENTIONAL graph change, and
the diff the check printed belongs in the PR description — the contract
file exists so graph changes are reviewed, not discovered.
"""

from __future__ import annotations

import json
import os
from typing import Optional

FORMAT_VERSION = 1

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "graph_contracts.json",
)


# ------------------------------------------------------------ fingerprints


def op_counts(closed) -> dict:
    """Primitive name -> occurrence count, recursing into sub-jaxprs."""
    from alphafold2_tpu.analysis.jaxpr_audit import iter_eqns

    counts: dict = {}
    for eqn in iter_eqns(closed.jaxpr):
        name = eqn.primitive.name
        counts[name] = counts.get(name, 0) + 1
    return dict(sorted(counts.items()))


def fingerprint_target(target) -> dict:
    import jax

    from alphafold2_tpu.analysis.targets import example_arg_summary

    fn, args = target.build()
    closed = jax.make_jaxpr(fn)(*args)
    counts = op_counts(closed)
    const_bytes = 0
    for const in closed.consts:
        try:
            const_bytes += int(const.nbytes)
        except Exception:  # extended dtypes (PRNG keys) have no nbytes
            import numpy as np

            itemsize = getattr(
                getattr(const, "dtype", None), "itemsize", None
            )
            const_bytes += int(
                np.prod(tuple(getattr(const, "shape", ())))
            ) * int(itemsize or 4)
    _, in_treedef = jax.tree.flatten(args)
    # static pytree fields (TrainState.apply_fn, ...) repr with memory
    # addresses; scrub them or the treedef string differs every process
    import re

    treedef_str = re.sub(r"0x[0-9a-f]+", "0x", str(in_treedef))
    return {
        "ops": counts,
        "n_eqns": sum(counts.values()),
        "n_consts": len(closed.consts),
        "const_bytes": const_bytes,
        "n_outputs": len(closed.jaxpr.outvars),
        "inputs": example_arg_summary(args),
        "in_treedef": treedef_str,
        "donation": sorted(target.donate_argnums),
    }


def compute_contracts(targets=None) -> dict:
    import jax

    from alphafold2_tpu.analysis.targets import default_targets

    targets = targets if targets is not None else default_targets()
    return {
        "format": FORMAT_VERSION,
        "jax_version": jax.__version__,
        "targets": {t.name: fingerprint_target(t) for t in targets},
    }


# -------------------------------------------------------------------- diff


def _diff_ops(name: str, old: dict, new: dict) -> list:
    lines = []
    for prim in sorted(set(old) | set(new)):
        a, b = old.get(prim, 0), new.get(prim, 0)
        if a != b:
            lines.append(
                f"{name}: op count drift: {prim}: {a} -> {b} ({b - a:+d})"
            )
    return lines


def diff_contracts(baseline: dict, current: dict) -> list:
    """Readable drift lines between two contract documents (empty = the
    contracts hold). Input-signature and donation drifts are flagged as
    recompile-key changes; op drifts as graph-shape changes."""
    lines: list = []
    base_t = baseline.get("targets", {})
    cur_t = current.get("targets", {})
    for name in sorted(set(base_t) - set(cur_t)):
        lines.append(f"{name}: target removed (was under contract)")
    for name in sorted(set(cur_t) - set(base_t)):
        lines.append(f"{name}: new target (no committed contract)")
    for name in sorted(set(base_t) & set(cur_t)):
        old, new = base_t[name], cur_t[name]
        if old.get("inputs") != new.get("inputs"):
            lines.append(
                f"{name}: RECOMPILE KEY: flat input signature changed: "
                f"{old.get('inputs')} -> {new.get('inputs')}"
            )
        if old.get("in_treedef") != new.get("in_treedef"):
            lines.append(
                f"{name}: RECOMPILE KEY: input treedef changed "
                "(argument pytree structure)"
            )
        if old.get("donation") != new.get("donation"):
            lines.append(
                f"{name}: donation map changed: {old.get('donation')} -> "
                f"{new.get('donation')}"
            )
        lines.extend(_diff_ops(name, old.get("ops", {}), new.get("ops", {})))
        for field in ("n_consts", "const_bytes", "n_outputs"):
            if old.get(field) != new.get(field):
                lines.append(
                    f"{name}: {field}: {old.get(field)} -> {new.get(field)}"
                )
    return lines


def check_against(
    baseline_path: str = DEFAULT_BASELINE, targets=None
) -> dict:
    """Structured verdict: ``{"verdict": "pass"|"drift"|"stale-baseline"|
    "missing-baseline", ...}`` mirroring observe.regress's explicit
    no-data third state."""
    import jax

    if not os.path.exists(baseline_path):
        return {
            "verdict": "missing-baseline",
            "baseline": baseline_path,
            "reason": "no committed graph_contracts.json; run --update",
        }
    with open(baseline_path, "r", encoding="utf-8") as fh:
        baseline = json.load(fh)
    current = compute_contracts(targets)
    out = {
        "baseline": baseline_path,
        "baseline_jax": baseline.get("jax_version"),
        "current_jax": jax.__version__,
    }
    if baseline.get("jax_version") != jax.__version__:
        # an upstream tracing change is not a repo regression: report
        # loudly, do not fail the build, and ask for a re-baseline
        return {
            **out,
            "verdict": "stale-baseline",
            "reason": (
                f"baseline traced under jax {baseline.get('jax_version')}, "
                f"running {jax.__version__}; re-baseline with --update"
            ),
        }
    diffs = diff_contracts(baseline, current)
    return {
        **out,
        "verdict": "drift" if diffs else "pass",
        "diffs": diffs,
        "current": current,
    }


# --------------------------------------------------------------------- CLI


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    mode = parser.add_mutually_exclusive_group(required=True)
    mode.add_argument(
        "--check", action="store_true",
        help="diff current fingerprints against the committed baseline",
    )
    mode.add_argument(
        "--update", action="store_true",
        help="recompute fingerprints and rewrite the baseline",
    )
    parser.add_argument("--baseline", default=DEFAULT_BASELINE)
    parser.add_argument(
        "--json", dest="json_path", default=None,
        help="write the structured verdict/contracts JSON to this path",
    )
    args = parser.parse_args(argv)

    if args.update:
        contracts = compute_contracts()
        with open(args.baseline, "w", encoding="utf-8") as fh:
            json.dump(contracts, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(
            f"graph_contracts: baselined {len(contracts['targets'])} "
            f"target(s) under jax {contracts['jax_version']} -> "
            f"{args.baseline}"
        )
        if args.json_path:
            with open(args.json_path, "w", encoding="utf-8") as fh:
                json.dump(contracts, fh, indent=2, sort_keys=True)
        return 0

    result = check_against(args.baseline)
    if args.json_path:
        with open(args.json_path, "w", encoding="utf-8") as fh:
            json.dump(result, fh, indent=2, sort_keys=True)
    for line in result.get("diffs", []):
        print(f"graph-contract DRIFT: {line}")
    print(f"graph_contracts: verdict={result['verdict']}"
          + (f" ({result['reason']})" if result.get("reason") else ""))
    if result["verdict"] == "missing-baseline":
        return 2
    return 1 if result["verdict"] == "drift" else 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
