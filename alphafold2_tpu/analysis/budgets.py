"""Declared HBM budgets and verdicts for compiled executables.

The "forgot-the-sharding cliff" is a *memory* cliff first: replicating the
N^2 pair state onto every device multiplies the per-device footprint by the
mesh size long before it shows up as a latency regression. Until now the
only guard was a runtime bench threshold (``per_device_program_bytes`` at
2x in observe/regress.py) — this module makes the figure a *static
contract*: each audited target (analysis/targets.py) declares an
``hbm_budget_bytes`` ceiling, the HLO audit (analysis/hlo_audit.py) reads
the per-device footprint from XLA ``memory_analysis()`` at compile time,
and :func:`check_budget` turns the pair into a three-way verdict:

- ``pass``        — footprint measured and under budget (headroom reported)
- ``over-budget`` — footprint measured and over; the gate fails (AF2A110)
- ``no-data``     — no declared budget or no backend figure; the gate does
                    not fail, but the verdict is loud so "we never gated
                    this rung" can't masquerade as "this rung fits"

:func:`lattice_report` extends the same contract to a live ServeEngine: it
walks every (bucket, batch) rung the engine's ladder admits, compiles each
(the engine's own AOT path, so records/counters ride along), and gates
per-rung footprints against the device HBM — the offline pre-validation
the compile-once roadmap item asks for before a lattice is persisted.

Pure-stdlib except where a compiled executable is already in hand; no jax
import at module scope so verdict logic is testable in milliseconds.
"""

from __future__ import annotations

import os
from typing import Optional

# Published per-chip HBM for device kinds the bench stack meets; the
# serving budget is a fraction of this (XLA reserves workspace and the
# runtime needs headroom for infeed/outfeed and donation churn).
DEVICE_HBM_BYTES = {
    "TPU v4": 32 << 30,
    "TPU v5e": 16 << 30,
    "TPU v5p": 95 << 30,
    "TPU v6e": 32 << 30,
}

# Fraction of physical HBM a serve lattice may plan to; the rest is
# runtime/workspace headroom.
DEFAULT_HBM_FRACTION = 0.9


def device_hbm_bytes(device=None) -> Optional[int]:
    """Physical HBM of ``device`` (default: first jax device).

    ``AF2TPU_HBM_BYTES`` overrides (the knob for CPU meshes and for
    planning against a *smaller* chip than the one compiling). None when
    the device kind is unknown — CPUs included, where "HBM" is
    meaningless and the lattice report degrades to no-data verdicts.
    """
    env = os.environ.get("AF2TPU_HBM_BYTES")
    if env:
        return int(env)
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        kind = device.device_kind
        return next(
            (v for k, v in DEVICE_HBM_BYTES.items()
             if k.lower() in kind.lower()),
            None,
        )
    except Exception:
        return None


def check_budget(
    program_bytes: Optional[int], budget_bytes: Optional[int]
) -> dict:
    """Gate a measured per-device footprint against a declared budget.

    Returns ``{"verdict", "program_bytes", "budget_bytes", ...}`` with
    ``headroom_frac`` (fraction of budget still free; negative when over)
    on measured verdicts and a ``reason`` on no-data ones. Never raises:
    the verdict IS the error channel.
    """
    rec = {
        "program_bytes": int(program_bytes) if program_bytes else None,
        "budget_bytes": int(budget_bytes) if budget_bytes else None,
    }
    if not budget_bytes:
        rec.update(verdict="no-data", reason="no declared hbm budget")
        return rec
    if not program_bytes:
        rec.update(
            verdict="no-data",
            reason="backend exposes no memory_analysis figure",
        )
        return rec
    rec["headroom_frac"] = round(1.0 - program_bytes / budget_bytes, 4)
    rec["verdict"] = "pass" if program_bytes <= budget_bytes else "over-budget"
    return rec


def format_budget(name: str, rec: dict) -> str:
    """One human line per verdict, bench_compare-style."""
    pb, bb = rec.get("program_bytes"), rec.get("budget_bytes")
    if rec["verdict"] == "no-data":
        return f"{name}: no-data ({rec.get('reason', '?')})"
    frac = rec.get("headroom_frac", 0.0)
    return (
        f"{name}: {rec['verdict']} — {pb} / {bb} bytes per device "
        f"({frac:+.1%} headroom)"
    )


def lattice_report(
    engine, hbm_bytes: Optional[int] = None,
    hbm_fraction: float = DEFAULT_HBM_FRACTION,
) -> dict:
    """Pre-validate a ServeEngine's full (bucket, batch, mesh) executable
    lattice offline: compile every rung the ladder admits and gate each
    per-device footprint against ``hbm_fraction`` of the device HBM
    (override with ``hbm_bytes``; unknown devices yield no-data verdicts).

    Returns ``{"mesh", "hbm_budget_bytes", "rungs": [...], "verdict"}``
    where the overall verdict is over-budget if ANY rung is.
    """
    from alphafold2_tpu.analysis.hlo_audit import collective_census
    from alphafold2_tpu.observe.flops import (
        executable_costs,
        executable_memory,
    )
    from alphafold2_tpu.parallel.sharding import DATA_AXIS, describe_mesh

    if hbm_bytes is None:
        raw = device_hbm_bytes()
        hbm_bytes = int(raw * hbm_fraction) if raw else None

    rungs = []
    for bucket in engine.buckets:
        # same rung geometry as ServeEngine.warmup: padded dispatch batch,
        # rounded up to the dp axis so shardings divide
        batch = (
            engine.batch_for(bucket) if engine.cfg.serve.pad_batches else 1
        )
        if engine.mesh is not None:
            n_dp = dict(
                zip(engine.mesh.axis_names, engine.mesh.devices.shape)
            ).get(DATA_AXIS, 1)
            batch += (-batch) % n_dp
        compiled = engine._get_executable(bucket, batch)
        memory = executable_memory(compiled)
        costs = executable_costs(compiled)
        census = {}
        if engine.mesh is not None:
            try:
                census = collective_census(compiled.as_text())
            except Exception:
                census = {}
        budget = check_budget(memory.get("program_bytes"), hbm_bytes)
        rungs.append({
            "bucket": int(bucket),
            "batch": int(batch),
            **memory,
            "flops": costs.get("flops"),
            "collectives": {k: v["count"] for k, v in census.items()},
            "comm_bytes": sum(v["bytes"] for v in census.values()),
            "budget": budget,
        })
    verdicts = {r["budget"]["verdict"] for r in rungs}
    overall = (
        "over-budget" if "over-budget" in verdicts
        else "pass" if verdicts == {"pass"}
        else "no-data"
    )
    return {
        "mesh": describe_mesh(engine.mesh),
        "hbm_budget_bytes": hbm_bytes,
        "rungs": rungs,
        "verdict": overall,
    }
