"""JAX graph-hygiene AST linter (layer 1 of the static-analysis subsystem).

The trunk flattens the pairwise map into an N^2 token stream, so a single
accidental host sync or retrace inside the jitted path is paid at quadratic
scale on hardware we cannot iterate on interactively. PRs 2-3 built the
*runtime* half (tracing, numerics, the bench-compare gate); this module is
the *static* half: purely syntactic rules over the package source that flag
graph-hygiene bugs at lint time, before a chip ever runs them.

Rules (id, severity):

- ``AF2L001`` error — Python ``if``/``while`` truthiness on a traced
  function parameter inside a jit context (concretization error at trace
  time, or worse: silently baked-in branch).
- ``AF2L002`` error — host sync under trace: ``.item()`` / ``.tolist()`` /
  ``.block_until_ready()`` / ``np.asarray`` / ``np.array`` /
  ``jax.device_get`` / builtin ``float``/``int``/``bool`` applied to a
  traced parameter.
- ``AF2L003`` error — wall-clock read under trace (``time.time`` /
  ``perf_counter`` / ``monotonic`` / ``datetime.now``): trace-time constant
  masquerading as a timestamp.
- ``AF2L004`` error — non-JAX RNG under trace (``random.*``,
  ``np.random.*``): trace-time constant masquerading as randomness.
- ``AF2L005`` warning — mutable default argument (shared across calls).
- ``AF2L006`` warning — bare ``except:`` (swallows KeyboardInterrupt and
  the tracer errors the other rules exist to surface).
- ``AF2L007`` warning — traced parameter of a jitted function used where
  only a Python value works (``range()``, f-string): needs
  ``static_argnames``/``static_argnums``.
- ``AF2L008`` warning — ``print`` under trace (fires at trace time only;
  use ``jax.debug.print`` or the observe subsystem).
- ``AF2L009`` warning — host side effect under trace (counter ``.bump`` /
  histogram ``.observe`` / ``logging``): runs per *trace*, not per step.

Threaded-serve rules (the async frontend runs a dispatcher thread next to
caller threads; these rules lint the locking discipline of any class that
creates a ``threading`` lock):

- ``AF2L010`` error — blocking call (``time.sleep``, file/socket/
  subprocess I/O) while holding a lock: every other thread stalls behind
  the critical section. ``.wait()`` is exempt — ``Condition.wait``
  *releases* the lock by design.
- ``AF2L011`` warning — an attribute that is mutated under the class's
  lock somewhere is mutated *outside* it elsewhere (``__init__``
  excepted): either the lock is unnecessary or the unlocked write is a
  race.
- ``AF2L012`` error — host sync (``device_get`` / ``.item()`` /
  ``.block_until_ready()`` / ``np.asarray``) directly in a function used
  as a ``threading.Thread`` target: the dispatcher thread exists to keep
  the device pipeline full, and a sync in its body serializes it.

Like everything here these are syntactic: AF2L011 sees direct ``self.x``
mutations (not aliases), AF2L012 sees the thread body function itself (no
call graph). The reviewable-by-grep class of bug, no more.

A *jit context* is a function that is (a) decorated with ``jax.jit`` /
``jit`` / ``partial(jax.jit, ...)``, (b) passed to a ``*.jit(...)`` call
anywhere in the same module (``jax.jit(step, ...)``, ``jax.jit(self._fwd,
donate_argnums=...)``), or (c) passed as the body of a ``lax`` control-flow
primitive (``scan``/``while_loop``/``fori_loop``/``cond``/``switch``).
Functions nested inside a jit context inherit it (closures are traced too).
Parameters named in ``static_argnames``/``static_argnums`` are exempt.

Suppression: ``# af2: noqa[AF2L001]`` (comma-separated ids) or a blanket
``# af2: noqa`` on the finding's line. Suppressions should carry a reason
in the surrounding comment — they are reviewed, not free.

Scope and honesty: this is syntactic analysis. It tracks direct parameter
references, not dataflow through locals, so it catches the
reviewable-by-grep class of bug and leaves semantic enforcement to the
jaxpr auditor (:mod:`alphafold2_tpu.analysis.jaxpr_audit`). Pure stdlib —
importable (and fast) without jax, so CI lints before installing a backend.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Iterable, Optional

SEVERITIES = ("error", "warning")

RULES = {
    "AF2L001": ("error", "traced-value Python control flow under jit"),
    "AF2L002": ("error", "host sync under jit"),
    "AF2L003": ("error", "wall-clock read under jit"),
    "AF2L004": ("error", "non-JAX RNG under jit"),
    "AF2L005": ("warning", "mutable default argument"),
    "AF2L006": ("warning", "bare except"),
    "AF2L007": ("warning", "traced param needs static_argnames"),
    "AF2L008": ("warning", "print under jit"),
    "AF2L009": ("warning", "host side effect under jit"),
    "AF2L010": ("error", "blocking call while holding a lock"),
    "AF2L011": ("warning", "lock-guarded state mutated outside its lock"),
    "AF2L012": ("error", "host sync in a thread body"),
}

_NOQA_RE = re.compile(r"#\s*af2:\s*noqa(?:\[([A-Za-z0-9_,\s]+)\])?")

# lax control-flow combinators whose function arguments are traced bodies
_LAX_BODY_CALLS = {
    "scan", "while_loop", "fori_loop", "cond", "switch", "associative_scan",
}
_WALLCLOCK_ATTRS = {
    "time", "perf_counter", "monotonic", "process_time", "perf_counter_ns",
    "monotonic_ns", "time_ns", "now", "utcnow",
}
_HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_SIDE_EFFECT_METHODS = {"bump", "observe", "add_scalar", "write"}
_NUMPY_ALIASES = {"np", "numpy", "onp"}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    severity: str
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule} "
            f"[{self.severity}] {self.message}"
        )

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _noqa_lines(source: str) -> dict:
    """line number -> set of suppressed rule ids (empty set = all rules)."""
    out: dict = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _NOQA_RE.search(text)
        if not m:
            continue
        ids = m.group(1)
        out[i] = (
            {s.strip().upper() for s in ids.split(",") if s.strip()}
            if ids else set()
        )
    return out


def _attr_chain(node: ast.AST) -> list:
    """``jax.lax.scan`` -> ["jax", "lax", "scan"]; [] if not a pure chain."""
    parts: list = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return []


def _is_jit_callable(node: ast.AST) -> bool:
    """Does this expression name a jit transform (``jax.jit``, ``jit``,
    ``nn.jit``)?"""
    chain = _attr_chain(node)
    return bool(chain) and chain[-1] == "jit"


def _static_names_from_call(call: ast.Call) -> set:
    """Parameter names declared static in a jit(...) call's keywords.

    ``static_argnums`` positions cannot be resolved to names here (the
    function definition may live elsewhere); callers resolve them against
    the def's positional args when they can.
    """
    names: set = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for node in ast.walk(kw.value):
                if isinstance(node, ast.Constant) and isinstance(
                    node.value, str
                ):
                    names.add(node.value)
    return names


def _static_nums_from_call(call: ast.Call) -> set:
    nums: set = set()
    for kw in call.keywords:
        if kw.arg in ("static_argnums", "static_argnum"):
            for node in ast.walk(kw.value):
                if isinstance(node, ast.Constant) and isinstance(
                    node.value, int
                ):
                    nums.add(node.value)
    return nums


class _JitIndex(ast.NodeVisitor):
    """Module pass 1: which function names are jitted / lax bodies, and
    with which static argument declarations."""

    def __init__(self):
        # name -> {"static_names": set, "static_nums": set}
        self.jitted: dict = {}

    def _record(self, name: str, static_names: set, static_nums: set):
        rec = self.jitted.setdefault(
            name, {"static_names": set(), "static_nums": set()}
        )
        rec["static_names"] |= static_names
        rec["static_nums"] |= static_nums

    def visit_Call(self, node: ast.Call):
        chain = _attr_chain(node.func)
        if _is_jit_callable(node.func) and node.args:
            target = node.args[0]
            tchain = _attr_chain(target)
            if tchain:
                self._record(
                    tchain[-1],
                    _static_names_from_call(node),
                    _static_nums_from_call(node),
                )
        elif chain and chain[-1] in _LAX_BODY_CALLS:
            for arg in node.args:
                achain = _attr_chain(arg)
                if achain and len(achain) == 1:
                    self._record(achain[-1], set(), set())
        self.generic_visit(node)


def _decorator_jit_info(fn: ast.AST) -> Optional[tuple]:
    """(static_names, static_nums) if the def carries a jit decorator."""
    for dec in fn.decorator_list:
        if _is_jit_callable(dec):
            return set(), set()
        if isinstance(dec, ast.Call):
            if _is_jit_callable(dec.func):
                return _static_names_from_call(dec), _static_nums_from_call(dec)
            chain = _attr_chain(dec.func)
            if chain and chain[-1] == "partial" and dec.args and \
                    _is_jit_callable(dec.args[0]):
                return _static_names_from_call(dec), _static_nums_from_call(dec)
    return None


def _positional_params(fn: ast.AST) -> list:
    return [a.arg for a in fn.args.posonlyargs + fn.args.args]


def _param_names(fn: ast.AST) -> list:
    names = _positional_params(fn)
    names += [a.arg for a in fn.args.kwonlyargs]
    if fn.args.vararg:
        names.append(fn.args.vararg.arg)
    if fn.args.kwarg:
        names.append(fn.args.kwarg.arg)
    return names


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, source: str):
        self.path = path
        self.findings: list = []
        self.noqa = _noqa_lines(source)
        index = _JitIndex()
        self.tree = ast.parse(source, filename=path)
        index.visit(self.tree)
        self.jit_index = index.jitted
        # stack of traced-name sets; non-empty means "inside a jit context"
        self._traced_stack: list = []

    # ------------------------------------------------------------- plumbing

    def run(self) -> list:
        self.visit(self.tree)
        return sorted(self.findings, key=lambda f: (f.line, f.col, f.rule))

    def _emit(self, rule: str, node: ast.AST, message: str):
        line = getattr(node, "lineno", 0)
        suppressed = self.noqa.get(line)
        if suppressed is not None and (not suppressed or rule in suppressed):
            return
        severity = RULES[rule][0]
        self.findings.append(
            Finding(rule, severity, self.path, line,
                    getattr(node, "col_offset", 0), message)
        )

    def _in_jit(self) -> bool:
        return bool(self._traced_stack)

    def _traced(self, name: str) -> bool:
        return any(name in frame for frame in self._traced_stack)

    def _names_in(self, node: ast.AST) -> set:
        return {
            n.id for n in ast.walk(node) if isinstance(n, ast.Name)
        }

    def _traced_names_in(self, node: ast.AST) -> set:
        return {n for n in self._names_in(node) if self._traced(n)}

    # ------------------------------------------------------------ functions

    def _function_traced_params(self, fn) -> Optional[set]:
        """The traced parameter set if ``fn`` opens a jit context here."""
        info = _decorator_jit_info(fn)
        if info is None and fn.name in self.jit_index:
            rec = self.jit_index[fn.name]
            info = (rec["static_names"], rec["static_nums"])
        if info is None:
            if self._in_jit():
                return set(_param_names(fn)) - {"self", "cls"}
            return None
        static_names, static_nums = info
        positional = _positional_params(fn)
        skip_self = positional[:1] == ["self"] or positional[:1] == ["cls"]
        resolved = set(static_names)
        for i in static_nums:
            # static_argnums indexes the python signature as jit sees it
            if 0 <= i < len(positional):
                resolved.add(positional[i])
        return set(_param_names(fn)) - resolved - {"self", "cls"}

    def _visit_function(self, fn):
        self._check_mutable_defaults(fn)
        traced = self._function_traced_params(fn)
        if traced is None:
            self.generic_visit(fn)
            return
        self._traced_stack.append(traced)
        try:
            self.generic_visit(fn)
        finally:
            self._traced_stack.pop()

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def visit_Lambda(self, node):
        if self._in_jit():
            self._traced_stack.append(set(_param_names(node)))
            try:
                self.generic_visit(node)
            finally:
                self._traced_stack.pop()
        else:
            self.generic_visit(node)

    # ------------------------------------------------- always-on rules

    def _check_mutable_defaults(self, fn):
        for default in list(fn.args.defaults) + [
            d for d in fn.args.kw_defaults if d is not None
        ]:
            mutable = isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(default, ast.Call)
                and _attr_chain(default.func) in (["list"], ["dict"], ["set"])
            )
            if mutable:
                self._emit(
                    "AF2L005", default,
                    f"mutable default argument in {fn.name}(): evaluated "
                    "once and shared across calls; default to None",
                )

    def visit_ExceptHandler(self, node):
        if node.type is None:
            self._emit(
                "AF2L006", node,
                "bare except: catches KeyboardInterrupt/SystemExit and "
                "masks tracer errors; name the exception class",
            )
        self.generic_visit(node)

    # ---------------------------------------------------- traced-only rules

    def _truthiness_on_traced(self, test: ast.AST) -> Optional[str]:
        """Name of a traced param whose runtime truthiness the test needs,
        or None. ``is (not) None`` / ``in`` checks are pytree-structure
        tests and exempt."""
        if isinstance(test, ast.Name) and self._traced(test.id):
            return test.id
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return self._truthiness_on_traced(test.operand)
        if isinstance(test, ast.BoolOp):
            for v in test.values:
                hit = self._truthiness_on_traced(v)
                if hit:
                    return hit
            return None
        if isinstance(test, ast.Compare):
            if all(
                isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                for op in test.ops
            ):
                return None
            for side in [test.left] + test.comparators:
                if isinstance(side, ast.Name) and self._traced(side.id):
                    return side.id
        return None

    def _check_branch(self, node, kind: str):
        if not self._in_jit():
            return
        hit = self._truthiness_on_traced(node.test)
        if hit:
            self._emit(
                "AF2L001", node,
                f"python {kind} on traced parameter {hit!r}: concretizes "
                "under trace; use lax.cond/lax.select or mark the argument "
                "static",
            )

    def visit_If(self, node):
        self._check_branch(node, "if")
        self.generic_visit(node)

    def visit_While(self, node):
        self._check_branch(node, "while")
        self.generic_visit(node)

    def visit_IfExp(self, node):
        self._check_branch(node, "conditional expression")
        self.generic_visit(node)

    def visit_Assert(self, node):
        if self._in_jit():
            hit = self._truthiness_on_traced(node.test)
            if hit:
                self._emit(
                    "AF2L001", node,
                    f"assert on traced parameter {hit!r} concretizes under "
                    "trace; use checkify or a mask",
                )
        self.generic_visit(node)

    def visit_JoinedStr(self, node):
        if self._in_jit():
            for value in node.values:
                if isinstance(value, ast.FormattedValue):
                    for name in self._traced_names_in(value.value):
                        self._emit(
                            "AF2L007", node,
                            f"traced parameter {name!r} formatted into an "
                            "f-string under trace: stringifies the tracer, "
                            "not the value; mark it static or use "
                            "jax.debug.print",
                        )
                        break
        self.generic_visit(node)

    def visit_Call(self, node):
        if self._in_jit():
            self._check_traced_call(node)
        self.generic_visit(node)

    def _check_traced_call(self, node: ast.Call):
        chain = _attr_chain(node.func)
        # AF2L002: host syncs
        if chain and chain[-1] in _HOST_SYNC_METHODS and len(chain) > 1:
            self._emit(
                "AF2L002", node,
                f".{chain[-1]}() under trace forces a device sync (or "
                "fails on a tracer); keep values on device",
            )
            return
        if len(chain) >= 2 and chain[0] in _NUMPY_ALIASES and chain[1] in (
            "asarray", "array"
        ):
            self._emit(
                "AF2L002", node,
                f"{'.'.join(chain)}() under trace pulls the value to host "
                "(ConcretizationError on a tracer); use jnp",
            )
            return
        if chain and chain[-1] == "device_get":
            self._emit(
                "AF2L002", node,
                "jax.device_get under trace is a host sync; return the "
                "value instead",
            )
            return
        if chain in (["float"], ["int"], ["bool"], ["complex"]) and node.args:
            names = self._traced_names_in(node.args[0])
            if names:
                self._emit(
                    "AF2L002", node,
                    f"builtin {chain[0]}() on traced parameter "
                    f"{sorted(names)[0]!r} concretizes under trace; use "
                    f"jnp/astype",
                )
                return
        # AF2L003: wall clock
        if (
            len(chain) >= 2
            and chain[0] in ("time", "datetime")
            and chain[-1] in _WALLCLOCK_ATTRS
        ):
            self._emit(
                "AF2L003", node,
                f"{'.'.join(chain)}() under trace is evaluated once at "
                "trace time and baked into the graph",
            )
            return
        # AF2L004: non-JAX RNG
        if chain and chain[0] == "random" and len(chain) >= 2:
            self._emit(
                "AF2L004", node,
                f"stdlib {'.'.join(chain)}() under trace bakes one sample "
                "into the graph; use jax.random with an explicit key",
            )
            return
        if len(chain) >= 3 and chain[0] in _NUMPY_ALIASES and \
                chain[1] == "random":
            self._emit(
                "AF2L004", node,
                f"{'.'.join(chain)}() under trace bakes one sample into "
                "the graph; use jax.random with an explicit key",
            )
            return
        # AF2L007: python-only sinks for traced params
        if chain == ["range"]:
            for arg in node.args:
                names = self._traced_names_in(arg)
                if names:
                    self._emit(
                        "AF2L007", node,
                        f"range() over traced parameter "
                        f"{sorted(names)[0]!r}: needs a concrete int — "
                        "declare it in static_argnames or use lax.fori_loop",
                    )
                    break
            return
        # AF2L008: print
        if chain == ["print"]:
            self._emit(
                "AF2L008", node,
                "print under trace fires once per trace, not per step; use "
                "jax.debug.print or observe",
            )
            return
        # AF2L009: host side effects
        if chain and len(chain) > 1 and chain[-1] in _SIDE_EFFECT_METHODS:
            self._emit(
                "AF2L009", node,
                f".{chain[-1]}() under trace is a host side effect: it "
                "runs per trace, never per executed step",
            )
            return
        if chain and chain[0] in ("logging", "logger", "log"):
            self._emit(
                "AF2L009", node,
                f"{'.'.join(chain)}() under trace logs at trace time only",
            )


# ------------------------------------------------- thread-safety rules

_LOCK_FACTORIES = {
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
}
# calls that block the calling thread (module.attr chains)
_BLOCKING_CHAIN_HEADS = {"socket", "subprocess", "requests", "urllib"}
_BLOCKING_CALLS = {
    ("time", "sleep"), ("os", "system"), ("os", "popen"),
}
# socket/file methods that block regardless of the receiver expression;
# .wait() is deliberately absent (Condition.wait releases the lock)
_BLOCKING_METHODS = {
    "recv", "recvfrom", "sendall", "sendto", "connect", "accept",
    "read_text", "write_text", "readline", "readlines",
}
_MUTATING_METHODS = {
    "append", "extend", "insert", "remove", "pop", "popleft",
    "appendleft", "clear", "update", "setdefault", "add", "discard",
}
_HOST_SYNC_CALLS = {"device_get", "block_until_ready"}


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.x`` -> "x" (None for anything else)."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _MethodScan(ast.NodeVisitor):
    """One pass over a method body tracking ``with self.<lock>:`` depth;
    records self-attribute mutations (with lock state) and blocking calls
    made while a lock is held."""

    def __init__(self, lock_attrs: set, assume_held: bool = False):
        self.lock_attrs = lock_attrs
        # the *_locked naming convention documents "caller holds the
        # lock": treat the whole body as a critical section, which both
        # exempts its mutations from AF2L011 and (correctly) arms
        # AF2L010 for blocking calls inside it
        self.lock_depth = 1 if assume_held else 0
        self.mutations: list = []  # (attr, node, held: bool)
        self.blocking: list = []  # (node, description)

    def _is_lock_expr(self, node: ast.AST) -> bool:
        attr = _self_attr(node)
        return attr is not None and attr in self.lock_attrs

    def visit_With(self, node: ast.With):
        held = sum(
            1 for item in node.items if self._is_lock_expr(item.context_expr)
        )
        self.lock_depth += held
        try:
            self.generic_visit(node)
        finally:
            self.lock_depth -= held

    def _mutation(self, attr: Optional[str], node: ast.AST):
        if attr is not None:
            self.mutations.append((attr, node, self.lock_depth > 0))

    def _mutated_attr_of_target(self, target: ast.AST) -> Optional[str]:
        attr = _self_attr(target)
        if attr is not None:
            return attr
        if isinstance(target, ast.Subscript):
            return _self_attr(target.value)
        return None

    def visit_Assign(self, node: ast.Assign):
        for target in node.targets:
            elts = target.elts if isinstance(
                target, (ast.Tuple, ast.List)
            ) else [target]
            for t in elts:
                self._mutation(self._mutated_attr_of_target(t), node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._mutation(self._mutated_attr_of_target(node.target), node)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete):
        for target in node.targets:
            self._mutation(self._mutated_attr_of_target(target), node)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        # self.<attr>.<mutator>(...) counts as a mutation of self.<attr>
        if isinstance(node.func, ast.Attribute):
            if node.func.attr in _MUTATING_METHODS:
                self._mutation(_self_attr(node.func.value), node)
        if self.lock_depth > 0:
            desc = self._blocking_desc(node)
            if desc:
                self.blocking.append((node, desc))
        self.generic_visit(node)

    @staticmethod
    def _blocking_desc(node: ast.Call) -> Optional[str]:
        chain = _attr_chain(node.func)
        if not chain:
            return None
        if chain == ["open"]:
            return "open"
        if tuple(chain) in _BLOCKING_CALLS:
            return ".".join(chain)
        if len(chain) >= 2 and chain[0] in _BLOCKING_CHAIN_HEADS:
            return ".".join(chain)
        if len(chain) >= 2 and chain[-1] in _BLOCKING_METHODS:
            return ".".join(chain)
        return None


class _ThreadSafetyLinter:
    """AF2L010–012 over one parsed module (see the module docstring for
    what each rule sees — and honestly does not see)."""

    def __init__(self, path: str, tree: ast.Module, noqa: dict):
        self.path = path
        self.tree = tree
        self.noqa = noqa
        self.findings: list = []

    def _emit(self, rule: str, node: ast.AST, message: str):
        line = getattr(node, "lineno", 0)
        suppressed = self.noqa.get(line)
        if suppressed is not None and (not suppressed or rule in suppressed):
            return
        self.findings.append(
            Finding(rule, RULES[rule][0], self.path, line,
                    getattr(node, "col_offset", 0), message)
        )

    def run(self) -> list:
        thread_targets = self._thread_target_names()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                self._lint_class(node)
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and node.name in thread_targets:
                self._lint_thread_body(node)
        return self.findings

    # ---------------------------------------------------------- discovery

    def _thread_target_names(self) -> set:
        """Function/method names passed as ``threading.Thread(target=...)``
        anywhere in the module."""
        names: set = set()
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if not chain or chain[-1] != "Thread":
                continue
            for kw in node.keywords:
                if kw.arg != "target":
                    continue
                attr = _self_attr(kw.value)
                if attr is not None:
                    names.add(attr)
                elif isinstance(kw.value, ast.Name):
                    names.add(kw.value.id)
        return names

    @staticmethod
    def _lock_attrs_of(cls: ast.ClassDef) -> set:
        """Instance attrs assigned a ``threading.<Lock factory>()``."""
        locks: set = set()
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            chain = _attr_chain(node.value.func)
            if (
                len(chain) == 2
                and chain[0] == "threading"
                and chain[1] in _LOCK_FACTORIES
            ):
                for target in node.targets:
                    attr = _self_attr(target)
                    if attr is not None:
                        locks.add(attr)
        return locks

    # -------------------------------------------------------------- rules

    def _lint_class(self, cls: ast.ClassDef):
        lock_attrs = self._lock_attrs_of(cls)
        if not lock_attrs:
            return
        methods = [
            n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        scans = {}
        for method in methods:
            scan = _MethodScan(
                lock_attrs, assume_held=method.name.endswith("_locked")
            )
            scan.visit(method)
            scans[method.name] = scan
            for node, desc in scan.blocking:
                self._emit(
                    "AF2L010", node,
                    f"blocking call {desc}() in {cls.name}.{method.name} "
                    "while holding a lock: every thread contending for it "
                    "stalls behind the I/O; move it outside the critical "
                    "section",
                )
        guarded = {
            attr
            for scan in scans.values()
            for attr, _, held in scan.mutations
            if held
        } - lock_attrs
        for name, scan in scans.items():
            if name == "__init__":
                continue  # construction happens-before any other thread
            for attr, node, held in scan.mutations:
                if held or attr not in guarded:
                    continue
                self._emit(
                    "AF2L011", node,
                    f"self.{attr} is mutated under {cls.name}'s lock "
                    f"elsewhere but written in {name}() without it: either "
                    "take the lock here or document why this write cannot "
                    "race",
                )

    def _lint_thread_body(self, fn):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            desc = None
            if chain and chain[-1] in _HOST_SYNC_CALLS:
                desc = ".".join(chain)
            elif chain and len(chain) > 1 and chain[-1] in (
                "item", "tolist"
            ):
                desc = f".{chain[-1]}()"
            elif (
                len(chain) >= 2
                and chain[0] in _NUMPY_ALIASES
                and chain[1] in ("asarray", "array")
            ):
                desc = ".".join(chain)
            if desc:
                self._emit(
                    "AF2L012", node,
                    f"host sync {desc} inside thread body {fn.name}(): "
                    "this thread exists to keep the device pipeline full — "
                    "a sync here serializes it; hand results back instead",
                )


# ------------------------------------------------------------------ drivers


def lint_source(source: str, path: str = "<string>") -> list:
    """Lint one source string; returns a list of :class:`Finding`."""
    linter = _Linter(path, source)
    findings = linter.run()
    findings += _ThreadSafetyLinter(path, linter.tree, linter.noqa).run()
    return sorted(findings, key=lambda f: (f.line, f.col, f.rule))


def lint_file(path: str) -> list:
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    try:
        return lint_source(source, path)
    except SyntaxError as e:
        return [
            Finding(
                "AF2L000", "error", path, e.lineno or 0, e.offset or 0,
                f"syntax error: {e.msg}",
            )
        ]


def iter_python_files(paths: Iterable[str]) -> Iterable[str]:
    for path in paths:
        if os.path.isfile(path):
            yield path
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(
                d for d in dirs
                if d not in ("__pycache__", ".git", ".venv", "node_modules")
            )
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def lint_paths(paths: Iterable[str], select: Optional[set] = None) -> list:
    """Lint files/directories; ``select`` restricts to those rule ids."""
    findings: list = []
    for path in iter_python_files(paths):
        for f in lint_file(path):
            if select is None or f.rule in select:
                findings.append(f)
    return findings


def findings_to_json(findings: list) -> str:
    return json.dumps(
        {
            "tool": "af2_lint",
            "findings": [f.to_dict() for f in findings],
            "counts": {
                sev: sum(1 for f in findings if f.severity == sev)
                for sev in SEVERITIES
            },
        },
        indent=2,
    )
