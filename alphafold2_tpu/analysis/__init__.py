"""Graph-hygiene static analysis: the lint/trace-time enforcement layer.

Three layers, each catching a class of defect before a chip runs it:

- :mod:`lint` — JAX-specific AST rules over the package source (host syncs,
  traced-value control flow, wall-clock/RNG under trace, ...), pure stdlib.
  CLI: ``python scripts/af2_lint.py alphafold2_tpu/``.
- :mod:`jaxpr_audit` — abstractly traces the model / train-step / serve
  executables and statically rejects forbidden primitives (f64 converts,
  host callbacks), giant baked-in constants and broken donation, under
  strict dtype promotion. Also fronts the Mosaic TPU lowering gate
  (:mod:`lowering`). CLI: ``python -m alphafold2_tpu.analysis.jaxpr_audit``.
- :mod:`contracts` — per-function jaxpr fingerprints (op counts by
  primitive, input treedefs, donation map) diffed against the committed
  ``graph_contracts.json`` in CI, mirroring how ``observe/regress.py``
  gates runtime perf. CLI: ``python -m alphafold2_tpu.analysis.contracts``.
- :mod:`hlo_audit` — one level below the jaxpr: compiles the registry
  targets to optimized HLO and audits the *post-SPMD* graph — collective
  census (count/bytes per all-reduce/all-gather/...), resharding
  detection, and per-device memory vs the HBM budgets in :mod:`budgets`,
  all diffed against the committed ``hlo_contracts.json``.
  CLI: ``python -m alphafold2_tpu.analysis.hlo_audit --check``.

Only :mod:`lint` is imported eagerly — it is jax-free so the lint CLI and
CI job stay fast and backend-less. The trace-based layers import jax and
load lazily.
"""

from alphafold2_tpu.analysis import lint
from alphafold2_tpu.analysis.lint import (
    Finding,
    RULES,
    lint_file,
    lint_paths,
    lint_source,
)

__all__ = [
    "Finding",
    "RULES",
    "budgets",
    "contracts",
    "hlo_audit",
    "jaxpr_audit",
    "lint",
    "lint_file",
    "lint_paths",
    "lint_source",
    "lowering",
]


def __getattr__(name):
    # lazy: these import jax (and lowering additionally assumes a scrubbed
    # env when run as a gate) — keep `import alphafold2_tpu.analysis` cheap
    if name in (
        "jaxpr_audit",
        "contracts",
        "lowering",
        "targets",
        "hlo_audit",
        "budgets",
    ):
        import importlib

        return importlib.import_module(f"alphafold2_tpu.analysis.{name}")
    raise AttributeError(name)
