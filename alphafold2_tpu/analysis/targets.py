"""The audited executable surface: what the static analysis traces.

One registry shared by the jaxpr auditor (:mod:`jaxpr_audit`) and the graph
contracts (:mod:`contracts`), so "the functions we audit" and "the functions
whose graph shape is pinned in CI" cannot drift apart. Each target names one
jit entry point of the system — the model forward, the distogram train step,
the serve-engine forward — built at tiny shapes: jaxpr structure (primitive
mix, dtype discipline, donation) is shape-independent for this model family,
and tiny builds keep the CI job in seconds, not minutes.

Targets intentionally waiving an audit rule carry the waived rule id in
``allow`` with a human reason in ``allow_reasons`` — a waiver without a
reason fails construction, mirroring the linter's reviewed-noqa policy.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class TraceTarget:
    """One audited executable: ``build()`` returns ``(fn, args)`` ready for
    ``jax.make_jaxpr(fn)(*args)``.

    ``hlo=True`` additionally opts the target into the compiled-HLO audit
    (:mod:`hlo_audit`): collective census, resharding detection and the
    memory-budget contract. ``sharded`` declares the *intent* — a target
    declared single-device must compile with zero cross-device collectives
    (AF2A109), a sharded one must actually shard (AF2A108).
    ``hbm_budget_bytes`` is the declared per-device footprint ceiling
    (arguments + outputs + temporaries) the budget contract gates against
    (AF2A110); None skips the gate with a loud "no-data" verdict."""

    name: str
    build: Callable[[], tuple]
    donate_argnums: tuple = ()
    allow: frozenset = frozenset()
    allow_reasons: Optional[dict] = None
    hlo: bool = False
    sharded: bool = False
    hbm_budget_bytes: Optional[int] = None

    def __post_init__(self):
        missing = set(self.allow) - set(self.allow_reasons or {})
        if missing:
            raise ValueError(
                f"target {self.name!r} waives {sorted(missing)} without a "
                "reason; every waiver is reviewed"
            )


def _tiny_model_cfg():
    from alphafold2_tpu.config import Config, DataConfig, ModelConfig, TrainConfig

    return Config(
        model=ModelConfig(
            dim=32, depth=1, heads=2, dim_head=16, max_seq_len=64,
            bfloat16=False,
        ),
        data=DataConfig(
            crop_len=16, msa_depth=2, msa_len=16, batch_size=1,
            min_len_filter=8,
        ),
        train=TrainConfig(gradient_accumulate_every=1, warmup_steps=2),
    )


def _build_model_fwd():
    import jax
    import jax.numpy as jnp

    from alphafold2_tpu.train.loop import build_model

    cfg = _tiny_model_cfg()
    model = build_model(cfg)
    seq = jnp.zeros((1, 16), jnp.int32)
    msa = jnp.zeros((1, 2, 16), jnp.int32)
    mask = jnp.ones((1, 16), bool)
    msa_mask = jnp.ones((1, 2, 16), bool)
    params = model.init(jax.random.key(0), seq, msa, mask=mask,
                        msa_mask=msa_mask)

    def fwd(params, seq, msa, mask, msa_mask):
        return model.apply(
            params, seq, msa, mask=mask, msa_mask=msa_mask,
            deterministic=True,
        )

    return fwd, (params, seq, msa, mask, msa_mask)


def _build_train_step():
    import jax

    from alphafold2_tpu.data.pipeline import SyntheticDataset
    from alphafold2_tpu.train.loop import (
        build_model,
        device_put_batch,
        init_state,
        make_train_step,
    )

    cfg = _tiny_model_cfg()
    batch = next(iter(SyntheticDataset(cfg.data, seed=0)))
    model = build_model(cfg)
    state = init_state(cfg, model, batch)
    step = make_train_step(model, jit=False)
    return step, (state, device_put_batch(batch), jax.random.key(0))


def _build_train_grad():
    """Forward + distogram loss + backward — the strict-promotion surface
    that is OUR code. The full train_step additionally runs the optax
    update, whose internals (``decay**count``: weak float vs int32 in
    ``tree_bias_correction``) fail strict promotion upstream of this repo,
    so train_step waives AF2A105 and this target keeps the gate closed on
    everything up to the gradients."""
    import jax

    from alphafold2_tpu.data.pipeline import SyntheticDataset
    from alphafold2_tpu.train.loop import (
        build_model,
        device_put_batch,
        distogram_cross_entropy,
    )
    from alphafold2_tpu.utils.structure import get_bucketed_distance_matrix

    cfg = _tiny_model_cfg()
    batch = device_put_batch(next(iter(SyntheticDataset(cfg.data, seed=0))))
    model = build_model(cfg)
    params = model.init(
        jax.random.key(0), batch["seq"], batch.get("msa"),
        mask=batch["mask"], msa_mask=batch.get("msa_mask"),
    )

    def loss_fn(params, batch, rng):
        logits = model.apply(
            params, batch["seq"], batch.get("msa"), mask=batch["mask"],
            msa_mask=batch.get("msa_mask"), deterministic=False,
            rngs={"dropout": rng},
        )
        labels = get_bucketed_distance_matrix(batch["coords"], batch["mask"])
        return distogram_cross_entropy(logits, labels)

    grad = jax.value_and_grad(loss_fn)
    return grad, (params, batch, jax.random.key(0))


def _build_serve_fwd():
    import jax
    import jax.numpy as jnp

    from alphafold2_tpu.train.end2end import End2EndModel

    # the serve engine's _fwd at its smallest bucket geometry
    # (tests/test_serve.py's tiny config): bucket 8, batch 2, msa depth 2
    bucket, batch, depth = 8, 2, 2
    model = End2EndModel(
        dim=32, depth=1, heads=2, dim_head=16, max_seq_len=3 * bucket,
        mds_iters=8, mds_per_position_init=True, dtype=jnp.float32,
    )
    seq = jnp.zeros((batch, bucket), jnp.int32)
    msa = jnp.zeros((batch, depth, bucket), jnp.int32)
    mask = jnp.ones((batch, bucket), bool)
    msa_mask = jnp.ones((batch, depth, bucket), bool)
    params = model.init(jax.random.key(0), seq, msa, mask=mask,
                        msa_mask=msa_mask)
    mds_key = jax.random.key(0)

    def fwd(params, seq, msa, mask, msa_mask):
        out = model.apply(
            params, seq, msa, mask=mask, msa_mask=msa_mask,
            mds_key=mds_key, deterministic=True,
        )
        return {"refined": out["refined"], "weights": out["weights"]}

    return fwd, (params, seq, msa, mask, msa_mask)


def _build_serve_fwd_grid():
    """The serve engine's _fwd traced under an active 2D pair-grid mesh —
    the sharded executable ServeEngine AOT-compiles when constructed with
    a mesh (serve/engine.py). Auditing it pins the sharded graph: the
    shard_map axial passes, their all_to_all transposes and the
    sharding-constraint boundaries are all part of the fingerprint's op
    mix. The mesh degrades to the devices available (fingerprints are
    mesh-SIZE independent: op counts recurse into the shard_map body and
    the input signature uses global shapes), so the audit runs identically
    on a 1-device laptop, the 8-virtual-device CI mesh, and on-chip."""
    import jax
    import jax.numpy as jnp

    from alphafold2_tpu.parallel.grid_parallel import make_grid_mesh
    from alphafold2_tpu.parallel.sharding import use_mesh
    from alphafold2_tpu.train.end2end import End2EndModel

    bucket, batch, depth = 8, 2, 2
    devices = jax.devices()
    n_col = 2 if len(devices) >= 2 else 1
    n_row = 2 if len(devices) >= 4 else 1
    mesh = make_grid_mesh(
        1, n_row, n_col, devices=devices[: n_row * n_col]
    )
    model = End2EndModel(
        dim=32, depth=1, heads=2, dim_head=16, max_seq_len=3 * bucket,
        mds_iters=8, mds_per_position_init=True, grid_parallel=True,
        dtype=jnp.float32,
    )
    seq = jnp.zeros((batch, bucket), jnp.int32)
    msa = jnp.zeros((batch, depth, bucket), jnp.int32)
    mask = jnp.ones((batch, bucket), bool)
    msa_mask = jnp.ones((batch, depth, bucket), bool)
    params = model.init(jax.random.key(0), seq, msa, mask=mask,
                        msa_mask=msa_mask)
    mds_key = jax.random.key(0)

    def fwd(params, seq, msa, mask, msa_mask):
        # the mesh context activates the model's shard_pair constraints
        # and the shard_map axial passes at trace time, exactly as the
        # engine's sharded _get_executable does
        with use_mesh(mesh):
            out = model.apply(
                params, seq, msa, mask=mask, msa_mask=msa_mask,
                mds_key=mds_key, deterministic=True,
            )
        return {"refined": out["refined"], "weights": out["weights"]}

    return fwd, (params, seq, msa, mask, msa_mask)


def _build_serve_fwd_long():
    """The crop-free long-chain rung's graph: the serve engine's _fwd on
    the mesh-gated long-bucket ladder (ServeConfig.long_buckets), scaled
    down to bucket 16 / batch 1 on a 1D (dp=1, sp=all) sequence-parallel
    mesh. Unlike serve_fwd_grid (whose shard_map in_specs pin the layout
    mechanically), this path's sharding rests ENTIRELY on the shard_pair
    constraints at layer boundaries — it is the target where dropping one
    constraint silently replicates the N^2 pair state onto every device,
    which is exactly the cliff the HLO audit's resharding detector and
    memory budget exist to catch before a bench ever runs."""
    import jax
    import jax.numpy as jnp

    from alphafold2_tpu.parallel.sharding import make_mesh, use_mesh
    from alphafold2_tpu.train.end2end import End2EndModel

    bucket, batch, depth = 16, 1, 2
    devices = jax.devices()
    n_seq = min(8, len(devices))
    mesh = make_mesh(1, n_seq, devices=devices[:n_seq])
    model = End2EndModel(
        dim=32, depth=1, heads=2, dim_head=16, max_seq_len=3 * bucket,
        mds_iters=8, mds_per_position_init=True, dtype=jnp.float32,
    )
    seq = jnp.zeros((batch, bucket), jnp.int32)
    msa = jnp.zeros((batch, depth, bucket), jnp.int32)
    mask = jnp.ones((batch, bucket), bool)
    msa_mask = jnp.ones((batch, depth, bucket), bool)
    params = model.init(jax.random.key(0), seq, msa, mask=mask,
                        msa_mask=msa_mask)
    mds_key = jax.random.key(0)

    def fwd(params, seq, msa, mask, msa_mask):
        with use_mesh(mesh):
            out = model.apply(
                params, seq, msa, mask=mask, msa_mask=msa_mask,
                mds_key=mds_key, deterministic=True,
            )
        return {"refined": out["refined"], "weights": out["weights"]}

    return fwd, (params, seq, msa, mask, msa_mask)


def _build_serve_fwd_bf16():
    """The serve engine's _fwd in the bf16 serving mode (serve.dtype=
    "bfloat16"): bf16-cast params + bf16 compute dtype, exactly what
    ServeEngine builds. A DISTINCT fingerprint target — flipping the
    serving precision must surface as an explicit contract diff (new
    convert_element_type mix, bf16 input signature), never as a silent
    mutation of the f32 serve_fwd contract."""
    import jax
    import jax.numpy as jnp

    from alphafold2_tpu.train.end2end import End2EndModel

    bucket, batch, depth = 8, 2, 2
    model = End2EndModel(
        dim=32, depth=1, heads=2, dim_head=16, max_seq_len=3 * bucket,
        mds_iters=8, mds_per_position_init=True, msa_tie_row_attn=True,
        dtype=jnp.bfloat16,
    )
    seq = jnp.zeros((batch, bucket), jnp.int32)
    msa = jnp.zeros((batch, depth, bucket), jnp.int32)
    mask = jnp.ones((batch, bucket), bool)
    msa_mask = jnp.ones((batch, depth, bucket), bool)
    params = model.init(jax.random.key(0), seq, msa, mask=mask,
                        msa_mask=msa_mask)
    params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16)
        if getattr(x, "dtype", None) == jnp.float32 else x,
        params,
    )
    mds_key = jax.random.key(0)

    def fwd(params, seq, msa, mask, msa_mask):
        out = model.apply(
            params, seq, msa, mask=mask, msa_mask=msa_mask,
            mds_key=mds_key, deterministic=True,
        )
        return {"refined": out["refined"], "weights": out["weights"]}

    return fwd, (params, seq, msa, mask, msa_mask)


def _build_attn_tied_row_pallas():
    """The fused tied-row kernel's graph at a tiny shape (interpret=True so
    the fingerprint is backend-independent): pins the pallas_call + fold
    relayouts so kernel plumbing changes are reviewed diffs."""
    import jax
    import jax.numpy as jnp

    from alphafold2_tpu.ops.pallas.tied_row import tied_row_attention

    b, r, n, h, d = 1, 2, 16, 2, 8
    key = jax.random.key(0)
    q = jax.random.normal(key, (b, r, n, h, d), jnp.float32)
    mask = jnp.ones((b, n), bool)

    def fwd(q, k, v):
        return tied_row_attention(
            q, k, v, q_mask=mask, kv_mask=mask, sm_scale=d**-0.5,
            interpret=True,
        )

    return fwd, (q, q, q)


def _build_attn_axial_pallas():
    """The fused axial kernel's graph (forward + backward through the
    custom VJP) at a tiny shape, interpret=True."""
    import jax
    import jax.numpy as jnp

    from alphafold2_tpu.ops.pallas.axial import fused_attention

    b, h, n, d = 1, 2, 16, 8
    key = jax.random.key(0)
    q = jax.random.normal(key, (b, h, n, d), jnp.float32)
    mask = jnp.ones((b, n), bool)

    def loss(q, k, v):
        out = fused_attention(
            q, k, v, kv_mask=mask, sm_scale=d**-0.5, interpret=True
        )
        return jnp.sum(out * out)

    return jax.grad(loss, argnums=(0, 1, 2)), (q, q, q)


def default_targets() -> list:
    """The audited surface: model forward, train step, serve forward
    (single-device, grid-mesh-sharded, long-bucket sequence-parallel, and
    bf16), and the fused Pallas kernel graphs."""
    return [
        TraceTarget(
            name="model_fwd", build=_build_model_fwd,
            hlo=True, sharded=False, hbm_budget_bytes=64 << 20,
        ),
        TraceTarget(
            name="train_step",
            build=_build_train_step,
            donate_argnums=(0,),
            allow=frozenset({"AF2A105"}),
            allow_reasons={
                "AF2A105": (
                    "optax's tree_bias_correction computes decay**count "
                    "(weak float vs int32), an upstream strict-promotion "
                    "failure this repo cannot fix; the train_grad target "
                    "keeps strict promotion enforced on all first-party "
                    "code (forward, loss, backward)"
                ),
            },
        ),
        TraceTarget(name="train_grad", build=_build_train_grad),
        TraceTarget(
            name="serve_fwd",
            build=_build_serve_fwd,
            # the engine donates the int/bool feature buffers
            # (donate_argnums=(1, 2, 3, 4) when serve.donate_buffers)
            donate_argnums=(1, 2, 3, 4),
            hlo=True, sharded=False, hbm_budget_bytes=64 << 20,
            allow=frozenset({"AF2A104"}),
            allow_reasons={
                "AF2A104": (
                    "int/bool feature buffers can never alias the f32 "
                    "coordinate outputs; donation is still wanted so the "
                    "runtime can release request buffers during execution "
                    "on HBM-tight serving (serve/engine.py)"
                ),
            },
        ),
        TraceTarget(
            name="serve_fwd_grid",
            build=_build_serve_fwd_grid,
            donate_argnums=(1, 2, 3, 4),
            hlo=True, sharded=True, hbm_budget_bytes=16 << 20,
            allow=frozenset({"AF2A104"}),
            allow_reasons={
                "AF2A104": (
                    "same early-free donation intent as serve_fwd: the "
                    "sharded engine donates the int/bool feature buffers "
                    "it device_put with explicit shardings"
                ),
            },
        ),
        TraceTarget(
            name="serve_fwd_long",
            build=_build_serve_fwd_long,
            donate_argnums=(1, 2, 3, 4),
            # the long-rung budget is deliberately tight (~5x the sharded
            # per-device footprint): replicating the pair state by dropping
            # a shard_pair constraint must blow THROUGH it, so the memory
            # contract fails alongside the census drift
            hlo=True, sharded=True, hbm_budget_bytes=8 << 20,
            allow=frozenset({"AF2A104"}),
            allow_reasons={
                "AF2A104": (
                    "same early-free donation intent as serve_fwd: the "
                    "sharded engine donates the int/bool feature buffers "
                    "it device_put with explicit shardings"
                ),
            },
        ),
        TraceTarget(
            name="serve_fwd_bf16",
            build=_build_serve_fwd_bf16,
            donate_argnums=(1, 2, 3, 4),
            hlo=True, sharded=False, hbm_budget_bytes=64 << 20,
            allow=frozenset({"AF2A104", "AF2A105"}),
            allow_reasons={
                "AF2A104": (
                    "same early-free donation intent as serve_fwd: the "
                    "bf16 engine donates the int/bool feature buffers"
                ),
                "AF2A105": (
                    "flax's LayerNorm._compute_stats promotes bf16 inputs "
                    "with float32 for the mean/variance reduction — an "
                    "upstream (and numerically desirable) promotion this "
                    "repo cannot spell explicitly; the f32 serve_fwd "
                    "target keeps strict promotion enforced on the same "
                    "graph at full precision"
                ),
            },
        ),
        TraceTarget(
            name="attn_tied_row_pallas",
            build=_build_attn_tied_row_pallas,
        ),
        TraceTarget(
            name="attn_axial_pallas",
            build=_build_attn_axial_pallas,
        ),
    ]


def hlo_targets(targets=None) -> list:
    """The compiled-HLO-audited subset: every target opted in with
    ``hlo=True``. Train and Pallas-kernel targets stay out — the train
    step's optax internals and the interpret-mode pallas_call callbacks
    make their optimized HLO backend-dependent, while the serve/model
    forwards are the executables the compile-once lattice actually
    ships."""
    targets = targets if targets is not None else default_targets()
    return [t for t in targets if t.hlo]


def target_by_name(name: str, targets=None) -> TraceTarget:
    targets = targets if targets is not None else default_targets()
    for t in targets:
        if t.name == name:
            return t
    raise KeyError(
        f"unknown target {name!r}; known: {[t.name for t in targets]}"
    )


def example_arg_summary(args) -> list:
    """Human-readable leaf summary of a target's example arguments."""
    import jax

    leaves = jax.tree.leaves(args)
    return [
        # str(dtype), not np.dtype(...): PRNG keys are extended dtypes
        # ("key<fry>") numpy cannot interpret
        f"{x.dtype}{list(np.shape(x))}"
        if hasattr(x, "dtype") else repr(type(x).__name__)
        for x in leaves
    ]
