"""Jaxpr/HLO auditor (layer 2): semantic graph-hygiene enforcement.

The AST linter (:mod:`lint`) catches what is visible in source; this module
catches what is only visible in the traced graph. It abstractly traces the
registered executables (:mod:`targets`: model forward, train step, serve
forward) on the host — no device, no compile — and statically rejects:

- ``AF2A100`` error — the target fails to trace at all (the audit cannot
  certify a graph it cannot build).
- ``AF2A101`` error — float64/complex128 anywhere in the graph (any aval or
  a ``convert_element_type`` to a wide dtype): on TPU an f64 leak is a
  silent 2x memory + emulation cliff, paid at N^2 scale in the pair stream.
- ``AF2A102`` error — host-callback primitives in the hot path
  (``pure_callback``/``io_callback``/``debug_callback``/infeed/outfeed):
  each one is a device->host round trip per step.
- ``AF2A103`` error — giant baked-in constants (> threshold bytes closed
  over into the jaxpr): they bloat every executable and recompile key
  instead of riding as arguments.
- ``AF2A104`` warning — broken donation: a ``donate_argnums`` declaration
  whose buffers can never alias any output (no shape/dtype match), i.e.
  the donation documents an intent the runtime cannot honor.
- ``AF2A105`` error — the target only traces under default dtype
  promotion: under ``jax.numpy_dtype_promotion("strict")`` the trace
  raises, meaning an implicit promotion (usually bool/int drawn into
  float math) is hiding in the graph.

Rule ``AF2A106`` (Mosaic TPU lowering failure) folds the Pallas lowering
gate (:mod:`alphafold2_tpu.analysis.lowering`, formerly the whole of
``scripts/check_tpu_lowering.py``) into the same findings stream: ``--rules
jaxpr,lowering`` is the single pre-hardware gate entry point.

CLI::

    JAX_PLATFORMS=cpu python -m alphafold2_tpu.analysis.jaxpr_audit \
        [--targets model_fwd,train_step] [--rules jaxpr,lowering] \
        [--const-threshold BYTES] [--json out.json]

Exit codes: 0 clean, 1 findings, 2 usage error. Targets may waive specific
rules (with a recorded reason) via ``TraceTarget.allow``.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Iterable, Optional

AUDIT_RULES = {
    "AF2A100": ("error", "target fails to trace"),
    "AF2A101": ("error", "float64/complex128 in graph"),
    "AF2A102": ("error", "host callback primitive in hot path"),
    "AF2A103": ("error", "giant baked-in constant"),
    "AF2A104": ("warning", "declared donation can never alias"),
    "AF2A105": ("error", "strict dtype promotion violation"),
    "AF2A106": ("error", "Mosaic TPU lowering failure"),
}

FORBIDDEN_PRIMITIVES = {
    "pure_callback",
    "io_callback",
    "debug_callback",
    "callback",
    "host_callback",
    "outside_call",
    "infeed",
    "outfeed",
}

WIDE_DTYPES = ("float64", "complex128")

DEFAULT_CONST_THRESHOLD = 1 << 20  # 1 MiB baked into a graph is a bug


@dataclasses.dataclass(frozen=True)
class AuditFinding:
    rule: str
    severity: str
    target: str
    message: str

    def format(self) -> str:
        return f"{self.target}: {self.rule} [{self.severity}] {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _finding(rule: str, target: str, message: str) -> AuditFinding:
    return AuditFinding(rule, AUDIT_RULES[rule][0], target, message)


# --------------------------------------------------------------- traversal


def _sub_jaxprs(params: dict):
    from jax.extend import core as jex_core

    def walk(value):
        if isinstance(value, jex_core.ClosedJaxpr):
            yield value.jaxpr
        elif isinstance(value, jex_core.Jaxpr):
            yield value
        elif isinstance(value, (list, tuple)):
            for v in value:
                yield from walk(v)

    for value in params.values():
        yield from walk(value)


def iter_eqns(jaxpr) -> Iterable:
    """Every equation in ``jaxpr``, recursing into call/control-flow
    sub-jaxprs (scan bodies, cond branches, pjit calls, remat)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn.params):
            yield from iter_eqns(sub)


def _aval_dtypes(eqn):
    for var in list(eqn.invars) + list(eqn.outvars):
        aval = getattr(var, "aval", None)
        dtype = getattr(aval, "dtype", None)
        if dtype is not None:
            yield str(dtype)


# ------------------------------------------------------------- jaxpr rules


def audit_closed_jaxpr(
    closed,
    target: str = "<jaxpr>",
    const_threshold: int = DEFAULT_CONST_THRESHOLD,
) -> list:
    """Pure jaxpr rules (AF2A101/102/103) over an already-traced graph."""
    import numpy as np

    findings: list = []
    wide_hits: dict = {}
    callback_hits: dict = {}
    for eqn in iter_eqns(closed.jaxpr):
        name = eqn.primitive.name
        if name in FORBIDDEN_PRIMITIVES:
            callback_hits[name] = callback_hits.get(name, 0) + 1
        if name == "convert_element_type":
            new = str(eqn.params.get("new_dtype", ""))
            if new in WIDE_DTYPES:
                wide_hits[f"convert_element_type->{new}"] = (
                    wide_hits.get(f"convert_element_type->{new}", 0) + 1
                )
        for dtype in _aval_dtypes(eqn):
            if dtype in WIDE_DTYPES:
                wide_hits[dtype] = wide_hits.get(dtype, 0) + 1
    for what, count in sorted(wide_hits.items()):
        findings.append(_finding(
            "AF2A101", target,
            f"{what} appears {count}x in the graph; the TPU path is "
            "f32/bf16-only — find the implicit widening",
        ))
    for prim, count in sorted(callback_hits.items()):
        findings.append(_finding(
            "AF2A102", target,
            f"host callback primitive {prim!r} appears {count}x: each is a "
            "device->host round trip per executed step",
        ))
    for i, const in enumerate(closed.consts):
        try:
            nbytes = int(const.nbytes)
        except Exception:  # extended dtypes (PRNG keys) have no nbytes
            shape = tuple(getattr(const, "shape", ()))
            itemsize = getattr(
                getattr(const, "dtype", None), "itemsize", None
            )
            nbytes = int(np.prod(shape)) * int(itemsize or 4)
        if nbytes > const_threshold:
            shape = tuple(getattr(const, "shape", ()))
            findings.append(_finding(
                "AF2A103", target,
                f"baked-in constant #{i} is {nbytes} bytes (shape {shape}) "
                f"> threshold {const_threshold}; pass it as an argument so "
                "it is not serialized into every executable",
            ))
    return findings


def audit_donation(fn, args, donate_argnums, target: str) -> list:
    """AF2A104: donated input leaves with no shape/dtype-matching output."""
    import collections

    import jax

    out_shape = jax.eval_shape(fn, *args)
    out_sig = collections.Counter(
        (tuple(leaf.shape), str(leaf.dtype))
        for leaf in jax.tree.leaves(out_shape)
        if hasattr(leaf, "shape")
    )
    findings = []
    for argnum in donate_argnums:
        donated = jax.tree.leaves(args[argnum])
        dead = []
        for leaf in donated:
            if not hasattr(leaf, "shape"):
                continue
            sig = (tuple(leaf.shape), str(leaf.dtype))
            if out_sig.get(sig, 0) > 0:
                out_sig[sig] -= 1
            else:
                dead.append(f"{leaf.dtype}{list(leaf.shape)}")
        if dead and len(dead) == len(donated):
            findings.append(_finding(
                "AF2A104", target,
                f"donated argument {argnum} ({len(dead)} buffer(s): "
                f"{', '.join(sorted(set(dead))[:4])}...) matches no output "
                "shape/dtype — XLA cannot alias any of it; drop or justify "
                "the donation",
            ))
    return findings


# ----------------------------------------------------------------- targets


def _is_promotion_error(e: BaseException) -> bool:
    text = f"{type(e).__name__}: {e}"
    return "promot" in text.lower()


def audit_target(
    target, const_threshold: int = DEFAULT_CONST_THRESHOLD
) -> list:
    """Trace one :class:`~alphafold2_tpu.analysis.targets.TraceTarget` and
    run every rule, honoring its ``allow`` waivers."""
    import jax

    name = target.name
    try:
        fn, args = target.build()
    except Exception as e:  # build failures are un-audit-able targets
        return [_finding(
            "AF2A100", name,
            f"target build failed: {type(e).__name__}: {str(e)[:300]}",
        )]

    findings: list = []
    # strict promotion first: the same trace, one config flag stricter
    with jax.numpy_dtype_promotion("strict"):
        try:
            closed = jax.make_jaxpr(fn)(*args)
            strict_ok = True
        except Exception as e:
            strict_ok = False
            if _is_promotion_error(e):
                findings.append(_finding(
                    "AF2A105", name,
                    "trace raises under strict dtype promotion: "
                    f"{str(e).splitlines()[0][:300]}",
                ))
            else:
                findings.append(_finding(
                    "AF2A100", name,
                    f"trace failed (strict promotion): {type(e).__name__}: "
                    f"{str(e)[:300]}",
                ))
    if not strict_ok:
        try:
            closed = jax.make_jaxpr(fn)(*args)
        except Exception as e:
            return [f for f in findings if f.rule != "AF2A105"] + [_finding(
                "AF2A100", name,
                f"trace failed: {type(e).__name__}: {str(e)[:300]}",
            )]

    findings.extend(audit_closed_jaxpr(closed, name, const_threshold))
    if target.donate_argnums:
        findings.extend(
            audit_donation(fn, args, target.donate_argnums, name)
        )
    return [f for f in findings if f.rule not in target.allow]


def audit(
    targets=None, const_threshold: int = DEFAULT_CONST_THRESHOLD
) -> list:
    from alphafold2_tpu.analysis.targets import default_targets

    targets = targets if targets is not None else default_targets()
    findings: list = []
    for t in targets:
        findings.extend(audit_target(t, const_threshold))
    return findings


# ------------------------------------------------------- lowering rule set


def lowering_findings(case_names=None) -> list:
    """Run the Mosaic TPU lowering gate (analysis.lowering) in a scrubbed
    subprocess and convert failed cases into AF2A106 findings.

    This is the fold-in of ``scripts/check_tpu_lowering.py``: same cases,
    same negative control, one findings stream."""
    import subprocess
    import sys

    from alphafold2_tpu.preflight import scrub_axon_env

    env = scrub_axon_env()
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["AF2TPU_LOWERING_GATE_SCRUBBED"] = "1"
    cmd = [sys.executable, "-m", "alphafold2_tpu.analysis.lowering"]
    cmd += list(case_names or ())
    proc = subprocess.run(
        cmd, capture_output=True, text=True, env=env, timeout=1800
    )
    findings = []
    summary = None
    for line in proc.stdout.splitlines():
        if not line.startswith("{"):
            continue
        rec = json.loads(line)
        if rec.get("gate"):
            summary = rec
        elif "case" in rec and not rec.get("ok"):
            findings.append(_finding(
                "AF2A106", rec["case"],
                f"Mosaic lowering failed: {rec.get('error', '?')[:300]}",
            ))
    if summary is None:
        findings.append(_finding(
            "AF2A106", "lowering_gate",
            "gate produced no summary record "
            f"(rc={proc.returncode}); stderr tail: {proc.stderr[-300:]}",
        ))
    elif summary.get("error"):
        # e.g. a typo'd case name: the gate refuses to certify anything —
        # that refusal must surface as a finding, not read as green
        findings.append(_finding(
            "AF2A106", "lowering_gate", f"gate error: {summary['error']}"
        ))
    return findings


# --------------------------------------------------------------------- CLI


def findings_to_json(findings: list) -> str:
    return json.dumps(
        {
            "tool": "jaxpr_audit",
            "findings": [f.to_dict() for f in findings],
            "counts": {
                "error": sum(1 for f in findings if f.severity == "error"),
                "warning": sum(
                    1 for f in findings if f.severity == "warning"
                ),
            },
        },
        indent=2,
    )


def main(argv=None) -> int:
    import argparse

    from alphafold2_tpu.analysis.targets import default_targets, target_by_name

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--targets", default=None,
        help="comma-separated target names (default: all registered)",
    )
    parser.add_argument(
        "--rules", default="jaxpr",
        help="comma-separated rule sets: jaxpr, lowering (default: jaxpr)",
    )
    parser.add_argument(
        "--const-threshold", type=int, default=DEFAULT_CONST_THRESHOLD
    )
    parser.add_argument("--json", dest="json_path", default=None)
    args = parser.parse_args(argv)

    rule_sets = {s.strip() for s in args.rules.split(",") if s.strip()}
    unknown = rule_sets - {"jaxpr", "lowering"}
    if unknown:
        print(f"unknown rule set(s): {sorted(unknown)}")
        return 2

    findings: list = []
    if "jaxpr" in rule_sets:
        if args.targets:
            try:
                targets = [
                    target_by_name(n.strip())
                    for n in args.targets.split(",") if n.strip()
                ]
            except KeyError as e:
                print(str(e))
                return 2
        else:
            targets = default_targets()
        findings.extend(audit(targets, args.const_threshold))
    if "lowering" in rule_sets:
        findings.extend(lowering_findings())

    for f in findings:
        print(f.format())
    if args.json_path:
        with open(args.json_path, "w", encoding="utf-8") as fh:
            fh.write(findings_to_json(findings))
    print(
        f"jaxpr_audit: {len(findings)} finding(s) over rule sets "
        f"{sorted(rule_sets)}"
    )
    return 1 if findings else 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
