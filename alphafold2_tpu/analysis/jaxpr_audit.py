"""Jaxpr/HLO auditor (layer 2): semantic graph-hygiene enforcement.

The AST linter (:mod:`lint`) catches what is visible in source; this module
catches what is only visible in the traced graph. It abstractly traces the
registered executables (:mod:`targets`: model forward, train step, serve
forward) on the host — no device, no compile — and statically rejects:

- ``AF2A100`` error — the target fails to trace at all (the audit cannot
  certify a graph it cannot build).
- ``AF2A101`` error — float64/complex128 anywhere in the graph (any aval or
  a ``convert_element_type`` to a wide dtype): on TPU an f64 leak is a
  silent 2x memory + emulation cliff, paid at N^2 scale in the pair stream.
- ``AF2A102`` error — host-callback primitives in the hot path
  (``pure_callback``/``io_callback``/``debug_callback``/infeed/outfeed):
  each one is a device->host round trip per step.
- ``AF2A103`` error — giant baked-in constants (> threshold bytes closed
  over into the jaxpr): they bloat every executable and recompile key
  instead of riding as arguments.
- ``AF2A104`` warning — broken donation: a ``donate_argnums`` declaration
  whose buffers can never alias any output (no shape/dtype match), i.e.
  the donation documents an intent the runtime cannot honor.
- ``AF2A105`` error — the target only traces under default dtype
  promotion: under ``jax.numpy_dtype_promotion("strict")`` the trace
  raises, meaning an implicit promotion (usually bool/int drawn into
  float math) is hiding in the graph.

Rule ``AF2A106`` (Mosaic TPU lowering failure) folds the Pallas lowering
gate (:mod:`alphafold2_tpu.analysis.lowering`, formerly the whole of
``scripts/check_tpu_lowering.py``) into the same findings stream, and the
``hlo`` rule set folds in the compiled-HLO audit
(:mod:`alphafold2_tpu.analysis.hlo_audit`) — collective census drift vs
the committed ``hlo_contracts.json`` (``AF2A107``), sharded-but-replicated
/ collective blowups (``AF2A108``), collectives in single-device targets
(``AF2A109``) and per-device HBM budget breaches (``AF2A110``) — so
``--rules jaxpr,lowering,hlo`` is the single pre-hardware gate entry point
the first TPU session runs before anything burns bench time.

Traversal note: rule scans walk :func:`iter_eqns_deep`, which additionally
recurses into ``custom_vjp``/``custom_jvp`` forward AND backward bodies
(traced on the spot from the stored thunks) — a host callback or f64
widening hiding inside a custom-VJP closure (e.g. a Pallas kernel's
backward) cannot pass silently. :func:`iter_eqns` keeps the historical
shallow-ish traversal because the graph-contract fingerprints
(:mod:`contracts`) are built on it; changing it would re-key every
committed contract.

CLI::

    JAX_PLATFORMS=cpu python -m alphafold2_tpu.analysis.jaxpr_audit \
        [--targets model_fwd,train_step] [--rules jaxpr,lowering,hlo] \
        [--const-threshold BYTES] [--json out.json]

Exit codes: 0 clean, 1 findings, 2 usage error. Targets may waive specific
rules (with a recorded reason) via ``TraceTarget.allow``.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Iterable, Optional

AUDIT_RULES = {
    "AF2A100": ("error", "target fails to trace"),
    "AF2A101": ("error", "float64/complex128 in graph"),
    "AF2A102": ("error", "host callback primitive in hot path"),
    "AF2A103": ("error", "giant baked-in constant"),
    "AF2A104": ("warning", "declared donation can never alias"),
    "AF2A105": ("error", "strict dtype promotion violation"),
    "AF2A106": ("error", "Mosaic TPU lowering failure"),
    "AF2A107": ("error", "HLO collective-census/contract drift"),
    "AF2A108": ("error", "sharded target replicated / collective blowup"),
    "AF2A109": ("error", "collectives in a single-device target"),
    "AF2A110": ("error", "per-device footprint over HBM budget"),
}

FORBIDDEN_PRIMITIVES = {
    "pure_callback",
    "io_callback",
    "debug_callback",
    "callback",
    "host_callback",
    "outside_call",
    "infeed",
    "outfeed",
}

WIDE_DTYPES = ("float64", "complex128")

DEFAULT_CONST_THRESHOLD = 1 << 20  # 1 MiB baked into a graph is a bug


@dataclasses.dataclass(frozen=True)
class AuditFinding:
    rule: str
    severity: str
    target: str
    message: str

    def format(self) -> str:
        return f"{self.target}: {self.rule} [{self.severity}] {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _finding(rule: str, target: str, message: str) -> AuditFinding:
    return AuditFinding(rule, AUDIT_RULES[rule][0], target, message)


# --------------------------------------------------------------- traversal


def _sub_jaxprs(params: dict):
    from jax.extend import core as jex_core

    def walk(value):
        if isinstance(value, jex_core.ClosedJaxpr):
            yield value.jaxpr
        elif isinstance(value, jex_core.Jaxpr):
            yield value
        elif isinstance(value, (list, tuple)):
            for v in value:
                yield from walk(v)

    for value in params.values():
        yield from walk(value)


def iter_eqns(jaxpr) -> Iterable:
    """Every equation in ``jaxpr``, recursing into call/control-flow
    sub-jaxprs (scan bodies, cond branches, pjit calls, remat).

    This is the traversal the graph-contract fingerprints (:mod:`contracts`)
    are keyed on — keep it stable; rule scans use :func:`iter_eqns_deep`."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn.params):
            yield from iter_eqns(sub)


def _custom_vjp_bodies(eqn, failures: Optional[list] = None):
    """The fwd and bwd bodies of a ``custom_vjp_call`` equation.

    ``_sub_jaxprs`` only sees the primal ``fun_jaxpr`` — exactly the body a
    custom VJP *replaces* under differentiation. The real fwd is stored as
    ``fwd_jaxpr_thunk`` (called with one tangent-nonzero flag per
    non-const input — all True is the generic jvp) and the bwd as the raw
    ``bwd`` callable, which we trace at the fwd's (residual, cotangent)
    avals (the fwd jaxpr returns residuals first, primal outputs last).
    Anything untraceable is recorded in ``failures`` instead of silently
    skipped — an unauditable closure must surface as a finding, not read
    as clean."""
    params = eqn.params
    thunk = params.get("fwd_jaxpr_thunk")
    if thunk is None:
        return
    n_primal = len(eqn.outvars)
    n_flags = len(eqn.invars) - params.get("num_consts", 0)
    try:
        fwd_jaxpr = thunk(*([True] * n_flags))[0]
    except Exception as e:
        if failures is not None:
            failures.append(
                f"custom_vjp fwd body untraceable: {type(e).__name__}: "
                f"{str(e)[:200]}"
            )
        return
    yield fwd_jaxpr
    bwd = params.get("bwd")
    if bwd is None:
        return
    try:
        import jax

        outs = [v.aval for v in fwd_jaxpr.outvars]
        res_avals = outs[:-n_primal] if n_primal else outs
        ct_avals = outs[-n_primal:] if n_primal else []
        closed = jax.make_jaxpr(lambda *a: bwd(*a))(*[
            jax.ShapeDtypeStruct(a.shape, a.dtype)
            for a in list(res_avals) + list(ct_avals)
        ])
        yield closed.jaxpr
    except Exception as e:
        if failures is not None:
            failures.append(
                f"custom_vjp bwd body untraceable: {type(e).__name__}: "
                f"{str(e)[:200]}"
            )


def _custom_jvp_bodies(eqn, failures: Optional[list] = None):
    """The jvp body of a ``custom_jvp_call`` equation: the memoized
    ``jvp_jaxpr_thunk`` takes one *symbolic-zero* flag per non-const input
    (NOTE: inverted vs the vjp thunk's nonzero flags — all False is the
    generic every-tangent-live case) and returns ``(jaxpr, consts, ...)``.
    Failures are recorded so an unauditable closure surfaces instead of
    passing silently."""
    params = eqn.params
    thunk = params.get("jvp_jaxpr_thunk")
    if thunk is None:
        return
    n_flags = len(eqn.invars) - params.get("num_consts", 0)
    try:
        jvp_jaxpr = thunk(*([False] * n_flags))[0]
    except Exception as e:
        if failures is not None:
            failures.append(
                f"custom_jvp body untraceable: {type(e).__name__}: "
                f"{str(e)[:200]}"
            )
        return
    yield jvp_jaxpr


def _eqn_signature(eqn) -> tuple:
    """Structural identity of a custom_vjp/jvp call site: the standard
    pattern (``f_fwd`` calling ``f(x)``) re-embeds the SAME custom call in
    its own fwd body, so expansion must dedupe by signature or it recurses
    forever — each thunk call builds a fresh jaxpr, so object identity
    cannot terminate it."""
    return (
        eqn.primitive.name,
        tuple(str(getattr(v, "aval", v)) for v in eqn.invars),
        tuple(str(getattr(v, "aval", v)) for v in eqn.outvars),
    )


def _deep_sub_jaxprs(eqn, failures: Optional[list] = None,
                     seen: Optional[set] = None):
    """Everything :func:`_sub_jaxprs` yields, plus dict-valued params and
    the custom_vjp/custom_jvp fwd/bwd/jvp bodies (expanded once per call
    signature)."""
    from jax.extend import core as jex_core

    yield from _sub_jaxprs(eqn.params)
    for value in eqn.params.values():
        if isinstance(value, dict):
            for v in value.values():
                if isinstance(v, jex_core.ClosedJaxpr):
                    yield v.jaxpr
                elif isinstance(v, jex_core.Jaxpr):
                    yield v
    name = eqn.primitive.name
    if not (name.startswith("custom_vjp_call")
            or name.startswith("custom_jvp_call")):
        return
    sig = _eqn_signature(eqn)
    if seen is not None:
        if sig in seen:
            return
        seen.add(sig)
    if name.startswith("custom_vjp_call"):
        yield from _custom_vjp_bodies(eqn, failures)
    else:
        yield from _custom_jvp_bodies(eqn, failures)


def iter_eqns_deep(jaxpr, failures: Optional[list] = None) -> Iterable:
    """:func:`iter_eqns` plus recursion into custom_vjp/custom_jvp bodies;
    untraceable bodies append a reason to ``failures`` (when given) so the
    caller can refuse to certify what it could not walk."""
    seen: set = set()

    def rec(jx):
        for eqn in jx.eqns:
            yield eqn
            for sub in _deep_sub_jaxprs(eqn, failures, seen):
                yield from rec(sub)

    yield from rec(jaxpr)


def _aval_dtypes(eqn):
    for var in list(eqn.invars) + list(eqn.outvars):
        aval = getattr(var, "aval", None)
        dtype = getattr(aval, "dtype", None)
        if dtype is not None:
            yield str(dtype)


# ------------------------------------------------------------- jaxpr rules


def audit_closed_jaxpr(
    closed,
    target: str = "<jaxpr>",
    const_threshold: int = DEFAULT_CONST_THRESHOLD,
) -> list:
    """Pure jaxpr rules (AF2A101/102/103) over an already-traced graph.

    Walks :func:`iter_eqns_deep`, so hits inside custom_vjp/custom_jvp
    closures count (possibly twice — a primal body shared by the fwd is
    walked in both; the count is a locator, not an exact census). A body
    the walker could not trace becomes an AF2A100 finding."""
    import numpy as np

    findings: list = []
    wide_hits: dict = {}
    callback_hits: dict = {}
    trace_failures: list = []
    for eqn in iter_eqns_deep(closed.jaxpr, trace_failures):
        name = eqn.primitive.name
        if name in FORBIDDEN_PRIMITIVES:
            callback_hits[name] = callback_hits.get(name, 0) + 1
        if name == "convert_element_type":
            new = str(eqn.params.get("new_dtype", ""))
            if new in WIDE_DTYPES:
                wide_hits[f"convert_element_type->{new}"] = (
                    wide_hits.get(f"convert_element_type->{new}", 0) + 1
                )
        for dtype in _aval_dtypes(eqn):
            if dtype in WIDE_DTYPES:
                wide_hits[dtype] = wide_hits.get(dtype, 0) + 1
    for why in sorted(set(trace_failures)):
        findings.append(_finding(
            "AF2A100", target,
            f"cannot audit a closed-over body: {why}",
        ))
    for what, count in sorted(wide_hits.items()):
        findings.append(_finding(
            "AF2A101", target,
            f"{what} appears {count}x in the graph; the TPU path is "
            "f32/bf16-only — find the implicit widening",
        ))
    for prim, count in sorted(callback_hits.items()):
        findings.append(_finding(
            "AF2A102", target,
            f"host callback primitive {prim!r} appears {count}x: each is a "
            "device->host round trip per executed step",
        ))
    for i, const in enumerate(closed.consts):
        try:
            nbytes = int(const.nbytes)
        except Exception:  # extended dtypes (PRNG keys) have no nbytes
            shape = tuple(getattr(const, "shape", ()))
            itemsize = getattr(
                getattr(const, "dtype", None), "itemsize", None
            )
            nbytes = int(np.prod(shape)) * int(itemsize or 4)
        if nbytes > const_threshold:
            shape = tuple(getattr(const, "shape", ()))
            findings.append(_finding(
                "AF2A103", target,
                f"baked-in constant #{i} is {nbytes} bytes (shape {shape}) "
                f"> threshold {const_threshold}; pass it as an argument so "
                "it is not serialized into every executable",
            ))
    return findings


def audit_donation(fn, args, donate_argnums, target: str) -> list:
    """AF2A104: donated input leaves with no shape/dtype-matching output."""
    import collections

    import jax

    out_shape = jax.eval_shape(fn, *args)
    out_sig = collections.Counter(
        (tuple(leaf.shape), str(leaf.dtype))
        for leaf in jax.tree.leaves(out_shape)
        if hasattr(leaf, "shape")
    )
    findings = []
    for argnum in donate_argnums:
        donated = jax.tree.leaves(args[argnum])
        dead = []
        for leaf in donated:
            if not hasattr(leaf, "shape"):
                continue
            sig = (tuple(leaf.shape), str(leaf.dtype))
            if out_sig.get(sig, 0) > 0:
                out_sig[sig] -= 1
            else:
                dead.append(f"{leaf.dtype}{list(leaf.shape)}")
        if dead and len(dead) == len(donated):
            findings.append(_finding(
                "AF2A104", target,
                f"donated argument {argnum} ({len(dead)} buffer(s): "
                f"{', '.join(sorted(set(dead))[:4])}...) matches no output "
                "shape/dtype — XLA cannot alias any of it; drop or justify "
                "the donation",
            ))
    return findings


# ----------------------------------------------------------------- targets


def _is_promotion_error(e: BaseException) -> bool:
    text = f"{type(e).__name__}: {e}"
    return "promot" in text.lower()


def audit_target(
    target, const_threshold: int = DEFAULT_CONST_THRESHOLD
) -> list:
    """Trace one :class:`~alphafold2_tpu.analysis.targets.TraceTarget` and
    run every rule, honoring its ``allow`` waivers."""
    import jax

    name = target.name
    try:
        fn, args = target.build()
    except Exception as e:  # build failures are un-audit-able targets
        return [_finding(
            "AF2A100", name,
            f"target build failed: {type(e).__name__}: {str(e)[:300]}",
        )]

    findings: list = []
    # strict promotion first: the same trace, one config flag stricter
    with jax.numpy_dtype_promotion("strict"):
        try:
            closed = jax.make_jaxpr(fn)(*args)
            strict_ok = True
        except Exception as e:
            strict_ok = False
            if _is_promotion_error(e):
                findings.append(_finding(
                    "AF2A105", name,
                    "trace raises under strict dtype promotion: "
                    f"{str(e).splitlines()[0][:300]}",
                ))
            else:
                findings.append(_finding(
                    "AF2A100", name,
                    f"trace failed (strict promotion): {type(e).__name__}: "
                    f"{str(e)[:300]}",
                ))
    if not strict_ok:
        try:
            closed = jax.make_jaxpr(fn)(*args)
        except Exception as e:
            return [f for f in findings if f.rule != "AF2A105"] + [_finding(
                "AF2A100", name,
                f"trace failed: {type(e).__name__}: {str(e)[:300]}",
            )]

    findings.extend(audit_closed_jaxpr(closed, name, const_threshold))
    if target.donate_argnums:
        findings.extend(
            audit_donation(fn, args, target.donate_argnums, name)
        )
    return [f for f in findings if f.rule not in target.allow]


def audit(
    targets=None, const_threshold: int = DEFAULT_CONST_THRESHOLD
) -> list:
    from alphafold2_tpu.analysis.targets import default_targets

    targets = targets if targets is not None else default_targets()
    findings: list = []
    for t in targets:
        findings.extend(audit_target(t, const_threshold))
    return findings


# ------------------------------------------------------- lowering rule set


def lowering_findings(case_names=None) -> list:
    """Run the Mosaic TPU lowering gate (analysis.lowering) in a scrubbed
    subprocess and convert failed cases into AF2A106 findings.

    This is the fold-in of ``scripts/check_tpu_lowering.py``: same cases,
    same negative control, one findings stream."""
    import subprocess
    import sys

    from alphafold2_tpu.preflight import scrub_axon_env

    env = scrub_axon_env()
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["AF2TPU_LOWERING_GATE_SCRUBBED"] = "1"
    cmd = [sys.executable, "-m", "alphafold2_tpu.analysis.lowering"]
    cmd += list(case_names or ())
    proc = subprocess.run(
        cmd, capture_output=True, text=True, env=env, timeout=1800
    )
    findings = []
    summary = None
    for line in proc.stdout.splitlines():
        if not line.startswith("{"):
            continue
        rec = json.loads(line)
        if rec.get("gate"):
            summary = rec
        elif "case" in rec and not rec.get("ok"):
            findings.append(_finding(
                "AF2A106", rec["case"],
                f"Mosaic lowering failed: {rec.get('error', '?')[:300]}",
            ))
    if summary is None:
        findings.append(_finding(
            "AF2A106", "lowering_gate",
            "gate produced no summary record "
            f"(rc={proc.returncode}); stderr tail: {proc.stderr[-300:]}",
        ))
    elif summary.get("error"):
        # e.g. a typo'd case name: the gate refuses to certify anything —
        # that refusal must surface as a finding, not read as green
        findings.append(_finding(
            "AF2A106", "lowering_gate", f"gate error: {summary['error']}"
        ))
    return findings


# ------------------------------------------------------------ hlo rule set


def hlo_findings(target_names=None) -> list:
    """Run the compiled-HLO audit (analysis.hlo_audit --check) in a
    scrubbed subprocess pinned to the CPU backend with 8 virtual devices —
    the same device count the committed ``hlo_contracts.json`` is keyed by
    — and fold its findings (AF2A107–110) into this stream.

    A subprocess for the same reason as the lowering gate: the parent may
    already hold a differently-sized backend, and device count is part of
    the contract key. A gate that produces no summary is itself an
    AF2A107 finding — a refusal to certify must never read as green."""
    import subprocess
    import sys

    from alphafold2_tpu.preflight import scrub_axon_env

    env = scrub_axon_env()
    env.setdefault("JAX_PLATFORMS", "cpu")
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    cmd = [sys.executable, "-m", "alphafold2_tpu.analysis.hlo_audit",
           "--check"]
    if target_names:
        cmd += ["--targets", ",".join(target_names)]
    proc = subprocess.run(
        cmd, capture_output=True, text=True, env=env, timeout=1800
    )
    summary = None
    for line in proc.stdout.splitlines():
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if rec.get("gate") == "hlo":
            summary = rec
    if summary is None:
        return [_finding(
            "AF2A107", "hlo_gate",
            f"hlo gate produced no summary record (rc={proc.returncode}); "
            f"stderr tail: {proc.stderr[-300:]}",
        )]
    if summary.get("verdict") == "stale-baseline":
        print(
            "jaxpr_audit: hlo gate reports a STALE baseline "
            "(recompile key changed) — re-baseline hlo_contracts.json"
        )
    return [
        AuditFinding(
            rec["rule"], rec["severity"], rec["target"], rec["message"]
        )
        for rec in summary.get("findings", [])
    ]


# ---------------------------------------------------- concurrency rule set


def concurrency_findings() -> list:
    """Run static layer 5 in-process — the concurrency auditor (AF2C:
    lock-order graph, guard contracts, thread/queue lifecycles), its
    committed-contract check, and the knob registry (AF2K) — and fold
    the findings into this stream.

    In-process because it is pure stdlib AST: no jax, no backend, no
    subprocess. The contract check honors the same stale-baseline escape
    as the graph/hlo gates; gated-defect functions (the
    ``AF2TPU_AUDIT_INVERT_LOCKS`` negative control) surface here as
    findings when their env var is set but never enter the contracts. A
    crashed scan must never read as green — it becomes AF2C000."""
    from alphafold2_tpu.analysis import concurrency, knobs

    findings: list = []
    try:
        model = concurrency.build_model()
        for f in model.findings():
            findings.append(AuditFinding(
                f.rule, f.severity, "concurrency",
                f"{f.path}:{f.line}: {f.message}",
            ))
        verdict, lines = concurrency.check_against(
            concurrency.DEFAULT_BASELINE, concurrency.compute_contracts(model)
        )
        if verdict == "stale-baseline":
            print(
                "jaxpr_audit: concurrency gate reports a STALE baseline "
                "(format changed) — re-baseline concurrency_contracts.json"
            )
        elif verdict != "pass":
            for line in lines:
                findings.append(AuditFinding(
                    "AF2C009", "error", "concurrency_contracts", line,
                ))
    except Exception as e:  # noqa: BLE001 — a broken gate must be loud
        findings.append(AuditFinding(
            "AF2C000", "error", "concurrency",
            f"concurrency audit crashed: {type(e).__name__}: {e}",
        ))
    try:
        for f in knobs.audit():
            findings.append(AuditFinding(
                f.rule, f.severity, "knobs",
                f"{f.path}:{f.line}: {f.message}",
            ))
    except Exception as e:  # noqa: BLE001
        findings.append(AuditFinding(
            "AF2C000", "error", "knobs",
            f"knob audit crashed: {type(e).__name__}: {e}",
        ))
    return findings


# --------------------------------------------------------------------- CLI


def findings_to_json(findings: list) -> str:
    return json.dumps(
        {
            "tool": "jaxpr_audit",
            "findings": [f.to_dict() for f in findings],
            "counts": {
                "error": sum(1 for f in findings if f.severity == "error"),
                "warning": sum(
                    1 for f in findings if f.severity == "warning"
                ),
            },
        },
        indent=2,
    )


def main(argv=None) -> int:
    import argparse

    from alphafold2_tpu.analysis.targets import default_targets, target_by_name

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--targets", default=None,
        help="comma-separated target names (default: all registered)",
    )
    parser.add_argument(
        "--rules", default="jaxpr",
        help=(
            "comma-separated rule sets: jaxpr, lowering, hlo, "
            "concurrency (default: jaxpr)"
        ),
    )
    parser.add_argument(
        "--const-threshold", type=int, default=DEFAULT_CONST_THRESHOLD
    )
    parser.add_argument("--json", dest="json_path", default=None)
    args = parser.parse_args(argv)

    rule_sets = {s.strip() for s in args.rules.split(",") if s.strip()}
    unknown = rule_sets - {"jaxpr", "lowering", "hlo", "concurrency"}
    if unknown:
        print(f"unknown rule set(s): {sorted(unknown)}")
        return 2

    findings: list = []
    if "jaxpr" in rule_sets:
        if args.targets:
            try:
                targets = [
                    target_by_name(n.strip())
                    for n in args.targets.split(",") if n.strip()
                ]
            except KeyError as e:
                print(str(e))
                return 2
        else:
            targets = default_targets()
        findings.extend(audit(targets, args.const_threshold))
    if "lowering" in rule_sets:
        findings.extend(lowering_findings())
    if "hlo" in rule_sets:
        findings.extend(hlo_findings())
    if "concurrency" in rule_sets:
        findings.extend(concurrency_findings())

    for f in findings:
        print(f.format())
    if args.json_path:
        with open(args.json_path, "w", encoding="utf-8") as fh:
            fh.write(findings_to_json(findings))
    print(
        f"jaxpr_audit: {len(findings)} finding(s) over rule sets "
        f"{sorted(rule_sets)}"
    )
    return 1 if findings else 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
