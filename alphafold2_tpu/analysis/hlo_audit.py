"""Compiled-HLO audit: static comm/memory contracts for the serve lattice.

The jaxpr auditor (:mod:`jaxpr_audit`) and the graph contracts
(:mod:`contracts`) pin what WE wrote — the traced graph. This module pins
what XLA actually *did* with it: the post-SPMD-partitioning optimized HLO,
where the real scaling hazards of the N^2 pair trunk live. Three passes
over each ``hlo=True`` target in the registry (analysis/targets.py):

1. **Collective census** — count and classify every cross-device
   collective (all-reduce / all-gather / reduce-scatter /
   collective-permute / all-to-all) in the optimized module, estimate the
   bytes each moves from its result shape, and report comm volume next to
   the XLA FLOP count as a comm/compute ratio.
2. **Resharding detector** — rules AF2A107–AF2A110: a dropped
   ``shard_pair`` constraint surfaces as a named per-collective census
   delta (AF2A107 drift), a fully-replicated "sharded" target or a
   single-collective byte blowup (AF2A108), collectives appearing in a
   target declared single-device (AF2A109).
3. **Memory-budget contract** — the per-device footprint from XLA
   ``memory_analysis()`` gated against the target's declared
   ``hbm_budget_bytes`` (AF2A110, verdicts via analysis/budgets.py).

Census + memory + budget verdicts are fingerprinted into a committed
``hlo_contracts.json`` beside ``graph_contracts.json`` and diffed exactly:
any collective appearing, disappearing, or changing size is a named,
reviewed diff — caught at compile time on a laptop or in CI's 8-virtual-
device mesh, with no bench run and no TPU.

Byte estimates read the HLO *result* types: for all-gather that is the
gathered (global) operand — the traffic a ring implementation actually
moves per device up to the (P-1)/P factor — and for tuple-shaped
all-to-alls the sum over tuple elements. They are contract figures
(deterministic, comparable), not a performance model.

Baselines are keyed by jax version AND device count; a mismatch reports
``stale-baseline`` loudly without failing (exactly the graph-contract
policy), so version bumps are explicit re-baselines, not red CI.

CLI::

    python -m alphafold2_tpu.analysis.hlo_audit --check
    python -m alphafold2_tpu.analysis.hlo_audit --update
    python -m alphafold2_tpu.analysis.hlo_audit --check --targets serve_fwd_long

Exit codes: 0 clean (or stale-baseline, loudly), 1 findings/drift,
2 missing baseline or usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Optional

from alphafold2_tpu.analysis.budgets import check_budget, format_budget
from alphafold2_tpu.analysis.jaxpr_audit import _finding

FORMAT_VERSION = 1

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ),
    "hlo_contracts.json",
)

COLLECTIVE_KINDS = (
    "all-gather",
    "all-reduce",
    "all-to-all",
    "collective-permute",
    "reduce-scatter",
)

# An HLO instruction line is "%name = <result type> <opcode>(operands...)";
# requiring "(" right after the opcode keeps operand *references* to ops
# named %all-gather.3 (never followed by "(") from matching, and the
# -start/-done suffixes fold async pairs into one logical op.
_COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|collective-permute"
    r"|all-to-all)(-start|-done)?\("
)
_SHAPE_RE = re.compile(r"\b([a-z]+[0-9]*(?:\[[0-9,]*\]))")
_NUM_PARTITIONS_RE = re.compile(r"num_partitions=(\d+)")

DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "bf16": 2, "f16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8,
}

# Absolute backstop for the single-collective blowup rule when a target
# declares no budget: no tiny audit target legitimately gathers a GiB.
DEFAULT_BLOWUP_BYTES = 1 << 30


# --------------------------------------------------------------- parsing


def shape_bytes(token: str) -> int:
    """Bytes of one HLO shape token like ``f32[2,48,48,32]`` (0 if the
    token is not a shape; unknown dtypes assume 4 bytes)."""
    m = re.match(r"([a-z]+[0-9]*)\[([0-9,]*)\]", token)
    if not m:
        return 0
    dtype, dims = m.groups()
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * DTYPE_BYTES.get(dtype, 4)


def parse_collectives(hlo_text: str) -> list:
    """Every collective op in an optimized HLO module text, as
    ``{"kind", "bytes"}`` dicts (bytes = result-shape size, summed over
    tuple elements; async ``-done`` halves skipped so start/done pairs
    count once)."""
    ops = []
    for line in hlo_text.splitlines():
        if " = " not in line:
            continue
        rhs = line.split(" = ", 1)[1]
        m = _COLLECTIVE_RE.search(rhs)
        if not m or m.group(2) == "-done":
            continue
        nbytes = sum(
            shape_bytes(tok) for tok in _SHAPE_RE.findall(rhs[: m.start()])
        )
        ops.append({"kind": m.group(1), "bytes": nbytes})
    return ops


def collective_census(hlo_text: str) -> dict:
    """Aggregate :func:`parse_collectives` into
    ``{kind: {"count", "bytes"}}``, kinds sorted for stable JSON."""
    census: dict = {}
    for op in parse_collectives(hlo_text):
        d = census.setdefault(op["kind"], {"count": 0, "bytes": 0})
        d["count"] += 1
        d["bytes"] += op["bytes"]
    return {k: census[k] for k in sorted(census)}


def num_partitions(hlo_text: str) -> int:
    """SPMD partition count from the HloModule header (1 if absent). The
    header line can run to many KB (the entry layout rides on it), so
    scan the whole text — the attribute only ever appears there."""
    m = _NUM_PARTITIONS_RE.search(hlo_text)
    return int(m.group(1)) if m else 1


# ------------------------------------------------------------- recording


def compile_target(target):
    """AOT-compile one registry target the way the serve engine does
    (lower at the example args, then compile)."""
    import jax

    fn, args = target.build()
    return jax.jit(fn).lower(*args).compile()


def hlo_record(target, compiled=None, hlo_text: Optional[str] = None) -> dict:
    """The committed per-target contract record: census, comm/compute
    ratio, per-device memory figures, and the budget verdict."""
    from alphafold2_tpu.observe.flops import (
        executable_costs,
        executable_memory,
    )

    if compiled is None:
        compiled = compile_target(target)
    if hlo_text is None:
        hlo_text = compiled.as_text()
    census = collective_census(hlo_text)
    memory = executable_memory(compiled)
    flops = executable_costs(compiled)["flops"]
    comm_bytes = int(sum(v["bytes"] for v in census.values()))
    return {
        "sharded": bool(target.sharded),
        "num_partitions": num_partitions(hlo_text),
        "collectives": census,
        "collective_count": int(sum(v["count"] for v in census.values())),
        "comm_bytes": comm_bytes,
        "flops": flops,
        "comm_bytes_per_flop": (
            round(comm_bytes / flops, 8) if flops else None
        ),
        **memory,
        "hbm_budget_bytes": target.hbm_budget_bytes,
        "budget": check_budget(
            memory.get("program_bytes"), target.hbm_budget_bytes
        ),
    }


# --------------------------------------------------- structural rules


def audit_record(name: str, rec: dict, per_op=None) -> list:
    """Baseline-free structural rules over one contract record:
    AF2A108 (sharded-but-replicated / single-collective blowup),
    AF2A109 (collectives in a single-device target),
    AF2A110 (per-device footprint over the declared HBM budget)."""
    findings = []
    n_coll = rec.get("collective_count", 0)
    kinds = ", ".join(
        f"{k} x{v['count']}" for k, v in rec.get("collectives", {}).items()
    )
    if not rec.get("sharded") and n_coll:
        findings.append(_finding(
            "AF2A109", name,
            f"declared single-device but the optimized HLO contains "
            f"{n_coll} cross-device collective(s): {kinds} — an implicit "
            "resharding crept into an unsharded executable",
        ))
    if rec.get("sharded") and rec.get("num_partitions", 1) > 1 and not n_coll:
        findings.append(_finding(
            "AF2A108", name,
            f"declared sharded and SPMD-partitioned "
            f"{rec['num_partitions']} ways, yet the optimized HLO has "
            "ZERO cross-device collectives — the sharding constraints "
            "are inert and every device holds the fully replicated state",
        ))
    blowup = rec.get("hbm_budget_bytes") or DEFAULT_BLOWUP_BYTES
    for op in per_op or ():
        if op["bytes"] > blowup:
            findings.append(_finding(
                "AF2A108", name,
                f"single {op['kind']} result is {op['bytes']} bytes "
                f"(> {blowup}) — a replicated-operand blowup; some input "
                "to this collective lost its sharding",
            ))
    budget = rec.get("budget", {})
    if budget.get("verdict") == "over-budget":
        findings.append(_finding(
            "AF2A110", name,
            "per-device footprint over declared HBM budget: "
            + format_budget(name, budget),
        ))
    return findings


# ------------------------------------------------------------ contracts


def audit_hlo(targets=None) -> tuple:
    """Compile every HLO-audited target and return
    ``(contract_doc, structural_findings)``. Compile failures become
    AF2A100 findings (the audit cannot certify what it cannot compile);
    per-target ``allow`` waivers apply exactly as in the jaxpr audit."""
    import jax

    from alphafold2_tpu.analysis.targets import hlo_targets

    doc = {
        "format": FORMAT_VERSION,
        "jax_version": jax.__version__,
        "n_devices": len(jax.devices()),
        "platform": jax.default_backend(),
        "targets": {},
    }
    findings = []
    for target in hlo_targets(targets):
        try:
            compiled = compile_target(target)
            hlo_text = compiled.as_text()
        except Exception as e:  # noqa: BLE001 — any compile failure gates
            findings.append(_finding(
                "AF2A100", target.name,
                f"HLO compile failed: {type(e).__name__}: {e}"[:400],
            ))
            continue
        rec = hlo_record(target, compiled, hlo_text)
        doc["targets"][target.name] = rec
        findings.extend(
            f for f in audit_record(
                target.name, rec, per_op=parse_collectives(hlo_text)
            )
            if f.rule not in target.allow
        )
    return doc, findings


def _diff_record(name: str, base: dict, cur: dict) -> list:
    lines = []
    bcoll = base.get("collectives", {})
    ccoll = cur.get("collectives", {})
    for kind in sorted(set(bcoll) | set(ccoll)):
        b = bcoll.get(kind, {"count": 0, "bytes": 0})
        c = ccoll.get(kind, {"count": 0, "bytes": 0})
        if b["count"] != c["count"]:
            lines.append(
                f"{name}: {kind} count drift: {b['count']} -> "
                f"{c['count']} ({c['count'] - b['count']:+d})"
            )
        if b["bytes"] != c["bytes"]:
            lines.append(
                f"{name}: {kind} bytes drift: {b['bytes']} -> "
                f"{c['bytes']} ({c['bytes'] - b['bytes']:+d})"
            )
    for field in (
        "sharded", "num_partitions", "comm_bytes", "flops",
        "argument_bytes", "output_bytes", "temp_bytes",
    ):
        if base.get(field) != cur.get(field):
            lines.append(
                f"{name}: {field} drift: {base.get(field)} -> "
                f"{cur.get(field)}"
            )
    bpb, cpb = base.get("program_bytes"), cur.get("program_bytes")
    if bpb != cpb:
        ratio = f" ({cpb / bpb:.2f}x)" if bpb and cpb else ""
        lines.append(
            f"{name}: per-device program_bytes drift: {bpb} -> "
            f"{cpb}{ratio}"
        )
    bver = base.get("budget", {}).get("verdict")
    cver = cur.get("budget", {}).get("verdict")
    if bver != cver:
        lines.append(f"{name}: budget verdict drift: {bver} -> {cver}")
    return lines


def diff_hlo_contracts(baseline: dict, current: dict,
                       subset: bool = False) -> list:
    """Exact per-collective drift lines between two contract docs.
    ``subset=True`` restricts to targets present in ``current`` (a
    ``--targets`` run), so unaudited targets don't read as removed."""
    bt = baseline.get("targets", {})
    ct = current.get("targets", {})
    names = sorted(set(ct) if subset else set(bt) | set(ct))
    lines = []
    for name in names:
        if name not in bt:
            lines.append(
                f"{name}: NEW TARGET (not in baseline) — re-baseline with "
                "--update after review"
            )
        elif name not in ct:
            lines.append(
                f"{name}: missing from current audit (target removed or "
                "failed to compile)"
            )
        else:
            lines.extend(_diff_record(name, bt[name], ct[name]))
    return lines


def check_against(baseline_path: str, current: dict,
                  subset: bool = False) -> dict:
    """Gate a freshly computed doc against the committed baseline.
    Verdicts: ``missing-baseline`` / ``stale-baseline`` (jax version,
    device count or format changed — loud, not failing, exactly the
    graph-contract policy) / ``drift`` / ``pass``."""
    if not os.path.exists(baseline_path):
        return {
            "verdict": "missing-baseline",
            "reason": f"no baseline at {baseline_path}; run --update",
        }
    with open(baseline_path, encoding="utf-8") as fh:
        baseline = json.load(fh)
    for key in ("format", "jax_version", "n_devices", "platform"):
        if baseline.get(key) != current.get(key):
            return {
                "verdict": "stale-baseline",
                "reason": (
                    f"RECOMPILE KEY {key}: baseline "
                    f"{baseline.get(key)!r} vs current "
                    f"{current.get(key)!r}; re-baseline with --update"
                ),
            }
    drift = diff_hlo_contracts(baseline, current, subset=subset)
    return {
        "verdict": "drift" if drift else "pass",
        "drift": drift,
    }


# --------------------------------------------------------------------- CLI


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    mode = parser.add_mutually_exclusive_group(required=True)
    mode.add_argument(
        "--check", action="store_true",
        help="audit + diff against the committed baseline",
    )
    mode.add_argument(
        "--update", action="store_true",
        help="audit + rewrite the baseline (a reviewed re-baseline)",
    )
    parser.add_argument("--baseline", default=DEFAULT_BASELINE)
    parser.add_argument(
        "--targets", default=None,
        help="comma-separated target subset (default: all hlo=True)",
    )
    parser.add_argument(
        "--json", dest="json_path", default=None,
        help="write the full result (doc + check + findings) here",
    )
    args = parser.parse_args(argv)

    from alphafold2_tpu.analysis.targets import (
        default_targets,
        hlo_targets,
    )

    registry = default_targets()
    subset = None
    if args.targets:
        names = [s.strip() for s in args.targets.split(",") if s.strip()]
        known = {t.name for t in hlo_targets(registry)}
        unknown = set(names) - known
        if unknown:
            print(
                f"unknown hlo target(s): {sorted(unknown)}; "
                f"known: {sorted(known)}",
                file=sys.stderr,
            )
            return 2
        subset = [t for t in registry if t.name in names]

    doc, findings = audit_hlo(subset if subset is not None else registry)

    check = None
    if args.update:
        if not findings:
            with open(args.baseline, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, indent=2, sort_keys=True)
                fh.write("\n")
            print(
                f"hlo-contracts: wrote {args.baseline} "
                f"({len(doc['targets'])} targets, "
                f"n_devices={doc['n_devices']})"
            )
        else:
            # never pin a violating surface as the reviewed baseline
            print(
                "hlo-contracts: REFUSING to baseline a surface with "
                f"{len(findings)} structural finding(s)"
            )
    else:
        check = check_against(
            args.baseline, doc, subset=subset is not None
        )
        for line in check.get("drift", []):
            print(f"hlo-contract DRIFT: {line}")
        if check["verdict"] == "drift":
            findings.append(_finding(
                "AF2A107", "hlo_contracts",
                f"{len(check['drift'])} contract drift line(s) vs "
                f"{os.path.basename(args.baseline)}; intended? "
                "re-baseline with --update",
            ))
        elif check["verdict"] == "missing-baseline":
            findings.append(
                _finding("AF2A107", "hlo_gate", check["reason"])
            )
        elif check["verdict"] == "stale-baseline":
            print(f"hlo-contracts: STALE BASELINE — {check['reason']}")

    for f in findings:
        print(f.format())
    summary = {
        "gate": "hlo",
        "verdict": (
            check["verdict"] if check is not None
            else ("fail" if findings else "updated")
        ),
        "n_targets": len(doc["targets"]),
        "findings": [f.to_dict() for f in findings],
    }
    print(json.dumps(summary))
    if args.json_path:
        with open(args.json_path, "w", encoding="utf-8") as fh:
            json.dump(
                {"doc": doc, "check": check, "summary": summary},
                fh, indent=2, sort_keys=True,
            )
            fh.write("\n")

    if check is not None and check["verdict"] == "missing-baseline":
        return 2
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
