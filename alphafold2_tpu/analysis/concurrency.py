"""Static concurrency auditor: lock graph, guard contracts, lifecycles.

The serving stack is the most concurrent code in the repo — the async
frontend's dispatcher, the pipeline's three stage executors, observer and
submit hooks, the metrics snapshotter, the exposition server and the
liveness watchdog all share mutable state behind an ad-hoc set of locks —
and the next tentpole (multi-replica fleet serving) multiplies the thread
count. The AF2L010–012 lint rules catch three *local* anti-patterns; this
module proves the *global* properties a fleet needs, statically, the way
layer 4 proves sharding properties:

1. **Lock-order graph** (AF2C001) — every ``threading.Lock`` / ``RLock``
   / ``Condition`` / ``Semaphore`` attribute of every class becomes a
   node ``Class.attr``; acquiring B while A is held (``with`` nesting,
   ``acquire``/``release`` pairs, ``*_locked``-convention entry
   assumptions, and *transitively* through calls whose receiver type is
   statically known) adds edge A → B. Any cycle is a lock-order
   inversion: the finding prints every edge of the cycle with the
   acquisition path that witnesses it.
2. **Guarded-state inference** (AF2C002–004) — per class, which
   attributes are written under which lock. An attribute whose writes
   are majority-guarded by one lock gets a *guard contract*; further
   unguarded writes (AF2C002), writes under a different lock (AF2C003)
   and unlocked *iteration* of guarded containers (AF2C004 — single-key
   reads are GIL-atomic and exempt; iteration over a mutating dict/list
   is the multi-word hazard) are findings. ``__init__`` /
   ``__post_init__`` bodies are exempt, ``*_locked`` bodies count as
   held, and a private helper called *only* from held regions inherits
   the guard (the ``_remember``-under-``observe`` pattern).
3. **Thread/queue lifecycle** (AF2C005–008) — threads with neither a
   ``daemon=True`` flag nor a reachable ``join`` (AF2C005), unbounded
   ``queue.Queue()``/bare ``deque()`` attributes in threaded classes
   (AF2C006), ``Condition.wait`` outside a predicate loop (AF2C007),
   and observer/callback/sink collections invoked while a lock is held
   (AF2C008 — snapshot under the lock, call outside).

The committed ``concurrency_contracts.json`` pins the lock graph's named
edges and the per-class guard map; ``--check`` diffs exactly like
``graph_contracts.json`` (named deltas, ``stale-baseline`` escape on
format mismatch, re-baseline with ``--update``). The auditor folds into
the single static gate as ``jaxpr_audit --rules ...,concurrency``.

Seeded negative control: functions marked ``# af2: gated-defect[ENV]``
are skipped unless ``$ENV`` is set — ``AF2TPU_AUDIT_INVERT_LOCKS=1``
activates an inverted acquisition in ``serve/scheduler.py`` and the gate
must fail rc=1 naming the ``AsyncServeFrontend._lock`` ↔
``PipelineBatch._lock`` cycle, with no bench run and no thread spawned.

Suppress an intentional finding with ``# af2: noqa[AF2C00x]`` plus a
reason in the surrounding comment, mirroring ``analysis/lint.py``. Pure
stdlib AST — no jax import, runs before any install in CI.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Tuple

from alphafold2_tpu.analysis.lint import (
    Finding,
    _attr_chain,
    _noqa_lines,
    _self_attr,
    iter_python_files,
)

RULES = {
    "AF2C000": "concurrency scan failure (unparseable source) — never "
               "silent green",
    "AF2C001": "lock-order inversion: the whole-repo lock graph has a "
               "cycle (two threads taking the edges in opposite order "
               "deadlock)",
    "AF2C002": "write to a guard-contracted attribute with no lock held",
    "AF2C003": "mixed guards: attribute written under a different lock "
               "than its contract",
    "AF2C004": "unlocked iteration of a guard-contracted container "
               "(concurrent mutation tears the traversal)",
    "AF2C005": "thread created with neither daemon=True nor a reachable "
               "join (leaks past shutdown)",
    "AF2C006": "unbounded queue.Queue()/deque() attribute in a "
               "threaded class (producer can outrun every consumer)",
    "AF2C007": "Condition.wait outside a predicate loop (spurious "
               "wakeups and missed notifies)",
    "AF2C008": "observer/callback collection invoked while holding a "
               "lock (re-entrant or slow callbacks deadlock/stall the "
               "owner)",
    "AF2C009": "concurrency contract drift vs the committed baseline",
}

_SEVERITY = {
    "AF2C000": "error",
    "AF2C001": "error",
    "AF2C002": "error",
    "AF2C003": "error",
    "AF2C004": "warning",
    "AF2C005": "error",
    "AF2C006": "warning",
    "AF2C007": "error",
    "AF2C008": "error",
    "AF2C009": "error",
}

FORMAT_VERSION = 1
_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
DEFAULT_BASELINE = os.path.join(_REPO, "concurrency_contracts.json")

# functions carrying this marker (on the def line or the line above) hold
# seeded defects for the CI negative control: invisible to the audit and
# to contract computation unless the named env var is set truthy
_GATED_RE = re.compile(r"#\s*af2:\s*gated-defect\[([A-Z0-9_]+)\]")

_LOCK_FACTORIES = {
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
}
# lock kinds that re-enter safely: a self-edge (same lock taken while
# held) is only a deadlock for a plain Lock
_REENTRANT = {"RLock", "Condition", "Semaphore", "BoundedSemaphore"}

_MUTATING_METHODS = {
    "append", "extend", "insert", "remove", "pop", "popleft",
    "appendleft", "extendleft", "rotate", "clear", "update", "setdefault",
    "add", "discard", "popitem", "move_to_end",
}
# calls that traverse their container argument — the AF2C004 surface
_ITERATING_FUNCS = {
    "list", "tuple", "sorted", "set", "dict", "sum", "min", "max",
    "any", "all", "frozenset",
}
_ITERATING_METHODS = {"items", "keys", "values", "copy"}
_OBSERVER_ATTR_RE = re.compile(
    r"(observer|callback|hook|sink|listener|subscriber)s?$"
)


# --------------------------------------------------------------- collection


def _ann_name(node: Optional[ast.AST]) -> Optional[str]:
    """A class name out of an annotation: ``T``, ``mod.T``, ``"T"``,
    ``Optional[T]``. None for anything fancier."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.rsplit(".", 1)[-1] or None
    if isinstance(node, ast.Subscript):
        return _ann_name(node.slice)
    chain = _attr_chain(node)
    return chain[-1] if chain else None


def _call_class_name(node: ast.AST) -> Optional[str]:
    """``ClassName(...)`` / ``mod.ClassName(...)`` -> "ClassName"."""
    if not isinstance(node, ast.Call):
        return None
    chain = _attr_chain(node.func)
    return chain[-1] if chain else None


@dataclasses.dataclass
class ClassInfo:
    name: str
    path: str
    node: ast.ClassDef
    locks: Dict[str, str] = dataclasses.field(default_factory=dict)
    attr_types: Dict[str, str] = dataclasses.field(default_factory=dict)
    methods: Dict[str, ast.AST] = dataclasses.field(default_factory=dict)

    @property
    def sole_lock(self) -> Optional[str]:
        """The class's only lock attribute, if unambiguous — the
        ``*_locked`` convention's entry assumption."""
        if len(self.locks) == 1:
            return next(iter(self.locks))
        return None


def _collect_class(node: ast.ClassDef, path: str) -> ClassInfo:
    info = ClassInfo(name=node.name, path=path, node=node)
    for item in node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info.methods[item.name] = item
            params = {
                a.arg: _ann_name(a.annotation)
                for a in item.args.args + item.args.kwonlyargs
                if a.annotation is not None
            }
            for sub in ast.walk(item):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    attr = _self_attr(sub.targets[0])
                    if attr is None:
                        continue
                    chain = _attr_chain(sub.value)
                    if (
                        isinstance(sub.value, ast.Call)
                        and len(_attr_chain(sub.value.func)) >= 1
                        and _attr_chain(sub.value.func)[-1]
                        in _LOCK_FACTORIES
                        and _attr_chain(sub.value.func)[0]
                        in ("threading", "Lock", "RLock", "Condition",
                            "Semaphore", "BoundedSemaphore")
                    ):
                        info.locks[attr] = _attr_chain(sub.value.func)[-1]
                    elif (cls := _call_class_name(sub.value)) is not None:
                        info.attr_types.setdefault(attr, cls)
                    elif chain and len(chain) == 1 and chain[0] in params:
                        # self.batch = batch  (param annotated: PipelineBatch)
                        typ = params[chain[0]]
                        if typ:
                            info.attr_types.setdefault(attr, typ)
                elif isinstance(sub, ast.AnnAssign):
                    attr = _self_attr(sub.target)
                    if attr is None:
                        continue
                    typ = _ann_name(sub.annotation)
                    if typ and typ not in ("Optional", "dict", "list",
                                           "set", "tuple", "int", "float",
                                           "str", "bool"):
                        info.attr_types.setdefault(attr, typ)
        elif isinstance(item, ast.AnnAssign):
            attr = (
                item.target.id if isinstance(item.target, ast.Name) else None
            )
            typ = _ann_name(item.annotation)
            if attr and typ:
                info.attr_types.setdefault(attr, typ)
    return info


# ------------------------------------------------------- per-function scan


@dataclasses.dataclass
class FnScan:
    """Everything one pass extracts from one function/method body."""

    qual: str                       # "Class.method" or "module_fn"
    path: str
    node: ast.AST
    owner: Optional[ClassInfo]
    gated_env: Optional[str] = None
    # (labels held below, acquired label, own-attr if self lock, line)
    acquires: list = dataclasses.field(default_factory=list)
    # (held labels, own attrs held, (ClassName, method), line)
    calls: list = dataclasses.field(default_factory=list)
    # (attr, own lock attrs held, line, col)
    writes: list = dataclasses.field(default_factory=list)
    # (attr, own lock attrs held, line, col)
    iter_reads: list = dataclasses.field(default_factory=list)
    # (line, col, daemon_ok, self_attr or local name or None)
    threads: list = dataclasses.field(default_factory=list)
    joined: set = dataclasses.field(default_factory=set)  # names .join()ed
    # (attr, line, col, kind) unbounded queue/deque self attrs
    queues: list = dataclasses.field(default_factory=list)
    # (line, col, attr) Condition.wait outside a loop
    naked_waits: list = dataclasses.field(default_factory=list)
    # (line, col, attr, held label) observer collection called under lock
    observer_calls: list = dataclasses.field(default_factory=list)

    @property
    def entry_locked(self) -> bool:
        name = self.qual.rsplit(".", 1)[-1]
        return name.endswith("_locked")


class _FnVisitor(ast.NodeVisitor):
    """One pass over a function body with a held-lock stack."""

    def __init__(self, scan: FnScan, registry: Dict[str, ClassInfo]):
        self.scan = scan
        self.reg = registry
        self.owner = scan.owner
        # (label, own_attr or None, kind)
        self.held: List[Tuple[str, Optional[str], str]] = []
        self.loop_depth = 0
        self.types: Dict[str, str] = {}
        self._handled_calls: set = set()
        fn = scan.node
        for a in fn.args.args + fn.args.kwonlyargs:
            typ = _ann_name(a.annotation)
            if typ and typ in registry:
                self.types[a.arg] = typ

    # ----------------------------------------------------------- resolution

    def _held_labels(self) -> tuple:
        return tuple(label for label, _own, _k in self.held)

    def _held_own(self) -> frozenset:
        return frozenset(own for _l, own, _k in self.held if own)

    def _type_of(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name):
            return self.types.get(node.id)
        attr = _self_attr(node)
        if attr is not None and self.owner is not None:
            return self.owner.attr_types.get(attr)
        return None

    def _resolve_lock(self, node: ast.AST) -> Optional[tuple]:
        """A lock-valued expression -> (label, own_attr|None, kind)."""
        attr = _self_attr(node)
        if attr is not None and self.owner is not None:
            kind = self.owner.locks.get(attr)
            if kind:
                return f"{self.owner.name}.{attr}", attr, kind
            return None
        if isinstance(node, ast.Attribute):
            typ = self._type_of(node.value)
            if typ and typ in self.reg:
                kind = self.reg[typ].locks.get(node.attr)
                if kind:
                    return f"{typ}.{node.attr}", None, kind
        return None

    def _resolve_callee(self, func: ast.AST) -> Optional[tuple]:
        """``self.m`` / ``typed.m`` / ``self.attr.m`` -> (Class, m)."""
        if not isinstance(func, ast.Attribute):
            return None
        if (
            isinstance(func.value, ast.Name) and func.value.id == "self"
            and self.owner is not None
            and func.attr in self.owner.methods
        ):
            return self.owner.name, func.attr
        typ = self._type_of(func.value)
        if typ and typ in self.reg and func.attr in self.reg[typ].methods:
            return typ, func.attr
        return None

    def _push(self, lock: tuple, line: int) -> None:
        label, own, kind = lock
        self.scan.acquires.append(
            (self._held_labels(), label, own, kind, line)
        )
        self.held.append(lock)

    # ------------------------------------------------------------- visitors

    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            lock = self._resolve_lock(item.context_expr)
            if lock is not None:
                self._push(lock, item.context_expr.lineno)
                pushed += 1
            else:
                self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self.held.pop()

    visit_AsyncWith = visit_With

    def visit_While(self, node: ast.While) -> None:
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    def visit_For(self, node: ast.For) -> None:
        self._note_iteration(node.iter)
        self._note_observer_loop(node)
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_AsyncFor = visit_For

    def visit_comprehension(self, node: ast.comprehension) -> None:
        self._note_iteration(node.iter)
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.AST) -> None:
        # nested defs (thread targets, callbacks) run on another thread's
        # schedule — their bodies are scanned as their own functions by
        # the caller, not under this frame's held stack
        return

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            self._note_write_target(target)
            if isinstance(target, ast.Name):
                if isinstance(node.value, ast.Call):
                    cls = _call_class_name(node.value)
                    if cls and cls in self.reg:
                        self.types[target.id] = cls
                if self._is_thread_call(node.value):
                    self._note_thread(node.value, target.id)
                    self._handled_calls.add(id(node.value))
            attr = _self_attr(target)
            if attr is not None:
                if self._is_thread_call(node.value):
                    self._note_thread(node.value, attr)
                    self._handled_calls.add(id(node.value))
                self._note_queue(attr, node.value)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if isinstance(node.target, ast.Name):
            typ = _ann_name(node.annotation)
            if typ and typ in self.reg:
                self.types[node.target.id] = typ
        self._note_write_target(node.target)
        if node.value is not None:
            self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._note_write_target(node.target)
        self.visit(node.value)

    def visit_Delete(self, node: ast.Delete) -> None:
        for target in node.targets:
            self._note_write_target(target)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute):
            # lock acquire/release pairs (held to function end when the
            # release is on another path — conservative and correct for
            # edge extraction, which records at acquisition time)
            if func.attr == "acquire":
                lock = self._resolve_lock(func.value)
                if lock is not None:
                    self._push(lock, node.lineno)
                    self.generic_visit(node)
                    return
            elif func.attr == "release":
                lock = self._resolve_lock(func.value)
                if lock is not None:
                    for i in range(len(self.held) - 1, -1, -1):
                        if self.held[i][0] == lock[0]:
                            del self.held[i]
                            break
                    self.generic_visit(node)
                    return
            elif func.attr == "wait":
                lock = self._resolve_lock(func.value)
                if (
                    lock is not None and lock[2] == "Condition"
                    and self.loop_depth == 0
                ):
                    self.scan.naked_waits.append(
                        (node.lineno, node.col_offset, lock[0])
                    )
            elif func.attr == "join" and not node.args[:0]:
                attr = _self_attr(func.value)
                if attr is not None:
                    self.scan.joined.add(attr)
                elif isinstance(func.value, ast.Name):
                    self.scan.joined.add(func.value.id)
            # mutating method on a self attribute = a write
            if func.attr in _MUTATING_METHODS:
                attr = _self_attr(func.value)
                if attr is not None:
                    self.scan.writes.append((
                        attr, self._held_own(), node.lineno,
                        node.col_offset,
                    ))
            if func.attr in _ITERATING_METHODS:
                attr = _self_attr(func.value)
                if attr is not None:
                    self.scan.iter_reads.append((
                        attr, self._held_own(), node.lineno,
                        node.col_offset,
                    ))
        if isinstance(func, ast.Name) and func.id in _ITERATING_FUNCS:
            for arg in node.args:
                self._note_iteration(arg)
        if self._is_thread_call(node) and id(node) not in self._handled_calls:
            self._note_thread(node, None)
        callee = self._resolve_callee(func)
        if callee is not None:
            self.scan.calls.append((
                self._held_labels(), self._held_own(), callee, node.lineno,
            ))
        # observer collection invoked by subscript: self._cbs[0](...)
        if (
            isinstance(func, ast.Subscript)
            and (attr := _self_attr(func.value)) is not None
            and _OBSERVER_ATTR_RE.search(attr)
            and self.held
        ):
            self.scan.observer_calls.append(
                (node.lineno, node.col_offset, attr, self.held[-1][0])
            )
        self.generic_visit(node)

    # ---------------------------------------------------------------- notes

    def _note_write_target(self, target: ast.AST) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._note_write_target(elt)
            return
        attr = _self_attr(target)
        if attr is None and isinstance(target, ast.Subscript):
            attr = _self_attr(target.value)
        if attr is not None:
            self.scan.writes.append((
                attr, self._held_own(), target.lineno, target.col_offset,
            ))

    def _note_iteration(self, node: ast.AST) -> None:
        attr = _self_attr(node)
        if attr is None and isinstance(node, ast.Call):
            # self.X.items()/values()/keys()/copy() — already recorded by
            # visit_Call when it gets there; record here too is harmless
            # but double-counts, so leave it to visit_Call
            return
        if attr is not None:
            self.scan.iter_reads.append((
                attr, self._held_own(), node.lineno, node.col_offset,
            ))

    def _note_observer_loop(self, node: ast.For) -> None:
        attr = _self_attr(node.iter)
        if attr is None or not _OBSERVER_ATTR_RE.search(attr):
            return
        if not self.held and not self.scan.entry_locked:
            return
        if not isinstance(node.target, ast.Name):
            return
        var = node.target.id
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Name)
                and sub.func.id == var
            ):
                held = (
                    self.held[-1][0] if self.held
                    else f"{self.owner.name}.{self.owner.sole_lock}"
                    if self.owner and self.owner.sole_lock else "a lock"
                )
                self.scan.observer_calls.append(
                    (sub.lineno, sub.col_offset, attr, held)
                )
                return

    def _is_thread_call(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        chain = _attr_chain(node.func)
        return chain[-1:] == ["Thread"] and (
            len(chain) == 1 or chain[0] == "threading"
        )

    def _note_thread(self, node: ast.Call, bound_to: Optional[str]) -> None:
        daemon_ok = any(
            kw.arg == "daemon"
            and isinstance(kw.value, ast.Constant) and kw.value.value is True
            for kw in node.keywords
        )
        self.scan.threads.append(
            (node.lineno, node.col_offset, daemon_ok, bound_to)
        )

    def _note_queue(self, attr: str, value: ast.AST) -> None:
        if not isinstance(value, ast.Call):
            return
        chain = _attr_chain(value.func)
        if chain[-1:] == ["Queue"] and (
            len(chain) == 1 or chain[0] in ("queue", "multiprocessing")
        ):
            bounded = bool(value.args) or any(
                kw.arg == "maxsize" for kw in value.keywords
            )
            if not bounded:
                self.scan.queues.append(
                    (attr, value.lineno, value.col_offset, "queue.Queue")
                )
        elif chain[-1:] == ["deque"] and (
            len(chain) == 1 or chain[0] == "collections"
        ):
            bounded = len(value.args) >= 2 or any(
                kw.arg == "maxlen"
                and not (isinstance(kw.value, ast.Constant)
                         and kw.value.value is None)
                for kw in value.keywords
            )
            if not bounded:
                self.scan.queues.append(
                    (attr, value.lineno, value.col_offset, "deque")
                )


# ------------------------------------------------------------- repo model


class RepoModel:
    """The whole-repo concurrency model: classes, scans, graph, guards."""

    def __init__(self) -> None:
        self.classes: Dict[str, ClassInfo] = {}
        self.scans: List[FnScan] = []
        self.methods: Dict[Tuple[str, str], FnScan] = {}
        self.noqa: Dict[str, dict] = {}        # path -> {line: rules}
        self.parse_failures: List[Finding] = []
        # edge -> (provenance string, path, line); edge = (from, to)
        self.edges: Dict[Tuple[str, str], Tuple[str, str, int]] = {}
        self.lock_kinds: Dict[str, str] = {}   # label -> factory kind
        # class -> attr -> guard lock attr
        self.guards: Dict[str, Dict[str, str]] = {}
        self._entry_held: Dict[Tuple[str, str], frozenset] = {}

    # ----------------------------------------------------------- building

    def scan_paths(
        self, paths: Iterable[str], gated: str = "env"
    ) -> "RepoModel":
        """``gated`` controls ``# af2: gated-defect[ENV]`` functions:
        "env" includes one when $ENV is set truthy (the audit path),
        "none" always excludes (contract computation), "all" always
        includes (tests)."""
        trees = []
        for path in iter_python_files(paths):
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    source = fh.read()
                tree = ast.parse(source)
            except (OSError, SyntaxError) as e:
                self.parse_failures.append(Finding(
                    "AF2C000", _SEVERITY["AF2C000"], path,
                    getattr(e, "lineno", 0) or 0, 0,
                    f"cannot scan: {type(e).__name__}: {e}",
                ))
                continue
            self.noqa[path] = _noqa_lines(source)
            gated_lines = self._gated_lines(source)
            trees.append((path, tree, gated_lines))
            for node in ast.walk(tree):
                if isinstance(node, ast.ClassDef):
                    info = _collect_class(node, path)
                    self.classes[info.name] = info
                    for attr, kind in info.locks.items():
                        self.lock_kinds[f"{info.name}.{attr}"] = kind
        for path, tree, gated_lines in trees:
            self._scan_tree(path, tree, gated_lines, gated)
        self._infer_entry_held()
        self._build_edges()
        self._infer_guards()
        return self

    @staticmethod
    def _gated_lines(source: str) -> Dict[int, str]:
        out = {}
        for i, text in enumerate(source.splitlines(), start=1):
            m = _GATED_RE.search(text)
            if m:
                out[i] = m.group(1)
        return out

    def _gate_env_for(self, node: ast.AST, gated: Dict[int, str]):
        for line in range(node.lineno - 1, node.lineno + 2):
            if line in gated:
                return gated[line]
        return None

    def _scan_tree(
        self, path: str, tree: ast.Module, gated_lines: Dict[int, str],
        gated: str,
    ) -> None:
        def scan_fn(fn, owner: Optional[ClassInfo], qual: str) -> None:
            env = self._gate_env_for(fn, gated_lines)
            if env is not None and gated != "all":
                if gated == "none" or os.environ.get(env, "") in ("", "0"):
                    return
            scan = FnScan(
                qual=qual, path=path, node=fn, owner=owner, gated_env=env
            )
            visitor = _FnVisitor(scan, self.classes)
            for stmt in fn.body:
                visitor.visit(stmt)
            self.scans.append(scan)
            if owner is not None:
                self.methods[(owner.name, fn.name)] = scan

        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan_fn(node, None, node.name)
            elif isinstance(node, ast.ClassDef):
                owner = self.classes.get(node.name)
                for item in node.body:
                    if isinstance(
                        item, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        scan_fn(item, owner, f"{node.name}.{item.name}")

    # ------------------------------------------------- entry-held inference

    def _infer_entry_held(self) -> None:
        """Locks assumed held at entry: ``*_locked`` methods hold their
        class's sole lock; a private helper called ONLY with one own lock
        held inherits it (fixpoint over the in-class call graph)."""
        for scan in self.scans:
            if scan.owner is None:
                continue
            key = (scan.owner.name, scan.node.name)
            if scan.entry_locked and scan.owner.sole_lock:
                self._entry_held[key] = frozenset({scan.owner.sole_lock})
        for _ in range(4):  # bounded fixpoint (call chains are short)
            changed = False
            call_sites: Dict[Tuple[str, str], List[frozenset]] = {}
            for scan in self.scans:
                if scan.owner is None:
                    continue
                caller_key = (scan.owner.name, scan.node.name)
                extra = self._entry_held.get(caller_key, frozenset())
                for _held, own_held, callee, _line in scan.calls:
                    if callee[0] != scan.owner.name:
                        continue
                    call_sites.setdefault(callee, []).append(
                        own_held | extra
                    )
            for key, held_sets in call_sites.items():
                cls, meth = key
                if key in self._entry_held:
                    continue
                if not meth.startswith("_") or meth.startswith("__"):
                    continue
                common = frozenset.intersection(*held_sets)
                if len(common) == 1:
                    self._entry_held[key] = common
                    changed = True
            if not changed:
                break

    def entry_held_of(self, scan: FnScan) -> frozenset:
        if scan.owner is None:
            return frozenset()
        return self._entry_held.get(
            (scan.owner.name, scan.node.name), frozenset()
        )

    # ------------------------------------------------------------ the graph

    def _acq_closure(
        self, key: Tuple[str, str], memo: dict, stack: set
    ) -> set:
        """Every lock label a method may acquire, transitively through
        statically-resolved calls (cycle-guarded)."""
        if key in memo:
            return memo[key]
        if key in stack:
            return set()
        scan = self.methods.get(key)
        if scan is None:
            return set()
        stack.add(key)
        out = {label for _below, label, _own, _k, _l in scan.acquires}
        for _held, _own_held, callee, _line in scan.calls:
            out |= self._acq_closure(callee, memo, stack)
        stack.discard(key)
        memo[key] = out
        return out

    def _add_edge(self, src: str, dst: str, prov: str, path: str,
                  line: int) -> None:
        if (src, dst) not in self.edges:
            self.edges[(src, dst)] = (prov, path, line)

    def _prefix_labels(self, scan: FnScan) -> list:
        if scan.owner is None:
            return []
        return [
            f"{scan.owner.name}.{attr}"
            for attr in sorted(self.entry_held_of(scan))
        ]

    def _build_edges(self) -> None:
        memo: dict = {}
        self.self_deadlocks: List[Tuple[str, str, int, str]] = []
        for scan in self.scans:
            prefix = self._prefix_labels(scan)
            for below, label, _own, kind, line in scan.acquires:
                for h in prefix + list(below):
                    if h == label:
                        if kind == "Lock":
                            self.self_deadlocks.append(
                                (label, scan.path, line, scan.qual)
                            )
                        continue
                    self._add_edge(
                        h, label,
                        f"{os.path.relpath(scan.path, _REPO)}:{line} "
                        f"({scan.qual})",
                        scan.path, line,
                    )
            for held, _own_held, callee, line in scan.calls:
                acquired = self._acq_closure(callee, memo, set())
                for h in prefix + list(held):
                    for label in acquired:
                        if h == label:
                            if self.lock_kinds.get(label) == "Lock":
                                self.self_deadlocks.append(
                                    (label, scan.path, line, scan.qual)
                                )
                            continue
                        self._add_edge(
                            h, label,
                            f"{os.path.relpath(scan.path, _REPO)}:{line} "
                            f"({scan.qual} -> {callee[0]}.{callee[1]})",
                            scan.path, line,
                        )

    def cycles(self) -> List[List[Tuple[str, str]]]:
        """Elementary cycles in the lock graph (SCC-based; each SCC with
        a cycle yields one representative edge list)."""
        adj: Dict[str, set] = {}
        for (src, dst) in self.edges:
            adj.setdefault(src, set()).add(dst)
            adj.setdefault(dst, set())
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: set = set()
        stack: List[str] = []
        sccs: List[set] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            # iterative Tarjan: the lock graph is small but recursion
            # limits are not worth risking in a CI gate
            work = [(v, iter(sorted(adj[v])))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(sorted(adj[w]))))
                        advanced = True
                        break
                    if w in on_stack:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = set()
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.add(w)
                        if w == node:
                            break
                    sccs.append(scc)

        for v in sorted(adj):
            if v not in index:
                strongconnect(v)
        out = []
        for scc in sccs:
            cyclic = len(scc) > 1
            if not cyclic:
                continue
            edges = sorted(
                (s, d) for (s, d) in self.edges
                if s in scc and d in scc
            )
            out.append(edges)
        return out

    # --------------------------------------------------------------- guards

    def _infer_guards(self) -> None:
        """Majority-guard contracts per class attribute. ``__init__`` /
        ``__post_init__`` writes never count; ``*_locked`` (and inferred
        held-only helper) writes count as locked."""
        tallies: Dict[str, Dict[str, dict]] = {}
        for scan in self.scans:
            if scan.owner is None:
                continue
            name = scan.node.name
            if name in ("__init__", "__post_init__"):
                continue
            entry = self.entry_held_of(scan)
            cls = scan.owner.name
            for attr, own_held, _line, _col in scan.writes:
                held = own_held | entry
                slot = tallies.setdefault(cls, {}).setdefault(
                    attr, {"locked": {}, "unlocked": 0}
                )
                if held:
                    lock = sorted(held)[0]
                    slot["locked"][lock] = slot["locked"].get(lock, 0) + 1
                else:
                    slot["unlocked"] += 1
        for cls, attrs in tallies.items():
            for attr, slot in attrs.items():
                if attr in self.classes[cls].locks:
                    continue  # the lock itself is not guarded state
                locked_total = sum(slot["locked"].values())
                if not locked_total or locked_total < slot["unlocked"]:
                    continue
                lock, count = max(
                    slot["locked"].items(), key=lambda kv: (kv[1], kv[0])
                )
                if count * 2 >= locked_total:
                    self.guards.setdefault(cls, {})[attr] = lock

    # ------------------------------------------------------------- findings

    def _suppressed(self, path: str, line: int, rule: str) -> bool:
        rules = self.noqa.get(path, {}).get(line)
        if rules is None:
            return False
        return not rules or rule in rules

    def _finding(self, rule: str, path: str, line: int, col: int,
                 message: str, out: list) -> None:
        if not self._suppressed(path, line, rule):
            out.append(Finding(rule, _SEVERITY[rule], path, line, col,
                               message))

    def findings(self) -> List[Finding]:
        out: List[Finding] = list(self.parse_failures)
        # AF2C001 — lock-order cycles, each edge with its witness path
        for cycle_edges in self.cycles():
            witness = "; ".join(
                f"{src} -> {dst} (acquired at {self.edges[(src, dst)][0]})"
                for src, dst in cycle_edges
            )
            _prov, path, line = self.edges[cycle_edges[0]]
            nodes = sorted({n for e in cycle_edges for n in e})
            self._finding(
                "AF2C001", path, line, 0,
                f"lock-order inversion between {', '.join(nodes)}: "
                f"{witness} — two threads taking these edges in opposite "
                "order deadlock",
                out,
            )
        for label, path, line, qual in getattr(self, "self_deadlocks", []):
            self._finding(
                "AF2C001", path, line, 0,
                f"{label} (a non-reentrant Lock) acquired in {qual} while "
                "already held — self-deadlock",
                out,
            )
        # AF2C002/003 — guard-contract violations on writes
        for scan in self.scans:
            if scan.owner is None:
                continue
            name = scan.node.name
            if name in ("__init__", "__post_init__") or scan.entry_locked:
                continue
            cls = scan.owner.name
            contracts = self.guards.get(cls, {})
            entry = self.entry_held_of(scan)
            for attr, own_held, line, col in scan.writes:
                lock = contracts.get(attr)
                if lock is None:
                    continue
                held = own_held | entry
                if not held:
                    self._finding(
                        "AF2C002", scan.path, line, col,
                        f"{cls}.{attr} is guarded by {cls}.{lock} "
                        f"(majority of writes) but written here with no "
                        "lock held",
                        out,
                    )
                elif lock not in held:
                    self._finding(
                        "AF2C003", scan.path, line, col,
                        f"{cls}.{attr} is guarded by {cls}.{lock} but "
                        f"written under {', '.join(sorted(held))} — mixed "
                        "guards protect nothing",
                        out,
                    )
            # AF2C004 — unlocked iteration of guarded containers
            for attr, own_held, line, col in scan.iter_reads:
                lock = contracts.get(attr)
                if lock is None:
                    continue
                held = own_held | entry
                if lock not in held:
                    self._finding(
                        "AF2C004", scan.path, line, col,
                        f"iterating {cls}.{attr} (guarded by {cls}.{lock}) "
                        "without the lock — concurrent mutation tears the "
                        "traversal; snapshot under the lock first",
                        out,
                    )
        # AF2C005-008 — lifecycle rules
        for scan in self.scans:
            cls_joined: set = set()
            if scan.owner is not None:
                for m in self.scans:
                    if m.owner is scan.owner:
                        cls_joined |= m.joined
            for line, col, daemon_ok, bound in scan.threads:
                if daemon_ok:
                    continue
                joined = (
                    bound is not None
                    and (bound in scan.joined or bound in cls_joined)
                )
                if not joined:
                    self._finding(
                        "AF2C005", scan.path, line, col,
                        "thread created with neither daemon=True nor a "
                        "reachable join"
                        + (f" of {bound!r}" if bound else "")
                        + " — it outlives shutdown",
                        out,
                    )
            if scan.owner is not None and (
                scan.owner.locks
                or any(m.threads for m in self.scans
                       if m.owner is scan.owner)
            ):
                for attr, line, col, kind in scan.queues:
                    self._finding(
                        "AF2C006", scan.path, line, col,
                        f"{scan.owner.name}.{attr} is an unbounded {kind} "
                        "in a threaded class — a producer can outrun every "
                        "consumer; set maxsize/maxlen",
                        out,
                    )
            for line, col, label in scan.naked_waits:
                self._finding(
                    "AF2C007", scan.path, line, col,
                    f"{label}.wait() outside a predicate loop — spurious "
                    "wakeups and missed notifies slip through; use "
                    "`while not pred: cv.wait()` or wait_for",
                    out,
                )
            for line, col, attr, held in scan.observer_calls:
                self._finding(
                    "AF2C008", scan.path, line, col,
                    f"callbacks in self.{attr} invoked while holding "
                    f"{held} — a slow or re-entrant callback stalls or "
                    "deadlocks the owner; snapshot under the lock, call "
                    "outside",
                    out,
                )
        return sorted(out, key=lambda f: (f.path, f.line, f.col, f.rule))


# -------------------------------------------------------------- contracts


def default_paths() -> list:
    return [
        os.path.join(_REPO, "alphafold2_tpu"),
        os.path.join(_REPO, "scripts"),
        os.path.join(_REPO, "bench.py"),
    ]


def build_model(
    paths: Optional[Iterable[str]] = None, gated: str = "env"
) -> RepoModel:
    return RepoModel().scan_paths(
        paths if paths is not None else default_paths(), gated=gated
    )


def compute_contracts(
    model: Optional[RepoModel] = None,
    paths: Optional[Iterable[str]] = None,
) -> dict:
    """The committed shape: named lock-graph edges (with the witness
    acquisition site) + per-class guard map. Gated defects are never part
    of a contract regardless of environment (baseline stability)."""
    if model is None or any(s.gated_env for s in model.scans):
        model = build_model(paths, gated="none")
    return {
        "format": FORMAT_VERSION,
        "lock_graph": {
            f"{src} -> {dst}": prov
            for (src, dst), (prov, _p, _l) in sorted(model.edges.items())
        },
        "guards": {
            cls: dict(sorted(attrs.items()))
            for cls, attrs in sorted(model.guards.items())
        },
    }


def diff_contracts(baseline: dict, current: dict) -> List[str]:
    lines: List[str] = []
    old_edges = baseline.get("lock_graph", {})
    new_edges = current.get("lock_graph", {})
    for edge in sorted(set(new_edges) - set(old_edges)):
        lines.append(f"lock-graph edge added: {edge} ({new_edges[edge]})")
    for edge in sorted(set(old_edges) - set(new_edges)):
        lines.append(f"lock-graph edge removed: {edge}")
    old_guards = baseline.get("guards", {})
    new_guards = current.get("guards", {})
    for cls in sorted(set(new_guards) | set(old_guards)):
        o = old_guards.get(cls, {})
        n = new_guards.get(cls, {})
        for attr in sorted(set(n) - set(o)):
            lines.append(f"guard added: {cls}.{attr} -> {cls}.{n[attr]}")
        for attr in sorted(set(o) - set(n)):
            lines.append(
                f"guard removed: {cls}.{attr} (was {cls}.{o[attr]})"
            )
        for attr in sorted(set(o) & set(n)):
            if o[attr] != n[attr]:
                lines.append(
                    f"guard changed: {cls}.{attr}: {cls}.{o[attr]} -> "
                    f"{cls}.{n[attr]}"
                )
    return lines


def check_against(
    baseline_path: str, current: dict
) -> Tuple[str, List[str]]:
    """-> (verdict, detail lines); verdict in pass | drift |
    stale-baseline | missing-baseline, mirroring graph/hlo contracts."""
    if not os.path.exists(baseline_path):
        return "missing-baseline", [
            f"no baseline at {baseline_path}; record one with --update"
        ]
    with open(baseline_path) as fh:
        baseline = json.load(fh)
    if baseline.get("format") != current.get("format"):
        return "stale-baseline", [
            f"baseline format {baseline.get('format')} != current "
            f"{current.get('format')}; re-record with --update"
        ]
    lines = diff_contracts(baseline, current)
    return ("drift", lines) if lines else ("pass", [])


def write_contracts(path: str, contracts: dict) -> None:
    with open(path, "w") as fh:
        json.dump(contracts, fh, indent=2, sort_keys=True)
        fh.write("\n")


# --------------------------------------------------------------------- CLI


def findings_to_json(findings: List[Finding]) -> str:
    return json.dumps(
        {
            "tool": "af2_concurrency",
            "findings": [f.to_dict() for f in findings],
            "counts": {
                sev: sum(1 for f in findings if f.severity == sev)
                for sev in ("error", "warning")
            },
        },
        indent=2,
    )


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m alphafold2_tpu.analysis.concurrency",
        description=__doc__.splitlines()[0],
    )
    parser.add_argument("paths", nargs="*", help="files/dirs to audit "
                        "(default: alphafold2_tpu/, scripts/, bench.py)")
    parser.add_argument("--select", help="comma-separated rule ids")
    parser.add_argument("--severity", choices=("error", "warning"))
    parser.add_argument("--json", action="store_true")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--graph", action="store_true",
                        help="print the lock-order graph and exit")
    parser.add_argument("--check", action="store_true",
                        help="diff contracts vs the committed baseline")
    parser.add_argument("--update", action="store_true",
                        help="re-record the baseline")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE)
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in sorted(RULES):
            print(f"{rule} [{_SEVERITY[rule]}] {RULES[rule]}")
        return 0

    paths = args.paths or default_paths()
    model = build_model(paths)

    if args.graph:
        for (src, dst), (prov, _p, _l) in sorted(model.edges.items()):
            print(f"{src} -> {dst}    [{prov}]")
        print(f"{len(model.edges)} edges, "
              f"{len(model.lock_kinds)} lock attributes, "
              f"{sum(len(v) for v in model.guards.values())} guard "
              "contracts")
        return 0

    if args.update:
        contracts = compute_contracts(model, paths)
        verdict, lines = check_against(args.baseline, contracts)
        write_contracts(args.baseline, contracts)
        print(f"concurrency contracts written to {args.baseline} "
              f"({len(contracts['lock_graph'])} edges, "
              f"{sum(len(v) for v in contracts['guards'].values())} "
              "guards)")
        for line in lines:
            print(f"  {line}")
        return 0

    findings = model.findings()
    if args.select:
        wanted = {s.strip().upper() for s in args.select.split(",")}
        findings = [f for f in findings if f.rule in wanted]
    if args.severity:
        findings = [f for f in findings if f.severity == args.severity]

    rc = 0
    if args.json:
        print(findings_to_json(findings))
    else:
        for f in findings:
            print(f.format())
    if findings:
        rc = 1

    if args.check:
        contracts = compute_contracts(model, paths)
        verdict, lines = check_against(args.baseline, contracts)
        print(f"concurrency-contract verdict: {verdict}")
        for line in lines:
            print(f"  concurrency-contract {verdict.upper()}: {line}")
        if verdict == "drift":
            print("  (intentional change? re-record with --update and "
                  "put the diff above in the PR)")
            rc = 1
        elif verdict == "missing-baseline":
            rc = 2
    if not findings and not args.json and not args.check:
        print("concurrency audit clean "
              f"({len(model.edges)} lock-graph edges, "
              f"{sum(len(v) for v in model.guards.values())} guard "
              "contracts)")
    return rc


if __name__ == "__main__":
    sys.exit(main())
