"""Knob-registry auditor: every ``AF2TPU_*`` env read, cross-checked.

The repo has grown ~130 ``AF2TPU_*`` environment knobs (serve sizing,
bench drivers, session orchestration, kernel/precision switches) plus
the ``ServeConfig``/``TrainConfig``/... dataclass fields they mostly
mirror. A knob nobody documents is a knob nobody can operate, and a
documented knob nobody reads is a lie in the README — both have bitten
real deployments. This auditor enumerates, cross-checks, and gates:

- **AF2K001** (error) — a knob read in code that the README never
  mentions. Undocumented knobs can't be operated.
- **AF2K002** (error) — a knob documented in the README that no code
  (including tests) ever reads. Dead documentation misleads operators.
- **AF2K003** (warning) — a ``*Config`` dataclass field whose name is
  never referenced outside ``config.py``: a dead knob in the config
  surface.
- **AF2K004** (warning) — a ``*Config`` field with no ``#`` comment
  (trailing on its line, or a block comment directly above — the
  config.py idiom) and no README mention: undocumented config.

Enumeration is exact-match AST: any string constant fully matching
``AF2TPU_[A-Z0-9_]+`` in ``alphafold2_tpu/``, ``scripts/``, ``bench.py``
(README prose never matches because docstrings embed knob names inside
longer sentences, and comments are invisible to the AST). A literal with
a trailing underscore (``"AF2TPU_SERVE_"``) is a *prefix wildcard*: it
legitimizes every README name sharing the prefix, and any README name
matched by some code prefix is not dead. Reads in ``tests/`` count for
liveness (AF2K002) but are not themselves required to be documented.

``--markdown`` emits the README "Knob registry" tables so the committed
docs are generated, not hand-tracked. Pure stdlib; folds into
``jaxpr_audit --rules ...,concurrency`` beside the concurrency rules.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Tuple

from alphafold2_tpu.analysis.lint import Finding, iter_python_files

RULES = {
    "AF2K001": "env knob read in code but undocumented in README",
    "AF2K002": "env knob documented in README but never read anywhere",
    "AF2K003": "config dataclass field never referenced outside config.py",
    "AF2K004": "config field with no comment (trailing or block-above) "
               "and no README mention",
}

_SEVERITY = {
    "AF2K001": "error",
    "AF2K002": "error",
    "AF2K003": "warning",
    "AF2K004": "warning",
}

_KNOB_RE = re.compile(r"AF2TPU_[A-Z0-9_]+_?")
_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def default_code_paths() -> list:
    return [
        os.path.join(_REPO, "alphafold2_tpu"),
        os.path.join(_REPO, "scripts"),
        os.path.join(_REPO, "bench.py"),
    ]


def default_liveness_paths() -> list:
    # tests read knobs too (AF2TPU_HEAVY gates the 768-crop grid test);
    # that keeps a README knob alive but carries no documentation duty
    return default_code_paths() + [os.path.join(_REPO, "tests")]


def collect_env_reads(paths: Iterable[str]) -> Dict[str, List[str]]:
    """knob name -> sorted read sites ("relpath:line"). Names ending in
    ``_`` are prefix wildcards used to build families dynamically."""
    out: Dict[str, List[str]] = {}
    for path in iter_python_files(paths):
        if os.path.abspath(path) == os.path.abspath(__file__):
            continue  # _GROUPS labels are classifications, not reads
        try:
            tree = ast.parse(open(path, encoding="utf-8").read())
        except (OSError, SyntaxError):
            continue
        rel = os.path.relpath(path, _REPO)
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and _KNOB_RE.fullmatch(node.value)
            ):
                out.setdefault(node.value, []).append(
                    f"{rel}:{node.lineno}"
                )
    return {k: sorted(set(v)) for k, v in out.items()}


def collect_documented(readme_path: Optional[str] = None) -> set:
    path = readme_path or os.path.join(_REPO, "README.md")
    try:
        text = open(path, encoding="utf-8").read()
    except OSError:
        return set()
    return set(re.findall(r"AF2TPU_[A-Z0-9_]+", text))


def collect_config_fields(
    config_path: Optional[str] = None,
) -> List[Tuple[str, str, int, bool]]:
    """-> [(ClassName, field, line, has_trailing_comment)] for every
    ``*Config`` dataclass field in config.py."""
    path = config_path or os.path.join(_REPO, "alphafold2_tpu", "config.py")
    source = open(path, encoding="utf-8").read()
    lines = source.splitlines()
    tree = ast.parse(source)
    out = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.ClassDef)
                and node.name.endswith("Config")):
            continue
        for item in node.body:
            if isinstance(item, ast.AnnAssign) and isinstance(
                item.target, ast.Name
            ):
                end = item.end_lineno or item.lineno
                commented = "#" in lines[end - 1] or (
                    item.lineno >= 2
                    and lines[item.lineno - 2].lstrip().startswith("#")
                )
                out.append(
                    (node.name, item.target.id, item.lineno, commented)
                )
    return out


def collect_referenced_names(
    paths: Iterable[str], exclude: str
) -> set:
    """Every attribute-access and keyword-argument name outside
    ``exclude`` — the (loose) liveness universe for config fields,
    collected in ONE pass so the per-field check is set membership."""
    names: set = set()
    for path in iter_python_files(paths):
        if os.path.abspath(path) == os.path.abspath(exclude):
            continue
        try:
            tree = ast.parse(open(path, encoding="utf-8").read())
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute):
                names.add(node.attr)
            elif isinstance(node, ast.keyword) and node.arg:
                names.add(node.arg)
    return names


def audit(
    code_paths: Optional[Iterable[str]] = None,
    liveness_paths: Optional[Iterable[str]] = None,
    readme_path: Optional[str] = None,
    config_path: Optional[str] = None,
) -> List[Finding]:
    code_paths = list(code_paths or default_code_paths())
    liveness_paths = list(liveness_paths or default_liveness_paths())
    config_path = config_path or os.path.join(
        _REPO, "alphafold2_tpu", "config.py"
    )
    reads = collect_env_reads(code_paths)
    live_reads = collect_env_reads(liveness_paths)
    documented = collect_documented(readme_path)
    prefixes = {k for k in live_reads if k.endswith("_")}
    findings: List[Finding] = []

    # AF2K001 — read but undocumented (prefix literals document their
    # whole family: the README must mention the prefix itself)
    for name, sites in sorted(reads.items()):
        key = name  # prefix literals must appear verbatim in README too
        if key not in documented:
            path, _, line = sites[0].rpartition(":")
            findings.append(Finding(
                "AF2K001", _SEVERITY["AF2K001"],
                os.path.join(_REPO, path), int(line), 0,
                f"env knob {name} is read here but the README never "
                "mentions it — add it to the Knob registry "
                "(README.md, regenerate with `python -m "
                "alphafold2_tpu.analysis.knobs --markdown`)",
            ))

    # AF2K002 — documented but never read (a code prefix literal keeps
    # its README family alive)
    readme_file = readme_path or os.path.join(_REPO, "README.md")
    for name in sorted(documented):
        if name in live_reads or name + "_" in prefixes:
            continue
        if any(name.startswith(p) for p in prefixes):
            continue
        findings.append(Finding(
            "AF2K002", _SEVERITY["AF2K002"], readme_file, 0, 0,
            f"README documents env knob {name} but no code (incl. "
            "tests) ever reads it — dead documentation",
        ))

    # AF2K003/004 — config-field surface
    referenced = collect_referenced_names(liveness_paths, config_path)
    for cls, field, line, commented in collect_config_fields(config_path):
        if field not in referenced:
            findings.append(Finding(
                "AF2K003", _SEVERITY["AF2K003"], config_path, line, 0,
                f"{cls}.{field} is never referenced outside config.py — "
                "a dead knob in the config surface",
            ))
        if not commented and field not in documented:
            findings.append(Finding(
                "AF2K004", _SEVERITY["AF2K004"], config_path, line, 0,
                f"{cls}.{field} has no `#` comment (trailing or "
                "block-above) and no README mention — undocumented "
                "config",
            ))
    return findings


# ---------------------------------------------------------------- markdown


_GROUPS = [
    ("AF2TPU_SERVE_ASYNC_", "serve-async bench sizing"),
    ("AF2TPU_SERVE_FLEET_", "fleet serving driver"),
    ("AF2TPU_SERVE_REPLAY_", "workload capture/replay driver"),
    ("AF2TPU_SERVE_SCAN_", "variant-scan bench driver"),
    ("AF2TPU_SERVE_", "serve bench sizing"),
    ("AF2TPU_FLEET_", "fleet frontend"),
    ("AF2TPU_KERNELS_BENCH_", "kernel microbench"),
    ("AF2TPU_KERNELS", "kernel backend selection"),
    ("AF2TPU_BENCH_", "bench harness"),
    ("AF2TPU_SESSION_", "TPU session orchestration"),
    ("AF2TPU_TRAIN_REAL_", "real-data training session"),
    ("AF2TPU_", "core / misc"),
]


def markdown_registry(reads: Optional[Dict[str, List[str]]] = None) -> str:
    """The README "Knob registry" tables, grouped by family."""
    reads = reads if reads is not None else collect_env_reads(
        default_code_paths()
    )
    grouped: Dict[str, list] = {title: [] for _p, title in _GROUPS}
    for name in sorted(reads):
        for prefix, title in _GROUPS:
            if name.startswith(prefix):
                grouped[title].append(name)
                break
    lines: List[str] = []
    for _prefix, title in _GROUPS:
        names = grouped[title]
        if not names:
            continue
        lines.append(f"**{title}:**")
        lines.append("")
        lines.append("| knob | read at |")
        lines.append("|---|---|")
        for name in names:
            sites = reads[name]
            shown = ", ".join(f"`{s}`" for s in sites[:2])
            if len(sites) > 2:
                shown += f" (+{len(sites) - 2})"
            lines.append(f"| `{name}` | {shown} |")
        lines.append("")
    return "\n".join(lines)


# --------------------------------------------------------------------- CLI


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m alphafold2_tpu.analysis.knobs",
        description=__doc__.splitlines()[0],
    )
    parser.add_argument("--json", action="store_true")
    parser.add_argument("--markdown", action="store_true",
                        help="emit the README Knob registry tables")
    parser.add_argument("--select", help="comma-separated rule ids")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in sorted(RULES):
            print(f"{rule} [{_SEVERITY[rule]}] {RULES[rule]}")
        return 0
    if args.markdown:
        print(markdown_registry())
        return 0

    findings = audit()
    if args.select:
        wanted = {s.strip().upper() for s in args.select.split(",")}
        findings = [f for f in findings if f.rule in wanted]
    if args.json:
        print(json.dumps(
            {
                "tool": "af2_knobs",
                "findings": [f.to_dict() for f in findings],
                "counts": {
                    sev: sum(1 for f in findings if f.severity == sev)
                    for sev in ("error", "warning")
                },
            },
            indent=2,
        ))
    else:
        for f in findings:
            print(f.format())
        if not findings:
            reads = collect_env_reads(default_code_paths())
            print(f"knob audit clean ({len(reads)} env knobs, all "
                  "documented and live)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
