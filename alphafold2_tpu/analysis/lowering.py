"""Mosaic TPU lowering gate — the auditor's pre-hardware rule set.

Formerly the whole of ``scripts/check_tpu_lowering.py`` (that script is now
a thin shim over this module, and ``python -m
alphafold2_tpu.analysis.jaxpr_audit --rules lowering`` folds these cases
into the same findings stream as the jaxpr rules — one lowering-gate entry
point).

Round 4's only compiled-mode Pallas attempt on a real chip died in Mosaic's
``_check_block_mappings`` — an error class interpret-mode tests can never
surface, because interpret mode skips the Mosaic lowering entirely
(VERDICT r4 weak #3). This gate runs the FULL Mosaic lowering pipeline on a
CPU-only host via JAX's cross-platform AOT path::

    jax.jit(f).trace(*args).lower(lowering_platforms=("tpu",))

which executes ``jax._src.pallas.mosaic.lowering.lower_jaxpr_to_module`` —
block-mapping tiling checks, scratch allocation, op lowering, the works —
without any TPU backend. Every kernel entry point is lowered at the exact
shapes ``scripts/tpu_session.py stage_pallas`` runs on hardware, plus the
stock flash-attention kernel at the shapes ``ops/flash.py`` feeds it from
the axial/cross attention paths.

A NEGATIVE CONTROL lowers a deliberately mis-tiled kernel (the round-4
(1, block) row-stat bug class) and requires the gate to reject it — proving
the gate actually detects what it claims to.

IMPORTANT: this module imports jax at import time. In an axon-hooked
environment the cross-platform trace hangs, so run it through the shim
(``python scripts/check_tpu_lowering.py``, which scrubs and re-execs
before any jax import) or in a subprocess built with
``preflight.scrub_axon_env()`` — exactly what ``jaxpr_audit
--rules lowering`` does. Running this module directly as ``__main__``
re-execs itself through a scrubbed environment as a last line of defense.

Prints one JSON line per case; exit 0 iff every positive case lowers AND
the negative control is rejected.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp


def lower_for_tpu(fn, *args) -> None:
    """Run the full Mosaic TPU lowering of ``fn(*args)`` on this (CPU)
    host; raises exactly what a real-chip compile's lowering phase would."""
    jax.jit(fn).trace(*args).lower(lowering_platforms=("tpu",))


def _sparse_inputs(n: int, block_size: int):
    """The exact configuration stage_pallas measures on hardware
    (scripts/tpu_session.py): 4 heads, head dim 64, 17 padded tail keys."""
    from alphafold2_tpu.ops.sparse import BlockSparseConfig

    cfg = BlockSparseConfig(
        block_size=block_size, num_local_blocks=4, num_global_blocks=1,
        num_random_blocks=None,
    )
    layout = cfg.layout(n)
    k1, k2, k3 = jax.random.split(jax.random.key(0), 3)
    shape = (1, 4, n, 64)
    q = jax.random.normal(k1, shape, jnp.float32)
    k = jax.random.normal(k2, shape, jnp.float32)
    v = jax.random.normal(k3, shape, jnp.float32)
    mask = jnp.ones((1, n), bool).at[:, -17:].set(False)
    return q, k, v, layout, mask


def case_block_sparse_fwd(n=512, block_size=128, with_lse=True):
    from alphafold2_tpu.ops.pallas.block_sparse import (
        pallas_block_sparse_attention,
    )

    q, k, v, layout, mask = _sparse_inputs(n, block_size)

    def f(q, k, v):
        return pallas_block_sparse_attention(
            q, k, v, layout, block_size, mask=mask, interpret=False,
            return_lse=with_lse,
        )

    lower_for_tpu(f, q, k, v)


def case_block_sparse_bwd(n=512, block_size=128):
    from alphafold2_tpu.ops.pallas.block_sparse import (
        pallas_block_sparse_attention,
        pallas_block_sparse_attention_bwd,
    )

    q, k, v, layout, mask = _sparse_inputs(n, block_size)

    def f(q, k, v):
        out, lse = pallas_block_sparse_attention(
            q, k, v, layout, block_size, mask=mask, interpret=False,
            return_lse=True,
        )
        return pallas_block_sparse_attention_bwd(
            q, k, v, out, lse, jnp.ones_like(out), layout, block_size,
            mask=mask, interpret=False,
        )

    lower_for_tpu(f, q, k, v)


def case_block_sparse_custom_vjp(n=512, block_size=128):
    """The composed custom_vjp wrapper the model actually calls — grads
    through it exercise fwd+dq+dkv inside one traced program."""
    from alphafold2_tpu.ops import pallas as _p  # noqa: F401
    import alphafold2_tpu.ops.sparse as sparse

    q, k, v, layout, mask = _sparse_inputs(n, block_size)

    def loss(q, k, v):
        o = sparse.block_sparse_attention_pallas(
            q, k, v, layout, block_size, mask=mask, interpret=False,
        )
        return jnp.sum(o * o)

    lower_for_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)


def _stock_flash(q_shape, kv_shape):
    """The stock jax flash kernel at the (pre-padded, segment-id-masked)
    shapes ops/flash.py produces for the axial and compressed-cross paths."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        SegmentIds,
        flash_attention as _fa,
    )

    b, h, nq, d = q_shape
    nk = kv_shape[2]
    q = jnp.ones(q_shape, jnp.float32)
    k = jnp.ones(kv_shape, jnp.float32)
    v = jnp.ones(kv_shape, jnp.float32)
    qs = jnp.ones((b, nq), jnp.int32)
    ks = jnp.ones((b, nk), jnp.int32)

    def f(q, k, v):
        return _fa(
            q, k, v, segment_ids=SegmentIds(q=qs, kv=ks), sm_scale=0.125
        )

    lower_for_tpu(f, q, k, v)


def case_flash_axial_256():
    # axial row/col pass at crop 256: (B*N, H, N, D) with B*N folded small
    _stock_flash((4, 8, 256, 64), (4, 8, 256, 64))


def case_flash_compressed_cross():
    # pair-stream queries (crop 64 -> 4096 tokens) against a 128-padded
    # compressed MSA context — the ops/flash.py wrapper's padded geometry
    _stock_flash((1, 8, 4096, 64), (1, 8, 128, 64))


def case_flash_bwd_256():
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        SegmentIds,
        flash_attention as _fa,
    )

    shape = (2, 8, 256, 64)
    q = jnp.ones(shape, jnp.float32)
    k = jnp.ones(shape, jnp.float32)
    v = jnp.ones(shape, jnp.float32)
    qs = jnp.ones((2, 256), jnp.int32)

    def loss(q, k, v):
        o = _fa(q, k, v, segment_ids=SegmentIds(q=qs, kv=qs), sm_scale=0.125)
        return jnp.sum(o * o)

    lower_for_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)


def case_fused_axial_fwd(n=256):
    """The in-repo fused dense attention kernel (ops/pallas/axial.py) at
    the axial-pass shape, compiled-mode Mosaic lowering with a padding
    mask (the bias-streaming layout is what tiling checks bite on)."""
    from alphafold2_tpu.ops.pallas.axial import fused_attention

    b, h, d = 2, 4, 64
    k1, k2, k3 = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(k1, (b, h, n, d), jnp.float32)
    k = jax.random.normal(k2, (b, h, n, d), jnp.float32)
    v = jax.random.normal(k3, (b, h, n, d), jnp.float32)
    mask = jnp.ones((b, n), bool).at[:, -17:].set(False)

    def f(q, k, v):
        return fused_attention(
            q, k, v, q_mask=mask, kv_mask=mask, sm_scale=d**-0.5,
            interpret=False,
        )

    lower_for_tpu(f, q, k, v)


def case_fused_axial_bwd(n=256):
    """Gradients through the fused kernel's custom VJP: lowers the dq and
    dk/dv kernels inside one traced program."""
    from alphafold2_tpu.ops.pallas.axial import fused_attention

    b, h, d = 2, 4, 64
    q = jnp.ones((b, h, n, d), jnp.float32)
    mask = jnp.ones((b, n), bool).at[:, -17:].set(False)

    def loss(q, k, v):
        o = fused_attention(
            q, k, v, kv_mask=mask, sm_scale=d**-0.5, interpret=False
        )
        return jnp.sum(o * o)

    lower_for_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, q, q)


def case_tied_row_fwd(n=256):
    """The fused tied-row MSA kernel at trunk shape: fused feature axis
    R*D = 512 exercises the wide-accumulator tiling."""
    from alphafold2_tpu.ops.pallas.tied_row import tied_row_attention

    b, r, h, d = 1, 8, 4, 64
    q = jnp.ones((b, r, n, h, d), jnp.float32)
    mask = jnp.ones((b, n), bool).at[:, -9:].set(False)

    def f(q, k, v):
        return tied_row_attention(
            q, k, v, q_mask=mask, kv_mask=mask, sm_scale=d**-0.5,
            interpret=False,
        )

    lower_for_tpu(f, q, q, q)


def case_tied_row_bwd(n=256):
    from alphafold2_tpu.ops.pallas.tied_row import tied_row_attention

    b, r, h, d = 1, 8, 4, 64
    q = jnp.ones((b, r, n, h, d), jnp.float32)
    mask = jnp.ones((b, n), bool).at[:, -9:].set(False)

    def loss(q, k, v):
        o = tied_row_attention(
            q, k, v, kv_mask=mask, sm_scale=d**-0.5, interpret=False
        )
        return jnp.sum(o * o)

    lower_for_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, q, q)


def case_negative_control():
    """The round-4 bug class, reconstructed: a (1, block) row-stat output
    block on a (rows, n) array. The gate MUST reject it — if this lowers,
    the gate is not checking what it claims and the run fails."""
    from jax.experimental import pallas as pl

    def kern(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0

    def f(x):
        return pl.pallas_call(
            kern,
            out_shape=jax.ShapeDtypeStruct((4, 512), jnp.float32),
            grid=(4,),
            in_specs=[pl.BlockSpec((1, 512), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((1, 512), lambda i: (i, 0)),
        )(x)

    x = jnp.ones((4, 512), jnp.float32)
    try:
        lower_for_tpu(f, x)
    except Exception as e:
        if _is_mosaic_tiling_rejection(e):
            return  # gate correctly rejects the round-4 bug class
        raise
    raise AssertionError(
        "negative control LOWERED: the gate is not exercising Mosaic's "
        "tiling checks (jax behavior change?) — do not trust green results"
    )


def _is_mosaic_tiling_rejection(e: BaseException) -> bool:
    """Does this exception look like Mosaic's lowering rejecting the
    mis-tiled kernel? The old exact-substring match ('divisible by 8 and
    128') turned into a false RED whenever JAX reworded the message; accept
    any error that (a) mentions tiling/block-shape vocabulary, or (b) was
    raised from inside the Pallas/Mosaic lowering code, chained causes
    included. The hard failure stays only for the case that matters: the
    bad kernel lowering CLEANLY."""
    seen = set()
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        msg = str(e).lower()
        if any(
            s in msg
            for s in (
                "divisible by",
                "tiling",
                "tile",
                "block shape",
                "block_shape",
                "layout",
            )
        ):
            return True
        tb = e.__traceback__
        while tb is not None:
            fname = tb.tb_frame.f_code.co_filename.lower()
            if "pallas" in fname or "mosaic" in fname:
                return True
            tb = tb.tb_next
        e = e.__cause__ or e.__context__
    return False


CASES = [
    ("block_sparse_fwd_n512", lambda: case_block_sparse_fwd(512)),
    ("block_sparse_fwd_nolse_n512",
     lambda: case_block_sparse_fwd(512, with_lse=False)),
    ("block_sparse_fwd_n1024", lambda: case_block_sparse_fwd(1024)),
    ("block_sparse_bwd_n512", lambda: case_block_sparse_bwd(512)),
    ("block_sparse_bwd_n1024", lambda: case_block_sparse_bwd(1024)),
    ("block_sparse_custom_vjp_n512", case_block_sparse_custom_vjp),
    ("flash_axial_256", case_flash_axial_256),
    ("flash_compressed_cross", case_flash_compressed_cross),
    ("flash_bwd_256", case_flash_bwd_256),
    ("fused_axial_fwd_256", case_fused_axial_fwd),
    ("fused_axial_bwd_256", case_fused_axial_bwd),
    ("tied_row_fwd_256", case_tied_row_fwd),
    ("tied_row_bwd_256", case_tied_row_bwd),
    ("negative_control_rejects_bad_tiling", case_negative_control),
]


def run_gate(names=()) -> tuple:
    """Run the named cases (all when empty). Returns (records, failed)."""
    run = [(n, f) for n, f in CASES if not names or n in names]
    records = []
    failed = []
    for name, fn in run:
        t0 = time.monotonic()
        try:
            fn()
            rec = {"case": name, "ok": True}
        except Exception as e:
            failed.append(name)
            rec = {
                "case": name, "ok": False,
                "error": f"{type(e).__name__}: {str(e)[:500]}",
            }
        rec["seconds"] = round(time.monotonic() - t0, 1)
        records.append(rec)
    return records, failed


def main(argv=None) -> int:
    names = (argv or sys.argv)[1:]
    unknown = sorted(set(names) - {n for n, _ in CASES})
    if unknown:
        # a typo'd case name must be a loud red, not a zero-case run that
        # exits green having certified nothing
        print(json.dumps({
            "gate": "tpu_lowering",
            "error": f"unknown case name(s): {unknown}",
            "known": [n for n, _ in CASES],
        }), flush=True)
        return 2
    records, failed = run_gate(names)
    for rec in records:
        print(json.dumps(rec), flush=True)
    print(json.dumps({
        "gate": "tpu_lowering", "cases": len(records), "failed": failed,
    }), flush=True)
    return 1 if failed else 0


if __name__ == "__main__":
    # last line of defense for direct `python -m` runs in a hooked env:
    # re-exec through a scrubbed environment (jax is already imported in
    # THIS process, but execve replaces the process wholesale)
    import os

    if os.environ.get("AF2TPU_LOWERING_GATE_SCRUBBED") != "1":
        from alphafold2_tpu.preflight import scrub_axon_env

        env = scrub_axon_env()
        env["AF2TPU_LOWERING_GATE_SCRUBBED"] = "1"
        os.execve(
            sys.executable,
            [sys.executable, "-m", "alphafold2_tpu.analysis.lowering"]
            + sys.argv[1:],
            env,
        )
    sys.exit(main())
