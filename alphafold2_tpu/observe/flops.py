"""Unified FLOPs/bytes accounting and MFU: the tree's ONE cost_analysis parser.

``compiled.cost_analysis()`` parsing used to be duplicated ad hoc in
``bench.py`` and ``scripts/bisect_perf.py``; every consumer (the train
bench, the serve engine's ``compile_records``, the train loop's metrics and
the microbenchmarks) now sources flops/bytes/MFU from here, so the peak
tables and the plausibility ceiling cannot drift apart between call sites.

jax is imported lazily (only where a device is actually consulted) so the
module rides along with ``alphafold2_tpu.observe`` imports in host-side
tools without touching a backend.
"""

from __future__ import annotations

from typing import Optional

# published peak dense bf16 FLOPs/s per chip (v5e's oft-quoted 394 is int8)
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6e": 918e12,
}

# no production chip sustains 2 PFLOP/s dense bf16 today (v6e peaks at
# 918 TF); a measurement implying more is a broken clock on ANY device,
# known or not — the unknown-device fallback for the implausibility guard
SANITY_FLOPS_CEILING = 2e15


def cost_analysis(compiled) -> dict:
    """Normalized XLA cost-analysis properties of a compiled executable.

    Handles the older-jax list-of-per-device-dicts form; returns ``{}`` when
    the backend exposes nothing (cost analysis is best-effort and must never
    break a measurement)."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        return dict(cost) if cost else {}
    except Exception:
        return {}


def executable_costs(compiled) -> dict:
    """``{"flops": float|None, "bytes_accessed": float|None}`` for one
    compiled executable (None = the backend exposes no such count)."""
    cost = cost_analysis(compiled)
    flops = float(cost.get("flops", 0.0))
    bytes_accessed = float(cost.get("bytes accessed", 0.0))
    return {
        "flops": flops if flops > 0 else None,
        "bytes_accessed": bytes_accessed if bytes_accessed > 0 else None,
    }


def step_flops(compiled) -> Optional[float]:
    """The compiled program's own FLOP count from XLA cost analysis; None
    when the backend exposes none."""
    return executable_costs(compiled)["flops"]


def executable_memory(compiled) -> dict:
    """Per-device memory footprint of one compiled executable from XLA's
    ``memory_analysis()``: ``argument_bytes`` / ``output_bytes`` /
    ``temp_bytes`` (+ their sum ``program_bytes``). For SPMD programs these
    are PER-DEVICE numbers — exactly the quantity the pair-grid sharding
    exists to shrink, and what the serve compile records and the mesh
    regression gate key on. ``{}`` when the backend exposes nothing (the
    accounting must never break a measurement)."""
    try:
        ma = compiled.memory_analysis()
        out = {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
        }
        out["program_bytes"] = sum(out.values())
        return out
    except Exception:
        return {}


def device_peak_flops(device=None) -> Optional[float]:
    """Published peak dense bf16 FLOPs/s of ``device`` (default: the first
    jax device); None for chips the table does not know (CPUs included)."""
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        kind = device.device_kind
        return next(
            (v for k, v in PEAK_FLOPS.items() if k.lower() in kind.lower()),
            None,
        )
    except Exception:
        return None


def mfu(
    flops: Optional[float],
    seconds: float,
    device=None,
    peak: Optional[float] = None,
) -> Optional[float]:
    """Model FLOPs utilization: ``flops / seconds / peak``. None when the
    flop count or the chip's peak is unknown."""
    if not flops or not seconds or seconds <= 0:
        return None
    peak = peak if peak is not None else device_peak_flops(device)
    if not peak:
        return None
    return flops / seconds / peak


def estimate_mfu(compiled, step_seconds: float) -> Optional[float]:
    """MFU of one executed step of ``compiled`` taking ``step_seconds``."""
    return mfu(step_flops(compiled), step_seconds)


def attention_flops_attribution(
    *,
    batch: int,
    pair_len: int,
    msa_depth: int,
    msa_len: int,
    depth: int,
    heads: int,
    dim_head: int,
    tie_rows: bool = False,
    total_flops: Optional[float] = None,
) -> dict:
    """Per-kernel attribution of one trunk forward's attention FLOPs.

    XLA's ``cost_analysis`` reports one number for the whole executable;
    when MFU moves, nothing says WHICH attention shape is responsible. This
    is the analytical split (matmul FLOPs only, 2 flops per MAC, QK^T + AV
    per pass) over the trunk's attention families at the engine's static
    shapes — the same quantities the fused kernels target:

    - ``axial``: the two axial passes per layer over the (pair_len,
      pair_len) pair grid — 2 * 4 * B * N^3 * inner per layer, the N^2
      hot path.
    - ``tied_row``: the MSA row pass when rows are tied (the tied-row
      kernel's shape) — 4 * B * M * Nm^2 * inner per layer; attributed to
      ``msa_axial_untied`` instead when ``tie_rows`` is False.
    - ``msa_axial_untied``: the remaining MSA axial work (column pass, and
      the row pass when untied).
    - ``other``: ``total_flops`` minus the attention families (cross-attn,
      feedforwards, embeddings, realization) when a total is given.

    Shapes follow the serve engine's geometry: ``pair_len`` is the
    elongated token length (3 * bucket), ``msa_len`` the unelongated
    bucket. Purely analytical — never touches a backend."""
    inner = heads * dim_head
    axial = depth * 2 * 4.0 * batch * float(pair_len) ** 3 * inner
    msa_row = depth * 4.0 * batch * msa_depth * float(msa_len) ** 2 * inner
    msa_col = depth * 4.0 * batch * msa_len * float(msa_depth) ** 2 * inner
    out = {
        "axial": axial,
        "tied_row": msa_row if tie_rows else 0.0,
        "msa_axial_untied": (0.0 if tie_rows else msa_row) + msa_col,
    }
    if total_flops:
        out["other"] = max(0.0, float(total_flops) - sum(out.values()))
    return out


# one measured-peak probe per process (keyed by device kind)
_CALIBRATED: dict = {}


def calibrated_peak_flops(device=None, n: int = 1024, iters: int = 8):
    """MEASURED dense-matmul peak FLOPs/s for chips the published table
    does not know (the CPU mesh above all): times a jitted f32 matmul of
    known cost. This is what lets the serve bench report an honest MFU on
    the 8-virtual-device CPU mesh — utilization against the host's own
    measured matmul roofline, labeled as such (``mfu_basis``), never
    against a made-up CPU "peak". Virtual devices share the physical
    silicon, so the calibration is per HOST and callers must not multiply
    it by the virtual device count. Cached per device kind."""
    import time

    try:
        import jax
        import jax.numpy as jnp

        device = device if device is not None else jax.devices()[0]
        kind = device.device_kind
        if kind in _CALIBRATED:
            return _CALIBRATED[kind]
        x = jax.device_put(jnp.ones((n, n), jnp.float32), device)
        f = jax.jit(lambda a: a @ a)
        jax.block_until_ready(f(x))  # compile + warm outside the timing
        t0 = time.perf_counter()
        y = x
        for _ in range(iters):
            y = f(y)
        jax.block_until_ready(y)
        peak = iters * 2 * n**3 / max(time.perf_counter() - t0, 1e-9)
        _CALIBRATED[kind] = peak
        return peak
    except Exception:
        return None


def mesh_mfu(flops: Optional[float], seconds: float, mesh=None) -> dict:
    """MFU of a (possibly sharded) program: ``{"mfu": ..., "mfu_basis":
    "published-peak" | "calibrated-matmul"}`` (empty values -> {"mfu":
    None}). On chips with a published peak the denominator is
    peak * n_devices (the multi-chip MFU the ROADMAP wants from the
    sharded serve path); on unknown chips (CPU mesh) it is the measured
    host matmul roofline — virtual devices share silicon, so no
    multiplier."""
    if not flops or not seconds or seconds <= 0:
        return {"mfu": None}
    peak = device_peak_flops()
    if peak is not None:
        n_dev = 1
        if mesh is not None:
            try:
                n_dev = int(mesh.devices.size)
            except Exception:
                n_dev = 1
        return {
            "mfu": flops / seconds / (peak * max(1, n_dev)),
            "mfu_basis": "published-peak",
        }
    peak = calibrated_peak_flops()
    if not peak:
        return {"mfu": None}
    return {
        "mfu": flops / seconds / peak,
        "mfu_basis": "calibrated-matmul",
    }
