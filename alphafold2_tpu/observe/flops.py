"""Unified FLOPs/bytes accounting and MFU: the tree's ONE cost_analysis parser.

``compiled.cost_analysis()`` parsing used to be duplicated ad hoc in
``bench.py`` and ``scripts/bisect_perf.py``; every consumer (the train
bench, the serve engine's ``compile_records``, the train loop's metrics and
the microbenchmarks) now sources flops/bytes/MFU from here, so the peak
tables and the plausibility ceiling cannot drift apart between call sites.

jax is imported lazily (only where a device is actually consulted) so the
module rides along with ``alphafold2_tpu.observe`` imports in host-side
tools without touching a backend.
"""

from __future__ import annotations

from typing import Optional

# published peak dense bf16 FLOPs/s per chip (v5e's oft-quoted 394 is int8)
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6e": 918e12,
}

# no production chip sustains 2 PFLOP/s dense bf16 today (v6e peaks at
# 918 TF); a measurement implying more is a broken clock on ANY device,
# known or not — the unknown-device fallback for the implausibility guard
SANITY_FLOPS_CEILING = 2e15


def cost_analysis(compiled) -> dict:
    """Normalized XLA cost-analysis properties of a compiled executable.

    Handles the older-jax list-of-per-device-dicts form; returns ``{}`` when
    the backend exposes nothing (cost analysis is best-effort and must never
    break a measurement)."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        return dict(cost) if cost else {}
    except Exception:
        return {}


def executable_costs(compiled) -> dict:
    """``{"flops": float|None, "bytes_accessed": float|None}`` for one
    compiled executable (None = the backend exposes no such count)."""
    cost = cost_analysis(compiled)
    flops = float(cost.get("flops", 0.0))
    bytes_accessed = float(cost.get("bytes accessed", 0.0))
    return {
        "flops": flops if flops > 0 else None,
        "bytes_accessed": bytes_accessed if bytes_accessed > 0 else None,
    }


def step_flops(compiled) -> Optional[float]:
    """The compiled program's own FLOP count from XLA cost analysis; None
    when the backend exposes none."""
    return executable_costs(compiled)["flops"]


def device_peak_flops(device=None) -> Optional[float]:
    """Published peak dense bf16 FLOPs/s of ``device`` (default: the first
    jax device); None for chips the table does not know (CPUs included)."""
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        kind = device.device_kind
        return next(
            (v for k, v in PEAK_FLOPS.items() if k.lower() in kind.lower()),
            None,
        )
    except Exception:
        return None


def mfu(
    flops: Optional[float],
    seconds: float,
    device=None,
    peak: Optional[float] = None,
) -> Optional[float]:
    """Model FLOPs utilization: ``flops / seconds / peak``. None when the
    flop count or the chip's peak is unknown."""
    if not flops or not seconds or seconds <= 0:
        return None
    peak = peak if peak is not None else device_peak_flops(device)
    if not peak:
        return None
    return flops / seconds / peak


def estimate_mfu(compiled, step_seconds: float) -> Optional[float]:
    """MFU of one executed step of ``compiled`` taking ``step_seconds``."""
    return mfu(step_flops(compiled), step_seconds)
