"""Request-scoped trace context: W3C-traceparent-shaped ids + reconstruction.

The Tracer (tracing.py) emits spans that are stream-global: nesting is
inferred from ts/dur overlap, so no single request's journey through the
async frontend (queue -> batch formation -> dispatch -> retry / dedup
join) can be reconstructed once requests interleave. This module is the
missing identity layer:

- :class:`TraceContext` — ``trace_id`` (32 hex, one per request lifetime)
  / ``span_id`` (16 hex, one per operation) / ``parent_id`` (the parent
  operation's span_id, ``None`` at the root). ``child()`` mints the next
  link in the chain; ``traceparent()`` round-trips the W3C header form so
  an external frontend can hand a context in (or take one out).
- **Thread-local current context** — ``use_trace(ctx)`` installs a
  context for a ``with`` region and ``current_trace()`` reads it;
  ``Tracer.span`` auto-attaches the current context to every event it
  emits, minting a child per span, so instrumented code needs no explicit
  id plumbing on a single thread. Cross-thread handoff is explicit by
  design (the scheduler carries the context on the request object): an
  ambient context silently inherited by an unrelated worker thread is
  exactly the mislabeling this layer exists to prevent.
- **Reconstruction** — :func:`reconstruct_traces` groups emitted events
  by owning trace (single-owner events via ``args.trace_id``, shared
  batch spans via ``args.trace_ids`` membership) and
  :func:`trace_incomplete_reason` / :func:`trace_completeness` verify a
  request's lifecycle is an unbroken span chain (every ``parent_id``
  resolves inside the trace, submit and resolve both present, a real
  dispatch span behind every non-cached ``ok``). The serve-async bench
  records the completeness fraction and CI gates on it.

Pure stdlib; importable without a jax backend.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import uuid
from contextlib import contextmanager
from typing import Optional

_TRACEPARENT_VERSION = "00"


def _new_trace_id() -> str:
    return uuid.uuid4().hex  # 32 hex chars (128 bit)


def _new_span_id() -> str:
    return os.urandom(8).hex()  # 16 hex chars (64 bit)


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One node of a request's span chain. Frozen: a context is an
    identity, not a mutable accumulator — derive with :meth:`child`."""

    trace_id: str
    span_id: str
    parent_id: Optional[str] = None

    @classmethod
    def new(cls) -> "TraceContext":
        """Mint a root context (a fresh trace)."""
        return cls(trace_id=_new_trace_id(), span_id=_new_span_id())

    def child(self) -> "TraceContext":
        """The next chain link: same trace, fresh span, parented here."""
        return TraceContext(
            trace_id=self.trace_id,
            span_id=_new_span_id(),
            parent_id=self.span_id,
        )

    def traceparent(self) -> str:
        """W3C ``traceparent`` header form (``00-<trace>-<span>-01``)."""
        return f"{_TRACEPARENT_VERSION}-{self.trace_id}-{self.span_id}-01"

    @classmethod
    def from_traceparent(cls, header: str) -> "TraceContext":
        parts = header.strip().split("-")
        if (
            len(parts) != 4
            or len(parts[1]) != 32
            or len(parts[2]) != 16
            or any(c not in "0123456789abcdef" for c in parts[1] + parts[2])
        ):
            raise ValueError(f"malformed traceparent {header!r}")
        return cls(trace_id=parts[1], span_id=parts[2])

    def event_args(self) -> dict:
        """The id triple as trace-event args (``parent_id`` only when
        set, so root events are recognizable by its absence)."""
        out = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_id:
            out["parent_id"] = self.parent_id
        return out


_tls = threading.local()


def current_trace() -> Optional[TraceContext]:
    """The thread's active context (None outside ``use_trace``)."""
    return getattr(_tls, "ctx", None)


@contextmanager
def use_trace(ctx: Optional[TraceContext]):
    """Install ``ctx`` as the thread's current context for the region.
    ``None`` explicitly clears it (detaching a worker thread from an
    ambient context it must not inherit)."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    try:
        yield ctx
    finally:
        _tls.ctx = prev


# ------------------------------------------------------------ reconstruction


def _args(event: dict) -> dict:
    a = event.get("args")
    return a if isinstance(a, dict) else {}


def reconstruct_traces(events) -> dict:
    """Group trace events by owning trace_id.

    Single-owner events carry ``args.trace_id``; batch-scoped spans (one
    dispatch carrying several requests) list every member trace in
    ``args.trace_ids`` and appear under each. Returns
    ``{trace_id: [events in emission order]}``."""
    traces: dict = {}
    for e in events:
        a = _args(e)
        tid = a.get("trace_id")
        if tid:
            traces.setdefault(tid, []).append(e)
        for shared in a.get("trace_ids") or ():
            if shared != tid:
                traces.setdefault(shared, []).append(e)
    return traces


# the lifecycle event names the scheduler/engine emit (serve/scheduler.py,
# serve/engine.py); reconstruction keys on these
SUBMIT_EVENT = "sched.submit"
RESOLVE_EVENT = "sched.resolve"
DEDUP_EVENT = "sched.dedup_join"
CACHE_HIT_EVENT = "sched.cache_hit"
_DISPATCH_EVENTS = ("sched.dispatch", "sched.retry", "serve.batch")


def trace_incomplete_reason(
    trace_id: str, trace_events: list
) -> Optional[str]:
    """Why this trace does NOT reconstruct to a complete, unbroken request
    lifecycle (None = it does).

    Complete means: a ``sched.submit`` root and a ``sched.resolve``
    terminal both present; every ``parent_id`` resolves to a ``span_id``
    within the trace (the unbroken-chain property); an ``ok`` result is
    backed by a dispatch span (or, for cached/deduped results, by the
    cache-hit / dedup-join event that explains why no dispatch exists)."""
    if not trace_events:
        return "no events for trace"
    own = [e for e in trace_events if _args(e).get("trace_id") == trace_id]
    names = {e.get("name") for e in trace_events}
    if not any(e.get("name") == SUBMIT_EVENT for e in own):
        return f"missing {SUBMIT_EVENT} root"
    resolves = [e for e in own if e.get("name") == RESOLVE_EVENT]
    if not resolves:
        return f"missing {RESOLVE_EVENT} terminal"
    span_ids = {
        _args(e).get("span_id") for e in own if _args(e).get("span_id")
    }
    for e in own:
        parent = _args(e).get("parent_id")
        if parent and parent not in span_ids:
            return (
                f"broken span chain: {e.get('name')} parent {parent} "
                "not emitted in this trace"
            )
    terminal = _args(resolves[-1])
    if terminal.get("status") == "ok":
        if terminal.get("cache_hit"):
            if not ({CACHE_HIT_EVENT, DEDUP_EVENT} & names):
                return (
                    "cached ok result without a cache-hit or dedup-join "
                    "event"
                )
        elif not (set(_DISPATCH_EVENTS) & names):
            return "ok result without a dispatch span"
    return None


def trace_completeness(events, trace_ids, max_reasons: int = 8) -> dict:
    """Completeness summary over the given request traces: ``total`` /
    ``complete`` / ``fraction`` plus the first few incompleteness reasons
    (enough to debug, bounded so a systemic break can't bloat a record)."""
    traces = reconstruct_traces(events)
    total = complete = 0
    reasons: dict = {}
    for tid in trace_ids:
        if not tid:
            continue
        total += 1
        reason = trace_incomplete_reason(tid, traces.get(tid, []))
        if reason is None:
            complete += 1
        elif len(reasons) < max_reasons:
            reasons[tid] = reason
    return {
        "total": total,
        "complete": complete,
        "fraction": round(complete / total, 4) if total else 1.0,
        **({"incomplete": reasons} if reasons else {}),
    }
