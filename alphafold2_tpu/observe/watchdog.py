"""Liveness watchdog: per-stage deadlines backed by a cheap backend probe.

Round 5's bench burned its entire 1500 s deadline hung inside
``backend_init`` with no structured signal (BENCH_r05.json). The fix
mirrors ``scripts/tpu_session.py``'s subprocess probe, generalized: a
heartbeat thread watches which stage the process is in; when a stage
overstays its deadline, a tiny jax computation runs in a *subprocess* with
a hard timeout — cheap when the backend answers (seconds), bounded when
the tunnel is dead. A dead probe fires ``on_dead`` with a structured
record marked ``liveness: "dead"``; a live probe means slow-but-healthy
and the stage earns another deadline instead of a spurious kill.

The main process can hang un-interruptibly inside C++ (a dead in-process
relay), which is exactly why both the checking and the probing live on a
daemon thread + subprocess: neither needs the hung thread's cooperation.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, Optional, Tuple

_DEFAULT_PROBE_CODE = (
    "import jax, jax.numpy as jnp; "
    "assert float(jnp.ones((8, 8)).sum()) == 64.0"
)


def probe_backend(
    timeout: Optional[float] = None,
    env: Optional[dict] = None,
    code: Optional[str] = None,
) -> Tuple[bool, str]:
    """One tiny jax computation in a subprocess, hard-bounded. True iff the
    backend completes it. The child inherits this process's environment
    (including any relay/site hooks) by default, so it probes the same
    backend the caller would use. ``AF2TPU_LIVENESS_PROBE_CODE`` overrides
    the probe body (tests simulate a hung tunnel with a sleep)."""
    timeout = timeout if timeout is not None else float(
        os.environ.get("AF2TPU_LIVENESS_TIMEOUT", 25)
    )
    code = code or os.environ.get(
        "AF2TPU_LIVENESS_PROBE_CODE", _DEFAULT_PROBE_CODE
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout, capture_output=True, text=True, env=env,
        )
        if r.returncode == 0:
            return True, "probe ok"
        return False, f"probe rc={r.returncode}: {r.stderr[-300:]}"
    except subprocess.TimeoutExpired:
        return False, f"probe hung >{timeout:.0f}s (dead tunnel)"


class LivenessWatchdog:
    """Heartbeat thread with per-stage deadlines.

    ``stage_fn`` reports the process's current stage name (polled — the
    hung thread never has to call in); ``deadlines`` maps stage names to
    seconds (a name matches if it equals the stage or its suffix after the
    last ``:``, so ``"backend_init"`` covers ``"serve:backend_init"`` and
    ``"first_light:backend_init"``). Stages with no deadline are
    unbounded here (an overall-deadline watchdog still covers them).

    On expiry the ``probe`` runs: dead → ``on_dead(record)`` fires once
    with ``record["liveness"] == "dead"`` and the watchdog stops; alive →
    the stage's clock resets (it re-probes after another deadline).
    """

    def __init__(
        self,
        stage_fn: Callable[[], str],
        deadlines: Dict[str, float],
        on_dead: Callable[[dict], None],
        probe: Callable[..., Tuple[bool, str]] = probe_backend,
        poll_s: float = 1.0,
    ):
        self._stage_fn = stage_fn
        self._deadlines = dict(deadlines)
        self._on_dead = on_dead
        self._probe = probe
        self._poll_s = poll_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.fired: Optional[dict] = None

    def _deadline_for(self, stage: str) -> Optional[float]:
        if stage in self._deadlines:
            return self._deadlines[stage]
        suffix = stage.rsplit(":", 1)[-1]
        return self._deadlines.get(suffix)

    def start(self) -> "LivenessWatchdog":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        current = self._stage_fn()
        t0 = time.monotonic()
        while not self._stop.wait(self._poll_s):
            stage = self._stage_fn()
            if stage != current:
                current, t0 = stage, time.monotonic()
                continue
            deadline = self._deadline_for(stage)
            if deadline is None:
                continue
            waited = time.monotonic() - t0
            if waited <= deadline:
                continue
            alive, why = self._probe()
            if alive:
                # slow but healthy: earn another deadline, re-probe later
                t0 = time.monotonic()
                continue
            self.fired = {
                "liveness": "dead",
                "stage": stage,
                "waited_s": round(time.monotonic() - t0, 1),
                "stage_deadline_s": deadline,
                "probe": why,
            }
            self._on_dead(self.fired)
            return
