"""Workload capture & deterministic replay plane.

The telemetry plane (tracectx/registry/slo/flightrec) can reconstruct any
single request lifecycle, but nothing records the request *stream* itself
— so scheduler/fleet changes could only ever be judged against synthetic
benches, never against the traffic that actually hit a deployment. This
module closes that gap:

- :class:`WorkloadRecorder` — logs every ``AsyncServeFrontend`` request
  as a scrubbed JSONL event via the scheduler's submit-side hook
  (``add_submit_observer``) plus the existing resolution observer
  (``add_observer``). Submit events carry the arrival offset from stream
  start, a derivation fingerprint (sha256 over
  ``serve.cache.feature_key`` — the same tuple the FeatureCache keys on),
  sequence length, a mutation-edit summary against recent traffic (so
  scan families survive scrubbing), priority, deadline, a HASHED parent
  hint and the trace id; resolve events carry status, reuse class and
  latency. **Raw sequences are recorded only with an explicit
  ``record_raw=True`` opt-in** — the scrubbed default leaks neither
  sequence content nor caller-controlled metadata (parent hints and
  family labels are one-way hashed, error text is never recorded).
- :func:`load_workload` / :func:`build_replay` — turn a recorded log
  back into a timed ``ServeRequest`` stream for ``bench.py --mode
  serve-replay``: original timing, ``time_warp`` compression and
  ``load_scale`` multiplication (extra copies get distinct seeds so they
  are real work, not dedup fodder).
- :func:`synthetic_diurnal` — a seeded inhomogeneous-Poisson generator
  (sinusoidal rate curve: the classic diurnal wave) for when no
  recording exists; its events are shaped exactly like recorded ones,
  so the replay driver treats both identically.

The recorder also keeps a bounded in-memory ring of its scrubbed events:
``FlightRecorder.attach_workload(recorder.tail)`` includes the last N
request events in incident dumps, so a watchdog/SIGTERM/dispatch-error
dump records what traffic preceded the incident.

Pure host-side python (numpy only inside the generator) — importable
without a jax backend, like the rest of ``observe``.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from collections import deque
from typing import Callable, Iterable, Optional

# NOTE: alphafold2_tpu.serve imports are deliberately function-local.
# Importing serve.bucketing/serve.cache at module scope initializes the
# serve package (engine -> predict -> models), and models itself imports
# observe (numerics.tag) — a cycle that breaks any `import
# alphafold2_tpu.models` entry point. Deferring keeps observe leaf-free.

SCHEMA_VERSION = 1

# mutation-edit summaries stop past this many substitutions: the request
# is no longer "a mutant of" recent traffic in any scan sense (mirrors
# ServeEngine.DELTA_MAX_EDITS, kept independent so the recorded summary
# is a property of the log, not of one engine's fast-lane config)
EDIT_SUMMARY_MAX = 8


def _hash16(text: str) -> str:
    return hashlib.sha256(text.encode()).hexdigest()[:16]


def derivation_fingerprint(
    seq: str, bucket: int, msa_depth: int, seed: int
) -> str:
    """Content address of a request's derivation: sha256 over the same
    ``feature_key`` tuple the FeatureCache keys featurized trees on, so
    two log lines share a fingerprint iff the engine would featurize them
    identically. One-way: the scrubbed log never exposes the sequence."""
    from alphafold2_tpu.serve.cache import feature_key

    return _hash16(repr(feature_key(seq, bucket, msa_depth, seed)))


def _edit_summary(seq: str, recent: Iterable) -> Optional[dict]:
    """Mutation-edit summary against recent traffic: the scrubbed log's
    substitute for raw sequences — scan families stay visible (same
    ``parent_fp``, small edit counts, positions) without leaking content.
    ``recent`` iterates (seq, fingerprint) pairs, newest last."""
    best = None
    for prev, prev_fp in recent:
        if len(prev) != len(seq) or prev == seq:
            continue
        pos = [i for i, (a, b) in enumerate(zip(prev, seq)) if a != b]
        if not 0 < len(pos) <= EDIT_SUMMARY_MAX:
            continue
        if best is None or len(pos) < len(best["edit_pos"]):
            best = {"edits": len(pos), "edit_pos": pos,
                    "parent_fp": prev_fp}
    return best


class WorkloadRecorder:
    """Records one serving frontend's request stream as scrubbed events.

    Wire it to a frontend with BOTH hooks::

        rec = WorkloadRecorder(path, buckets=engine.buckets,
                               msa_depth=engine.msa_depth)
        frontend.add_submit_observer(rec.on_submit)
        frontend.add_observer(rec.observe)

    ``path=None`` keeps a ring only (the flightrec tail); with a path
    every event is also appended as one JSON line. ``record_raw=True`` is
    the explicit opt-in that adds the raw sequence to submit events —
    required for the log to be replayable, appropriate for synthetic
    bench traffic, never the default. The recorder is thread-safe and
    never raises into the serving path."""

    def __init__(
        self,
        path: Optional[str] = None,
        record_raw: bool = False,
        ring: int = 512,
        buckets: tuple = (),
        msa_depth: int = 0,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.path = path
        self.record_raw = bool(record_raw)
        self.buckets = tuple(buckets)
        self.msa_depth = int(msa_depth)
        self._clock = clock if clock is not None else time.perf_counter
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(16, int(ring)))
        self._recent: deque = deque(maxlen=64)  # (seq, fp) edit window
        self._t0: Optional[float] = None
        self._file = open(path, "a") if path else None
        self.events_recorded = 0
        self.errors = 0

    # ---------------------------------------------------------------- hooks

    def on_submit(self, req, bucket=None, family=None) -> None:
        """Submit-side hook (``AsyncServeFrontend.add_submit_observer``):
        one scrubbed submit event per submitted request, rejects and
        unservables included."""
        try:
            from alphafold2_tpu.serve.bucketing import bucket_for

            now = req.arrival_s if req.arrival_s is not None else (
                self._clock()
            )
            if bucket is None and self.buckets:
                try:
                    bucket = bucket_for(len(req.seq), self.buckets)
                except ValueError:
                    bucket = None
            fp = derivation_fingerprint(
                req.seq, int(bucket or len(req.seq)), self.msa_depth,
                req.seed,
            )
            ev = {
                "v": SCHEMA_VERSION,
                "kind": "submit",
                "t": 0.0,  # patched under the lock once t0 is known
                "trace": req.trace.trace_id if req.trace else None,
                "fp": fp,
                "len": len(req.seq),
                "seed": int(req.seed),
                "priority": int(req.priority),
                **({"deadline_s": float(req.deadline_s)}
                   if req.deadline_s else {}),
                **({"bucket": int(bucket)} if bucket else {}),
                # caller-controlled free text is NEVER recorded verbatim:
                # parent hints and family labels are one-way hashed —
                # hint equality (all affinity batching needs) survives,
                # planted secrets do not
                **({"parent": _hash16(str(req.parent_id))}
                   if req.parent_id else {}),
                **({"family": _hash16(str(family))} if family else {}),
            }
            with self._lock:
                if self._t0 is None:
                    self._t0 = now
                ev["t"] = round(max(0.0, now - self._t0), 6)
                summary = _edit_summary(req.seq, self._recent)
                if summary is not None:
                    ev.update(summary)
                if self.record_raw:
                    ev["seq"] = req.seq
                self._recent.append((req.seq, fp))
                self._append_locked(ev)
        except Exception:
            self.errors += 1  # recording must never take serving down

    def observe(self, result, priority: int) -> None:
        """Resolution hook (``AsyncServeFrontend.add_observer``): one
        event per resolution, linked to its submit by trace id. Only the
        structured taxonomy is recorded — error text can quote request
        content, so it stays out of the log."""
        try:
            ev = {
                "v": SCHEMA_VERSION,
                "kind": "resolve",
                "t": 0.0,
                "trace": result.trace_id,
                "status": result.status,
                "priority": int(priority),
                "bucket": int(result.bucket),
                "cache_hit": bool(result.cache_hit),
                "retried": bool(result.retried),
                "latency_s": round(float(result.latency_s), 6),
                **({"reuse": result.feat_reuse}
                   if result.feat_reuse else {}),
            }
            with self._lock:
                if self._t0 is None:
                    self._t0 = self._clock()
                ev["t"] = round(max(0.0, self._clock() - self._t0), 6)
                self._append_locked(ev)
        except Exception:
            self.errors += 1

    def write_summary(self, summary: dict) -> None:
        """Append the run's closing summary (reuse ledger, goodput, tails)
        — the reference half of the replay-vs-record diff."""
        try:
            with self._lock:
                self._append_locked({
                    "v": SCHEMA_VERSION, "kind": "summary", **summary,
                })
        except Exception:
            self.errors += 1

    def _append_locked(self, ev: dict) -> None:
        self._ring.append(ev)
        self.events_recorded += 1
        if self._file is not None:
            self._file.write(json.dumps(ev) + "\n")
            self._file.flush()

    # ------------------------------------------------------------- consumers

    def events(self) -> list:
        with self._lock:
            return list(self._ring)

    def tail(self, n: int = 64) -> list:
        """The last ``n`` scrubbed events — the FlightRecorder's bounded
        workload tail (``FlightRecorder.attach_workload``)."""
        with self._lock:
            return list(self._ring)[-max(0, int(n)):]

    def family_by_trace(self) -> dict:
        """trace_id -> hashed family label, from the ring's submit events
        (the serve bench's per-family cost aggregation key)."""
        with self._lock:
            return {
                ev["trace"]: ev.get("family")
                for ev in self._ring
                if ev.get("kind") == "submit" and ev.get("trace")
            }

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


# ------------------------------------------------------------------ replay


def load_workload(path: str) -> dict:
    """Parse a recorded JSONL log into ``{"submits", "resolves",
    "summary"}`` (submits sorted by arrival offset; summary ``None``
    when the recording has no closing summary line). Torn trailing lines
    (a recorder killed mid-write) are tolerated."""
    submits, resolves, summary = [], [], None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail line
            kind = ev.get("kind")
            if kind == "submit":
                submits.append(ev)
            elif kind == "resolve":
                resolves.append(ev)
            elif kind == "summary":
                summary = ev
    submits.sort(key=lambda e: e.get("t", 0.0))
    return {"submits": submits, "resolves": resolves, "summary": summary}


def replayable_reason(submits: list) -> Optional[str]:
    """Why this log CANNOT drive a replay (None = it can). A scrubbed
    default log carries fingerprints, not sequences — replay needs the
    ``record_raw`` opt-in at record time (bench's own synthetic
    recordings enable it; their sequences are synthetic)."""
    if not submits:
        return "no submit events in the recording"
    missing = sum(1 for ev in submits if not ev.get("seq"))
    if missing:
        return (
            f"{missing}/{len(submits)} submit events carry no raw "
            "sequence (scrubbed recording; re-record with the raw opt-in)"
        )
    return None


def build_replay(
    submits: list,
    time_warp: float = 1.0,
    load_scale: int = 1,
) -> list:
    """Turn submit events into a timed request stream: a sorted list of
    ``(offset_s, ServeRequest)``. ``time_warp`` divides every arrival
    offset (2.0 = twice as fast); ``load_scale`` issues each request that
    many times — extra copies get distinct seeds and per-copy parent
    labels so they are genuinely new work (same featurization shape,
    no result-cache dedup), multiplying offered load, not cache hits."""
    from alphafold2_tpu.serve.engine import ServeRequest

    if time_warp <= 0:
        raise ValueError(f"time_warp must be > 0, got {time_warp}")
    if load_scale < 1:
        raise ValueError(f"load_scale must be >= 1, got {load_scale}")
    out = []
    for ev in submits:
        seq = ev.get("seq")
        if not seq:
            raise ValueError(
                "un-replayable submit event (no raw sequence): "
                + (replayable_reason(submits) or "")
            )
        for copy in range(int(load_scale)):
            parent = ev.get("parent")
            if parent and copy:
                parent = f"{parent}+{copy}"
            out.append((
                float(ev.get("t", 0.0)) / float(time_warp),
                ServeRequest(
                    seq,
                    seed=int(ev.get("seed", 0)) + copy * 1000003,
                    priority=int(ev.get("priority", 0)),
                    deadline_s=ev.get("deadline_s"),
                    parent_id=parent,
                ),
            ))
    out.sort(key=lambda pair: pair[0])
    return out


# --------------------------------------------------------------- synthetic


def synthetic_diurnal(
    seed: int = 0,
    requests: int = 50,
    mean_rate: float = 8.0,
    period_s: float = 6.0,
    amplitude: float = 0.8,
    buckets: tuple = (12, 16, 24),
    msa_depth: int = 2,
    class_mix: tuple = (0.2, 0.6, 0.2),
    dup_fraction: float = 0.1,
    mutant_fraction: float = 0.3,
    deadline_s: float = 30.0,
) -> list:
    """A seeded synthetic request stream riding a diurnal load curve, for
    replay when no recording exists. Arrivals are an inhomogeneous
    Poisson process with sinusoidal rate ``mean_rate * (1 + amplitude *
    sin(2*pi*t/period_s))`` (thinning), so the scheduler sees a load wave,
    not a flat stream. ``mutant_fraction`` of requests are single-point
    mutants of earlier traffic with a parent hint (scan families);
    ``dup_fraction`` are exact (seq, seed) repeats (cache/dedup traffic).
    Returns submit events shaped exactly like a raw-opt-in recording, so
    :func:`build_replay` drives both identically. Deterministic per seed."""
    import numpy as np

    from alphafold2_tpu.serve.bucketing import bucket_for

    rng = np.random.default_rng(seed)
    alpha = "ACDEFGHIKLMNPQRSTVWY"
    lo = max(4, buckets[0] // 2)
    hi = buckets[-1]
    pri_levels = (1, 0, -1)
    lam_max = mean_rate * (1.0 + abs(amplitude))
    events: list = []
    t = 0.0
    while len(events) < requests:
        t += float(rng.exponential(1.0 / lam_max))
        lam = mean_rate * (
            1.0 + amplitude * np.sin(2.0 * np.pi * t / period_s)
        )
        if rng.uniform() * lam_max > max(0.0, lam):
            continue  # thinned: we are in the trough of the wave
        priority = pri_levels[rng.choice(len(pri_levels), p=class_mix)]
        roll = rng.uniform()
        if events and roll < dup_fraction:
            src = events[int(rng.integers(len(events)))]
            seq, seed_i, parent = src["seq"], src["seed"], None
        elif events and roll < dup_fraction + mutant_fraction:
            src = events[int(rng.integers(len(events)))]
            pos = int(rng.integers(len(src["seq"])))
            sub = alpha[int(rng.integers(len(alpha)))]
            seq = src["seq"][:pos] + sub + src["seq"][pos + 1:]
            seed_i = src["seed"]  # delta featurize requires seed equality
            parent = f"fam-{src['fp']}"
        else:
            n = int(rng.integers(lo, hi + 1))
            seq = "".join(rng.choice(list(alpha), size=n))
            seed_i = int(rng.integers(0, 4))
            parent = None
        bucket = bucket_for(len(seq), tuple(buckets))
        events.append({
            "v": SCHEMA_VERSION,
            "kind": "submit",
            "t": round(t, 6),
            "fp": derivation_fingerprint(seq, bucket, msa_depth, seed_i),
            "len": len(seq),
            "seed": seed_i,
            "priority": priority,
            **({"deadline_s": float(deadline_s)} if deadline_s else {}),
            "bucket": bucket,
            **({"parent": _hash16(parent)} if parent else {}),
            "seq": seq,  # synthetic: raw is safe by construction
        })
    return events
