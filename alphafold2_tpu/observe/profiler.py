"""XLA trace capture over a configured train-step window.

Complements the span tracing in :mod:`alphafold2_tpu.observe.tracing`:
spans time host-side stages; this captures the device-side XLA trace
(``train.profile_dir`` / ``train.profile_steps``) for TensorBoard/XProf.
"""

from __future__ import annotations

from typing import Optional, Tuple


class Profiler:
    """Start/stop a jax profiler trace across a [start, stop) step window."""

    def __init__(self, trace_dir: Optional[str], steps: Tuple[int, int] = (10, 13)):
        self._dir = trace_dir
        self._start, self._stop = steps
        self._active = False

    def maybe_start(self, step: int) -> None:
        if self._dir and step == self._start and not self._active:
            import jax

            jax.profiler.start_trace(self._dir)
            self._active = True

    def maybe_stop(self, step: int) -> None:
        if self._active and step >= self._stop:
            import jax

            jax.block_until_ready(jax.numpy.zeros(()))
            jax.profiler.stop_trace()
            self._active = False
