"""FlightRecorder: a bounded ring of recent telemetry, dumped on death.

Every on-chip bench round that died at the tunnel (r01-r05) left one line
of liveness verdict and nothing else — the spans, counters and metric
snapshots leading up to the death were lost with the process. The flight
recorder keeps the RECENT telemetry in bounded in-memory rings (attached
as a :class:`~alphafold2_tpu.observe.tracing.Tracer` sink, so it costs
one deque append per event while healthy) and writes one structured,
scrubbed incident file when something dies:

- **LivenessWatchdog fire** — bench's ``on_dead`` dumps before
  ``os._exit`` (bench.py).
- **dispatch error** — the serve engine notes every converted dispatch
  exception and dumps on the first one (serve/engine.py).
- **SIGTERM** — :func:`install_signal_handler` dumps, then re-raises the
  default handler so exit semantics are unchanged.

The dump's environment echo goes through :func:`scrub_env` — AXON_ keys
dropped entirely (the preflight scrub's rule, alphafold2_tpu/preflight),
secret-shaped values redacted — because incident files get attached to
tickets and uploaded as CI artifacts. ``scripts/obs_report.py`` reuses
the same scrub for its env echo.

Module-level :func:`install` / :func:`active` hold one process-wide
recorder (bench and the engine find it without plumbing); dumps are
once-per-reason so a storm of dispatch errors yields one incident file,
not thousands. Pure stdlib, jax-free.
"""

from __future__ import annotations

import json
import os
import re
import signal
import sys
import threading
import time
from collections import deque
from typing import Optional

# env-value redaction: keys matching this carry credentials; their values
# must never reach an incident file (which CI uploads as an artifact)
_SECRET_KEY_RE = re.compile(
    r"(KEY|TOKEN|SECRET|PASSWORD|PASSWD|CREDENTIAL|AUTH|COOKIE)",
    re.IGNORECASE,
)
# keys dropped entirely (same families alphafold2_tpu.preflight
# scrub_axon_env strips from child environments)
_DROP_PREFIXES = ("AXON_", "PALLAS_AXON")

REDACTED = "[redacted]"


def scrub_env(env: Optional[dict] = None) -> dict:
    """A display-safe copy of ``env`` (default ``os.environ``): AXON_ /
    PALLAS_AXON keys dropped, secret-shaped keys' values replaced with
    ``[redacted]``. Key NAMES survive redaction — "this var was set" is
    exactly what a postmortem needs; the value is what must not leak."""
    src = dict(os.environ if env is None else env)
    out = {}
    for key in sorted(src):
        if key.startswith(_DROP_PREFIXES):
            continue
        out[key] = REDACTED if _SECRET_KEY_RE.search(key) else src[key]
    return out


class FlightRecorder:
    """Bounded rings of recent spans/events, notes, and metric snapshots.

    ``attach(tracer)`` registers the event ring as a tracer sink;
    :meth:`note` records structured annotations (dispatch errors, SLO
    alerts); :meth:`snapshot` records periodic metric snapshots (the
    registry snapshotter's ``also`` hook). :meth:`dump` writes the
    incident file — once per ``reason`` unless forced."""

    def __init__(
        self,
        directory: Optional[str] = None,
        capacity: int = 4096,
    ):
        self.directory = directory or os.environ.get("AF2TPU_FLIGHTREC_DIR")
        self._events: deque = deque(maxlen=max(16, int(capacity)))
        self._notes: deque = deque(maxlen=256)
        self._snapshots: deque = deque(maxlen=64)
        self._dumped: set = set()
        self._lock = threading.Lock()
        self._t0 = time.time()
        # optional workload-tail provider (observe/workload.py): a
        # callable returning the recorder's last-N SCRUBBED request
        # events, included in dumps so an incident file shows what
        # traffic preceded the death
        self._workload_tail = None

    # ------------------------------------------------------------ recording

    def record_event(self, event: dict) -> None:
        """Tracer-sink callback (invoked outside the tracer's lock from
        its per-event sink snapshot: a deque append only, no locks of our
        own — no deadlock surface)."""
        self._events.append(event)

    def attach(self, tracer) -> "FlightRecorder":
        tracer.add_sink(self.record_event)
        return self

    def attach_workload(self, tail_provider) -> "FlightRecorder":
        """Register ``tail_provider()`` (e.g. ``WorkloadRecorder.tail``)
        whose return — a bounded list of already-scrubbed request events —
        rides in every subsequent dump as ``workload_tail``."""
        self._workload_tail = tail_provider
        return self

    def note(self, kind: str, **info) -> None:
        self._notes.append({"kind": kind, "time": time.time(), **info})

    def snapshot(self, name: str, data: dict) -> None:
        self._snapshots.append(
            {"name": name, "time": time.time(), "data": dict(data)}
        )

    # -------------------------------------------------------------- dumping

    def dump(
        self,
        reason: str,
        extra: Optional[dict] = None,
        force: bool = False,
    ) -> Optional[str]:
        """Write the incident file; returns its path (None when no
        directory is configured or this reason already dumped)."""
        with self._lock:
            if not force and reason in self._dumped:
                return None
            self._dumped.add(reason)
        if not self.directory:
            return None
        workload_tail = None
        if self._workload_tail is not None:
            try:  # a broken provider must not mask the original failure
                workload_tail = list(self._workload_tail())[-64:]
            except Exception:
                workload_tail = None
        doc = {
            "reason": reason,
            "time_unix": round(time.time(), 3),
            "uptime_s": round(time.time() - self._t0, 3),
            "pid": os.getpid(),
            "argv": list(sys.argv),
            "python": sys.version.split()[0],
            "env": scrub_env(),
            "notes": list(self._notes),
            "metric_snapshots": list(self._snapshots),
            # newest-last; ts values are on the tracer's process timebase
            "events": list(self._events),
            # last-N request events from the workload ring (same scrub
            # contract as the recorder: hashed parents, no raw sequences
            # unless that recorder opted in)
            **({"workload_tail": workload_tail}
               if workload_tail is not None else {}),
            **({"extra": extra} if extra else {}),
        }
        try:
            os.makedirs(self.directory, exist_ok=True)
            safe = re.sub(r"[^A-Za-z0-9_.-]", "_", reason)[:64]
            path = os.path.join(
                self.directory,
                f"incident_{safe}_{os.getpid()}_{int(time.time())}.json",
            )
            with open(path, "w") as f:
                json.dump(doc, f, indent=2, default=str)
            return path
        except OSError:
            return None  # a full disk must not mask the original failure


# ------------------------------------------------------- process singleton

_ACTIVE: dict = {"recorder": None}


def install(recorder: FlightRecorder) -> FlightRecorder:
    _ACTIVE["recorder"] = recorder
    return recorder


def active() -> Optional[FlightRecorder]:
    return _ACTIVE["recorder"]


def maybe_install_from_env() -> Optional[FlightRecorder]:
    """Install a recorder when ``$AF2TPU_FLIGHTREC_DIR`` is set (the
    opt-in); returns the active recorder either way."""
    if _ACTIVE["recorder"] is None and os.environ.get("AF2TPU_FLIGHTREC_DIR"):
        install(FlightRecorder())
    return _ACTIVE["recorder"]


def install_signal_handler(recorder: FlightRecorder) -> None:
    """Dump on SIGTERM, then restore and re-raise the default handler so
    exit codes and parent-process semantics stay exactly as before. Only
    callable from the main thread (signal module rule); silently skipped
    elsewhere."""

    def _on_term(signum, frame):
        recorder.note("signal", signum=int(signum))
        recorder.dump("sigterm")
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        signal.raise_signal(signal.SIGTERM)

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:
        pass  # not the main thread
