"""Device-keyed perf regression gate over bench/serve records.

Five bench rounds produced records (``BENCH_r01..r05.json``) that were all
invalid tunnel-hang diagnostics, and nothing automated ever compared a new
number against the committed baselines — the ROADMAP's "fast as the
hardware allows" north star had no machinery that notices a regression.
This module is that machinery, shared by ``scripts/bench_compare.py`` (the
CI gate) and anything else that wants a verdict:

- **validity** — :func:`record_invalid_reason` distinguishes a real
  measurement from the failure shapes the bench deliberately emits
  (``error`` records, ``implausible``/``clock_suspect`` clock failures,
  value-0.0 watchdog records, withdrawn baselines).
- **comparability** — :func:`comparable_reason` requires the same metric
  label, the same device kind (a CPU-mesh number vs a TPU number is not a
  comparison), the same mesh identity (a sharded record vs a single-device
  one is not a comparison either) and, for train-bench records, the same
  in-graph step count (the timing methodology).
- **thresholds** — per-metric direction + tolerated fractional change;
  anything past tolerance in the bad direction regresses the verdict.

The output is a structured ``pass`` / ``regress`` / ``no-data`` verdict:
``no-data`` (invalid or incomparable records, missing baseline) is an
explicit third state so a broken bench can never silently read as "at
parity". Pure python, no jax — runs host-side in CI.
"""

from __future__ import annotations

from typing import Optional

# name -> (direction, tolerated fractional change vs baseline). "higher"
# means bigger is better (regress when current < (1 - tol) * baseline);
# "lower" means smaller is better (regress when current > (1 + tol) *
# baseline). Latency tolerances are generous: CI runners and the CPU mesh
# are noisy, and the gate must catch real cliffs, not scheduler jitter.
DEFAULT_THRESHOLDS = {
    "value": ("higher", 0.10),
    "mfu": ("higher", 0.15),
    "p50_ms": ("lower", 0.50),
    "p95_ms": ("lower", 0.50),
    "p99_ms": ("lower", 0.50),
    # absolute gate (baseline-independent), serve records only (train
    # records don't carry the key): fraction of the dispatch window the
    # device sat idle, computed from the trace spans
    # (observe.tracing.device_idle_fraction). The pipelined dispatch
    # exists to keep this low on the closed-loop bench — host featurize /
    # transfer / unpad overlapping compute; a pipeline wired wrong (a
    # stage serializing again, a lost overlap) shows up here before it
    # shows up in throughput noise.
    "device_idle_frac": ("absmax", 0.30),
}

# serve-async (open-loop frontend) records: the headline is goodput and
# tail latency under offered load, plus the admission-control outcome —
# each with its own direction so the gate yields real per-metric verdicts
# instead of falling back to no-data on the shape. Tolerances are wider
# still: open-loop records compare across machines (a committed CPU-mesh
# baseline vs a CI runner), where absolute speed legitimately varies —
# the gate exists for order-of-magnitude cliffs (a lost cache, a dwell
# misconfiguration, rejection storms), not machine-to-machine jitter.
SERVE_ASYNC_THRESHOLDS = {
    "value": ("higher", 0.50),  # ok-residues/sec over the open-loop window
    "goodput_rps": ("higher", 0.50),  # completed requests/sec
    "p50_ms": ("lower", 2.00),
    "p95_ms": ("lower", 2.00),
    "p99_ms": ("lower", 2.00),
    "rejection_rate": ("lower", 1.00),
    # per-priority-class tails: the class breakdown is what the SLO specs
    # promise, so a high-class-only regression must not hide in the
    # aggregate (a priority-inversion bug leaves p95_ms flat while
    # p95_ms_high triples)
    "p95_ms_high": ("lower", 2.00),
    "p95_ms_normal": ("lower", 2.00),
    "p95_ms_low": ("lower", 2.50),
    "goodput_rps_high": ("higher", 0.60),
    "goodput_rps_normal": ("higher", 0.60),
    # absolute gates (baseline-independent): the telemetry plane's own
    # contracts. Tracing/SLO/registry accounting may cost <5% goodput, and
    # ≥99% of non-rejected requests must reconstruct a complete trace.
    "telemetry_overhead_frac": ("absmax", 0.05),
    "trace_complete_fraction": ("absmin", 0.99),
    # open-loop device idleness is dominated by the offered arrival rate
    # (the device legitimately waits for Poisson gaps and dwell windows),
    # so the absolute bound is necessarily loose — it exists to catch the
    # pipeline collapsing entirely (idle ~1.0 under saturating load), not
    # to assert continuous occupancy
    "device_idle_frac": ("absmax", 0.90),
}

# mesh-sharded serve records (a "mesh" key beside mode=serve): throughput
# and latency get the wide cross-machine tolerances (the committed baseline
# is a CPU-mesh record; CI runners differ in core count), while the
# per-device program footprint gets a tight-ish one — it is DETERMINISTIC
# per (program, jax version), and a 2x jump is exactly the forgot-the-
# sharding-constraint cliff (an unsharded pair grid on a 2x4 grid mesh is
# 8x per device) this gate exists to catch.
SERVE_MESH_THRESHOLDS = {
    "value": ("higher", 0.60),
    "p50_ms": ("lower", 2.50),
    "p95_ms": ("lower", 2.50),
    "p99_ms": ("lower", 2.50),
    "per_device_program_bytes": ("lower", 1.00),
    # looser than the single-device bound: the CPU mesh's per-dispatch
    # host work (sharded device_puts per axis) is a larger fraction of
    # its window, and the gate targets lost-overlap cliffs, not jitter
    "device_idle_frac": ("absmax", 0.50),
}

# variant-scan fast-lane records (bench.py --mode serve-scan): one parent
# plus a deep-mutational-scan mutant set through the affinity-batched,
# feature-cached frontend vs the same variants dispatched cold one at a
# time. The headline (variants/sec) gets the wide cross-machine tolerance;
# the STRUCTURAL claims are absolute gates judged on the current record
# alone — the amortized speedup over the cold path is the tentpole's >=5x
# acceptance bar, and the reuse ledger must account every dispatched
# request (hits + misses + delta-reuses == featurized requests), because
# an unaccounted ledger means requests silently took the cold path.
SERVE_SCAN_THRESHOLDS = {
    "value": ("higher", 0.50),  # scan-lane variants/sec
    "p50_ms": ("lower", 2.00),
    "p95_ms": ("lower", 2.00),
    # the tentpole bar, absolute: amortized per-variant latency must stay
    # >=5x better than the measured cold path on the same machine — a
    # same-run ratio, so it holds across machine speeds
    "speedup_vs_cold": ("absmin", 5.0),
    "ledger_accounted_frac": ("absmin", 1.0),  # every request accounted
    # scan traffic is near-duplicate by construction: almost everything
    # after the parent must ride the delta/hit lanes (cold misses are the
    # parent plus at most a handful of cache-churn refills)
    "reuse_fraction": ("absmin", 0.90),
}

# kernels microbench (bench.py --mode kernels): fused-vs-stock attention
# timings at fixed shapes. The headline is the geomean speedup (on CPU the
# fused kernels run in Pallas interpret mode, so the committed CPU baseline
# sits well below 1x — the gate watches for CLIFFS in that ratio, e.g. an
# interpret-path blowup or a kernel suddenly falling back to dense, not for
# absolute speed). Wide tolerances: single-shape microbenches on shared CI
# runners are the noisiest records in the tree.
KERNELS_THRESHOLDS = {
    "value": ("higher", 0.50),
    "fused_ms_total": ("lower", 1.50),
    "stock_ms_total": ("lower", 1.50),
}

# workload record→replay records (bench.py --mode serve-replay): a
# recorded (or synthetic-diurnal) request stream replayed against a fresh
# engine in the same process. Throughput/latency ratios get the standard
# wide cross-machine tolerances; the STRUCTURAL claims — the loop this
# mode exists to close — are absolute gates judged on the current record
# alone: the replay must reproduce the recording's feature-reuse ledger
# EXACTLY (ledger_match is 1.0 or the replay is not deterministic), the
# replayed lifecycles must still reconstruct complete traces, and the
# recorder itself (submit hook + resolve hook + JSONL append) may cost
# <=5% goodput measured on/off on a warm engine, exactly like
# telemetry_overhead_frac.
REPLAY_THRESHOLDS = {
    "value": ("higher", 0.50),  # replayed ok-residues/sec
    "goodput_rps": ("higher", 0.50),
    "p50_ms": ("lower", 2.00),
    "p95_ms": ("lower", 2.00),
    "ledger_match": ("absmin", 1.0),  # exact reuse-ledger reproduction
    "replay_bytes_identical": ("absmin", 1.0),  # (seq, seed) determinism
    "trace_complete_fraction": ("absmin", 0.99),
    "recorder_overhead_frac": ("absmax", 0.05),
}


# fleet serving records (bench.py --mode serve-fleet): the same offered
# open-loop stream through N replica cells behind the health-aware
# router. Throughput/latency ratios get the standard wide cross-machine
# tolerances; the STRUCTURAL claims the fleet exists for are absolute
# gates judged on the current record alone — goodput must scale (>= 1.6x
# single-replica at 2 replicas, the tentpole bar), a mid-run replica kill
# must resolve every accepted request (zero silent drops: every handle
# reaches a terminal ServeResult), and the router hop must not break
# trace reconstruction (>= 99% complete end-to-end across the
# traceparent round-trip). Records carry ``replicas`` as a comparability
# variant key: a 2-replica number must never ratio a 4-replica baseline.
# ``thresholds_for`` waives ONLY the speedup floor on single-core hosts
# (record ``host_cpus`` < 2), where replica threads cannot run in
# parallel by construction.
FLEET_THRESHOLDS = {
    "value": ("higher", 0.50),  # fleet ok-residues/sec
    "goodput_rps": ("higher", 0.50),
    "p50_ms": ("lower", 2.00),
    "p95_ms": ("lower", 2.00),
    "fleet_speedup": ("absmin", 1.6),  # N-replica vs 1-replica goodput
    "accepted_unresolved": ("absmax", 0.0),  # drain drill: zero drops
    "dropped_requests": ("absmax", 0.0),
    "trace_complete_fraction": ("absmin", 0.99),  # across the hop
}


def thresholds_for(record) -> dict:
    """The gate's per-metric direction/tolerance table for this record's
    shape (keyed by the record's ``mode`` and mesh identity)."""
    if isinstance(record, dict) and record.get("mode") == "serve-async":
        return SERVE_ASYNC_THRESHOLDS
    if isinstance(record, dict) and record.get("mode") == "serve-fleet":
        # the speedup floor is a statement about replica PARALLELISM:
        # replica dispatchers are OS threads, so a single-core host
        # physically cannot exceed 1x and the floor would only gate the
        # machine, not the router. Zero-drop and trace-completeness stay
        # unconditional — they hold on any host.
        if record.get("host_cpus", 2) < 2:
            return {
                k: v for k, v in FLEET_THRESHOLDS.items()
                if k != "fleet_speedup"
            }
        return FLEET_THRESHOLDS
    if isinstance(record, dict) and record.get("mode") == "serve-scan":
        return SERVE_SCAN_THRESHOLDS
    if isinstance(record, dict) and record.get("mode") == "serve-replay":
        return REPLAY_THRESHOLDS
    if isinstance(record, dict) and record.get("mode") == "kernels":
        return KERNELS_THRESHOLDS
    if isinstance(record, dict) and record.get("mesh"):
        return SERVE_MESH_THRESHOLDS
    return DEFAULT_THRESHOLDS


def record_invalid_reason(rec) -> Optional[str]:
    """Why this record is NOT a usable measurement (None = it is)."""
    if not isinstance(rec, dict):
        return "not a record object"
    if rec.get("error"):
        return f"error record ({str(rec['error'])[:120]})"
    if rec.get("invalid"):
        return "withdrawn/invalid record"
    if rec.get("implausible"):
        return "implausible measurement (clock not syncing with device)"
    if rec.get("clock_suspect"):
        return "clock_suspect measurement (probe failed)"
    if rec.get("liveness") == "dead":
        return "liveness-dead failure record"
    if not rec.get("value"):
        return "no measured value"
    return None


def comparable_reason(current: dict, baseline: dict) -> Optional[str]:
    """Why these two valid records must not be compared (None = they may).

    Comparisons are keyed by metric label (which encodes the measured
    config), device kind, and — for train-bench records — the in-graph step
    count, since changing any of those changes what the number means."""
    if current.get("metric") != baseline.get("metric"):
        return (
            f"metric label mismatch: current={current.get('metric')!r} "
            f"baseline={baseline.get('metric')!r}"
        )
    cur_dev, base_dev = current.get("device"), baseline.get("device")
    if cur_dev and base_dev and cur_dev != base_dev:
        return f"device mismatch: current={cur_dev!r} baseline={base_dev!r}"
    # variant keys records carry only when non-default: mesh identity
    # (sharded serving), serving dtype (bf16 mode), kernel policy
    # (fused Pallas selection) and dispatch pipeline ("depth2"/"off" —
    # pipelined and serial dispatch have different latency anatomy, so a
    # pipelined record must never ratio against a pre-pipeline baseline).
    # A sharded vs single-device number, a bf16 vs f32 one, or two
    # different kernel selections are not comparisons — precision/kernel
    # changes must surface as explicit no-data diffs (and their own
    # baselines), never as silent ratio drift.
    # "scan" fences variant-scan fast-lane records: their value is an
    # amortized near-duplicate-traffic number that must never ratio
    # against a plain serve record (or vice versa). "replay" fences the
    # record→replay loop's knobs the same way — a time-warped or
    # load-scaled replay measures a different offered stream than the
    # flagship synthetic run the baseline committed. "replicas" fences
    # fleet records: goodput through 2 replica cells and through 4 are
    # different machines as far as a ratio is concerned.
    for key in (
        "mesh", "dtype", "kernels", "pipeline", "scan", "replay", "replicas",
    ):
        if current.get(key) != baseline.get(key):
            return (
                f"{key} mismatch: current={current.get(key)!r} "
                f"baseline={baseline.get(key)!r}"
            )
    if "ingraph" in baseline and baseline.get("ingraph") != current.get(
        "ingraph"
    ):
        return (
            f"timing methodology mismatch: ingraph current="
            f"{current.get('ingraph')} baseline={baseline.get('ingraph')}"
        )
    return None


def _compare_one(name, cur, base, direction, tolerance) -> dict:
    if direction in ("absmax", "absmin"):
        # absolute bound on the CURRENT value: "tolerance" is the bound
        # itself and the baseline is informational only — for metrics that
        # are contracts (trace completeness, telemetry overhead), not
        # measurements that drift with the machine
        ok = cur <= tolerance if direction == "absmax" else cur >= tolerance
        return {
            "name": name,
            "current": cur,
            "baseline": base,
            "ratio": None,
            "direction": direction,
            "tolerance": tolerance,
            "ok": bool(ok),
        }
    ratio = cur / base if base else None
    if ratio is None:
        ok = True  # zero/absent baseline value: nothing to gate on
    elif direction == "higher":
        ok = ratio >= 1.0 - tolerance
    else:
        ok = ratio <= 1.0 + tolerance
    return {
        "name": name,
        "current": cur,
        "baseline": base,
        "ratio": round(ratio, 4) if ratio is not None else None,
        "direction": direction,
        "tolerance": tolerance,
        "ok": bool(ok),
    }


def compare(
    current: dict,
    baseline: Optional[dict],
    thresholds: Optional[dict] = None,
) -> dict:
    """Structured verdict of ``current`` against ``baseline``.

    Returns ``{"verdict": "pass"|"regress"|"no-data", ...}`` with a
    ``reason`` for no-data and per-metric ``comparisons`` otherwise. Only
    metrics present in BOTH records and named in ``thresholds`` are gated.
    ``thresholds=None`` routes by the record's shape (:func:`thresholds_for`)
    — serve-async and mesh-serve records get their own tables.
    """
    thresholds = thresholds if thresholds is not None else thresholds_for(current)
    out = {
        "metric": current.get("metric") if isinstance(current, dict) else None,
        "device": current.get("device") if isinstance(current, dict) else None,
    }
    if isinstance(current, dict) and isinstance(
        current.get("slo_alerts"), (int, float)
    ):
        # informational, never gated: a legitimately-firing SLO alert on a
        # fault-injected run must not flap CI, but the verdict should show it
        out["slo_alerts"] = current["slo_alerts"]

    reason = record_invalid_reason(current)
    if reason is not None:
        return {**out, "verdict": "no-data",
                "reason": f"current record invalid: {reason}"}
    if baseline is None:
        return {**out, "verdict": "no-data", "reason": "missing baseline"}
    reason = record_invalid_reason(baseline)
    if reason is not None:
        return {**out, "verdict": "no-data",
                "reason": f"baseline record invalid: {reason}"}
    reason = comparable_reason(current, baseline)
    if reason is not None:
        return {**out, "verdict": "no-data",
                "reason": f"not comparable: {reason}"}

    comparisons = []
    for name, (direction, tolerance) in thresholds.items():
        cur, base = current.get(name), baseline.get(name)
        if not isinstance(cur, (int, float)):
            continue
        if direction in ("absmax", "absmin"):
            # absolute gates judge the current record alone; an older
            # baseline without the metric must not disable the contract
            comparisons.append(_compare_one(
                name, float(cur),
                float(base) if isinstance(base, (int, float)) else None,
                direction, tolerance,
            ))
            continue
        if not isinstance(base, (int, float)):
            continue
        comparisons.append(
            _compare_one(name, float(cur), float(base), direction, tolerance)
        )
    if not comparisons:
        return {**out, "verdict": "no-data",
                "reason": "no shared gated metrics between the records"}
    regressions = [c["name"] for c in comparisons if not c["ok"]]
    return {
        **out,
        "verdict": "regress" if regressions else "pass",
        "comparisons": comparisons,
        "regressions": regressions,
    }


def parse_threshold_overrides(items, base: Optional[dict] = None) -> dict:
    """CLI ``metric=tolerance`` (keep the default direction) or
    ``metric=direction:tolerance`` overrides onto a copy of the defaults."""
    out = dict(base if base is not None else DEFAULT_THRESHOLDS)
    for item in items or ():
        name, _, spec = item.partition("=")
        if not spec:
            raise ValueError(
                f"bad threshold {item!r}; expected metric=tol or "
                "metric=direction:tol"
            )
        direction, _, tol = spec.rpartition(":")
        if not direction:
            direction = out.get(name, ("higher", 0.0))[0]
        if direction not in ("higher", "lower", "absmax", "absmin"):
            raise ValueError(f"bad direction {direction!r} in {item!r}")
        out[name] = (direction, float(tol))
    return out
