"""Span tracing emitted as Chrome-trace-event JSONL.

``Tracer.span("serve.dispatch", bucket=32)`` times a nested region and
emits one complete ("ph": "X") trace event per span; the output file loads
directly in Perfetto / ``chrome://tracing`` (the file opens with ``[`` and
the trace-event spec makes the closing ``]`` optional, so the format is
simultaneously a streaming JSONL-per-line file and a valid JSON-array
trace). Nesting is inferred by the viewer from ts/dur overlap within a
thread — no explicit parent ids needed.

A disabled tracer (no path, ``enabled=False``) is a near-zero-cost no-op,
so instrumentation can stay permanently wired through hot paths (the serve
engine, the train step) and be switched on per run.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Optional

# one timeline origin per process: spans from every tracer share it, so a
# serve-engine trace and a bench-stage trace interleave correctly
_PROC_T0 = time.perf_counter()


def _now_us() -> float:
    return (time.perf_counter() - _PROC_T0) * 1e6


class Span:
    """Handle yielded by ``Tracer.span``: attach args mid-flight via
    ``set(key=value)`` (e.g. the compile-cache verdict known only at the
    end of the region)."""

    __slots__ = ("name", "args", "duration_s")

    def __init__(self, name: str, args: dict):
        self.name = name
        self.args = args
        self.duration_s = 0.0

    def set(self, **kw) -> "Span":
        self.args.update(kw)
        return self


class _NullSpan:
    __slots__ = ()

    def set(self, **kw):
        return self


_NULL_SPAN = _NullSpan()


class Tracer:
    """Thread-safe span tracer writing Chrome trace events.

    ``path=None`` keeps events only in memory (tests, ``span_totals``);
    ``enabled=False`` disables everything. Events are flushed to the file
    as they complete, so a killed process still leaves a loadable trace.
    """

    def __init__(self, path: Optional[str] = None,
                 enabled: Optional[bool] = None):
        self.enabled = bool(path) if enabled is None else bool(enabled)
        self._path = path
        self._lock = threading.Lock()
        self._events: list = []
        self._file = None
        if self.enabled and path:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            self._file = open(path, "w")
            self._file.write("[\n")
            self._file.flush()

    @classmethod
    def from_env(cls, var: str = "AF2TPU_TRACE_EVENTS") -> "Tracer":
        """Tracer writing to $AF2TPU_TRACE_EVENTS, disabled when unset."""
        return cls(path=os.environ.get(var) or None)

    # ------------------------------------------------------------- emission

    def _emit(self, event: dict) -> None:
        with self._lock:
            self._events.append(event)
            if self._file is not None:
                self._file.write(json.dumps(event) + ",\n")
                self._file.flush()

    @contextmanager
    def span(self, name: str, **args):
        """Time a region; emits one complete event on exit (exceptions
        included — a span that dies still appears, flagged ``error``)."""
        if not self.enabled:
            yield _NULL_SPAN
            return
        sp = Span(name, dict(args))
        t0 = _now_us()
        try:
            yield sp
        except BaseException as e:
            sp.args["error"] = type(e).__name__
            raise
        finally:
            t1 = _now_us()
            sp.duration_s = (t1 - t0) / 1e6
            self._emit({
                "name": name, "ph": "X", "ts": round(t0, 1),
                "dur": round(t1 - t0, 1), "pid": os.getpid(),
                "tid": threading.get_ident(),
                **({"args": sp.args} if sp.args else {}),
            })

    def instant(self, name: str, **args) -> None:
        """A zero-duration marker event (ph "i")."""
        if not self.enabled:
            return
        self._emit({
            "name": name, "ph": "i", "ts": round(_now_us(), 1), "s": "p",
            "pid": os.getpid(), "tid": threading.get_ident(),
            **({"args": dict(args)} if args else {}),
        })

    def counter(self, name: str, **values) -> None:
        """A counter sample event (ph "C") — e.g. HBM bytes over time."""
        if not self.enabled:
            return
        self._emit({
            "name": name, "ph": "C", "ts": round(_now_us(), 1),
            "pid": os.getpid(), "args": dict(values),
        })

    # ------------------------------------------------------------ summaries

    def events(self) -> list:
        with self._lock:
            return list(self._events)

    def span_totals(self) -> dict:
        """Per-span-name aggregate: {name: {count, total_s, max_s}} over the
        complete ("X") events seen so far — the bench records embed this as
        the per-stage timing breakdown."""
        out: dict = {}
        for e in self.events():
            if e.get("ph") != "X":
                continue
            agg = out.setdefault(
                e["name"], {"count": 0, "total_s": 0.0, "max_s": 0.0}
            )
            dur_s = e.get("dur", 0.0) / 1e6
            agg["count"] += 1
            agg["total_s"] = round(agg["total_s"] + dur_s, 6)
            agg["max_s"] = round(max(agg["max_s"], dur_s), 6)
        return out

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.flush()
                self._file.close()
                self._file = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def load_trace_events(path: str) -> list:
    """Parse a trace file written by ``Tracer`` (or any Chrome trace-event
    JSON array). Tolerates the streaming form: leading ``[``, one event per
    line with a trailing comma, no closing ``]``."""
    with open(path) as f:
        text = f.read().strip()
    if not text:
        return []
    try:  # a well-formed JSON array (or {"traceEvents": [...]})
        doc = json.loads(text)
        if isinstance(doc, dict):
            return doc.get("traceEvents", [])
        return doc
    except json.JSONDecodeError:
        pass
    events = []
    for line in text.splitlines():
        line = line.strip().rstrip(",")
        if not line or line in ("[", "]"):
            continue
        events.append(json.loads(line))
    return events
