"""Span tracing emitted as Chrome-trace-event JSONL.

``Tracer.span("serve.dispatch", bucket=32)`` times a nested region and
emits one complete ("ph": "X") trace event per span; the output file loads
directly in Perfetto / ``chrome://tracing`` (the file opens with ``[`` and
the trace-event spec makes the closing ``]`` optional, so the format is
simultaneously a streaming JSONL-per-line file and a valid JSON-array
trace). Nesting is inferred by the viewer from ts/dur overlap within a
thread — no explicit parent ids needed.

A disabled tracer (no path, ``enabled=False``) is a near-zero-cost no-op,
so instrumentation can stay permanently wired through hot paths (the serve
engine, the train step) and be switched on per run.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Optional, Tuple

from alphafold2_tpu.observe.tracectx import current_trace, use_trace

# one timeline origin per process: spans from every tracer share it, so a
# serve-engine trace and a bench-stage trace interleave correctly
_PROC_T0 = time.perf_counter()


def _now_us() -> float:
    return (time.perf_counter() - _PROC_T0) * 1e6


class Span:
    """Handle yielded by ``Tracer.span``: attach args mid-flight via
    ``set(key=value)`` (e.g. the compile-cache verdict known only at the
    end of the region)."""

    __slots__ = ("name", "args", "duration_s")

    def __init__(self, name: str, args: dict):
        self.name = name
        self.args = args
        self.duration_s = 0.0

    def set(self, **kw) -> "Span":
        self.args.update(kw)
        return self


class _NullSpan:
    __slots__ = ()

    def set(self, **kw):
        return self


_NULL_SPAN = _NullSpan()


class Tracer:
    """Thread-safe span tracer writing Chrome trace events.

    ``path=None`` keeps events only in memory (tests, ``span_totals``);
    ``enabled=False`` disables everything. Events are flushed to the file
    as they complete, so a killed process still leaves a loadable trace.
    """

    def __init__(self, path: Optional[str] = None,
                 enabled: Optional[bool] = None):
        self.enabled = bool(path) if enabled is None else bool(enabled)
        self._path = path
        self._lock = threading.Lock()
        self._events: list = []
        self._sinks: list = []  # e.g. the flight recorder's ring buffer
        self._file = None
        if self.enabled and path:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            self._file = open(path, "w")
            self._file.write("[\n")
            self._file.flush()

    @classmethod
    def from_env(cls, var: str = "AF2TPU_TRACE_EVENTS") -> "Tracer":
        """Tracer writing to $AF2TPU_TRACE_EVENTS, disabled when unset."""
        return cls(path=os.environ.get(var) or None)

    # ------------------------------------------------------------- emission

    def add_sink(self, sink) -> None:
        """Register a callback receiving every emitted event dict (the
        flight recorder's ring buffer attaches here). Sinks are invoked
        *outside* the tracer lock from a per-event snapshot, so a slow or
        re-entrant sink cannot stall or deadlock emitters."""
        with self._lock:
            if sink not in self._sinks:
                self._sinks.append(sink)

    def _emit(self, event: dict) -> None:
        # record + persist under the lock; snapshot the sink list and
        # invoke outside it (a sink that emits, or blocks, must not hold
        # every other emitting thread hostage)
        with self._lock:
            self._events.append(event)
            sinks = list(self._sinks)
            if self._file is not None:
                self._file.write(json.dumps(event) + ",\n")
                self._file.flush()
        for sink in sinks:
            try:
                sink(event)
            except Exception:
                pass  # a broken sink must never lose the trace itself

    @contextmanager
    def span(self, name: str, **args):
        """Time a region; emits one complete event on exit (exceptions
        included — a span that dies still appears, flagged ``error``).

        When a :mod:`tracectx` context is active on this thread (and the
        caller didn't attach ids explicitly), a child context is minted
        for the region — nested spans chain parent ids automatically and
        every event carries its owning ``trace_id``."""
        if not self.enabled:
            yield _NULL_SPAN
            return
        sp = Span(name, dict(args))
        ctx = None
        if "trace_id" not in sp.args:
            cur = current_trace()
            if cur is not None:
                ctx = cur.child()
                sp.args.update(ctx.event_args())
        t0 = _now_us()
        try:
            if ctx is not None:
                with use_trace(ctx):
                    yield sp
            else:
                yield sp
        except BaseException as e:
            sp.args["error"] = type(e).__name__
            raise
        finally:
            t1 = _now_us()
            sp.duration_s = (t1 - t0) / 1e6
            self._emit({
                "name": name, "ph": "X", "ts": round(t0, 1),
                "dur": round(t1 - t0, 1), "pid": os.getpid(),
                "tid": threading.get_ident(),
                **({"args": sp.args} if sp.args else {}),
            })

    def span_event(self, name: str, t0_s: float, t1_s: float, **args) -> None:
        """Emit a complete span with EXPLICIT bounds (``time.perf_counter``
        seconds) — for retroactive regions whose start predates the call,
        e.g. the scheduler's per-request queue-residency span, known only
        when the batch forms."""
        if not self.enabled:
            return
        ts = (t0_s - _PROC_T0) * 1e6
        dur = max(0.0, (t1_s - t0_s) * 1e6)
        self._emit({
            "name": name, "ph": "X", "ts": round(ts, 1),
            "dur": round(dur, 1), "pid": os.getpid(),
            "tid": threading.get_ident(),
            **({"args": dict(args)} if args else {}),
        })

    def instant(self, name: str, **args) -> None:
        """A zero-duration marker event (ph "i"). Auto-attaches the
        thread's active trace context like :meth:`span` (no child mint —
        an instant is a point, not a region)."""
        if not self.enabled:
            return
        if "trace_id" not in args:
            cur = current_trace()
            if cur is not None:
                args = {**args, **cur.event_args()}
        self._emit({
            "name": name, "ph": "i", "ts": round(_now_us(), 1), "s": "p",
            "pid": os.getpid(), "tid": threading.get_ident(),
            **({"args": dict(args)} if args else {}),
        })

    def counter(self, name: str, **values) -> None:
        """A counter sample event (ph "C") — e.g. HBM bytes over time."""
        if not self.enabled:
            return
        self._emit({
            "name": name, "ph": "C", "ts": round(_now_us(), 1),
            "pid": os.getpid(), "args": dict(values),
        })

    # ------------------------------------------------------------ summaries

    def events(self) -> list:
        with self._lock:
            return list(self._events)

    def span_totals(self) -> dict:
        """Per-span-name aggregate: {name: {count, total_s, max_s}} over the
        complete ("X") events seen so far — the bench records embed this as
        the per-stage timing breakdown."""
        out: dict = {}
        for e in self.events():
            if e.get("ph") != "X":
                continue
            agg = out.setdefault(
                e["name"], {"count": 0, "total_s": 0.0, "max_s": 0.0}
            )
            dur_s = e.get("dur", 0.0) / 1e6
            agg["count"] += 1
            agg["total_s"] = round(agg["total_s"] + dur_s, 6)
            agg["max_s"] = round(max(agg["max_s"], dur_s), 6)
        return out

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.flush()
                self._file.close()
                self._file = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# span names whose duration means "the device is (or is being kept) busy":
# serve.dispatch covers executable submission through (sync path) blocking
# execution; serve.device_get blocks until execution drains and results
# land on the host, so its extent covers the async execution tail too
DEVICE_SPAN_NAMES = ("serve.dispatch", "serve.device_get")


def merge_intervals(intervals) -> list:
    """Union a list of (start, end) intervals into disjoint sorted spans."""
    merged: list = []
    for start, end in sorted((s, e) for s, e in intervals if e > s):
        if merged and start <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], end))
        else:
            merged.append((start, end))
    return merged


def device_idle_fraction(events, names=DEVICE_SPAN_NAMES) -> Optional[dict]:
    """Device idle fraction over a serve trace: 1 - (union of device-busy
    span extents) / (window from first device span start to last end).

    The pipeline's whole point is to shrink this number — host featurize /
    device_put / unpad overlapping with compute shows up directly as busy
    spans tiling the window. Computed from the same trace events the
    Chrome timeline renders, so the metric and the picture can't diverge.
    Returns ``{"device_idle_frac", "busy_s", "window_s", "dispatches"}``,
    or None when the trace holds no ``serve.dispatch`` span (nothing was
    dispatched — an idle fraction would be meaningless).
    """
    intervals = []
    dispatches = 0
    for e in events:
        if e.get("ph") != "X" or e.get("name") not in names:
            continue
        ts = e.get("ts", 0.0)
        intervals.append((ts / 1e6, (ts + e.get("dur", 0.0)) / 1e6))
        if e.get("name") == "serve.dispatch":
            dispatches += 1
    if not dispatches or not intervals:
        return None
    lo = min(s for s, _ in intervals)
    hi = max(e for _, e in intervals)
    window = hi - lo
    busy = sum(e - s for s, e in merge_intervals(intervals))
    idle = max(0.0, 1.0 - busy / window) if window > 0 else 0.0
    return {
        "device_idle_frac": round(idle, 4),
        "busy_s": round(busy, 6),
        "window_s": round(window, 6),
        "dispatches": dispatches,
    }


def load_trace_events(path: str) -> list:
    """Parse a trace file written by ``Tracer`` (or any Chrome trace-event
    JSON array). Tolerates the streaming form: leading ``[``, one event per
    line with a trailing comma, no closing ``]``. Raises on malformed
    lines; use :func:`load_trace_events_lenient` to collect them instead."""
    events, errors = load_trace_events_lenient(path)
    if errors:
        raise json.JSONDecodeError(
            f"{len(errors)} malformed trace line(s) in {path} "
            f"(first: {errors[0]})",
            doc="", pos=0,
        )
    return events


def load_trace_events_lenient(path: str) -> Tuple[list, list]:
    """Like :func:`load_trace_events`, but a truncated/malformed line
    (killed writer mid-flush, disk-full tail) becomes an entry in the
    returned error list instead of an exception mid-parse — every parseable
    event is still returned. Returns ``(events, errors)`` where each error
    is a ``"line N: <detail>"`` string."""
    with open(path) as f:
        text = f.read().strip()
    if not text:
        return [], []
    try:  # a well-formed JSON array (or {"traceEvents": [...]})
        doc = json.loads(text)
        if isinstance(doc, dict):
            doc = doc.get("traceEvents", [])
        if isinstance(doc, list):
            return doc, []
        return [], [f"line 1: top-level {type(doc).__name__}, not a list"]
    except json.JSONDecodeError:
        pass
    events, errors = [], []
    for lineno, line in enumerate(text.splitlines(), start=1):
        line = line.strip().rstrip(",")
        if not line or line in ("[", "]"):
            continue
        try:
            event = json.loads(line)
        except json.JSONDecodeError as e:
            errors.append(f"line {lineno}: {e.msg} ({line[:60]!r})")
            continue
        if isinstance(event, dict):
            events.append(event)
        else:
            errors.append(
                f"line {lineno}: event is {type(event).__name__}, not dict"
            )
    return events, errors
