"""MetricsRegistry: named counters/gauges/windowed series with snapshots.

``EventCounters`` (metrics.py) is a flat monotonic counter map and
``Histogram`` (histogram.py) a process-lifetime distribution — neither can
answer "what was the error rate over the LAST minute", which is the shape
SLO burn-rate math (observe/slo.py) and a fleet scheduler's scrape both
need. This registry adds the time axis:

- :class:`Counter` / :class:`Gauge` — plain named scalars.
- :class:`WindowedCounter` — per-second buckets over a bounded horizon:
  ``sum(window_s)`` / ``rate(window_s)`` answer rolling-rate questions in
  O(window) with O(horizon) memory, however long the process lives.
- :class:`WindowedValues` — a bounded deque of (t, value) samples with
  windowed percentile snapshots (p50/p95/p99) — per-priority-class rolling
  latency for the SLO monitor.
- :class:`MetricsRegistry` — the named registry over all four, one lock,
  ``snapshot()`` as a flat dict. ``start_snapshotter`` emits periodic
  snapshots through the existing :class:`~alphafold2_tpu.observe.metrics.
  MetricsLogger` JSONL channel (and any extra callback, e.g. the flight
  recorder's ring buffer); ``observe/exposition.py`` renders the same
  snapshot as Prometheus text.

Injectable ``clock`` (default ``time.monotonic``) keeps every window
deterministic under the fake-clock tests. Pure stdlib, jax-free.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional


class Counter:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> float:
        with self._lock:
            self._value += n
            return self._value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class WindowedCounter:
    """Per-second time buckets over a bounded horizon. ``add`` lands in
    the current second's bucket; ``sum(window_s)`` totals the buckets
    inside the window. Buckets past the horizon are pruned on touch, so
    memory is O(horizon) regardless of process lifetime."""

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 horizon_s: float = 3600.0):
        self._clock = clock
        self._horizon = float(horizon_s)
        self._buckets: dict = {}  # int(second) -> float
        self._total = 0.0
        self._lock = threading.Lock()

    def _prune_locked(self, now: float) -> None:
        floor = int(now - self._horizon)
        if len(self._buckets) > self._horizon + 2:
            for sec in [s for s in self._buckets if s < floor]:
                del self._buckets[sec]

    def add(self, n: float = 1.0) -> None:
        now = self._clock()
        with self._lock:
            sec = int(now)
            self._buckets[sec] = self._buckets.get(sec, 0.0) + n
            self._total += n
            self._prune_locked(now)

    def sum(self, window_s: float) -> float:
        now = self._clock()
        floor = now - float(window_s)
        with self._lock:
            return sum(
                v for sec, v in self._buckets.items() if sec + 1 > floor
            )

    def rate(self, window_s: float) -> float:
        w = max(1e-9, float(window_s))
        return self.sum(w) / w

    @property
    def total(self) -> float:
        with self._lock:
            return self._total


class WindowedValues:
    """Bounded (t, value) samples with windowed percentile snapshots.
    ``maxlen`` bounds memory; within the window the newest ``maxlen``
    samples are exact, which is the accuracy an SLO verdict needs."""

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 maxlen: int = 4096):
        self._clock = clock
        self._samples: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._samples.append((self._clock(), float(value)))

    def values(self, window_s: Optional[float] = None) -> list:
        with self._lock:
            if window_s is None:
                return [v for _, v in self._samples]
            floor = self._clock() - float(window_s)
            return [v for t, v in self._samples if t >= floor]

    def snapshot(self, window_s: Optional[float] = None,
                 digits: int = 4) -> dict:
        vals = sorted(self.values(window_s))
        if not vals:
            return {"count": 0}
        n = len(vals)

        def pct(p: float) -> float:
            return round(vals[min(n - 1, int(p * n))], digits)

        return {
            "count": n,
            "p50": pct(0.50),
            "p95": pct(0.95),
            "p99": pct(0.99),
            "max": round(vals[-1], digits),
        }


class MetricsRegistry:
    """Named registry over Counter/Gauge/WindowedCounter/WindowedValues.

    ``counter(name)`` et al. get-or-create (a name is one kind forever —
    mixing kinds under one name raises). ``snapshot()`` flattens to plain
    floats: counters/gauges by name, windowed counters as
    ``name.rate_<window>s``, windowed values as ``name.p50/p95/p99``."""

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 snapshot_windows=(60.0,)):
        self._clock = clock
        self._snapshot_windows = tuple(snapshot_windows)
        self._metrics: dict = {}  # name -> (kind, obj)
        self._lock = threading.Lock()
        self._snap_thread: Optional[threading.Thread] = None
        self._snap_stop = threading.Event()

    def _get(self, name: str, kind: str, factory):
        with self._lock:
            hit = self._metrics.get(name)
            if hit is not None:
                if hit[0] != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as {hit[0]}, "
                        f"not {kind}"
                    )
                return hit[1]
            obj = factory()
            self._metrics[name] = (kind, obj)
            return obj

    def counter(self, name: str) -> Counter:
        return self._get(name, "counter", Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, "gauge", Gauge)

    def windowed_counter(self, name: str,
                         horizon_s: float = 3600.0) -> WindowedCounter:
        return self._get(
            name, "windowed_counter",
            lambda: WindowedCounter(clock=self._clock, horizon_s=horizon_s),
        )

    def windowed_values(self, name: str,
                        maxlen: int = 4096) -> WindowedValues:
        return self._get(
            name, "windowed_values",
            lambda: WindowedValues(clock=self._clock, maxlen=maxlen),
        )

    def snapshot(self) -> dict:
        with self._lock:
            items = list(self._metrics.items())
        out: dict = {}
        for name, (kind, obj) in items:
            if kind in ("counter", "gauge"):
                out[name] = obj.value
            elif kind == "windowed_counter":
                out[f"{name}.total"] = obj.total
                for w in self._snapshot_windows:
                    out[f"{name}.rate_{w:g}s"] = round(obj.rate(w), 6)
            else:  # windowed_values
                snap = obj.snapshot(
                    self._snapshot_windows[0]
                    if self._snapshot_windows else None
                )
                for k, v in snap.items():
                    out[f"{name}.{k}"] = v
        return out

    # ---------------------------------------------------------- snapshotter

    def start_snapshotter(
        self,
        logger,
        period_s: float = 1.0,
        also: Optional[Callable[[dict], None]] = None,
    ) -> None:
        """Periodic JSONL snapshots through a MetricsLogger (and ``also``,
        e.g. the flight recorder). Daemon thread; one per registry."""
        if self._snap_thread is not None:
            return
        self._snap_stop.clear()

        def _run():
            step = 0
            while not self._snap_stop.wait(period_s):
                step += 1
                snap = self.snapshot()
                try:
                    if logger is not None:
                        logger.log(step, {"registry": 1, **snap})
                    if also is not None:
                        also(snap)
                except Exception:
                    pass  # telemetry must never take the serving path down

        self._snap_thread = threading.Thread(
            target=_run, name="af2-metrics-snapshot", daemon=True
        )
        self._snap_thread.start()

    def stop_snapshotter(self, timeout: float = 5.0) -> None:
        if self._snap_thread is None:
            return
        self._snap_stop.set()
        self._snap_thread.join(timeout)
        self._snap_thread = None
