"""In-graph numerics telemetry: per-tensor stats as auxiliary jit outputs.

The jitted programs are otherwise a black box: when the train step detects
non-finite gradients it skips the update, but nothing says WHICH tensor went
non-finite first, and nothing records the activation/gradient/param-norm
trajectories that show a run going unhealthy before it diverges. This module
is the in-graph half of the answer:

- ``tag(name, x)`` is an identity that, while a :func:`collect` context is
  active on the tracing thread, records per-tensor statistics (L2 norm,
  max-abs over finite entries, NaN/Inf counts). Tags are permanently wired
  through the model (trunk layer boundaries, embeddings, the distogram
  head and loss) and cost **zero ops** when no collector is active — the
  jaxpr is identical to untagged code, so instrumentation can ship in hot
  paths.
- Collection must live INSIDE the traced function (stats become part of its
  returned pytree, typically via ``value_and_grad(..., has_aux=True)``);
  ``jax.jit`` caches by function identity, so a tagged and an untagged step
  must be two different functions — see ``train.loop.make_train_step``
  (``numerics="full"``) and ``make_triage_step``.
- Tag order is trace-execution order, i.e. topological order of the
  program: :func:`first_nonfinite` over a stats dict names the first tensor
  that went bad, which is what the NaN-triage report is built on.

Host-side helpers (:func:`triage_report`, :func:`flatten_stats`,
:func:`counters_to_tracer`) push the same ``numerics/<name>/<stat>``
vocabulary into ``MetricsLogger`` JSONL and the ``Tracer`` span stream, so
``metrics.jsonl`` and a Perfetto trace describe tensors with the same names.

jax is imported lazily so ``alphafold2_tpu.observe`` stays importable by
host-side tools (``scripts/obs_report.py``) without a jax backend.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Optional

STAT_KEYS = ("l2", "max_abs", "nan_count", "inf_count")


def tensor_stats(x) -> dict:
    """Per-tensor health statistics, computed in float32.

    ``l2`` and ``max_abs`` are over the FINITE entries only (a single Inf
    would otherwise wash out the magnitude signal); non-finites are counted
    separately in ``nan_count`` / ``inf_count``.
    """
    import jax.numpy as jnp

    xf = jnp.asarray(x).astype(jnp.float32)
    finite = jnp.isfinite(xf)
    safe = jnp.where(finite, xf, 0.0)
    return {
        "l2": jnp.sqrt(jnp.sum(safe * safe)),
        "max_abs": jnp.max(jnp.abs(safe), initial=0.0),
        "nan_count": jnp.sum(jnp.isnan(xf)).astype(jnp.int32),
        "inf_count": jnp.sum(jnp.isinf(xf)).astype(jnp.int32),
    }


def tree_stats(tree) -> dict:
    """:func:`tensor_stats` over a whole pytree (e.g. one parameter group's
    gradients): l2 combines as a global norm, max/counts combine across
    leaves."""
    import jax
    import jax.numpy as jnp

    per = [tensor_stats(leaf) for leaf in jax.tree.leaves(tree)]
    if not per:
        z = jnp.zeros((), jnp.float32)
        return {"l2": z, "max_abs": z,
                "nan_count": z.astype(jnp.int32),
                "inf_count": z.astype(jnp.int32)}
    return {
        "l2": jnp.sqrt(sum(s["l2"] ** 2 for s in per)),
        "max_abs": jnp.max(jnp.stack([s["max_abs"] for s in per])),
        "nan_count": sum(s["nan_count"] for s in per),
        "inf_count": sum(s["inf_count"] for s in per),
    }


class Collector:
    """Accumulates ``{name: tensor_stats}`` in tag order. Repeated names
    (a module applied twice in one trace) are disambiguated as ``name#2``,
    ``name#3``, ... Each entry carries an explicit ``index`` (its tag
    position): jax sorts dict keys at the jit boundary, so python dict
    insertion order does NOT survive a jitted return — the index is what
    preserves topological order for :func:`first_nonfinite`."""

    def __init__(self):
        self._stats: dict = {}

    def record(self, name: str, x) -> None:
        base, n = name, 1
        while name in self._stats:
            n += 1
            name = f"{base}#{n}"
        self._stats[name] = {"index": len(self._stats), **tensor_stats(x)}

    def stats(self) -> dict:
        return dict(self._stats)


class _ThreadState(threading.local):
    collector: Optional[Collector] = None


_STATE = _ThreadState()


def tag(name: str, x):
    """Identity on ``x``; records its stats when collection is active on
    this (tracing) thread. Safe to leave permanently in model code — with
    no active collector it adds nothing to the jaxpr."""
    col = _STATE.collector
    if col is not None:
        col.record(name, x)
    return x


@contextmanager
def collect(enabled: bool = True):
    """Activate stat collection for tags fired within the block.

    Must be entered INSIDE the function being traced, with the collector's
    ``stats()`` included in that function's return value — stats are traced
    arrays and cannot escape the trace any other way. ``enabled=False``
    yields an inert collector (``stats() == {}``) so call sites can keep a
    single code path.
    """
    if not enabled:
        yield Collector()
        return
    prev = _STATE.collector
    col = Collector()
    _STATE.collector = col
    try:
        yield col
    finally:
        _STATE.collector = prev


# --------------------------------------------------------------- host side


def stats_to_host(stats: dict) -> dict:
    """Device/traced scalars -> plain python floats (fetches values)."""
    return {
        name: {k: float(v) for k, v in s.items()}
        for name, s in stats.items()
    }


def _ordered(stats: dict):
    """Items in topological (tag) order via the recorded ``index`` — dict
    order is unreliable after a round-trip through jit's sorted pytrees."""
    return sorted(
        stats.items(), key=lambda kv: float(kv[1].get("index", 0))
    )


def first_nonfinite(stats: dict) -> Optional[str]:
    """Name of the first tensor (in tag = topological order) with any
    NaN/Inf entries; None when everything is finite."""
    for name, s in _ordered(stats):
        if float(s.get("nan_count", 0)) or float(s.get("inf_count", 0)):
            return name
    return None


def flatten_stats(stats: dict, prefix: str = "numerics") -> dict:
    """``{"numerics/<name>/<stat>": float}`` — the flat vocabulary shared by
    metrics.jsonl records and trace counter events (the ordering ``index``
    is bookkeeping, not a metric, and is dropped)."""
    return {
        f"{prefix}/{name}/{k}": float(v)
        for name, s in stats.items()
        for k, v in s.items()
        if k != "index"
    }


def triage_report(stats: dict, step: Optional[int] = None) -> dict:
    """Structured NaN-triage record: which tensor went non-finite first
    (topological order), every non-finite tensor, and the full stat table."""
    host = stats_to_host(stats)
    bad = [
        name for name, s in _ordered(host)
        if s.get("nan_count") or s.get("inf_count")
    ]
    return {
        "event": "nan_triage",
        **({"step": int(step)} if step is not None else {}),
        "first_nonfinite": bad[0] if bad else None,
        "nonfinite": bad,
        "tensors": host,
    }


def counters_to_tracer(stats: dict, tracer, prefix: str = "numerics") -> None:
    """Emit one Chrome trace counter event per tagged tensor, same
    ``numerics/<name>`` vocabulary as :func:`flatten_stats`."""
    if tracer is None or not getattr(tracer, "enabled", False):
        return
    for name, s in stats.items():
        tracer.counter(
            f"{prefix}/{name}",
            **{k: float(v) for k, v in s.items() if k != "index"},
        )
