"""Device memory telemetry via ``device.memory_stats()``.

TPU/GPU PJRT backends expose per-device allocator stats (``bytes_in_use``,
``peak_bytes_in_use``, ``bytes_limit``); the CPU backend exposes none (or
an empty dict depending on jax version). The sampler degrades to a no-op
there — serving code can call it unconditionally and a CPU-mesh run simply
records no HBM numbers instead of crashing.
"""

from __future__ import annotations

from typing import Optional

_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


class MemorySampler:
    """Samples HBM usage across jax devices.

    ``sample()`` returns one record per device that exposes stats (empty
    list on CPU); ``peak_bytes()`` is the max ``peak_bytes_in_use`` across
    devices; ``log_to`` emits a summary through a MetricsLogger and
    ``counter_to`` a Chrome counter event through a Tracer, so traces show
    HBM alongside the spans that allocated it."""

    def __init__(self, devices=None):
        self._devices = devices

    def _get_devices(self):
        if self._devices is not None:
            return self._devices
        try:
            import jax

            return jax.devices()
        except Exception:
            return []

    def sample(self) -> list:
        records = []
        for d in self._get_devices():
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue  # backend exposes no allocator stats (CPU)
            rec = {"device": str(getattr(d, "id", d))}
            for k in _KEYS:
                if k in stats:
                    rec[k] = int(stats[k])
            if len(rec) > 1:
                records.append(rec)
        return records

    def peak_bytes(self) -> Optional[int]:
        peaks = [
            r["peak_bytes_in_use"] for r in self.sample()
            if "peak_bytes_in_use" in r
        ]
        return max(peaks) if peaks else None

    def log_to(self, logger, step: int = 0, per_device: bool = False) -> None:
        """Emit an HBM summary record; ``per_device=True`` additionally
        logs one ``hbm/device<N>/peak_bytes`` key per device — the
        mesh-serving view (obs_report's sharding section reads these), so
        an uneven shard (one device holding the unsharded pair grid) is
        visible instead of averaged away."""
        records = self.sample()
        if not records:
            return
        summary = {
            "hbm_peak_bytes": max(
                r.get("peak_bytes_in_use", 0) for r in records
            ),
            "hbm_in_use_bytes": max(
                r.get("bytes_in_use", 0) for r in records
            ),
            "hbm_devices": len(records),
        }
        if per_device:
            for r in records:
                if "peak_bytes_in_use" in r:
                    summary[f"hbm/device{r['device']}/peak_bytes"] = r[
                        "peak_bytes_in_use"
                    ]
        logger.log(step, summary)

    def counter_to(self, tracer) -> None:
        for r in self.sample():
            tracer.counter(
                f"hbm.device{r['device']}",
                bytes_in_use=r.get("bytes_in_use", 0),
                peak_bytes_in_use=r.get("peak_bytes_in_use", 0),
            )
