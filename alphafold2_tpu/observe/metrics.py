"""Structured metrics: JSONL logging and thread-safe event counters.

``MetricsLogger`` is the step-axis channel (one JSON record per step,
greppable/plottable); ``EventCounters`` is the event-axis channel (named
monotonic counters without a step: compile counts, cache hits, request
totals). Both are construction-safe without a jax backend so host-side
tools (``scripts/obs_report.py``, tests) can use them.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional


class MetricsLogger:
    """JSONL + stdout metrics.

    In multi-host runs only process 0 logs — otherwise every host appends
    to the same metrics.jsonl on shared storage (duplicated and potentially
    interleaved records). ``enabled`` overrides that decision explicitly:
    pass ``True``/``False`` to construct the logger without touching jax at
    all (non-JAX tools, tests, code running before jax.distributed is
    initialized — ``jax.process_index()`` on an uninitialized distributed
    runtime can itself trigger backend init or raise)."""

    def __init__(
        self,
        directory: Optional[str] = None,
        filename: str = "metrics.jsonl",
        enabled: Optional[bool] = None,
        echo: bool = True,
    ):
        # echo=False keeps stdout clean (bench.py's one-JSON-line contract:
        # the driver parses stdout, so telemetry goes to the file only)
        self._echo = echo
        if enabled is None:
            try:
                import jax

                enabled = jax.process_index() == 0
            except Exception:
                # no jax / no initialized backend: a single-process tool —
                # logging from it is always safe
                enabled = True
        self._enabled = bool(enabled)
        self._path = None
        if directory and self._enabled:
            os.makedirs(directory, exist_ok=True)
            self._path = os.path.join(directory, filename)

    @property
    def enabled(self) -> bool:
        return self._enabled

    @property
    def path(self) -> Optional[str]:
        return self._path

    def log(self, step: int, metrics: dict) -> None:
        if not self._enabled:
            return
        record = {"step": step, "time": time.time(), **metrics}
        line = json.dumps(record)
        if self._echo:
            print(f"[step {step}] " + " ".join(
                f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in metrics.items()
            ), flush=True)
        if self._path:
            with open(self._path, "a") as f:
                f.write(line + "\n")


def flatten_metrics(metrics: dict, prefix: str = "", sep: str = "/") -> dict:
    """Flatten nested metric dicts into ``a/b/c`` float keys.

    The train loops log through this so structured step metrics (the
    numerics stats tree, per-parameter-group norms) land in metrics.jsonl
    as flat greppable keys. Leaves are coerced with ``float()`` — which
    also fetches device scalars — falling back to the raw value for
    non-numeric leaves (strings)."""
    out: dict = {}
    for k, v in metrics.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(flatten_metrics(v, prefix=key + sep, sep=sep))
            continue
        try:
            out[key] = float(v)
        except (TypeError, ValueError):
            out[key] = v
    return out


class EventCounters:
    """Named monotonic counters for process-local accounting (compile
    counts, cache hits, request totals). Same spirit as MetricsLogger but
    for events without a step axis: ``bump`` from anywhere, ``snapshot``
    into a record, ``log_to`` to emit through a MetricsLogger. The serve
    engine's compile-count/cache-hit instrumentation is built on this so
    tests can assert exact executable-cache behavior.

    Thread-safe: the serve dispatch path and observability threads (the
    liveness watchdog's heartbeat, memory samplers) bump concurrently, and
    a lost update would corrupt the compile-count accounting the tests
    pin down."""

    def __init__(self):
        self._counts: dict = {}
        self._lock = threading.Lock()

    def bump(self, name: str, n: int = 1) -> int:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n
            return self._counts[name]

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._counts)

    def log_to(self, logger: "MetricsLogger", step: int = 0) -> None:
        logger.log(step, self.snapshot())
