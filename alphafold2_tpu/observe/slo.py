"""Declarative SLOs with multi-window burn-rate alerting.

A fixed threshold on a raw metric either pages on every blip (tight) or
sleeps through a slow burn (loose). The standard fix is error-budget burn
rate over TWO windows: the budget is the tolerated bad-event fraction
(``1 - target``), the burn rate is ``observed bad fraction / budget``
(1.0 = consuming exactly the budget), and an alert fires only when BOTH a
fast window (catches it now) and a slow window (proves it is sustained,
not one bad batch) exceed the threshold. Both windows' series come from
the :mod:`~alphafold2_tpu.observe.registry` rolling structures.

Objectives over :class:`~alphafold2_tpu.serve.engine.ServeResult` streams:

- ``latency`` — bad = an ``ok`` result slower than ``threshold_ms``
  (non-ok results are judged by the other objectives, not double-counted
  as latency misses).
- ``error_rate`` — bad = ``status == "error"`` among dispatched results.
- ``deadline_miss`` — bad = ``status == "deadline_exceeded"`` among
  admitted results.
- ``availability`` — bad = any non-``ok`` outcome, rejections included
  (the caller's view: did the service answer at all).

Specs are per priority class (``high``/``normal``/``low`` from the
request's scheduler priority, or ``None`` = all traffic) and parse from a
compact text form (``AF2TPU_SLO_SPECS``) so a deployment can declare its
objectives without code. Alerts are emitted as structured ``slo.alert``
tracer events and surfaced in serve bench records and
``observe/regress.py`` verdicts. Pure stdlib, fake-clock testable.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

from alphafold2_tpu.observe.registry import MetricsRegistry

OBJECTIVES = ("latency", "error_rate", "deadline_miss", "availability")


def priority_class(priority: int) -> str:
    """Scheduler priority -> SLO class name (>0 high, 0 normal, <0 low —
    the same ordering admission control sheds by)."""
    if priority > 0:
        return "high"
    if priority < 0:
        return "low"
    return "normal"


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One objective. ``target`` is the good-event fraction promised
    (0.99 = 1% error budget); ``burn_threshold`` is the burn rate that
    alerts when sustained in both windows; ``min_events`` keeps a
    near-empty window from alerting on one unlucky request."""

    name: str
    objective: str  # one of OBJECTIVES
    target: float = 0.99
    threshold_ms: Optional[float] = None  # latency objective only
    priority_class: Optional[str] = None  # None = all classes
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    burn_threshold: float = 2.0
    min_events: int = 10

    def __post_init__(self):
        if self.objective not in OBJECTIVES:
            raise ValueError(
                f"SLO {self.name!r}: objective {self.objective!r} not in "
                f"{OBJECTIVES}"
            )
        if self.objective == "latency" and not self.threshold_ms:
            raise ValueError(
                f"SLO {self.name!r}: latency objective needs threshold_ms"
            )
        if not (0.0 < self.target < 1.0):
            raise ValueError(
                f"SLO {self.name!r}: target must be in (0, 1), got "
                f"{self.target}"
            )

    @classmethod
    def from_str(cls, text: str) -> "SLOSpec":
        """Parse ``name,objective=latency,threshold_ms=500,target=0.95,
        class=high`` (first comma field is the name; the rest k=v)."""
        fields = [f.strip() for f in text.split(",") if f.strip()]
        if not fields or "=" in fields[0]:
            raise ValueError(
                f"bad SLO spec {text!r}: first field must be the name"
            )
        kw: dict = {"name": fields[0]}
        keymap = {
            "objective": ("objective", str),
            "target": ("target", float),
            "threshold_ms": ("threshold_ms", float),
            "class": ("priority_class", str),
            "fast_window_s": ("fast_window_s", float),
            "slow_window_s": ("slow_window_s", float),
            "burn_threshold": ("burn_threshold", float),
            "min_events": ("min_events", int),
        }
        for field in fields[1:]:
            key, _, val = field.partition("=")
            if key not in keymap:
                raise ValueError(
                    f"bad SLO spec {text!r}: unknown key {key!r}"
                )
            dest, cast = keymap[key]
            kw[dest] = cast(val)
        if "objective" not in kw:
            raise ValueError(f"bad SLO spec {text!r}: objective missing")
        return cls(**kw)


def parse_slo_specs(text: str) -> list:
    """Semicolon-separated :meth:`SLOSpec.from_str` forms -> spec list
    (the ``AF2TPU_SLO_SPECS`` format)."""
    return [
        SLOSpec.from_str(part)
        for part in (text or "").split(";")
        if part.strip()
    ]


def default_serve_slos(deadline_s: float = 30.0) -> list:
    """The serve bench's stock objectives: per-priority-class p-latency
    (high promised a tighter bound than low), plus stream-wide error and
    deadline-miss budgets. Latency thresholds scale with the configured
    request deadline so the same specs fit smoke and flagship configs."""
    lat_ms = max(1000.0, deadline_s * 1e3)
    return [
        SLOSpec(name="latency_high", objective="latency",
                threshold_ms=0.5 * lat_ms, target=0.95,
                priority_class="high"),
        SLOSpec(name="latency_normal", objective="latency",
                threshold_ms=0.8 * lat_ms, target=0.95,
                priority_class="normal"),
        SLOSpec(name="latency_low", objective="latency",
                threshold_ms=1.0 * lat_ms, target=0.90,
                priority_class="low"),
        SLOSpec(name="error_rate", objective="error_rate", target=0.95),
        SLOSpec(name="deadline_miss", objective="deadline_miss",
                target=0.95),
    ]


class SLOMonitor:
    """Feed :meth:`observe` every resolved ServeResult; read
    :meth:`evaluate` for per-spec burn-rate verdicts and :meth:`alerts`
    for the firing subset. Rolling series live in a
    :class:`MetricsRegistry` (shared with the exposition endpoint when
    the caller passes one in)."""

    def __init__(
        self,
        specs,
        registry: Optional[MetricsRegistry] = None,
        clock: Callable[[], float] = time.monotonic,
        tracer=None,
    ):
        self.specs = list(specs)
        self._clock = clock
        self.registry = (
            registry if registry is not None
            else MetricsRegistry(clock=clock)
        )
        self.tracer = tracer
        self._alerted: set = set()  # spec names that have ever fired
        for spec in self.specs:
            self.registry.windowed_counter(f"slo.{spec.name}.good")
            self.registry.windowed_counter(f"slo.{spec.name}.bad")

    # ------------------------------------------------------------ ingestion

    def _classify(self, spec: SLOSpec, status: str,
                  latency_ms: float) -> Optional[bool]:
        """True = good, False = bad, None = not this spec's event."""
        if spec.objective == "latency":
            if status != "ok":
                return None
            return latency_ms <= spec.threshold_ms
        if spec.objective == "error_rate":
            if status == "rejected":
                return None  # never dispatched: not an error-rate event
            return status != "error"
        if spec.objective == "deadline_miss":
            if status == "rejected":
                return None
            return status != "deadline_exceeded"
        return status == "ok"  # availability

    def observe(self, result, priority: int = 0) -> None:
        """One resolved request. ``result`` is a ServeResult (or anything
        with ``status`` and ``latency_s``)."""
        status = getattr(result, "status", "ok")
        latency_ms = float(getattr(result, "latency_s", 0.0) or 0.0) * 1e3
        cls = priority_class(int(priority))
        for spec in self.specs:
            if spec.priority_class is not None and spec.priority_class != cls:
                continue
            good = self._classify(spec, status, latency_ms)
            if good is None:
                continue
            kind = "good" if good else "bad"
            self.registry.windowed_counter(f"slo.{spec.name}.{kind}").add()

    # ----------------------------------------------------------- evaluation

    def _burn(self, spec: SLOSpec, window_s: float):
        good = self.registry.windowed_counter(
            f"slo.{spec.name}.good"
        ).sum(window_s)
        bad = self.registry.windowed_counter(
            f"slo.{spec.name}.bad"
        ).sum(window_s)
        total = good + bad
        budget = 1.0 - spec.target
        bad_frac = bad / total if total else 0.0
        return bad_frac / budget if budget else 0.0, int(total)

    def evaluate(self) -> list:
        """One verdict dict per spec: fast/slow burn rates, event counts,
        and whether the alert condition holds right now. A newly-firing
        alert also emits a structured ``slo.alert`` tracer event."""
        out = []
        for spec in self.specs:
            fast_burn, fast_n = self._burn(spec, spec.fast_window_s)
            slow_burn, slow_n = self._burn(spec, spec.slow_window_s)
            alert = (
                fast_n >= spec.min_events
                and fast_burn >= spec.burn_threshold
                and slow_burn >= spec.burn_threshold
            )
            verdict = {
                "spec": spec.name,
                "objective": spec.objective,
                "class": spec.priority_class or "all",
                "target": spec.target,
                "fast_burn": round(fast_burn, 3),
                "slow_burn": round(slow_burn, 3),
                "fast_events": fast_n,
                "slow_events": slow_n,
                "burn_threshold": spec.burn_threshold,
                "alert": bool(alert),
            }
            if spec.threshold_ms is not None:
                verdict["threshold_ms"] = spec.threshold_ms
            if alert and spec.name not in self._alerted:
                self._alerted.add(spec.name)
                if self.tracer is not None:
                    self.tracer.instant("slo.alert", **verdict)
            out.append(verdict)
        return out

    def alerts(self) -> list:
        return [v for v in self.evaluate() if v["alert"]]


def aggregate_slo_verdicts(verdict_lists) -> list:
    """Fleet-level rollup of per-replica :meth:`SLOMonitor.evaluate`
    outputs: one ``AF2TPU_SLO_SPECS`` string fans out to one monitor per
    replica, and this folds their verdicts back into one fleet verdict
    per spec — burn rates averaged weighted by each replica's event count
    (a replica that served nothing contributes nothing), event counts
    summed, and the alert recomputed on the AGGREGATED burn (so one hot
    replica diluted across a healthy fleet alerts fleet-wide only if the
    fleet-wide budget is actually burning)."""
    by_spec: dict = {}
    order: list = []
    for verdicts in verdict_lists:
        for v in verdicts or ():
            key = v["spec"]
            if key not in by_spec:
                by_spec[key] = []
                order.append(key)
            by_spec[key].append(v)
    out = []
    for key in order:
        group = by_spec[key]
        fast_n = sum(v["fast_events"] for v in group)
        slow_n = sum(v["slow_events"] for v in group)
        fast_burn = (
            sum(v["fast_burn"] * v["fast_events"] for v in group) / fast_n
            if fast_n else 0.0
        )
        slow_burn = (
            sum(v["slow_burn"] * v["slow_events"] for v in group) / slow_n
            if slow_n else 0.0
        )
        head = group[0]
        agg = {
            "spec": key,
            "objective": head["objective"],
            "class": head["class"],
            "target": head["target"],
            "fast_burn": round(fast_burn, 3),
            "slow_burn": round(slow_burn, 3),
            "fast_events": fast_n,
            "slow_events": slow_n,
            "burn_threshold": head["burn_threshold"],
            "replicas": len(group),
            "alert": bool(
                fast_n >= 1
                and fast_burn >= head["burn_threshold"]
                and slow_burn >= head["burn_threshold"]
            ),
        }
        if "threshold_ms" in head:
            agg["threshold_ms"] = head["threshold_ms"]
        out.append(agg)
    return out
