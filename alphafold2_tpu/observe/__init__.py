"""Unified observability subsystem: spans, histograms, telemetry, liveness.

The reference's only observability is ``print`` (SURVEY.md S5.1/S5.5), and
round 5 showed why that is fatal at scale: a whole bench deadline burned
hung in ``backend_init`` with no structured signal. This package is the
first-class answer:

- :mod:`tracing` — ``Tracer``/``Span``: nested span tracing emitted as
  Chrome-trace-event JSONL, loadable in Perfetto / ``chrome://tracing``,
  wired through the serve request lifecycle, the train step and bench.
- :mod:`histogram` — streaming log-bucketed ``Histogram`` with
  p50/p95/p99 snapshots (per-request latency, queue wait, batch occupancy,
  pad ratio).
- :mod:`metrics` — ``MetricsLogger`` (structured JSONL + stdout) and
  thread-safe ``EventCounters`` (compile counts, cache hits, totals).
- :mod:`memory` — ``MemorySampler`` over ``device.memory_stats()`` (HBM
  peaks; graceful no-op on backends that expose none).
- :mod:`watchdog` — ``LivenessWatchdog``: a heartbeat thread with
  per-stage deadlines backed by a cheap subprocess backend probe, so a
  dead-at-start backend produces a structured ``liveness: dead`` failure
  in seconds instead of eating a whole deadline.
- :mod:`profiler` — ``Profiler``: jax.profiler XLA trace over a step
  window (TensorBoard/XProf), unchanged from the original train hook.
- :mod:`numerics` — in-graph per-tensor telemetry: ``tag(name, x)``
  collects L2/max-abs/NaN/Inf stats as auxiliary jit outputs (zero ops
  when disabled), powering the train loop's NaN-triage reports.
- :mod:`flops` — the tree's single ``cost_analysis()`` parser: flops /
  bytes per compiled executable, peak-FLOPs tables and uniform MFU for
  bench, serve and the microbenchmarks.
- :mod:`regress` — device-keyed perf regression gate over bench/serve
  records (``scripts/bench_compare.py`` is the CLI/CI entry point).
- :mod:`tracectx` — request-scoped ``TraceContext`` (W3C-traceparent ids,
  thread-local with explicit handoff) plus trace reconstruction and
  completeness verification over emitted events.
- :mod:`registry` — ``MetricsRegistry``: named counters/gauges/rolling
  windows with periodic JSONL snapshots; :mod:`exposition` renders the
  same snapshot as a Prometheus text endpoint (``AF2TPU_METRICS_PORT``).
- :mod:`slo` — declarative ``SLOSpec`` objectives with multi-window
  burn-rate alerting over the resolved-request stream.
- :mod:`flightrec` — ``FlightRecorder``: bounded rings of recent
  telemetry dumped as a scrubbed incident file on watchdog fire,
  dispatch error, or SIGTERM.
- :mod:`workload` — ``WorkloadRecorder``: the request STREAM itself as
  a scrubbed, replayable JSONL artifact (fingerprints, not sequences,
  unless opted in), plus the replay builder and the seeded synthetic
  diurnal generator behind ``bench.py --mode serve-replay``.

``alphafold2_tpu.train.observe`` remains as a re-export shim for existing
imports. ``scripts/obs_report.py`` summarizes the emitted artifacts.

Everything here is importable without a jax backend (jax is imported
lazily where a device is consulted), so host-side tools stay jax-free.
"""

from alphafold2_tpu.observe import flops, numerics, regress
from alphafold2_tpu.observe.flightrec import FlightRecorder, scrub_env
from alphafold2_tpu.observe.histogram import Histogram
from alphafold2_tpu.observe.memory import MemorySampler
from alphafold2_tpu.observe.metrics import EventCounters, MetricsLogger
from alphafold2_tpu.observe.numerics import tag
from alphafold2_tpu.observe.profiler import Profiler
from alphafold2_tpu.observe.registry import MetricsRegistry
from alphafold2_tpu.observe.slo import SLOMonitor, SLOSpec, parse_slo_specs
from alphafold2_tpu.observe.tracectx import (
    TraceContext,
    current_trace,
    use_trace,
)
from alphafold2_tpu.observe.tracing import Span, Tracer
from alphafold2_tpu.observe.watchdog import LivenessWatchdog, probe_backend
from alphafold2_tpu.observe.workload import (
    WorkloadRecorder,
    build_replay,
    load_workload,
    synthetic_diurnal,
)

__all__ = [
    "EventCounters",
    "FlightRecorder",
    "Histogram",
    "LivenessWatchdog",
    "MemorySampler",
    "MetricsLogger",
    "MetricsRegistry",
    "Profiler",
    "SLOMonitor",
    "SLOSpec",
    "Span",
    "TraceContext",
    "Tracer",
    "WorkloadRecorder",
    "build_replay",
    "current_trace",
    "flops",
    "load_workload",
    "numerics",
    "parse_slo_specs",
    "probe_backend",
    "regress",
    "scrub_env",
    "synthetic_diurnal",
    "tag",
    "use_trace",
]
