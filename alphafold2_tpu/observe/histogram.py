"""Streaming histogram with percentile snapshots.

Log-spaced buckets (bounded memory whatever the stream length) with exact
min/max/sum tracking: percentile estimates carry the bucket's relative
error (~``growth - 1``) but clamp to the true extremes, which is what a
latency distribution needs — p50/p95/p99 to a few percent, never a bogus
tail. Replaces the serve engine's single ``latency_s`` scalar with real
distributions (queue wait, dispatch time, batch occupancy, pad ratio).
"""

from __future__ import annotations

import math
import threading


class Histogram:
    """Thread-safe streaming histogram over non-negative values.

    ``growth`` is the geometric bucket ratio (default 1.1 → ≤5% relative
    percentile error); values at or below ``floor`` share one underflow
    bucket (exact zeros are common: queue wait of the first dispatch,
    pad ratio of an exact-fit request)."""

    def __init__(self, growth: float = 1.1, floor: float = 1e-9):
        if growth <= 1.0:
            raise ValueError(f"growth must be > 1, got {growth}")
        self._growth = growth
        self._log_growth = math.log(growth)
        self._floor = floor
        self._counts: dict = {}  # bucket index -> count; -inf bucket is None
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._lock = threading.Lock()

    def _index(self, value: float):
        if value <= self._floor:
            return None  # underflow bucket
        return int(math.floor(math.log(value / self._floor) / self._log_growth))

    def observe(self, value: float) -> None:
        value = float(value)
        if value < 0 or not math.isfinite(value):
            raise ValueError(f"histogram values must be finite and >= 0: {value}")
        with self._lock:
            idx = self._index(value)
            self._counts[idx] = self._counts.get(idx, 0) + 1
            self._count += 1
            self._sum += value
            self._min = min(self._min, value)
            self._max = max(self._max, value)

    @property
    def count(self) -> int:
        return self._count

    def percentile(self, q: float) -> float:
        """Estimate the q-th percentile (q in [0, 100])."""
        with self._lock:
            return self._percentile_locked(q)

    def _percentile_locked(self, q: float) -> float:
        if self._count == 0:
            return 0.0
        rank = q / 100.0 * self._count
        seen = 0
        # None (underflow) sorts before every finite bucket index
        for idx in sorted(
            self._counts, key=lambda i: -math.inf if i is None else i
        ):
            seen += self._counts[idx]
            if seen >= rank:
                if idx is None:
                    return self._min if math.isfinite(self._min) else 0.0
                # geometric bucket midpoint, clamped to observed extremes
                mid = self._floor * self._growth ** (idx + 0.5)
                return min(max(mid, self._min), self._max)
        return self._max

    def snapshot(self, unit_scale: float = 1.0, digits: int = 4) -> dict:
        """One summary dict: count/mean/p50/p95/p99/min/max, values scaled
        by ``unit_scale`` (e.g. 1e3 for seconds → ms in a record)."""
        with self._lock:
            if self._count == 0:
                return {"count": 0}

            def r(v):
                return round(v * unit_scale, digits)

            return {
                "count": self._count,
                "mean": r(self._sum / self._count),
                "p50": r(self._percentile_locked(50)),
                "p95": r(self._percentile_locked(95)),
                "p99": r(self._percentile_locked(99)),
                "min": r(self._min),
                "max": r(self._max),
            }
