"""Prometheus-text metrics exposition over stdlib HTTP.

The ROADMAP's next open item — multi-replica fleet serving — needs a
per-replica health/scrape surface the fleet scheduler can poll without a
client library on either side. This module is that surface, pure stdlib:

- :func:`render_prometheus` — a flat snapshot dict (the
  :class:`~alphafold2_tpu.observe.registry.MetricsRegistry` /
  ``EventCounters`` shape) as Prometheus text exposition format 0.0.4,
  names sanitized and prefixed.
- :class:`MetricsHTTPServer` — a ``ThreadingHTTPServer`` on a daemon
  thread serving ``GET /metrics`` (the rendered snapshot, collected
  per-request via a callback so the numbers are always current) and
  ``GET /healthz`` (a small JSON liveness document).
- :func:`serve_from_env` — the opt-in wiring: ``AF2TPU_METRICS_PORT``
  set -> a server on that port (0 = ephemeral, for tests); unset -> None
  and zero overhead, which is why it is safe to wire through bench
  permanently.

Binds 127.0.0.1 by default: the scrape surface is intentionally not
exposed beyond the host unless a deployment overrides ``host``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

_NAME_OK = set("abcdefghijklmnopqrstuvwxyz"
               "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:")


def _sanitize(name: str) -> str:
    out = "".join(c if c in _NAME_OK else "_" for c in name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def render_prometheus(snapshot: dict, prefix: str = "af2tpu") -> str:
    """Flat ``{name: number}`` -> Prometheus text (format 0.0.4). Names
    are prefixed and sanitized (``sched.cache_hits`` ->
    ``af2tpu_sched_cache_hits``); non-numeric values are skipped (the
    scrape surface is numbers; strings ride the JSONL channel)."""
    lines = []
    for name in sorted(snapshot):
        value = snapshot[name]
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            continue
        metric = _sanitize(f"{prefix}_{name}" if prefix else name)
        lines.append(f"# TYPE {metric} untyped")
        lines.append(f"{metric} {float(value):g}")
    return "\n".join(lines) + ("\n" if lines else "")


class MetricsHTTPServer:
    """``/metrics`` + ``/healthz`` over ThreadingHTTPServer.

    ``collect`` is called per ``/metrics`` request and must return the
    flat snapshot dict; exceptions inside it yield a 500 instead of
    killing the serving thread. ``port=0`` binds an ephemeral port (read
    back via :attr:`port`)."""

    def __init__(
        self,
        collect: Callable[[], dict],
        port: int = 0,
        host: str = "127.0.0.1",
        prefix: str = "af2tpu",
    ):
        self._collect = collect
        self._prefix = prefix
        self._t0 = time.time()
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # stdout belongs to the bench record
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    try:
                        body = render_prometheus(
                            outer._collect(), prefix=outer._prefix
                        ).encode()
                    except Exception as e:
                        self._send(
                            500, f"collect failed: {e}".encode(),
                            "text/plain",
                        )
                        return
                    self._send(
                        200, body,
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                elif path == "/healthz":
                    doc = {
                        "ok": True,
                        "pid": os.getpid(),
                        "uptime_s": round(time.time() - outer._t0, 1),
                    }
                    self._send(
                        200, json.dumps(doc).encode(), "application/json"
                    )
                else:
                    self._send(404, b"not found", "text/plain")

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> "MetricsHTTPServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._server.serve_forever,
                name="af2-metrics-http", daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def serve_from_env(
    collect: Callable[[], dict], var: str = "AF2TPU_METRICS_PORT"
) -> Optional[MetricsHTTPServer]:
    """Start an exposition server when ``$AF2TPU_METRICS_PORT`` is set
    (0 = ephemeral); None (and no thread, no socket) when unset."""
    raw = os.environ.get(var)
    if raw is None or raw == "":
        return None
    return MetricsHTTPServer(collect, port=int(raw)).start()
