"""End-to-end structure training: distogram -> 3D coords -> refine -> RMSD loss.

The reference's ``train_end2end.py`` is a non-running design sketch (7 distinct
crash bugs, SURVEY.md S2.5); this module implements that *intent* (SURVEY.md
S3.4), corrected and compiled as ONE jitted differentiable program:

  elongate residues x3 into (N, CA, C) atom tokens  (train_end2end.py:134-146)
  -> Alphafold2 distogram over the 3L x 3L atom grid (:149)
  -> softmax (the reference feeds raw logits to centering, a bug)
  -> center_distogram -> distances + confidence weights (:152)
  -> MDS (Guttman scan) with per-element chirality fix (:154-160)
  -> sidechain_container lift to the 14-atom cloud (:163)
  -> SE(3)-equivariant refiner over the atom point cloud (:168-169)
  -> Kabsch-align vs ground truth, RMSD + 0.1*||1/w - 1|| loss (:172-176)

Gradients flow through the whole chain (MDS iterations are differentiable;
the chirality decision and Kabsch rotation are computed on stopped gradients,
matching the reference's detach points utils.py:463,533).

TPU-first: everything static-shape; the MDS loop is a fixed-trip lax.scan;
elongation is a static reshape; the only non-jnp control flow is the python
driver loop.
"""

from __future__ import annotations

from contextlib import nullcontext
from typing import Optional

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from alphafold2_tpu import constants
from alphafold2_tpu.config import Config
from alphafold2_tpu.models.alphafold2 import Alphafold2
from alphafold2_tpu.models.se3 import SE3Refiner
from alphafold2_tpu.parallel.sharding import DATA_AXIS, use_mesh
from alphafold2_tpu.train.loop import TrainState, build_optimizer
from alphafold2_tpu.utils.metrics import kabsch
from alphafold2_tpu.utils.structure import sidechain_container


def elongate(seq: jnp.ndarray, mask: jnp.ndarray):
    """Repeat each residue token x3 -> (N, CA, C) atom-level stream.

    (B, L) -> (B, 3L); the reference builds this with a python loop over
    tokens (train_end2end.py:134-146) — here it is a broadcast+reshape.
    """
    b, l = seq.shape
    seq3 = jnp.broadcast_to(seq[:, :, None], (b, l, 3)).reshape(b, 3 * l)
    mask3 = jnp.broadcast_to(mask[:, :, None], (b, l, 3)).reshape(b, 3 * l)
    return seq3, mask3


class End2EndModel(nn.Module):
    """Alphafold2 trunk + differentiable structure realization + SE(3) refiner."""

    dim: int = 256
    depth: int = 1
    heads: int = 8
    dim_head: int = 64
    max_seq_len: int = 2048
    mds_iters: int = 200
    # position-keyed MDS init: valid-region realization independent of the
    # padded bucket shape (serve engine turns this on; see utils/mds.py)
    mds_per_position_init: bool = False
    refiner_depth: int = 2
    remat: bool = False
    remat_policy: "str | None" = None  # None/"nothing" | "dots" | "dots_no_batch"
    reversible: bool = False  # inversion-based trunk engine (needs MSA)
    msa_tie_row_attn: bool = False
    msa_row_shard: bool = False  # shard MSA rows over sp (tied-row psum)
    context_parallel: Optional[str] = None
    grid_parallel: bool = False  # 2D-sharded pair axial passes (spr x spc)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, seq, msa=None, mask=None, msa_mask=None, embedds=None,
                 mds_key=None, deterministic: bool = True):
        b, l = seq.shape
        seq3, mask3 = elongate(seq, mask)

        if embedds is not None:
            # PLM embeddings are per-residue; elongate x3 alongside the tokens
            embedds = jnp.repeat(embedds, 3, axis=1)
        logits = Alphafold2(
            dim=self.dim, depth=self.depth, heads=self.heads,
            dim_head=self.dim_head, max_seq_len=self.max_seq_len,
            remat=self.remat, remat_policy=self.remat_policy,
            reversible=self.reversible,
            msa_tie_row_attn=self.msa_tie_row_attn,
            msa_row_shard=self.msa_row_shard,
            context_parallel=self.context_parallel,
            grid_parallel=self.grid_parallel,
            dtype=self.dtype, name="af2",
        )(seq3, msa, mask=mask3, msa_mask=msa_mask, embedds=embedds,
          deterministic=deterministic)

        from alphafold2_tpu.predict import realize_structure

        coords, distances, weights = realize_structure(
            logits, iters=self.mds_iters,
            key=mds_key if mds_key is not None else jax.random.key(0),
            # extend the token-validity mask through realization: pairs
            # touching padded positions get weight 0 and the chirality
            # statistic sees only valid residues, so padding (crop padding
            # in training, bucket padding in serving) cannot distort the
            # valid-region coordinates
            mask=mask3,
            per_position_init=self.mds_per_position_init,
        )  # coords (B, 3, 3L)

        backbone = jnp.swapaxes(coords, -1, -2)  # (B, 3L, 3)
        proto = sidechain_container(
            backbone, place_oxygen=True, mask=mask
        )  # (B, L, 14, 3)
        if mask is not None:
            # park padded residues' atoms at the origin: the refiner's
            # geometry (pairwise distances -> RBF logits) must see a value
            # that is finite and independent of whatever the padded MDS/NeRF
            # positions happened to be — additive attention masking removes
            # their influence on logits, not NaN/garbage in them
            proto = jnp.where(mask[:, :, None, None], proto, 0.0)

        atom_tokens = jnp.broadcast_to(
            jnp.arange(constants.NUM_COORDS_PER_RES)[None, None],
            (b, l, constants.NUM_COORDS_PER_RES),
        ).reshape(b, -1)
        atom_mask = jnp.broadcast_to(
            mask[:, :, None], (b, l, constants.NUM_COORDS_PER_RES)
        ).reshape(b, -1)
        refined = SE3Refiner(
            dim=64, depth=self.refiner_depth,
            num_tokens=constants.NUM_COORDS_PER_RES, dtype=self.dtype,
            name="refiner",
        )(atom_tokens, proto.reshape(b, -1, 3), mask=atom_mask)
        refined = refined.reshape(b, l, constants.NUM_COORDS_PER_RES, 3)

        return {
            "distogram": logits,
            "distances": distances,
            "weights": weights,
            "proto": proto,
            "refined": refined,
        }


def structure_loss(out: dict, backbone_true: jnp.ndarray, mask: jnp.ndarray):
    """Kabsch-aligned backbone RMSD + distogram-dispersion regularizer
    (reference train_end2end.py:172-176)."""
    refined_bb = out["refined"][:, :, :3].reshape(backbone_true.shape)  # (B, 3L, 3)
    pred = jnp.swapaxes(refined_bb, -1, -2)  # (B, 3, 3L)
    true = jnp.swapaxes(backbone_true, -1, -2)
    mask3 = jnp.broadcast_to(mask[:, :, None], (*mask.shape, 3)).reshape(
        mask.shape[0], -1
    )
    # zero masked atoms on both sides so they do not skew the alignment
    pred = pred * mask3[:, None, :]
    true = true * mask3[:, None, :]
    aligned, centered = kabsch(pred, true)
    denom = jnp.maximum(mask3.sum(-1), 1)
    sq = jnp.sum((aligned - centered) ** 2, axis=-2) * mask3
    rmsd_val = jnp.sqrt(jnp.sum(sq, axis=-1) / denom)
    w = out["weights"]
    # explicit bool->float cast (strict-promotion audit AF2A105)
    disp = jnp.mean(
        jnp.abs(1.0 / jnp.clip(w, 1e-7, None) - 1.0)
        * (w > 0).astype(w.dtype),
        axis=(-1, -2),
    )
    return jnp.mean(rmsd_val + 0.1 * disp), {
        "rmsd": jnp.mean(rmsd_val),
        "dispersion": jnp.mean(disp),
    }


def make_end2end_step(model: End2EndModel, mesh: Optional[Mesh] = None):
    def step(state: TrainState, batch: dict, rng: jax.Array):
        ctx = use_mesh(mesh) if mesh is not None else nullcontext()
        with ctx:
            drop_rng, mds_rng = jax.random.split(rng)

            def loss_fn(params):
                out = model.apply(
                    params,
                    batch["seq"],
                    batch.get("msa"),
                    mask=batch["mask"],
                    msa_mask=batch.get("msa_mask"),
                    embedds=batch.get("embedds"),
                    mds_key=mds_rng,
                    deterministic=False,
                    rngs={"dropout": drop_rng},
                )
                return structure_loss(out, batch["backbone"], batch["mask"])

            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                state.params
            )
            grads_ok = jnp.all(
                jnp.asarray(
                    [jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(grads)]
                )
            )
            safe = jax.tree.map(
                lambda g: jnp.where(grads_ok, g, jnp.zeros_like(g)), grads
            )
            new_state = state.apply_gradients(grads=safe)
            new_state = new_state.replace(
                skipped=state.skipped + jnp.where(grads_ok, 0, 1)
            )
            return new_state, {
                "loss": loss,
                "grad_norm": optax.global_norm(grads),
                "grads_ok": grads_ok,
                **aux,
            }

    if mesh is None:
        return jax.jit(step, donate_argnums=0)
    repl = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P(DATA_AXIS))
    return jax.jit(
        step, in_shardings=(repl, data, repl), out_shardings=(repl, repl),
        donate_argnums=0,
    )


def init_end2end_state(cfg: Config, model: End2EndModel, batch: dict) -> TrainState:
    rng = jax.random.key(cfg.train.seed)

    def opt(key):
        v = batch.get(key)
        return jnp.asarray(v) if v is not None else None

    params = model.init(
        rng,
        jnp.asarray(batch["seq"]),
        opt("msa"),
        mask=jnp.asarray(batch["mask"]),
        msa_mask=opt("msa_mask"),
        embedds=opt("embedds"),
    )
    state = TrainState.create(
        apply_fn=model.apply,
        params=params,
        tx=build_optimizer(cfg),
        skipped=jnp.zeros((), jnp.int32),
    )
    # flax's create() sets step to the python int 0; keep every state leaf
    # on device so the first jitted step performs no implicit host->device
    # transfer (jax.transfer_guard("disallow") clean — tests/conftest.py)
    return state.replace(step=jnp.zeros((), jnp.int32))


def train_end2end(cfg: Config, num_steps: Optional[int] = None, dataset=None):
    """Runnable end-to-end driver (the reference's never-ran intent)."""
    import time

    from alphafold2_tpu.data.pipeline import make_dataset
    from alphafold2_tpu.train.loop import apply_features
    from alphafold2_tpu.train.observe import MetricsLogger

    num_steps = num_steps or cfg.train.num_steps
    if cfg.model.max_seq_len < 3 * cfg.data.crop_len:
        raise ValueError(
            f"end-to-end training elongates each residue x3 (N/CA/C): "
            f"model.max_seq_len={cfg.model.max_seq_len} must be >= "
            f"3*data.crop_len={3 * cfg.data.crop_len}"
        )
    owns_dataset = dataset is None
    # per-host data seed: each process feeds its own global-batch slice
    data_seed = cfg.train.seed + 7919 * jax.process_index()
    dataset = dataset or make_dataset(cfg.data, seed=data_seed)
    data_iter = apply_features(iter(dataset), cfg)
    mesh = None
    if cfg.mesh.data_parallel * cfg.mesh.seq_parallel > 1:
        from alphafold2_tpu.parallel.distributed import pod_mesh

        mesh = pod_mesh(cfg.mesh.data_parallel, cfg.mesh.seq_parallel)

    model = End2EndModel(
        dim=cfg.model.dim, depth=cfg.model.depth, heads=cfg.model.heads,
        dim_head=cfg.model.dim_head, max_seq_len=cfg.model.max_seq_len,
        remat=cfg.model.remat, remat_policy=cfg.model.remat_policy,
        reversible=cfg.model.reversible,
        msa_tie_row_attn=cfg.model.msa_tie_row_attn,
        msa_row_shard=cfg.model.msa_row_shard,
        context_parallel=cfg.model.context_parallel,
        grid_parallel=cfg.model.grid_parallel,
        dtype=jnp.bfloat16 if cfg.model.bfloat16 else jnp.float32,
    )
    sample = next(data_iter)
    # tiny-sliced init: identical params, none of the full-size init
    # compile (train.loop.tiny_batch_like)
    from alphafold2_tpu.train.loop import tiny_batch_like

    state = init_end2end_state(cfg, model, tiny_batch_like(sample))
    step_fn = make_end2end_step(model, mesh)

    ckpt = None
    start_step = 0
    if cfg.train.checkpoint_dir:
        from alphafold2_tpu.train.checkpoint import CheckpointManager

        ckpt = CheckpointManager(
            cfg.train.checkpoint_dir, keep=cfg.train.keep_checkpoints
        )
        state, start_step = ckpt.maybe_restore(state)

    logger = MetricsLogger(cfg.train.checkpoint_dir)
    rng = jax.random.key(cfg.train.seed + 1)

    from itertools import chain

    from alphafold2_tpu.train.loop import device_prefetch

    prefetched = device_prefetch(chain([sample], data_iter), mesh)
    batch = next(prefetched)
    t0 = time.perf_counter()
    last_logged = None
    for i in range(start_step, num_steps):
        rng, r = jax.random.split(rng)
        state, metrics = step_fn(state, batch, r)
        if (i + 1) % cfg.train.log_every == 0 or i == start_step:
            from alphafold2_tpu.observe.metrics import flatten_metrics

            m = flatten_metrics(metrics)
            now = time.perf_counter()
            if last_logged is None:
                # compile-dominated first step: its wall time is a metric of
                # its own, not a bogus steps_per_sec=0.0 placeholder
                m["first_step_s"] = round(now - t0, 4)
            else:
                m["steps_per_sec"] = (i - last_logged) / max(now - t0, 1e-9)
            last_logged = i
            t0 = now
            logger.log(i, m)
        if ckpt is not None and (i + 1) % cfg.train.checkpoint_every == 0:
            ckpt.save(i + 1, state)
        batch = next(prefetched)
    if ckpt is not None:
        ckpt.save(num_steps, state)
        ckpt.wait()
        ckpt.close()
    if owns_dataset and hasattr(dataset, "close"):
        dataset.close()
    return state
