"""Observability: metric logging + jax.profiler trace hooks.

The reference's only observability is ``print`` (train_pre.py:92,
SURVEY.md S5.1/S5.5). Here: structured JSONL metrics (greppable, plottable)
plus stdout, and a profiler that captures an XLA trace for a configured step
window (``train.profile_dir`` / ``train.profile_steps``) viewable in
TensorBoard/XProf — the first-class tracing subsystem SURVEY.md asks for.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional, Tuple


class MetricsLogger:
    """JSONL + stdout metrics. In multi-host runs only process 0 logs —
    otherwise every host appends to the same metrics.jsonl on shared
    storage (duplicated and potentially interleaved records)."""

    def __init__(self, directory: Optional[str] = None, filename: str = "metrics.jsonl"):
        import jax

        self._enabled = jax.process_index() == 0
        self._path = None
        if directory and self._enabled:
            os.makedirs(directory, exist_ok=True)
            self._path = os.path.join(directory, filename)

    def log(self, step: int, metrics: dict) -> None:
        if not self._enabled:
            return
        record = {"step": step, "time": time.time(), **metrics}
        line = json.dumps(record)
        print(f"[step {step}] " + " ".join(
            f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
            for k, v in metrics.items()
        ), flush=True)
        if self._path:
            with open(self._path, "a") as f:
                f.write(line + "\n")


class EventCounters:
    """Named monotonic counters for process-local accounting (compile
    counts, cache hits, request totals). Same spirit as MetricsLogger but
    for events without a step axis: ``bump`` from anywhere, ``snapshot``
    into a record, ``log_to`` to emit through a MetricsLogger. The serve
    engine's compile-count/cache-hit instrumentation is built on this so
    tests can assert exact executable-cache behavior."""

    def __init__(self):
        self._counts: dict = {}

    def bump(self, name: str, n: int = 1) -> int:
        self._counts[name] = self._counts.get(name, 0) + n
        return self._counts[name]

    def get(self, name: str) -> int:
        return self._counts.get(name, 0)

    def snapshot(self) -> dict:
        return dict(self._counts)

    def log_to(self, logger: "MetricsLogger", step: int = 0) -> None:
        logger.log(step, self.snapshot())


class Profiler:
    """Start/stop a jax profiler trace across a [start, stop) step window."""

    def __init__(self, trace_dir: Optional[str], steps: Tuple[int, int] = (10, 13)):
        self._dir = trace_dir
        self._start, self._stop = steps
        self._active = False

    def maybe_start(self, step: int) -> None:
        if self._dir and step == self._start and not self._active:
            import jax

            jax.profiler.start_trace(self._dir)
            self._active = True

    def maybe_stop(self, step: int) -> None:
        if self._active and step >= self._stop:
            import jax

            jax.block_until_ready(jax.numpy.zeros(()))
            jax.profiler.stop_trace()
            self._active = False
