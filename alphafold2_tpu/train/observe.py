"""Re-export shim: the observability subsystem moved to
:mod:`alphafold2_tpu.observe` (spans, histograms, memory telemetry and the
liveness watchdog live there alongside these originals). Existing imports
of ``alphafold2_tpu.train.observe`` keep working unchanged.
"""

from alphafold2_tpu.observe import (  # noqa: F401
    EventCounters,
    Histogram,
    MemorySampler,
    MetricsLogger,
    Profiler,
    Span,
    Tracer,
)

__all__ = [
    "EventCounters",
    "Histogram",
    "MemorySampler",
    "MetricsLogger",
    "Profiler",
    "Span",
    "Tracer",
]
