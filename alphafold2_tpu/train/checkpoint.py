"""Checkpoint/resume via orbax — a subsystem the reference lacks entirely
(no torch.save/state_dict anywhere, SURVEY.md S5.4)."""

from __future__ import annotations

import os
from typing import Optional, Tuple

import orbax.checkpoint as ocp


class CheckpointManager:
    """Thin wrapper: save(step, state) / maybe_restore(template) -> (state, step)."""

    def __init__(self, directory: str, keep: int = 3):
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True, enable_async_checkpointing=True
            ),
        )

    def save(self, step: int, state) -> None:
        self._mgr.save(step, args=ocp.args.StandardSave(state))

    def maybe_restore(self, template) -> Tuple[object, int]:
        latest = self._mgr.latest_step()
        if latest is None:
            return template, 0
        restored = self._mgr.restore(
            latest, args=ocp.args.StandardRestore(template)
        )
        return restored, latest

    def restore_params(self, params_template):
        """Restore ONLY the model parameters from the latest checkpoint.

        Inference doesn't need (and must not depend on) the optimizer
        state — its tree shape varies with training config (e.g.
        optax.MultiSteps wrapping under gradient accumulation). Partial
        restore matches just the ``params`` subtree. ``params_template``
        may be abstract (jax.eval_shape output).
        """
        latest = self._mgr.latest_step()
        if latest is None:
            raise FileNotFoundError(f"no checkpoint found under {self._dir!r}")
        import inspect

        if "partial_restore" in inspect.signature(
            ocp.args.PyTreeRestore
        ).parameters:
            restored = self._mgr.restore(
                latest,
                args=ocp.args.PyTreeRestore(
                    item={"params": params_template}, partial_restore=True
                ),
            )
        else:
            # older orbax has no partial_restore and requires item trees to
            # match the saved structure; restore template-free (numpy, from
            # saved metadata) and slice the params subtree out. Costs a
            # transient opt_state read but keeps inference independent of
            # the training run's optimizer tree shape.
            restored = self._mgr.restore(latest, args=ocp.args.PyTreeRestore())
        return restored["params"], latest

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
