from alphafold2_tpu.train.loop import (
    TrainState,
    build_model,
    build_optimizer,
    device_put_batch,
    distogram_cross_entropy,
    init_state,
    make_train_step,
    train,
)
