"""Training: losses, state, pjit-sharded train step, distogram pretraining.

Capability target: reference ``train_pre.py`` (distogram pretraining loop:
cross-entropy vs bucketed CA distances with ignore_index -100, Adam 3e-4,
gradient accumulation 16 — train_pre.py:13-24, 66-95) re-designed TPU-first:

- the whole step (forward, loss, backward, optimizer) is ONE jitted program
  laid out over a (dp, sp) mesh; batch enters data-parallel-sharded, params
  and optimizer state are replicated, pair activations are row-sharded via
  the constraints in parallel/sharding.py — XLA inserts the psum for the
  gradient all-reduce (the reference is strictly single-device, SURVEY.md
  S2.3)
- gradient accumulation uses optax.MultiSteps (single compiled step instead
  of a python accumulation loop)
- bfloat16 compute / float32 params + optimizer
- failure handling the reference lacks (SURVEY.md S5.3): NaN/Inf gradients
  are detected in-graph and the step is skipped (state update suppressed).
"""

from __future__ import annotations

from contextlib import nullcontext
from typing import Optional

import jax
import jax.numpy as jnp
import optax
from flax.training import train_state
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from alphafold2_tpu.config import Config
from alphafold2_tpu.models.alphafold2 import Alphafold2
from alphafold2_tpu.observe import numerics
from alphafold2_tpu.parallel.sharding import DATA_AXIS, use_mesh
from alphafold2_tpu.utils.structure import get_bucketed_distance_matrix


class TrainState(train_state.TrainState):
    """Adds a monotone count of skipped (non-finite-gradient) steps."""

    skipped: jnp.ndarray = None  # scalar int32


def distogram_cross_entropy(
    logits: jnp.ndarray, labels: jnp.ndarray, ignore_index: int = -100
) -> jnp.ndarray:
    """Mean CE over non-ignored pairs (reference train_pre.py:84-87)."""
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    # numerics tag (no-op unless collection is active): the loss is the
    # last forward tensor, so a first-NaN here means the loss itself, not
    # the trunk, went bad
    nll = numerics.tag("loss.distogram_nll", nll)
    # explicit bool->float cast: bool*float is an implicit promotion the
    # strict-promotion audit (analysis/jaxpr_audit.py AF2A105) forbids
    validf = valid.astype(nll.dtype)
    return jnp.sum(nll * validf) / jnp.maximum(jnp.sum(validf), 1.0)


def apply_features(data_iter, cfg: Config):
    """Adapt the batch stream to data.features: "msa" (as-is), "plm" (frozen
    PLM embeddings replace the MSA — reference train_end2end.py FEATURES),
    or "none" (sequence only)."""
    if cfg.data.features == "plm":
        from alphafold2_tpu.data.plm import make_provider, wrap_with_embeddings

        provider = make_provider(
            cfg.data.plm_provider, path=cfg.data.plm_path, seed=cfg.train.seed
        )
        return wrap_with_embeddings(data_iter, provider)
    if cfg.data.features == "none":
        return (
            {k: v for k, v in b.items() if k not in ("msa", "msa_mask")}
            for b in data_iter
        )
    if cfg.data.features != "msa":
        raise ValueError(f"unknown data.features {cfg.data.features!r}")
    return data_iter


def build_model(cfg: Config) -> Alphafold2:
    m = cfg.model
    return Alphafold2(
        dim=m.dim,
        max_seq_len=m.max_seq_len,
        depth=m.depth,
        heads=m.heads,
        dim_head=m.dim_head,
        attn_dropout=m.attn_dropout,
        ff_dropout=m.ff_dropout,
        gelu_exact=m.gelu_exact,
        remat=m.remat,
        remat_policy=m.remat_policy,
        reversible=m.reversible,
        sparse_self_attn=m.sparse_self_attn,
        cross_attn_compress_ratio=m.cross_attn_compress_ratio,
        msa_tie_row_attn=m.msa_tie_row_attn,
        msa_row_shard=m.msa_row_shard,
        context_parallel=m.context_parallel,
        use_flash=m.flash_attention,
        grid_parallel=m.grid_parallel,
        scan_layers=m.scan_layers,
        template_attn_depth=m.template_attn_depth,
        dtype=jnp.bfloat16 if m.bfloat16 else jnp.float32,
    )


def build_optimizer(cfg: Config) -> optax.GradientTransformation:
    t = cfg.train
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=t.learning_rate,
        warmup_steps=t.warmup_steps,
        decay_steps=max(t.num_steps, t.warmup_steps + 1),
        end_value=t.learning_rate * 0.1,
    )
    tx = optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(schedule, weight_decay=t.weight_decay),
    )
    if t.gradient_accumulate_every > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=t.gradient_accumulate_every)
    return tx


def init_state(cfg: Config, model: Alphafold2, sample_batch: dict) -> TrainState:
    # validate the init scheme BEFORE the (expensive) model.init trace
    if cfg.model.init_scheme == "torch":
        if cfg.model.scan_layers or cfg.model.reversible:
            raise ValueError(
                "init_scheme='torch' is incompatible with scan_layers and "
                "the reversible engine: their depth-stacked params would "
                "corrupt the fan_in computation (models/init.py)"
            )
    elif cfg.model.init_scheme != "flax":
        raise ValueError(
            f"unknown init_scheme {cfg.model.init_scheme!r}; "
            "expected 'flax' or 'torch'"
        )
    rng = jax.random.key(cfg.train.seed)

    def opt(key):
        v = sample_batch.get(key)
        return jnp.asarray(v) if v is not None else None

    params = model.init(
        rng,
        jnp.asarray(sample_batch["seq"]),
        opt("msa"),
        mask=jnp.asarray(sample_batch["mask"]),
        msa_mask=opt("msa_mask"),
        embedds=opt("embedds"),
    )
    if cfg.model.init_scheme == "torch":
        # re-draw under the reference's torch module defaults (models/init.py)
        from alphafold2_tpu.models.init import torch_match_reinit

        params = torch_match_reinit(params, rng)
    state = TrainState.create(
        apply_fn=model.apply,
        params=params,
        tx=build_optimizer(cfg),
        skipped=jnp.zeros((), jnp.int32),
    )
    # flax's create() sets step to the python int 0; keep every state leaf
    # on device so the first jitted step performs no implicit host->device
    # transfer (jax.transfer_guard("disallow") clean — tests/conftest.py)
    return state.replace(step=jnp.zeros((), jnp.int32))


def tiny_batch_like(sample_batch: dict, n: int = 16, m: int = 2) -> dict:
    """Slice a real batch's feature arrays to tiny shapes for init tracing.

    Preserves the feature STRUCTURE (msa vs embedds vs none, embedds
    width) while shrinking the shapes that param construction never
    depends on (batch, crop, MSA depth/length)."""
    import numpy as np

    tiny = {}
    for key in ("seq", "mask"):
        if key in sample_batch:
            tiny[key] = np.asarray(sample_batch[key])[:1, :n]
    for key in ("msa", "msa_mask"):
        if sample_batch.get(key) is not None:
            tiny[key] = np.asarray(sample_batch[key])[:1, :m, :n]
    if sample_batch.get("embedds") is not None:
        tiny["embedds"] = np.asarray(sample_batch["embedds"])[:1, :n, :]
    return tiny


def tiny_init_state(
    cfg: Config, model: Alphafold2, sample_batch: Optional[dict] = None
) -> TrainState:
    """init_state at minimal data shapes with cfg's feature structure.

    Param shapes (and init values) depend only on the model config — the
    positional tables are sized by max_seq_len / max_num_msas, every other
    layer by dim, and ``embedd_project`` by the embedds feature width —
    not on crop/MSA batch shapes. Initializing with a tiny batch therefore
    produces the identical TrainState while skipping the compile of the
    full-size forward that ``model.init`` would otherwise trigger: at
    crop 256 that init compile costs more than the training-step compile
    itself (measured 1348s vs 49s on CPU).

    When a real ``sample_batch`` is given its arrays are sliced to tiny
    shapes (which preserves the feature structure and the embedds width
    for any PLM provider); otherwise a tiny synthetic batch is built with
    cfg's feature adaptation.
    """
    from dataclasses import replace

    if sample_batch is not None:
        return init_state(cfg, model, tiny_batch_like(sample_batch))

    from alphafold2_tpu.data.pipeline import SyntheticDataset

    d = cfg.data
    tiny_data = replace(
        d,
        crop_len=min(16, d.crop_len),
        msa_depth=min(2, d.msa_depth),
        msa_len=min(16, d.msa_len),
        batch_size=1,
        min_len_filter=min(16, d.crop_len, d.min_len_filter),
        max_len_filter=max(16, d.max_len_filter),
        source="synthetic",
    )
    tiny_cfg = replace(cfg, data=tiny_data)
    batch = next(apply_features(iter(SyntheticDataset(tiny_data, seed=0)), tiny_cfg))
    return init_state(cfg, model, batch)


def _param_groups(tree) -> dict:
    """Split a param/grad tree into its top-level module groups (``trunk``,
    ``token_emb``, ...), unwrapping the flax ``params`` collection."""
    if hasattr(tree, "keys") and set(tree.keys()) == {"params"}:
        tree = tree["params"]
    if not hasattr(tree, "items"):
        return {"all": tree}
    return dict(tree.items())


def make_train_step(
    model: Alphafold2,
    mesh: Optional[Mesh] = None,
    jit: bool = True,
    numerics_mode: str = "off",
):
    """Build the jitted distogram-pretraining step.

    Returns step(state, batch, rng) -> (state, metrics). When a mesh is
    given, inputs/outputs carry explicit shardings and the model's internal
    sharding constraints are active. ``jit=False`` returns the raw traceable
    step for embedding in a larger program (e.g. the in-graph multi-step
    scan in bench.py).

    ``numerics_mode`` widens the metrics dict (observe.numerics):

    - ``"off"`` — exactly the historic metrics (loss, grad_norm, grads_ok,
      distogram_entropy, skipped).
    - ``"norms"`` — adds per-parameter-group grad/param/update norms
      (``grad_norm/<group>`` etc.) beside the existing global ``grad_norm``.
    - ``"full"`` — norms plus the in-graph activation stats of every
      ``numerics.tag`` in the model under ``metrics["numerics"]``.

    A tagged and an untagged step are DIFFERENT jitted functions (jit
    caches by identity); the mode is fixed at build time on purpose.
    """
    if numerics_mode not in ("off", "norms", "full"):
        raise ValueError(
            f"unknown numerics_mode {numerics_mode!r}; "
            "expected 'off', 'norms' or 'full'"
        )

    def step(state: TrainState, batch: dict, rng: jax.Array):
        ctx = use_mesh(mesh) if mesh is not None else nullcontext()
        with ctx:
            def loss_fn(params):
                # collection must live inside the differentiated function:
                # the tagged activations are forward-pass tracers, valid
                # only as loss_fn aux outputs (value_and_grad has_aux)
                with numerics.collect(enabled=numerics_mode == "full") as col:
                    logits = model.apply(
                        params,
                        batch["seq"],
                        batch.get("msa"),
                        mask=batch["mask"],
                        msa_mask=batch.get("msa_mask"),
                        embedds=batch.get("embedds"),  # frozen-PLM feature path
                        deterministic=False,
                        rngs={"dropout": rng},
                    )
                    # native-loader batches carry host-precomputed labels
                    # (data/native.py); otherwise bucketize on device
                    labels = batch.get("labels")
                    if labels is None:
                        labels = get_bucketed_distance_matrix(
                            batch["coords"], batch["mask"]
                        )
                    loss = distogram_cross_entropy(logits, labels)
                return loss, (logits, col.stats())

            ((loss, (logits, act_stats)), grads) = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state.params)
            # failure detection: skip the update on non-finite gradients
            grads_ok = jnp.all(
                jnp.asarray(
                    [jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(grads)]
                )
            )
            safe_grads = jax.tree.map(
                lambda g: jnp.where(grads_ok, g, jnp.zeros_like(g)), grads
            )
            new_state = state.apply_gradients(grads=safe_grads)
            new_state = new_state.replace(
                skipped=state.skipped + jnp.where(grads_ok, 0, 1)
            )
            gnorm = optax.global_norm(grads)
            metrics = {
                "loss": loss,
                "grad_norm": gnorm,
                "grads_ok": grads_ok,
                "skipped": new_state.skipped,
                "distogram_entropy": -jnp.mean(
                    jnp.sum(
                        jax.nn.softmax(logits, -1) * jax.nn.log_softmax(logits, -1),
                        -1,
                    )
                ),
            }
            if numerics_mode in ("norms", "full"):
                # per-parameter-group norm trajectories: which part of the
                # model is drifting/spiking shows up long before the global
                # grad_norm moves
                groups_g = _param_groups(grads)
                groups_new = _param_groups(new_state.params)
                groups_old = _param_groups(state.params)
                for k in groups_g:
                    metrics[f"grad_norm/{k}"] = optax.global_norm(groups_g[k])
                    metrics[f"param_norm/{k}"] = optax.global_norm(
                        groups_new[k]
                    )
                    metrics[f"update_norm/{k}"] = optax.global_norm(
                        jax.tree.map(
                            lambda a, b: a - b, groups_new[k], groups_old[k]
                        )
                    )
                metrics["param_norm"] = optax.global_norm(new_state.params)
            if numerics_mode == "full":
                metrics["numerics"] = act_stats
            return new_state, metrics

    if not jit:
        return step
    if mesh is None:
        return jax.jit(step, donate_argnums=0)

    repl = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P(DATA_AXIS))
    return jax.jit(
        step,
        in_shardings=(repl, data, repl),
        out_shardings=(repl, repl),
        donate_argnums=0,
    )


def make_triage_step(model: Alphafold2, mesh: Optional[Mesh] = None):
    """Fully-tagged diagnostic step for NaN triage.

    Returns triage(params, batch, rng) -> stats, where stats maps every
    tagged tensor — embeddings, per-trunk-layer pair/MSA streams, distogram
    logits, the loss — to its ``numerics.tensor_stats``, followed by
    per-parameter-group gradient stats (``grad/<group>``). Insertion order
    is topological (forward order, then gradients), so
    ``numerics.first_nonfinite(stats)`` names the first tensor that went
    bad. No state update, no donation: the train loop reruns the exact
    (params, batch, rng) of a skipped step through this after the fast
    step's non-finite-grad skip fires.
    """

    def triage(params, batch: dict, rng: jax.Array):
        ctx = use_mesh(mesh) if mesh is not None else nullcontext()
        with ctx:
            def loss_fn(p):
                with numerics.collect() as col:
                    logits = model.apply(
                        p,
                        batch["seq"],
                        batch.get("msa"),
                        mask=batch["mask"],
                        msa_mask=batch.get("msa_mask"),
                        embedds=batch.get("embedds"),
                        deterministic=False,
                        rngs={"dropout": rng},  # the skipped step's exact rng
                    )
                    labels = batch.get("labels")
                    if labels is None:
                        labels = get_bucketed_distance_matrix(
                            batch["coords"], batch["mask"]
                        )
                    loss = distogram_cross_entropy(logits, labels)
                return loss, col.stats()

            (loss, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params
            )
            # continue the topological index past the activation tags: the
            # loss follows the forward pass, gradients follow the loss
            stats = dict(stats)
            order = len(stats)
            stats["loss"] = {
                "index": order, **numerics.tensor_stats(loss)
            }
            for name, sub in _param_groups(grads).items():
                order += 1
                stats[f"grad/{name}"] = {
                    "index": order, **numerics.tree_stats(sub)
                }
            return stats

    return jax.jit(triage)


def device_prefetch(data_iter, mesh: Optional[Mesh] = None, size: int = 2):
    """Wrap a host batch iterator with an N-deep on-device prefetch queue.

    ``jax.device_put`` is async: enqueueing the NEXT batch's transfer before
    the current step is consumed overlaps host->device copy with device
    compute (the reference's single-device loop has no such overlap; its
    DataLoader prefetches only into host memory). Python-level, so it works
    for any of the data sources including the native C++ loader."""
    from collections import deque

    queue: deque = deque()
    it = iter(data_iter)
    try:
        for _ in range(size):
            queue.append(device_put_batch(next(it), mesh))
        while queue:
            out = queue.popleft()
            try:
                queue.append(device_put_batch(next(it), mesh))
            except StopIteration:
                pass
            yield out
    except StopIteration:
        while queue:
            yield queue.popleft()


def device_put_batch(batch: dict, mesh: Optional[Mesh] = None) -> dict:
    if mesh is None:
        return {k: jnp.asarray(v) for k, v in batch.items()}
    if jax.process_count() > 1:
        # multi-host: this process holds only its slice of the global batch
        from alphafold2_tpu.parallel.distributed import global_batch

        return global_batch(batch, mesh)
    sh = NamedSharding(mesh, P(DATA_AXIS))
    return {k: jax.device_put(jnp.asarray(v), sh) for k, v in batch.items()}


def train(cfg: Config, num_steps: Optional[int] = None, dataset=None, callbacks=()):
    """Distogram pretraining driver (the runnable train_pre.py equivalent)."""
    import os
    import sys
    import time

    from alphafold2_tpu.data.pipeline import make_dataset
    from alphafold2_tpu.observe import MetricsLogger, Profiler, Tracer
    from alphafold2_tpu.observe import flops as flops_mod
    from alphafold2_tpu.observe.metrics import flatten_metrics
    from alphafold2_tpu.train.checkpoint import CheckpointManager

    num_steps = num_steps or cfg.train.num_steps
    owns_dataset = dataset is None
    # fold the process index into the data seed: each host must feed a
    # DIFFERENT slice of the global batch (global_batch() stitches them)
    data_seed = cfg.train.seed + 7919 * jax.process_index()
    dataset = dataset or make_dataset(cfg.data, seed=data_seed)
    data_iter = apply_features(iter(dataset), cfg)

    mesh = None
    if cfg.mesh.grid_rows * cfg.mesh.grid_cols > 1:
        # 2D pair-grid sharding: (dp, spr, spc) mesh
        from alphafold2_tpu.parallel.grid_parallel import make_grid_mesh

        if cfg.mesh.seq_parallel > 1 or cfg.model.context_parallel:
            raise ValueError(
                "grid_rows/grid_cols builds a (dp, spr, spc) mesh with no "
                "sp axis: mesh.seq_parallel and model.context_parallel "
                "cannot be combined with it"
            )
        if not cfg.model.grid_parallel:
            raise ValueError(
                "mesh.grid_rows/grid_cols requires model.grid_parallel=true "
                "— without it the axial passes run dense and GSPMD "
                "all-gathers the attended axis, losing the memory benefit"
            )
        n_dp = cfg.mesh.data_parallel
        if n_dp == -1:  # fill with all devices, like the 1D path
            n_dp = jax.device_count() // (cfg.mesh.grid_rows * cfg.mesh.grid_cols)
        mesh = make_grid_mesh(n_dp, cfg.mesh.grid_rows, cfg.mesh.grid_cols)
    n_mesh = cfg.mesh.data_parallel * cfg.mesh.seq_parallel
    if mesh is None and (n_mesh > 1 or cfg.mesh.seq_parallel > 1):
        # ICI/DCN-aware device ordering over the whole (multi-host) pod
        from alphafold2_tpu.parallel.distributed import pod_mesh

        mesh = pod_mesh(cfg.mesh.data_parallel, cfg.mesh.seq_parallel)

    model = build_model(cfg)
    sample = next(data_iter)
    # init at tiny slices of the sample: identical params, none of the
    # full-size init compile (see tiny_init_state)
    state = tiny_init_state(cfg, model, sample)
    # numerics telemetry mode (observe.numerics): "off" | "triage" (fast
    # step widened with per-parameter-group norms; a fully-tagged rerun
    # fires only when the non-finite-grad skip does) | "full" (every step
    # carries tagged activation stats). AF2TPU_NUMERICS overrides the
    # config for one run.
    numerics_mode = (
        os.environ.get("AF2TPU_NUMERICS") or cfg.train.numerics or "off"
    ).lower()
    if numerics_mode not in ("off", "triage", "full"):
        raise ValueError(
            f"unknown train.numerics {numerics_mode!r}; "
            "expected 'off', 'triage' or 'full'"
        )
    step_fn = make_train_step(
        model,
        mesh,
        numerics_mode={"off": "off", "triage": "norms", "full": "full"}[
            numerics_mode
        ],
    )

    ckpt = (
        CheckpointManager(cfg.train.checkpoint_dir, keep=cfg.train.keep_checkpoints)
        if cfg.train.checkpoint_dir
        else None
    )
    start_step = 0
    if ckpt is not None:
        state, start_step = ckpt.maybe_restore(state)

    logger = MetricsLogger(cfg.train.checkpoint_dir)
    profiler = Profiler(cfg.train.profile_dir, cfg.train.profile_steps)
    # host-side span trace beside the XLA profile: step dispatch, batch
    # fetch and checkpoint writes as Chrome trace events (observe.Tracer);
    # disabled (near-zero overhead) unless train.trace_events is set
    tracer = Tracer(cfg.train.trace_events)
    rng = jax.random.key(cfg.train.seed + 1)

    # preemption safety (SURVEY.md S5.3 — the reference has no failure
    # handling at all): on SIGTERM, finish the in-flight step, checkpoint,
    # and exit cleanly; the next run resumes from maybe_restore above.
    import signal

    stop = {"requested": False}
    prev_handler = None
    if ckpt is not None:
        def _on_sigterm(signum, frame):
            stop["requested"] = True

        try:
            prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)
        except ValueError:  # not on the main thread
            prev_handler = None

    def stop_agreed() -> bool:
        # multi-host: the stop decision must be COLLECTIVE — hosts receive
        # SIGTERM at slightly different times, and a host breaking out
        # early while others run the next step's collectives deadlocks the
        # pod. One tiny bool allgather per step synchronizes the decision.
        if jax.process_count() > 1:
            import numpy as np
            from jax.experimental import multihost_utils

            return bool(
                multihost_utils.process_allgather(
                    np.asarray(stop["requested"])
                ).any()
            )
        return stop["requested"]

    # on-device prefetch: the next batch's host->device transfer overlaps
    # the current step's compute
    from itertools import chain

    prefetched = device_prefetch(chain([sample], data_iter), mesh)
    batch = next(prefetched)

    # AOT-compile the step on the single-mesh path: compile time becomes an
    # explicit metric instead of polluting the first step's rate, and the
    # compiled executable's XLA cost analysis gives flops/bytes for MFU
    # accounting (observe.flops — the same parser bench and serve use).
    # The mesh/multi-host path keeps implicit jit compilation: AOT-compiled
    # calls are strict about input shardings the loop does not guarantee.
    step_call = step_fn
    step_flops = None
    if mesh is None and jax.process_count() == 1:
        try:
            t_c = time.perf_counter()
            with tracer.span("train.compile"):
                compiled = step_fn.lower(state, batch, rng).compile()
            compile_s = time.perf_counter() - t_c
            costs = flops_mod.executable_costs(compiled)
            step_flops = costs["flops"]
            step_call = compiled
            logger.log(start_step, {
                "compile_s": round(compile_s, 3),
                **({"step_flops": step_flops} if step_flops else {}),
                **({"step_bytes_accessed": costs["bytes_accessed"]}
                   if costs["bytes_accessed"] else {}),
            })
        except Exception as e:  # AOT is an optimization; never block training
            print(
                f"train-step AOT compile unavailable ({type(e).__name__}: "
                f"{e}); falling back to jit", file=sys.stderr,
            )

    # NaN triage (numerics_mode "triage"/"full"): when a step's non-finite-
    # grad skip fired, rerun it fully tagged and report the first bad
    # tensor in topological order. The check runs one iteration LATE (top
    # of the next loop pass): the skip left params untouched, so the exact
    # (params, batch, rng) triple is still live, and the host only blocks
    # on a step that has had a full iteration to complete.
    triage_fn = None
    pending = None  # (grads_ok, batch, rng, step index) of the last step

    def run_triage(ok, t_batch, t_rng, t_step):
        nonlocal triage_fn
        if bool(ok):
            return
        if triage_fn is None:
            triage_fn = make_triage_step(model, mesh)
        with tracer.span("train.nan_triage", step=t_step):
            stats = triage_fn(state.params, t_batch, t_rng)
        report = numerics.triage_report(stats, step=t_step)
        logger.log(t_step, {
            "event": "nan_triage",
            "first_nonfinite": report["first_nonfinite"],
            "nonfinite": report["nonfinite"],
            **numerics.flatten_stats(stats),
        })
        tracer.instant(
            "numerics.nan_triage", step=t_step,
            first_nonfinite=report["first_nonfinite"],
        )

    t0 = time.perf_counter()
    last_logged = None
    for i in range(start_step, num_steps):
        if pending is not None:
            run_triage(*pending)
            pending = None
        profiler.maybe_start(i)
        rng, step_rng = jax.random.split(rng)
        with tracer.span("train.step", step=i):
            state, metrics = step_call(state, batch, step_rng)
        profiler.maybe_stop(i)
        if numerics_mode in ("triage", "full"):
            pending = (metrics["grads_ok"], batch, step_rng, i)
        if (i + 1) % cfg.train.log_every == 0 or i == start_step:
            m = flatten_metrics(metrics)
            now = time.perf_counter()
            if last_logged is None:
                # the session's first step is dispatch- (or, without AOT,
                # compile-)dominated: record its wall time as its own
                # metric instead of the old steps_per_sec=0.0 placeholder
                m["first_step_s"] = round(now - t0, 4)
            else:
                m["steps_per_sec"] = (i - last_logged) / max(now - t0, 1e-9)
                if step_flops:
                    m["model_flops_per_s"] = step_flops * m["steps_per_sec"]
                    mfu = flops_mod.mfu(step_flops, 1.0 / m["steps_per_sec"])
                    if mfu is not None:
                        m["mfu"] = round(mfu, 4)
            if numerics_mode == "full" and isinstance(
                metrics.get("numerics"), dict
            ):
                # same numerics/<name> vocabulary in the Perfetto trace
                numerics.counters_to_tracer(metrics["numerics"], tracer)
            last_logged = i
            t0 = now
            logger.log(i, m)
        for cb in callbacks:
            cb(i, state, metrics)
        if ckpt is not None and (i + 1) % cfg.train.checkpoint_every == 0:
            with tracer.span("train.checkpoint", step=i + 1):
                ckpt.save(i + 1, state)
        if ckpt is not None and stop_agreed():
            stop["requested"] = True
            logger.log(i, {"preempted": 1.0})
            if ckpt.latest_step() != i + 1:
                ckpt.save(i + 1, state)
            break
        with tracer.span("train.next_batch", step=i + 1):
            batch = next(prefetched)
    if pending is not None:  # a skip on the session's final step
        run_triage(*pending)
    if prev_handler is not None:
        signal.signal(signal.SIGTERM, prev_handler)
    if ckpt is not None:
        if not stop["requested"] and ckpt.latest_step() != num_steps:
            ckpt.save(num_steps, state)
        ckpt.wait()
    if owns_dataset and hasattr(dataset, "close"):
        dataset.close()  # shut down native prefetch workers
    tracer.close()
    return state
