"""2D sequence sharding of the pair grid: rows x cols with all-to-all.

SURVEY.md S7 "hard parts": axial attention needs all of a row (or column)
local to one device for the attended axis; the clean mesh layout for the
(B, N, N, D) pair representation is therefore TWO sequence axes — rows
sharded over ``spr`` and columns over ``spc`` — with an all-to-all transpose
before/after each axial pass. Per pass, each device temporarily trades a
factor of the *non-attended* axis for the full *attended* axis:

    at rest:   (B, N/spr, N/spc, ...)           P(dp, spr, spc)
    row pass:  all_to_all over spc ->  (B, N/(spr*spc), N, ...)   attend cols
    col pass:  all_to_all over spr ->  (B, N, N/(spr*spc), ...)   attend rows

Peak per-device memory for the pair grid is O(N^2 / (spr*spc)) — square in
the mesh size rather than linear as with the 1D ``sp`` layout
(parallel/sharding.py), which is what lets crop 768+ fit a pod slice. The
collectives are ``lax.all_to_all`` over one mesh axis each, riding ICI.

The reference has no analogue (single device, SURVEY.md S2.3); this and
ring/Ulysses (parallel/seq_parallel.py) are the green-field long-context
layer. Everything is jnp-only and differentiable; exactness vs the dense
oracle (values and grads) is proven on the 8-virtual-device CPU mesh in
tests/test_grid_parallel.py.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from alphafold2_tpu.ops.attention import MASK_VALUE
from alphafold2_tpu.parallel.sharding import (
    axis_size_compat,
    shard_map_compat as shard_map,
)

DATA_AXIS_NAME = "dp"
ROW_AXIS_NAME = "spr"  # shards grid axis 1 (rows / height)
COL_AXIS_NAME = "spc"  # shards grid axis 2 (cols / width)


def make_grid_mesh(
    n_data: int = 1, n_row: int = 1, n_col: int = 1, devices=None
) -> Mesh:
    """A (dp, spr, spc) mesh for 2D pair-grid sharding.

    Device order comes from ``mesh_utils.create_device_mesh`` so the spr/spc
    axes land on physically-adjacent chips (their per-layer all_to_all
    transposes then ride ICI, with dp crossing DCN — same placement policy
    as distributed.pod_mesh); falls back to raw order off-TPU."""
    import numpy as np

    devices = devices if devices is not None else jax.devices()
    n = n_data * n_row * n_col
    if n != len(devices):
        raise ValueError(
            f"mesh {n_data}x{n_row}x{n_col} != {len(devices)} devices"
        )
    try:
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_device_mesh(
            (n_data, n_row, n_col), devices=devices
        )
    except Exception:  # non-TPU backends: any order works, nothing to optimize
        arr = np.asarray(devices).reshape(n_data, n_row, n_col)
    return Mesh(arr, (DATA_AXIS_NAME, ROW_AXIS_NAME, COL_AXIS_NAME))


def grid_spec() -> P:
    """At-rest spec for (B, H, W, ...) grid arrays on a grid mesh."""
    return P(DATA_AXIS_NAME, ROW_AXIS_NAME, COL_AXIS_NAME)


def _attend_last_grid_axis(q, k, v, mask, attn_fn=None):
    """Attention over grid axis 2. q/k/v: (B, R, N, H, D); mask: (B, R, N)
    bool key validity. Rows R are independent batch entries.

    ``attn_fn`` is an optional fused kernel taking row-flattened
    ``(B*R, H, N, D)`` q/k/v and a ``(B*R, N)`` mask (or None), returning
    the attended values in the same layout — or None to decline the shape
    (trace-time), falling back to the dense jnp path. This is how flash /
    block-sparse attention run INSIDE the 2D-sharded axial passes.

    ``mask=None`` stays None all the way down so fused kernels keep their
    unmasked fast paths (e.g. flash without SegmentIds)."""
    b, r, n, h, d = q.shape
    if attn_fn is not None:
        # shape-only pre-probe: a hook exposing ``accepts`` can decline
        # from the static shape alone, BEFORE the row-flattening ops are
        # traced — a declined call must leave zero footprint in the jaxpr
        # (the graph contracts fingerprint dead eqns too)
        accepts = getattr(attn_fn, "accepts", None)
        if accepts is None or accepts(b * r, h, n):
            def flat(t):  # (B, R, N, H, D) -> (B*R, H, N, D)
                return jnp.moveaxis(t.reshape(b * r, n, h, d), 2, 1)

            m2 = mask.reshape(b * r, n) if mask is not None else None
            out = attn_fn(flat(q), flat(k), flat(v), m2)
            if out is not None:
                return jnp.moveaxis(out, 1, 2).reshape(b, r, n, h, d)
    scale = d**-0.5
    dots = jnp.einsum("brihd,brjhd->brhij", q, k).astype(jnp.float32) * scale
    if mask is not None:
        bias = jnp.where(mask, 0.0, MASK_VALUE)
        dots = dots + bias[:, :, None, None, :].astype(jnp.float32)
    attn = jax.nn.softmax(dots, axis=-1).astype(q.dtype)
    return jnp.einsum("brhij,brjhd->brihd", attn, v)


def _sharded_pass(q, k, v, mask, attend_axis: int, attn_fn=None):
    """Runs inside shard_map over (dp, spr, spc). Local blocks:
    q/k/v (b, hl, wl, heads, d), mask (b, hl, wl) or None."""
    if attend_axis == 2:
        gather_name, split_axis = COL_AXIS_NAME, 1
    elif attend_axis == 1:
        gather_name, split_axis = ROW_AXIS_NAME, 2
    else:
        raise ValueError(f"attend_axis must be 1 or 2, got {attend_axis}")
    size = axis_size_compat(gather_name)
    if q.shape[split_axis] % size:
        raise ValueError(
            f"non-attended local axis {q.shape[split_axis]} must divide by "
            f"mesh axis {gather_name}={size} for the all-to-all transpose"
        )

    def gather(t):  # trade non-attended axis for the full attended axis
        return lax.all_to_all(
            t, gather_name, split_axis=split_axis, concat_axis=attend_axis,
            tiled=True,
        )

    def scatter(t):  # inverse transpose
        return lax.all_to_all(
            t, gather_name, split_axis=attend_axis, concat_axis=split_axis,
            tiled=True,
        )

    q, k, v = gather(q), gather(k), gather(v)
    if mask is not None:
        mask = gather(mask)
    if attend_axis == 1:  # put the attended axis last for the shared kernel
        q, k, v = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
        mask = jnp.swapaxes(mask, 1, 2) if mask is not None else None
    out = _attend_last_grid_axis(q, k, v, mask, attn_fn=attn_fn)
    if attend_axis == 1:
        out = jnp.swapaxes(out, 1, 2)
    return scatter(out)


def grid_axial_attention(
    q: jnp.ndarray,  # (B, H, W, heads, dh) global grid
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,  # (B, H, W) bool key-validity
    mesh: Optional[Mesh] = None,
    attend_axis: int = 2,
    attn_fn=None,  # fused kernel hook, see _attend_last_grid_axis
) -> jnp.ndarray:
    """One axial attention pass over a 2D-sharded grid.

    ``attend_axis=2`` attends within rows (over columns), ``attend_axis=1``
    within columns (over rows) — call twice and sum for the full axial
    block (ops/attention.py AxialAttention semantics). Exact dense
    attention in both the sharded and meshless paths; ``attn_fn`` swaps the
    per-device attended-axis computation for a fused kernel (flash /
    block-sparse) after the all-to-all gather.
    """
    if mesh is None or ROW_AXIS_NAME not in mesh.axis_names:
        if attend_axis == 1:
            qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
            mt = jnp.swapaxes(mask, 1, 2) if mask is not None else None
            out = _attend_last_grid_axis(qt, kt, vt, mt, attn_fn=attn_fn)
            return jnp.swapaxes(out, 1, 2)
        return _attend_last_grid_axis(q, k, v, mask, attn_fn=attn_fn)

    qkv_spec = P(DATA_AXIS_NAME, ROW_AXIS_NAME, COL_AXIS_NAME, None, None)
    mask_spec = P(DATA_AXIS_NAME, ROW_AXIS_NAME, COL_AXIS_NAME)
    if mask is None:
        # mask stays None down to the per-device kernels (their unmasked
        # fast paths) — shard_map over the three tensor inputs only
        mapped = shard_map(
            partial(
                _sharded_pass, mask=None, attend_axis=attend_axis,
                attn_fn=attn_fn,
            ),
            mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec),
            out_specs=qkv_spec,
            check_vma=False,
        )
        return mapped(q, k, v)
    mapped = shard_map(
        partial(_sharded_pass, attend_axis=attend_axis, attn_fn=attn_fn),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return mapped(q, k, v, mask)
