"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference scales sequence length only with single-device memory tricks
(axial factorization, block sparsity, KV compression, reversibility —
SURVEY.md S5.7); it has no multi-device sequence parallelism of any kind
(S2.3). This module is the green-field capability layer: exact attention over
a sequence axis SHARDED across the ``sp`` mesh axis, in two standard flavors:

- :func:`ring_attention` — KV blocks rotate around the ring via
  ``lax.ppermute`` while each device folds them into a flash-style online
  softmax (f32 running max / sum / accumulator). Communication overlaps
  compute, memory per device is O(N/sp), and the result is exactly dense
  attention (not an approximation). ppermute rides neighbor ICI links.
- :func:`ulysses_attention` — ``lax.all_to_all`` re-shards from
  sequence-sharded to head-sharded, runs ordinary dense attention locally
  over the full sequence for H/sp heads, and all-to-alls back. Two
  collectives per call, best when heads % sp == 0 and N/sp is small.

Both are jnp-only (differentiable; XLA emits the collective gradients) and
are written to run inside ``shard_map`` with a named ``sp`` axis.
:func:`sequence_parallel_attention` is the host-level entry: it shard_maps
over an explicit (dp, sp) mesh and reduces to plain dense attention when no
mesh/axis is present, so the same call site works single-chip and on a pod.

This is the ring-attention-adjacent design SURVEY.md S7 lists as the key
novel engineering vs the reference; differential tests against the dense
oracle run on the 8-virtual-device CPU mesh (tests/test_seq_parallel.py).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from alphafold2_tpu.ops.attention import MASK_VALUE
from alphafold2_tpu.parallel.sharding import (
    axis_size_compat,
    shard_map_compat as shard_map,
)

SEQ_AXIS_NAME = "sp"
DATA_AXIS_NAME = "dp"


def _dense(q, k, v, kmask_bias):
    """Local dense attention with additive key bias. (B, H, n, d) x 3."""
    scale = q.shape[-1] ** -0.5
    dots = jnp.einsum("bhid,bhjd->bhij", q, k) * scale
    dots = dots + kmask_bias[:, None, None, :]
    attn = jax.nn.softmax(dots.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhij,bhjd->bhid", attn, v)


def ring_attention(
    q: jnp.ndarray,  # (B, H, n_local, D) — this device's query block
    k: jnp.ndarray,  # (B, H, n_local, D) — this device's KV block
    v: jnp.ndarray,
    kmask_bias: jnp.ndarray,  # (B, n_local) f32 additive bias (0 / MASK_VALUE)
    axis_name: str = SEQ_AXIS_NAME,
) -> jnp.ndarray:
    """Exact attention over the ring-sharded sequence axis.

    Flash-style accumulation: per rotation step, fold the visiting KV block
    into (running_max, running_sum, accumulator); rotate KV one hop with
    ppermute. After ``sp`` steps every query block has seen every key.
    """
    sp = axis_size_compat(axis_name)
    scale = q.shape[-1] ** -0.5
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    b, h, n, d = q.shape
    m0 = jnp.full((b, h, n, 1), MASK_VALUE, jnp.float32)
    l0 = jnp.zeros((b, h, n, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, n, d), jnp.float32)

    def body(carry, _):
        m_prev, l_prev, acc, k_cur, v_cur, bias_cur = carry
        dots = (
            jnp.einsum("bhid,bhjd->bhij", q, k_cur).astype(jnp.float32) * scale
            + bias_cur[:, None, None, :]
        )
        m_new = jnp.maximum(m_prev, jnp.max(dots, axis=-1, keepdims=True))
        p = jnp.exp(dots - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bhij,bhjd->bhid", p, v_cur.astype(jnp.float32)
        )
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        b_nxt = lax.ppermute(bias_cur, axis_name, perm)
        return (m_new, l_new, acc_new, k_nxt, v_nxt, b_nxt), None

    (m, l, acc, _, _, _), _ = lax.scan(
        body, (m0, l0, acc0, k, v, kmask_bias), None, length=sp
    )
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ulysses_attention(
    q: jnp.ndarray,  # (B, H, n_local, D)
    k: jnp.ndarray,
    v: jnp.ndarray,
    kmask_bias: jnp.ndarray,  # (B, n_local)
    axis_name: str = SEQ_AXIS_NAME,
) -> jnp.ndarray:
    """All-to-all sequence parallelism (Ulysses): re-shard seq -> heads,
    attend densely over the full sequence locally, re-shard back."""
    sp = axis_size_compat(axis_name)
    if q.shape[1] % sp != 0:
        raise ValueError(
            f"heads {q.shape[1]} must divide by sp={sp} for ulysses"
        )
    # (B, H, n, D) -> (B, H/sp, n*sp, D): split heads across devices,
    # gather the sequence
    def seq_to_heads(t):
        return lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    bias_full = lax.all_gather(kmask_bias, axis_name, axis=1, tiled=True)
    out = _dense(qh, kh, vh, bias_full)
    # back: (B, H/sp, n*sp, D) -> (B, H, n, D)
    return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def _tied_core(q, k, v, num_rows_global: int, axis_name: Optional[str]):
    """Tied-row contraction; ``axis_name`` completes row-sharded logits with
    a psum, None means the rows are all local. One source of truth for the
    scale convention and dtype-cast points."""
    d = q.shape[-1]
    scale = d**-0.5 * num_rows_global**-0.5
    logits = jnp.einsum("brhid,brhjd->bhij", q, k).astype(jnp.float32)
    if axis_name is not None:
        logits = lax.psum(logits, axis_name)
    attn = jax.nn.softmax(logits * scale, axis=-1).astype(q.dtype)
    return jnp.einsum("bhij,brhjd->brhid", attn, v)


def tied_row_attention_sharded(
    q: jnp.ndarray,  # (B, R_local, H, N, D) — this device's MSA rows
    k: jnp.ndarray,
    v: jnp.ndarray,
    num_rows_global: int,
    axis_name: str = SEQ_AXIS_NAME,
) -> jnp.ndarray:
    """Tied-row (MSA-Transformer) attention with rows SHARDED over the mesh.

    The tied attention matrix sums QK^T logits over every MSA row with an
    extra r^-0.5 scale (SURVEY.md S7: "this is where tied-rows becomes a
    collective"): each device contracts its local rows, one psum over the
    row-sharding axis completes the global logits, and the shared softmax
    is applied to the local rows' values — the MSA need not be replicated.
    Standalone primitive for row-sharded layouts; the in-model tied path
    (ops/attention.py tie_dim) currently runs on a replicated MSA.
    """
    return _tied_core(q, k, v, num_rows_global, axis_name)


def tied_row_attention(
    q: jnp.ndarray,  # (B, R, H, N, D) global arrays
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Optional[Mesh] = None,
) -> jnp.ndarray:
    """Host-level tied-row attention; rows sharded over sp when a mesh is
    given, dense contraction otherwise. Exact in both modes."""
    b, r = q.shape[0], q.shape[1]
    if mesh is None or SEQ_AXIS_NAME not in mesh.axis_names:
        return _tied_core(q, k, v, r, None)
    sp = mesh.shape[SEQ_AXIS_NAME]
    dp = mesh.shape.get(DATA_AXIS_NAME, 1)
    if r % sp != 0:
        raise ValueError(f"MSA rows {r} must divide by sp={sp}")
    if b % dp != 0:
        raise ValueError(f"batch {b} must divide by dp={dp}")
    spec = P(DATA_AXIS_NAME, SEQ_AXIS_NAME)
    mapped = shard_map(
        partial(tied_row_attention_sharded, num_rows_global=r),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return mapped(q, k, v)


def sequence_parallel_attention(
    q: jnp.ndarray,  # (B, H, N, D) — global arrays
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,  # (B, N) bool key padding
    mesh: Optional[Mesh] = None,
    impl: str = "ring",
) -> jnp.ndarray:
    """Host-level entry: shard the sequence axis over the mesh's sp axis and
    run ring or ulysses attention; dense fallback without a mesh."""
    if impl not in ("ring", "ulysses"):
        raise ValueError(f"unknown context-parallel impl {impl!r}")
    b = q.shape[0]
    nk = k.shape[2]  # key length — differs from q length in cross-attention
    bias = (
        jnp.where(mask, 0.0, MASK_VALUE).astype(jnp.float32)
        if mask is not None
        else jnp.zeros((b, nk), jnp.float32)
    )
    if mesh is None or SEQ_AXIS_NAME not in mesh.axis_names:
        return _dense(q, k, v, bias)

    fn = ring_attention if impl == "ring" else ulysses_attention
    qkv_spec = P(DATA_AXIS_NAME, None, SEQ_AXIS_NAME, None)
    bias_spec = P(DATA_AXIS_NAME, SEQ_AXIS_NAME)
    mapped = shard_map(
        partial(fn, axis_name=SEQ_AXIS_NAME),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, bias_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return mapped(q, k, v, bias)
