"""Sharding constraints for the pair/MSA streams over a device mesh.

The reference has no multi-device parallelism of any kind (SURVEY.md S2.3);
this module is the green-field capability layer. Design (scaling-book recipe):

- Mesh axes: ``dp`` (data parallel over batch) x ``sp`` (sequence parallel
  over pair-map rows). The pair grid (B, N, N, D) is sharded
  P(dp, sp, None, None): each device holds a contiguous band of rows i with
  all columns j — so the *row* attention pass (attend over j) is fully local.
  The *column* pass needs all i per column; annotating the layer-boundary
  constraint and leaving the interior unconstrained lets XLA insert the
  all-to-all transposes between the two passes (the ring/Ulysses-adjacent
  design SURVEY.md S7 calls for) over ICI.
- The MSA grid (B, M, Nm, D) is tiny next to the N^2 pair grid (M <= 20);
  it is replicated across ``sp`` and sharded only over ``dp``.
- Cross-attention (N^2 queries vs M*Nm keys) keeps pair tokens row-sharded;
  the MSA context is replicated so no gather is needed on the KV side.

Blocks call :func:`shard_pair`/:func:`shard_msa` at their boundaries; outside
an active mesh context these are identity, so the same model code runs
single-chip, under tests, and on a pod.
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "dp"
SEQ_AXIS = "sp"

_active: dict = {"mesh": None}


def shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma=False):
    """``jax.shard_map`` across jax versions.

    The public ``jax.shard_map`` (with its ``check_vma`` kwarg) only exists
    on newer jax; older versions ship it as ``jax.experimental.shard_map``
    where the same knob is spelled ``check_rep``. Every shard_map in this
    package goes through here so a version bump is a one-line change."""
    try:
        from jax import shard_map as _sm

        return _sm(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm

        return _sm(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )


def axis_size_compat(axis_name: str):
    """``lax.axis_size`` across jax versions: absent on older jax, where
    ``psum(1, axis)`` is the idiomatic (constant-folded) equivalent."""
    from jax import lax

    try:
        return lax.axis_size(axis_name)
    except AttributeError:
        return lax.psum(1, axis_name)


def make_mesh(
    n_data: Optional[int] = None, n_seq: int = 1, devices=None
) -> Mesh:
    """Create a (dp, sp) mesh. Defaults to all devices on the dp axis."""
    import numpy as np

    devices = devices if devices is not None else jax.devices()
    if n_data is None:
        n_data = len(devices) // n_seq
    if n_data * n_seq != len(devices):
        raise ValueError(
            f"mesh {n_data}x{n_seq} != {len(devices)} devices"
        )
    arr = np.asarray(devices).reshape(n_data, n_seq)
    return Mesh(arr, (DATA_AXIS, SEQ_AXIS))


@contextmanager
def use_mesh(mesh: Mesh):
    """Activate sharding constraints for model code traced inside."""
    prev = _active["mesh"]
    _active["mesh"] = mesh
    try:
        with mesh:
            yield mesh
    finally:
        _active["mesh"] = prev


def active_mesh() -> Optional[Mesh]:
    return _active["mesh"]


def _constrain(x, spec: P):
    mesh = _active["mesh"]
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def pair_spec() -> P:
    mesh = _active["mesh"]
    if mesh is not None:
        from alphafold2_tpu.parallel.grid_parallel import (
            COL_AXIS_NAME,
            ROW_AXIS_NAME,
        )

        if ROW_AXIS_NAME in mesh.axis_names:
            # 2D grid mesh (parallel/grid_parallel.py): rows x cols sharding
            return P(DATA_AXIS, ROW_AXIS_NAME, COL_AXIS_NAME)
    return P(DATA_AXIS, SEQ_AXIS)


def msa_spec(rows: bool = False) -> P:
    """MSA grid (B, M, Nm, D) layout: replicated over sp by default (M is
    tiny next to N^2); ``rows=True`` shards the row axis over sp — the
    tied-row logit contraction then completes with an XLA-inserted psum
    (SURVEY.md S7: "tied-rows becomes a collective"), scaling MSA depth.
    On a 2D grid mesh (no sp axis) the row axis shards over spr instead,
    so tied-row psum composes with the pair-grid layout."""
    if rows:
        mesh = _active["mesh"]
        if mesh is not None and SEQ_AXIS in mesh.axis_names:
            return P(DATA_AXIS, SEQ_AXIS)
        if mesh is not None:
            from alphafold2_tpu.parallel.grid_parallel import ROW_AXIS_NAME

            if ROW_AXIS_NAME in mesh.axis_names:
                return P(DATA_AXIS, ROW_AXIS_NAME)
    return P(DATA_AXIS)


def batch_spec() -> P:
    return P(DATA_AXIS)


def shard_pair(x):
    """Constrain a (B, N, N, D) or (B, N, N) pair array: batch x row sharded."""
    if os.environ.get("AF2TPU_AUDIT_DROP_SHARD_PAIR"):
        # Seeded-defect hook for the HLO audit's negative control (analysis/
        # hlo_audit.py, CI static-analysis job): deliberately drop the pair
        # constraint so the resharding detector must catch the resulting
        # implicit all-gathers / per-device footprint blowup statically.
        # Never set in production; trace-time only, so no runtime cost.
        return x
    return _constrain(x, pair_spec())


def shard_msa(m, rows: bool = False):
    """Constrain a (B, M, Nm, D) MSA array: batch sharded; ``rows=True``
    additionally shards the MSA-row axis over sp (see :func:`msa_spec`)."""
    return _constrain(m, msa_spec(rows))


def shard_batch(t):
    """Constrain any batch-leading array to data-parallel sharding."""
    return _constrain(t, batch_spec())


def replicated(t):
    return _constrain(t, P())


def describe_mesh(mesh: Optional[Mesh]) -> Optional[str]:
    """Stable mesh-identity string, e.g. ``"dp1.spr2.spc4"`` — the key the
    serve executable cache, result cache, bench records and regression gate
    all share, so a CPU-mesh number can never silently compare against a
    differently-sharded (or unsharded) one. None for no mesh."""
    if mesh is None:
        return None
    return ".".join(
        f"{name}{size}" for name, size in zip(mesh.axis_names, mesh.devices.shape)
    )


def parse_mesh_spec(spec: Optional[str]) -> Optional[Mesh]:
    """Build a mesh from a compact CLI/env spec: ``"DPxSPRxSPC"`` (three
    ints — a 2D pair-grid mesh, parallel/grid_parallel.py) or ``"DPxSP"``
    (two ints — the 1D (dp, sp) mesh). Empty/None -> no mesh."""
    if not spec:
        return None
    parts = [int(p) for p in spec.lower().replace("x", " ").split()]
    if len(parts) == 3:
        from alphafold2_tpu.parallel.grid_parallel import make_grid_mesh

        return make_grid_mesh(*parts)
    if len(parts) == 2:
        return make_mesh(parts[0], parts[1])
    raise ValueError(
        f"mesh spec {spec!r} must be 'dpxsprxspc' (grid) or 'dpxsp' (1D)"
    )
