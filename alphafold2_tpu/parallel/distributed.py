"""Multi-host bootstrap: process initialization, pod meshes, global batches.

The reference is strictly single-process/single-device (SURVEY.md S2.3 — no
torch.distributed, no NCCL/MPI anywhere). The TPU framework's communication
backend is XLA itself: collectives ride ICI within a slice and DCN across
slices, and what the framework owes is the *bootstrap* — process group
initialization, a mesh laid out so the fast axes stay on ICI, and the
host-local -> globally-sharded batch hand-off. That is this module:

- :func:`initialize` — ``jax.distributed.initialize`` wrapper. On TPU pods
  everything is auto-detected from the metadata server; on CPU/GPU clusters
  the coordinator/rank come from standard env vars (COORDINATOR_ADDRESS,
  NUM_PROCESSES, PROCESS_ID) or arguments. Safe to call when single-process
  (no-op without a coordinator).
- :func:`pod_mesh` — an (dp, sp) mesh over ALL processes' devices via
  ``mesh_utils.create_device_mesh``, which orders devices so the trailing
  mesh axis maps to physically-adjacent chips: put ``sp`` last so ring
  attention's ppermute hops ride single ICI links, and dp spans DCN.
- :func:`global_batch` — build globally-sharded arrays from each host's
  local batch shard (``jax.make_array_from_process_local_data``): every
  host feeds ``global_batch_size / num_processes`` examples and the result
  is one logical array sharded P(dp, ...) over the pod, without any host
  ever materializing the full batch.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from alphafold2_tpu.parallel.sharding import DATA_AXIS, SEQ_AXIS


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize the JAX process group for multi-host execution.

    Returns True if distributed init ran, False for single-process.
    Initialization requires an EXPLICIT multi-process signal — a
    coordinator address (argument or COORDINATOR_ADDRESS env), a
    multi-worker TPU slice environment (TPU_WORKER_HOSTNAMES with >1
    host), or AF2TPU_MULTIHOST=1 to force jax's own pod auto-detection.
    Single-chip and tunneled-TPU runs must not call
    jax.distributed.initialize, so silence is the safe default; on pod
    launchers that set none of these vars, export AF2TPU_MULTIHOST=1.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "COORDINATOR_ADDRESS"
    )
    if num_processes is None and os.environ.get("NUM_PROCESSES"):
        num_processes = int(os.environ["NUM_PROCESSES"])
    if process_id is None and os.environ.get("PROCESS_ID"):
        process_id = int(os.environ["PROCESS_ID"])

    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    multihost_tpu = len([h for h in hosts.split(",") if h]) > 1
    forced = os.environ.get("AF2TPU_MULTIHOST") == "1"
    if coordinator_address is None and not multihost_tpu and not forced:
        return False  # single-process run; nothing to initialize
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def pod_mesh(
    n_data: int = -1,
    n_seq: int = 1,
    *,
    allow_split_physical_axes: bool = False,
) -> Mesh:
    """(dp, sp) mesh over every device in the (possibly multi-host) runtime.

    ``n_data=-1`` fills dp with all remaining devices. The sp axis is placed
    LAST in the mesh shape so ``create_device_mesh`` keeps its devices
    physically contiguous — ring-attention ppermute then uses nearest-
    neighbor ICI links, and the dp all-reduce crosses DCN only once per
    step.
    """
    total = jax.device_count()
    if n_data == -1:
        if total % n_seq != 0:
            raise ValueError(
                f"{total} devices do not divide by sp={n_seq}"
            )
        n_data = total // n_seq
    if n_data * n_seq != total:
        raise ValueError(f"mesh {n_data}x{n_seq} != {total} devices")
    devices = mesh_utils.create_device_mesh(
        (n_data, n_seq), allow_split_physical_axes=allow_split_physical_axes
    )
    return Mesh(devices, (DATA_AXIS, SEQ_AXIS))


def global_batch(batch: dict, mesh: Mesh) -> dict:
    """Assemble a globally batch-sharded batch from this host's local shard.

    Each process passes its own slice of the global batch (same dict schema,
    local batch size = global / num_processes); the returned arrays are
    jax.Arrays sharded P(dp) over the full pod. Single-process this reduces
    to a device_put.
    """
    out = {}
    for key, value in batch.items():
        value = np.asarray(value)
        sharding = NamedSharding(mesh, P(DATA_AXIS))
        out[key] = jax.make_array_from_process_local_data(sharding, value)
    return out
