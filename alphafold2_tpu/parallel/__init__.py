from alphafold2_tpu.parallel.sharding import (
    DATA_AXIS,
    SEQ_AXIS,
    active_mesh,
    make_mesh,
    shard_batch,
    shard_msa,
    shard_pair,
    use_mesh,
)
