from alphafold2_tpu.parallel.sharding import (
    DATA_AXIS,
    SEQ_AXIS,
    active_mesh,
    describe_mesh,
    make_mesh,
    parse_mesh_spec,
    shard_batch,
    shard_msa,
    shard_pair,
    use_mesh,
)
