"""alphafold2_tpu: a TPU-native (JAX/XLA/pjit/Pallas) protein structure framework.

Re-designed from scratch with the capabilities of the reference
alphafold2-pytorch (lucidrains v0.0.33): axial-attention trunk over a pairwise
residue representation cross-attending an MSA stream, distogram prediction,
and structure realization (distogram -> MDS -> sidechain lift -> refinement)
with alignment/quality metrics — built TPU-first: static shapes, scan/remat
trunks, mesh-sharded pair maps, Pallas kernels for the sparse paths.
"""

__version__ = "0.1.0"

from alphafold2_tpu import constants
