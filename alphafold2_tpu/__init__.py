"""alphafold2_tpu: a TPU-native (JAX/XLA/pjit/Pallas) protein structure framework.

Re-designed from scratch with the capabilities of the reference
alphafold2-pytorch (lucidrains v0.0.33): axial-attention trunk over a pairwise
residue representation cross-attending an MSA stream, distogram prediction,
and structure realization (distogram -> MDS -> sidechain lift -> refinement)
with alignment/quality metrics — built TPU-first: static shapes, scan/remat
trunks, mesh-sharded pair maps, Pallas kernels for the sparse paths.
"""

__version__ = "0.1.0"

import os as _os

from alphafold2_tpu import constants


def setup_platform(default: str | None = None) -> None:
    """Pin the JAX platform before any backend initializes.

    Drivers call this at startup. ``AF2TPU_PLATFORM`` (e.g. ``cpu``, ``tpu``)
    wins; otherwise ``default`` is applied when given. This must go through
    ``jax.config`` — site hooks that register accelerator PJRT plugins may
    set ``jax_platforms`` programmatically, which overrides the
    ``JAX_PLATFORMS`` env var, and a dead accelerator tunnel then hangs
    every ``jax.devices()`` call with no timeout.
    """
    platform = _os.environ.get("AF2TPU_PLATFORM", default)
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    enable_compile_cache()


def enable_compile_cache() -> None:
    """Point XLA's persistent compilation cache at a stable directory.

    The flagship step takes minutes to compile; through the TPU tunnel a
    single compile can consume a whole driver budget (round 1 lost both
    driver artifacts to exactly that). With the cache, any later process
    compiling the same HLO (the round-end bench after a measurement
    session, a session relaunched after a tunnel drop) reuses the
    serialized executable in seconds. Best-effort: backends that cannot
    serialize executables simply miss the cache. ``AF2TPU_COMPILE_CACHE=``
    (empty) disables."""
    cache_dir = _os.environ.get("AF2TPU_COMPILE_CACHE", "/tmp/af2tpu_xla_cache")
    if not cache_dir:
        return
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 10.0)
    except Exception:  # unknown flags on old jax — the cache is optional
        pass
