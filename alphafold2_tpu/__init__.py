"""alphafold2_tpu: a TPU-native (JAX/XLA/pjit/Pallas) protein structure framework.

Re-designed from scratch with the capabilities of the reference
alphafold2-pytorch (lucidrains v0.0.33): axial-attention trunk over a pairwise
residue representation cross-attending an MSA stream, distogram prediction,
and structure realization (distogram -> MDS -> sidechain lift -> refinement)
with alignment/quality metrics — built TPU-first: static shapes, scan/remat
trunks, mesh-sharded pair maps, Pallas kernels for the sparse paths.
"""

__version__ = "0.1.0"

import os as _os

from alphafold2_tpu import constants


def setup_platform(default: str | None = None) -> None:
    """Pin the JAX platform before any backend initializes.

    Drivers call this at startup. ``AF2TPU_PLATFORM`` (e.g. ``cpu``, ``tpu``)
    wins; otherwise ``default`` is applied when given. This must go through
    ``jax.config`` — site hooks that register accelerator PJRT plugins may
    set ``jax_platforms`` programmatically, which overrides the
    ``JAX_PLATFORMS`` env var, and a dead accelerator tunnel then hangs
    every ``jax.devices()`` call with no timeout.
    """
    platform = _os.environ.get("AF2TPU_PLATFORM", default)
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    enable_compile_cache()


def compile_cache_dir() -> str:
    """The persistent XLA compile cache location. Per-user (not a fixed
    world-readable /tmp path — on a shared host another user could
    pre-create it and poison serialized executables this process would
    deserialize). ``AF2TPU_COMPILE_CACHE`` overrides; empty disables."""
    override = _os.environ.get("AF2TPU_COMPILE_CACHE")
    if override is not None:  # set (possibly empty = disabled): the
        return override  # per-user default must not even be touched
    return _os.path.join(user_cache_dir(), "xla_cache")


def user_cache_dir() -> str:
    """Per-user scratch root for caches/checkpoints/shards (mode 0700).

    A pre-existing directory is validated: it must belong to this uid
    (anything else is refused — a directory planted by another user could
    feed poisoned serialized executables) and is tightened to 0700 if a
    prior process left it group/other-accessible. The HOME-less fallback
    is a SINGLE component directly under /tmp: /tmp's sticky bit stops
    other users renaming/replacing it, which a nested path (whose
    intermediate parents an attacker could pre-create) would not."""
    home = _os.path.expanduser("~")
    if home != "~":
        root = _os.path.join(home, ".cache", "af2tpu")
    else:
        root = "/tmp/af2tpu_u%d" % _os.getuid()
    _os.makedirs(root, mode=0o700, exist_ok=True)
    st = _os.stat(root)
    if st.st_uid != _os.getuid():
        raise RuntimeError(
            f"refusing cache dir {root}: owned by uid {st.st_uid}, not "
            f"{_os.getuid()} — set AF2TPU_COMPILE_CACHE (and the other "
            "AF2TPU_* path overrides) to a directory you own"
        )
    if st.st_mode & 0o077:
        _os.chmod(root, 0o700)
    return root


def enable_compile_cache() -> None:
    """Point XLA's persistent compilation cache at a stable directory.

    The flagship step takes minutes to compile; through the TPU tunnel a
    single compile can consume a whole driver budget (round 1 lost both
    driver artifacts to exactly that). With the cache, any later process
    compiling the same HLO (the round-end bench after a measurement
    session, a session relaunched after a tunnel drop) reuses the
    serialized executable in seconds. Best-effort: backends that cannot
    serialize executables simply miss the cache."""
    # fully best-effort: this runs from setup_platform at driver import
    # time, and a raise here (unwritable path, foreign-owned dir) would
    # kill bench.py before its watchdog/JSON-record machinery exists —
    # running without a cache is always better than not running
    try:
        cache_dir = compile_cache_dir()
        if not cache_dir:
            return
        _os.makedirs(cache_dir, mode=0o700, exist_ok=True)
    except (OSError, RuntimeError) as e:
        import sys as _sys

        print(
            f"alphafold2_tpu: compile cache disabled ({e})", file=_sys.stderr
        )
        return
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 10.0)
    except Exception:  # unknown flags on old jax — the cache is optional
        pass
