"""Inference: sequence (+MSA) -> distogram -> 3D structure -> PDB.

The reference documents this flow only as README snippets + a notebook (run
the model, softmax the distogram, ``center_distogram_torch``, ``MDScaling``,
Kabsch against the truth); there is no runnable prediction entry point. This
module is that entry point, jit-compiled end to end:

- :func:`realize_structure` — distogram logits -> (coords, confidence
  weights): softmax (the reference README feeds raw logits, a bug —
  SURVEY.md S2.5), distogram centering, weighted MDS with per-element
  chirality fix.
- :func:`predict` — full pipeline on the end-to-end model (trunk ->
  realization -> SE(3) refinement) returning atom14 coordinates plus a
  :class:`PDBStructure` ready to write (utils/pdb.py).
- CLI: ``python scripts/predict.py --seq ACDEFG... --out pred.pdb``
  (optionally restoring a checkpoint from training).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from alphafold2_tpu import constants
from alphafold2_tpu.config import Config
from alphafold2_tpu.train.end2end import End2EndModel, init_end2end_state
from alphafold2_tpu.utils.mds import mdscaling_backbone
from alphafold2_tpu.utils.structure import center_distogram
from alphafold2_tpu.utils import pdb as pdbio


def realize_structure(
    logits: jnp.ndarray,  # (B, N, N, K) distogram logits
    iters: int = 200,
    key: Optional[jax.Array] = None,
    fix_mirror: bool = True,
    mask: Optional[jnp.ndarray] = None,  # (B, N) bool token validity
    per_position_init: bool = False,
):
    """Distogram logits -> (coords (B, 3, N), distances, weights).

    The single realization implementation — End2EndModel calls this inside
    the compiled train step too. Assumes the token stream is
    (N, CA, C)-elongated when ``fix_mirror`` (the chirality test reads
    backbone phi angles). ``mask`` zeroes the MDS weights of pairs touching
    padded positions so padding's arbitrary pseudo-distances cannot distort
    the valid region, and restricts the chirality statistic to valid
    residues. ``per_position_init`` keys each position's MDS start by its
    absolute index so the valid-region solve is reproducible across padded
    bucket shapes (see utils/mds.py)."""
    from alphafold2_tpu.parallel.sharding import shard_pair

    # identity without an active mesh; under one (sharded serving), the
    # realization-stage pair tensors — logits (B,N,N,K) f32, probs, and
    # the (B,N,N) distance/weight maps — stay on the pair-grid layout
    # instead of being silently replicated per device (at bucket 512 the
    # replicated realization alone was ~3 GB/device)
    logits = shard_pair(logits)
    probs = shard_pair(jax.nn.softmax(logits.astype(jnp.float32), axis=-1))
    distances, weights = center_distogram(probs)
    distances, weights = shard_pair(distances), shard_pair(weights)
    residue_mask = None
    if mask is not None:
        pair_valid = mask[:, :, None] & mask[:, None, :]
        # explicit bool->float cast: bool*float is an implicit promotion
        # the strict-promotion audit (jaxpr_audit AF2A105) forbids
        weights = weights * pair_valid.astype(weights.dtype)
        if fix_mirror:
            b, n = mask.shape
            residue_mask = mask.reshape(b, n // 3, 3).any(-1)  # (B, L)
    coords, _ = mdscaling_backbone(
        distances, weights=weights, iters=iters,
        key=key if key is not None else jax.random.key(0),
        fix_mirror=fix_mirror,
        residue_mask=residue_mask,
        per_position_init=per_position_init,
    )
    return coords, distances, weights


@dataclasses.dataclass
class Prediction:
    atom14: np.ndarray  # (L, 14, 3) refined all-atom coordinates
    backbone: np.ndarray  # (L, 3, 3) N/CA/C
    weights: np.ndarray  # (3L, 3L) distogram confidence
    distogram: np.ndarray  # (3L, 3L, K) logits

    def to_pdb(self, seq: str, chain: str = "A") -> pdbio.PDBStructure:
        return pdbio.backbone_to_pdb(seq, self.backbone, chain=chain)


def encode_sequence(seq: str) -> np.ndarray:
    """One-letter AA string -> (1, L) int tokens (AA_ALPHABET order)."""
    idx = {a: i for i, a in enumerate(constants.AA_ALPHABET)}
    return np.asarray([[idx.get(c.upper(), constants.AA_PAD_INDEX) for c in seq]],
                      np.int32)


def synthesize_msa(seq_tokens: np.ndarray, depth: int, seed: int = 0,
                   rate: float = 0.15):
    """Mutate the primary sequence into a stand-in MSA (as the data pipeline
    does) for checkpoints trained with an MSA stream."""
    rng = np.random.default_rng(seed)
    b, l = seq_tokens.shape
    msa = np.repeat(seq_tokens[:, None], depth, axis=1)
    mut = rng.random((b, depth, l)) < rate
    msa[mut] = rng.integers(0, 20, size=int(mut.sum()))
    return msa


def predict(
    cfg: Config,
    seq: str,
    checkpoint_dir: Optional[str] = None,
    msa_depth: Optional[int] = None,
    seed: int = 0,
) -> Prediction:
    """Full prediction on the end-to-end model. Random init when no
    checkpoint is given (useful for pipeline validation, not accuracy)."""
    L = len(seq)
    if 3 * L > cfg.model.max_seq_len:
        raise ValueError(
            f"sequence of {L} residues needs 3L={3 * L} positions but "
            f"model.max_seq_len={cfg.model.max_seq_len}; raise it (positions "
            "beyond the table would silently clamp to the last embedding)"
        )
    depth = msa_depth if msa_depth is not None else cfg.data.msa_depth
    if depth > constants.MAX_NUM_MSA:
        raise ValueError(
            f"msa_depth={depth} exceeds MAX_NUM_MSA={constants.MAX_NUM_MSA} "
            "(deeper rows would clamp the msa_num_pos_emb table)"
        )
    model = End2EndModel(
        dim=cfg.model.dim, depth=cfg.model.depth, heads=cfg.model.heads,
        dim_head=cfg.model.dim_head, max_seq_len=cfg.model.max_seq_len,
        remat=cfg.model.remat, msa_tie_row_attn=cfg.model.msa_tie_row_attn,
        context_parallel=cfg.model.context_parallel,
        dtype=jnp.bfloat16 if cfg.model.bfloat16 else jnp.float32,
    )
    seq_tokens = encode_sequence(seq)
    batch = {
        "seq": seq_tokens,
        "mask": np.ones((1, L), bool),
        "msa": synthesize_msa(seq_tokens, depth, seed=seed),
        "msa_mask": np.ones((1, depth, L), bool),
    }
    if checkpoint_dir:
        from alphafold2_tpu.train.checkpoint import CheckpointManager

        mgr = CheckpointManager(checkpoint_dir)
        try:
            # abstract params template via eval_shape (no throwaway forward
            # pass); restore params only — inference must not depend on the
            # training run's optimizer-state tree shape
            template = jax.eval_shape(
                lambda: init_end2end_state(cfg, model, batch)
            )
            params, _ = mgr.restore_params(template.params)
        finally:
            mgr.close()
    else:
        params = init_end2end_state(cfg, model, batch).params

    @jax.jit
    def fwd(params):
        return model.apply(
            params,
            jnp.asarray(batch["seq"]),
            jnp.asarray(batch["msa"]),
            mask=jnp.asarray(batch["mask"]),
            msa_mask=jnp.asarray(batch["msa_mask"]),
            mds_key=jax.random.key(seed),
        )

    out = fwd(params)
    atom14 = np.asarray(out["refined"])[0]  # (L, 14, 3)
    return Prediction(
        atom14=atom14,
        backbone=atom14[:, :3],
        weights=np.asarray(out["weights"])[0],
        distogram=np.asarray(out["distogram"])[0],
    )
