"""Structure math: distance binning, distogram centering, NeRF, sidechain lift.

TPU-native (single-jnp, fully batched, jit-compatible) equivalents of the
reference's ``alphafold2_pytorch/utils.py``:

- :func:`get_bucketed_distance_matrix`  <- utils.py:33-38
- :func:`center_distogram`              <- utils.py:269-311 (center_distogram_torch)
- :func:`scn_cloud_mask`                <- utils.py:163-180
- :func:`scn_backbone_mask`             <- utils.py:182-198
- :func:`nerf`                          <- utils.py:200-226 (nerf_torch)
- :func:`sidechain_container`           <- utils.py:228-263

Design notes (not a port):
- One implementation on jnp replaces the reference's torch/numpy dual backend
  (utils.py:42-85) — jax runs on host CPU and TPU alike.
- Everything is batched and traceable: no in-place mutation, no python loops
  over batch or residues (the reference's sidechain O-placement loops per
  residue, utils.py:249-253; here it is one vectorized NeRF call).
- The reference's README feeds raw logits to distogram centering; the math
  assumes a normalized distribution, so :func:`center_distogram` takes
  probabilities (callers softmax first — see models/alphafold2.py head).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from alphafold2_tpu import constants

# bucket thresholds spanning 2-20 A (reference utils.py:29)
DISTANCE_THRESHOLDS = np.linspace(
    constants.DISTOGRAM_MIN_DIST,
    constants.DISTOGRAM_MAX_DIST,
    constants.DISTOGRAM_BUCKETS,
)


def cdist(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Pairwise Euclidean distances, batched: (..., N, D), (..., M, D) -> (..., N, M).

    Uses the expanded-difference form rather than the (x-y)^2 broadcast so the
    inner op is a matmul that lands on the MXU.
    """
    x2 = jnp.sum(x * x, axis=-1, keepdims=True)
    y2 = jnp.sum(y * y, axis=-1, keepdims=True)
    sq = x2 - 2.0 * jnp.einsum("...nd,...md->...nm", x, y) + jnp.swapaxes(y2, -1, -2)
    sq = jnp.maximum(sq, 0.0)
    # safe sqrt: d(sqrt)/dx at 0 is inf; gate it so self-distances carry zero grad
    positive = sq > 0.0
    return jnp.where(positive, jnp.sqrt(jnp.where(positive, sq, 1.0)), 0.0)


def get_bucketed_distance_matrix(
    coords: jnp.ndarray,
    mask: jnp.ndarray,
    num_buckets: int = constants.DISTOGRAM_BUCKETS,
    ignore_index: int = -100,
) -> jnp.ndarray:
    """Discretize pairwise distances into ``num_buckets`` bins over 2-20 A.

    coords: (..., N, 3); mask: (..., N) bool. Pairs where either residue is
    masked get ``ignore_index`` (matches reference utils.py:33-38; the bin
    assignment replicates torch.bucketize(right=False) == searchsorted-left).
    """
    distances = cdist(coords, coords)
    boundaries = jnp.linspace(
        constants.DISTOGRAM_MIN_DIST, constants.DISTOGRAM_MAX_DIST, num_buckets
    )[:-1]
    discretized = jnp.searchsorted(boundaries, distances, side="left")
    pair_mask = mask[..., :, None] & mask[..., None, :]
    return jnp.where(pair_mask, discretized, ignore_index)


def center_distogram(
    distogram: jnp.ndarray,
    bins: jnp.ndarray | None = None,
    center: str = "mean",
    wide: str = "std",
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Central distance estimate + confidence weights from a distogram.

    distogram: (B, N, N, K) *probabilities* (softmax first!).
    Returns (central (B,N,N), weights (B,N,N)).

    Semantics follow reference utils.py:269-311: bin centers are thresholds
    shifted down by half a bin width, first center clamped to 1.5 A, last
    center inflated to 1.33*max (a catch-all "far" bin); pairs whose central
    estimate falls in the last bin get weight 0; the diagonal is zeroed;
    weights = mask / (1 + dispersion), NaNs scrubbed to 0.
    """
    if bins is None:
        bins = jnp.asarray(DISTANCE_THRESHOLDS, dtype=distogram.dtype)
    half_width = 0.5 * (bins[2] - bins[1])
    centers = bins - half_width
    centers = centers.at[0].set(1.5)
    centers = centers.at[-1].set(1.33 * bins[-1])

    if center == "median":
        cum = jnp.cumsum(distogram, axis=-1)
        idx = jnp.sum(cum < 0.5, axis=-1)
        idx = jnp.minimum(idx, centers.shape[0] - 1)
        central = centers[idx]
    elif center == "mean":
        central = jnp.sum(distogram * centers, axis=-1)
    else:
        raise ValueError(f"unknown center mode {center!r}")

    # last-class mask: estimates beyond the penultimate threshold are "no contact"
    mask = (central <= bins[-2]).astype(distogram.dtype)

    n = central.shape[-1]
    eye = jnp.eye(n, dtype=bool)
    central = jnp.where(eye, 0.0, central)

    if wide == "var":
        dispersion = jnp.sum(distogram * (centers - central[..., None]) ** 2, axis=-1)
    elif wide == "std":
        dispersion = jnp.sqrt(
            jnp.sum(distogram * (centers - central[..., None]) ** 2, axis=-1)
        )
    else:
        dispersion = jnp.zeros_like(central)

    weights = mask / (1.0 + dispersion)
    weights = jnp.nan_to_num(weights, nan=0.0)
    return central, weights


def scn_cloud_mask(seq: jnp.ndarray, boolean: bool = True) -> jnp.ndarray:
    """Per-residue atom-existence mask in the 14-slot sidechainnet layout.

    seq: (B, L) int AA indices (AA_ALPHABET order, 20 = pad).
    Returns (B, L, 14) bool. The reference builds this with a python double
    loop over SC_BUILD_INFO (utils.py:171-177); here it is a table lookup.
    """
    counts = jnp.asarray(constants.ATOM_COUNTS)[seq]  # (B, L)
    slots = jnp.arange(constants.NUM_COORDS_PER_RES)
    mask = slots[None, None, :] < counts[..., None]
    if boolean:
        return mask
    return jnp.argwhere(mask)


def scn_backbone_mask(
    seq: jnp.ndarray, boolean: bool = True, l_aa: int = constants.NUM_COORDS_PER_RES
):
    """Masks selecting backbone N (slot 0) and CA (slot 1) in a flat atom stream.

    seq: (B, L). Returns (N_mask, CA_mask) of shape (L*l_aa,).
    Mirrors reference utils.py:182-198 (index-mod construction).
    """
    idx = jnp.arange(seq.shape[-1] * l_aa)
    n_mask = idx % l_aa == 0
    ca_mask = idx % l_aa == 1
    if boolean:
        return n_mask, ca_mask
    return jnp.argwhere(n_mask), jnp.argwhere(ca_mask)


def nerf(
    a: jnp.ndarray,
    b: jnp.ndarray,
    c: jnp.ndarray,
    l: jnp.ndarray,
    theta: jnp.ndarray,
    chi: jnp.ndarray,
) -> jnp.ndarray:
    """Natural extension of reference frame: place atom d from a, b, c.

    a, b, c: (..., 3); l, theta, chi: (...,) bond length, bond angle (radians,
    in [-pi, pi]), dihedral. Returns d: (..., 3). Matches reference
    utils.py:200-226 (rotation-matrix construction), fully batched.
    """
    ba = b - a
    cb = c - b
    n_plane = jnp.cross(ba, cb)
    n_plane_ = jnp.cross(n_plane, cb)
    rotate = jnp.stack([cb, n_plane_, n_plane], axis=-1)
    # guarded normalization: degenerate frames (coincident a/b/c — e.g.
    # padded residues parked at the origin) must yield a finite placement,
    # not a 0/0 NaN that additive attention masks downstream cannot stop
    rotate = rotate / jnp.maximum(
        jnp.linalg.norm(rotate, axis=-2, keepdims=True), 1e-8
    )
    d = jnp.stack(
        [
            -jnp.cos(theta),
            jnp.sin(theta) * jnp.cos(chi),
            jnp.sin(theta) * jnp.sin(chi),
        ],
        axis=-1,
    )
    return c + l[..., None] * jnp.einsum("...ij,...j->...i", rotate, d)


def sidechain_container(
    backbones: jnp.ndarray,
    place_oxygen: bool = False,
    n_atoms: int = constants.NUM_COORDS_PER_RES,
    padding: float = constants.GLOBAL_PAD_CHAR,
    mask: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Lift a (B, L*3, 3) backbone (N, CA, C per residue) to (B, L, 14, 3).

    Slots 0-2 = backbone; slot 3 = carbonyl O (NeRF-placed from the psi
    dihedral when ``place_oxygen``, else CA-copied like the rest); slots 3+
    default to CA copies. Differentiable. Matches reference utils.py:228-263
    but vectorizes the per-residue psi/NeRF loop (utils.py:249-262) into one
    batched NeRF call.

    ``mask``: optional (B, L) residue validity. The psi dihedral reads the
    NEXT residue's N; without a mask, the last *valid* residue of a padded
    chain would read a padded pseudo-atom instead of getting the fixed
    last-residue psi (5pi/4) — its oxygen would then depend on how much
    padding the shape carries.
    """
    batch, length = backbones.shape[0], backbones.shape[1] // 3
    bb = backbones.reshape(batch, length, 3, 3)
    ca = bb[:, :, 1:2]  # (B, L, 1, 3)
    rest = jnp.broadcast_to(ca, (batch, length, n_atoms - 3, 3))
    coords = jnp.concatenate([bb, rest], axis=2)

    if place_oxygen:
        from alphafold2_tpu.utils.metrics import get_dihedral

        n_i, ca_i, c_i = bb[:, :, 0], bb[:, :, 1], bb[:, :, 2]
        n_next = jnp.concatenate([n_i[:, 1:], jnp.zeros_like(n_i[:, :1])], axis=1)
        psis = get_dihedral(n_i, ca_i, c_i, n_next)  # (B, L)
        # psi undefined where no valid next residue exists: the stream's
        # final residue, and (under a mask) every chain-terminal residue;
        # reference uses 5pi/4 there (utils.py:252)
        no_next = (jnp.arange(length) == length - 1)[None, :]
        if mask is not None:
            next_valid = jnp.concatenate(
                [mask[:, 1:], jnp.zeros_like(mask[:, :1])], axis=1
            )
            no_next = no_next | ~next_valid
        psis = jnp.where(no_next, np.pi * 5 / 4, psis)

        bond_len = jnp.full((batch, length), constants.BB_BUILD_INFO["BONDLENS"]["c-o"])
        bond_ang = jnp.full((batch, length), constants.BB_BUILD_INFO["BONDANGS"]["ca-c-o"])
        oxygen = nerf(n_i, ca_i, c_i, bond_len, bond_ang, psis - np.pi)
        coords = coords.at[:, :, 3].set(oxygen)

    return coords
