"""Alignment and structure-quality metrics: Kabsch, RMSD, GDT, TMscore, dihedrals.

Single-jnp, batched equivalents of reference ``alphafold2_pytorch/utils.py``:

- :func:`get_dihedral`     <- utils.py:410-444 (get_dihedral_{torch,numpy})
- :func:`calc_phis`        <- utils.py:446-517
- :func:`kabsch`           <- utils.py:523-567
- :func:`rmsd`/:func:`gdt`/:func:`tmscore` <- utils.py:572-633
- public ``Kabsch``/``RMSD``/``GDT``/``TMscore`` wrappers <- utils.py:707-770

The reference implements each twice (torch + numpy) with a runtime dispatch
decorator chain (utils.py:42-85, 680-770); jnp accepts numpy arrays directly so
one implementation serves both, and the public wrappers keep only the useful
part of that API: automatic batch-dim expansion. A ``backend`` kwarg is
accepted (ignored) for drop-in compatibility.

Differentiability: the SVD inside Kabsch is computed on a stop_gradient'd
covariance (degenerate singular values give NaN grads on every backend; the
reference detaches too, utils.py:533). The rotation is applied to live
tensors, so gradients flow through everything except the rotation itself.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

GDT_TS_CUTOFFS = (1.0, 2.0, 4.0, 8.0)
GDT_HA_CUTOFFS = (0.5, 1.0, 2.0, 4.0)


def _expand_to(t: jnp.ndarray, length: int) -> jnp.ndarray:
    if length <= 0:
        return t
    return t.reshape((1,) * length + t.shape)


def get_dihedral(c1, c2, c3, c4) -> jnp.ndarray:
    """Dihedral angle (radians) for four points, batched over leading dims.

    atan2 polymer-physics formula (reference utils.py:410-426). Inputs (..., 3),
    output (...,).
    """
    u1 = c2 - c1
    u2 = c3 - c2
    u3 = c4 - c3
    y = jnp.sum(
        jnp.linalg.norm(u2, axis=-1, keepdims=True) * u1 * jnp.cross(u2, u3), axis=-1
    )
    x = jnp.sum(jnp.cross(u1, u2) * jnp.cross(u2, u3), axis=-1)
    return jnp.arctan2(y, x)


def calc_phis(
    pred_coords: jnp.ndarray,
    N_mask: jnp.ndarray,
    CA_mask: jnp.ndarray,
    C_mask: jnp.ndarray | None = None,
    prop: bool = True,
):
    """Backbone phi angles (or proportion < 0) used for MDS mirror detection.

    pred_coords: (B, 3, L_atoms); masks: (L_atoms,) bool over the flat atom
    stream. Boolean-mask gathers make this host-side (not jit-traceable) —
    it runs once per structure realization, off the hot path, exactly like
    the reference (utils.py:446-480, gradients detached there too).
    """
    coords = jnp.swapaxes(jax.lax.stop_gradient(pred_coords), -1, -2)  # (B, L, 3)
    N_mask = jnp.asarray(N_mask).reshape(-1)
    CA_mask = jnp.asarray(CA_mask).reshape(-1)
    n_terms = coords[:, N_mask]
    c_alphas = coords[:, CA_mask]
    if C_mask is not None:
        c_terms = coords[:, jnp.asarray(C_mask).reshape(-1)]
    else:
        c_terms = coords[:, ~(N_mask | CA_mask)]
    phis = get_dihedral(
        c_terms[:, :-1], n_terms[:, 1:], c_alphas[:, 1:], c_terms[:, 1:]
    )  # (B, L-1)
    if prop:
        return jnp.mean((phis < 0).astype(jnp.float32), axis=-1)
    return phis


def kabsch(X: jnp.ndarray, Y: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Kabsch-align X onto Y. Both (..., 3, N). Returns (X_aligned, Y_centered).

    Batched over leading dims (the reference is single-structure only,
    utils.py:523-544). SVD on a detached covariance, determinant sign fix via
    where (no data-dependent python branch — jit/vmap safe).
    """
    Xc = X - X.mean(axis=-1, keepdims=True)
    Yc = Y - Y.mean(axis=-1, keepdims=True)
    C = jnp.einsum("...dn,...en->...de", Xc, Yc)
    U, S, Vt = jnp.linalg.svd(jax.lax.stop_gradient(C))
    # sign correction for proper rotation
    d = jnp.linalg.det(U) * jnp.linalg.det(Vt)
    flip = (d < 0.0)[..., None, None]
    U = jnp.concatenate([U[..., :-1], jnp.where(flip, -U[..., -1:], U[..., -1:])], axis=-1)
    R = jnp.einsum("...ij,...jk->...ik", U, Vt)
    X_aligned = jnp.einsum("...nd,...de->...en", jnp.swapaxes(Xc, -1, -2), R)
    return X_aligned, Yc


def rmsd(X: jnp.ndarray, Y: jnp.ndarray) -> jnp.ndarray:
    """RMSD over (..., D, N) -> (...,). Reference utils.py:572-578."""
    return jnp.sqrt(jnp.mean((X - Y) ** 2, axis=(-1, -2)))


def gdt(X, Y, cutoffs, weights=None) -> jnp.ndarray:
    """GDT over (..., D, N) -> (...,): weighted mean of per-cutoff fractions.

    Vectorized over cutoffs (reference loops, utils.py:594-595).
    """
    cutoffs = jnp.asarray(cutoffs, dtype=X.dtype)
    if weights is None:
        weights = jnp.ones_like(cutoffs)
    else:
        weights = jnp.asarray(weights, dtype=X.dtype)
    dist = jnp.sqrt(jnp.sum((X - Y) ** 2, axis=-2))  # (..., N)
    frac = jnp.mean(
        (dist[..., None, :] <= cutoffs[:, None]).astype(X.dtype), axis=-1
    )  # (..., K)
    return jnp.mean(frac * weights, axis=-1)


def tmscore(X: jnp.ndarray, Y: jnp.ndarray) -> jnp.ndarray:
    """TM-score over (..., D, N) -> (...,); d0 = 1.24*cbrt(L-15) - 1.8."""
    L = X.shape[-1]
    d0 = 1.24 * np.cbrt(max(L - 15, 0.1)) - 1.8
    dist = jnp.sqrt(jnp.sum((X - Y) ** 2, axis=-2))
    return jnp.mean(1.0 / (1.0 + (dist / d0) ** 2), axis=-1)


def _lddt_from_distances(
    d_pred: jnp.ndarray,  # (..., N, N)
    d_true: jnp.ndarray,
    mask: jnp.ndarray | None,
    cutoff: float,
    thresholds,
    exclude_neighbors: int = 0,
) -> jnp.ndarray:
    """Shared lDDT scoring core over precomputed distance matrices."""
    n = d_true.shape[-1]
    eye = jnp.eye(n, dtype=bool)
    incl = (d_true < cutoff) & ~eye
    if exclude_neighbors > 0:
        idx = jnp.arange(n)
        near = jnp.abs(idx[:, None] - idx[None, :]) <= exclude_neighbors
        incl = incl & ~near
    if mask is not None:
        incl = incl & mask[..., :, None] & mask[..., None, :]
    delta = jnp.abs(d_true - d_pred)
    th = jnp.asarray(thresholds, dtype=delta.dtype)
    ok = (delta[..., None] < th).astype(delta.dtype).mean(-1)  # (..., N, N)
    # explicit bool->float casts: bool*float and float/int are implicit
    # promotions the strict-promotion audit (jaxpr_audit AF2A105) forbids
    inclf = incl.astype(delta.dtype)
    denom = jnp.maximum(inclf.sum((-1, -2)), 1.0)
    return jnp.sum(ok * inclf, axis=(-1, -2)) / denom


def lddt(
    pred_coords: jnp.ndarray,  # (..., N, 3)
    true_coords: jnp.ndarray,  # (..., N, 3)
    mask: jnp.ndarray | None = None,  # (..., N) bool
    cutoff: float = 15.0,
    thresholds=(0.5, 1.0, 2.0, 4.0),
    exclude_neighbors: int = 0,
) -> jnp.ndarray:
    """Local Distance Difference Test over CA coordinates -> (...,) in [0, 1].

    Superposition-free local quality score (Mariani et al. 2013): for every
    pair within ``cutoff`` A in the TRUE structure, the fraction of pairs
    whose predicted distance deviates by less than each threshold, averaged
    over thresholds. This is the BASELINE.md quality bar ("distogram lDDT");
    the reference defines no lDDT anywhere — only RMSD/GDT/TM.
    """
    from alphafold2_tpu.utils.structure import cdist

    return _lddt_from_distances(
        cdist(pred_coords, pred_coords), cdist(true_coords, true_coords),
        mask, cutoff, thresholds, exclude_neighbors,
    )


def distogram_lddt(
    logits: jnp.ndarray,  # (..., N, N, K) distogram logits
    true_coords: jnp.ndarray,  # (..., N, 3)
    mask: jnp.ndarray | None = None,
    cutoff: float = 15.0,
    thresholds=(0.5, 1.0, 2.0, 4.0),
) -> jnp.ndarray:
    """lDDT of the distogram's expected distances against true geometry.

    Evaluates the distogram directly (no MDS realization): predicted
    distance = probability-weighted bin centers. The BASELINE.md metric.
    """
    from alphafold2_tpu.utils.structure import center_distogram, cdist

    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    d_pred, _ = center_distogram(probs)
    d_true = cdist(true_coords, true_coords)
    return _lddt_from_distances(d_pred, d_true, mask, cutoff, thresholds)


# ---------------------------------------------------------------------------
# Public API wrappers: accept (D, N) or (B, D, N), numpy or jax arrays.
# Names match the reference's exports (utils.py:707-770).
# ---------------------------------------------------------------------------


def _normalize_pair(A, B, dim_len):
    A = jnp.asarray(A)
    B = jnp.asarray(B)
    if A.ndim != B.ndim:
        raise ValueError(
            f"shapes of A ({A.shape}) and B ({B.shape}) must match"
        )
    A = _expand_to(A, dim_len - A.ndim)
    B = _expand_to(B, dim_len - B.ndim)
    return A, B


def Kabsch(A, B, backend: str = "auto"):
    """Kabsch-rotate A into B; inputs (3, N) or (B, 3, N)."""
    del backend
    A, B = _normalize_pair(A, B, 3)
    X, Y = kabsch(A, B)
    if X.shape[0] == 1:
        return X[0], Y[0]
    return X, Y


def RMSD(A, B, backend: str = "auto"):
    del backend
    A, B = _normalize_pair(A, B, 3)
    return rmsd(A, B)


def GDT(A, B, mode: str = "TS", weights=None, backend: str = "auto"):
    del backend
    A, B = _normalize_pair(A, B, 3)
    cutoffs = GDT_HA_CUTOFFS if mode.lower() == "ha" else GDT_TS_CUTOFFS
    return gdt(A, B, cutoffs, weights=weights)


def TMscore(A, B, backend: str = "auto"):
    del backend
    A, B = _normalize_pair(A, B, 3)
    return tmscore(A, B)
