"""Native structure relaxation: gradient descent on a simple backbone energy.

The reference ships only a PyRosetta FastRelax *stub* that raises
NotImplementedError (reference scripts/refinement.py:56-74). This module
goes beyond that contract with a dependency-free, jit-compatible relaxation
usable on TPU: a differentiable energy over backbone geometry minimized
with Adam under ``lax.scan``.

Energy terms (soft analogues of the ideal-geometry + repulsion core of a
relax protocol):

- harmonic bond terms for consecutive backbone bonds N-CA (1.458 A),
  CA-C (1.525 A), C-N' (1.329 A) — same ideal values the NeRF
  reconstruction uses (utils/structure.py);
- a soft-sphere clash penalty between non-bonded atom pairs closer than
  ``clash_dist``;
- a harmonic restraint to the input coordinates so relaxation fixes local
  geometry without drifting from the prediction.

All terms are masked and fully batched; the minimizer is a fixed-iteration
``lax.scan`` (static shape, jit/grad-friendly — no data-dependent stopping,
matching the SURVEY.md S7 compile-model rules).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

# ideal backbone bond lengths (Angstrom), cycling N->CA, CA->C, C->N'
_IDEAL_BONDS = jnp.array([1.458, 1.525, 1.329], jnp.float32)


class RelaxResult(NamedTuple):
    coords: jnp.ndarray  # (B, L3, 3) relaxed backbone
    energy: jnp.ndarray  # (B,) final energy
    energy_history: jnp.ndarray  # (iters, B)


def backbone_energy(
    coords: jnp.ndarray,  # (B, L3, 3) N/CA/C interleaved
    ref_coords: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,  # (B, L3) bool
    clash_dist: float = 2.8,
    bond_weight: float = 1.0,
    clash_weight: float = 0.5,
    restraint_weight: float = 0.02,
) -> jnp.ndarray:
    """Per-batch-element scalar energy. Differentiable everywhere."""
    b, l3, _ = coords.shape
    if mask is None:
        mask = jnp.ones((b, l3), bool)
    fm = mask.astype(jnp.float32)

    # bond terms: consecutive atoms, ideal length cycles with position.
    # A "bond" only counts where the REFERENCE geometry is within 1 A of
    # ideal — chain breaks and sequence gaps (C...N' tens of A apart in the
    # input) are thereby excluded instead of being dragged to 1.329 A.
    deltas = coords[:, 1:] - coords[:, :-1]
    lengths = jnp.sqrt(jnp.sum(deltas**2, -1) + 1e-12)  # (B, L3-1)
    ideal = jnp.tile(_IDEAL_BONDS, l3 // 3 + 1)[: l3 - 1]
    ref_deltas = ref_coords[:, 1:] - ref_coords[:, :-1]
    ref_lengths = jnp.sqrt(jnp.sum(ref_deltas**2, -1) + 1e-12)
    is_bond = (jnp.abs(ref_lengths - ideal) < 1.0).astype(jnp.float32)
    pair_m = fm[:, 1:] * fm[:, :-1] * is_bond
    e_bond = jnp.sum(pair_m * (lengths - ideal) ** 2, -1)

    # soft-sphere clashes between non-bonded pairs (|i-j| > 2). Above a few
    # thousand atoms the dense (B, L3, L3) distance matrix would dominate
    # memory (and OOM under grad), so large structures stream row-chunks
    # with lax.map: peak extra memory O(B * chunk * L3).
    def _clash_rows(rows, frows, iglob, all_coords, fall, jidx):
        d = jnp.sqrt(
            jnp.sum((rows[:, :, None, :] - all_coords[:, None, :, :]) ** 2, -1)
            + 1e-12
        )
        nb = (jnp.abs(iglob[:, None] - jidx[None, :]) > 2)[None]
        pm = frows[:, :, None] * fall[:, None, :] * nb
        return jnp.sum(pm * jnp.maximum(clash_dist - d, 0.0) ** 2, (-1, -2))

    jidx = jnp.arange(l3)
    if l3 <= 1536:
        e_clash = _clash_rows(coords, fm, jidx, coords, fm, jidx) / 2
    else:
        chunk = 512
        pad = (-l3) % chunk
        cp = jnp.pad(coords, ((0, 0), (0, pad), (0, 0)))
        fp = jnp.pad(fm, ((0, 0), (0, pad)))
        jp = jnp.arange(l3 + pad)

        def one(start):
            rows = jax.lax.dynamic_slice_in_dim(cp, start, chunk, axis=1)
            frows = jax.lax.dynamic_slice_in_dim(fp, start, chunk, axis=1)
            return _clash_rows(rows, frows, start + jnp.arange(chunk), cp, fp, jp)

        starts = jnp.arange((l3 + pad) // chunk) * chunk
        e_clash = jnp.sum(jax.lax.map(one, starts), axis=0) / 2

    # restraint to the prediction
    e_rest = jnp.sum(fm * jnp.sum((coords - ref_coords) ** 2, -1), -1)

    return bond_weight * e_bond + clash_weight * e_clash + restraint_weight * e_rest


def fast_relax(
    backbone: jnp.ndarray,  # (B, L3, 3)
    mask: Optional[jnp.ndarray] = None,  # (B, L3) bool
    iters: int = 200,
    lr: float = 2e-2,
    **energy_kw,
) -> RelaxResult:
    """Minimize :func:`backbone_energy` with Adam for a fixed ``iters``.

    The native stand-in for the reference's PyRosetta FastRelax intent;
    jittable, batched, differentiable (gradients flow to ``backbone``)."""
    backbone = jnp.asarray(backbone, jnp.float32)
    ref = jax.lax.stop_gradient(backbone)
    # eps_root: differentiating THROUGH the relaxation backprops across
    # adam's sqrt(v); at v=0 (any zero first-step gradient component) that
    # derivative is NaN without a regularizer inside the root
    opt = optax.adam(lr, eps_root=1e-8)

    def e_total(c):
        return backbone_energy(c, ref, mask=mask, **energy_kw)

    def sum_and_items(c):
        e = e_total(c)
        return jnp.sum(e), e

    def body(carry, _):
        coords, opt_state = carry
        (_, per_item), g = jax.value_and_grad(sum_and_items, has_aux=True)(
            coords
        )
        updates, opt_state = opt.update(g, opt_state, coords)
        coords = optax.apply_updates(coords, updates)
        if mask is not None:
            coords = jnp.where(mask[..., None], coords, ref)
        return (coords, opt_state), per_item

    (coords, _), hist = jax.lax.scan(
        body, (backbone, opt.init(backbone)), None, length=iters
    )
    return RelaxResult(coords=coords, energy=e_total(coords),
                       energy_history=hist)
