"""Multidimensional scaling (SMACOF/Guttman) distogram -> 3D, with mirror fix.

TPU-native equivalent of reference ``alphafold2_pytorch/utils.py``:

- :func:`mds`        <- utils.py:315-408 (mds_torch/mds_numpy, sklearn-adapted)
- :func:`mdscaling`  <- utils.py:636-673
- :func:`MDScaling`  <- utils.py:680-705 (public wrapper)

Design (not a port):
- The reference's data-dependent ``break`` on relative stress improvement
  (utils.py:352-356) becomes a ``done`` flag carried through ``lax.scan`` —
  fixed trip count, jit/grad-compatible, iterations after convergence are
  frozen with ``where``.
- The mirror fix is **per batch element** (the reference compares a whole
  tensor to 0.5 inside a loop, utils.py:645-649 — correct only for batch 1;
  we replicate the capability, not the bug).
- Differentiable end-to-end: gradients flow through the Guttman iterations;
  the phi-based mirror decision is computed on stopped gradients (the sign
  flip itself stays differentiable), matching the reference's detach
  (utils.py:463).
- Random init takes an explicit PRNG key (stateless jax.random) instead of
  global RNG state.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from alphafold2_tpu.utils.metrics import calc_phis, get_dihedral
from alphafold2_tpu.utils.structure import cdist


def mds(
    pre_dist_mat: jnp.ndarray,
    weights: jnp.ndarray | None = None,
    iters: int = 10,
    tol: float = 1e-5,
    key: jax.Array | None = None,
    per_position_init: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Weighted metric MDS via iterative Guttman transform.

    pre_dist_mat: (B, N, N) or (N, N) target distances; weights same shape.
    Returns (coords (B, 3, N), stress_history (iters, B)).

    Padding-aware: the Guttman step divides by the number of *participating*
    points (positions with any positive weight) per batch element, not the
    padded array size, so zero-weighting the pairs that touch padded
    positions makes the valid-region iteration independent of how much
    padding the shape carries. Convergence (``done``) is tracked per batch
    element — co-batched elements cannot freeze or extend each other's
    iterations, which batched serving's solo-vs-batched parity requires.

    ``per_position_init``: derive each position's random start from
    ``fold_in(key, position)`` instead of one draw over the whole (B, N, 3)
    block. The init then depends only on the absolute position index — the
    same residue gets the same start whatever bucket shape or batch slot it
    is served in (the shape-bucketed engine turns this on for
    reproducibility across bucket/batch padding).
    """
    if key is None:
        key = jax.random.key(0)
    pre_dist_mat = jnp.asarray(pre_dist_mat)
    if pre_dist_mat.ndim == 2:
        pre_dist_mat = pre_dist_mat[None]
    batch, N, _ = pre_dist_mat.shape

    if per_position_init:
        pos = jnp.arange(N)
        draw = jax.vmap(
            lambda i: jax.random.uniform(
                jax.random.fold_in(key, i), (3,), pre_dist_mat.dtype
            )
        )(pos)  # (N, 3), independent of batch/bucket shape
        coords0 = jnp.broadcast_to(2.0 * draw - 1.0, (batch, N, 3))
    else:
        coords0 = (
            2.0 * jax.random.uniform(key, (batch, N, 3), pre_dist_mat.dtype)
            - 1.0
        )
    if weights is None:
        weights = jnp.ones_like(pre_dist_mat)
        n_eff = jnp.full((batch,), float(N), pre_dist_mat.dtype)
    else:
        participating = jnp.any(weights > 0, axis=-1)  # (B, N)
        n_eff = jnp.maximum(
            jnp.sum(participating, axis=-1).astype(pre_dist_mat.dtype), 1.0
        )
    diag = jnp.eye(N, dtype=pre_dist_mat.dtype)

    def step(carry, _):
        coords, best_stress, done = carry
        dist_mat = cdist(coords, coords)
        stress = 0.5 * jnp.sum(weights * (dist_mat - pre_dist_mat) ** 2, axis=(-1, -2))
        dist_mat = jnp.where(dist_mat == 0.0, 1e-7, dist_mat)
        ratio = weights * (pre_dist_mat / dist_mat)
        B = -ratio + diag * jnp.sum(ratio, axis=-1, keepdims=True)
        new_coords = jnp.einsum("bij,bjd->bid", B, coords) / n_eff[:, None, None]
        dis = jnp.linalg.norm(new_coords, axis=(-1, -2))
        rel_stress = stress / dis
        # converged when the element's relative improvement drops below tol
        improved = (best_stress - rel_stress) > tol
        done = done | ~improved  # (B,)
        coords = jnp.where(done[:, None, None], coords, new_coords)
        best_stress = jnp.where(done, best_stress, rel_stress)
        return (coords, best_stress, done), rel_stress

    init = (
        coords0,
        jnp.full((batch,), jnp.inf, pre_dist_mat.dtype),
        jnp.zeros((batch,), bool),
    )
    (coords, _, _), history = jax.lax.scan(step, init, None, length=iters)
    return jnp.swapaxes(coords, -1, -2), history


def _flip_mirrors(preds: jnp.ndarray, phi_ratios: jnp.ndarray) -> jnp.ndarray:
    """Flip the Z axis of batch elements whose negative-phi ratio < 0.5."""
    flip = (phi_ratios < 0.5)[:, None]  # (B, 1)
    z = jnp.where(flip, -preds[:, -1], preds[:, -1])
    return preds.at[:, -1].set(z)


def mdscaling(
    pre_dist_mat,
    weights=None,
    iters: int = 10,
    tol: float = 1e-5,
    fix_mirror: bool = True,
    N_mask=None,
    CA_mask=None,
    C_mask=None,
    key: jax.Array | None = None,
):
    """MDS + chirality correction via backbone phi angles.

    Masks are boolean over the flat atom stream (see scn_backbone_mask). The
    mask-gather path is host-side; for a fully jittable pipeline use
    :func:`mdscaling_backbone`.
    """
    preds, stresses = mds(pre_dist_mat, weights=weights, iters=iters, tol=tol, key=key)
    if not fix_mirror:
        return preds, stresses
    phi_ratios = calc_phis(preds, N_mask, CA_mask, C_mask, prop=True)
    return _flip_mirrors(preds, phi_ratios), stresses


def calc_phis_backbone(
    coords: jnp.ndarray,
    prop: bool = True,
    mask: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Phi angles assuming the flat stream is (N, CA, C) repeating (l_aa=3).

    coords: (B, 3, L*3). Static reshape instead of boolean gathers -> traceable
    under jit, for use inside a compiled end-to-end train step.

    ``mask``: optional (B, L) residue validity. Padded residues sit at
    degenerate (zeroed) coordinates whose dihedrals are meaningless; with a
    mask, the negative-phi ratio averages only transitions where both
    flanking residues are valid, so padding cannot skew the chirality
    decision toward a spurious mirror flip.
    """
    coords = jnp.swapaxes(jax.lax.stop_gradient(coords), -1, -2)  # (B, 3L, 3)
    b, flat, _ = coords.shape
    res = coords.reshape(b, flat // 3, 3, 3)  # (B, L, atom, 3)
    n, ca, c = res[:, :, 0], res[:, :, 1], res[:, :, 2]
    phis = get_dihedral(c[:, :-1], n[:, 1:], ca[:, 1:], c[:, 1:])
    if not prop:
        return phis
    neg = (phis < 0).astype(jnp.float32)
    if mask is None:
        return jnp.mean(neg, axis=-1)
    valid = (mask[:, :-1] & mask[:, 1:]).astype(jnp.float32)  # (B, L-1)
    return jnp.sum(neg * valid, axis=-1) / jnp.maximum(
        jnp.sum(valid, axis=-1), 1.0
    )


def mdscaling_backbone(
    pre_dist_mat,
    weights=None,
    iters: int = 10,
    tol: float = 1e-5,
    fix_mirror: bool = True,
    key: jax.Array | None = None,
    residue_mask: jnp.ndarray | None = None,
    per_position_init: bool = False,
):
    """Jit-compatible MDScaling for (N, CA, C)-elongated backbone streams.

    ``residue_mask``: (B, L) validity over residues (NOT the 3L atom
    stream) restricting the chirality statistic to real residues.
    """
    preds, stresses = mds(
        pre_dist_mat, weights=weights, iters=iters, tol=tol, key=key,
        per_position_init=per_position_init,
    )
    if not fix_mirror:
        return preds, stresses
    phi_ratios = calc_phis_backbone(preds, prop=True, mask=residue_mask)
    return _flip_mirrors(preds, phi_ratios), stresses


def MDScaling(pre_dist_mat, backend: str = "auto", **kwargs):
    """Public API matching the reference (utils.py:680-705).

    pre_dist_mat: (N, N) or (B, N, N). Returns (coords (B, 3, N), stress
    history). ``backend`` accepted for compatibility, ignored (one jnp impl).
    """
    del backend
    pre_dist_mat = jnp.asarray(pre_dist_mat)
    if pre_dist_mat.ndim == 2:
        pre_dist_mat = pre_dist_mat[None]
    return mdscaling(pre_dist_mat, **kwargs)
