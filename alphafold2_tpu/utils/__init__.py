from alphafold2_tpu.utils.structure import (
    DISTANCE_THRESHOLDS,
    cdist,
    center_distogram,
    get_bucketed_distance_matrix,
    nerf,
    scn_backbone_mask,
    scn_cloud_mask,
    sidechain_container,
)
from alphafold2_tpu.utils.metrics import (
    GDT,
    Kabsch,
    RMSD,
    TMscore,
    calc_phis,
    distogram_lddt,
    gdt,
    get_dihedral,
    kabsch,
    lddt,
    rmsd,
    tmscore,
)
from alphafold2_tpu.utils.mds import (
    MDScaling,
    calc_phis_backbone,
    mds,
    mdscaling,
    mdscaling_backbone,
)
