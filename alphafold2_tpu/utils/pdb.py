"""PDB I/O: pure-python parse / write / clean / coordinate-export.

Replaces the reference's mdtraj+curl path (reference utils.py:92-158:
``download_pdb`` shells out to curl, ``clean_pdb`` selects chains via mdtraj
topology, ``custom2pdb`` rewrites a downloaded scaffold's coordinates).
Host-side I/O has no TPU perf constraint (SURVEY.md S2.4), so this is a
dependency-free implementation:

- :class:`PDBStructure` — columnar atom records (numpy arrays), the unit all
  functions operate on. Columnar beats an object-per-atom topology here: the
  common operations (chain select, CA extraction, coordinate replacement) are
  boolean-mask one-liners, and coords land directly in the (N, 3) float32
  layout the jnp structure math consumes.
- :func:`parse_pdb` / :func:`to_pdb_string` — fixed-column ATOM/HETATM record
  codec (PDB format v3.3).
- :func:`clean_pdb` — keep protein ATOM records, optionally one chain
  (reference utils.py:103-129).
- :func:`download_pdb` — RCSB fetch via urllib (reference utils.py:92-101);
  network-gated with a clear error in hermetic environments.
- :func:`custom2pdb` — model coords -> .pdb via a scaffold whose coordinates
  are replaced in file order (reference utils.py:131-158), taking an optional
  local scaffold path instead of forcing a download.
- :func:`backbone_to_pdb` — scaffold-free export: build a PDB directly from a
  predicted (L, 3, 3) N/CA/C backbone (or (L, 3) CA trace) + sequence, which
  the reference cannot do at all.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence

import numpy as np

from alphafold2_tpu import constants

THREE_TO_ONE = {
    "ALA": "A", "CYS": "C", "ASP": "D", "GLU": "E", "PHE": "F",
    "GLY": "G", "HIS": "H", "ILE": "I", "LYS": "K", "LEU": "L",
    "MET": "M", "ASN": "N", "PRO": "P", "GLN": "Q", "ARG": "R",
    "SER": "S", "THR": "T", "VAL": "V", "TRP": "W", "TYR": "Y",
    # common non-standard residues mapped to their parent
    "MSE": "M", "SEC": "C", "PYL": "K",
}
ONE_TO_THREE = {v: k for k, v in reversed(list(THREE_TO_ONE.items()))}


@dataclasses.dataclass
class PDBStructure:
    """Columnar ATOM/HETATM records of one model."""

    serial: np.ndarray  # (N,) int32
    name: np.ndarray  # (N,) <U4 atom name, e.g. "CA"
    resname: np.ndarray  # (N,) <U3
    chain: np.ndarray  # (N,) <U1
    resseq: np.ndarray  # (N,) int32
    coords: np.ndarray  # (N, 3) float32 Angstroms
    element: np.ndarray  # (N,) <U2
    hetero: np.ndarray  # (N,) bool — HETATM record
    icode: np.ndarray = None  # (N,) <U1 insertion code ('' when absent)

    def __post_init__(self):
        if self.icode is None:  # constructors predating insertion codes
            self.icode = np.full(len(self.serial), "", "<U1")

    def __len__(self) -> int:
        return len(self.serial)

    def _icode(self) -> np.ndarray:
        return self.icode

    def select(self, mask: np.ndarray) -> "PDBStructure":
        return PDBStructure(
            self.serial[mask], self.name[mask], self.resname[mask],
            self.chain[mask], self.resseq[mask], self.coords[mask],
            self.element[mask], self.hetero[mask], self._icode()[mask],
        )

    def chains(self) -> list[str]:
        seen: dict[str, None] = {}
        for c in self.chain:
            seen.setdefault(str(c), None)
        return list(seen)

    def ca_trace(self) -> tuple[str, np.ndarray]:
        """(sequence, (L, 3) CA coords) over protein residues, file order."""
        mask = (self.name == "CA") & ~self.hetero
        sub = self.select(mask)
        seq = "".join(THREE_TO_ONE.get(str(r), "X") for r in sub.resname)
        return seq, sub.coords.copy()

    def backbone_trace(
        self, return_indices: bool = False
    ) -> tuple[str, np.ndarray] | tuple[str, np.ndarray, np.ndarray]:
        """(sequence, (L, 3, 3) N/CA/C coords) over protein residues that
        have all three backbone atoms, file order. ``return_indices`` adds
        the (L, 3) row indices of those atoms into THIS structure's arrays
        (for scattering modified coordinates back without losing chains,
        numbering, or other atoms)."""
        residues: dict = {}
        order: list = []
        icodes = self._icode()
        for i in range(len(self)):
            if self.hetero[i]:
                continue
            key = (str(self.chain[i]), int(self.resseq[i]), str(icodes[i]))
            if key not in residues:
                residues[key] = {"resname": str(self.resname[i])}
                order.append(key)
            nm = str(self.name[i])
            if nm in ("N", "CA", "C") and nm not in residues[key]:
                residues[key][nm] = i
        seq_chars, coords, indices = [], [], []
        for key in order:
            r = residues[key]
            if all(nm in r for nm in ("N", "CA", "C")):
                seq_chars.append(THREE_TO_ONE.get(r["resname"], "X"))
                rows = [r["N"], r["CA"], r["C"]]
                indices.append(rows)
                coords.append([self.coords[j] for j in rows])
        seq = "".join(seq_chars)
        coords_arr = np.asarray(coords, np.float32).reshape(-1, 3, 3)
        if return_indices:
            return seq, coords_arr, np.asarray(indices, np.int64).reshape(-1, 3)
        return seq, coords_arr


def parse_pdb(text: str) -> PDBStructure:
    """Parse ATOM/HETATM records (first MODEL only) from PDB-format text."""
    serial, name, resname, chain, resseq = [], [], [], [], []
    coords, element, hetero, icode = [], [], [], []
    for line in text.splitlines():
        rec = line[:6]
        if rec == "ENDMDL":  # first model only, like mdtraj's default frame
            break
        if rec not in ("ATOM  ", "HETATM"):
            continue
        # altloc: keep blank or 'A' only
        if line[16] not in (" ", "A"):
            continue
        serial.append(int(line[6:11]))
        name.append(line[12:16].strip())
        resname.append(line[17:20].strip())
        chain.append(line[21])
        resseq.append(int(line[22:26]))
        icode.append(line[26].strip() if len(line) > 26 else "")
        coords.append(
            (float(line[30:38]), float(line[38:46]), float(line[46:54]))
        )
        element.append(line[76:78].strip() if len(line) >= 78 else "")
        hetero.append(rec == "HETATM")
    return PDBStructure(
        np.asarray(serial, np.int32), np.asarray(name, "<U4"),
        np.asarray(resname, "<U3"), np.asarray(chain, "<U1"),
        np.asarray(resseq, np.int32),
        np.asarray(coords, np.float32).reshape(-1, 3),
        np.asarray(element, "<U2"), np.asarray(hetero, bool),
        np.asarray(icode, "<U1"),
    )


def load_pdb(path: str) -> PDBStructure:
    with open(path) as f:
        return parse_pdb(f.read())


def to_pdb_string(s: PDBStructure) -> str:
    """Serialize to fixed-column PDB v3.3 ATOM/HETATM records + TER/END."""
    lines = []
    prev_chain = None
    for i in range(len(s)):
        if prev_chain is not None and s.chain[i] != prev_chain:
            lines.append("TER")
        prev_chain = s.chain[i]
        rec = "HETATM" if s.hetero[i] else "ATOM  "
        nm = str(s.name[i])
        # PDB atom-name column quirk: 1-letter elements start at col 14
        nm = f" {nm:<3}" if len(nm) < 4 and len(str(s.element[i])) < 2 else f"{nm:<4}"
        x, y, z = (float(v) for v in s.coords[i])
        ic = str(s._icode()[i]) or " "
        lines.append(
            f"{rec}{int(s.serial[i]):5d} {nm} {str(s.resname[i]):>3}"
            f" {str(s.chain[i])}{int(s.resseq[i]):4d}{ic}   "
            f"{x:8.3f}{y:8.3f}{z:8.3f}{1.0:6.2f}{0.0:6.2f}"
            f"          {str(s.element[i]):>2}"
        )
    lines.append("TER")
    lines.append("END")
    return "\n".join(lines) + "\n"


def save_pdb(s: PDBStructure, path: str) -> str:
    with open(path, "w") as f:
        f.write(to_pdb_string(s))
    return path


def download_pdb(name: str, route: str, timeout: float = 30.0) -> str:
    """Fetch an RCSB entry (reference utils.py:92-101 shells out to curl).

    Raises a clear RuntimeError in hermetic (no-egress) environments instead
    of silently writing an empty file like ``curl > route`` does.
    """
    import urllib.error
    import urllib.request

    url = f"https://files.rcsb.org/download/{name}.pdb"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            data = resp.read()
    except (urllib.error.URLError, OSError) as e:
        raise RuntimeError(
            f"cannot download {url!r} (no network access?): {e}"
        ) from e
    with open(route, "wb") as f:
        f.write(data)
    return route


def clean_pdb(
    name: str,
    route: Optional[str] = None,
    chain_id: Optional[str] = None,
    chain_num: Optional[int] = None,
) -> str:
    """Keep protein ATOM records, optionally a single chain; write back.

    Mirrors reference utils.py:103-129 (mdtraj chain selection) with the same
    overwrite-input default. ``chain_num`` is the 0-based chain index in file
    order (the reference compares against mdtraj's ``chain.index``);
    ``chain_id`` selects by letter.
    """
    destin = route if route is not None else name
    s = load_pdb(name)
    keep = ~s.hetero & np.isin(s.resname, list(THREE_TO_ONE))
    if chain_id is not None:
        keep &= s.chain == chain_id
    elif chain_num is not None:
        keep &= s.chain == s.chains()[chain_num]
    return save_pdb(s.select(keep), destin)


def replace_coords(s: PDBStructure, coords: np.ndarray) -> PDBStructure:
    """New structure with coordinates replaced in file order (scaffold trick,
    reference utils.py:152-157)."""
    coords = np.asarray(coords, np.float32)
    if coords.shape[0] == 3 and coords.shape[-1] != 3:
        coords = coords.T
    if coords.shape != s.coords.shape:
        raise ValueError(
            f"coords shape {coords.shape} != structure {s.coords.shape}"
        )
    return dataclasses.replace(s, coords=coords)


def custom2pdb(
    coords,
    proteinnet_id: str,
    route: str,
    scaffold_path: Optional[str] = None,
) -> tuple[str, str]:
    """Model coords -> .pdb via a scaffold structure (reference utils.py:131-158).

    proteinnet_id: ``<class>#<pdb_id>_<chain_number>_<chain_id>``. When
    ``scaffold_path`` is given the download step is skipped (the reference
    always re-downloads); coordinates are replaced in file order.
    """
    coords = np.asarray(coords, np.float32)
    tokens = proteinnet_id.split("#")[-1].split("_")
    pdb_name, chain_num = tokens[0], tokens[1]
    if scaffold_path is None:
        scaffold_path = os.path.join(os.path.dirname(route) or ".", pdb_name + ".pdb")
        download_pdb(pdb_name, scaffold_path)
        clean_pdb(scaffold_path, chain_num=int(chain_num))
    scaffold = load_pdb(scaffold_path)
    save_pdb(replace_coords(scaffold, coords), route)
    return scaffold_path, route


def backbone_to_pdb(
    seq: Sequence[int] | str,
    backbone: np.ndarray,
    chain: str = "A",
) -> PDBStructure:
    """Build a structure from predicted coords — no scaffold needed.

    seq: length-L string or int indices (AA_ALPHABET order). backbone:
    (L, 3, 3) N/CA/C per residue, or (L, 3) CA-only. This is the natural
    export for the end-to-end pipeline's MDS/refined output
    (train/end2end.py), which the reference could only write through a
    downloaded scaffold of the *true* structure.
    """
    backbone = np.asarray(backbone, np.float32)
    if isinstance(seq, str):
        letters = list(seq)
    else:
        letters = [
            constants.AA_ALPHABET[int(i)] if int(i) < 20 else "X" for i in seq
        ]
    L = len(letters)
    ca_only = backbone.ndim == 2
    names = ["CA"] if ca_only else ["N", "CA", "C"]
    per = len(names)
    if backbone.size != L * per * 3:
        raise ValueError(
            f"backbone {backbone.shape} does not hold {L} residues x "
            f"{per} atoms x 3"
        )
    coords = backbone.reshape(L * per, 3)
    n = L * per
    return PDBStructure(
        serial=np.arange(1, n + 1, dtype=np.int32),
        name=np.asarray(names * L, "<U4"),
        resname=np.asarray(
            [ONE_TO_THREE.get(a, "UNK") for a in letters for _ in names], "<U3"
        ),
        chain=np.full(n, chain, "<U1"),
        resseq=np.repeat(np.arange(1, L + 1, dtype=np.int32), per),
        coords=coords,
        element=np.asarray([nm[0] for nm in names] * L, "<U2"),
        hetero=np.zeros(n, bool),
    )
