"""Dataclass config tree + CLI parsing.

The reference has no config system at all — hyperparameters are module-level
constants edited in-source (train_pre.py:13-24, train_end2end.py:22-28,
constants.py:5-14) and model config is ctor kwargs (alphafold2.py:330-350).
SURVEY.md S5.6 calls for a real config system; this is it: typed dataclasses,
flat ``--section.field=value`` CLI overrides, JSON round-trip for
checkpointing reproducibility.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Optional, Tuple


@dataclass
class ModelConfig:
    dim: int = 256  # trunk embedding width (single-repr channels)
    max_seq_len: int = 2048  # positional-embedding table size (max residues)
    depth: int = 6  # trunk layers (MSA+pair block repeats)
    heads: int = 8  # attention heads per layer
    dim_head: int = 64  # per-head channel width
    attn_dropout: float = 0.0  # attention-prob dropout rate (train only)
    ff_dropout: float = 0.0  # feedforward dropout rate (train only)
    # exact erf GELU in the GEGLU feedforwards (the reference's torch
    # F.gelu); default False = tanh approximation, the faster form on TPU
    gelu_exact: bool = False
    remat: bool = False  # rematerialize trunk layers (memory for recompute)
    # remat checkpoint policy: None/"nothing" (save nothing — max memory
    # savings) | "dots" | "dots_no_batch" (save matmul outputs: backward
    # skips recomputing MXU-heavy ops — the memory/MFU trade)
    remat_policy: Optional[str] = None
    reversible: bool = False  # inversion-based O(1)-memory trunk engine
    sparse_self_attn: bool = False  # block-sparse axial self-attention
    cross_attn_compress_ratio: int = 1  # pair-token pooling for cross-attn
    msa_tie_row_attn: bool = False  # tie row-attention logits across MSA rows
    # shard the MSA-row axis over sp: the tied-row logit sum completes via
    # an XLA-inserted psum, scaling MSA depth across the mesh
    msa_row_shard: bool = False
    # sequence/context parallelism for the cross-attention over the N^2 pair
    # tokens: None | "ring" | "ulysses" (parallel/seq_parallel.py)
    context_parallel: Optional[str] = None
    # fused Pallas flash attention for dense paths: None = auto (on TPU)
    flash_attention: Optional[bool] = None
    # 2D-sharded pair axial attention over a (dp, spr, spc) grid mesh
    grid_parallel: bool = False
    # compile the trunk as ONE scanned layer with stacked params (compile
    # time independent of depth); needs homogeneous layers
    scan_layers: bool = False
    template_attn_depth: int = 2  # template pointwise-attention layers
    bfloat16: bool = True  # compute dtype on TPU
    # parameter init distributions: "flax" (lecun-normal Dense, N(0,1/dim)
    # embeddings) | "torch" (the reference's module defaults — see
    # models/init.py; incompatible with scan_layers' stacked params)
    init_scheme: str = "flax"


@dataclass
class MeshConfig:
    data_parallel: int = 1  # dp axis size; -1 = fill with all devices
    seq_parallel: int = 1  # sp axis size (pair-map row sharding)
    # 2D pair-grid sharding (parallel/grid_parallel.py); both > 1 builds a
    # (dp, spr, spc) mesh instead of (dp, sp)
    grid_rows: int = 1  # spr axis (pair-row shards)
    grid_cols: int = 1  # spc axis (pair-col shards)


@dataclass
class DataConfig:
    crop_len: int = 128  # residues per crop (static shape)
    msa_depth: int = 5  # MSA rows per example
    msa_len: int = 64  # MSA row length (columns)
    batch_size: int = 1  # examples per training batch
    max_len_filter: int = 250  # drop chains longer than this (train_pre.py:47)
    min_len_filter: int = 16  # drop chains shorter than this
    source: str = "synthetic"  # "synthetic" | "native" | "npz" | "sidechainnet"
    casp_version: int = 12  # sidechainnet CASP release to load
    thinning: int = 30  # sidechainnet thinning percentage
    data_dir: Optional[str] = None  # on-disk dataset root for "npz"/"native"
    # feature stream fed beside the sequence (reference train_end2end.py:22-28
    # FEATURES): "msa" | "plm" (frozen PLM embeddings via data/plm.py) | "none"
    features: str = "msa"
    plm_provider: str = "hash"  # "hash" | "precomputed" | "esm"
    plm_path: Optional[str] = None  # .npz archive for "precomputed"


@dataclass
class ServeConfig:
    """Shape-bucketed batched inference (alphafold2_tpu/serve).

    Sequence lengths are padded up a geometric bucket ladder so the number
    of distinct compiled executables is bounded by ``len(buckets)`` instead
    of the number of distinct request lengths; requests sharing a bucket are
    batched up to ``max_batch`` with batch-dim padding (masked dummy slots)
    so each bucket compiles exactly one (bucket, max_batch) executable."""

    buckets: Tuple[int, ...] = (64, 96, 128, 192, 256)  # residues, ascending
    # mesh-gated long-chain rungs (e.g. 512,768,1024 — the crop-free
    # ladder): their O(N^2) pair state only fits per-device memory when
    # sharded, so ServeEngine REJECTS them without a device mesh and admits
    # them (appended above ``buckets``) when constructed with one
    long_buckets: Tuple[int, ...] = ()
    # requests fused per dispatch on the long-chain rungs (their per-request
    # memory is what the mesh exists to shard; batch multiplies it back)
    long_max_batch: int = 1
    max_batch: int = 4  # requests fused per dispatch (batch-dim padded)
    # pad partial chunks up to max_batch: one executable per bucket (the
    # serving default); False compiles one executable per seen chunk size
    pad_batches: bool = True
    msa_depth: int = 0  # synthesized MSA rows per request; 0 -> data.msa_depth
    mds_iters: int = 200  # structure-realization Guttman iterations
    # serving precision: "float32" (default — model.bfloat16 still governs
    # the TPU compute dtype exactly as before) | "bfloat16" (params cast to
    # bf16 at engine build + bf16 compute; numerically gated by the drift
    # bounds tests/test_precision.py pins, and fingerprinted as distinct
    # graph-contract targets so precision changes are explicit diffs)
    dtype: str = "float32"
    # kernel policy spec (ops/kernels.py KernelPolicy), e.g.
    # "tied_row=pallas,axial=pallas"; "" = the process default
    # (AF2TPU_KERNELS env var, all-auto when unset). The resolved identity
    # keys the engine's executable cache, compile records and bench records.
    kernels: str = ""
    donate_buffers: bool = True  # donate per-request feature buffers to XLA
    return_distogram: bool = False  # ship (3L,3L,K) logits back per request
    # --- pipelined dispatch (serve/pipeline.py: PipelinedDispatcher) ---
    # batches in flight at once: the host stage featurizes + device_puts
    # batch N+1 while batch N computes and batch N-1's results fetch, so
    # the executable stays fed. 2 = classic double buffering; 0 disables
    # the pipeline (every dispatch runs the serial featurize->compute->
    # fetch path in the calling thread, pre-pipeline behavior)
    pipeline_depth: int = 2
    # admit a request arriving while its bucket's next formation is still
    # in the host stage into that in-flight batch (continuous batching)
    # instead of making it wait a full fill-or-dwell window
    inflight_admission: bool = True
    # --- async frontend (serve/scheduler.py: AsyncServeFrontend) ---
    queue_depth: int = 64  # bounded admission queue; full -> structured reject
    dwell_ms: float = 25.0  # max wait for batch fill before partial dispatch
    default_deadline_s: float = 0.0  # per-request deadline; 0 = none
    cache_size: int = 256  # (seq, seed)-keyed LRU result entries; 0 disables
    shed_watermark: float = 0.75  # queue fraction where low-priority sheds
    retry_failed: bool = True  # retry a failed dispatch on another executable
    # --- variant-scan fast lane (serve/cache.py FeatureCache + affinity) ---
    # featurized input trees kept in the content-addressed FeatureCache
    # (leaf-interned LRU over derivation keys); 0 disables the layer
    feature_cache_size: int = 128
    # featurize a point mutant of a cached parent by patching only the
    # columns its mutation touches (data.pipeline.featurize_delta) instead
    # of recomputing the whole tree — byte-identical to cold featurization
    delta_featurize: bool = True
    # pack same-parent mutants (edit-distance-1 family, or an explicit
    # ServeRequest.parent_id hint) into the same bucket formation so scan
    # traffic rides full near-zero-padding batches
    affinity_batching: bool = True


@dataclass
class TrainConfig:
    learning_rate: float = 3e-4  # train_pre.py:18
    num_steps: int = 100000  # train_pre.py:14 NUM_BATCHES
    gradient_accumulate_every: int = 16  # train_pre.py:16
    warmup_steps: int = 1000  # linear LR warmup steps before cosine decay
    weight_decay: float = 0.0  # AdamW decoupled weight decay
    seed: int = 0  # PRNG seed for params + data order
    log_every: int = 50  # steps between train-metric log lines
    checkpoint_every: int = 1000  # steps between checkpoint writes
    checkpoint_dir: Optional[str] = None  # checkpoint root; None disables
    keep_checkpoints: int = 3  # newest checkpoints retained (older pruned)
    profile_dir: Optional[str] = None  # jax.profiler trace output
    profile_steps: Tuple[int, int] = (10, 13)  # [start, end) profiled steps
    # observe.Tracer span output (Chrome trace-event JSONL, Perfetto-
    # loadable): per-step host-side spans beside the XLA profile above
    trace_events: Optional[str] = None
    # in-graph numerics telemetry (observe.numerics): "off" | "triage"
    # (per-parameter-group norms every step; on a non-finite-grad skip,
    # rerun the step fully tagged and report the first bad tensor) |
    # "full" (tagged activation stats on every step). AF2TPU_NUMERICS
    # env var overrides per run.
    numerics: str = "triage"


def _tuplify(section, name):
    """JSON round-trips tuples as lists; restore the tuple type so configs
    hash/compare consistently (executable-cache keys include buckets)."""
    value = getattr(section, name)
    if isinstance(value, list):
        setattr(section, name, tuple(value))
    return section


@dataclass
class Config:
    model: ModelConfig = field(default_factory=ModelConfig)  # architecture
    mesh: MeshConfig = field(default_factory=MeshConfig)  # device mesh axes
    data: DataConfig = field(default_factory=DataConfig)  # dataset + features
    train: TrainConfig = field(default_factory=TrainConfig)  # optimizer loop
    serve: ServeConfig = field(default_factory=ServeConfig)  # inference plane

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @classmethod
    def from_json(cls, s: str) -> "Config":
        raw = json.loads(s)
        return cls(
            model=ModelConfig(**raw.get("model", {})),
            mesh=MeshConfig(**raw.get("mesh", {})),
            data=DataConfig(**raw.get("data", {})),
            train=_tuplify(TrainConfig(**raw.get("train", {})), "profile_steps"),
            serve=_tuplify(
                _tuplify(ServeConfig(**raw.get("serve", {})), "buckets"),
                "long_buckets",
            ),
        )

    def apply_overrides(self, overrides: list[str]) -> "Config":
        """Apply ``section.field=value`` strings (CLI) onto a copy."""
        cfg = dataclasses.replace(self)
        for item in overrides:
            key, _, value = item.partition("=")
            key = key.lstrip("-")
            section_name, _, field_name = key.partition(".")
            section = getattr(cfg, section_name)
            if not hasattr(section, field_name):
                raise KeyError(f"unknown config field {key!r}")
            current = getattr(section, field_name)
            if isinstance(current, bool):
                parsed = value.lower() in ("1", "true", "yes")
            elif isinstance(current, int):
                parsed = int(value)
            elif isinstance(current, float):
                parsed = float(value)
            elif isinstance(current, tuple):
                # comma-separated ints, e.g. --serve.buckets=64,128,256
                parsed = tuple(int(v) for v in value.split(",") if v)
            else:
                parsed = value
            setattr(section, field_name, parsed)
        return cfg


def parse_cli(argv: list[str], base: Optional[Config] = None) -> Config:
    cfg = base or Config()
    return cfg.apply_overrides([a for a in argv if "=" in a])
