"""Axon-relay compile-mode preflight, shared by every TPU driver.

Observed failure mode (see bench.py and scripts/tpu_session.py): the
tunneled TPU relay's backend init succeeds but its /remote_compile HTTP
endpoint is dead — the first jax computation then hangs inside C++ with no
timeout (a 50-minute session was lost to exactly this in round 2). The
compile mode is fixed at interpreter start (the site hook reads
``PALLAS_AXON_REMOTE_COMPILE`` when it registers the PJRT plugin), so the
probe must run in subprocesses and switching modes requires re-exec'ing the
current driver.

``preflight_compile_mode`` is called by drivers (bench.py __main__,
scripts/tpu_session.py main) BEFORE their first jax computation. It either
returns a status string or — when remote compile is dead but client-side
compile works — re-execs the current process with
``PALLAS_AXON_REMOTE_COMPILE=0`` (never returns).
"""

from __future__ import annotations

import os
import subprocess
import sys

_PROBE = (
    "import jax, jax.numpy as jnp; "
    "assert float(jnp.ones((8, 8)).sum()) == 64.0"
)


def scrub_axon_env(env: dict | None = None) -> dict:
    """A copy of ``env`` (default os.environ) with the axon tunnel hook
    removed: no ``.axon_site`` PYTHONPATH entry (its sitecustomize patches
    jax's backend lookup at interpreter start), no PALLAS_AXON/AXON_ vars,
    platform forced to CPU. The single source of truth for "run a
    subprocess on the host backend, never the tunnel" — used by the TPU
    lowering gate (cross-platform lowering hangs through the hook) and by
    tests of the liveness probe."""
    env = dict(os.environ if env is None else env)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and ".axon_site" not in p
    )
    for k in list(env):
        if k.startswith(("PALLAS_AXON", "AXON_")):
            del env[k]
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _probe_ok(extra_env: dict | None = None, timeout: int = 240) -> bool:
    """Run one tiny jax computation in a subprocess; True iff it completes."""
    try:
        return (
            subprocess.run(
                [sys.executable, "-c", _PROBE],
                env={**os.environ, **(extra_env or {})},
                timeout=timeout,
                capture_output=True,
            ).returncode
            == 0
        )
    except subprocess.TimeoutExpired:
        return False


def preflight_compile_mode(
    remaining_fn=None,
    deadline_env_var: str | None = None,
    probe_timeout: int = 240,
) -> str:
    """Probe the relay's compile modes; re-exec into client-side compile if
    that is the only working mode.

    Returns one of:
      ``"skipped"``         — host-side CPU run or already client-compile
                              mode; nothing to probe
      ``"remote_ok"``       — remote compile answered the probe
      ``"both_dead"``       — neither mode completed a computation (callers'
                              own watchdogs/retries take it from here)
    and does NOT return (``os.execv``) when remote compile is dead but
    client-side compile works.

    ``remaining_fn``/``deadline_env_var``: a re-exec resets the new
    interpreter's clock, so the caller hands a zero-arg callable returning
    its remaining budget in seconds; it is evaluated immediately before
    exec (the probes themselves burn up to 2 x ``probe_timeout`` — a value
    computed at call time would overstate the child's budget by that much)
    and written into the caller's deadline env var (e.g.
    ``AF2TPU_BENCH_DEADLINE``, ``AF2TPU_SESSION_DEADLINE``).
    """
    if (
        os.environ.get("AF2TPU_PLATFORM") == "cpu"
        or os.environ.get("JAX_PLATFORMS") == "cpu"
        or os.environ.get("AF2TPU_NO_PREFLIGHT") == "1"
    ):
        return "skipped"
    if os.environ.get("PALLAS_AXON_REMOTE_COMPILE") != "1":
        return "skipped"  # already client-compile mode (or no relay at all)

    if _probe_ok(timeout=probe_timeout):
        return "remote_ok"
    if _probe_ok({"PALLAS_AXON_REMOTE_COMPILE": "0"}, timeout=probe_timeout):
        print(
            "preflight: remote-compile endpoint unhealthy but client-side "
            "compile works; re-exec with PALLAS_AXON_REMOTE_COMPILE=0",
            file=sys.stderr,
            flush=True,
        )
        os.environ["PALLAS_AXON_REMOTE_COMPILE"] = "0"
        # the re-exec'd process skips the probe (mode already 0) but must
        # still know the tunnel was just proven alive (cold-cache budgeting)
        os.environ["AF2TPU_PREFLIGHT_CLIENT_OK"] = "1"
        if deadline_env_var and remaining_fn is not None:
            os.environ[deadline_env_var] = str(
                max(1, int(remaining_fn()))
            )
        os.execv(sys.executable, [sys.executable] + sys.argv)
    return "both_dead"
