"""Data pipeline: fixed-shape protein batches for TPU training.

Replaces the reference's sidechainnet DataLoader usage (train_pre.py:37-48:
``scn.load(casp_version=12, thinning=30)`` + a python length filter < 250 and
``cycle``). TPU-first differences:

- **Static shapes.** The reference feeds variable-length chains (anything
  < 250) straight into the model, retracing shapes every batch on a compiler
  backend. Here every batch is cropped/padded to ``crop_len`` with masks —
  one compiled program for the whole run.
- Sources: ``sidechainnet`` when the package is installed (same CASP12 /
  thinning-30 default), else a deterministic synthetic sampler with
  realistic marginals (sequence/MSA agreement, compact 3D coords from a
  smoothed random walk) so every part of the framework is exercisable in
  this hermetic environment.
- MSA synthesis: sidechainnet has no MSAs; the reference trains distogram-only
  without them (train_pre.py:79). We synthesize MSA rows by mutating the
  primary sequence (rate ~0.15) so the MSA stream trains end-to-end.

Batches are dicts of numpy arrays:
  seq (B, L) int32 | msa (B, M, L) int32 | mask (B, L) bool |
  msa_mask (B, M, L) bool | coords (B, L, 3) float32 CA positions |
  backbone (B, L*3, 3) float32 N/CA/C positions (end-to-end target)
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional

import numpy as np

from alphafold2_tpu import constants
from alphafold2_tpu.config import DataConfig


def _smooth_walk(rng: np.random.Generator, n: int) -> np.ndarray:
    """Compact protein-like CA trace: random walk with ~3.8A steps, smoothed."""
    steps = rng.normal(size=(n, 3))
    steps /= np.linalg.norm(steps, axis=-1, keepdims=True) + 1e-9
    # correlate consecutive steps for secondary-structure-like persistence
    for i in range(1, n):
        steps[i] = 0.6 * steps[i - 1] + 0.4 * steps[i]
        steps[i] /= np.linalg.norm(steps[i]) + 1e-9
    coords = np.cumsum(3.8 * steps, axis=0)
    return (coords - coords.mean(0)).astype(np.float32)


def _synthesize_backbone(rng: np.random.Generator, ca: np.ndarray) -> np.ndarray:
    """Place N and C pseudo-atoms ~1.5A off each CA along the chain direction."""
    n = ca.shape[0]
    d = np.diff(ca, axis=0, prepend=ca[:1] - (ca[1:2] - ca[:1]))
    d /= np.linalg.norm(d, axis=-1, keepdims=True) + 1e-9
    jitter = rng.normal(scale=0.1, size=(n, 3)).astype(np.float32)
    n_atom = ca - 1.46 * d + jitter
    c_atom = ca + 1.52 * d - jitter
    bb = np.stack([n_atom, ca, c_atom], axis=1)  # (L, 3, 3)
    return bb.reshape(n * 3, 3).astype(np.float32)


@dataclasses.dataclass
class SyntheticDataset:
    """Deterministic synthetic chains; infinite iterator of fixed-shape batches."""

    config: DataConfig
    seed: int = 0

    def __iter__(self) -> Iterator[dict]:
        cfg = self.config
        rng = np.random.default_rng(self.seed)
        L, M, NM, B = cfg.crop_len, cfg.msa_depth, cfg.msa_len, cfg.batch_size
        while True:
            batch = {
                "seq": np.zeros((B, L), np.int32),
                "msa": np.zeros((B, M, NM), np.int32),
                "mask": np.zeros((B, L), bool),
                "msa_mask": np.zeros((B, M, NM), bool),
                "coords": np.zeros((B, L, 3), np.float32),
                "backbone": np.zeros((B, L * 3, 3), np.float32),
            }
            for b in range(B):
                true_len = int(rng.integers(cfg.min_len_filter, L + 1))
                seq = rng.integers(0, 20, size=true_len)
                ca = _smooth_walk(rng, true_len)
                batch["seq"][b, :true_len] = seq
                batch["seq"][b, true_len:] = constants.AA_PAD_INDEX
                batch["mask"][b, :true_len] = True
                batch["coords"][b, :true_len] = ca
                batch["backbone"][b, : true_len * 3] = _synthesize_backbone(rng, ca)
                msa_len = min(NM, true_len)
                for m in range(M):
                    mut = rng.random(msa_len) < 0.15
                    row = seq[:msa_len].copy()
                    row[mut] = rng.integers(0, 20, size=int(mut.sum()))
                    batch["msa"][b, m, :msa_len] = row
                    batch["msa"][b, m, msa_len:] = constants.AA_PAD_INDEX
                    batch["msa_mask"][b, m, :msa_len] = True
            yield batch


class SidechainnetDataset:
    """CASP data via the sidechainnet package (reference train_pre.py:37-48),
    cropped/padded to static shapes. Import-gated: raises a clear error when
    the package is absent (it is not in this image)."""

    def __init__(self, config: DataConfig, seed: int = 0):
        try:
            import sidechainnet as scn
        except ImportError as e:  # pragma: no cover - env-dependent
            raise ImportError(
                "sidechainnet is not installed; use source='synthetic'"
            ) from e
        self.config = config
        self.seed = seed
        self._data = scn.load(
            casp_version=config.casp_version,
            thinning=config.thinning,
            with_pytorch="dataloaders",
            batch_size=config.batch_size,
            dynamic_batching=False,
        )

    def __iter__(self):  # pragma: no cover - env-dependent
        cfg = self.config
        rng = np.random.default_rng(self.seed)
        L, M, NM, B = cfg.crop_len, cfg.msa_depth, cfg.msa_len, cfg.batch_size
        while True:
            for batch in self._data["train"]:
                seqs = batch.int_seqs.numpy()
                masks = batch.msks.numpy().astype(bool)
                coords = batch.crds.numpy().reshape(
                    seqs.shape[0], -1, constants.NUM_COORDS_PER_RES, 3
                )
                lengths = masks.sum(-1)
                keep = (lengths >= cfg.min_len_filter) & (
                    lengths <= cfg.max_len_filter
                )
                if not keep.any():
                    continue
                out = {
                    "seq": np.full((B, L), constants.AA_PAD_INDEX, np.int32),
                    "msa": np.full((B, M, NM), constants.AA_PAD_INDEX, np.int32),
                    "mask": np.zeros((B, L), bool),
                    "msa_mask": np.zeros((B, M, NM), bool),
                    "coords": np.zeros((B, L, 3), np.float32),
                    "backbone": np.zeros((B, L * 3, 3), np.float32),
                }
                rows = np.nonzero(keep)[0][:B]
                for i, r in enumerate(rows):
                    n = int(lengths[r])
                    start = 0 if n <= L else int(rng.integers(0, n - L + 1))
                    end = min(start + L, n)
                    sl = slice(start, end)
                    w = end - start
                    out["seq"][i, :w] = seqs[r, sl]
                    out["mask"][i, :w] = masks[r, sl]
                    out["coords"][i, :w] = coords[r, sl, 1]  # CA slot
                    bb = coords[r, sl, :3].reshape(w * 3, 3)
                    out["backbone"][i, : w * 3] = bb
                    msa_len = min(NM, w)
                    for m in range(M):
                        mut = rng.random(msa_len) < 0.15
                        row = seqs[r, sl][:msa_len].copy()
                        row[mut] = rng.integers(0, 20, size=int(mut.sum()))
                        out["msa"][i, m, :msa_len] = row
                        out["msa_mask"][i, m, :msa_len] = masks[r, sl][:msa_len]
                yield out


def make_dataset(config: DataConfig, seed: int = 0):
    if config.source == "synthetic":
        return SyntheticDataset(config, seed=seed)
    if config.source == "native":
        from alphafold2_tpu.data import native

        if native.available():
            return native.NativeSyntheticLoader(config, seed=seed)
        import warnings

        warnings.warn(
            "native loader requested but libaf2data.so is not built "
            "(make -C native); falling back to the numpy pipeline"
        )
        return SyntheticDataset(config, seed=seed)
    if config.source == "sidechainnet":
        return SidechainnetDataset(config, seed=seed)
    raise ValueError(f"unknown data source {config.source!r}")
