"""Data pipeline: fixed-shape protein batches for TPU training.

Replaces the reference's sidechainnet DataLoader usage (train_pre.py:37-48:
``scn.load(casp_version=12, thinning=30)`` + a python length filter < 250 and
``cycle``). TPU-first differences:

- **Static shapes.** The reference feeds variable-length chains (anything
  < 250) straight into the model, retracing shapes every batch on a compiler
  backend. Here every batch is cropped/padded to ``crop_len`` with masks —
  one compiled program for the whole run.
- Sources: ``sidechainnet`` when the package is installed (same CASP12 /
  thinning-30 default), else a deterministic synthetic sampler with
  realistic marginals (sequence/MSA agreement, compact 3D coords from a
  smoothed random walk) so every part of the framework is exercisable in
  this hermetic environment.
- MSA synthesis: sidechainnet has no MSAs; the reference trains distogram-only
  without them (train_pre.py:79). We synthesize MSA rows by mutating the
  primary sequence (rate ~0.15) so the MSA stream trains end-to-end.

Batches are dicts of numpy arrays:
  seq (B, L) int32 | msa (B, M, L) int32 | mask (B, L) bool |
  msa_mask (B, M, L) bool | coords (B, L, 3) float32 CA positions |
  backbone (B, L*3, 3) float32 N/CA/C positions (end-to-end target)
"""

from __future__ import annotations

import dataclasses
from typing import Iterator

import numpy as np

from alphafold2_tpu import constants
from alphafold2_tpu.config import DataConfig


def _smooth_walk(rng: np.random.Generator, n: int) -> np.ndarray:
    """Compact protein-like CA trace: random walk with ~3.8A steps, smoothed."""
    steps = rng.normal(size=(n, 3))
    steps /= np.linalg.norm(steps, axis=-1, keepdims=True) + 1e-9
    # correlate consecutive steps for secondary-structure-like persistence
    for i in range(1, n):
        steps[i] = 0.6 * steps[i - 1] + 0.4 * steps[i]
        steps[i] /= np.linalg.norm(steps[i]) + 1e-9
    coords = np.cumsum(3.8 * steps, axis=0)
    return (coords - coords.mean(0)).astype(np.float32)


def _fill_msa(rng, seq_crop, msa_out, msa_mask_out, mutation_rate=0.15,
              mut_rows=None):
    """Fill (M, NM) MSA rows by mutating the cropped primary sequence —
    the one MSA-synthesis implementation shared by every data source.

    The rng stream consumed here depends only on (seed state, msa_len, M),
    never on the sequence CONTENT: the mutation mask is drawn first and the
    replacement residues are drawn for the masked positions regardless of
    what they replace. ``featurize_delta`` builds on exactly that property.
    ``mut_rows`` (a list) collects the per-row mutation masks when the
    caller wants the delta-featurization plan."""
    M, NM = msa_out.shape
    msa_len = min(NM, len(seq_crop))
    for m in range(M):
        mut = rng.random(msa_len) < mutation_rate
        row = np.asarray(seq_crop[:msa_len]).copy()
        row[mut] = rng.integers(0, 20, size=int(mut.sum()))
        msa_out[m, :msa_len] = row
        msa_mask_out[m, :msa_len] = True
        if mut_rows is not None:
            mut_rows.append(mut)


def _synthesize_backbone(rng: np.random.Generator, ca: np.ndarray) -> np.ndarray:
    """Place N and C pseudo-atoms ~1.5A off each CA along the chain direction."""
    n = ca.shape[0]
    d = np.diff(ca, axis=0, prepend=ca[:1] - (ca[1:2] - ca[:1]))
    d /= np.linalg.norm(d, axis=-1, keepdims=True) + 1e-9
    jitter = rng.normal(scale=0.1, size=(n, 3)).astype(np.float32)
    n_atom = ca - 1.46 * d + jitter
    c_atom = ca + 1.52 * d - jitter
    bb = np.stack([n_atom, ca, c_atom], axis=1)  # (L, 3, 3)
    return bb.reshape(n * 3, 3).astype(np.float32)


def featurize_bucketed(
    seq_tokens: np.ndarray,  # (L,) int32 AA tokens
    bucket_len: int,
    msa_depth: int,
    seed: int = 0,
    msa_len: int | None = None,
) -> dict:
    """One inference request -> fixed-shape features at a bucket length.

    The serve engine's featurizer: the sequence is padded up to
    ``bucket_len`` with ``AA_PAD_INDEX`` + a validity mask, and an MSA is
    synthesized by mutating the primary sequence (the same ``_fill_msa``
    every training source uses) into ``(msa_depth, msa_len or bucket_len)``
    padded rows. Returns an UNBATCHED item dict (``seq`` (bucket,), ``mask``,
    ``msa``, ``msa_mask``) — the engine stacks items into its batch dim.
    """
    item, _ = featurize_bucketed_with_plan(
        seq_tokens, bucket_len, msa_depth, seed=seed, msa_len=msa_len
    )
    return item


def featurize_bucketed_with_plan(
    seq_tokens: np.ndarray,
    bucket_len: int,
    msa_depth: int,
    seed: int = 0,
    msa_len: int | None = None,
) -> tuple:
    """:func:`featurize_bucketed` plus the delta-featurization *plan*.

    The plan records what :func:`featurize_delta` needs to featurize a
    point mutant of this sequence without re-running the MSA synthesis:
    the parent's tokens, the derivation coordinates (bucket/msa_depth/
    seed), and the per-row mutation masks ``_fill_msa`` drew — at a given
    (seed, length, msa_depth) those masks and the replacement residues are
    sequence-content-independent, which is the whole trick. The item dict
    is byte-identical to a plain ``featurize_bucketed`` call (same rng
    consumption order)."""
    seq_tokens = np.asarray(seq_tokens, np.int32).reshape(-1)
    L = len(seq_tokens)
    if L > bucket_len:
        raise ValueError(
            f"sequence of {L} residues does not fit bucket {bucket_len}"
        )
    NM = msa_len or bucket_len
    rng = np.random.default_rng(seed)
    item = {
        "seq": np.full(bucket_len, constants.AA_PAD_INDEX, np.int32),
        "mask": np.zeros(bucket_len, bool),
        "msa": np.full((msa_depth, NM), constants.AA_PAD_INDEX, np.int32),
        "msa_mask": np.zeros((msa_depth, NM), bool),
    }
    item["seq"][:L] = seq_tokens
    item["mask"][:L] = True
    mut_rows: list = []
    _fill_msa(rng, seq_tokens, item["msa"], item["msa_mask"],
              mut_rows=mut_rows)
    eff_len = min(NM, L)
    plan = {
        "tokens": seq_tokens.copy(),
        "bucket_len": int(bucket_len),
        "msa_depth": int(msa_depth),
        "msa_len": int(NM),
        "seed": int(seed),
        # (M, min(NM, L)) bool: True where _fill_msa replaced the primary
        # residue with a content-independent random one
        "mut": (
            np.stack(mut_rows) if mut_rows
            else np.zeros((0, eff_len), bool)
        ),
    }
    return item, plan


def featurize_delta(
    parent_item: dict,
    plan: dict,
    mutant_tokens: np.ndarray,
) -> dict:
    """Featurize a mutant of ``plan``'s parent by patching only the
    touched columns — byte-identical to cold featurization.

    For a mutant at the parent's length, the same (bucket, msa_depth,
    seed) cold featurization differs from the parent's only at the mutated
    positions: the primary-sequence slot, and per MSA row the positions
    the row's mutation mask did NOT replace (masked positions hold random
    residues whose draw never saw the sequence content). So the mutant's
    feature tree is the parent's with those columns patched — an O(M ·
    n_mutations) copy-and-patch instead of an O(M · L) re-synthesis. The
    parity test (tests/test_variant_scan.py) pins byte-level equality
    against :func:`featurize_bucketed`, tolerance zero.

    Masks are returned as the PARENT'S arrays (they are content-independent
    at equal length); callers must treat items as immutable, which the
    serve engine does (stacking copies). Raises ValueError when the mutant
    is not delta-eligible (different length)."""
    mutant_tokens = np.asarray(mutant_tokens, np.int32).reshape(-1)
    parent_tokens = plan["tokens"]
    if len(mutant_tokens) != len(parent_tokens):
        raise ValueError(
            f"delta featurization needs equal lengths: mutant "
            f"{len(mutant_tokens)} vs parent {len(parent_tokens)}"
        )
    positions = np.nonzero(mutant_tokens != parent_tokens)[0]
    seq = parent_item["seq"].copy()
    msa = parent_item["msa"].copy()
    mut = plan["mut"]  # (M, eff_len) bool
    eff_len = mut.shape[1] if mut.size else min(
        plan["msa_len"], len(parent_tokens)
    )
    for p in positions:
        seq[p] = mutant_tokens[p]
        if p < eff_len:
            msa[~mut[:, p], p] = mutant_tokens[p]
    return {
        "seq": seq,
        "mask": parent_item["mask"],
        "msa": msa,
        "msa_mask": parent_item["msa_mask"],
    }


@dataclasses.dataclass
class SyntheticDataset:
    """Deterministic synthetic chains; infinite iterator of fixed-shape batches."""

    config: DataConfig
    seed: int = 0

    def __iter__(self) -> Iterator[dict]:
        cfg = self.config
        rng = np.random.default_rng(self.seed)
        L, M, NM, B = cfg.crop_len, cfg.msa_depth, cfg.msa_len, cfg.batch_size
        while True:
            batch = {
                "seq": np.zeros((B, L), np.int32),
                "msa": np.zeros((B, M, NM), np.int32),
                "mask": np.zeros((B, L), bool),
                "msa_mask": np.zeros((B, M, NM), bool),
                "coords": np.zeros((B, L, 3), np.float32),
                "backbone": np.zeros((B, L * 3, 3), np.float32),
            }
            min_len = min(cfg.min_len_filter, L)  # crop shorter than the
            # filter floor: full-length chains, not a crash
            for b in range(B):
                true_len = int(rng.integers(min_len, L + 1))
                seq = rng.integers(0, 20, size=true_len)
                ca = _smooth_walk(rng, true_len)
                batch["seq"][b, :true_len] = seq
                batch["seq"][b, true_len:] = constants.AA_PAD_INDEX
                batch["mask"][b, :true_len] = True
                batch["coords"][b, :true_len] = ca
                batch["backbone"][b, : true_len * 3] = _synthesize_backbone(rng, ca)
                batch["msa"][b, :, :] = constants.AA_PAD_INDEX
                _fill_msa(rng, seq, batch["msa"][b], batch["msa_mask"][b])
            yield batch


class SidechainnetDataset:
    """CASP data via the sidechainnet package (reference train_pre.py:37-48),
    cropped/padded to static shapes. Import-gated: raises a clear error when
    the package is absent (it is not in this image)."""

    def __init__(self, config: DataConfig, seed: int = 0):
        try:
            import sidechainnet as scn
        except ImportError as e:
            raise ImportError(
                "sidechainnet is not installed; use source='synthetic'"
            ) from e
        self.config = config
        self.seed = seed
        self._data = scn.load(
            casp_version=config.casp_version,
            thinning=config.thinning,
            with_pytorch="dataloaders",
            batch_size=config.batch_size,
            dynamic_batching=False,
        )

    def __iter__(self):
        cfg = self.config
        rng = np.random.default_rng(self.seed)
        L, M, NM, B = cfg.crop_len, cfg.msa_depth, cfg.msa_len, cfg.batch_size
        while True:
            for batch in self._data["train"]:
                seqs = batch.int_seqs.numpy()
                masks = batch.msks.numpy().astype(bool)
                coords = batch.crds.numpy().reshape(
                    seqs.shape[0], -1, constants.NUM_COORDS_PER_RES, 3
                )
                lengths = masks.sum(-1)
                keep = (lengths >= cfg.min_len_filter) & (
                    lengths <= cfg.max_len_filter
                )
                if not keep.any():
                    continue
                out = {
                    "seq": np.full((B, L), constants.AA_PAD_INDEX, np.int32),
                    "msa": np.full((B, M, NM), constants.AA_PAD_INDEX, np.int32),
                    "mask": np.zeros((B, L), bool),
                    "msa_mask": np.zeros((B, M, NM), bool),
                    "coords": np.zeros((B, L, 3), np.float32),
                    "backbone": np.zeros((B, L * 3, 3), np.float32),
                }
                rows = np.nonzero(keep)[0][:B]
                for i, r in enumerate(rows):
                    n = int(lengths[r])
                    start = 0 if n <= L else int(rng.integers(0, n - L + 1))
                    end = min(start + L, n)
                    sl = slice(start, end)
                    w = end - start
                    out["seq"][i, :w] = seqs[r, sl]
                    out["mask"][i, :w] = masks[r, sl]
                    out["coords"][i, :w] = coords[r, sl, 1]  # CA slot
                    bb = coords[r, sl, :3].reshape(w * 3, 3)
                    out["backbone"][i, : w * 3] = bb
                    _fill_msa(rng, seqs[r, sl], out["msa"][i], out["msa_mask"][i])
                    msa_len = min(NM, w)
                    out["msa_mask"][i, :, :msa_len] &= masks[r, sl][:msa_len]
                yield out


def _npz_paths(data_dir: str) -> list:
    import glob
    import os

    if not data_dir:
        raise ValueError("npz shards need data.data_dir")
    paths = sorted(glob.glob(os.path.join(data_dir, "*.npz")))
    if not paths:
        raise FileNotFoundError(f"no .npz shards under {data_dir!r}")
    return paths


def _read_shard(path: str):
    """One shard -> (seq (L,) int32, coords float32, msa (M, L) int32 or
    None), shape-validated so malformed shards fail loudly here rather than
    corrupting downstream consumers (the native loader trusts lengths)."""
    with np.load(path) as z:
        seq = np.ascontiguousarray(z["seq"], np.int32)
        coords = np.asarray(z["coords"], np.float32)
        msa = np.asarray(z["msa"], np.int32) if "msa" in z else None
    n = len(seq)
    ok = (coords.ndim == 2 and coords.shape == (n, 3)) or (
        coords.ndim == 3
        and coords.shape[0] == n
        and coords.shape[1] >= 3
        and coords.shape[2] == 3
    )
    if not ok:
        raise ValueError(
            f"shard {path!r}: coords shape {coords.shape} does not match "
            f"seq length {n} (want (L, 3) CA or (L, k>=3, 3) atomic)"
        )
    if msa is not None and (msa.ndim != 2 or msa.shape[1] != n):
        raise ValueError(
            f"shard {path!r}: msa shape "
            f"{msa.shape} does not match seq length {n} (want (M, L))"
        )
    return seq, coords, msa


def _length_ok(n: int, config: DataConfig) -> bool:
    return max(4, config.min_len_filter) <= n <= config.max_len_filter


def _shard_backbone(coords: np.ndarray, rng) -> tuple:
    """coords -> (ca (L, 3), backbone_atoms (L*3, 3)); CA-only shards get
    synthesized N/C pseudo-atoms so structure losses have a real target."""
    if coords.ndim == 3:  # (L, k, 3) atomic: slots 0..2 = N/CA/C
        return coords[:, 1], coords[:, :3].reshape(-1, 3)
    return coords, _synthesize_backbone(rng, coords)


# one message for the one policy, whichever entry point detects it
MSA_FALLBACK_WARNING = (
    "shards carry stored MSAs, which the native loader would replace with "
    "mutation-synthesized ones; use the numpy npz pipeline "
    "(data.source='npz') to train on the stored alignments"
)


def shards_carry_msa(config: DataConfig) -> bool:
    """Cheap pre-scan: does any length-passing shard store an MSA? Reads
    only zip directories and the small ``seq`` arrays — no coords — so
    routing decisions don't pay a full dataset load."""
    for p in _npz_paths(config.data_dir):
        with np.load(p) as z:
            if "msa" in z.files and _length_ok(len(z["seq"]), config):
                return True
    return False


def load_npz_chains(config: DataConfig, seed: int = 0) -> tuple:
    """Load every length-filtered chain from the ``.npz`` shard directory as
    ``(seq (L,) int32, backbone (L, 3, 3) float32)`` pairs — the registry
    format the native real-data loader copies once at startup. Returns
    ``(chains, any_msa)``; ``any_msa`` is True when any length-passing
    shard carries a stored MSA (which this registry format cannot hold).

    ``seed`` drives the N/C pseudo-atom jitter for CA-only shards. The
    registry is built once, so that jitter is fixed for the run (the numpy
    pipeline re-draws per epoch) but varies across training seeds."""
    rng = np.random.default_rng(seed)
    chains = []
    any_msa = False
    for p in _npz_paths(config.data_dir):
        seq, coords, msa = _read_shard(p)
        if not _length_ok(len(seq), config):
            continue
        any_msa = any_msa or msa is not None
        _, backbone_atoms = _shard_backbone(coords, rng)
        chains.append((
            seq,
            np.ascontiguousarray(backbone_atoms.reshape(len(seq), 3, 3)),
        ))
    if not chains:
        raise ValueError(
            f"no shard in {config.data_dir!r} passes the length filter "
            f"[{config.min_len_filter}, {config.max_len_filter}]"
        )
    return chains, any_msa


class NpzShardDataset:
    """Local real-data ingestion: a directory of ``.npz`` shards.

    Each shard holds one chain: ``seq`` (L,) int tokens (AA_ALPHABET
    order), ``coords`` (L, 3) CA positions (or (L, k>=3, 3) atom14-style,
    slot 1 = CA, slots 0..2 = N/CA/C), optional ``msa`` (M, L) int. Chains
    are length-filtered, cropped/padded to static shapes, cycled forever
    with a seeded shuffle; MSAs absent from a shard are synthesized by
    mutation like the other sources. ``scripts/import_pdbs.py`` converts a
    directory of PDB files into this format using the built-in PDB codec.
    """

    def __init__(self, config: DataConfig, seed: int = 0):
        self.config = config
        self.seed = seed
        self.paths = _npz_paths(config.data_dir)

    def __iter__(self) -> Iterator[dict]:
        cfg = self.config
        rng = np.random.default_rng(self.seed)
        L, M, NM, B = cfg.crop_len, cfg.msa_depth, cfg.msa_len, cfg.batch_size
        order = np.arange(len(self.paths))
        buf = []
        while True:
            rng.shuffle(order)
            accepted = 0
            for idx in order:
                seq, coords, msa_full = _read_shard(self.paths[idx])
                n = len(seq)
                if not _length_ok(n, cfg):
                    continue
                accepted += 1
                ca, backbone_atoms = _shard_backbone(coords, rng)
                start = 0 if n <= L else int(rng.integers(0, n - L + 1))
                end = min(start + L, n)
                w = end - start
                item = {
                    "seq": np.full(L, constants.AA_PAD_INDEX, np.int32),
                    "msa": np.full((M, NM), constants.AA_PAD_INDEX, np.int32),
                    "mask": np.zeros(L, bool),
                    "msa_mask": np.zeros((M, NM), bool),
                    "coords": np.zeros((L, 3), np.float32),
                    "backbone": np.zeros((L * 3, 3), np.float32),
                }
                item["seq"][:w] = seq[start:end]
                item["mask"][:w] = True
                item["coords"][:w] = ca[start:end]
                item["backbone"][: w * 3] = backbone_atoms[start * 3 : end * 3]
                if msa_full is not None:
                    msa_len = min(NM, w)
                    rows = min(M, len(msa_full))
                    item["msa"][:rows, :msa_len] = msa_full[
                        :rows, start : start + msa_len
                    ]
                    item["msa_mask"][:rows, :msa_len] = True
                    if rows < M:
                        _fill_msa(rng, seq[start:end], item["msa"][rows:],
                                  item["msa_mask"][rows:])
                else:
                    _fill_msa(rng, seq[start:end], item["msa"], item["msa_mask"])
                buf.append(item)
                if len(buf) == B:
                    yield {
                        k: np.stack([it[k] for it in buf]) for k in buf[0]
                    }
                    buf = []
            if accepted == 0:
                raise ValueError(
                    f"no shard in {cfg.data_dir!r} passes the length filter "
                    f"[{cfg.min_len_filter}, {cfg.max_len_filter}]"
                )


def make_dataset(config: DataConfig, seed: int = 0):
    if config.source == "synthetic":
        return SyntheticDataset(config, seed=seed)
    if config.source == "native":
        from alphafold2_tpu.data import native

        if native.available():
            # data_dir set -> real npz shards through the native prefetch
            # ring; otherwise the native synthetic stream
            if config.data_dir:
                if shards_carry_msa(config):
                    import warnings

                    warnings.warn(MSA_FALLBACK_WARNING)
                    return NpzShardDataset(config, seed=seed)
                return native.NativeShardLoader(config, seed=seed)
            return native.NativeSyntheticLoader(config, seed=seed)
        import warnings

        warnings.warn(
            "native loader requested but libaf2data.so is not built "
            "(make -C native); falling back to the numpy pipeline"
        )
        if config.data_dir:
            return NpzShardDataset(config, seed=seed)
        return SyntheticDataset(config, seed=seed)
    if config.source == "npz":
        return NpzShardDataset(config, seed=seed)
    if config.source == "sidechainnet":
        return SidechainnetDataset(config, seed=seed)
    raise ValueError(f"unknown data source {config.source!r}")
