"""ctypes bindings for the native (C++) data-loader runtime.

The reference's data path crosses into native code via mdtraj (C) and torch
DataLoader workers (SURVEY.md S2.4); ``native/dataloader.cc`` provides this
framework's equivalent: host-thread batch synthesis + distogram-label
bucketization behind a bounded prefetch queue, so the accelerator step never
waits on the Python interpreter (ctypes releases the GIL for the blocking
``next`` call).

Build once with ``make -C native``; everything degrades gracefully to the
pure-numpy pipeline (data/pipeline.py) when the shared library is absent.

Public surface:
- :func:`available` — is the native library loadable?
- :func:`bucketize_distances` — native twin of
  utils.structure.get_bucketed_distance_matrix (differentially tested).
- :class:`NativeSyntheticLoader` — iterator of fixed-shape batch dicts with
  precomputed ``labels``, produced by C++ worker threads.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from alphafold2_tpu import constants
from alphafold2_tpu.config import DataConfig

_LIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "libaf2data.so",
)
_lib: Optional[ctypes.CDLL] = None


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH):
        return None
    lib = ctypes.CDLL(_LIB_PATH)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    f32p = ctypes.POINTER(ctypes.c_float)
    lib.af2_bucketize_distances.argtypes = [
        f32p, u8p, ctypes.c_int, ctypes.c_int, ctypes.c_float, ctypes.c_float,
        ctypes.c_int32, i32p,
    ]
    lib.af2_bucketize_distances.restype = None
    lib.af2_synthesize_batch.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_uint64, i32p, i32p, u8p, u8p, f32p, f32p,
    ]
    lib.af2_synthesize_batch.restype = None
    lib.af2_loader_create.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_uint64, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_float, ctypes.c_float, ctypes.c_int32,
    ]
    lib.af2_loader_create.restype = ctypes.c_void_p
    lib.af2_real_loader_create.argtypes = [
        ctypes.c_int, i32p, i32p, f32p,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_double, ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_float, ctypes.c_float, ctypes.c_int32,
    ]
    lib.af2_real_loader_create.restype = ctypes.c_void_p
    lib.af2_loader_next.argtypes = [
        ctypes.c_void_p, i32p, i32p, u8p, u8p, f32p, f32p, i32p,
    ]
    lib.af2_loader_next.restype = ctypes.c_int
    lib.af2_loader_queue_size.argtypes = [ctypes.c_void_p]
    lib.af2_loader_queue_size.restype = ctypes.c_int
    lib.af2_loader_destroy.argtypes = [ctypes.c_void_p]
    lib.af2_loader_destroy.restype = None
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def bucketize_distances(
    coords: np.ndarray,
    mask: np.ndarray,
    num_buckets: int = constants.DISTOGRAM_BUCKETS,
    min_dist: float = constants.DISTOGRAM_MIN_DIST,
    max_dist: float = constants.DISTOGRAM_MAX_DIST,
    ignore_index: int = -100,
) -> np.ndarray:
    """(N, 3) float32 coords + (N,) bool mask -> (N, N) int32 labels."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library not built (make -C native)")
    coords = np.ascontiguousarray(coords, np.float32)
    mask_u8 = np.ascontiguousarray(mask, np.uint8)
    n = coords.shape[0]
    out = np.empty((n, n), np.int32)
    lib.af2_bucketize_distances(
        _ptr(coords, ctypes.c_float), _ptr(mask_u8, ctypes.c_uint8), n,
        num_buckets, min_dist, max_dist, ignore_index,
        _ptr(out, ctypes.c_int32),
    )
    return out


def synthesize_batch(config: DataConfig, seed: int) -> dict:
    """One-shot native batch synthesis (deterministic by seed)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library not built (make -C native)")
    B, L, M, NM = (
        config.batch_size, config.crop_len, config.msa_depth, config.msa_len,
    )
    out = _alloc(B, L, M, NM, labels=False)
    lib.af2_synthesize_batch(
        B, L, M, NM, config.min_len_filter, seed,
        _ptr(out["seq"], ctypes.c_int32), _ptr(out["msa"], ctypes.c_int32),
        _ptr(out["_mask_u8"], ctypes.c_uint8),
        _ptr(out["_msa_mask_u8"], ctypes.c_uint8),
        _ptr(out["coords"], ctypes.c_float), _ptr(out["backbone"], ctypes.c_float),
    )
    return _finish(out)


def _alloc(B, L, M, NM, labels: bool) -> dict:
    out = {
        "seq": np.empty((B, L), np.int32),
        "msa": np.empty((B, M, NM), np.int32),
        "_mask_u8": np.empty((B, L), np.uint8),
        "_msa_mask_u8": np.empty((B, M, NM), np.uint8),
        "coords": np.empty((B, L, 3), np.float32),
        "backbone": np.empty((B, L * 3, 3), np.float32),
    }
    if labels:
        out["labels"] = np.empty((B, L, L), np.int32)
    return out

def _finish(out: dict) -> dict:
    out["mask"] = out.pop("_mask_u8").astype(bool)
    out["msa_mask"] = out.pop("_msa_mask_u8").astype(bool)
    return out


class NativeSyntheticLoader:
    """Prefetching batch iterator backed by C++ worker threads.

    Yields the same dict schema as data/pipeline.py datasets, plus ``labels``
    (precomputed distogram targets) so the device step skips the O(N^2)
    bucketization. The batch STREAM is deterministic for a given seed
    regardless of ``num_workers`` (workers claim sequential batch indices;
    the consumer pops in index order). Use as a context manager or call
    ``close()``.
    """

    def _bind(self, config: DataConfig) -> ctypes.CDLL:
        """Shared init prelude: load the library and stash lib/config."""
        lib = _load()
        if lib is None:
            raise RuntimeError(
                "native library not built (make -C native)"
            )
        self._lib = lib
        self.config = config
        return lib

    def __init__(
        self,
        config: DataConfig,
        seed: int = 0,
        num_workers: int = 2,
        queue_capacity: int = 4,
        ignore_index: int = -100,
    ):
        lib = self._bind(config)
        self._handle = lib.af2_loader_create(
            config.batch_size, config.crop_len, config.msa_depth,
            config.msa_len, config.min_len_filter, seed, num_workers,
            queue_capacity, constants.DISTOGRAM_BUCKETS,
            constants.DISTOGRAM_MIN_DIST, constants.DISTOGRAM_MAX_DIST,
            ignore_index,
        )

    def __iter__(self):
        return self

    def __next__(self) -> dict:
        if self._handle is None:
            raise StopIteration("loader is closed")
        cfg = self.config
        out = _alloc(cfg.batch_size, cfg.crop_len, cfg.msa_depth, cfg.msa_len,
                     labels=True)
        rc = self._lib.af2_loader_next(
            self._handle,
            _ptr(out["seq"], ctypes.c_int32), _ptr(out["msa"], ctypes.c_int32),
            _ptr(out["_mask_u8"], ctypes.c_uint8),
            _ptr(out["_msa_mask_u8"], ctypes.c_uint8),
            _ptr(out["coords"], ctypes.c_float),
            _ptr(out["backbone"], ctypes.c_float),
            _ptr(out["labels"], ctypes.c_int32),
        )
        if rc != 0:
            raise StopIteration
        return _finish(out)

    def queue_size(self) -> int:
        if self._handle is None:
            return 0
        return int(self._lib.af2_loader_queue_size(self._handle))

    def close(self):
        if self._handle is not None:
            self._lib.af2_loader_destroy(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass


class NativeShardLoader(NativeSyntheticLoader):
    """Real-data twin of :class:`NativeSyntheticLoader`: npz shard chains
    are loaded once on the Python side (np.load at startup), registered with
    (copied into) the C++ loader, and worker threads then do the per-step
    crop/pad/MSA-synthesis/label work in the prefetch ring — the real-data
    equivalent of torch DataLoader workers the reference leans on
    (train_pre.py:37-48). Chain choice is uniform per sample (seeded), so
    the stream is deterministic in (seed, batch index) for any worker count
    — unlike :class:`~alphafold2_tpu.data.pipeline.NpzShardDataset`'s
    epoch-shuffle order.
    """

    def __init__(
        self,
        config: DataConfig,
        seed: int = 0,
        num_workers: int = 2,
        queue_capacity: int = 4,
        ignore_index: int = -100,
        mutation_rate: float = 0.15,
        chains: Optional[list] = None,  # precomputed load_npz_chains output
    ):
        from alphafold2_tpu.data.pipeline import (
            MSA_FALLBACK_WARNING,
            load_npz_chains,
        )

        lib = self._bind(config)
        if chains is None:
            chains, any_msa = load_npz_chains(config, seed=seed)
            if any_msa:
                import warnings

                warnings.warn(MSA_FALLBACK_WARNING)
        lens = np.asarray([len(s) for s, _ in chains], np.int32)
        seq_cat = np.ascontiguousarray(
            np.concatenate([s for s, _ in chains]), np.int32
        )
        bb_cat = np.ascontiguousarray(
            np.concatenate([b.reshape(-1) for _, b in chains]), np.float32
        )
        self.num_chains = len(chains)
        self._handle = lib.af2_real_loader_create(
            len(chains), _ptr(lens, ctypes.c_int32),
            _ptr(seq_cat, ctypes.c_int32), _ptr(bb_cat, ctypes.c_float),
            config.batch_size, config.crop_len, config.msa_depth,
            config.msa_len, mutation_rate, seed, num_workers, queue_capacity,
            constants.DISTOGRAM_BUCKETS, constants.DISTOGRAM_MIN_DIST,
            constants.DISTOGRAM_MAX_DIST, ignore_index,
        )
        if not self._handle:
            raise RuntimeError("af2_real_loader_create failed")
