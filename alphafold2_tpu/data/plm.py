"""Protein-language-model embedding providers for the ``embedds`` input path.

The reference feeds frozen ESM-1b residue embeddings (1280-dim) into the
model via torch.hub (reference train_end2end.py:37-43,54-59: download ~30GB,
run under no_grad, project 1280->dim). The TPU framework keeps the same
boundary — the model's ``embedds`` argument + ``embedd_project`` — and makes
the provider pluggable:

- :class:`HashProjectionProvider` — hermetic, dependency-free stand-in: a
  fixed random projection of one-hot residue identity + position features to
  ``dim`` (deterministic per seed). Lets the full PLM input path train and
  test in environments with no model weights or network.
- :class:`PrecomputedProvider` — loads embeddings exported ahead of time to
  ``.npz`` (key = sequence string), the standard workflow for frozen-PLM
  features on TPU pods (embed once on any machine, stream arrays).
- :class:`TransformersESMProvider` — runs a HuggingFace ESM checkpoint
  (e.g. ``facebook/esm1b_t33_650M_UR50S``) when its weights are available
  locally; import/download is gated with a clear error.

- :func:`wrap_with_embeddings` — dataset adapter: adds ``embedds`` to each
  batch and drops the MSA (the two are mutually exclusive model inputs,
  reference alphafold2.py:493-496); the train steps pick whichever key the
  batch carries.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from alphafold2_tpu import constants


class HashProjectionProvider:
    """Deterministic pseudo-PLM: fixed random projection of (one-hot AA,
    sinusoidal position) features to ``dim``. Zero dependencies; the point is
    exercising the embedds path end-to-end, not biological signal."""

    def __init__(self, dim: int = constants.NUM_EMBEDDS_TR, seed: int = 0):
        self.dim = dim
        rng = np.random.default_rng(seed)
        self._aa_table = rng.normal(
            scale=1.0, size=(constants.NUM_AMINO_ACIDS, dim)
        ).astype(np.float32)

    def __call__(self, seq: np.ndarray) -> np.ndarray:
        """(B, L) int tokens -> (B, L, dim) float32 embeddings."""
        seq = np.asarray(seq)
        emb = self._aa_table[seq]  # (B, L, dim)
        pos = np.arange(seq.shape[1], dtype=np.float32)
        freqs = np.exp(
            -np.log(10000.0)
            * np.arange(0, self.dim, 2, dtype=np.float32)
            / self.dim
        )
        ang = pos[:, None] * freqs[None, :]
        pe = np.zeros((seq.shape[1], self.dim), np.float32)
        pe[:, 0::2] = np.sin(ang)[:, : pe[:, 0::2].shape[1]]
        pe[:, 1::2] = np.cos(ang)[:, : pe[:, 1::2].shape[1]]
        return emb + pe[None]


class PrecomputedProvider:
    """Looks embeddings up from an ``.npz`` archive keyed by sequence string
    (letters from AA_ALPHABET). Missing sequences raise KeyError."""

    def __init__(self, npz_path: str):
        self._store = np.load(npz_path)

    def __call__(self, seq: np.ndarray) -> np.ndarray:
        seq = np.asarray(seq)
        out = []
        for row in seq:
            key = "".join(
                constants.AA_ALPHABET[t] if t < 20 else "X" for t in row
            )
            out.append(np.asarray(self._store[key], np.float32))
        return np.stack(out)


class TransformersESMProvider:
    """Frozen ESM via HuggingFace ``transformers`` (the reference's ESM-1b
    boundary, minus torch.hub). Requires the checkpoint to be locally
    available; gated with a clear error otherwise."""

    def __init__(self, model_name: str = "facebook/esm1b_t33_650M_UR50S"):
        try:
            import torch  # noqa: F401
            from transformers import AutoModel, AutoTokenizer
        except ImportError as e:  # pragma: no cover - env-dependent
            raise ImportError("transformers+torch required for ESM") from e
        try:
            self._tok = AutoTokenizer.from_pretrained(
                model_name, local_files_only=True
            )
            self._model = AutoModel.from_pretrained(
                model_name, local_files_only=True
            ).eval()
        except OSError as e:  # pragma: no cover - env-dependent
            raise RuntimeError(
                f"ESM checkpoint {model_name!r} not cached locally and this "
                "environment has no network; precompute embeddings elsewhere "
                "and use PrecomputedProvider"
            ) from e

    def __call__(self, seq: np.ndarray) -> np.ndarray:  # pragma: no cover
        import torch

        seqs = [
            "".join(constants.AA_ALPHABET[t] if t < 20 else "X" for t in row)
            for row in np.asarray(seq)
        ]
        with torch.no_grad():
            toks = self._tok(seqs, return_tensors="pt", padding=True)
            h = self._model(**toks).last_hidden_state
        return h[:, 1 : 1 + seq.shape[1]].float().numpy()


def make_provider(kind: str, dim: int = constants.NUM_EMBEDDS_TR,
                  path: Optional[str] = None, seed: int = 0):
    if kind == "hash":
        return HashProjectionProvider(dim=dim, seed=seed)
    if kind == "precomputed":
        if not path:
            raise ValueError("precomputed provider needs data.plm_path")
        return PrecomputedProvider(path)
    if kind == "esm":
        return TransformersESMProvider()
    raise ValueError(f"unknown plm provider {kind!r}")


def wrap_with_embeddings(dataset, provider) -> Iterator[dict]:
    """Adapter: stream batches with ``embedds`` added and the MSA removed
    (embedds and MSA are mutually exclusive model inputs)."""
    for batch in dataset:
        out = {k: v for k, v in batch.items() if k not in ("msa", "msa_mask")}
        out["embedds"] = provider(batch["seq"])
        yield out
