from alphafold2_tpu.data.pipeline import (
    SidechainnetDataset,
    SyntheticDataset,
    make_dataset,
)
