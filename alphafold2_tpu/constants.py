"""Global constants for alphafold2_tpu.

TPU-native re-design of the reference's ``alphafold2_pytorch/constants.py:1-14``.
The reference also defines a global ``DEVICE`` (cuda-if-available); in JAX device
placement is handled by meshes/shardings (see ``alphafold2_tpu.parallel``), so no
device global exists here.
"""

MAX_NUM_MSA = 20
MAX_NUM_TEMPLATES = 10
NUM_AMINO_ACIDS = 21
NUM_EMBEDDS_TR = 1280  # ESM-1b width
DISTOGRAM_BUCKETS = 37

# distogram span in Angstroms (reference utils.py:29,35)
DISTOGRAM_MIN_DIST = 2.0
DISTOGRAM_MAX_DIST = 20.0

# sidechainnet-compatible atom layout (reference utils.py:13,18-21)
NUM_COORDS_PER_RES = 14
GLOBAL_PAD_CHAR = 0
BB_BUILD_INFO = {
    "BONDLENS": {"c-o": 1.229},
    "BONDANGS": {"ca-c-o": 2.0944},
}

# Amino-acid vocabulary: 20 canonical AAs in single-letter alphabetical order,
# index 20 = padding/unknown. Matches sidechainnet's ProteinVocabulary layout
# the reference relies on (utils.py:11,16).
AA_ALPHABET = "ACDEFGHIKLMNPQRSTVWY"
AA_PAD_INDEX = 20

# Heavy-atom count per residue type (backbone N,CA,C,O = 4 + sidechain),
# indexed by AA_ALPHABET order; pad gets 0. Used by scn_cloud_mask
# (reference utils.py:163-180 derives this from SC_BUILD_INFO at runtime).
ATOMS_PER_AA = {
    "A": 5, "C": 6, "D": 8, "E": 9, "F": 11,
    "G": 4, "H": 10, "I": 8, "K": 9, "L": 8,
    "M": 8, "N": 8, "P": 7, "Q": 9, "R": 11,
    "S": 6, "T": 7, "V": 7, "W": 14, "Y": 12,
}
ATOM_COUNTS = tuple(ATOMS_PER_AA[c] for c in AA_ALPHABET) + (0,)
