"""SE(3)-equivariant attention over point clouds, degrees {0, 1}.

TPU-native replacement for the external ``se3-transformer-pytorch`` dependency
at both reference call sites:

- template sidechain coloring (reference alphafold2.py:372-384, 519-537):
  scalar residue embeddings + one type-1 (vector) sidechain feature at
  template coords -> colored scalar embeddings (``return_type=0``)
- end-to-end coordinate refiner (reference train_end2end.py:86-94, 168-169):
  atom-token scalars at proto-structure coords -> refined coords (type-1 out)

Both sites use only degree-0 and degree-1 features (SURVEY.md S7 "hard
parts"), so instead of a spherical-harmonic SE(3)-Transformer this is a
geometric vector attention network: all interactions go through rotation
invariants (scalar features, pairwise distances) and rotation-covariant
linear maps (channel-mixing of vectors, relative-position directions), which
is exactly equivariant under SE(3) by construction.

TPU-first choices: dense all-pairs attention with an RBF distance bias in
place of the reference's 12-nearest-neighbor graph gathers (dynamic gathers
are hostile to XLA; N here is a few hundred, so dense attention is a clean
MXU matmul), static shapes throughout. Equivariance is verified numerically
in tests/test_se3.py (the reference has no such test).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn

from alphafold2_tpu.ops.attention import MASK_VALUE


def _safe_norm(v, axis=-1, keepdims=False, eps=1e-8):
    sq = jnp.sum(v * v, axis=axis, keepdims=keepdims)
    return jnp.sqrt(sq + eps)


class RadialBasis(nn.Module):
    """Distances -> smooth RBF features (invariant edge descriptors)."""

    num_basis: int = 16
    max_dist: float = 20.0

    @nn.compact
    def __call__(self, dist):
        centers = jnp.linspace(0.0, self.max_dist, self.num_basis)
        width = self.max_dist / self.num_basis
        return jnp.exp(-(((dist[..., None] - centers) / width) ** 2))


class EquivariantLayer(nn.Module):
    """One block: invariant attention + scalar/vector residual updates.

    Scalars s: (B, N, ds); vectors v: (B, N, dv, 3); coords: (B, N, 3).
    Attention logits are built from scalars and RBF(distance) only
    (invariant); value aggregation mixes neighbor vectors and relative
    directions gated by invariant scalars (covariant).
    """

    dim: int
    vec_dim: int = 16
    heads: int = 4
    num_basis: int = 16
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, s, v, coords, mask=None):
        b, n, ds = s.shape
        h = self.heads
        dh = self.dim // h

        rel = coords[:, :, None, :] - coords[:, None, :, :]  # (B, N, N, 3)
        dist = _safe_norm(rel)  # (B, N, N)
        unit = rel / dist[..., None]
        rbf = RadialBasis(self.num_basis)(dist).astype(self.dtype)  # (B,N,N,R)

        sn = nn.LayerNorm(dtype=self.dtype, name="s_norm")(s)
        q = nn.Dense(self.dim, use_bias=False, dtype=self.dtype, name="q")(sn)
        k = nn.Dense(self.dim, use_bias=False, dtype=self.dtype, name="k")(sn)
        q = q.reshape(b, n, h, dh)
        k = k.reshape(b, n, h, dh)
        logits = jnp.einsum("bihd,bjhd->bhij", q, k) * dh**-0.5
        logits = logits + jnp.moveaxis(
            nn.Dense(h, dtype=self.dtype, name="rbf_bias")(rbf), -1, 1
        )
        if mask is not None:
            pair = mask[:, None, None, :] & mask[:, None, :, None]
            logits = jnp.where(pair, logits, MASK_VALUE)
        attn = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(self.dtype)
        attn_mean = attn.mean(axis=1)  # (B, N, N) head-averaged for vector agg

        # scalar update: attended neighbor scalars + invariant vector norms
        vals = nn.Dense(self.dim, use_bias=False, dtype=self.dtype, name="val")(sn)
        vals = vals.reshape(b, n, h, dh)
        s_agg = jnp.einsum("bhij,bjhd->bihd", attn, vals).reshape(b, n, self.dim)
        v_norms = _safe_norm(v)  # (B, N, dv) invariant
        s_in = jnp.concatenate([s_agg, v_norms.astype(self.dtype)], axis=-1)
        s = s + nn.Dense(ds, dtype=self.dtype, name="s_out")(s_in)

        # vector update: equivariant combination of
        #   (a) channel-mixed own vectors, (b) attended neighbor vectors,
        #   (c) attended relative directions — each gated by invariant scalars
        gates = nn.Dense(3 * self.vec_dim, dtype=self.dtype, name="gates")(
            nn.LayerNorm(dtype=self.dtype, name="s_norm2")(s)
        )
        g_self, g_nbr, g_rel = jnp.split(gates, 3, axis=-1)

        v_mix = nn.DenseGeneral(
            features=self.vec_dim, axis=-1, use_bias=False, dtype=self.dtype, name="v_mix"
        )(jnp.swapaxes(v, -1, -2))  # (B, N, 3, dv) channel-mixed
        v_mix = jnp.swapaxes(v_mix, -1, -2)  # (B, N, dv, 3)

        v_nbr = jnp.einsum("bij,bjcd->bicd", attn_mean, v_mix)  # (B, N, dv, 3)
        edge_gate = nn.Dense(self.vec_dim, dtype=self.dtype, name="edge_gate")(rbf)
        v_rel = jnp.einsum("bij,bijc,bijd->bicd", attn_mean, edge_gate, unit)

        v = v + (
            g_self[..., None] * v_mix
            + g_nbr[..., None] * v_nbr
            + g_rel[..., None] * v_rel
        )
        return s, v


class SE3Transformer(nn.Module):
    """Stack of equivariant layers over (scalars, vectors, coords)."""

    dim: int
    depth: int = 4
    vec_dim: int = 16
    heads: int = 4
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, s, v, coords, mask=None):
        for i in range(self.depth):
            s, v = EquivariantLayer(
                dim=self.dim, vec_dim=self.vec_dim, heads=self.heads,
                dtype=self.dtype, name=f"layer_{i}",
            )(s, v, coords, mask=mask)
        return s, v


class SE3TemplateEmbedder(nn.Module):
    """Color residue embeddings with sidechain direction features.

    s: (B, N, dim) residue scalars; sidechain: (B, N, 3) type-1 feature
    (e.g. C -> C-alpha unit vectors); coords: (B, N, 3). Returns colored
    (B, N, dim) scalars — the ``return_type=0`` call of the reference
    (alphafold2.py:530-535).
    """

    dim: int
    depth: int = 2
    vec_dim: int = 8
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, s, sidechain, coords, mask=None):
        # lift the single type-1 feature to vec_dim channels with learned
        # (invariant) per-channel scales
        scales = self.param(
            "sidechain_proj", nn.initializers.normal(1.0), (self.vec_dim,)
        )
        v = sidechain[:, :, None, :] * scales[None, None, :, None].astype(
            sidechain.dtype
        )
        s, _ = SE3Transformer(
            dim=self.dim, depth=self.depth, vec_dim=self.vec_dim,
            dtype=self.dtype, name="net",
        )(s, v, coords, mask=mask)
        return s


class SE3Refiner(nn.Module):
    """Equivariant coordinate refiner (the end-to-end pipeline's final stage).

    tokens: (B, N) int atom/residue tokens; coords: (B, N, 3) proto-structure.
    Returns refined coords (B, N, 3) = coords + equivariant delta — the
    type-1 output call of the reference (train_end2end.py:86-94,168-169).
    """

    dim: int = 64
    depth: int = 2
    vec_dim: int = 8
    num_tokens: int = 32
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, tokens, coords, mask=None):
        s = nn.Embed(self.num_tokens, self.dim, dtype=self.dtype, name="token_emb")(
            tokens
        )
        v = jnp.zeros((*coords.shape[:2], self.vec_dim, 3), dtype=coords.dtype)
        s, v = SE3Transformer(
            dim=self.dim, depth=self.depth, vec_dim=self.vec_dim,
            dtype=self.dtype, name="net",
        )(s, v, coords, mask=mask)
        delta = nn.DenseGeneral(
            features=1, axis=-1, use_bias=False, dtype=self.dtype, name="to_delta"
        )(jnp.swapaxes(v, -1, -2))[..., 0]  # (B, N, 3)
        if mask is not None:
            delta = jnp.where(mask[..., None], delta, 0.0)
        return coords + delta
