"""SE(3)-equivariant attention over point clouds, degrees {0, 1}.

TPU-native replacement for the external ``se3-transformer-pytorch`` dependency
at both reference call sites:

- template sidechain coloring (reference alphafold2.py:372-384, 519-537):
  scalar residue embeddings + one type-1 (vector) sidechain feature at
  template coords -> colored scalar embeddings (``return_type=0``)
- end-to-end coordinate refiner (reference train_end2end.py:86-94, 168-169):
  atom-token scalars at proto-structure coords -> refined coords (type-1 out)

Both sites use only degree-0 and degree-1 features (SURVEY.md S7 "hard
parts"), so instead of a spherical-harmonic SE(3)-Transformer this is a
geometric vector attention network: all interactions go through rotation
invariants (scalar features, pairwise distances) and rotation-covariant
linear maps (channel-mixing of vectors, relative-position directions), which
is exactly equivariant under SE(3) by construction.

TPU-first choices: dense all-pairs attention with an RBF distance bias in
place of the reference's 12-nearest-neighbor graph gathers (dynamic gathers
are hostile to XLA; N here is a few hundred, so dense attention is a clean
MXU matmul), static shapes throughout. Equivariance is verified numerically
in tests/test_se3.py (the reference has no such test).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn

from alphafold2_tpu.ops.attention import MASK_VALUE


def _safe_norm(v, axis=-1, keepdims=False, eps=1e-8):
    sq = jnp.sum(v * v, axis=axis, keepdims=keepdims)
    return jnp.sqrt(sq + eps)


def radial_basis(dist, num_basis: int = 16, max_dist: float = 20.0):
    """Distances -> smooth RBF features. Plain function so the streamed
    edge-attention path can evaluate it inside lax.scan (flax submodules
    cannot be called under traced control flow); RadialBasis wraps it for
    the module API. Parameter-free either way."""
    centers = jnp.linspace(0.0, max_dist, num_basis)
    width = max_dist / num_basis
    return jnp.exp(-(((dist[..., None] - centers) / width) ** 2))


class RadialBasis(nn.Module):
    """Distances -> smooth RBF features (invariant edge descriptors)."""

    num_basis: int = 16
    max_dist: float = 20.0

    @nn.compact
    def __call__(self, dist):
        return radial_basis(dist, self.num_basis, self.max_dist)


class EquivariantLayer(nn.Module):
    """One block: invariant attention + scalar/vector residual updates.

    Scalars s: (B, N, ds); vectors v: (B, N, dv, 3); coords: (B, N, 3).
    Attention logits are built from scalars and RBF(distance) only
    (invariant); value aggregation mixes neighbor vectors and relative
    directions gated by invariant scalars (covariant).
    """

    dim: int
    vec_dim: int = 16
    heads: int = 4
    num_basis: int = 16
    dtype: jnp.dtype = jnp.float32

    # q-block / kv-chunk edge of the streamed long-chain path (elements of
    # one (B, blk, blk) edge tile; all tiles are static shapes)
    edge_block: int = 1024

    @nn.compact
    def __call__(self, s, v, coords, mask=None):
        b, n, ds = s.shape
        h = self.heads
        dh = self.dim // h

        # all parameterized submodules are created here with explicit names
        # so the dense and streamed paths own the IDENTICAL parameter tree
        rbf_basis = RadialBasis(self.num_basis)
        rbf_bias = nn.Dense(h, dtype=self.dtype, name="rbf_bias")
        edge_gate = nn.Dense(self.vec_dim, dtype=self.dtype, name="edge_gate")

        sn = nn.LayerNorm(dtype=self.dtype, name="s_norm")(s)
        q = nn.Dense(self.dim, use_bias=False, dtype=self.dtype, name="q")(sn)
        k = nn.Dense(self.dim, use_bias=False, dtype=self.dtype, name="k")(sn)
        q = q.reshape(b, n, h, dh)
        k = k.reshape(b, n, h, dh)
        vals = nn.Dense(self.dim, use_bias=False, dtype=self.dtype, name="val")(sn)
        vals = vals.reshape(b, n, h, dh)
        v_mix = nn.DenseGeneral(
            features=self.vec_dim, axis=-1, use_bias=False, dtype=self.dtype, name="v_mix"
        )(jnp.swapaxes(v, -1, -2))  # (B, N, 3, dv) channel-mixed
        v_mix = jnp.swapaxes(v_mix, -1, -2)  # (B, N, dv, 3)

        from alphafold2_tpu.ops.chunked import should_chunk

        # long-chain point clouds (serve buckets 512+ lift to 14L atoms):
        # the dense path's (B, N, N, R) RBF edge tensor alone is GBs, so
        # past the chunk threshold the edge features, attention and all
        # three attended aggregations stream block-by-block with an online
        # softmax — exact, same parameters, O(block^2) peak memory.
        if should_chunk(b * self.num_basis, n, n):
            s_agg, v_nbr, v_rel = self._streamed_attention(
                b, n, h, dh, q, k, vals, v_mix, coords, mask,
                rbf_basis, rbf_bias, edge_gate,
            )
        else:
            rel = coords[:, :, None, :] - coords[:, None, :, :]  # (B,N,N,3)
            dist = _safe_norm(rel)  # (B, N, N)
            unit = rel / dist[..., None]
            rbf = rbf_basis(dist).astype(self.dtype)  # (B, N, N, R)

            logits = jnp.einsum("bihd,bjhd->bhij", q, k) * dh**-0.5
            logits = logits + jnp.moveaxis(rbf_bias(rbf), -1, 1)
            if mask is not None:
                pair = mask[:, None, None, :] & mask[:, None, :, None]
                logits = jnp.where(pair, logits, MASK_VALUE)
            attn = jax.nn.softmax(
                logits.astype(jnp.float32), axis=-1
            ).astype(self.dtype)
            attn_mean = attn.mean(axis=1)  # (B, N, N) head-averaged

            s_agg = jnp.einsum("bhij,bjhd->bihd", attn, vals).reshape(
                b, n, self.dim
            )
            v_nbr = jnp.einsum("bij,bjcd->bicd", attn_mean, v_mix)
            v_rel = jnp.einsum(
                "bij,bijc,bijd->bicd", attn_mean, edge_gate(rbf), unit
            )

        # scalar update: attended neighbor scalars + invariant vector norms
        v_norms = _safe_norm(v)  # (B, N, dv) invariant
        s_in = jnp.concatenate([s_agg, v_norms.astype(self.dtype)], axis=-1)
        s = s + nn.Dense(ds, dtype=self.dtype, name="s_out")(s_in)

        # vector update: equivariant combination of
        #   (a) channel-mixed own vectors, (b) attended neighbor vectors,
        #   (c) attended relative directions — each gated by invariant scalars
        gates = nn.Dense(3 * self.vec_dim, dtype=self.dtype, name="gates")(
            nn.LayerNorm(dtype=self.dtype, name="s_norm2")(s)
        )
        g_self, g_nbr, g_rel = jnp.split(gates, 3, axis=-1)

        v = v + (
            g_self[..., None] * v_mix
            + g_nbr[..., None] * v_nbr
            + g_rel[..., None] * v_rel
        )
        return s, v

    def _streamed_attention(
        self, b, n, h, dh, q, k, vals, v_mix, coords, mask,
        rbf_basis, rbf_bias, edge_gate,
    ):
        """Online-softmax edge streaming: one (q-block, kv-chunk) tile of
        rel/dist/RBF/logits is live at a time; the three attended
        aggregations (neighbor scalars, neighbor vectors, gated relative
        directions) share the running (max, denom) like ops/chunked.py.

        lax.map over q blocks + lax.scan over kv chunks, so XLA's buffer
        assignment genuinely reuses one tile (an unrolled python loop kept
        every tile alive — 5 GB of temps at 14L = 7168 atoms; this form
        measures ~tile-sized). Flax submodules cannot be CALLED under
        traced control flow, so the edge Dense layers are materialized
        once on a dummy row and their kernels applied as plain matmuls
        inside the scan — same parameters, same math."""
        blk = min(self.edge_block, n)
        dv = self.vec_dim
        f32 = jnp.float32
        dt = self.dtype

        # materialize the edge Dense params outside the scan (output
        # unused -> DCE'd), then read their kernels for in-scan matmuls
        dummy = jnp.zeros((1, self.num_basis), dt)
        rbf_bias(dummy)
        edge_gate(dummy)
        bias_w = rbf_bias.variables["params"]["kernel"].astype(dt)
        bias_b = rbf_bias.variables["params"]["bias"].astype(dt)
        gate_w = edge_gate.variables["params"]["kernel"].astype(dt)
        gate_b = edge_gate.variables["params"]["bias"].astype(dt)

        pad = (-n) % blk
        n_p = n + pad
        eff_mask = mask if mask is not None else jnp.ones((b, n), bool)
        if pad:  # padded rows are masked keys; padded q rows sliced off
            eff_mask = jnp.pad(eff_mask, ((0, 0), (0, pad)))

        def pad_n(t):
            return jnp.pad(
                t, ((0, 0), (0, pad)) + ((0, 0),) * (t.ndim - 2)
            ) if pad else t

        q_p, k_p = pad_n(q), pad_n(k)
        vals_p, vmix_p, coords_p = pad_n(vals), pad_n(v_mix), pad_n(coords)
        n_blocks = n_p // blk

        def chunks(t, axis_to_front=1):
            # (B, n_p, ...) -> (n_blocks, B, blk, ...)
            return jnp.moveaxis(
                t.reshape(t.shape[0], n_blocks, blk, *t.shape[2:]), 1, 0
            )

        k_s, vals_s = chunks(k_p), chunks(vals_p)
        vmix_s, coords_s = chunks(vmix_p), chunks(coords_p)
        mask_s = jnp.moveaxis(eff_mask.reshape(b, n_blocks, blk), 1, 0)

        def q_block(args):
            q_blk, c_blk, m_blk = args  # (B, blk, h, dh) / (B, blk, 3) / ..

            def kv_step(carry, chunk):
                m_run, l_run, acc_s, acc_nbr, acc_rel = carry
                k_c, val_c, vm_c, c_c, km_c = chunk
                rel = c_blk[:, :, None, :] - c_c[:, None, :, :]
                dist = _safe_norm(rel)  # (B, blk_i, blk_j)
                unit = rel / dist[..., None]
                rbf = radial_basis(dist, self.num_basis).astype(dt)
                logits = (
                    jnp.einsum("bihd,bjhd->bhij", q_blk, k_c) * dh**-0.5
                )
                logits = logits + jnp.moveaxis(
                    rbf @ bias_w + bias_b, -1, 1
                )
                pair = km_c[:, None, None, :] & m_blk[:, None, :, None]
                logits = jnp.where(pair, logits, MASK_VALUE).astype(f32)
                m_new = jnp.maximum(m_run, logits.max(axis=-1))
                p = jnp.exp(logits - m_new[..., None])
                r = jnp.exp(m_run - m_new)
                l_new = l_run * r + p.sum(axis=-1)
                acc_s = acc_s * r[..., None] + jnp.einsum(
                    "bhij,bjhd->bhid", p, val_c.astype(f32)
                )
                acc_nbr = acc_nbr * r[..., None, None] + jnp.einsum(
                    "bhij,bjcd->bhicd", p, vm_c.astype(f32)
                )
                acc_rel = acc_rel * r[..., None, None] + jnp.einsum(
                    "bhij,bijc,bijd->bhicd",
                    p,
                    (rbf @ gate_w + gate_b).astype(f32),
                    unit.astype(f32),
                )
                return (m_new, l_new, acc_s, acc_nbr, acc_rel), None

            init = (
                jnp.full((b, h, blk), -jnp.inf, f32),
                jnp.zeros((b, h, blk), f32),
                jnp.zeros((b, h, blk, dh), f32),
                jnp.zeros((b, h, blk, dv, 3), f32),
                jnp.zeros((b, h, blk, dv, 3), f32),
            )
            (m_run, l_run, acc_s, acc_nbr, acc_rel), _ = jax.lax.scan(
                kv_step, init, (k_s, vals_s, vmix_s, coords_s, mask_s)
            )
            inv_l = 1.0 / jnp.maximum(l_run, 1e-30)  # (B, h, blk)
            s_blk = (
                jnp.moveaxis(acc_s * inv_l[..., None], 1, 2)
                .reshape(b, blk, self.dim)
                .astype(dt)
            )
            nbr_blk = (acc_nbr * inv_l[..., None, None]).mean(axis=1)
            rel_blk = (acc_rel * inv_l[..., None, None]).mean(axis=1)
            return s_blk, nbr_blk.astype(v_mix.dtype), rel_blk.astype(
                v_mix.dtype
            )

        s_b, nbr_b, rel_b = jax.lax.map(
            q_block, (chunks(q_p), coords_s, mask_s)
        )

        def unblock(t):  # (n_blocks, B, blk, ...) -> (B, n, ...)
            t = jnp.moveaxis(t, 0, 1)
            return t.reshape(b, n_p, *t.shape[3:])[:, :n]

        return unblock(s_b), unblock(nbr_b), unblock(rel_b)


class SE3Transformer(nn.Module):
    """Stack of equivariant layers over (scalars, vectors, coords)."""

    dim: int
    depth: int = 4
    vec_dim: int = 16
    heads: int = 4
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, s, v, coords, mask=None):
        for i in range(self.depth):
            s, v = EquivariantLayer(
                dim=self.dim, vec_dim=self.vec_dim, heads=self.heads,
                dtype=self.dtype, name=f"layer_{i}",
            )(s, v, coords, mask=mask)
        return s, v


class SE3TemplateEmbedder(nn.Module):
    """Color residue embeddings with sidechain direction features.

    s: (B, N, dim) residue scalars; sidechain: (B, N, 3) type-1 feature
    (e.g. C -> C-alpha unit vectors); coords: (B, N, 3). Returns colored
    (B, N, dim) scalars — the ``return_type=0`` call of the reference
    (alphafold2.py:530-535).
    """

    dim: int
    depth: int = 2
    vec_dim: int = 8
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, s, sidechain, coords, mask=None):
        # lift the single type-1 feature to vec_dim channels with learned
        # (invariant) per-channel scales
        scales = self.param(
            "sidechain_proj", nn.initializers.normal(1.0), (self.vec_dim,)
        )
        v = sidechain[:, :, None, :] * scales[None, None, :, None].astype(
            sidechain.dtype
        )
        s, _ = SE3Transformer(
            dim=self.dim, depth=self.depth, vec_dim=self.vec_dim,
            dtype=self.dtype, name="net",
        )(s, v, coords, mask=mask)
        return s


class SE3Refiner(nn.Module):
    """Equivariant coordinate refiner (the end-to-end pipeline's final stage).

    tokens: (B, N) int atom/residue tokens; coords: (B, N, 3) proto-structure.
    Returns refined coords (B, N, 3) = coords + equivariant delta — the
    type-1 output call of the reference (train_end2end.py:86-94,168-169).
    """

    dim: int = 64
    depth: int = 2
    vec_dim: int = 8
    num_tokens: int = 32
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, tokens, coords, mask=None):
        s = nn.Embed(self.num_tokens, self.dim, dtype=self.dtype, name="token_emb")(
            tokens
        )
        v = jnp.zeros((*coords.shape[:2], self.vec_dim, 3), dtype=coords.dtype)
        s, v = SE3Transformer(
            dim=self.dim, depth=self.depth, vec_dim=self.vec_dim,
            dtype=self.dtype, name="net",
        )(s, v, coords, mask=mask)
        delta = nn.DenseGeneral(
            features=1, axis=-1, use_bias=False, dtype=self.dtype, name="to_delta"
        )(jnp.swapaxes(v, -1, -2))[..., 0]  # (B, N, 3)
        if mask is not None:
            delta = jnp.where(mask[..., None], delta, 0.0)
        return coords + delta
