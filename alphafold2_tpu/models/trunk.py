"""Trunk execution engines: interleaved [self-attn, cross-attn] layer pairs.

Covers both reference engines (alphafold2.py:291-327 SequentialSequence,
reversible.py ReversibleSequence) with three TPU-native options:

- default: python loop over :class:`TrunkLayer` (the SequentialSequence
  equivalent); ``scan_layers=True`` rolls it into one ``lax.scan`` with
  stacked params (depth-independent compile, no reference analogue).
- ``remat=True``: O(1)-in-depth activation memory via XLA rematerialization
  (``jax.checkpoint``) — recompute in backward, dropout replayed exactly by
  stateless PRNG keys (no ``Deterministic`` RNG capture machinery,
  reference reversible.py:26-56). Parameter-isomorphic with the default
  engine (the reference's two engines are NOT isomorphic — it drops each
  self-block's MSA feedforward in the sequential engine, alphafold2.py:
  427-428; SURVEY.md S2.5 flags this defect and we do not replicate it).
  Gradient parity proven in tests/test_remat.py.
- ``reversible=True``: the direct equivalent of the reference's reversible
  engine — inversion-based O(1) memory coupling (models/reversible.py).
  A DIFFERENT network from the other two engines (halved two-stream state,
  twice the feedforwards per depth step, its own stacked parameter tree):
  checkpoints are not interchangeable across this flag, exactly as
  reference reversible/sequential configs differ. Takes precedence over
  ``remat``/``scan_layers`` (it already scans stacked params and needs no
  remat). Gradient parity of its custom backward: tests/test_reversible.py.

Streams stay in grid form throughout: pair (B, N, N, D), MSA (B, M, Nm, D).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from alphafold2_tpu.observe.numerics import tag
from alphafold2_tpu.ops.attention import Attention, AxialAttention, FeedForward
from alphafold2_tpu.parallel.sharding import shard_pair, shard_msa


class TrunkLayer(nn.Module):
    """One depth step: axial self-attn on both streams, bidirectional
    cross-attn between them, then feedforwards. All residual, all pre-LN."""

    dim: int
    heads: int = 8
    dim_head: int = 64
    attn_dropout: float = 0.0
    ff_dropout: float = 0.0
    gelu_exact: bool = False  # erf GELU (the reference's torch F.gelu)
    sparse_attn: bool = False
    seq_len: Optional[int] = None
    sparse_config: Optional[object] = None  # ops.sparse.BlockSparseConfig
    sparse_use_pallas: Optional[bool] = None
    cross_attn_compress_ratio: int = 1
    msa_tie_row_attn: bool = False
    msa_row_shard: bool = False  # shard MSA rows over sp (tied psum via GSPMD)
    context_parallel: Optional[str] = None  # None | "ring" | "ulysses"
    use_flash: Optional[bool] = None  # fused dense attention on TPU
    grid_parallel: bool = False  # 2D-sharded pair axial passes (spr x spc)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,  # (B, N, N, D) pair grid
        m: Optional[jnp.ndarray],  # (B, M, Nm, D) MSA grid or None
        pair_mask: Optional[jnp.ndarray] = None,  # (B, N, N)
        msa_mask: Optional[jnp.ndarray] = None,  # (B, M, Nm)
        deterministic: bool = True,
    ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
        dt = self.dtype
        ln = lambda name: nn.LayerNorm(dtype=dt, name=name)

        # pair self-attention (axial over the N x N grid)
        x = x + AxialAttention(
            dim=self.dim,
            heads=self.heads,
            dim_head=self.dim_head,
            dropout=self.attn_dropout,
            sparse_attn=self.sparse_attn,
            seq_len=self.seq_len,
            sparse_config=self.sparse_config,
            sparse_use_pallas=self.sparse_use_pallas,
            use_flash=self.use_flash,
            grid_parallel=self.grid_parallel,
            dtype=dt,
            name="pair_axial",
        )(ln("pair_axial_norm")(x), mask=pair_mask, deterministic=deterministic)
        x = shard_pair(x)

        if m is not None:
            # MSA self-attention (axial over the M x Nm grid, rows optionally tied)
            m = m + AxialAttention(
                dim=self.dim,
                heads=self.heads,
                dim_head=self.dim_head,
                dropout=self.attn_dropout,
                tie_row_attn=self.msa_tie_row_attn,
                use_flash=self.use_flash,
                dtype=dt,
                name="msa_axial",
            )(ln("msa_axial_norm")(m), mask=msa_mask, deterministic=deterministic)
            m = shard_msa(m, rows=self.msa_row_shard)

            # cross-attention: pair tokens query the MSA stream and vice versa
            b, n, n2, d = x.shape
            bm, mm, nm, _ = m.shape
            x_flat = x.reshape(b, n * n2, d)
            m_flat = m.reshape(bm, mm * nm, d)
            x_mask_flat = (
                pair_mask.reshape(b, n * n2) if pair_mask is not None else None
            )
            m_mask_flat = (
                msa_mask.reshape(bm, mm * nm) if msa_mask is not None else None
            )

            x_flat = x_flat + Attention(
                dim=self.dim,
                heads=self.heads,
                dim_head=self.dim_head,
                dropout=self.attn_dropout,
                compress_ratio=self.cross_attn_compress_ratio,
                context_parallel=self.context_parallel,
                use_flash=self.use_flash,
                dtype=dt,
                name="pair_from_msa",
            )(
                ln("pair_cross_norm")(x_flat),
                context=ln("pair_cross_ctx_norm")(m_flat),
                mask=x_mask_flat,
                context_mask=m_mask_flat,
                deterministic=deterministic,
            )
            m_flat = m_flat + Attention(
                dim=self.dim,
                heads=self.heads,
                dim_head=self.dim_head,
                dropout=self.attn_dropout,
                context_parallel=self.context_parallel,
                use_flash=self.use_flash,
                dtype=dt,
                name="msa_from_pair",
            )(
                ln("msa_cross_norm")(m_flat),
                context=ln("msa_cross_ctx_norm")(x_flat),
                mask=m_mask_flat,
                context_mask=x_mask_flat,
                deterministic=deterministic,
            )
            x = shard_pair(x_flat.reshape(b, n, n2, d))
            m = shard_msa(m_flat.reshape(bm, mm, nm, d), rows=self.msa_row_shard)

        # feedforwards
        x = x + FeedForward(
            dim=self.dim, dropout=self.ff_dropout,
            gelu_exact=self.gelu_exact, dtype=dt, name="pair_ff"
        )(ln("pair_ff_norm")(x), deterministic=deterministic)
        x = shard_pair(x)
        if m is not None:
            m = m + FeedForward(
                dim=self.dim, dropout=self.ff_dropout,
                gelu_exact=self.gelu_exact, dtype=dt, name="msa_ff"
            )(ln("msa_ff_norm")(m), deterministic=deterministic)
            m = shard_msa(m, rows=self.msa_row_shard)

        return x, m


def resolve_remat_policy(name):
    """Map a config-level policy name to a jax.checkpoint policy.

    None/"nothing" = save nothing (full recompute — max memory savings,
    the long-standing behavior). "dots" / "dots_no_batch" save matmul
    outputs ("no_batch" excludes batched dots): the backward pass skips
    recomputing the MXU-heavy ops at the cost of keeping their outputs —
    the standard memory/MFU trade on TPU.
    """
    if name is None or name == "nothing":
        return None
    policies = {
        "dots": jax.checkpoint_policies.checkpoint_dots,
        "dots_no_batch": (
            jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
        ),
    }
    if name not in policies:
        raise ValueError(
            f"unknown remat_policy {name!r}; have "
            f"{[None, 'nothing', *policies]}"
        )
    return policies[name]


class _ScanBody(nn.Module):
    """nn.scan body: carries (x, m) through one TrunkLayer; masks ride in
    as broadcast (loop-invariant) scan inputs."""

    layer_kwargs: dict
    deterministic: bool
    remat: bool
    remat_policy: Optional[str] = None

    @nn.compact
    def __call__(self, carry, pair_mask, msa_mask):
        x, m = carry
        layer_cls = TrunkLayer
        if self.remat:
            # prevent_cse=False: the CSE-prevention barriers jax.checkpoint
            # inserts by default are unnecessary (and costly) inside scan
            layer_cls = nn.remat(
                TrunkLayer, static_argnums=(5,), prevent_cse=False,
                policy=resolve_remat_policy(self.remat_policy),
            )
        x, m = layer_cls(**self.layer_kwargs, name="layer")(
            x, m, pair_mask, msa_mask, self.deterministic
        )
        return (x, m), ()


class Trunk(nn.Module):
    """Stack of TrunkLayers; ``remat=True`` checkpoints each layer, and
    ``reversible=True`` dispatches to the inversion-based engine (see the
    module docstring for the three-engine map; reversible takes precedence
    over remat/scan_layers and has its own parameter layout).

    ``scan_layers=True`` rolls the depth loop into one ``lax.scan`` over a
    single layer with stacked parameters: the trunk is traced/compiled ONCE
    regardless of depth (compile time and program size stop growing with
    depth — the TPU-first answer to deep trunks). Requires homogeneous
    layers (a per-layer ``sparse_self_attn`` tuple needs the python loop).
    Parameter trees differ between the two modes (stacked vs layer_i), so
    checkpoints are not interchangeable across the flag.
    """

    dim: int
    depth: int = 6
    heads: int = 8
    dim_head: int = 64
    attn_dropout: float = 0.0
    ff_dropout: float = 0.0
    gelu_exact: bool = False  # erf GELU (the reference's torch F.gelu)
    sparse_self_attn: tuple | bool = False
    seq_len: Optional[int] = None
    sparse_config: Optional[object] = None  # ops.sparse.BlockSparseConfig
    sparse_use_pallas: Optional[bool] = None
    cross_attn_compress_ratio: int = 1
    msa_tie_row_attn: bool = False
    msa_row_shard: bool = False  # shard MSA rows over sp (tied psum via GSPMD)
    context_parallel: Optional[str] = None  # None | "ring" | "ulysses"
    use_flash: Optional[bool] = None  # fused dense attention on TPU
    grid_parallel: bool = False  # 2D-sharded pair axial passes (spr x spc)
    remat: bool = False
    remat_policy: Optional[str] = None  # None/"nothing" | "dots" | "dots_no_batch"
    reversible: bool = False  # inversion-based O(1)-memory engine
    scan_layers: bool = False
    dtype: jnp.dtype = jnp.float32

    def _layer_kwargs(self, sparse: bool) -> dict:
        return dict(
            dim=self.dim,
            heads=self.heads,
            dim_head=self.dim_head,
            attn_dropout=self.attn_dropout,
            ff_dropout=self.ff_dropout,
            gelu_exact=self.gelu_exact,
            sparse_attn=sparse,
            seq_len=self.seq_len,
            sparse_config=self.sparse_config,
            sparse_use_pallas=self.sparse_use_pallas,
            cross_attn_compress_ratio=self.cross_attn_compress_ratio,
            msa_tie_row_attn=self.msa_tie_row_attn,
            msa_row_shard=self.msa_row_shard,
            context_parallel=self.context_parallel,
            use_flash=self.use_flash,
            grid_parallel=self.grid_parallel,
            dtype=self.dtype,
        )

    @nn.compact
    def __call__(
        self, x, m, pair_mask=None, msa_mask=None, deterministic: bool = True
    ):
        sparse_flags = self.sparse_self_attn
        if not isinstance(sparse_flags, (tuple, list)):
            sparse_flags = (sparse_flags,) * self.depth
        if len(sparse_flags) != self.depth:
            raise ValueError(
                f"sparse_self_attn tuple has {len(sparse_flags)} entries "
                f"for depth {self.depth}"
            )

        # validate eagerly: a policy name (even a typo) with remat off, or
        # with the reversible engine (which never applies it), would
        # otherwise be a silent no-op — the config asked for a memory/MFU
        # trade that is not happening. "nothing" is the explicit spelling
        # of the default and is always allowed.
        if resolve_remat_policy(self.remat_policy) is not None and (
            not self.remat or self.reversible
        ):
            raise ValueError(
                f"remat_policy={self.remat_policy!r} has no effect "
                + ("with the reversible engine (it has its own O(1)-memory "
                   "schedule and never applies checkpoint policies)"
                   if self.reversible else "without remat=True")
            )

        if self.reversible:
            # true reversible coupling engine (reference reversible.py);
            # already scans over stacked per-depth params, so scan_layers
            # is implied and remat is redundant
            from alphafold2_tpu.models.reversible import ReversibleTrunk

            if len(set(sparse_flags)) > 1:
                raise ValueError(
                    "the reversible engine scans one stacked layer; "
                    f"per-layer sparse_self_attn={sparse_flags} needs the "
                    "python loop"
                )
            if self.context_parallel is not None:
                raise ValueError(
                    "context_parallel is not supported by the reversible "
                    "engine (its cross-attention runs dense per device); "
                    "use remat=True with context_parallel, or reversible "
                    "without it"
                )
            if self.msa_row_shard:
                raise ValueError(
                    "msa_row_shard is not supported by the reversible "
                    "engine (its MSA streams are replicated); use "
                    "remat=True to combine MSA-row sharding with O(1) "
                    "activation memory"
                )
            if self.grid_parallel:
                raise ValueError(
                    "grid_parallel is not supported by the reversible "
                    "engine (its axial passes run dense, so the 2D-sharded "
                    "pair state would be all-gathered and the memory "
                    "benefit silently lost); use remat=True with "
                    "grid_parallel"
                )
            x, m = ReversibleTrunk(
                dim=self.dim,
                depth=self.depth,
                heads=self.heads,
                dim_head=self.dim_head,
                attn_dropout=self.attn_dropout,
                ff_dropout=self.ff_dropout,
                gelu_exact=self.gelu_exact,
                sparse_attn=sparse_flags[0],
                seq_len=self.seq_len,
                sparse_config=self.sparse_config,
                sparse_use_pallas=self.sparse_use_pallas,
                cross_attn_compress_ratio=self.cross_attn_compress_ratio,
                msa_tie_row_attn=self.msa_tie_row_attn,
                use_flash=self.use_flash,
                dtype=self.dtype,
                name="reversible",
            )(x, m, pair_mask=pair_mask, msa_mask=msa_mask,
              deterministic=deterministic)
            # numerics tags only at the engine boundary: tagging inside the
            # scanned/custom-backward body would capture inner-trace tracers
            x = tag("trunk.out.pair", x)
            if m is not None:
                m = tag("trunk.out.msa", m)
            return x, m

        if self.scan_layers:
            if len(set(sparse_flags)) > 1:
                raise ValueError(
                    "scan_layers needs homogeneous layers; per-layer "
                    f"sparse_self_attn={sparse_flags} requires the python "
                    "loop"
                )
            scanned = nn.scan(
                _ScanBody,
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                in_axes=(nn.broadcast, nn.broadcast),
                length=self.depth,
            )(
                layer_kwargs=self._layer_kwargs(sparse_flags[0]),
                deterministic=deterministic,
                remat=self.remat,
                remat_policy=self.remat_policy,
                name="scan",
            )
            (x, m), _ = scanned((x, m), pair_mask, msa_mask)
            # per-layer tags would sit inside the scan body (inner tracers);
            # the scanned engine tags at the trunk boundary only
            x = tag("trunk.out.pair", x)
            if m is not None:
                m = tag("trunk.out.msa", m)
            return x, m

        layer_cls = TrunkLayer
        if self.remat:
            layer_cls = nn.remat(
                TrunkLayer, static_argnums=(5,),
                policy=resolve_remat_policy(self.remat_policy),
            )

        for i, sparse in enumerate(sparse_flags):
            x, m = layer_cls(
                **self._layer_kwargs(sparse), name=f"layer_{i}"
            )(x, m, pair_mask, msa_mask, deterministic)
            # layer-boundary numerics tags: OUTSIDE the (possibly remat'ed)
            # layer body, so the stats are outer-trace values in every
            # engine mode; tag order == depth order == topological order
            x = tag(f"trunk.layer_{i}.pair", x)
            if m is not None:
                m = tag(f"trunk.layer_{i}.msa", m)
        return x, m
