"""True reversible trunk: inversion-based O(1)-activation-memory backward.

Direct TPU-native equivalent of the reference's reversible engine
(``alphafold2_pytorch/reversible.py``): ``ReversibleSelfAttnBlock`` /
``ReversibleCrossAttnBlock`` (:60-262) couple two halves of each stream with
additive updates, and a hand-written ``torch.autograd.Function`` (:266-300)
reconstructs activations in backward by *inverting* the coupling instead of
storing them.

Design (not a port):

- The coupling runs under ONE ``lax.scan`` over stacked per-depth parameters,
  wrapped in ``jax.custom_vjp``. Forward saves only the final carry; backward
  scans the layers in reverse, walking each layer's 8 additive updates
  backwards — every sub-function is evaluated ONCE under a local ``jax.vjp``,
  its output reused for both the inversion subtraction and the cotangent
  pull. Activation memory is O(1) in depth and recompute cost is one extra
  evaluation per sub-function, like the reference — but the schedule is
  compiled by XLA, not interpreted per-block by an autograd tape.
- The reference needs CUDA RNG state capture/replay (``Deterministic``,
  reversible.py:26-56) to make dropout recompute bit-exact. Stateless JAX
  PRNG keys make replay exact by construction: the same per-layer key is
  passed to the forward, the inversion, and the recompute.
- The reference doubles channels and halves them per block
  (reversible.py:319,327); here the two halves are two copies of the
  stream — same coupling math, no concat/split churn.

Where ``Trunk(remat=True)`` trades memory for a full forward recompute,
the reversible engine reconstructs activations by inversion (one extra
f/g/j/k evaluation per block, same as the reference's backward_pass). Both
are exposed; ``tests/test_reversible.py`` proves gradient parity of the
custom backward against plain autodiff — the analogue of the reference's
``tests/test_reversible.py`` oracle.

Coupling per depth step (reference reversible.py:76-83, 176-181):

    self block:   x1 += f_s(x2);        x2 += g_s(x1)
                  m1 += j_s(m2);        m2 += k_s(m1)
    cross block:  x1 += f_c(x2, m2);    x2 += g_c(x1)
                  m1 += j_c(m2, x2);    m2 += k_c(m1)

Each update writes one half from the other(s), so the whole step inverts
exactly by running the updates backwards with subtraction.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from alphafold2_tpu.ops.attention import Attention, AxialAttention, FeedForward
from alphafold2_tpu.parallel.sharding import shard_msa, shard_pair


def _float0_zeros(x):
    return np.zeros(np.shape(x), dtype=jax.dtypes.float0)


class RevLayerPair(nn.Module):
    """One reversible depth step: [self-attn block, cross-attn block] over the
    (x1, x2, m1, m2) halved two-stream state. ``__call__`` is the forward
    coupling; :meth:`invert` reconstructs inputs from outputs exactly."""

    dim: int
    heads: int = 8
    dim_head: int = 64
    attn_dropout: float = 0.0
    ff_dropout: float = 0.0
    gelu_exact: bool = False  # erf GELU (the reference's torch F.gelu)
    sparse_attn: bool = False
    seq_len: Optional[int] = None
    sparse_config: Optional[object] = None
    sparse_use_pallas: Optional[bool] = None
    cross_attn_compress_ratio: int = 1
    msa_tie_row_attn: bool = False
    use_flash: Optional[bool] = None
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        dt = self.dtype
        ax = dict(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            dropout=self.attn_dropout, use_flash=self.use_flash, dtype=dt,
        )
        self.f_s_norm = nn.LayerNorm(dtype=dt)
        self.f_s = AxialAttention(
            sparse_attn=self.sparse_attn, seq_len=self.seq_len,
            sparse_config=self.sparse_config,
            sparse_use_pallas=self.sparse_use_pallas, **ax,
        )
        self.g_s_norm = nn.LayerNorm(dtype=dt)
        self.g_s = FeedForward(dim=self.dim, dropout=self.ff_dropout, gelu_exact=self.gelu_exact, dtype=dt)
        self.j_s_norm = nn.LayerNorm(dtype=dt)
        self.j_s = AxialAttention(tie_row_attn=self.msa_tie_row_attn, **ax)
        self.k_s_norm = nn.LayerNorm(dtype=dt)
        self.k_s = FeedForward(dim=self.dim, dropout=self.ff_dropout, gelu_exact=self.gelu_exact, dtype=dt)

        at = dict(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            dropout=self.attn_dropout, use_flash=self.use_flash, dtype=dt,
        )
        self.f_c_norm = nn.LayerNorm(dtype=dt)
        self.f_c_ctx_norm = nn.LayerNorm(dtype=dt)
        self.f_c = Attention(compress_ratio=self.cross_attn_compress_ratio, **at)
        self.g_c_norm = nn.LayerNorm(dtype=dt)
        self.g_c = FeedForward(dim=self.dim, dropout=self.ff_dropout, gelu_exact=self.gelu_exact, dtype=dt)
        self.j_c_norm = nn.LayerNorm(dtype=dt)
        self.j_c_ctx_norm = nn.LayerNorm(dtype=dt)
        self.j_c = Attention(**at)
        self.k_c_norm = nn.LayerNorm(dtype=dt)
        self.k_c = FeedForward(dim=self.dim, dropout=self.ff_dropout, gelu_exact=self.gelu_exact, dtype=dt)

    # --- the eight sub-functions (each used once per direction) ---

    def _f_s(self, x2, pm, det):
        return self.f_s(self.f_s_norm(x2), mask=pm, deterministic=det)

    def _g_s(self, x1, det):
        return self.g_s(self.g_s_norm(x1), deterministic=det)

    def _j_s(self, m2, mm, det):
        return self.j_s(self.j_s_norm(m2), mask=mm, deterministic=det)

    def _k_s(self, m1, det):
        return self.k_s(self.k_s_norm(m1), deterministic=det)

    def _f_c(self, x2, m2, pm, mm, det):
        b, n, n2, d = x2.shape
        xf = x2.reshape(b, n * n2, d)
        mf = m2.reshape(b, -1, d)
        out = self.f_c(
            self.f_c_norm(xf),
            context=self.f_c_ctx_norm(mf),
            mask=pm.reshape(b, -1) if pm is not None else None,
            context_mask=mm.reshape(b, -1) if mm is not None else None,
            deterministic=det,
        )
        return out.reshape(b, n, n2, d)

    def _g_c(self, x1, det):
        return self.g_c(self.g_c_norm(x1), deterministic=det)

    def _j_c(self, m2, x2, pm, mm, det):
        b = m2.shape[0]
        mf = m2.reshape(b, -1, m2.shape[-1])
        xf = x2.reshape(b, -1, x2.shape[-1])
        out = self.j_c(
            self.j_c_norm(mf),
            context=self.j_c_ctx_norm(xf),
            mask=mm.reshape(b, -1) if mm is not None else None,
            context_mask=pm.reshape(b, -1) if pm is not None else None,
            deterministic=det,
        )
        return out.reshape(m2.shape)

    def _k_c(self, m1, det):
        return self.k_c(self.k_c_norm(m1), deterministic=det)

    def __call__(self, h, pair_mask=None, msa_mask=None, deterministic=True):
        x1, x2, m1, m2 = h
        pm, mm, det = pair_mask, msa_mask, deterministic
        # self block
        x1 = shard_pair(x1 + self._f_s(x2, pm, det))
        x2 = shard_pair(x2 + self._g_s(x1, det))
        m1 = shard_msa(m1 + self._j_s(m2, mm, det))
        m2 = shard_msa(m2 + self._k_s(m1, det))
        # cross block
        x1 = shard_pair(x1 + self._f_c(x2, m2, pm, mm, det))
        x2 = shard_pair(x2 + self._g_c(x1, det))
        m1 = shard_msa(m1 + self._j_c(m2, x2, pm, mm, det))
        m2 = shard_msa(m2 + self._k_c(m1, det))
        return (x1, x2, m1, m2)

    def invert(self, h, pair_mask=None, msa_mask=None, deterministic=True):
        """Exact inverse of ``__call__``: the updates run in reverse order with
        subtraction (reference backward_pass, reversible.py:85-156,184-262 —
        minus the autograd bookkeeping, which custom_vjp supplies)."""
        x1, x2, m1, m2 = h
        pm, mm, det = pair_mask, msa_mask, deterministic
        # cross block
        m2 = shard_msa(m2 - self._k_c(m1, det))
        m1 = shard_msa(m1 - self._j_c(m2, x2, pm, mm, det))
        x2 = shard_pair(x2 - self._g_c(x1, det))
        x1 = shard_pair(x1 - self._f_c(x2, m2, pm, mm, det))
        # self block
        m2 = shard_msa(m2 - self._k_s(m1, det))
        m1 = shard_msa(m1 - self._j_s(m2, mm, det))
        x2 = shard_pair(x2 - self._g_s(x1, det))
        x1 = shard_pair(x1 - self._f_s(x2, pm, det))
        return (x1, x2, m1, m2)


def _make_rev_scan(forward_one, backward_one):
    """Build the custom-vjp reversible scan.

    ``forward_one(p, h, pm, mm, key) -> h`` and
    ``backward_one(p, h_out, gh, pm, mm, key) -> (h_in, gh_in, gp)`` are
    static closures over the (unbound) layer module and static config only —
    masks and keys are explicit operands, as custom_vjp requires.
    """

    @jax.custom_vjp
    def rev_scan(params, h, pm, mm, keys):
        def body(carry, xs):
            p, key = xs
            return forward_one(p, carry, pm, mm, key), None

        h, _ = jax.lax.scan(body, h, (params, keys))
        return h

    def fwd(params, h, pm, mm, keys):
        out = rev_scan(params, h, pm, mm, keys)
        # residuals: only the FINAL state (reference reversible.py:277) —
        # this is the O(1)-in-depth activation memory property
        return out, (params, out, pm, mm, keys)

    def bwd(res, g):
        params, out, pm, mm, keys = res

        def body(carry, xs):
            h_out, gh = carry
            p, key = xs
            h_in, gh_in, gp = backward_one(p, h_out, gh, pm, mm, key)
            return (h_in, gh_in), gp

        (h0, gh0), gparams = jax.lax.scan(
            body, (out, g), (params, keys), reverse=True
        )
        del h0
        return (gparams, gh0, _float0_zeros(pm), _float0_zeros(mm),
                _float0_zeros(keys))

    rev_scan.defvjp(fwd, bwd)
    return rev_scan


class ReversibleTrunk(nn.Module):
    """Drop-in trunk engine with inversion-based backward.

    Requires the MSA stream (the reference asserts the same,
    reversible.py:316). ``use_custom_vjp=False`` runs the identical coupling
    under plain autodiff — the differential oracle for the custom backward.
    """

    dim: int
    depth: int = 6
    heads: int = 8
    dim_head: int = 64
    attn_dropout: float = 0.0
    ff_dropout: float = 0.0
    gelu_exact: bool = False  # erf GELU (the reference's torch F.gelu)
    sparse_attn: bool = False
    seq_len: Optional[int] = None
    sparse_config: Optional[object] = None
    sparse_use_pallas: Optional[bool] = None
    cross_attn_compress_ratio: int = 1
    msa_tie_row_attn: bool = False
    use_flash: Optional[bool] = None
    use_custom_vjp: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, m, pair_mask=None, msa_mask=None, deterministic=True):
        if m is None:
            raise ValueError(
                "ReversibleTrunk requires the MSA stream (reference "
                "reversible.py:316); use Trunk(remat=True) without one"
            )
        # The carried state must stay float32 even under bf16 compute:
        # inversion reconstructs x1 as (x1 + f) - f, and in bf16 that
        # roundoff compounds across the 8 updates x depth steps, silently
        # perturbing the inputs the backward vjp is evaluated at. With an
        # f32 carry, block outputs (bf16) promote on add and the
        # reconstruction error stays at f32 roundoff. Blocks still compute
        # in self.dtype (their LayerNorms cast on entry).
        x = x.astype(jnp.float32)
        m = m.astype(jnp.float32)
        template = RevLayerPair(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            attn_dropout=self.attn_dropout, ff_dropout=self.ff_dropout,
            gelu_exact=self.gelu_exact,
            sparse_attn=self.sparse_attn, seq_len=self.seq_len,
            sparse_config=self.sparse_config,
            sparse_use_pallas=self.sparse_use_pallas,
            cross_attn_compress_ratio=self.cross_attn_compress_ratio,
            msa_tie_row_attn=self.msa_tie_row_attn, use_flash=self.use_flash,
            dtype=self.dtype,
        )
        h0 = (x, x, m, m)

        def init_stack(rng):
            def init_one(k):
                return template.init(
                    k, h0, pair_mask, msa_mask, True
                )["params"]

            return jax.vmap(init_one)(jax.random.split(rng, self.depth))

        params = self.param("layers", init_stack)

        has_dropout = (self.attn_dropout > 0 or self.ff_dropout > 0) and (
            not deterministic
        )
        key = self.make_rng("dropout") if has_dropout else jax.random.key(0)
        keys = jax.random.key_data(jax.random.split(key, self.depth))

        has_pm = pair_mask is not None
        has_mm = msa_mask is not None
        det = deterministic
        # placeholders keep the operand list static; the closures below bake
        # in the None-ness so the placeholders are never read
        pm_arr = pair_mask if has_pm else jnp.zeros((1,), bool)
        mm_arr = msa_mask if has_mm else jnp.zeros((1,), bool)

        def forward_one(p, h, pm, mm, key_data):
            return template.apply(
                {"params": p}, h,
                pm if has_pm else None,
                mm if has_mm else None,
                det,
                rngs={"dropout": jax.random.wrap_key_data(key_data)},
            )

        def backward_one(p, h, gh, pm, mm, key_data):
            """One layer of the reverse schedule: walk the 8 additive updates
            backwards; each sub-function is evaluated ONCE under jax.vjp and
            its output reused for both the inversion subtraction and the
            cotangent pull (the reference's backward_pass schedule,
            reversible.py:85-156 — one extra evaluation per sub-function,
            not a full forward re-run)."""
            pmq = pm if has_pm else None
            mmq = mm if has_mm else None
            rngs = {"dropout": jax.random.wrap_key_data(key_data)}

            def vjp(method, *args):
                def f(p_, *a):
                    return template.apply(
                        {"params": p_}, *a, rngs=rngs, method=method
                    )

                return jax.vjp(f, p, *args)

            x1, x2, m1, m2 = h
            gx1, gx2, gm1, gm2 = gh
            add = lambda a, b: jax.tree.map(jnp.add, a, b)

            # 8. m2 += k_c(m1)
            out, pull = vjp(lambda s, a: s._k_c(a, det), m1)
            m2 = m2 - out
            gp, gi = pull(gm2.astype(out.dtype))
            gm1 = gm1 + gi
            # 7. m1 += j_c(m2, x2)
            out, pull = vjp(lambda s, a, b: s._j_c(a, b, pmq, mmq, det), m2, x2)
            m1 = m1 - out
            gp_i, gi_m2, gi_x2 = pull(gm1.astype(out.dtype))
            gp, gm2, gx2 = add(gp, gp_i), gm2 + gi_m2, gx2 + gi_x2
            # 6. x2 += g_c(x1)
            out, pull = vjp(lambda s, a: s._g_c(a, det), x1)
            x2 = x2 - out
            gp_i, gi = pull(gx2.astype(out.dtype))
            gp, gx1 = add(gp, gp_i), gx1 + gi
            # 5. x1 += f_c(x2, m2)
            out, pull = vjp(lambda s, a, b: s._f_c(a, b, pmq, mmq, det), x2, m2)
            x1 = x1 - out
            gp_i, gi_x2, gi_m2 = pull(gx1.astype(out.dtype))
            gp, gx2, gm2 = add(gp, gp_i), gx2 + gi_x2, gm2 + gi_m2
            # 4. m2 += k_s(m1)
            out, pull = vjp(lambda s, a: s._k_s(a, det), m1)
            m2 = m2 - out
            gp_i, gi = pull(gm2.astype(out.dtype))
            gp, gm1 = add(gp, gp_i), gm1 + gi
            # 3. m1 += j_s(m2)
            out, pull = vjp(lambda s, a: s._j_s(a, mmq, det), m2)
            m1 = m1 - out
            gp_i, gi = pull(gm1.astype(out.dtype))
            gp, gm2 = add(gp, gp_i), gm2 + gi
            # 2. x2 += g_s(x1)
            out, pull = vjp(lambda s, a: s._g_s(a, det), x1)
            x2 = x2 - out
            gp_i, gi = pull(gx2.astype(out.dtype))
            gp, gx1 = add(gp, gp_i), gx1 + gi
            # 1. x1 += f_s(x2)
            out, pull = vjp(lambda s, a: s._f_s(a, pmq, det), x2)
            x1 = x1 - out
            gp_i, gi = pull(gx1.astype(out.dtype))
            gp, gx2 = add(gp, gp_i), gx2 + gi

            return (x1, x2, m1, m2), (gx1, gx2, gm1, gm2), gp

        if self.use_custom_vjp:
            h = _make_rev_scan(forward_one, backward_one)(
                params, h0, pm_arr, mm_arr, keys
            )
        else:

            def body(carry, xs):
                p, key_data = xs
                return forward_one(p, carry, pm_arr, mm_arr, key_data), None

            h, _ = jax.lax.scan(body, h0, (params, keys))

        x1, x2, m1, m2 = h
        # average the duplicated halves back out (reference reversible.py:327)
        return 0.5 * (x1 + x2), 0.5 * (m1 + m2)
