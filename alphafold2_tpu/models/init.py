"""Torch-matched parameter re-initialization (init-distribution A/B).

The reference model never customizes initialization — every layer uses the
torch module defaults (`/root/reference/alphafold2_pytorch/alphafold2.py:354-361`
constructs plain ``nn.Embedding``/``nn.Linear``/``nn.LayerNorm``;
`/root/reference/train_pre.py:52-57` trains them as-is):

- ``nn.Linear``: weight = kaiming_uniform(a=sqrt(5)) which reduces to
  U(-1/sqrt(fan_in), +1/sqrt(fan_in)); bias = U(-1/sqrt(fan_in), ...)
  (torch ``Linear.reset_parameters``)
- ``nn.Conv1d``: same rule with fan_in = in_channels/groups * kernel_size
- ``nn.Embedding``: N(0, 1)
- ``nn.LayerNorm``: ones/zeros

Flax defaults differ materially: Dense kernels are lecun-normal
(std 1/sqrt(fan_in), vs torch's uniform with std 1/sqrt(3*fan_in)), biases
are zeros (vs torch's uniform), and ``nn.Embed`` draws N(0, 1/features) —
at dim 256 the reference's token embeddings are 16x larger in scale.
VERDICT r3 named this distribution mismatch the prime suspect for the
flagship-width in-distribution quality gap; re-drawing an initialized tree
under the torch rules isolates init alone while keeping data, optimizer,
and architecture bit-identical.

Scope note: ``scan_layers=True`` and the reversible engine both stack a
leading depth axis onto their trunk kernels (lax.scan params /
ReversibleTrunk's vmap-initialized ``layers``), which would corrupt the
fan_in computation here. Stackedness cannot be inferred from shapes alone,
so those configs are rejected at the callers: ``train.loop.init_state``
and ``scripts/baseline_jax.py`` raise before any init work.
"""

from __future__ import annotations

import math
import zlib

import jax
import jax.numpy as jnp
import numpy as np


def _path_key(rng, path: tuple) -> jax.Array:
    # crc32 is stable across processes (unlike str hash under hash
    # randomization): same tree + same rng => bit-identical params
    return jax.random.fold_in(rng, zlib.crc32("/".join(path).encode()))


def torch_match_reinit(params, rng: jax.Array):
    """Re-draw every parameter of an initialized tree per torch defaults.

    Walks the nested param dict; any module dict holding a ``kernel``
    (Dense / DenseGeneral / Conv) gets the kaiming-uniform(a=sqrt(5)) rule
    on kernel AND bias with fan_in = prod(kernel.shape[:-1]); ``embedding``
    leaves become N(0,1); LayerNorm (``scale``) modules keep flax's
    ones/zeros, which already equal torch's. Leaf dtypes are preserved.
    Deterministic in (params, rng).
    """

    def rec(tree, path):
        # flax puts a module's own params and its child-module dicts in ONE
        # mapping — after handling this level's params, always recurse into
        # the remaining (dict-valued) siblings so children of a
        # param-holding scope are never silently left at flax init
        if not isinstance(tree, dict):
            return tree
        if "kernel" in tree:
            k = tree["kernel"]
            fan_in = int(np.prod(k.shape[:-1]))
            bound = 1.0 / math.sqrt(fan_in)
            kk, kb = jax.random.split(_path_key(rng, path))
            out = dict(tree)
            out["kernel"] = jax.random.uniform(
                kk, k.shape, k.dtype, -bound, bound
            )
            if "bias" in tree:
                b = tree["bias"]
                out["bias"] = jax.random.uniform(
                    kb, b.shape, b.dtype, -bound, bound
                )
            for key, v in tree.items():
                if key not in ("kernel", "bias"):
                    out[key] = rec(v, path + (key,))
            return out
        if "embedding" in tree:
            out = dict(tree)
            out["embedding"] = jax.random.normal(
                _path_key(rng, path), tree["embedding"].shape,
                tree["embedding"].dtype,
            )
            for key, v in tree.items():
                if key != "embedding":
                    out[key] = rec(v, path + (key,))
            return out
        if "scale" in tree:
            # LayerNorm: flax ones/zeros == torch ones/zeros — keep the
            # params, still visit any sibling children
            return {
                key: (v if key in ("scale", "bias") else rec(v, path + (key,)))
                for key, v in tree.items()
            }
        return {k: rec(v, path + (k,)) for k, v in tree.items()}

    return rec(params, ())
