"""The Alphafold2 model: embeddings, template attention, trunk, distogram head.

TPU-native re-design of reference ``alphafold2_pytorch/alphafold2.py:329-610``
(class ``Alphafold2``). Capability parity:

- token + axial positional embeddings, outer-sum pair construction (:354-356,
  :463-479)
- MSA stream with per-position and per-row embeddings (:360-361, :485-491)
- ESM/PLM embedding input path (``embedds``) (:388, :493-496) — *fixed*: the
  reference leaves ``msa_shape=None`` and crashes (SURVEY.md S2.5); here the
  projected embedding outer-sum simply becomes an (N, N) MSA grid
- template embedding + TimeSformer-style template-axis attention (:503-589),
  optional SE(3)-equivariant sidechain coloring (:519-537, models/se3.py)
- trunk dispatch with remat instead of hand-written reversibility (:427-431)
- symmetrized distogram head (:435-438, :606-610)

Deliberate divergences (capabilities, not bugs — SURVEY.md S2.5):
- pair mask combines with AND (the reference uses OR at :468 but AND for
  templates at :560; AND is the correct semantics)
- the ``embedds`` path works (broken upstream)
- no vestigial ``pos_token`` arg / crashing ``(seq, seq_pos)`` tuple path;
  positions are always ``arange`` (the tuple path crashes upstream :453-459)

Streams are grids end-to-end: pair (B, N, N, D), MSA (B, M, Nm, D) — the
N^2-flatten of the reference exists only transiently inside cross-attention.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from alphafold2_tpu import constants
from alphafold2_tpu.models.trunk import Trunk
from alphafold2_tpu.observe.numerics import tag
from alphafold2_tpu.ops.attention import Attention, AxialAttention, FeedForward
from alphafold2_tpu.parallel.sharding import shard_msa, shard_pair
from alphafold2_tpu.utils.structure import get_bucketed_distance_matrix


class TemplateBlock(nn.Module):
    """One template-attention layer: pair self-attn (no residual, matching
    reference :568), template self-attn, attention along the template axis
    (each pair position attends over [pair token, template_1..T tokens] —
    TimeSformer-style, reference :574-587), template FF."""

    dim: int
    heads: int
    dim_head: int
    dropout: float = 0.0
    gelu_exact: bool = False
    use_flash: Optional[bool] = None
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, t, pair_mask, t_mask, deterministic: bool = True):
        # x: (B, N, N, D); t: (B, T, N, N, D)
        b, n, _, d = x.shape
        T = t.shape[1]
        ln = lambda name: nn.LayerNorm(dtype=self.dtype, name=name)

        x = AxialAttention(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            dropout=self.dropout, use_flash=self.use_flash, dtype=self.dtype,
            name="pair_axial",
        )(ln("pair_norm")(x), mask=pair_mask, deterministic=deterministic)

        t_flat = t.reshape(b * T, n, n, d)
        tm_flat = t_mask.reshape(b * T, n, n) if t_mask is not None else None
        t_flat = t_flat + AxialAttention(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            dropout=self.dropout, use_flash=self.use_flash, dtype=self.dtype,
            name="template_axial",
        )(ln("template_norm")(t_flat), mask=tm_flat, deterministic=deterministic)
        t = t_flat.reshape(b, T, n, n, d)

        # template-axis attention: tokens = [pair_ij, t^1_ij, ..., t^T_ij]
        y = jnp.concatenate([x[:, None], t], axis=1)  # (B, 1+T, N, N, D)
        y = jnp.moveaxis(y, 1, 3).reshape(b * n * n, 1 + T, d)
        y_mask = None
        if t_mask is not None and pair_mask is not None:
            ym = jnp.concatenate([pair_mask[:, None], t_mask], axis=1)
            y_mask = jnp.moveaxis(ym, 1, 3).reshape(b * n * n, 1 + T)
        y = y + Attention(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            dropout=self.dropout, use_flash=self.use_flash, dtype=self.dtype,
            name="template_axis_attn",
        )(ln("template_axis_norm")(y), mask=y_mask, deterministic=deterministic)
        y = jnp.moveaxis(y.reshape(b, n, n, 1 + T, d), 3, 1)
        x, t = y[:, 0], y[:, 1:]

        t = t + FeedForward(
            dim=self.dim, dropout=self.dropout, gelu_exact=self.gelu_exact,
            dtype=self.dtype, name="template_ff"
        )(ln("template_ff_norm")(t), deterministic=deterministic)
        return x, t


class Alphafold2(nn.Module):
    """Distogram-predicting trunk over a pair grid cross-attending an MSA.

    Ctor parity with reference alphafold2.py:330-350. Two O(1)-activation
    engines: ``remat`` (XLA rematerialization — recompute in backward) and
    ``reversible`` (inversion-based coupling, models/reversible.py — the
    direct equivalent of the reference's reversible trunk).
    """

    dim: int
    max_seq_len: int = 2048
    depth: int = 6
    heads: int = 8
    dim_head: int = 64
    num_tokens: int = constants.NUM_AMINO_ACIDS
    num_embedds: int = constants.NUM_EMBEDDS_TR
    max_num_msas: int = constants.MAX_NUM_MSA
    max_num_templates: int = constants.MAX_NUM_TEMPLATES
    attn_dropout: float = 0.0
    ff_dropout: float = 0.0
    gelu_exact: bool = False  # erf GELU (the reference's torch F.gelu)
    remat: bool = False
    remat_policy: Optional[str] = None  # None/"nothing" | "dots" | "dots_no_batch"
    reversible: bool = False  # true inversion-based reversible trunk engine
    sparse_self_attn: tuple | bool = False
    sparse_config: Optional[object] = None  # ops.sparse.BlockSparseConfig
    sparse_use_pallas: Optional[bool] = None  # None -> Pallas kernel on TPU
    cross_attn_compress_ratio: int = 1
    msa_tie_row_attn: bool = False
    msa_row_shard: bool = False  # shard MSA rows over sp (tied-row psum)
    context_parallel: Optional[str] = None  # None | "ring" | "ulysses"
    use_flash: Optional[bool] = None  # fused dense attention kernel on TPU
    grid_parallel: bool = False  # 2D-sharded pair axial passes (spr x spc mesh)
    scan_layers: bool = False  # roll the trunk depth loop into lax.scan
    template_attn_depth: int = 2
    use_se3_template_embedder: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        seq: jnp.ndarray,  # (B, N) int tokens
        msa: Optional[jnp.ndarray] = None,  # (B, M, Nm) int tokens
        mask: Optional[jnp.ndarray] = None,  # (B, N) bool
        msa_mask: Optional[jnp.ndarray] = None,  # (B, M, Nm) bool
        templates_seq: Optional[jnp.ndarray] = None,  # (B, T, N) int
        templates_dist: Optional[jnp.ndarray] = None,  # (B, T, N, N) int buckets
        templates_mask: Optional[jnp.ndarray] = None,  # (B, T, N) bool
        templates_coors: Optional[jnp.ndarray] = None,  # (B, T, N, 3)
        templates_sidechains: Optional[jnp.ndarray] = None,  # (B, T, N, 3)
        embedds: Optional[jnp.ndarray] = None,  # (B, N, num_embedds) PLM path
        deterministic: bool = True,
    ) -> jnp.ndarray:
        b, n = seq.shape
        dt = self.dtype
        # Loud trace-time guards: the positional tables are fixed-size, and
        # out-of-range gathers clip silently — observed as NaN logits /
        # aliased positions rather than an actionable error. Shapes are
        # static under jit, so plain Python raises work here. Driver-level
        # remediation hints live with the drivers (train/end2end.py,
        # predict.py).
        if n > self.max_seq_len:
            raise ValueError(
                f"sequence length {n} exceeds max_seq_len {self.max_seq_len}"
            )
        if msa is not None:
            if msa.shape[-1] > self.max_seq_len:
                raise ValueError(
                    f"MSA length {msa.shape[-1]} exceeds max_seq_len "
                    f"{self.max_seq_len}"
                )
            if msa.shape[1] > self.max_num_msas:
                raise ValueError(
                    f"MSA depth {msa.shape[1]} exceeds max_num_msas "
                    f"{self.max_num_msas} (reference MAX_NUM_MSA)"
                )
        if templates_seq is not None and (
            templates_seq.shape[1] > self.max_num_templates
        ):
            raise ValueError(
                f"{templates_seq.shape[1]} templates exceed "
                f"max_num_templates {self.max_num_templates} "
                "(reference MAX_NUM_TEMPLATES)"
            )

        token_emb = nn.Embed(self.num_tokens, self.dim, dtype=dt, name="token_emb")
        pos_emb = nn.Embed(self.max_seq_len, self.dim, dtype=dt, name="pos_emb")
        pos_emb_ax = nn.Embed(self.max_seq_len, self.dim, dtype=dt, name="pos_emb_ax")

        n_range = jnp.arange(n)

        # pair representation: outer sum of residue embeddings + axial pos emb
        e = token_emb(seq)  # (B, N, D)
        x = e[:, :, None, :] + e[:, None, :, :]
        x = x + pos_emb(n_range)[None, :, None, :] + pos_emb_ax(n_range)[None, None, :, :]
        x = tag("embed.pair", shard_pair(x))

        pair_mask = None
        if mask is not None:
            pair_mask = mask[:, :, None] & mask[:, None, :]

        # MSA stream
        m = None
        m_mask = None
        if msa is not None:
            nm = msa.shape[-1]
            m = token_emb(msa)
            m = m + nn.Embed(
                self.max_seq_len, self.dim, dtype=dt, name="msa_pos_emb"
            )(jnp.arange(nm))[None, None]
            m = m + nn.Embed(
                self.max_num_msas, self.dim, dtype=dt, name="msa_num_pos_emb"
            )(jnp.arange(msa.shape[1]))[None, :, None]
            m_mask = msa_mask
        elif embedds is not None:
            # PLM residue embeddings -> pairwise grid standing in for the MSA
            pe = nn.Dense(self.dim, dtype=dt, name="embedd_project")(
                embedds.astype(dt)
            )
            m = pe[:, :, None, :] + pe[:, None, :, :]  # (B, N, N, D)
            if mask is not None:
                m_mask = mask[:, :, None] & mask[:, None, :]
        if m is not None:
            m = tag("embed.msa", shard_msa(m, rows=self.msa_row_shard))

        # template stream
        if templates_seq is not None:
            if templates_coors is None:
                raise ValueError(
                    "template residue coordinates must be supplied "
                    "via `templates_coors`"
                )
            T = templates_seq.shape[1]
            if templates_dist is None:
                templates_dist = get_bucketed_distance_matrix(
                    templates_coors, templates_mask, constants.DISTOGRAM_BUCKETS
                )
                templates_dist = jnp.maximum(templates_dist, 0)  # ignore -> bucket 0

            t_seq = token_emb(templates_seq)  # (B, T, N, D)

            if templates_sidechains is not None and self.use_se3_template_embedder:
                from alphafold2_tpu.models.se3 import SE3TemplateEmbedder

                t_seq = SE3TemplateEmbedder(
                    dim=self.dim, dtype=dt, name="template_sidechain_emb"
                )(
                    t_seq.reshape(b * T, n, self.dim),
                    templates_sidechains.reshape(b * T, n, 3),
                    templates_coors.reshape(b * T, n, 3),
                    mask=templates_mask.reshape(b * T, n)
                    if templates_mask is not None
                    else None,
                ).reshape(b, T, n, self.dim)

            t_dist = nn.Embed(
                constants.DISTOGRAM_BUCKETS, self.dim, dtype=dt, name="template_dist_emb"
            )(templates_dist)  # (B, T, N, N, D)
            t = t_seq[:, :, :, None, :] + t_seq[:, :, None, :, :] + t_dist
            t = t + nn.Embed(
                self.max_num_templates, self.dim, dtype=dt, name="template_num_pos_emb"
            )(jnp.arange(T))[None, :, None, None]
            t = (
                t
                + nn.Embed(self.max_seq_len, self.dim, dtype=dt, name="template_pos_emb")(
                    n_range
                )[None, None, :, None]
                + nn.Embed(
                    self.max_seq_len, self.dim, dtype=dt, name="template_pos_emb_ax"
                )(n_range)[None, None, None, :]
            )

            t_mask = None
            if templates_mask is not None:
                t_mask = templates_mask[..., :, None] & templates_mask[..., None, :]

            for i in range(self.template_attn_depth):
                x, t = TemplateBlock(
                    dim=self.dim, heads=self.heads, dim_head=self.dim_head,
                    dropout=self.attn_dropout, gelu_exact=self.gelu_exact,
                    use_flash=self.use_flash,
                    dtype=dt, name=f"template_block_{i}",
                )(x, t, pair_mask, t_mask, deterministic=deterministic)
            x = shard_pair(x)

        # trunk
        x, m = Trunk(
            dim=self.dim,
            depth=self.depth,
            heads=self.heads,
            dim_head=self.dim_head,
            attn_dropout=self.attn_dropout,
            ff_dropout=self.ff_dropout,
            gelu_exact=self.gelu_exact,
            sparse_self_attn=self.sparse_self_attn,
            seq_len=self.max_seq_len,
            sparse_config=self.sparse_config,
            sparse_use_pallas=self.sparse_use_pallas,
            cross_attn_compress_ratio=self.cross_attn_compress_ratio,
            msa_tie_row_attn=self.msa_tie_row_attn,
            msa_row_shard=self.msa_row_shard,
            context_parallel=self.context_parallel,
            use_flash=self.use_flash,
            grid_parallel=self.grid_parallel,
            remat=self.remat,
            remat_policy=self.remat_policy,
            reversible=self.reversible,
            scan_layers=self.scan_layers,
            dtype=dt,
            name="trunk",
        )(x, m, pair_mask=pair_mask, msa_mask=m_mask, deterministic=deterministic)

        # distogram head: symmetrize, norm, project
        x = 0.5 * (x + jnp.swapaxes(x, 1, 2))
        x = nn.LayerNorm(dtype=dt, name="distogram_norm")(x)
        logits = nn.Dense(constants.DISTOGRAM_BUCKETS, dtype=dt, name="distogram_proj")(x)
        return tag("distogram.logits", logits.astype(jnp.float32))
