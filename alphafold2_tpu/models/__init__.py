from alphafold2_tpu.models.alphafold2 import Alphafold2, TemplateBlock
from alphafold2_tpu.models.trunk import Trunk, TrunkLayer
from alphafold2_tpu.models.se3 import SE3Refiner, SE3TemplateEmbedder, SE3Transformer
