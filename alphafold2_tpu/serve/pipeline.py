"""Double-buffered host/device dispatch pipeline for ServeEngine.

The serial dispatch path runs featurize -> device_put -> compute ->
device_get -> unpad in one thread, so the device idles during every host
phase and the host idles while the device computes. This module overlaps
them: three single-worker stages connected as a pipeline, with at most
``serve.pipeline_depth`` batches in flight,

    host stage    featurize + stack + device_put of batch N+1
    device stage  executable lookup + dispatch of batch N (async on CPU/TPU:
                  the call returns while XLA executes in the background)
    fetch stage   ONE blocking device_get of batch N-1's whole output tree,
                  then unpad/realize + future resolution (completion)

``submit`` returns a :class:`DispatchHandle` future immediately; the
caller blocks only in ``result()``. Each stage worker is a one-thread
``concurrent.futures.ThreadPoolExecutor`` so per-stage ordering is the
submission order (batch N's compute is always enqueued before batch
N+1's) while different stages run concurrently on different batches.

While a batch sits in the host stage its formation is still *open*: the
scheduler's in-flight admission joins late-arriving requests into it via
:meth:`PipelineBatch.try_join` until the featurize loop drains and seals
the membership (continuous batching — the real admission window is the
host stage's duration, not a dwell timer).

Failure routing: an exception in any stage (including injected
``serve.faults`` stage faults) is carried on the job to the completion
stage, which converts it into structured per-request error results and
resolves the future — the completion worker can never wedge on a
poisoned batch, and the in-flight slot is always released.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional


class PipelineBatch:
    """One batch's membership while it forms in the host stage.

    ``try_join`` admits a request while the formation is open (the host
    worker has not drained the member list) and below ``fill``; the host
    worker pulls members one at a time via :meth:`next_member`, which
    seals the formation the first time it finds nothing left to
    featurize. Thread-safe; joiners and the host worker race only on the
    member list, never on featurized data.
    """

    def __init__(self, bucket: int, requests: list, fill: int):
        self.bucket = int(bucket)
        self.fill = max(len(requests), int(fill), 1)
        self._lock = threading.Lock()
        self._members = list(requests)
        self._sealed = False

    def try_join(self, req) -> bool:
        """Admit ``req`` into this in-flight batch; False once sealed/full."""
        with self._lock:
            if self._sealed or len(self._members) >= self.fill:
                return False
            self._members.append(req)
            return True

    def next_member(self, i: int):
        """Member ``i`` if admitted, else seal the formation and return
        None — called only by the host worker, with ``i`` = number of
        members it has already featurized."""
        with self._lock:
            if i < len(self._members):
                return self._members[i]
            self._sealed = True
            return None

    def seal(self) -> None:
        with self._lock:
            self._sealed = True

    @property
    def sealed(self) -> bool:
        with self._lock:
            return self._sealed

    @property
    def members(self) -> list:
        with self._lock:
            return list(self._members)


class DispatchHandle:
    """Future over one pipelined batch's ordered ServeResult list."""

    def __init__(self, batch: PipelineBatch):
        self.batch = batch
        self._done = threading.Event()
        self._cb_lock = threading.Lock()
        self._results: Optional[list] = None
        self._callbacks: list = []

    def try_join(self, req) -> bool:
        """Admit ``req`` into the batch while its host stage still runs."""
        return self.batch.try_join(req)

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> list:
        """Block until the batch completes; returns one ServeResult per
        member in admission order (initial requests, then joiners)."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"pipelined dispatch (bucket {self.batch.bucket}) did not "
                f"complete within {timeout}s"
            )
        return self._results

    def add_done_callback(self, fn) -> None:
        """Run ``fn(results)`` on completion — immediately (caller thread)
        if already resolved, else on the completion worker."""
        with self._cb_lock:
            if self._results is None:
                self._callbacks.append(fn)
                return
        fn(self._results)

    def _resolve(self, results: list) -> None:
        with self._cb_lock:
            self._results = results
            callbacks = list(self._callbacks)
            self._callbacks.clear()
        # callbacks run BEFORE the done event: ``result()`` returning means
        # the batch is fully settled — the scheduler's completion callback
        # (retry, cache fulfil, sched.resolve trace terminals) has finished,
        # so a caller may close the frontend/tracer the moment it unblocks
        for fn in callbacks:
            try:
                fn(results)
            except Exception:
                pass  # a broken observer must not wedge the completion worker
        self._done.set()


class _Job:
    """Mutable per-batch state riding through the three stages."""

    __slots__ = (
        "bucket", "index", "arrival", "batch", "handle", "members",
        "n_real", "batch_size", "stacked", "compiled", "out", "fetched",
        "error", "t_host0", "t_device0", "feat",
    )

    def __init__(self, bucket: int, index: int, arrival, batch, handle):
        self.bucket = bucket
        self.index = index  # global 1-based dispatch index (serve.batches)
        self.arrival = arrival  # stream-level queue-wait origin (fallback)
        self.batch = batch
        self.handle = handle
        self.members: list = []
        self.n_real = 0
        self.batch_size = 0
        self.stacked = None
        self.compiled = None
        self.out = None
        self.fetched = None
        self.error: Optional[BaseException] = None
        self.t_host0: Optional[float] = None
        self.t_device0: Optional[float] = None
        self.feat: Optional[list] = None  # per-member featurize-reuse ledger


class PipelinedDispatcher:
    """The pipeline over one :class:`~alphafold2_tpu.serve.engine.
    ServeEngine`: owns the three stage workers and the in-flight bound.

    ``depth`` batches may be in flight at once (2 = classic double
    buffering: the host featurizes N+1 while the device computes N);
    ``submit`` blocks once the bound is reached, which is the pipeline's
    backpressure toward the caller.
    """

    def __init__(self, engine, depth: int = 2):
        self.engine = engine
        self.depth = max(1, int(depth))
        self._slots = threading.BoundedSemaphore(self.depth)
        self._host = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="af2-pipe-host"
        )
        self._device = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="af2-pipe-device"
        )
        self._fetch = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="af2-pipe-fetch"
        )

    def submit(
        self, bucket: int, requests: list, arrival=None, joinable: bool = False
    ) -> DispatchHandle:
        """Enqueue one batch; returns its future. ``joinable`` keeps the
        formation open to ``try_join`` up to the engine's batch target
        while the host stage runs (the scheduler's in-flight admission);
        a pre-formed batch (predict_many chunks) stays closed."""
        eng = self.engine
        fill = eng.batch_for(bucket) if joinable else len(requests)
        batch = PipelineBatch(bucket, list(requests), fill=fill)
        handle = DispatchHandle(batch)
        self._slots.acquire()  # backpressure: <= depth batches in flight
        index = eng.counters.bump("serve.batches")
        job = _Job(bucket, index, arrival, batch, handle)
        self._host.submit(self._host_stage, job)
        return handle

    # ----------------------------------------------------------- the stages

    def _host_stage(self, job: _Job) -> None:
        eng = self.engine
        try:
            job.t_host0 = time.perf_counter()
            if eng.faults is not None:
                # legacy top-of-dispatch injection point (fail_stage=None
                # plans); staged plans fire from the stage helpers below
                eng.faults.on_dispatch(job.index, job.bucket)
            with eng.tracer.span(
                "serve.featurize", bucket=job.bucket,
                dispatch_index=job.index,
            ):
                items: list = []
                job.feat = []
                while True:  # drain members; joiners may land mid-loop
                    req = job.batch.next_member(len(items))
                    if req is None:
                        break  # nothing left unfeaturized: formation sealed
                    item, reuse = eng._featurize_one(job.bucket, req)
                    items.append(item)
                    job.feat.append(reuse)
            job.members = job.batch.members
            job.n_real = len(job.members)
            job.batch_size = eng._padded_batch(job.bucket, job.n_real)
            eng.counters.bump(
                "serve.padded_slots", job.batch_size - job.n_real
            )
            with eng.tracer.span(
                "serve.device_put", bucket=job.bucket,
                dispatch_index=job.index,
            ):
                host = eng._stack_host(job.bucket, items, job.batch_size)
                job.stacked = eng._transfer(host, job.index, job.bucket)
        except BaseException as e:  # carried to completion, never raised
            job.batch.seal()
            job.members = job.batch.members
            job.error = e
        self._device.submit(self._device_stage, job)

    def _device_stage(self, job: _Job) -> None:
        eng = self.engine
        try:
            if job.error is None:
                with eng.tracer.span(
                    "serve.get_executable", bucket=job.bucket,
                    batch=job.batch_size,
                ) as exe_span:
                    before = eng.counters.get("serve.compiles")
                    job.compiled = eng._get_executable(
                        job.bucket, job.batch_size
                    )
                    exe_span.set(
                        compiled_now=eng.counters.get("serve.compiles")
                        > before
                    )
                job.t_device0 = time.perf_counter()
                with eng.tracer.span(
                    "serve.dispatch", bucket=job.bucket,
                    dispatch_index=job.index,
                    **({"mesh": eng.mesh_desc} if eng.mesh_desc else {}),
                ):
                    # async dispatch: returns as soon as XLA enqueues the
                    # execution; the fetch stage's device_get rides the tail
                    job.out = eng._execute_batch(
                        job.compiled, job.stacked, job.index, job.bucket
                    )
                job.stacked = None  # let donated input buffers release
        except BaseException as e:
            job.error = e
        self._fetch.submit(self._fetch_stage, job)

    def _fetch_stage(self, job: _Job) -> None:
        eng = self.engine
        try:
            if job.error is None:
                with eng.tracer.span(
                    "serve.device_get", bucket=job.bucket,
                    dispatch_index=job.index,
                ):
                    job.fetched = eng._fetch(job.out, job.index, job.bucket)
                job.out = None
        except BaseException as e:
            job.error = e
        try:
            results = eng._complete_pipelined(job)
        except BaseException as e:  # completion itself must never wedge
            job.error = e
            results = eng._completion_fallback(job)
        finally:
            self._slots.release()
        job.handle._resolve(results)

    def shutdown(self, wait: bool = True) -> None:
        """Stop the stage workers (in-flight batches finish when ``wait``)."""
        self._host.shutdown(wait=wait)
        self._device.shutdown(wait=wait)
        self._fetch.shutdown(wait=wait)
