"""Serving layer: shape-bucketed, batched inference with compile accounting.

See :mod:`alphafold2_tpu.serve.engine` (the synchronous batched engine),
:mod:`alphafold2_tpu.serve.bucketing` (the ladder math),
:mod:`alphafold2_tpu.serve.scheduler` (the async open-loop frontend:
admission control, deadlines, continuous batch formation),
:mod:`alphafold2_tpu.serve.cache` (LRU result cache + in-flight dedup),
:mod:`alphafold2_tpu.serve.faults` (deterministic fault injection),
:mod:`alphafold2_tpu.serve.pipeline` (double-buffered host/device dispatch
pipeline with in-flight batch admission) and
:mod:`alphafold2_tpu.serve.fleet` (the multi-replica fleet frontend:
health-aware routing, work stealing, replica-death draining).
Configured by ``config.ServeConfig``; benched by ``bench.py --mode serve``
(closed loop), ``--mode serve-async`` (open loop, Poisson arrivals) and
``--mode serve-fleet`` (N replicas behind one router).
"""

from alphafold2_tpu.serve.bucketing import (
    FamilyTracker,
    affinity_take,
    bucket_for,
    formation_ripe,
    geometric_ladder,
    padding_fraction,
    point_mutation,
    validate_ladder,
)
from alphafold2_tpu.serve.cache import (
    FeatureCache,
    ResultCache,
    feature_fingerprint,
    feature_key,
    result_key,
)
from alphafold2_tpu.serve.engine import ServeEngine, ServeRequest, ServeResult
from alphafold2_tpu.serve.faults import (
    FaultPlan,
    FleetFaultPlan,
    InjectedFault,
)
from alphafold2_tpu.serve.fleet import FleetFrontend, ReplicaCell
from alphafold2_tpu.serve.pipeline import (
    DispatchHandle,
    PipelineBatch,
    PipelinedDispatcher,
)
from alphafold2_tpu.serve.scheduler import AsyncServeFrontend, PendingResult

__all__ = [
    "AsyncServeFrontend",
    "DispatchHandle",
    "FamilyTracker",
    "FaultPlan",
    "FeatureCache",
    "FleetFaultPlan",
    "FleetFrontend",
    "InjectedFault",
    "PendingResult",
    "ReplicaCell",
    "PipelineBatch",
    "PipelinedDispatcher",
    "ResultCache",
    "ServeEngine",
    "ServeRequest",
    "ServeResult",
    "affinity_take",
    "bucket_for",
    "feature_fingerprint",
    "feature_key",
    "formation_ripe",
    "geometric_ladder",
    "padding_fraction",
    "point_mutation",
    "result_key",
    "validate_ladder",
]
