"""Serving layer: shape-bucketed, batched inference with compile accounting.

See :mod:`alphafold2_tpu.serve.engine` (the engine) and
:mod:`alphafold2_tpu.serve.bucketing` (the ladder math). Configured by
``config.ServeConfig``; benched by ``bench.py --mode serve``.
"""

from alphafold2_tpu.serve.bucketing import (
    bucket_for,
    geometric_ladder,
    padding_fraction,
    validate_ladder,
)
from alphafold2_tpu.serve.engine import ServeEngine, ServeRequest, ServeResult

__all__ = [
    "ServeEngine",
    "ServeRequest",
    "ServeResult",
    "bucket_for",
    "geometric_ladder",
    "padding_fraction",
    "validate_ladder",
]
