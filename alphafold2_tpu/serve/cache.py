"""Sequence-keyed LRU result cache with in-flight request dedup.

Identical requests are common in production serving (the same viral
sequence submitted by thousands of users), and the engine's outputs are
deterministic in ``(seq, seed)`` whatever bucket or batch slot the request
lands in (pinned by the serve parity tests) — so recomputing them is pure
waste. Two layers remove it:

- **LRU cache** — completed results keyed by ``(seq, seed)``; a hit
  returns the stored :class:`~alphafold2_tpu.serve.engine.ServeResult`
  (same arrays — byte-identical to the dispatch that produced it).
- **In-flight dedup** — a request whose key is already queued or on the
  device *joins* the in-flight entry as a follower instead of dispatching
  again; when the leader's dispatch completes, every follower is resolved
  with the same result. Dedup works even with the LRU disabled
  (``capacity=0``): concurrent identical requests still share one
  dispatch, they just aren't remembered afterwards.

The cache stores and returns results; it never stamps latencies or bumps
counters — the scheduler owns per-request accounting. Pure stdlib.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Tuple


def result_key(seq: str, seed: int, mesh_desc: Optional[str] = None) -> tuple:
    """The canonical result-cache / in-flight-dedup key. Outputs are
    deterministic in ``(seq, seed)`` on a FIXED execution layout, but a
    sharded executable's floats are only equal to the single-device ones
    to ~1e-4 (reduction order differs) — so the mesh identity
    (``parallel.sharding.describe_mesh``) is part of the key, and results
    computed on one layout are never served as byte-identical answers for
    another."""
    return (seq, int(seed), mesh_desc)


class InFlightEntry:
    """One key's in-flight record: the leader token plus the follower
    contexts (opaque to the cache — the scheduler registers its pending
    handles here) to resolve when the leader's dispatch completes.
    ``leader_trace`` carries the leader's trace_id so a follower's
    ``sched.dedup_join`` event can name the trace it attached to."""

    __slots__ = ("key", "followers", "leader_trace")

    def __init__(self, key):
        self.key = key
        self.followers: list = []
        self.leader_trace: Optional[str] = None


class ResultCache:
    """Thread-safe LRU + in-flight table over ``(seq, seed)`` keys.

    Protocol (scheduler side):

    1. ``status, payload = lookup_or_claim(key, follower_ctx)`` at submit:
       ``"hit"`` (payload = cached result, done), ``"follower"``
       (``follower_ctx`` was registered on the in-flight entry; the leader
       will resolve it), or ``"leader"`` (payload = the new
       :class:`InFlightEntry`; the caller must eventually ``fulfill``).
    2. ``followers = fulfill(key, result, cache=...)`` when the leader's
       dispatch (or rejection/deadline) resolves: stores ``result`` in the
       LRU when ``cache=True`` (only genuinely-ok results belong there)
       and returns the follower contexts for the caller to resolve.
    """

    def __init__(self, capacity: int):
        self.capacity = max(0, int(capacity))
        self._lru: "OrderedDict" = OrderedDict()
        self._inflight: dict = {}
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._lru)

    def lookup_or_claim(self, key, follower_ctx=None) -> Tuple[str, object]:
        with self._lock:
            if key in self._lru:
                self._lru.move_to_end(key)
                return "hit", self._lru[key]
            entry = self._inflight.get(key)
            if entry is not None:
                if follower_ctx is not None:
                    entry.followers.append(follower_ctx)
                return "follower", entry
            entry = InFlightEntry(key)
            self._inflight[key] = entry
            return "leader", entry

    def fulfill(self, key, result, cache: bool = True) -> list:
        with self._lock:
            entry = self._inflight.pop(key, None)
            if cache and self.capacity:
                self._lru[key] = result
                self._lru.move_to_end(key)
                while len(self._lru) > self.capacity:
                    self._lru.popitem(last=False)
            return list(entry.followers) if entry is not None else []

    def peek(self, key) -> Optional[object]:
        """Cached result without LRU promotion (tests, introspection)."""
        with self._lock:
            return self._lru.get(key)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._lru),
                "capacity": self.capacity,
                "inflight": len(self._inflight),
            }
