"""Sequence-keyed LRU result cache with in-flight request dedup.

Identical requests are common in production serving (the same viral
sequence submitted by thousands of users), and the engine's outputs are
deterministic in ``(seq, seed)`` whatever bucket or batch slot the request
lands in (pinned by the serve parity tests) — so recomputing them is pure
waste. Two layers remove it:

- **LRU cache** — completed results keyed by ``(seq, seed)``; a hit
  returns the stored :class:`~alphafold2_tpu.serve.engine.ServeResult`
  (same arrays — byte-identical to the dispatch that produced it).
- **In-flight dedup** — a request whose key is already queued or on the
  device *joins* the in-flight entry as a follower instead of dispatching
  again; when the leader's dispatch completes, every follower is resolved
  with the same result. Dedup works even with the LRU disabled
  (``capacity=0``): concurrent identical requests still share one
  dispatch, they just aren't remembered afterwards.

A third layer (:class:`FeatureCache`) serves the variant-scan fast lane:
featurized input trees content-addressed by the bytes of their leaves
(not the raw request string), so requests whose features coincide share
storage — across seeds the seed-independent leaves (``seq``/``mask``)
intern to one copy — and a point mutant of a cached parent can be
featurized by column patching (``data.pipeline.featurize_delta``) instead
of from scratch.

The caches store and return results; they never stamp latencies or bump
counters — the scheduler/engine own per-request accounting. Pure stdlib
(the feature fingerprint duck-types ``.shape``/``.dtype``/``.tobytes()``
so numpy never has to be imported here).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Optional, Tuple


def result_key(seq: str, seed: int, mesh_desc: Optional[str] = None) -> tuple:
    """The canonical result-cache / in-flight-dedup key. Outputs are
    deterministic in ``(seq, seed)`` on a FIXED execution layout, but a
    sharded executable's floats are only equal to the single-device ones
    to ~1e-4 (reduction order differs) — so the mesh identity
    (``parallel.sharding.describe_mesh``) is part of the key, and results
    computed on one layout are never served as byte-identical answers for
    another."""
    return (seq, int(seed), mesh_desc)


class InFlightEntry:
    """One key's in-flight record: the leader token plus the follower
    contexts (opaque to the cache — the scheduler registers its pending
    handles here) to resolve when the leader's dispatch completes.
    ``leader_trace`` carries the leader's trace_id so a follower's
    ``sched.dedup_join`` event can name the trace it attached to."""

    __slots__ = ("key", "followers", "leader_trace")

    def __init__(self, key):
        self.key = key
        self.followers: list = []
        self.leader_trace: Optional[str] = None


class ResultCache:
    """Thread-safe LRU + in-flight table over ``(seq, seed)`` keys.

    Protocol (scheduler side):

    1. ``status, payload = lookup_or_claim(key, follower_ctx)`` at submit:
       ``"hit"`` (payload = cached result, done), ``"follower"``
       (``follower_ctx`` was registered on the in-flight entry; the leader
       will resolve it), or ``"leader"`` (payload = the new
       :class:`InFlightEntry`; the caller must eventually ``fulfill``).
    2. ``followers = fulfill(key, result, cache=...)`` when the leader's
       dispatch (or rejection/deadline) resolves: stores ``result`` in the
       LRU when ``cache=True`` (only genuinely-ok results belong there)
       and returns the follower contexts for the caller to resolve.
    """

    def __init__(self, capacity: int):
        self.capacity = max(0, int(capacity))
        self._lru: "OrderedDict" = OrderedDict()
        self._inflight: dict = {}
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._lru)

    def lookup_or_claim(self, key, follower_ctx=None) -> Tuple[str, object]:
        with self._lock:
            if key in self._lru:
                self._lru.move_to_end(key)
                return "hit", self._lru[key]
            entry = self._inflight.get(key)
            if entry is not None:
                if follower_ctx is not None:
                    entry.followers.append(follower_ctx)
                return "follower", entry
            entry = InFlightEntry(key)
            self._inflight[key] = entry
            return "leader", entry

    def fulfill(self, key, result, cache: bool = True) -> list:
        with self._lock:
            entry = self._inflight.pop(key, None)
            if cache and self.capacity:
                self._lru[key] = result
                self._lru.move_to_end(key)
                while len(self._lru) > self.capacity:
                    self._lru.popitem(last=False)
            return list(entry.followers) if entry is not None else []

    def peek(self, key) -> Optional[object]:
        """Cached result without LRU promotion (tests, introspection)."""
        with self._lock:
            return self._lru.get(key)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._lru),
                "capacity": self.capacity,
                "inflight": len(self._inflight),
            }


# --------------------------------------------------- content-addressed layer


def feature_key(seq: str, bucket: int, msa_depth: int, seed: int) -> tuple:
    """Derivation key of one featurized tree: everything
    ``data.pipeline.featurize_bucketed`` consumes. Request metadata
    (priority, deadline, parent hints, trace identity) is deliberately
    absent — requests differing only in metadata address the same entry."""
    return (seq, int(bucket), int(msa_depth), int(seed))


def feature_fingerprint(item: dict) -> str:
    """Content address of a featurized tree: sha256 over leaf names,
    shapes, dtypes and raw bytes — the hash is of what the model will
    actually consume, not of the request string that produced it."""
    h = hashlib.sha256()
    for name in sorted(item):
        leaf = item[name]
        h.update(name.encode())
        h.update(repr((tuple(leaf.shape), str(leaf.dtype))).encode())
        h.update(leaf.tobytes())
    return h.hexdigest()


def _leaf_fingerprint(name: str, leaf) -> str:
    h = hashlib.sha256()
    h.update(name.encode())
    h.update(repr((tuple(leaf.shape), str(leaf.dtype))).encode())
    h.update(leaf.tobytes())
    return h.hexdigest()


class _FeatureEntry:
    __slots__ = ("key", "item", "plan", "fingerprint", "leaf_fps", "shape")

    def __init__(self, key, item, plan, fingerprint, leaf_fps, shape):
        self.key = key
        self.item = item
        self.plan = plan
        self.fingerprint = fingerprint
        self.leaf_fps = leaf_fps
        self.shape = shape


class FeatureCache:
    """Content-addressed LRU of featurized input trees.

    Two structures under one lock:

    - **derivation LRU** — :func:`feature_key` → entry holding the
      featurized item, its content fingerprint, and the delta plan
      (``data.pipeline.featurize_bucketed_with_plan``) needed to featurize
      point mutants by column patching.
    - **leaf intern table** — per-leaf content hash → (array, refcount).
      Leaves are stored by VALUE: two entries whose ``seq``/``mask``/
      ``msa`` bytes coincide (e.g. different seeds sharing the
      seed-independent leaves, or a delta-featurized mutant sharing the
      parent's masks) hold references to one array. ``leaf_dedup_hits``
      counts every share, so the reuse is observable, not assumed.

    Cached arrays are shared across requests and must never be mutated;
    ``put`` freezes them (numpy ``writeable=False``) so an accidental
    in-place edit fails loudly instead of corrupting every holder.

    ``delta_parent(bucket, msa_depth, seed, length)`` yields recent
    same-derivation-shape entries (most recent first, bounded scan) for
    the engine's point-mutant search."""

    # bounded same-shape scan: mutant-scan traffic keeps the parent hot at
    # the front, so a short window finds it; unrelated traffic pays at
    # most this many token-array comparisons per miss
    DELTA_SCAN = 8

    def __init__(self, capacity: int):
        self.capacity = max(0, int(capacity))
        self._lru: "OrderedDict[tuple, _FeatureEntry]" = OrderedDict()
        self._leaves: dict = {}  # leaf fp -> [array, refcount]
        self._by_shape: dict = {}  # (bucket, msa_depth, seed, length) -> [key]
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.leaf_dedup_hits = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._lru)

    def lookup(self, key) -> Optional[tuple]:
        """(item, plan) for an exact derivation key, with LRU promotion."""
        with self._lock:
            entry = self._lru.get(key)
            if entry is None:
                self.misses += 1
                return None
            self._lru.move_to_end(key)
            self.hits += 1
            return entry.item, entry.plan

    def put(self, key, item: dict, plan: Optional[dict] = None) -> dict:
        """Intern ``item`` under ``key``; returns the canonical (leaf-
        shared, frozen) tree the caller should use from now on."""
        if self.capacity == 0:
            return item
        seq_len = len(key[0]) if isinstance(key[0], str) else None
        shape = (key[1], key[2], key[3], seq_len)
        with self._lock:
            existing = self._lru.get(key)
            if existing is not None:  # racing featurizers: first put wins
                self._lru.move_to_end(key)
                return existing.item
            interned = {}
            leaf_fps = {}
            for name in sorted(item):
                fp = _leaf_fingerprint(name, item[name])
                slot = self._leaves.get(fp)
                if slot is None:
                    leaf = item[name]
                    if hasattr(leaf, "setflags"):
                        leaf.setflags(write=False)
                    self._leaves[fp] = [leaf, 1]
                    interned[name] = leaf
                else:
                    slot[1] += 1
                    interned[name] = slot[0]
                    self.leaf_dedup_hits += 1
                leaf_fps[name] = fp
            entry = _FeatureEntry(
                key, interned, plan,
                hashlib.sha256(
                    "".join(leaf_fps[n] for n in sorted(leaf_fps)).encode()
                ).hexdigest(),
                leaf_fps, shape,
            )
            self._lru[key] = entry
            self._by_shape.setdefault(shape, []).append(key)
            while len(self._lru) > self.capacity:
                self._evict_oldest_locked()
            return interned

    def _evict_oldest_locked(self) -> None:
        _, entry = self._lru.popitem(last=False)
        for name, fp in entry.leaf_fps.items():
            slot = self._leaves.get(fp)
            if slot is not None:
                slot[1] -= 1
                if slot[1] <= 0:
                    del self._leaves[fp]
        keys = self._by_shape.get(entry.shape)
        if keys is not None:
            try:
                keys.remove(entry.key)
            except ValueError:
                pass
            if not keys:
                del self._by_shape[entry.shape]

    def delta_parent(self, bucket: int, msa_depth: int, seed: int,
                     length: int) -> list:
        """Recent entries at the same derivation shape — the candidates a
        point mutant could delta-featurize from. Most recent first,
        bounded to :attr:`DELTA_SCAN`; only entries that carry a plan."""
        shape = (int(bucket), int(msa_depth), int(seed), int(length))
        with self._lock:
            keys = self._by_shape.get(shape)
            if not keys:
                return []
            out = []
            for key in reversed(keys[-self.DELTA_SCAN:]):
                entry = self._lru.get(key)
                if entry is not None and entry.plan is not None:
                    out.append((entry.item, entry.plan))
            return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._lru),
                "capacity": self.capacity,
                "hits": self.hits,
                "misses": self.misses,
                "unique_leaves": len(self._leaves),
                "leaf_dedup_hits": self.leaf_dedup_hits,
            }
