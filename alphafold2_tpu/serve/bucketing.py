"""Shape-bucket ladder: bounded executable count under ragged request lengths.

A fresh XLA program per distinct sequence length is the canonical serving
anti-pattern — the compile (minutes at flagship sizes, even through the
persistent cache) dwarfs the inference it serves. Instead, request lengths
are padded UP to the nearest rung of a geometric ladder
(``config.ServeConfig.buckets``): the number of executables is bounded by
the ladder size, padding waste is bounded by the ladder's growth ratio, and
everything downstream (trunk attention, distogram, MDS realization, SE(3)
refinement) runs masked so the padding cannot leak into valid coordinates.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Sequence


def validate_ladder(buckets: Sequence[int]) -> tuple:
    """Normalize + sanity-check a bucket ladder (ascending unique ints)."""
    if not buckets:
        raise ValueError("bucket ladder is empty")
    ladder = tuple(int(b) for b in buckets)
    if any(b <= 0 for b in ladder):
        raise ValueError(f"bucket lengths must be positive: {ladder}")
    if list(ladder) != sorted(set(ladder)):
        raise ValueError(
            f"bucket ladder must be strictly ascending: {ladder}"
        )
    return ladder


def bucket_for(length: int, buckets: Sequence[int]) -> int:
    """Smallest ladder rung >= ``length`` (residues).

    Raises ValueError when the request exceeds the top rung — the caller
    decides whether that is a reject or a reason to extend the ladder.
    """
    if length <= 0:
        raise ValueError(f"sequence length must be positive, got {length}")
    for b in buckets:
        if length <= b:
            return int(b)
    raise ValueError(
        f"sequence of {length} residues exceeds the largest bucket "
        f"{max(buckets)}; extend serve.buckets or reject the request"
    )


def geometric_ladder(lo: int, hi: int, ratio: float = 1.5) -> tuple:
    """Build a ladder from ``lo`` up to (at least) ``hi`` growing by
    ``ratio`` — the worst-case padded-compute overhead is ``ratio**2`` on
    the N^2 pair grid, the executable count is log_ratio(hi/lo)."""
    if lo <= 0 or hi < lo:
        raise ValueError(f"need 0 < lo <= hi, got lo={lo} hi={hi}")
    if ratio <= 1.0:
        raise ValueError(f"ladder ratio must be > 1, got {ratio}")
    out = [int(lo)]
    while out[-1] < hi:
        nxt = max(out[-1] + 1, int(round(out[-1] * ratio)))
        out.append(min(nxt, int(hi)) if nxt >= hi else nxt)
    return tuple(out)


def formation_ripe(
    n_queued: int, fill: int, oldest_wait_s: float, dwell_s: float
) -> bool:
    """Fill-or-dwell batch-formation predicate: a bucket's queue dispatches
    when it reaches its fill target (a full batch) or its oldest member has
    waited ``dwell_s`` (latency bound on partial batches).

    This is the *queue-side* barrier only — with pipelined dispatch, a
    request arriving while the bucket's previous formation is still in the
    host stage joins that in-flight batch instead of queueing behind this
    predicate (continuous batching; serve.inflight_admission)."""
    if n_queued <= 0:
        return False
    return n_queued >= max(1, int(fill)) or oldest_wait_s >= dwell_s


def padding_fraction(lengths: Sequence[int], buckets: Sequence[int]) -> float:
    """Fraction of padded (wasted) positions a request mix incurs on this
    ladder — an ops-facing planning metric (also in bench_serve records)."""
    total = padded = 0
    for n in lengths:
        b = bucket_for(n, buckets)
        total += b
        padded += b - n
    return padded / total if total else 0.0


# ------------------------------------------------ variant-scan affinity


def point_mutation(seq: str, other: str) -> Optional[int]:
    """Position of the single substitution separating two equal-length
    sequences, or ``None`` when they are not point mutants of each other
    (different lengths, identical, or >1 substitution). Early-exits at the
    second mismatch, so scanning a window of non-relatives is cheap."""
    if len(seq) != len(other):
        return None
    pos = -1
    for i, (a, b) in enumerate(zip(seq, other)):
        if a != b:
            if pos >= 0:
                return None
            pos = i
    return pos if pos >= 0 else None


class FamilyTracker:
    """Mutant-family detection over the arriving request stream.

    A deep mutational scan is ~20·L point mutants of one parent; packing
    them into the same batch formations (parent affinity) is what turns
    near-duplicate traffic into near-zero-padding, maximally-reusing
    batches. ``observe(seq, parent_id)`` assigns each request a family
    label:

    - an explicit ``ServeRequest.parent_id`` hint wins (``"hint:<id>"``) —
      the client knows its scan better than any detector;
    - otherwise the sequence is matched edit-distance-1 (substitutions
      only; indels change length and bucket anyway) against a bounded
      window of recently observed sequences, inheriting the match's label;
    - an unmatched sequence starts a (so far singleton) family of its own
      and ``observe`` returns ``None`` — regular traffic stays regular.

    Thread-safe; the window is an LRU over sequences so a long-running
    frontend's memory stays bounded."""

    def __init__(self, window: int = 64):
        self.window = max(1, int(window))
        self._label: "OrderedDict[str, str]" = OrderedDict()  # seq -> label
        self._lock = threading.Lock()

    def observe(self, seq: str, parent_id: Optional[str] = None
                ) -> Optional[str]:
        with self._lock:
            if parent_id:
                label = f"hint:{parent_id}"
                self._remember(seq, label)
                return label
            known = self._label.get(seq)
            if known is not None:
                self._label.move_to_end(seq)
                # an exact repeat only counts as family traffic when its
                # label names a real family (not its own singleton start)
                return known if known != seq else None
            for other in reversed(self._label):
                if point_mutation(seq, other) is not None:
                    label = self._label[other]
                    self._remember(seq, label)
                    return label
            self._remember(seq, seq)
            return None

    def _remember(self, seq: str, label: str) -> None:
        self._label[seq] = label
        self._label.move_to_end(seq)
        while len(self._label) > self.window:
            self._label.popitem(last=False)


def affinity_take(pendings: list, fill: int) -> list:
    """Choose up to ``fill`` members for one batch formation, preferring
    the head-of-queue request's family: same-family pendings deeper in the
    queue jump ahead so a scan's mutants ride together (identical lengths
    → near-zero padding, one executable). The head is always taken —
    affinity reorders *within* a formation, it never delays the oldest
    request — and leftover slots fall back to plain queue order, so mixed
    traffic still fills the batch. Returns the chosen pendings; the caller
    removes them from its queue by identity."""
    if fill <= 0 or not pendings:
        return []
    head = pendings[0]
    family = getattr(head, "family", None)
    if family is None:
        return pendings[:fill]
    take = [head]
    taken = {id(head)}
    for p in pendings[1:]:
        if len(take) >= fill:
            break
        if getattr(p, "family", None) == family:
            take.append(p)
            taken.add(id(p))
    if len(take) < fill:
        for p in pendings[1:]:
            if len(take) >= fill:
                break
            if id(p) not in taken:
                take.append(p)
                taken.add(id(p))
    return take
