"""Shape-bucket ladder: bounded executable count under ragged request lengths.

A fresh XLA program per distinct sequence length is the canonical serving
anti-pattern — the compile (minutes at flagship sizes, even through the
persistent cache) dwarfs the inference it serves. Instead, request lengths
are padded UP to the nearest rung of a geometric ladder
(``config.ServeConfig.buckets``): the number of executables is bounded by
the ladder size, padding waste is bounded by the ladder's growth ratio, and
everything downstream (trunk attention, distogram, MDS realization, SE(3)
refinement) runs masked so the padding cannot leak into valid coordinates.
"""

from __future__ import annotations

from typing import Sequence


def validate_ladder(buckets: Sequence[int]) -> tuple:
    """Normalize + sanity-check a bucket ladder (ascending unique ints)."""
    if not buckets:
        raise ValueError("bucket ladder is empty")
    ladder = tuple(int(b) for b in buckets)
    if any(b <= 0 for b in ladder):
        raise ValueError(f"bucket lengths must be positive: {ladder}")
    if list(ladder) != sorted(set(ladder)):
        raise ValueError(
            f"bucket ladder must be strictly ascending: {ladder}"
        )
    return ladder


def bucket_for(length: int, buckets: Sequence[int]) -> int:
    """Smallest ladder rung >= ``length`` (residues).

    Raises ValueError when the request exceeds the top rung — the caller
    decides whether that is a reject or a reason to extend the ladder.
    """
    if length <= 0:
        raise ValueError(f"sequence length must be positive, got {length}")
    for b in buckets:
        if length <= b:
            return int(b)
    raise ValueError(
        f"sequence of {length} residues exceeds the largest bucket "
        f"{max(buckets)}; extend serve.buckets or reject the request"
    )


def geometric_ladder(lo: int, hi: int, ratio: float = 1.5) -> tuple:
    """Build a ladder from ``lo`` up to (at least) ``hi`` growing by
    ``ratio`` — the worst-case padded-compute overhead is ``ratio**2`` on
    the N^2 pair grid, the executable count is log_ratio(hi/lo)."""
    if lo <= 0 or hi < lo:
        raise ValueError(f"need 0 < lo <= hi, got lo={lo} hi={hi}")
    if ratio <= 1.0:
        raise ValueError(f"ladder ratio must be > 1, got {ratio}")
    out = [int(lo)]
    while out[-1] < hi:
        nxt = max(out[-1] + 1, int(round(out[-1] * ratio)))
        out.append(min(nxt, int(hi)) if nxt >= hi else nxt)
    return tuple(out)


def formation_ripe(
    n_queued: int, fill: int, oldest_wait_s: float, dwell_s: float
) -> bool:
    """Fill-or-dwell batch-formation predicate: a bucket's queue dispatches
    when it reaches its fill target (a full batch) or its oldest member has
    waited ``dwell_s`` (latency bound on partial batches).

    This is the *queue-side* barrier only — with pipelined dispatch, a
    request arriving while the bucket's previous formation is still in the
    host stage joins that in-flight batch instead of queueing behind this
    predicate (continuous batching; serve.inflight_admission)."""
    if n_queued <= 0:
        return False
    return n_queued >= max(1, int(fill)) or oldest_wait_s >= dwell_s


def padding_fraction(lengths: Sequence[int], buckets: Sequence[int]) -> float:
    """Fraction of padded (wasted) positions a request mix incurs on this
    ladder — an ops-facing planning metric (also in bench_serve records)."""
    total = padded = 0
    for n in lengths:
        b = bucket_for(n, buckets)
        total += b
        padded += b - n
    return padded / total if total else 0.0
