"""Async serving frontend: admission control, deadlines, dedup, dispatch.

``ServeEngine.predict_many`` is a closed loop: the caller assembles a
request list, blocks until every dispatch finishes, and nothing bounds how
much work piles up. This module is the open-loop layer a real frontend
needs between "user request arrives" and "bucketed batch hits the chip":

- **Bounded priority queue + admission control** — ``submit`` never
  blocks and never raises: a full queue yields a structured ``rejected``
  result carrying a ``retry_after_s`` hint, and past a configurable
  watermark (``serve.shed_watermark``) low-priority requests are load-shed
  before the queue is full, so high-priority traffic keeps a reserved
  slice of the queue under overload.
- **Continuous batch formation** — a background dispatcher thread forms
  (bucket, batch) groups and dispatches when a group *fills* to
  ``max_batch`` OR the oldest member has *dwelled* ``serve.dwell_ms``
  (:func:`~alphafold2_tpu.serve.bucketing.formation_ripe`) — the classic
  fill-vs-latency tradeoff, tunable per deployment.
- **In-flight admission (continuous batching)** — with the engine's
  pipelined dispatch (``serve.pipeline_depth > 0``), a request arriving
  while its bucket's previous formation is still in the *host stage*
  joins that in-flight batch (``DispatchHandle.try_join``) instead of
  queueing behind a fresh fill-or-dwell window; dispatches go through
  ``engine.dispatch_batch_async`` and resolve from the pipeline's
  completion worker, so the dispatcher thread never blocks on the device
  and batch N+1 forms while batch N computes.
- **Per-request deadlines** — a request whose deadline passes while
  queued resolves to a structured ``deadline_exceeded`` result instead of
  wasting a dispatch slot (or raising).
- **Result cache + in-flight dedup** — ``(seq, seed)``-keyed LRU
  (:mod:`alphafold2_tpu.serve.cache`): repeats resolve immediately with
  byte-identical arrays, and concurrent identical requests share one
  dispatch.
- **Fault tolerance** — a failed dispatch (structured ``error`` results
  from the engine, e.g. a :class:`~alphafold2_tpu.serve.faults.FaultPlan`
  injection) is retried once against a *different* (bucket, batch)
  executable (the next ladder rung) before the error reaches callers.

Observability rides the PR-2 plumbing: ``sched.*`` counters (rejections,
sheds, deadline misses, cache hits, dedups, retries) share the engine's
``EventCounters``; queue-depth / time-to-dispatch / dwell stream into
``observe.Histogram``; dispatches open ``sched.dispatch`` tracer spans.
Every request carries a :class:`~alphafold2_tpu.observe.tracectx.
TraceContext` from birth and the scheduler emits its full lifecycle as
trace events — ``sched.submit`` (root), ``sched.queue`` (residency span),
``sched.dispatch``/``sched.retry`` (batch spans listing member traces),
``sched.cache_hit``/``sched.dedup_join`` (shared-result provenance, the
join naming the leader's trace), ``sched.resolve`` (terminal, one per
caller) — so one request's journey reconstructs from the trace JSONL
alone (``observe.tracectx.reconstruct_traces``). ``add_observer`` hooks
every resolution for the SLO monitor (``observe/slo.py``).
``bench.py --mode serve-async`` drives it open-loop with Poisson arrivals.

Scheduling decisions use an injectable ``clock`` (default
``time.perf_counter``, the engine's queue-wait timebase), and with
``start=False`` the dispatcher can be pumped inline — the fake-clock tests
in ``tests/test_scheduler.py`` are fully deterministic.
"""

from __future__ import annotations

import bisect
import dataclasses
import threading
import time
from typing import Callable, Optional, Union

from alphafold2_tpu.observe import Histogram, Tracer
from alphafold2_tpu.observe.tracectx import (
    CACHE_HIT_EVENT,
    DEDUP_EVENT,
    RESOLVE_EVENT,
    SUBMIT_EVENT,
    TraceContext,
)
from alphafold2_tpu.serve.bucketing import (
    FamilyTracker,
    affinity_take,
    bucket_for,
    formation_ripe,
)
from alphafold2_tpu.serve.cache import ResultCache, result_key
from alphafold2_tpu.serve.pipeline import DispatchHandle, PipelineBatch
from alphafold2_tpu.serve.engine import (
    ServeEngine,
    ServeRequest,
    ServeResult,
    _as_request,
)


class PendingResult:
    """Caller-side handle for one submitted request.

    ``result(timeout)`` blocks until the request resolves (to an ``ok``
    result *or* a structured rejection/deadline/error result — the
    frontend never raises through this) and raises ``TimeoutError`` only
    if the timeout itself expires."""

    __slots__ = ("request", "_event", "_result")

    def __init__(self, request: ServeRequest):
        self.request = request
        self._event = threading.Event()
        self._result: Optional[ServeResult] = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> ServeResult:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request ({self.request.seq[:16]!r}...) not resolved "
                f"within {timeout}s"
            )
        return self._result

    def _resolve(self, result: ServeResult) -> None:
        self._result = result
        self._event.set()


@dataclasses.dataclass
class _Pending:
    """One admitted (leader) request queued for dispatch."""

    req: ServeRequest
    handle: PendingResult
    key: tuple
    bucket: int
    priority: int
    enqueued: float  # scheduler-clock timestamp
    deadline: Optional[float]  # absolute scheduler-clock deadline
    seq_no: int
    # mutant-family label (bucketing.FamilyTracker): None for regular
    # traffic; same-label pendings are packed into one formation
    family: Optional[str] = None

    @property
    def order(self) -> tuple:
        return (-self.priority, self.seq_no)


class AsyncServeFrontend:
    """Open-loop serving frontend over a :class:`ServeEngine`.

    >>> frontend = AsyncServeFrontend(engine)
    >>> handle = frontend.submit("MKTAYIAK...", deadline_s=2.0)
    >>> result = handle.result(timeout=30)   # structured, never raises
    >>> frontend.close()

    Scheduling knobs come from ``engine.cfg.serve``: ``queue_depth``,
    ``dwell_ms``, ``default_deadline_s``, ``cache_size``,
    ``shed_watermark``, ``retry_failed``. ``start=False`` skips the
    dispatcher thread; tests then call :meth:`pump` inline against an
    injected ``clock``.
    """

    def __init__(
        self,
        engine: ServeEngine,
        clock: Optional[Callable[[], float]] = None,
        tracer: Optional[Tracer] = None,
        start: bool = True,
    ):
        scfg = engine.cfg.serve
        self.engine = engine
        self.counters = engine.counters
        self.tracer = tracer if tracer is not None else engine.tracer
        self._clock = clock if clock is not None else time.perf_counter
        self.queue_depth = max(1, int(scfg.queue_depth))
        self.dwell_s = max(0.0, float(scfg.dwell_ms) / 1e3)
        self.default_deadline_s = float(scfg.default_deadline_s or 0.0)
        self.shed_watermark = float(scfg.shed_watermark)
        self.retry_failed = bool(scfg.retry_failed)
        self.cache = ResultCache(scfg.cache_size)
        self.histograms = {
            "queue_depth": Histogram(),
            "time_to_dispatch_s": Histogram(),
            "dwell_s": Histogram(),
            # per-formation padded fraction (slot + length padding over the
            # full bucket*fill rectangle), split by how the batch formed —
            # the variant-scan claim "affinity batches waste less" as a
            # measured distribution, not an assumption
            "affinity_pad_fraction": Histogram(),
            "regular_pad_fraction": Histogram(),
        }
        # parent-affinity batching (variant-scan fast lane): detect mutant
        # families on the arriving stream and pack same-family requests
        # into the same formations
        self.affinity_batching = bool(
            getattr(scfg, "affinity_batching", False)
        )
        self.families = FamilyTracker() if self.affinity_batching else None
        # pipelined dispatch: present when the engine was built with
        # serve.pipeline_depth > 0 (getattr so engine fakes in tests and
        # older engine objects keep the sync path)
        self.pipeline = getattr(engine, "pipeline", None)
        self.inflight_admission = (
            self.pipeline is not None
            and bool(getattr(scfg, "inflight_admission", False))
        )
        self._lock = threading.Condition()
        self._observers: list = []  # fn(result, priority) at every resolve
        self._submit_observers: list = []  # fn(req, bucket, family)
        self._queues: dict = {}  # bucket -> list[_Pending], priority-sorted
        # bucket -> (DispatchHandle, [_Pending]) while that batch's host
        # stage is still joinable; completion pops its own entry
        self._forming: dict = {}
        self._inflight: list = []  # DispatchHandles not yet completed
        self._depth = 0
        self._seq_no = 0
        self._ema_dispatch_s: Optional[float] = None
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="af2-serve-scheduler", daemon=True
        )
        self._thread.start()

    def close(self, timeout: float = 30.0) -> None:
        """Stop the dispatcher and resolve anything still queued as
        ``rejected`` (reason "frontend closed") — callers never hang on a
        handle whose dispatcher is gone."""
        with self._lock:
            self._stop = True
            self._lock.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        # drain pipelined in-flight batches first: their completion
        # callbacks resolve the member handles (joiners included), so the
        # leftover sweep below only sees what never got dispatched
        deadline = time.monotonic() + max(0.0, timeout)
        with self._lock:
            inflight = list(self._inflight)
        for dh in inflight:
            try:
                dh.result(timeout=max(0.1, deadline - time.monotonic()))
            except TimeoutError:
                break  # a wedged batch must not hang close(); sweep on
        leftovers = []
        with self._lock:
            for q in self._queues.values():
                leftovers.extend(q)
                q.clear()
            self._depth = 0
        for p in leftovers:
            self._resolve_leader(
                p,
                ServeResult(
                    seq=p.req.seq, bucket=p.bucket, status="rejected",
                    error="frontend closed",
                ),
                cache_ok=False,
            )

    def __enter__(self) -> "AsyncServeFrontend":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def depth(self) -> int:
        with self._lock:
            return self._depth

    def load_snapshot(self) -> dict:
        """One consistent routing-grade load reading: queued depth,
        batches in flight, and the buckets whose in-flight formation is
        still joinable. The fleet router's health substrate — taken under
        this frontend's lock so a router never has to hold its OWN lock
        across the call (the fleet's documented lock-order rule)."""
        with self._lock:
            return {
                "depth": self._depth,
                "inflight": len(self._inflight),
                "forming": tuple(self._forming),
                "closed": self._stop,
            }

    def evict_queued(self, max_n: int, reason: str = "evicted") -> int:
        """Pop up to ``max_n`` queued (not yet dispatched) requests —
        newest, lowest-priority first — and resolve them as structured
        rejections carrying ``reason``. The fleet router's work-stealing
        hook: the stolen requests resolve through the normal observer
        path, so a fleet tracking them by trace_id can re-submit each to
        another replica. Returns the number evicted."""
        taken: list = []
        with self._lock:
            for bucket in sorted(self._queues, reverse=True):
                q = self._queues[bucket]
                while q and len(taken) < max_n:
                    taken.append(q.pop())  # tail = lowest priority, newest
                if len(taken) >= max_n:
                    break
            self._depth -= len(taken)
        for p in taken:
            self.tracer.instant(
                "sched.evict", bucket=p.bucket, reason=reason,
                **(p.req.trace.child().event_args()
                   if p.req.trace is not None else {}),
            )
            self._resolve_leader(
                p,
                ServeResult(
                    seq=p.req.seq, bucket=p.bucket, status="rejected",
                    error=reason,
                ),
                cache_ok=False,
            )
        return len(taken)

    def stats(self) -> dict:
        return self.counters.snapshot()

    # ------------------------------------------------------------ observers

    def add_observer(self, fn: Callable) -> None:
        """Register ``fn(result, priority)``, called at EVERY resolution
        (ok, error, rejected, deadline, cache hit, dedup follower) — the
        SLO monitor's ingestion point, and the bench's per-class ledger."""
        self._observers.append(fn)

    def _notify(self, result: ServeResult, priority: int) -> None:
        for fn in self._observers:
            try:
                fn(result, priority)
            except Exception:
                pass  # an observer must never take the serving path down

    def add_submit_observer(self, fn: Callable) -> None:
        """Register ``fn(request, bucket, family)``, called once per
        submitted request at arrival — BEFORE admission control, so the
        observer sees the offered stream (rejects and sheds included),
        not just what the queue accepted. ``bucket``/``family`` are None
        for unservable requests / non-family traffic. The workload
        recorder's ingestion point (``observe/workload.py``)."""
        self._submit_observers.append(fn)

    def _notify_submit(self, req: ServeRequest, bucket, family) -> None:
        for fn in self._submit_observers:
            try:
                fn(req, bucket, family)
            except Exception:
                pass  # same contract as _notify: never break serving

    def _trace_resolve(
        self, tctx: Optional[TraceContext], result: ServeResult
    ) -> None:
        """The terminal lifecycle event: one ``sched.resolve`` per caller
        (followers get their own, on their own trace)."""
        args = tctx.child().event_args() if tctx is not None else {}
        self.tracer.instant(
            RESOLVE_EVENT, status=result.status,
            cache_hit=bool(result.cache_hit),
            retried=bool(result.retried), **args,
        )

    def histogram_snapshots(self, unit_scale: float = 1.0) -> dict:
        return {
            name: h.snapshot(
                unit_scale=unit_scale if name.endswith("_s") else 1.0,
                digits=4,
            )
            for name, h in self.histograms.items()
        }

    # --------------------------------------------------------------- submit

    def submit(
        self,
        request: Union[str, ServeRequest],
        priority: Optional[int] = None,
        deadline_s: Optional[float] = None,
    ) -> PendingResult:
        """Admit (or structurally reject) one request; never blocks on the
        device, never raises for a servable-or-not decision."""
        req = _as_request(request)
        now = self._clock()
        if priority is None:
            priority = req.priority
        if deadline_s is None:
            deadline_s = (
                req.deadline_s if req.deadline_s is not None
                else (self.default_deadline_s or None)
            )
        req = dataclasses.replace(
            req, arrival_s=now, priority=priority, deadline_s=deadline_s
        )
        handle = PendingResult(req)
        self.counters.bump("sched.submitted")
        tctx = req.trace
        # the trace root: every request — admitted, shed, or unservable —
        # gets exactly one, carrying the root span (no parent_id)
        self.tracer.instant(
            SUBMIT_EVENT, priority=int(priority),
            **(tctx.event_args() if tctx is not None else {}),
        )

        try:
            if not req.seq:
                raise ValueError("empty sequence")
            bucket = bucket_for(len(req.seq), self.engine.buckets)
        except ValueError as e:
            res = ServeResult(
                seq=req.seq, bucket=0, status="rejected",
                error=f"unservable request: {e}",
                trace_id=tctx.trace_id if tctx is not None else None,
            )
            handle._resolve(res)
            self.counters.bump("sched.rejected")
            self.tracer.instant(
                "sched.reject", reason="unservable",
                **(tctx.child().event_args() if tctx is not None else {}),
            )
            self._notify_submit(req, None, None)
            self._trace_resolve(tctx, res)
            self._notify(res, priority)
            return handle

        # mutant-family detection (variant-scan fast lane): an explicit
        # parent_id hint or an edit-distance-1 match against recent traffic
        # labels this request for parent-affinity batch formation
        family = None
        if self.families is not None:
            family = self.families.observe(req.seq, req.parent_id)
            if family is not None:
                self.counters.bump("sched.family_members")
        self._notify_submit(req, bucket, family)

        # mesh identity rides in the key (serve/cache.py): results from a
        # sharded engine and a single-device one are numerically close but
        # not byte-identical, so they must never dedup onto each other
        key = result_key(req.seq, req.seed, self.engine.mesh_desc)
        status, payload = self.cache.lookup_or_claim(
            key, follower_ctx=(handle, now, tctx, priority)
        )
        if status == "hit":
            self.counters.bump("sched.cache_hits")
            res = self._shared_result(payload, now, trace=tctx)
            handle._resolve(res)
            self.tracer.instant(
                CACHE_HIT_EVENT, bucket=bucket,
                **(tctx.child().event_args() if tctx is not None else {}),
            )
            self._trace_resolve(tctx, res)
            self._notify(res, priority)
            return handle
        if status == "follower":
            # rides the in-flight leader's dispatch; no queue slot consumed.
            # The join event names the leader's trace so the two lifecycles
            # cross-reference from either side of the dedup.
            self.counters.bump("sched.inflight_dedup")
            self.tracer.instant(
                DEDUP_EVENT, bucket=bucket,
                **({"leader_trace": payload.leader_trace}
                   if payload.leader_trace else {}),
                **(tctx.child().event_args() if tctx is not None else {}),
            )
            return handle
        if tctx is not None:
            payload.leader_trace = tctx.trace_id  # the InFlightEntry

        # leader: admission control under the scheduler lock
        with self._lock:
            if self.inflight_admission and not self._stop:
                # continuous batching: if this bucket's previous formation
                # is still in the pipeline's host stage, join it instead of
                # queueing behind a fresh fill-or-dwell window. No queue
                # slot is consumed; the join races the host worker sealing
                # the batch and simply falls through to normal admission
                # when it loses. (Lock order: scheduler lock -> batch
                # membership lock, never the reverse.)
                forming = self._forming.get(bucket)
                # typed so the static concurrency auditor sees this as
                # the AsyncServeFrontend._lock -> PipelineBatch._lock
                # edge (try_join acquires the membership lock)
                dh: Optional[DispatchHandle] = (
                    forming[0] if forming is not None else None
                )
                if dh is not None and dh.try_join(req):
                    pending = _Pending(
                        req=req, handle=handle, key=key, bucket=bucket,
                        priority=priority, enqueued=now, deadline=None,
                        seq_no=self._seq_no, family=family,
                    )
                    self._seq_no += 1
                    forming[1].append(pending)
                    self.counters.bump("sched.inflight_admitted")
                    if family is not None:
                        # a late-arriving sibling caught its family's batch
                        # while the host stage was still featurizing it
                        self.counters.bump("sched.family_inflight_joins")
                    joined_trace = (
                        tctx.child().event_args() if tctx is not None else {}
                    )
                    self.tracer.instant(
                        "sched.inflight_admit", bucket=bucket, **joined_trace
                    )
                    return handle
            rejected = None
            if self._stop:
                # the dispatcher is gone: a request queued now would hang
                # forever. A late arrival racing close() — e.g. a fleet
                # route landing on a replica being drained — gets the
                # same structured rejection close()'s sweep hands out.
                rejected = ("frontend closed", "sched.rejected")
            elif self._depth >= self.queue_depth:
                rejected = ("queue full", "sched.rejected")
            elif (
                self.shed_watermark > 0
                and self._depth + 1 > self.shed_watermark * self.queue_depth
                and priority <= 0
            ):
                rejected = ("load shed (queue past watermark)", "sched.shed")
            if rejected is None:
                deadline = now + deadline_s if deadline_s else None
                pending = _Pending(
                    req=req, handle=handle, key=key, bucket=bucket,
                    priority=priority, enqueued=now, deadline=deadline,
                    seq_no=self._seq_no, family=family,
                )
                self._seq_no += 1
                q = self._queues.setdefault(bucket, [])
                bisect.insort(q, pending, key=lambda p: p.order)
                self._depth += 1
                self.counters.bump("sched.admitted")
                self.histograms["queue_depth"].observe(self._depth)
                self._lock.notify_all()
                return handle
            reason, counter = rejected
            retry_after = self._retry_after_locked()
        # rejection resolves outside the lock (cache fulfill + callbacks)
        self.counters.bump("sched.rejected")
        if counter == "sched.shed":
            self.counters.bump("sched.shed")
        self.tracer.instant(
            "sched.reject", reason=reason, bucket=bucket,
            **(tctx.child().event_args() if tctx is not None else {}),
        )
        self._resolve_leader(
            _Pending(
                req=req, handle=handle, key=key, bucket=bucket,
                priority=priority, enqueued=now, deadline=None, seq_no=-1,
            ),
            ServeResult(
                seq=req.seq, bucket=bucket, status="rejected", error=reason,
                retry_after_s=retry_after,
            ),
            cache_ok=False,
        )
        return handle

    def _retry_after_locked(self) -> float:
        """Backoff hint: roughly how long until the queue drains a batch's
        worth of slack, from the dispatch-duration EMA (or the dwell window
        before any dispatch has been measured)."""
        per_batch = (
            self._ema_dispatch_s
            if self._ema_dispatch_s is not None
            else max(self.dwell_s, 0.05)
        )
        batches_ahead = self._depth // self.engine.max_batch + 1
        return round(batches_ahead * per_batch, 4)

    def _shared_result(
        self,
        result: ServeResult,
        submit_ts: float,
        trace: Optional[TraceContext] = None,
    ) -> ServeResult:
        """A cached/deduped caller's view of a shared result: identical
        arrays (byte-for-byte — same objects), per-caller latency, and the
        CALLER's trace identity (the shared result carries the leader's)."""
        wait = max(0.0, self._clock() - submit_ts)
        return dataclasses.replace(
            result, cache_hit=True, latency_s=wait, queue_wait_s=wait,
            **({"trace_id": trace.trace_id} if trace is not None else {}),
        )

    # ------------------------------------------------------------- dispatch

    def pump(self) -> int:
        """One scheduling pass: expire deadlines, form ripe batches, and
        dispatch them. Returns the number of dispatches executed. The
        dispatcher thread calls this in a loop; tests with ``start=False``
        call it inline for deterministic fake-clock scheduling."""
        now = self._clock()
        expired: list = []
        plans: list = []
        with self._lock:
            for bucket in sorted(self._queues):
                q = self._queues[bucket]
                keep = []
                dead = []
                for p in q:
                    if p.deadline is not None and p.deadline <= now:
                        dead.append(p)
                    else:
                        keep.append(p)
                if dead:
                    q[:] = keep
                    self._depth -= len(dead)
                    expired.extend(dead)
                fill = self.engine.batch_for(bucket)  # long rungs fill small
                while q:
                    oldest = min(p.enqueued for p in q)
                    if not formation_ripe(
                        len(q), fill, now - oldest, self.dwell_s
                    ):
                        break
                    if self.affinity_batching:
                        # parent-affinity formation: same-family pendings
                        # deeper in the queue jump into the head's batch
                        # (the head itself is never delayed)
                        take = affinity_take(q, fill)
                        chosen = {id(p) for p in take}
                        q[:] = [p for p in q if id(p) not in chosen]
                    else:
                        take = q[:fill]
                        del q[: len(take)]
                    self._depth -= len(take)
                    plans.append((bucket, take))
        for p in expired:
            self.counters.bump("sched.deadline_miss")
            self.tracer.instant(
                "sched.deadline_miss", bucket=p.bucket,
                **(p.req.trace.child().event_args()
                   if p.req.trace is not None else {}),
            )
            self._resolve_leader(
                p,
                ServeResult(
                    seq=p.req.seq, bucket=p.bucket,
                    status="deadline_exceeded",
                    error=(
                        f"deadline ({p.req.deadline_s}s) passed after "
                        f"{now - p.enqueued:.4g}s in queue"
                    ),
                    latency_s=max(0.0, now - p.enqueued),
                    queue_wait_s=max(0.0, now - p.enqueued),
                ),
                cache_ok=False,
            )
        for bucket, batch in plans:
            self._execute(bucket, batch, now)
        return len(plans)

    def _execute(self, bucket: int, pendings: list, formed_at: float) -> None:
        self.histograms["dwell_s"].observe(
            max(0.0, formed_at - min(p.enqueued for p in pendings))
        )
        # formation accounting: a batch is affinity-formed when >= 2
        # members share the head's family label. Padded fraction counts
        # the whole bucket*fill rectangle (empty slots + length padding).
        fam = pendings[0].family
        affine = (
            fam is not None
            and sum(1 for p in pendings if p.family == fam) >= 2
        )
        if affine:
            self.counters.bump("sched.affinity_batches")
        fill = max(1, self.engine.batch_for(bucket))
        total = fill * bucket
        padded = total - sum(len(p.req.seq) for p in pendings)
        self.histograms[
            "affinity_pad_fraction" if affine else "regular_pad_fraction"
        ].observe(max(0.0, padded) / total)
        for p in pendings:
            self.histograms["time_to_dispatch_s"].observe(
                max(0.0, formed_at - p.enqueued)
            )
            if p.req.trace is not None:
                # retroactive queue-residency span: the region is only
                # known once the batch forms, so it is emitted with
                # explicit bounds rather than timed live
                self.tracer.span_event(
                    "sched.queue", p.enqueued, formed_at, bucket=bucket,
                    **p.req.trace.child().event_args(),
                )
        if self.pipeline is not None:
            self._execute_pipelined(bucket, pendings)
            return
        reqs = [p.req for p in pendings]
        member_traces = [r.trace.trace_id for r in reqs if r.trace]
        t0 = self._clock()
        mesh_attr = (
            {"mesh": self.engine.mesh_desc} if self.engine.mesh_desc else {}
        )
        with self.tracer.span(
            "sched.dispatch", bucket=bucket, n=len(reqs), **mesh_attr,
            **({"trace_ids": member_traces} if member_traces else {}),
        ):
            results = self.engine.dispatch_batch(bucket, reqs)
        dt = max(0.0, self._clock() - t0)
        self._ema_dispatch_s = (
            dt if self._ema_dispatch_s is None
            else 0.8 * self._ema_dispatch_s + 0.2 * dt
        )
        self._settle(bucket, pendings, results)

    def _execute_pipelined(self, bucket: int, pendings: list) -> None:
        """Hand one formed batch to the engine's pipeline and return
        immediately — the dispatcher thread goes back to forming batch
        N+1 while this one runs. While the batch's host stage runs, its
        membership stays joinable and ``submit`` admits late arrivals into
        it (the ``_forming`` registry); the pipeline's completion worker
        calls :meth:`_finish_pipelined` with the ordered results."""
        t0 = self._clock()
        dh = self.engine.dispatch_batch_async(
            bucket, [p.req for p in pendings],
            joinable=self.inflight_admission,
        )
        entry = (dh, list(pendings))
        with self._lock:
            self._inflight.append(dh)
            if self.inflight_admission:
                self._forming[bucket] = entry
        dh.add_done_callback(
            lambda results: self._finish_pipelined(
                bucket, dh, entry, t0, results
            )
        )

    def _finish_pipelined(
        self, bucket: int, dh, entry: tuple, t0: float, results: list
    ) -> None:
        """Completion callback (pipeline fetch worker thread): un-register
        the batch, account the dispatch, retry failures synchronously, and
        resolve every member — initial pendings plus in-flight joiners."""
        with self._lock:
            if self._forming.get(bucket) is entry:
                del self._forming[bucket]
            # joiners append under this lock before the batch seals, and
            # sealing happens-before completion, so this snapshot is the
            # full membership in the engine's result order
            pendings = list(entry[1])
        try:
            dt = max(0.0, self._clock() - t0)
            self._ema_dispatch_s = (
                dt if self._ema_dispatch_s is None
                else 0.8 * self._ema_dispatch_s + 0.2 * dt
            )
            member_traces = [
                p.req.trace.trace_id for p in pendings if p.req.trace
            ]
            mesh_attr = (
                {"mesh": self.engine.mesh_desc}
                if self.engine.mesh_desc else {}
            )
            # retroactive: the dispatch ran on the pipeline workers, not here
            self.tracer.span_event(
                "sched.dispatch", t0, self._clock(), bucket=bucket,
                n=len(pendings), pipelined=True, **mesh_attr,
                **({"trace_ids": member_traces} if member_traces else {}),
            )
            self._settle(bucket, pendings, results)
        finally:
            # un-register only once fully settled (resolutions + terminal
            # sched.resolve events emitted): close()'s drain treats an
            # empty _inflight as "safe to tear the telemetry plane down"
            with self._lock:
                try:
                    self._inflight.remove(dh)
                except ValueError:
                    pass
                self._lock.notify_all()

    def _settle(self, bucket: int, pendings: list, results: list) -> None:
        """Post-dispatch tail shared by the sync and pipelined paths:
        retry failures against a different executable, then resolve."""
        reqs = [p.req for p in pendings]
        failed = [i for i, r in enumerate(results) if r.status == "error"]
        if failed and self.retry_failed:
            # retry once against a DIFFERENT executable: the next ladder
            # rung when one exists (a fresh (bucket, batch) shape excludes
            # whatever poisoned the first), else the same rung again
            retry_at = self.engine.retry_bucket(bucket) or bucket
            self.counters.bump("sched.retries", len(failed))
            retry_traces = [
                reqs[i].trace.trace_id for i in failed if reqs[i].trace
            ]
            with self.tracer.span(
                "sched.retry", bucket=retry_at, failed_bucket=bucket,
                n=len(failed),
                **({"trace_ids": retry_traces} if retry_traces else {}),
            ):
                retried = self.engine.dispatch_batch(
                    retry_at, [reqs[i] for i in failed]
                )
            for i, rr in zip(failed, retried):
                results[i] = dataclasses.replace(rr, retried=True)

        self.counters.bump("sched.dispatches")
        self.counters.bump("sched.batched_requests", len(pendings))
        for p, res in zip(pendings, results):
            self._resolve_leader(p, res, cache_ok=res.status == "ok")

    def _resolve_leader(
        self, pending: _Pending, result: ServeResult, cache_ok: bool
    ) -> None:
        """Resolve a leader's handle and fan the result out to every
        follower deduped onto its key (sharing failures too — one dispatch,
        one outcome). Only ok results enter the LRU. Every resolution —
        leader and followers — emits its own terminal ``sched.resolve``
        on its own trace and reaches every registered observer."""
        tctx = pending.req.trace
        if tctx is not None and result.trace_id != tctx.trace_id:
            result = dataclasses.replace(result, trace_id=tctx.trace_id)
        # Promote into the cache (and drain followers) BEFORE resolving the
        # leader's handle: once .result() returns, a resubmit of the same key
        # must observe a cache hit, not a still-in-flight entry.
        followers = self.cache.fulfill(pending.key, result, cache=cache_ok)
        pending.handle._resolve(result)
        self._trace_resolve(tctx, result)
        self._notify(result, pending.priority)
        for ctx in followers:
            handle, submit_ts = ctx[0], ctx[1]
            f_trace = ctx[2] if len(ctx) > 2 else None
            f_priority = ctx[3] if len(ctx) > 3 else 0
            shared = self._shared_result(result, submit_ts, trace=f_trace)
            handle._resolve(shared)
            self._trace_resolve(f_trace, shared)
            self._notify(shared, f_priority)

    # --------------------------------------------------------------- thread

    def _next_wakeup_locked(self, now: float) -> Optional[float]:
        """Seconds until the next dwell or deadline expiry (0 = a batch is
        already ripe, None = queue empty: wait for a submit)."""
        horizon = None
        for bucket, q in self._queues.items():
            if not q:
                continue
            if len(q) >= self.engine.batch_for(bucket):
                return 0.0
            oldest = min(p.enqueued for p in q)
            times = [oldest + self.dwell_s]
            times.extend(p.deadline for p in q if p.deadline is not None)
            t = min(times)
            horizon = t if horizon is None else min(horizon, t)
        if horizon is None:
            return None
        return max(0.0, horizon - now)

    def _run(self) -> None:
        while True:
            with self._lock:
                if self._stop:
                    return
                timeout = self._next_wakeup_locked(self._clock())
                if timeout is None:
                    self._lock.wait(timeout=1.0)
                elif timeout > 0:
                    self._lock.wait(timeout=timeout)
                if self._stop:
                    return
            self.pump()


def _audit_invert_locks(  # af2: gated-defect[AF2TPU_AUDIT_INVERT_LOCKS]
    frontend: AsyncServeFrontend, batch: PipelineBatch
) -> None:
    """Seeded negative control for the static concurrency gate.

    Never executed: the ``gated-defect`` marker keeps this function out
    of the audit (and out of ``concurrency_contracts.json``) unless
    ``AF2TPU_AUDIT_INVERT_LOCKS=1``, in which case it contributes the
    *inverted* acquisition order — batch membership lock taken first,
    scheduler lock inside it — closing a cycle against ``submit``'s
    documented ``AsyncServeFrontend._lock -> PipelineBatch._lock`` edge.
    CI flips the env var and asserts the gate exits 1 naming the cycle;
    no bench run and no thread ever executes this body.
    """
    with batch._lock:
        with frontend._lock:
            pass
