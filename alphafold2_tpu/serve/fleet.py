"""Multi-replica fleet serving: health-aware router over N serve cells.

One AsyncServeFrontend feeding one ServeEngine is a single serving cell.
``FleetFrontend`` is the fleet layer production needs on top: it owns N
replica cells (each a ServeEngine + AsyncServeFrontend + its dispatch
pipeline, process-local or mesh-slice) and

- **routes** every admitted request to the replica with the lowest load
  score, fed from per-replica :meth:`~alphafold2_tpu.serve.scheduler.
  AsyncServeFrontend.load_snapshot` health readings (queued depth,
  batches in flight, open in-flight formations). A replica holding a
  joinable in-flight formation for the request's bucket is preferred —
  continuous batching at the *replica* level: the arrival admits into
  the partially filled batch (``PipelineBatch.try_join`` via that
  replica's scheduler) instead of waiting out a fresh fill-or-dwell
  window anywhere.
- **steals** queued work from overloaded replicas into idle ones: the
  health pump evicts the newest, lowest-priority queued requests from
  the deepest queue (``AsyncServeFrontend.evict_queued``) and re-routes
  each to the shallowest (``fleet.steals`` / ``fleet.rerouted``).
- **drains** a dead replica with zero dropped (non-rejected) requests: a
  kill marks the replica unroutable, its dispatched batches complete and
  resolve normally, and its non-dispatched queued work resolves as
  internal "frontend closed" rejections the fleet re-submits to
  surviving replicas (``fleet.drains``). Replica death and degradation
  are first-class fault plans (:class:`~alphafold2_tpu.serve.faults.
  FleetFaultPlan`, ``AF2TPU_SERVE_FLEET_FAULT``), so the death drill is
  a reproducible scenario, not test-only plumbing.

**Trace continuity across the replica hop**: the router serializes each
request's context to its W3C ``traceparent()`` header form and the
replica-side request carries a child reconstructed with
``TraceContext.from_traceparent`` — one trace spans the router's
``fleet.admit``/``fleet.route`` events and the replica's full scheduler
lifecycle, so ``tracectx.trace_completeness`` reconstructs end-to-end
(the serve-fleet bench gates >= 0.99 across the hop). One
``AF2TPU_SLO_SPECS`` string fans out to one SLOMonitor per replica, fed
only caller-visible terminal results (reroute artifacts excluded);
:func:`~alphafold2_tpu.observe.slo.aggregate_slo_verdicts` rolls the
per-replica burn into the fleet-level verdict.

**Lock discipline** — the fleet's deadlock cliff, statically enforced by
the layer-5 concurrency gate: the router NEVER acquires a replica's
scheduler lock while holding its own. Every FleetFrontend method
snapshots routing state under ``_lock``, releases, and only then calls
into a replica frontend (``submit`` / ``evict_queued`` /
``load_snapshot`` / ``close`` all take ``AsyncServeFrontend._lock``).
The committed ``concurrency_contracts.json`` lock graph must therefore
never contain a ``FleetFrontend._lock -> AsyncServeFrontend._lock``
edge; the gated defect at the bottom of this file proves the gate
notices one. The reverse direction cannot arise either: replica
resolution observers run outside the replica's lock by the scheduler's
own contract, so the fleet may take its router lock inside them.

Env knobs: ``AF2TPU_FLEET_TICK_S`` (health-pump period, default 0.05s)
and ``AF2TPU_FLEET_STEAL_MARGIN`` (queue-depth gap that triggers a
steal; 0 = auto from the engine's max_batch).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Callable, List, Optional, Sequence, Union
from urllib.request import urlopen

from alphafold2_tpu.observe import EventCounters, Tracer
from alphafold2_tpu.observe.exposition import MetricsHTTPServer
from alphafold2_tpu.observe.slo import SLOMonitor, aggregate_slo_verdicts
from alphafold2_tpu.observe.tracectx import TraceContext
from alphafold2_tpu.serve.bucketing import bucket_for
from alphafold2_tpu.serve.engine import ServeRequest, ServeResult, _as_request
from alphafold2_tpu.serve.faults import FleetFaultPlan
from alphafold2_tpu.serve.scheduler import AsyncServeFrontend, PendingResult

# the internal rejection reasons that mean "this replica gave the request
# back to the router" (steal / drain), never "the fleet turned it away" —
# the router re-submits these instead of resolving the caller
STOLEN_ERROR = "stolen by fleet router"
_REROUTE_ERRORS = (STOLEN_ERROR, "frontend closed")


def fleet_counter_zeros(replicas: int) -> dict:
    """Every fleet counter at zero — merged UNDER live snapshots on the
    Prometheus exposition so a counter that never fired still exports
    (absent-at-zero reads as a dead exporter; same fix as the PR-13
    variant-scan counters)."""
    zeros = {
        "fleet.submitted": 0,
        "fleet.routed": 0,
        "fleet.rerouted": 0,
        "fleet.steals": 0,
        "fleet.drains": 0,
        "fleet.replica_deaths": 0,
        "fleet.degraded": 0,
        "fleet.no_replica": 0,
        "fleet.resolved": 0,
        "fleet.resolved_ok": 0,
        "fleet.pump_errors": 0,
    }
    for i in range(replicas):
        zeros[f"fleet.replica{i}.routed"] = 0
        zeros[f"fleet.replica{i}.resolved_ok"] = 0
    return zeros


@dataclasses.dataclass
class ReplicaCell:
    """One serving cell: an engine plus its async frontend. Immutable
    after fleet construction (liveness lives in the router's guarded
    ``_alive`` list, not here, so cell reads need no lock)."""

    index: int
    engine: object
    frontend: AsyncServeFrontend
    metrics: Optional[MetricsHTTPServer] = None


@dataclasses.dataclass
class _Tracked:
    """Router-side record of one accepted request's journey. Mutable
    fields (``replica``, ``attempts``) are written under the router
    lock only."""

    tid: str  # the ROUTER-side trace_id: the tracking key for life
    handle: PendingResult
    req: ServeRequest
    priority: int
    submitted: float  # router-clock admit timestamp
    deadline_at: Optional[float]  # absolute router-clock deadline
    replica: Optional[int] = None
    attempts: int = 0  # reroutes so far (bounds the retry loop)


class FleetFrontend:
    """Load-aware router over N replica cells.

    >>> fleet = FleetFrontend.build(cfg, replicas=2)
    >>> handle = fleet.submit("MKTAYIAK...", deadline_s=5.0)
    >>> result = handle.result(timeout=60)   # structured, never raises
    >>> fleet.close()

    ``engines`` supplies one (built) engine per replica — share params
    across them (``FleetFrontend.build`` does) so N replicas initialize
    once. ``start=False`` skips the replica dispatcher threads AND the
    health pump; tests then drive :meth:`pump_replicas` /
    :meth:`pump_health` inline against an injected ``clock``.
    ``metrics_ports`` (one port per replica, 0 = ephemeral) additionally
    exposes each replica's ``/metrics`` + ``/healthz`` scrape surface
    and makes the health pump poll ``/healthz`` for liveness — the
    telemetry plane as the fleet's health substrate.
    """

    #: score discount for a replica holding a joinable in-flight
    #: formation of the request's bucket (fleet-level continuous
    #: batching: the arrival will ride the partially filled batch)
    forming_bonus = 0.75
    #: consecutive failed /healthz polls before a replica is declared
    #: dead and drained
    health_strikes_limit = 2

    def __init__(
        self,
        engines: Sequence,
        clock: Optional[Callable[[], float]] = None,
        tracer: Optional[Tracer] = None,
        slo_specs: Optional[list] = None,
        counters: Optional[EventCounters] = None,
        fault: Optional[FleetFaultPlan] = None,
        steal_margin: Optional[int] = None,
        tick_s: Optional[float] = None,
        max_reroutes: Optional[int] = None,
        metrics_ports: Optional[Sequence[int]] = None,
        start: bool = True,
    ):
        if not engines:
            raise ValueError("FleetFrontend needs at least one engine")
        self._clock = clock if clock is not None else time.perf_counter
        self.tracer = (
            tracer if tracer is not None
            else getattr(engines[0], "tracer", None) or Tracer(enabled=False)
        )
        self.counters = counters if counters is not None else EventCounters()
        self._fault = fault
        self.tick_s = (
            float(tick_s) if tick_s is not None
            else float(os.environ.get("AF2TPU_FLEET_TICK_S", "0.05"))
        )
        margin = (
            int(steal_margin) if steal_margin is not None
            else int(os.environ.get("AF2TPU_FLEET_STEAL_MARGIN", "0"))
        )
        max_batch = max(
            1, int(getattr(engines[0], "max_batch", 1) or 1)
        )
        # auto margin: a gap worth at least two formations before the
        # router starts moving work around (stealing a single request
        # just trades one dwell window for another)
        self.steal_margin = margin if margin > 0 else max(2, 2 * max_batch)
        self.max_reroutes = (
            int(max_reroutes) if max_reroutes is not None
            else 2 * len(engines) + 4
        )
        self._cells: List[ReplicaCell] = []
        self._slo_monitors: List[SLOMonitor] = []
        for i, engine in enumerate(engines):
            fe = AsyncServeFrontend(
                engine, clock=clock, tracer=self.tracer, start=start
            )
            server = None
            if metrics_ports is not None:
                server = MetricsHTTPServer(
                    self._make_collect(i, engine, fe),
                    port=int(metrics_ports[i]),
                ).start()
            self._cells.append(ReplicaCell(
                index=i, engine=engine, frontend=fe, metrics=server,
            ))
            fe.add_observer(self._make_on_result(i))
            if slo_specs:
                # one monitor per replica from the ONE spec list: the
                # AF2TPU_SLO_SPECS fan-out. Each gets its own registry so
                # replica windows never merge; aggregate via slo_summary.
                self._slo_monitors.append(SLOMonitor(
                    list(slo_specs), clock=self._clock, tracer=self.tracer,
                ))
        self._lock = threading.Lock()
        self._routed: dict = {}  # router trace_id -> _Tracked
        self._alive: list = [True] * len(self._cells)
        self._health_strikes: dict = {}  # replica index -> failed polls
        self._rr = 0  # round-robin tiebreak cursor
        self._closing = False
        self._t0 = self._clock()
        self._stop_event = threading.Event()
        self._pump_thread: Optional[threading.Thread] = None
        if start:
            self.start()

    @classmethod
    def build(
        cls,
        cfg,
        replicas: int,
        params=None,
        checkpoint_dir: Optional[str] = None,
        mesh=None,
        **kw,
    ) -> "FleetFrontend":
        """Construct ``replicas`` ServeEngines sharing ONE parameter set
        (replica 0 initializes or loads; the rest alias its params — N
        replicas never re-initialize N times) and wrap them in a fleet."""
        from alphafold2_tpu.serve.engine import ServeEngine

        engines: list = []
        for _ in range(max(1, int(replicas))):
            engines.append(ServeEngine(
                cfg,
                params=params if params is not None else (
                    engines[0].params if engines else None
                ),
                checkpoint_dir=checkpoint_dir if not engines else None,
                tracer=kw.get("tracer"),
                mesh=mesh,
            ))
        return cls(engines, **kw)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        if self._pump_thread is not None:
            return
        self._pump_thread = threading.Thread(
            target=self._pump_loop, name="af2-fleet-health", daemon=True
        )
        self._pump_thread.start()

    def close(self, timeout: float = 30.0) -> None:
        """Stop the health pump, close every live replica (their queued
        leftovers resolve as structured rejections through the normal
        observer path), and sweep any handle still tracked."""
        with self._lock:
            self._closing = True
        self._stop_event.set()
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=5.0)
            self._pump_thread = None
        with self._lock:
            alive = [i for i, a in enumerate(self._alive) if a]
        for i in alive:
            self._cells[i].frontend.close(timeout=timeout)
        with self._lock:
            leftovers = list(self._routed.values())
            self._routed.clear()
        for t in leftovers:
            if not t.handle.done():
                t.handle._resolve(ServeResult(
                    seq=t.req.seq, bucket=0, status="rejected",
                    error="fleet closed",
                    trace_id=t.req.trace.trace_id if t.req.trace else None,
                ))
        for cell in self._cells:
            if cell.metrics is not None:
                try:
                    cell.metrics.stop()
                except Exception:
                    pass

    def __enter__(self) -> "FleetFrontend":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------ telemetry

    def _make_collect(self, index: int, engine, fe: AsyncServeFrontend):
        def _collect() -> dict:
            snap = fe.load_snapshot()
            return {
                **engine.counters.snapshot(),
                "sched.depth": snap["depth"],
                "sched.inflight": snap["inflight"],
                "replica": index,
            }
        return _collect

    def replica_alive(self, index: int) -> bool:
        with self._lock:
            return bool(self._alive[index])

    def alive_replicas(self) -> list:
        with self._lock:
            return [i for i, a in enumerate(self._alive) if a]

    @property
    def replicas(self) -> int:
        return len(self._cells)

    @property
    def cells(self) -> tuple:
        return tuple(self._cells)

    @property
    def depth(self) -> int:
        return sum(c.frontend.load_snapshot()["depth"] for c in self._cells)

    def stats(self) -> dict:
        return self.counters.snapshot()

    def snapshot(self) -> dict:
        """Zero-seeded fleet counters + live per-replica depth/liveness —
        the exposition collect payload (every counter present from the
        first scrape, mirroring the PR-13 absent-at-zero fix)."""
        out = fleet_counter_zeros(len(self._cells))
        out.update(self.counters.snapshot())
        for cell in self._cells:
            snap = cell.frontend.load_snapshot()
            out[f"fleet.replica{cell.index}.depth"] = snap["depth"]
            out[f"fleet.replica{cell.index}.inflight"] = snap["inflight"]
            out[f"fleet.replica{cell.index}.alive"] = int(
                self.replica_alive(cell.index)
            )
        return out

    def histogram_snapshots(self, unit_scale: float = 1.0) -> dict:
        """Per-replica scheduler histograms, replica-prefixed — drop-in
        for the bench paths that snapshot a single frontend's."""
        out: dict = {}
        for cell in self._cells:
            for name, snap in cell.frontend.histogram_snapshots(
                unit_scale
            ).items():
                out[f"replica{cell.index}.{name}"] = snap
        return out

    def slo_summary(self) -> dict:
        """Per-replica SLO verdicts plus the fleet-aggregated burn (event
        -weighted across replicas). Empty when no specs were given."""
        if not self._slo_monitors:
            return {}
        per = [m.evaluate() for m in self._slo_monitors]
        return {
            "replicas": per,
            "fleet": aggregate_slo_verdicts(per),
        }

    # --------------------------------------------------------------- submit

    def submit(
        self,
        request: Union[str, ServeRequest],
        priority: Optional[int] = None,
        deadline_s: Optional[float] = None,
    ) -> PendingResult:
        """Admit one request and route it; never blocks on a device,
        never raises for a servable-or-not decision. The returned handle
        resolves once the request reaches a terminal outcome on SOME
        replica — internal steal/drain bounces are invisible to the
        caller beyond latency."""
        req = _as_request(request)
        now = self._clock()
        if priority is None:
            priority = req.priority
        if deadline_s is None:
            deadline_s = req.deadline_s
        handle = PendingResult(req)
        tctx = req.trace
        self.counters.bump("fleet.submitted")
        # the router half of the cross-replica chain: this event carries
        # the request's ROOT span, which the replica-side lifecycle
        # (minted from the traceparent hop) parents onto
        self.tracer.instant(
            "fleet.admit",
            **(tctx.event_args() if tctx is not None else {}),
        )
        tracked = _Tracked(
            tid=tctx.trace_id if tctx is not None else "",
            handle=handle, req=req, priority=int(priority or 0),
            submitted=now,
            deadline_at=(now + deadline_s) if deadline_s else None,
        )
        with self._lock:
            closing = self._closing
            if not closing:
                self._routed[tracked.tid] = tracked
        if closing:
            handle._resolve(ServeResult(
                seq=req.seq, bucket=0, status="rejected",
                error="fleet closed",
                trace_id=tctx.trace_id if tctx is not None else None,
            ))
            return handle
        self._route(tracked, exclude=None)
        return handle

    def _route(self, tracked: _Tracked, exclude: Optional[int]) -> None:
        """Pick a replica and hand the request over. Runs WITHOUT the
        router lock held (the lock-order rule: replica ``submit`` takes
        the replica's scheduler lock)."""
        req = tracked.req
        now = self._clock()
        if tracked.deadline_at is not None and now >= tracked.deadline_at:
            wait = max(0.0, now - tracked.submitted)
            self._finish(tracked, ServeResult(
                seq=req.seq, bucket=0, status="deadline_exceeded",
                error=(
                    f"deadline passed after {wait:.4g}s "
                    "(expired while rerouting)"
                ),
                latency_s=wait, queue_wait_s=wait,
            ), replica=None)
            return
        try:
            bucket = bucket_for(
                len(req.seq), self._cells[0].engine.buckets
            ) if req.seq else None
        except ValueError:
            bucket = None  # unservable: the replica rejects structurally
        index = self._pick_replica(bucket, exclude)
        if index is None:
            self.counters.bump("fleet.no_replica")
            self._finish(tracked, ServeResult(
                seq=req.seq, bucket=bucket or 0, status="rejected",
                error="no alive replicas",
            ), replica=None)
            return
        with self._lock:
            tracked.replica = index
        # the hop: W3C header round-trip; the replica-side lifecycle is a
        # child of the router root, so ONE trace spans both sides
        hop = (
            TraceContext.from_traceparent(req.trace.traceparent()).child()
            if req.trace is not None else None
        )
        replica_req = dataclasses.replace(req, trace=hop, arrival_s=None)
        self.counters.bump("fleet.routed")
        self.counters.bump(f"fleet.replica{index}.routed")
        self.tracer.instant(
            "fleet.route", replica=index,
            **({"bucket": bucket} if bucket is not None else {}),
            **(req.trace.child().event_args()
               if req.trace is not None else {}),
        )
        remaining = None
        if tracked.deadline_at is not None:
            remaining = max(1e-3, tracked.deadline_at - now)
        self._cells[index].frontend.submit(
            replica_req, priority=tracked.priority, deadline_s=remaining
        )

    def _pick_replica(
        self, bucket: Optional[int], exclude: Optional[int]
    ) -> Optional[int]:
        """Lowest load score wins: queued depth + half the in-flight
        batches, minus a bonus when the replica holds a joinable
        formation of this bucket. Round-robin breaks exact ties so an
        idle fleet stripes instead of piling on replica 0."""
        with self._lock:
            alive = [i for i, a in enumerate(self._alive) if a]
            rr = self._rr
            self._rr += 1
        candidates = [i for i in alive if i != exclude] or alive
        if not candidates:
            return None
        n = len(self._cells)
        best = None
        for i in candidates:
            snap = self._cells[i].frontend.load_snapshot()
            if snap["closed"]:
                continue
            score = snap["depth"] + 0.5 * snap["inflight"]
            if bucket is not None and bucket in snap["forming"]:
                score -= self.forming_bonus
            key = (score, (i - rr) % n)
            if best is None or key < best[0]:
                best = (key, i)
        return best[1] if best is not None else None

    # ------------------------------------------------------- result routing

    def _make_on_result(self, index: int):
        def _on_result(result, priority):
            self._on_replica_result(index, result, priority)
        return _on_result

    def _on_replica_result(
        self, index: int, result: ServeResult, priority: int
    ) -> None:
        """Replica resolution hook (runs on replica worker threads,
        outside the replica's scheduler lock). Internal give-backs
        (steal / drain / a route that raced a replica close) re-route;
        everything else is terminal for the caller."""
        tid = result.trace_id
        if not tid:
            return
        with self._lock:
            tracked = self._routed.get(tid)
            if tracked is None:
                return  # not fleet-routed (or already finished)
            reroute = (
                result.status == "rejected"
                and result.error in _REROUTE_ERRORS
                and not self._closing
                and tracked.attempts < self.max_reroutes
                and any(
                    a for i, a in enumerate(self._alive) if i != index
                )
            )
            if reroute:
                tracked.attempts += 1
        if not reroute:
            self._finish(tracked, result, replica=index)
            return
        self.counters.bump("fleet.rerouted")
        self.tracer.instant(
            "fleet.reroute", from_replica=index, reason=result.error,
            **(tracked.req.trace.child().event_args()
               if tracked.req.trace is not None else {}),
        )
        self._route(tracked, exclude=index)

    def _finish(
        self, tracked: _Tracked, result: ServeResult,
        replica: Optional[int],
    ) -> None:
        """Terminal resolution: untrack, account, feed the producing
        replica's SLO monitor, release the caller."""
        with self._lock:
            self._routed.pop(tracked.tid, None)
        self.counters.bump("fleet.resolved")
        if result.status == "ok":
            self.counters.bump("fleet.resolved_ok")
            if replica is not None:
                self.counters.bump(f"fleet.replica{replica}.resolved_ok")
        if replica is not None and self._slo_monitors:
            self._slo_monitors[replica].observe(result, tracked.priority)
        if tracked.tid and result.trace_id != tracked.tid:
            result = dataclasses.replace(result, trace_id=tracked.tid)
        tracked.handle._resolve(result)

    # --------------------------------------------------------------- health

    def kill_replica(
        self, index: int, reason: str = "killed", timeout: float = 30.0
    ) -> bool:
        """Declare a replica dead and drain it: no new routes land on it,
        its dispatched batches complete and resolve normally, and its
        non-dispatched queued work resolves as internal rejections that
        re-route to the survivors — zero accepted requests dropped.
        Returns False when the replica was already dead."""
        with self._lock:
            if not (0 <= index < len(self._cells)) or not self._alive[index]:
                return False
            self._alive[index] = False
        self.counters.bump("fleet.replica_deaths")
        self.counters.bump("fleet.drains")
        self.tracer.instant("fleet.drain", replica=index, reason=reason)
        cell = self._cells[index]
        cell.frontend.close(timeout=timeout)
        closer = getattr(cell.engine, "close", None)
        if closer is not None:
            try:
                closer()
            except Exception:
                pass
        if cell.metrics is not None:
            try:
                cell.metrics.stop()
            except Exception:
                pass
        return True

    def degrade_replica(self, index: int, delay_s: float) -> None:
        """Install a delay-only match-all fault plan on one replica's
        engine: every dispatch there slows by ``delay_s`` — the slow
        replica the load-aware router (and the steal pass) route
        around."""
        from alphafold2_tpu.serve.faults import FaultPlan

        self._cells[index].engine.faults = FaultPlan(
            match_all=True, fail=False, delay_s=float(delay_s), times=0,
            message="fleet degrade",
        )
        self.counters.bump("fleet.degraded")
        self.tracer.instant(
            "fleet.degrade", replica=index, delay_s=float(delay_s)
        )

    def pump_replicas(self) -> int:
        """Inline scheduling pass over every live replica (tests with
        ``start=False`` drive formation deterministically through
        this). Returns total dispatches executed."""
        with self._lock:
            alive = [i for i, a in enumerate(self._alive) if a]
        return sum(self._cells[i].frontend.pump() for i in alive)

    def pump_health(self) -> dict:
        """One health pass: fire due replica faults, poll ``/healthz``
        liveness (when exposed), and run the steal pass. The background
        pump calls this every ``tick_s``; tests call it inline."""
        now = self._clock()
        summary: dict = {"killed": None, "degraded": None, "stolen": 0}
        fault = self._fault
        if fault is not None:
            action = fault.take(now - self._t0)
            if action == "kill":
                if self.kill_replica(fault.replica, reason="fault"):
                    summary["killed"] = fault.replica
            elif action == "degrade":
                self.degrade_replica(fault.replica, fault.degrade_s)
                summary["degraded"] = fault.replica
        for cell in self._cells:
            if cell.metrics is None or not self.replica_alive(cell.index):
                continue
            healthy = self._poll_healthz(cell)
            with self._lock:
                strikes = (
                    0 if healthy
                    else self._health_strikes.get(cell.index, 0) + 1
                )
                self._health_strikes[cell.index] = strikes
            if strikes >= self.health_strikes_limit:
                if self.kill_replica(cell.index, reason="healthz"):
                    summary["killed"] = cell.index
        summary["stolen"] = self._steal_pass()
        return summary

    def _poll_healthz(self, cell: ReplicaCell) -> bool:
        try:
            with urlopen(
                f"http://127.0.0.1:{cell.metrics.port}/healthz",
                timeout=1.0,
            ) as resp:
                return resp.status == 200
        except Exception:
            return False

    def _steal_pass(self) -> int:
        """Move work from the deepest queue to the fleet when the gap to
        the shallowest exceeds ``steal_margin``: evict the newest,
        lowest-priority half of the gap; each eviction re-routes through
        the normal observer path and lands on the least-loaded
        survivor."""
        with self._lock:
            alive = [i for i, a in enumerate(self._alive) if a]
        if len(alive) < 2:
            return 0
        loads = [
            (i, self._cells[i].frontend.load_snapshot()["depth"])
            for i in alive
        ]
        busy = max(loads, key=lambda t: t[1])
        idle = min(loads, key=lambda t: t[1])
        gap = busy[1] - idle[1]
        if gap <= self.steal_margin:
            return 0
        moved = self._cells[busy[0]].frontend.evict_queued(
            max(1, gap // 2), reason=STOLEN_ERROR
        )
        if moved:
            self.counters.bump("fleet.steals", moved)
            self.tracer.instant(
                "fleet.steal", from_replica=busy[0],
                to_replica=idle[0], n=moved,
            )
        return moved

    def _pump_loop(self) -> None:
        while not self._stop_event.wait(self.tick_s):
            try:
                self.pump_health()
            except Exception:
                # the health pump must never take the fleet down; the
                # counter makes a wedged pump visible on the scrape
                self.counters.bump("fleet.pump_errors")


def _audit_fleet_hold_router_lock(  # af2: gated-defect[AF2TPU_AUDIT_FLEET_LOCK]
    fleet: FleetFrontend, replica: AsyncServeFrontend
) -> None:
    """Seeded negative control for the fleet's lock-order rule.

    Never executed: the ``gated-defect`` marker keeps this out of the
    audit (and out of ``concurrency_contracts.json`` — contract
    computation always excludes gated defects) unless
    ``AF2TPU_AUDIT_FLEET_LOCK=1``, in which case the audit-path lock
    graph gains the FORBIDDEN edge — a replica scheduler lock acquired
    (via ``submit``) while the router lock is held. CI flips the env var
    and asserts ``--graph`` surfaces the new ``FleetFrontend._lock ->
    AsyncServeFrontend._lock`` edge; no thread ever runs this.
    """
    with fleet._lock:
        replica.submit("ACDE")
