"""Shape-bucketed, batched inference engine over the end-to-end predict path.

``predict.predict()`` traces and compiles a fresh XLA program per distinct
sequence length and serves one request at a time. This engine is the serving
layer the ROADMAP north star needs instead:

- **Bucketing** — request lengths pad up a geometric ladder
  (``serve.buckets``), so at most ``len(buckets)`` executables ever exist.
- **Batching** — requests sharing a bucket are fused to ``serve.max_batch``
  per dispatch; partial chunks are batch-dim padded with fully-masked dummy
  slots (``serve.pad_batches``), keeping one executable per bucket.
- **Masked padding end to end** — the token-validity mask flows through the
  trunk attention, the distogram realization (zero MDS weight on pairs
  touching padding + padding-blind chirality statistic, utils/mds.py) and
  the SE(3) refiner, so padded positions cannot distort valid coordinates;
  the position-keyed MDS init makes the valid-region solve independent of
  bucket shape and batch slot.
- **Compile accounting** — an in-process executable cache (fronting the
  persistent XLA compilation cache wired in ``alphafold2_tpu/__init__``)
  counts traces/compiles/cache-hits through an ``observe.EventCounters``
  hook, so tests can assert "N mixed-length requests in one bucket ==
  exactly 1 compile" instead of trusting it.
- **Observability** — every request rides through nested ``observe.Tracer``
  spans (featurize → get_executable/compile → dispatch → device_get →
  unpad) emitted as Chrome-trace-event JSONL; per-request queue-wait and
  dispatch latency, batch occupancy and pad ratio stream into
  ``observe.Histogram`` distributions (p50/p95/p99 in ``bench.py --mode
  serve`` records); compile durations are recorded per (bucket, batch)
  shape in ``compile_records``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import warnings
from contextlib import nullcontext
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from alphafold2_tpu import constants
from alphafold2_tpu.config import Config
from alphafold2_tpu.data.pipeline import (
    featurize_bucketed_with_plan,
    featurize_delta,
)
from alphafold2_tpu.observe import (
    EventCounters,
    Histogram,
    MemorySampler,
    TraceContext,
    Tracer,
)
from alphafold2_tpu.observe import flightrec
from alphafold2_tpu.observe.flops import (
    attention_flops_attribution,
    executable_costs,
    executable_memory,
)
from alphafold2_tpu.parallel.sharding import (
    DATA_AXIS,
    describe_mesh,
    use_mesh,
)
from alphafold2_tpu.predict import encode_sequence
from alphafold2_tpu.serve.bucketing import bucket_for, validate_ladder
from alphafold2_tpu.serve.cache import FeatureCache, feature_key
from alphafold2_tpu.train.end2end import End2EndModel


@dataclasses.dataclass
class ServeRequest:
    """One inference request. ``seed`` drives the synthesized-MSA sampling
    (and nothing else), so identical (seq, seed) requests are reproducible
    whatever bucket or batch slot they land in.

    ``arrival_s`` is the request's own arrival timestamp on the
    ``time.perf_counter`` timebase: when present, queue-wait accounting is
    per request instead of per stream (requests dispatched in a later
    bucket no longer accrue earlier buckets' dispatch time as "queue
    wait"). The async frontend (serve/scheduler.py) stamps it at submit;
    ``priority`` and ``deadline_s`` (relative seconds, 0/None = none) are
    likewise scheduler inputs that ride with the request.

    ``trace`` is the request's :class:`~alphafold2_tpu.observe.tracectx.
    TraceContext`, minted at construction when the caller doesn't hand one
    in (an external frontend propagating a W3C traceparent would) — so
    every request owns a trace_id from birth and every lifecycle event the
    scheduler/engine emit is attributable to it."""

    seq: str
    seed: int = 0
    arrival_s: Optional[float] = None
    priority: int = 0
    deadline_s: Optional[float] = None
    # variant-scan hint: requests carrying the same parent_id belong to one
    # mutant family — the scheduler packs them into the same bucket
    # formation (parent-affinity batching) without having to rediscover the
    # family by edit distance. Optional: edit-distance-1 detection against
    # recent traffic covers unhinted scans.
    parent_id: Optional[str] = None
    trace: Optional[TraceContext] = dataclasses.field(
        default=None, compare=False, repr=False
    )

    def __post_init__(self):
        if self.trace is None:
            self.trace = TraceContext.new()


@dataclasses.dataclass
class ServeResult:
    """One request's outcome. ``status`` is the structured failure
    taxonomy: ``"ok"`` (arrays populated), ``"error"`` (dispatch raised —
    converted, never propagated, so a batch partner's poison pill cannot
    crash the caller), ``"rejected"`` (admission control turned the request
    away; ``retry_after_s`` hints when to come back), or
    ``"deadline_exceeded"`` (the request's deadline passed while queued).
    Non-``ok`` results carry ``None`` arrays and an ``error`` message."""

    seq: str
    bucket: int
    atom14: Optional[np.ndarray] = None  # (L, 14, 3) refined all-atom coords
    backbone: Optional[np.ndarray] = None  # (L, 3, 3) N/CA/C
    weights: Optional[np.ndarray] = None  # (3L, 3L) distogram confidence
    distogram: Optional[np.ndarray] = None  # (3L, 3L, K) logits if requested
    latency_s: float = 0.0  # queue wait + dispatch: what a caller observes
    queue_wait_s: float = 0.0  # time between arrival and dispatch start
    dispatch_s: float = 0.0  # device execution + result fetch of the batch
    status: str = "ok"  # "ok" | "error" | "rejected" | "deadline_exceeded"
    error: Optional[str] = None  # failure detail for non-"ok" statuses
    retry_after_s: Optional[float] = None  # backoff hint on "rejected"
    cache_hit: bool = False  # served from the result cache / in-flight dedup
    retried: bool = False  # produced by the scheduler's retry dispatch
    trace_id: Optional[str] = None  # the owning request's trace identity
    # featurization-reuse ledger entry: how this request's input tree was
    # produced — "miss" (cold featurize), "hit" (FeatureCache), "delta"
    # (column-patched from a cached parent). None on non-dispatched
    # results (rejected / deadline / result-cache hits).
    feat_reuse: Optional[str] = None
    # per-request cost ledger: the request's even share of the batch it
    # rode in — queue_wait_s, device_share_s (dispatch wall over real
    # members), compile_share_s (executable compile seconds amortized
    # over that executable's dispatches so far, then split), flops_share
    # (analytic executable flops over real members), pad_fraction (the
    # batch rectangle's padded slots+residues fraction). None on
    # non-dispatched results.
    cost: Optional[dict] = None

    @property
    def ok(self) -> bool:
        return self.status == "ok"


def _as_request(r: Union[str, ServeRequest]) -> ServeRequest:
    return r if isinstance(r, ServeRequest) else ServeRequest(seq=r)


class ServeEngine:
    """Synchronous bucketed/batched inference engine.

    >>> engine = ServeEngine(cfg)
    >>> results = engine.predict_many(["ACDEFGH...", "MKV..."])

    ``counters`` (observe.EventCounters) accumulates:
    ``serve.requests``, ``serve.batches``, ``serve.traces`` (python trace
    executions), ``serve.compiles`` (XLA executable builds),
    ``serve.cache_hits`` (dispatches served by an already-built
    executable), ``serve.padded_slots`` / ``serve.padded_residues``
    (batch-dim / length-dim padding waste).

    ``tracer`` (observe.Tracer) receives the request-lifecycle spans; the
    default is a disabled tracer (near-zero overhead). ``histograms``
    (name -> observe.Histogram) streams ``latency_s`` / ``queue_wait_s`` /
    ``dispatch_s`` (seconds) and ``batch_occupancy`` / ``pad_ratio``
    (fractions); ``compile_records`` lists every XLA build as
    ``{"bucket", "batch", "seconds"}``.
    """

    def __init__(
        self,
        cfg: Config,
        params=None,
        checkpoint_dir: Optional[str] = None,
        counters: Optional[EventCounters] = None,
        tracer: Optional[Tracer] = None,
        faults=None,
        mesh: Optional[Mesh] = None,
    ):
        # faults: an optional serve.faults.FaultPlan consulted at the top of
        # every dispatch — the injection point that makes the scheduler's
        # retry and graceful-degradation paths testable
        self.faults = faults
        self.cfg = cfg
        # mesh: an optional jax device mesh ((dp, sp) from
        # parallel.sharding.make_mesh or (dp, spr, spc) from
        # parallel.grid_parallel.make_grid_mesh). With one, every
        # executable is AOT-compiled sharded (batch over dp, the pair grid
        # over the sequence axes via the model's shard_pair constraints)
        # and dispatch device_puts with explicit shardings; without one the
        # engine is the unchanged single-device path. The mesh identity is
        # part of the executable cache key, so one engine could in
        # principle be rebuilt against a different mesh without stale hits.
        self.mesh = mesh
        self.mesh_desc = describe_mesh(mesh)
        self.buckets = validate_ladder(cfg.serve.buckets)
        self.long_buckets: tuple = ()
        if cfg.serve.long_buckets:
            long = validate_ladder(cfg.serve.long_buckets)
            if mesh is None:
                # the mesh gate: long-chain rungs' O(N^2) pair state is
                # exactly what a single device cannot hold — refuse them
                # loudly instead of OOMing mid-dispatch
                raise ValueError(
                    f"serve.long_buckets={long} require a device mesh: "
                    "the long-chain rungs are mesh-gated (construct "
                    "ServeEngine with mesh=..., e.g. "
                    "parallel.grid_parallel.make_grid_mesh)"
                )
            if long[0] <= self.buckets[-1]:
                raise ValueError(
                    f"serve.long_buckets {long} must all exceed the top "
                    f"regular rung {self.buckets[-1]}"
                )
            self.long_buckets = long
            self.buckets = self.buckets + long
        self.max_batch = int(cfg.serve.max_batch)
        self.long_max_batch = int(cfg.serve.long_max_batch)
        if self.max_batch < 1:
            raise ValueError(f"serve.max_batch must be >= 1, got {self.max_batch}")
        if self.long_buckets and self.long_max_batch < 1:
            raise ValueError(
                f"serve.long_max_batch must be >= 1, got {self.long_max_batch}"
            )
        if 3 * self.buckets[-1] > cfg.model.max_seq_len:
            raise ValueError(
                f"largest bucket {self.buckets[-1]} elongates to "
                f"{3 * self.buckets[-1]} tokens > model.max_seq_len="
                f"{cfg.model.max_seq_len}; raise it or trim serve.buckets"
            )
        if mesh is not None:
            self._validate_mesh(mesh, cfg)
        self.msa_depth = int(cfg.serve.msa_depth or cfg.data.msa_depth)
        if self.msa_depth > constants.MAX_NUM_MSA:
            raise ValueError(
                f"serve msa_depth={self.msa_depth} exceeds MAX_NUM_MSA="
                f"{constants.MAX_NUM_MSA}"
            )
        # serving precision mode: "bfloat16" casts params at build (below)
        # and switches the compute dtype; proven against stated per-layer
        # drift bounds in tests/test_precision.py, fingerprinted as its own
        # graph-contract target (analysis/targets.py serve_fwd_bf16)
        self.serve_dtype = str(cfg.serve.dtype or "float32")
        if self.serve_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"serve.dtype must be 'float32' or 'bfloat16', got "
                f"{self.serve_dtype!r}"
            )
        # kernel policy (ops/kernels.py): a per-engine spec wins over the
        # process default; the RESOLVED identity keys every executable this
        # engine builds (cache key, compile records, bench records)
        from alphafold2_tpu.ops.kernels import current_policy, parse_policy

        self.kernel_policy = (
            parse_policy(cfg.serve.kernels) if cfg.serve.kernels else None
        )
        self.kernels_desc = (
            self.kernel_policy if self.kernel_policy is not None
            else current_policy()
        ).describe()
        self.counters = counters if counters is not None else EventCounters()
        self.tracer = tracer if tracer is not None else Tracer(enabled=False)
        self.memory = MemorySampler()
        self.histograms = {
            "latency_s": Histogram(),
            "queue_wait_s": Histogram(),
            "dispatch_s": Histogram(),
            "batch_occupancy": Histogram(),
            "pad_ratio": Histogram(),
        }
        self.compile_records: list = []
        # flops of every executed dispatch (observe.flops cost analysis of
        # the executable that carried it): the serve bench's MFU numerator.
        # The breakdown accumulates the analytical per-kernel attribution
        # (tied-row vs axial vs rest) so MFU deltas name the kernel.
        self.executed_flops: float = 0.0
        self.executed_flops_breakdown: dict = {}
        self._exe_flops: dict = {}
        self._exe_breakdown: dict = {}
        # per-executable compile seconds + dispatch counts: the cost
        # ledger's amortized-compile denominator (compile_s / dispatches,
        # so early dispatches carry more of the build than late ones)
        self._exe_compile_s: dict = {}
        self._exe_dispatches: dict = {}
        if self.serve_dtype == "bfloat16":
            compute_dtype = jnp.bfloat16
        else:
            compute_dtype = (
                jnp.bfloat16 if cfg.model.bfloat16 else jnp.float32
            )
        self.model = End2EndModel(
            dim=cfg.model.dim, depth=cfg.model.depth, heads=cfg.model.heads,
            dim_head=cfg.model.dim_head, max_seq_len=cfg.model.max_seq_len,
            mds_iters=cfg.serve.mds_iters,
            mds_per_position_init=True,
            remat=cfg.model.remat, msa_tie_row_attn=cfg.model.msa_tie_row_attn,
            context_parallel=cfg.model.context_parallel,
            grid_parallel=cfg.model.grid_parallel,
            dtype=compute_dtype,
        )
        self.params = self._init_params(params, checkpoint_dir)
        if self.serve_dtype == "bfloat16":
            # cast float params ONCE at build: weight memory halves and the
            # matmuls run bf16-in without per-dispatch casting. Checkpoints
            # stay f32 on disk; the cast is a serving-time decision whose
            # numerical safety observe/numerics drift bounds prove, not a
            # training-state change.
            self.params = jax.tree.map(
                lambda x: x.astype(jnp.bfloat16)
                if getattr(x, "dtype", None) == jnp.float32 else x,
                self.params,
            )
        self._mds_key = jax.random.key(cfg.train.seed)
        self._executables: dict = {}
        # the compile path and the flops accumulators are shared with the
        # pipeline's worker threads: double-checked locking on the
        # executable cache, a dedicated lock for executed-flops accounting
        self._compile_lock = threading.Lock()
        self._account_lock = threading.Lock()
        # params replicated onto the mesh once, reused by every sharded
        # dispatch (a sharded executable rejects differently-placed inputs)
        self._mesh_params = None
        # pipelined dispatch (serve/pipeline.py): depth batches in flight,
        # host featurize/device_put overlapping device compute overlapping
        # result fetch. 0 disables it (pure serial dispatch).
        self.pipeline_depth = int(cfg.serve.pipeline_depth)
        if self.pipeline_depth < 0:
            raise ValueError(
                f"serve.pipeline_depth must be >= 0, got {self.pipeline_depth}"
            )
        # variant-scan fast lane: content-addressed featurization reuse.
        # The FeatureCache holds featurized input trees keyed by their
        # derivation (seq, bucket, msa_depth, seed) with leaves interned by
        # content hash; delta featurization patches a point mutant's
        # columns out of a cached parent instead of recomputing the tree.
        fcap = int(cfg.serve.feature_cache_size)
        self.feature_cache = FeatureCache(fcap) if fcap > 0 else None
        self.delta_featurize = bool(cfg.serve.delta_featurize)
        self.pipeline = None
        if self.pipeline_depth > 0:
            from alphafold2_tpu.serve.pipeline import PipelinedDispatcher

            self.pipeline = PipelinedDispatcher(
                self, depth=self.pipeline_depth
            )

    @property
    def pipeline_desc(self) -> str:
        """The dispatch-path identity serve records carry (``"depth2"`` /
        ``"off"``) — regress.py refuses to compare across it, the same way
        mesh/dtype/kernels variants are fenced."""
        return (
            f"depth{self.pipeline_depth}" if self.pipeline is not None
            else "off"
        )

    def close(self) -> None:
        """Stop the pipeline stage workers (in-flight batches drain first)."""
        if self.pipeline is not None:
            self.pipeline.shutdown(wait=True)

    def _validate_mesh(self, mesh: Mesh, cfg: Config) -> None:
        from alphafold2_tpu.parallel.grid_parallel import (
            COL_AXIS_NAME,
            ROW_AXIS_NAME,
        )

        axes = dict(zip(mesh.axis_names, mesh.devices.shape))
        n_dp = axes.get(DATA_AXIS, 1)
        if self.max_batch % n_dp or (
            self.long_buckets and self.long_max_batch % n_dp
        ):
            raise ValueError(
                f"serve batch sizes (max_batch={self.max_batch}, "
                f"long_max_batch={self.long_max_batch}) must divide by the "
                f"mesh's dp axis ({n_dp}) for even batch sharding"
            )
        if ROW_AXIS_NAME in axes:
            if not cfg.model.grid_parallel:
                # same refusal as train/loop.py: without the sharded axial
                # primitive GSPMD all-gathers the attended axis and the
                # per-device memory win silently evaporates
                raise ValueError(
                    "a (dp, spr, spc) grid mesh requires "
                    "model.grid_parallel=true — without it the axial "
                    "passes run dense and the long-chain rungs lose their "
                    "O(N^2/(spr*spc)) per-device memory"
                )
            tile = axes[ROW_AXIS_NAME] * axes.get(COL_AXIS_NAME, 1)
            for b in self.buckets:
                if (3 * b) % tile:
                    raise ValueError(
                        f"bucket {b} elongates to {3 * b} pair rows, not "
                        f"divisible by the spr*spc tile ({tile}) the "
                        "all-to-all transposes need; adjust serve.buckets "
                        "or the mesh"
                    )

    def batch_for(self, bucket: int) -> int:
        """Dispatch batch size for one rung: long-chain rungs batch
        ``serve.long_max_batch`` (their per-request memory is what the mesh
        shards), everything else ``serve.max_batch``."""
        return (
            self.long_max_batch
            if bucket in self.long_buckets else self.max_batch
        )

    # ---------------------------------------------------------------- params

    def _init_params(self, params, checkpoint_dir):
        if params is not None:
            return params
        # params depend only on the model config, not the request length:
        # init at a tiny fixed shape (no bucket-sized init compile)
        n, m = 4, max(1, min(2, self.msa_depth))
        tiny = {
            "seq": np.zeros((1, n), np.int32),
            "mask": np.ones((1, n), bool),
            "msa": np.zeros((1, m, n), np.int32),
            "msa_mask": np.ones((1, m, n), bool),
        }
        if checkpoint_dir:
            from alphafold2_tpu.train.checkpoint import CheckpointManager

            def init_fn():
                return self.model.init(
                    jax.random.key(self.cfg.train.seed),
                    jnp.asarray(tiny["seq"]), jnp.asarray(tiny["msa"]),
                    mask=jnp.asarray(tiny["mask"]),
                    msa_mask=jnp.asarray(tiny["msa_mask"]),
                )

            template = jax.eval_shape(init_fn)
            mgr = CheckpointManager(checkpoint_dir)
            try:
                restored, _ = mgr.restore_params(template)
            finally:
                mgr.close()
            return restored
        return self.model.init(
            jax.random.key(self.cfg.train.seed),
            jnp.asarray(tiny["seq"]), jnp.asarray(tiny["msa"]),
            mask=jnp.asarray(tiny["mask"]),
            msa_mask=jnp.asarray(tiny["msa_mask"]),
        )

    # ----------------------------------------------------------- executables

    def _fwd(self, params, seq, msa, mask, msa_mask):
        # python side effect: runs once per TRACE, never per dispatch — the
        # compile-count tests pin the executable cache's behavior on it,
        # so the per-trace firing is the point, not a bug
        self.counters.bump("serve.traces")  # af2: noqa[AF2L009]
        out = self.model.apply(
            params, seq, msa, mask=mask, msa_mask=msa_mask,
            mds_key=self._mds_key, deterministic=True,
        )
        picked = {"refined": out["refined"], "weights": out["weights"]}
        if self.cfg.serve.return_distogram:
            picked["distogram"] = out["distogram"]
        return picked

    def _get_executable(self, bucket: int, batch: int):
        """One compiled executable per (bucket, batch, mesh) shape, AOT-
        built. The mesh identity in the key is what lets sharded and
        single-device executables (and their compile records) coexist.

        The in-process dict makes reuse O(1); the persistent XLA compilation
        cache behind it (enable_compile_cache) makes even the first build of
        a known HLO a deserialization instead of a compile."""
        key = (bucket, batch, self.mesh_desc, self.serve_dtype,
               self.kernels_desc)
        hit = self._executables.get(key)
        if hit is not None:
            self.counters.bump("serve.cache_hits")
            return hit
        with self._compile_lock:
            return self._compile_executable(key, bucket, batch)

    def _compile_executable(self, key, bucket: int, batch: int):
        """Build + record one executable; caller holds ``_compile_lock``
        (the pipeline's device worker, the sync path and warmup can race
        to the same rung — exactly one of them compiles)."""
        hit = self._executables.get(key)
        if hit is not None:  # lost the race: the build already happened
            self.counters.bump("serve.cache_hits")
            return hit
        donate = (1, 2, 3, 4) if self.cfg.serve.donate_buffers else ()
        abstract = self._abstract_batch(bucket, batch)
        jit_kwargs: dict = {"donate_argnums": donate}
        if self.mesh is not None:
            # explicit input shardings: params replicated, every request
            # buffer batch-sharded over dp; the pair grid's sequence-axis
            # sharding comes from the model's shard_pair constraints traced
            # under the active mesh (parallel/sharding.py)
            rep = NamedSharding(self.mesh, P())
            dp = NamedSharding(self.mesh, P(DATA_AXIS))
            jit_kwargs["in_shardings"] = (rep, dp, dp, dp, dp)
        ctx = use_mesh(self.mesh) if self.mesh is not None else nullcontext()
        t0 = time.perf_counter()
        with self.tracer.span(
            "serve.compile", bucket=bucket, batch=batch,
            **({"mesh": self.mesh_desc} if self.mesh_desc else {}),
        ):
            # capture the compile's warnings instead of suppressing them
            # blind: the "Some donated buffers were not usable" notice is
            # expected (feature buffers are int/bool, outputs f32 coords —
            # XLA cannot ALIAS the donation; donating still lets the
            # runtime release the request buffers during execution, the
            # point on HBM-tight serving) and is STRUCTURED into the
            # compile record below so tests can assert the donation intent
            # actually reached XLA; everything else is re-emitted.
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                from alphafold2_tpu.ops.kernels import use_kernel_policy

                with ctx, use_kernel_policy(self.kernel_policy):
                    compiled = (
                        jax.jit(self._fwd, **jit_kwargs)
                        .lower(self.params, *abstract)
                        .compile()
                    )
        donation_notes = [
            w for w in caught
            if "donated buffers were not usable" in str(w.message)
        ]
        for w in caught:
            if w not in donation_notes:
                warnings.warn_explicit(
                    w.message, w.category, w.filename, w.lineno
                )
        self.counters.bump("serve.compiles")
        costs = executable_costs(compiled)  # flops/bytes via observe.flops
        self._exe_flops[key] = costs["flops"] or 0.0
        memory = executable_memory(compiled)  # per-device, via observe.flops
        # analytical per-kernel attribution at this executable's static
        # shapes (observe.flops): names the kernel responsible for an MFU
        # delta — pair-axial vs tied-row MSA vs everything else
        breakdown = attention_flops_attribution(
            batch=batch, pair_len=3 * bucket, msa_depth=self.msa_depth,
            msa_len=bucket, depth=self.cfg.model.depth,
            heads=self.cfg.model.heads, dim_head=self.cfg.model.dim_head,
            tie_rows=self.model.msa_tie_row_attn,
            total_flops=costs["flops"],
        )
        self._exe_breakdown[key] = breakdown
        collectives: dict = {}
        if self.mesh is not None:
            # census of the post-SPMD collectives XLA actually emitted for
            # this rung (analysis/hlo_audit.py) — the runtime counterpart of
            # the committed hlo_contracts.json; a rung whose census is empty
            # here is paying for a mesh it does not use
            try:
                from alphafold2_tpu.analysis.hlo_audit import (
                    collective_census,
                )

                collectives = collective_census(compiled.as_text())
            except Exception:  # census is diagnostics, never a serve fault
                collectives = {}
        self._exe_compile_s[key] = round(time.perf_counter() - t0, 4)
        self.compile_records.append({
            "bucket": bucket, "batch": batch,
            "seconds": self._exe_compile_s[key],
            # donation audit: how many argument buffers we asked XLA to
            # donate, and how many shapes XLA reported back as unaliasable
            # (counted off the warning text) — a silently-dropped donation
            # would show up as donated_args without any unusable report
            # AND without aliasing, which tests/test_serve_pipeline.py pins
            **({"donated_args": len(donate)} if donate else {}),
            **({"donation_unusable":
                str(donation_notes[0].message).count("ShapedArray")}
               if donate and donation_notes else {}),
            **({"mesh": self.mesh_desc} if self.mesh_desc else {}),
            # precision/kernel keys ride only when non-default so records
            # (and the committed baselines) predating them stay comparable
            **({"dtype": self.serve_dtype}
               if self.serve_dtype != "float32" else {}),
            **({"kernels": self.kernels_desc}
               if self.kernels_desc != "auto" else {}),
            **({"flops": costs["flops"]} if costs["flops"] else {}),
            **({"flops_breakdown": breakdown} if costs["flops"] else {}),
            **({"bytes_accessed": costs["bytes_accessed"]}
               if costs["bytes_accessed"] else {}),
            **({"collectives": collectives} if collectives else {}),
            **memory,
        })
        self._executables[key] = compiled
        return compiled

    def _sharded_params(self):
        """The replicated-on-mesh copy of ``self.params`` every sharded
        executable consumes (built once, cached)."""
        if self._mesh_params is None:
            self._mesh_params = jax.device_put(
                self.params, NamedSharding(self.mesh, P())
            )
        return self._mesh_params

    def _abstract_batch(self, bucket: int, batch: int):
        f32 = jax.ShapeDtypeStruct
        return (
            f32((batch, bucket), jnp.int32),  # seq
            f32((batch, self.msa_depth, bucket), jnp.int32),  # msa
            f32((batch, bucket), jnp.bool_),  # mask
            f32((batch, self.msa_depth, bucket), jnp.bool_),  # msa_mask
        )

    # --------------------------------------------------- dispatch stages
    # Shared by the serial path (_dispatch_inner) and the pipelined path
    # (serve/pipeline.py stage workers), so the two produce byte-identical
    # results by construction — same featurize, same stacking, same
    # executable, same fetch.

    def _padded_batch(self, bucket: int, n_real: int) -> int:
        """Batch-dim size a chunk of ``n_real`` requests dispatches at:
        padded to the bucket's batch target (serve.pad_batches) and rounded
        up to the mesh's dp multiple for even batch sharding."""
        batch = (
            self.batch_for(bucket) if self.cfg.serve.pad_batches else n_real
        )
        if self.mesh is not None:
            n_dp = dict(
                zip(self.mesh.axis_names, self.mesh.devices.shape)
            ).get(DATA_AXIS, 1)
            batch += (-batch) % n_dp
        return batch

    # hamming-distance ceiling for the delta path: column patching is
    # exact at ANY same-length edit count (each touched column is O(M)),
    # but past a handful of edits the request is no longer "a mutant of"
    # the parent in any traffic sense, so treat it as cold
    DELTA_MAX_EDITS = 8

    def _featurize_one(self, bucket: int, req: ServeRequest) -> tuple:
        """Featurize one request, via the content-addressed fast lane when
        possible. Returns ``(item, reuse)`` with ``reuse`` the per-request
        ledger entry: ``"hit"`` (exact derivation-key cache hit),
        ``"delta"`` (column-patched from a cached same-shape parent —
        byte-identical to cold, pinned by tests), or ``"miss"`` (cold
        featurize). Every dispatched request bumps exactly one of
        ``serve.feat_hits`` / ``serve.feat_delta`` / ``serve.feat_misses``,
        so the ledger always sums to the dispatched-request count."""
        tokens = encode_sequence(req.seq)[0]
        pad = bucket - len(req.seq)
        self.counters.bump("serve.padded_residues", pad)
        self.histograms["pad_ratio"].observe(pad / bucket)
        fc = self.feature_cache
        if fc is None:
            item, _ = featurize_bucketed_with_plan(
                tokens, bucket, self.msa_depth, seed=req.seed
            )
            self.counters.bump("serve.feat_misses")
            return item, "miss"
        key = feature_key(req.seq, bucket, self.msa_depth, req.seed)
        found = fc.lookup(key)
        if found is not None:
            self.counters.bump("serve.feat_hits")
            return found[0], "hit"
        if self.delta_featurize:
            for p_item, p_plan in fc.delta_parent(
                bucket, self.msa_depth, req.seed, len(req.seq)
            ):
                edits = int((p_plan["tokens"] != tokens).sum())
                if 0 < edits <= self.DELTA_MAX_EDITS:
                    item = featurize_delta(p_item, p_plan, tokens)
                    # the mutant inherits the parent's plan verbatim apart
                    # from its own tokens: the MSA mutation mask depends
                    # only on (seed, msa_len, depth), never on sequence
                    # content, so the mutant is itself a valid delta parent
                    # (scan chains stay warm even after the original parent
                    # ages out of the LRU)
                    plan = dict(p_plan)
                    plan["tokens"] = tokens.copy()
                    item = fc.put(key, item, plan)
                    self.counters.bump("serve.feat_delta")
                    return item, "delta"
        item, plan = featurize_bucketed_with_plan(
            tokens, bucket, self.msa_depth, seed=req.seed
        )
        item = fc.put(key, item, plan)
        self.counters.bump("serve.feat_misses")
        return item, "miss"

    def _dummy_item(self, bucket: int) -> dict:
        """A fully-masked batch-padding slot."""
        return {
            "seq": np.full(bucket, constants.AA_PAD_INDEX, np.int32),
            "mask": np.zeros(bucket, bool),
            "msa": np.full(
                (self.msa_depth, bucket), constants.AA_PAD_INDEX, np.int32
            ),
            "msa_mask": np.zeros((self.msa_depth, bucket), bool),
        }

    def _stack_host(self, bucket: int, items: list, batch: int) -> dict:
        full = items + [
            self._dummy_item(bucket) for _ in range(batch - len(items))
        ]
        return {k: np.stack([it[k] for it in full]) for k in full[0]}

    def _transfer(self, host: dict, dispatch_index: int, bucket: int):
        """Explicit host->device transfer: handing raw numpy to the
        executable would be an implicit transfer, which the transfer-guard
        test fixtures (tests/conftest.py) and
        ``jax.transfer_guard("disallow")`` deployments reject. Under a mesh
        the transfer carries its sharding explicitly — batch split over dp
        at the host boundary, never an all-replicated copy that GSPMD
        reshards later."""
        if self.faults is not None:
            self.faults.on_stage("transfer", dispatch_index, bucket)
        if self.mesh is not None:
            dp = NamedSharding(self.mesh, P(DATA_AXIS))
            return {k: jax.device_put(a, dp) for k, a in host.items()}
        return jax.device_put(host)

    def _execute_batch(self, compiled, stacked, dispatch_index, bucket):
        """Invoke the executable; under async dispatch (CPU and TPU alike)
        the call returns while XLA executes in the background — blocking
        is the fetch stage's job."""
        if self.faults is not None:
            self.faults.on_stage("compute", dispatch_index, bucket)
        params = (
            self._sharded_params() if self.mesh is not None else self.params
        )
        return compiled(
            params, stacked["seq"], stacked["msa"],
            stacked["mask"], stacked["msa_mask"],
        )

    def _fetch(self, out, dispatch_index, bucket):
        """ONE blocking device_get of the whole output tree (one transfer
        issued, not three serial ones), closing on device completion."""
        if self.faults is not None:
            self.faults.on_stage("fetch", dispatch_index, bucket)
        fetched = jax.device_get(out)
        refined = np.asarray(fetched["refined"])
        weights = np.asarray(fetched["weights"])
        disto = (
            np.asarray(fetched["distogram"])
            if "distogram" in fetched else None
        )
        return refined, weights, disto

    def _exe_key(self, bucket: int, batch: int) -> tuple:
        return (bucket, batch, self.mesh_desc, self.serve_dtype,
                self.kernels_desc)

    def _account_flops(self, exe_key) -> None:
        # executed-flops accumulators are shared with the pipeline's
        # completion worker, hence the lock
        with self._account_lock:
            self.executed_flops += self._exe_flops.get(exe_key, 0.0)
            self._exe_dispatches[exe_key] = (
                self._exe_dispatches.get(exe_key, 0) + 1
            )
            for kernel, flops in self._exe_breakdown.get(
                exe_key, {}
            ).items():
                self.executed_flops_breakdown[kernel] = (
                    self.executed_flops_breakdown.get(kernel, 0.0) + flops
                )

    def _request_cost(
        self, bucket: int, batch: int, n_real: int, real_residues: int,
        wait: float, dispatch_s: float,
    ) -> dict:
        """One request's even share of its batch — the per-request cost
        ledger (``ServeResult.cost``). Amortized compile uses this
        executable's compile seconds over its dispatch count SO FAR
        (``_account_flops`` runs first, so the divisor is >= 1): the first
        dispatch carries the whole build, the Nth carries 1/N of it."""
        exe_key = self._exe_key(bucket, batch)
        with self._account_lock:
            dispatches = max(1, self._exe_dispatches.get(exe_key, 1))
        compile_s = self._exe_compile_s.get(exe_key, 0.0)
        flops = self._exe_flops.get(exe_key, 0.0)
        rect = max(1, batch * bucket)
        return {
            "queue_wait_s": round(wait, 6),
            "device_share_s": round(dispatch_s / n_real, 6),
            "compile_share_s": round(compile_s / dispatches / n_real, 6),
            "flops_share": round(flops / n_real, 3),
            "pad_fraction": round(
                max(0, rect - real_residues) / rect, 4
            ),
        }

    def _build_results(
        self, bucket, reqs, waits, dispatch_s, refined, weights, disto,
        feat=None, batch=None,
    ) -> list:
        """Unpad/realize one batch's outputs into per-request results.
        ``feat`` (optional, slot-aligned) carries each request's
        featurization-reuse ledger entry onto its result; ``batch`` (the
        padded batch dimension) enables the per-request cost ledger."""
        built = []
        real_residues = sum(len(r.seq) for r in reqs)
        for slot, req in enumerate(reqs):
            L = len(req.seq)
            atom14 = refined[slot, :L]
            wait = max(0.0, waits[slot])
            latency = wait + dispatch_s
            self.histograms["latency_s"].observe(latency)
            built.append(ServeResult(
                seq=req.seq,
                bucket=bucket,
                atom14=atom14,
                backbone=atom14[:, :3],
                weights=weights[slot, : 3 * L, : 3 * L],
                distogram=(
                    disto[slot, : 3 * L, : 3 * L]
                    if disto is not None else None
                ),
                latency_s=latency,
                queue_wait_s=wait,
                dispatch_s=dispatch_s,
                trace_id=req.trace.trace_id if req.trace else None,
                feat_reuse=feat[slot] if feat is not None else None,
                cost=(
                    self._request_cost(
                        bucket, batch, len(reqs), real_residues,
                        wait, dispatch_s,
                    )
                    if batch else None
                ),
            ))
        return built

    def _error_results(self, bucket, reqs, waits, msg, dispatch_s) -> list:
        """Structured per-request error results for a failed batch (the
        scheduler retries them against a different executable)."""
        self.counters.bump("serve.dispatch_errors")
        rec = flightrec.active()
        if rec is not None:  # preserve the telemetry leading up to it
            rec.note(
                "dispatch_error", bucket=int(bucket), error=msg,
                n_real=len(reqs),
                trace_ids=[r.trace.trace_id for r in reqs if r.trace],
            )
            rec.dump("dispatch_error")  # once per process (deduped)
        return [
            ServeResult(
                seq=req.seq,
                bucket=bucket,
                status="error",
                error=msg,
                latency_s=max(0.0, waits[slot]) + dispatch_s,
                queue_wait_s=max(0.0, waits[slot]),
                dispatch_s=dispatch_s,
                trace_id=req.trace.trace_id if req.trace else None,
            )
            for slot, req in enumerate(reqs)
        ]

    # -------------------------------------------------------------- serving

    def predict_many(
        self, requests: Sequence[Union[str, ServeRequest]]
    ) -> list:
        """Serve a request list: group by bucket, batch, dispatch, unpad.

        Results come back in input order. Latency per request is the wall
        time of the dispatch that carried it (what a caller of a batched
        service observes)."""
        reqs = [_as_request(r) for r in requests]
        self.counters.bump("serve.requests", len(reqs))
        by_bucket: dict = {}
        for i, r in enumerate(reqs):
            if not r.seq:
                raise ValueError(f"request {i} has an empty sequence")
            b = bucket_for(len(r.seq), self.buckets)
            by_bucket.setdefault(b, []).append(i)

        results: list = [None] * len(reqs)
        arrival = time.perf_counter()  # queue-wait origin for this stream
        if self.pipeline is not None:
            # pipelined path: every chunk is submitted up front, so the
            # host stage featurizes/transfers batch N+1 while batch N
            # computes and batch N-1's results fetch; submit() blocks at
            # pipeline_depth in flight (backpressure), result() drains in
            # submission order
            handles = []
            for bucket in sorted(by_bucket):
                order = by_bucket[bucket]
                step = self.batch_for(bucket)
                for lo in range(0, len(order), step):
                    chunk = order[lo : lo + step]
                    handles.append((chunk, self.pipeline.submit(
                        bucket, [reqs[i] for i in chunk], arrival=arrival
                    )))
            for chunk, handle in handles:
                for idx, res in zip(chunk, handle.result()):
                    results[idx] = res
            return results
        for bucket in sorted(by_bucket):
            order = by_bucket[bucket]
            step = self.batch_for(bucket)
            for lo in range(0, len(order), step):
                chunk = order[lo : lo + step]
                self._dispatch(
                    bucket, [reqs[i] for i in chunk], chunk, results, arrival
                )
        return results

    def dispatch_batch(
        self, bucket: int, requests: Sequence[Union[str, ServeRequest]]
    ) -> list:
        """Dispatch one pre-formed batch at ``bucket`` and return its
        results in order. The async frontend (serve/scheduler.py) forms its
        own batches and calls this; per-request ``arrival_s`` stamps drive
        the queue-wait accounting. A dispatch failure yields structured
        ``status="error"`` results, never an exception."""
        reqs = [_as_request(r) for r in requests]
        results: list = [None] * len(reqs)
        self._dispatch(bucket, reqs, list(range(len(reqs))), results)
        return results

    def dispatch_batch_async(
        self,
        bucket: int,
        requests: Sequence[Union[str, ServeRequest]],
        joinable: bool = False,
    ):
        """Pipelined dispatch of one pre-formed batch: returns a
        :class:`~alphafold2_tpu.serve.pipeline.DispatchHandle` future over
        the ordered result list instead of blocking through featurize /
        compute / fetch. With ``joinable=True`` the batch stays open to
        ``handle.try_join(req)`` while its host stage runs — the
        scheduler's in-flight admission (continuous batching). Requires
        ``serve.pipeline_depth > 0``."""
        if self.pipeline is None:
            raise RuntimeError(
                "pipelined dispatch requires serve.pipeline_depth > 0"
            )
        return self.pipeline.submit(
            bucket, [_as_request(r) for r in requests], joinable=joinable
        )

    def retry_bucket(self, bucket: int) -> Optional[int]:
        """The next rung up the ladder — a *different* (bucket, batch)
        executable for the scheduler's retry-with-exclusion path — or None
        when ``bucket`` is already the largest rung."""
        i = self.buckets.index(bucket)
        return self.buckets[i + 1] if i + 1 < len(self.buckets) else None

    def _dispatch(self, bucket, chunk_reqs, chunk_idx, results, arrival=None):
        n_real = len(chunk_reqs)
        batch = self._padded_batch(bucket, n_real)
        dispatch_index = self.counters.bump("serve.batches")
        self.counters.bump("serve.padded_slots", batch - n_real)
        t_start = time.perf_counter()
        # per-request queue wait when the request carries its own arrival
        # stamp (the scheduler sets it at submit); the stream-level arrival
        # is the fallback for the synchronous predict_many path
        waits = []
        for r in chunk_reqs:
            origin = r.arrival_s if r.arrival_s is not None else arrival
            waits.append(t_start - origin if origin is not None else 0.0)
            self.histograms["queue_wait_s"].observe(max(0.0, waits[-1]))
        self.histograms["batch_occupancy"].observe(n_real / batch)

        try:
            self._dispatch_inner(
                bucket, batch, dispatch_index, chunk_reqs, chunk_idx,
                results, waits,
            )
        except Exception as e:  # noqa: BLE001 — converted, never swallowed
            # an exception mid-dispatch (device fault, injected fault, OOM)
            # must not leave the whole chunk's result slots as None with
            # counters already bumped: every request gets a structured
            # per-request error result the scheduler can retry against a
            # different (bucket, batch) executable
            msg = f"{type(e).__name__}: {e}"
            dispatch_s = time.perf_counter() - t_start
            errs = self._error_results(
                bucket, chunk_reqs, waits, msg, dispatch_s
            )
            for idx, res in zip(chunk_idx, errs):
                results[idx] = res

    def _dispatch_inner(
        self, bucket, batch, dispatch_index, chunk_reqs, chunk_idx, results,
        waits,
    ):
        n_real = len(chunk_reqs)
        if self.faults is not None:
            # fault-injection hook: may delay (simulating a slow device) or
            # raise (converted to structured error results by the caller)
            self.faults.on_dispatch(dispatch_index, bucket)
        member_traces = [r.trace.trace_id for r in chunk_reqs if r.trace]
        with self.tracer.span(
            "serve.batch", bucket=bucket, batch=batch, n_real=n_real,
            dispatch_index=dispatch_index,
            **({"trace_ids": member_traces} if member_traces else {}),
        ) as batch_span:
            with self.tracer.span(
                "serve.featurize", bucket=bucket,
                dispatch_index=dispatch_index,
            ):
                items, feat = [], []
                for r in chunk_reqs:
                    item, reuse = self._featurize_one(bucket, r)
                    items.append(item)
                    feat.append(reuse)
                host = self._stack_host(bucket, items, batch)
                stacked = self._transfer(host, dispatch_index, bucket)

            with self.tracer.span(
                "serve.get_executable", bucket=bucket, batch=batch
            ) as exe_span:
                before = self.counters.get("serve.compiles")
                compiled = self._get_executable(bucket, batch)
                exe_span.set(
                    compiled_now=self.counters.get("serve.compiles") > before
                )

            t0 = time.perf_counter()
            with self.tracer.span(
                "serve.dispatch", bucket=bucket,
                dispatch_index=dispatch_index,
                **({"mesh": self.mesh_desc} if self.mesh_desc else {}),
            ):
                out = self._execute_batch(
                    compiled, stacked, dispatch_index, bucket
                )
            # fetch the values, not just readiness: the timed region must
            # close on device completion (the bench's validity contract)
            with self.tracer.span(
                "serve.device_get", bucket=bucket,
                dispatch_index=dispatch_index,
            ):
                refined, weights, disto = self._fetch(
                    out, dispatch_index, bucket
                )
            dispatch_s = time.perf_counter() - t0
            batch_span.set(dispatch_s=round(dispatch_s, 4))
            self.histograms["dispatch_s"].observe(dispatch_s)
            self._account_flops(self._exe_key(bucket, batch))
            self.memory.counter_to(self.tracer)  # HBM beside the spans

            with self.tracer.span(
                "serve.unpad", bucket=bucket, dispatch_index=dispatch_index
            ):
                built = self._build_results(
                    bucket, chunk_reqs, waits, dispatch_s,
                    refined, weights, disto, feat=feat, batch=batch,
                )
            for idx, res in zip(chunk_idx, built):
                results[idx] = res

    # ------------------------------------------------- pipelined completion

    def _complete_pipelined(self, job) -> list:
        """Completion stage of the pipelined dispatch (runs on the fetch
        worker): accounting + unpad/realize into ordered ServeResults.
        Always returns one result per member — an error carried from any
        stage becomes structured per-request error results, so a poisoned
        batch cannot wedge the completion thread."""
        t_end = time.perf_counter()
        reqs = job.members
        t0 = job.t_device0 if job.t_device0 is not None else t_end
        dispatch_s = max(0.0, t_end - t0)
        # queue wait runs from arrival to DEVICE dispatch: under the
        # pipeline, host featurize/transfer is pre-device residency the
        # request observes as waiting, and wait + dispatch_s spans the
        # whole arrival->completion interval
        waits = []
        for r in reqs:
            origin = r.arrival_s if r.arrival_s is not None else job.arrival
            waits.append(t0 - origin if origin is not None else 0.0)
            self.histograms["queue_wait_s"].observe(max(0.0, waits[-1]))
        if job.error is not None:
            msg = f"{type(job.error).__name__}: {job.error}"
            return self._error_results(
                job.bucket, reqs, waits, msg, dispatch_s
            )
        self.histograms["batch_occupancy"].observe(
            job.n_real / job.batch_size
        )
        self.histograms["dispatch_s"].observe(dispatch_s)
        self._account_flops(self._exe_key(job.bucket, job.batch_size))
        self.memory.counter_to(self.tracer)
        refined, weights, disto = job.fetched
        with self.tracer.span(
            "serve.unpad", bucket=job.bucket, dispatch_index=job.index
        ):
            built = self._build_results(
                job.bucket, reqs, waits, dispatch_s, refined, weights,
                disto, feat=job.feat, batch=job.batch_size,
            )
        member_traces = [r.trace.trace_id for r in reqs if r.trace]
        # the batch span is retroactive (its start predates this thread's
        # involvement); explicit bounds keep the Chrome timeline honest
        self.tracer.span_event(
            "serve.batch",
            job.t_host0 if job.t_host0 is not None else t0, t_end,
            bucket=job.bucket, batch=job.batch_size, n_real=job.n_real,
            dispatch_index=job.index, dispatch_s=round(dispatch_s, 4),
            pipelined=True,
            **({"trace_ids": member_traces} if member_traces else {}),
        )
        return built

    def _completion_fallback(self, job) -> list:
        """Last-resort error results if completion itself raised — the
        future always resolves with one result per member."""
        msg = f"{type(job.error).__name__}: {job.error}"
        return [
            ServeResult(
                seq=req.seq, bucket=job.bucket, status="error", error=msg,
                trace_id=req.trace.trace_id if req.trace else None,
            )
            for req in job.members
        ]

    def warmup(self) -> dict:
        """Compile every ladder rung ahead of traffic (one dummy dispatch
        per bucket). Returns the counter snapshot afterwards."""
        for bucket in self.buckets:
            self._get_executable(bucket, self._padded_batch(bucket, 1))
        return self.counters.snapshot()

    def stats(self) -> dict:
        return self.counters.snapshot()

    def histogram_snapshots(self, unit_scale: float = 1.0) -> dict:
        """One summary dict per latency/occupancy distribution; the time
        histograms (``*_s``) are scaled by ``unit_scale`` (1e3 → ms)."""
        return {
            name: h.snapshot(
                unit_scale=unit_scale if name.endswith("_s") else 1.0,
                digits=4,
            )
            for name, h in self.histograms.items()
        }
