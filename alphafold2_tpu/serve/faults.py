"""Fault injection for the serve dispatch path.

A serving frontend's failure handling is only as real as its tests: the
retry-with-exclusion path and the graceful-degradation paths (structured
error results instead of exceptions, rejection under load) are unreachable
on a healthy backend. ``FaultPlan`` is the injection point: the engine
consults it at the top of every dispatch (``ServeEngine(faults=plan)``)
and the plan may *delay* the dispatch (a slow device / congested
interconnect stand-in) or *fail* it (raise :class:`InjectedFault`, which
the engine converts to structured per-request error results the scheduler
retries against a different (bucket, batch) executable).

Plans target a specific dispatch index (``fail_dispatch=N``, 1-based over
the engine's ``serve.batches`` counter) or every dispatch of a bucket
(``fail_bucket=B``), and fire at most ``times`` times (0 = unlimited), so
"the first dispatch of bucket 8 fails once, the retry succeeds" is a
deterministic scenario instead of a race. ``fail_stage`` moves the
injection point from the top of the dispatch into a specific pipeline
stage (``transfer`` = host device_put, ``compute`` = executable call,
``fetch`` = result device_get), so the pipelined dispatch path's
error routing is exercised stage by stage. Pure stdlib, no jax.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional


class InjectedFault(RuntimeError):
    """Raised by a :class:`FaultPlan` to simulate a dispatch failure."""


@dataclasses.dataclass
class FaultPlan:
    """Deterministic dispatch fault/delay injection.

    ``fail_dispatch`` matches the global 1-based dispatch index (the
    engine's ``serve.batches`` counter value for that dispatch);
    ``fail_bucket`` matches every dispatch of that bucket. With neither
    set the plan is inert. A matching dispatch first sleeps ``delay_s``
    (if any), then raises :class:`InjectedFault` unless ``fail=False``
    (delay-only plans model slowness without failure). ``fired`` records
    every injection for test assertions."""

    fail_dispatch: Optional[int] = None  # 1-based dispatch index to hit
    fail_bucket: Optional[int] = None  # bucket whose dispatches are hit
    # hit EVERY dispatch regardless of index/bucket — the fleet's replica
    # degrade drill (match_all + fail=False + delay_s = a uniformly slow
    # replica the router should route around)
    match_all: bool = False
    times: int = 1  # max injections (0 = unlimited)
    delay_s: float = 0.0  # sleep before (optionally) failing
    fail: bool = True  # False = delay-only plan
    message: str = "injected fault"
    # pipeline stage to hit: "transfer" | "compute" | "fetch"; None keeps
    # the legacy injection point at the top of the dispatch (pre-featurize)
    fail_stage: Optional[str] = None

    _STAGES = ("transfer", "compute", "fetch")

    def __post_init__(self):
        if self.fail_stage is not None and self.fail_stage not in self._STAGES:
            raise ValueError(
                f"fail_stage must be one of {self._STAGES}, "
                f"got {self.fail_stage!r}"
            )
        self._lock = threading.Lock()
        self.fired: list = []

    def _matches(self, dispatch_index: int, bucket: int) -> bool:
        if self.match_all:
            return True
        if self.fail_dispatch is not None and (
            dispatch_index == self.fail_dispatch
        ):
            return True
        return self.fail_bucket is not None and bucket == self.fail_bucket

    def on_dispatch(self, dispatch_index: int, bucket: int) -> None:
        """Engine hook: called once per dispatch before any device work.

        Inert when ``fail_stage`` is set — a staged plan fires from its
        stage hook instead, keeping exactly one injection point per plan."""
        if self.fail_stage is None:
            self._fire(dispatch_index, bucket, stage=None)

    def on_stage(self, stage: str, dispatch_index: int, bucket: int) -> None:
        """Engine hook: called as the named pipeline stage begins.

        Only plans whose ``fail_stage`` names this stage fire; everything
        else (including legacy top-of-dispatch plans) passes through."""
        if self.fail_stage == stage:
            self._fire(dispatch_index, bucket, stage=stage)

    def _fire(
        self, dispatch_index: int, bucket: int, stage: Optional[str]
    ) -> None:
        with self._lock:
            if self.times and len(self.fired) >= self.times:
                return
            if not self._matches(dispatch_index, bucket):
                return
            record = {"dispatch": dispatch_index, "bucket": bucket}
            if stage is not None:
                record["stage"] = stage
            self.fired.append(record)
        if self.delay_s > 0:
            time.sleep(self.delay_s)
        if self.fail:
            where = f" at {stage}" if stage is not None else ""
            raise InjectedFault(
                f"{self.message}{where} "
                f"(dispatch {dispatch_index}, bucket {bucket})"
            )

    @classmethod
    def from_spec(cls, spec: Optional[str]) -> Optional["FaultPlan"]:
        """Parse ``"dispatch=2,bucket=16,times=1,delay=0.5,fail=0,
        stage=compute"`` specs (any subset of keys) — the
        ``AF2TPU_SERVE_ASYNC_FAULT`` env hook the serve-async bench uses
        for degradation drills. None/"" -> None."""
        if not spec:
            return None
        kw: dict = {}
        for part in spec.split(","):
            key, _, value = part.partition("=")
            key = key.strip()
            if key == "dispatch":
                kw["fail_dispatch"] = int(value)
            elif key == "bucket":
                kw["fail_bucket"] = int(value)
            elif key == "times":
                kw["times"] = int(value)
            elif key == "delay":
                kw["delay_s"] = float(value)
            elif key == "fail":
                kw["fail"] = value.strip() not in ("0", "false", "no")
            elif key == "stage":
                kw["fail_stage"] = value.strip()
            else:
                raise ValueError(f"unknown fault-spec key {key!r} in {spec!r}")
        return cls(**kw)


@dataclasses.dataclass
class FleetFaultPlan:
    """Replica-scoped fleet fault: kill or degrade one replica at a time
    offset into the run.

    ``replica`` is the target's 0-based index in the fleet; ``at_s`` is
    seconds from fleet start before the fault becomes due. ``degrade_s``
    = 0 means a *kill* (the fleet marks the replica dead and drains it:
    dispatched work completes, queued work re-routes); ``degrade_s`` > 0
    means a *latency injection* instead — the fleet installs a
    ``match_all`` delay-only :class:`FaultPlan` on that replica's engine
    so every one of its dispatches slows by that many seconds, which the
    load-aware router should route around. The fleet's health pump polls
    :meth:`take` each tick; ``fired`` records every action for test and
    bench assertions."""

    replica: int = 0  # 0-based index of the replica to hit
    at_s: float = 0.0  # seconds from fleet start before the fault is due
    degrade_s: float = 0.0  # 0 = kill; >0 = per-dispatch latency injection
    times: int = 1  # max firings (0 = unlimited; kills re-fire inertly)
    message: str = "injected replica fault"

    def __post_init__(self):
        self._lock = threading.Lock()
        self.fired: list = []

    @property
    def kind(self) -> str:
        return "degrade" if self.degrade_s > 0 else "kill"

    def take(self, elapsed_s: float) -> Optional[str]:
        """One-shot poll: ``"kill"`` / ``"degrade"`` when the fault is due
        and its budget remains, else None. Thread-safe; recording and the
        budget check share one critical section so two pump ticks can't
        both claim the same firing."""
        with self._lock:
            if self.times and len(self.fired) >= self.times:
                return None
            if elapsed_s < self.at_s:
                return None
            self.fired.append({
                "replica": self.replica,
                "elapsed_s": round(elapsed_s, 3),
                "kind": self.kind,
            })
            return self.kind

    def degrade_plan(self) -> FaultPlan:
        """The engine-side half of a degrade fault: delay every dispatch
        of the target replica, never fail it."""
        return FaultPlan(
            match_all=True, fail=False, delay_s=self.degrade_s, times=0,
            message=self.message,
        )

    @classmethod
    def from_spec(cls, spec: Optional[str]) -> Optional["FleetFaultPlan"]:
        """Parse ``"replica=1,at_s=2"`` (kill) / ``"replica=0,at_s=1,
        degrade=0.05"`` (latency) — the ``AF2TPU_SERVE_FLEET_FAULT`` env
        hook the serve-fleet bench uses for the death drill.
        None/"" -> None."""
        if not spec:
            return None
        kw: dict = {}
        for part in spec.split(","):
            key, _, value = part.partition("=")
            key = key.strip()
            if key == "replica":
                kw["replica"] = int(value)
            elif key == "at_s":
                kw["at_s"] = float(value)
            elif key == "degrade":
                kw["degrade_s"] = float(value)
            elif key == "times":
                kw["times"] = int(value)
            else:
                raise ValueError(
                    f"unknown fleet-fault key {key!r} in {spec!r}"
                )
        return cls(**kw)
