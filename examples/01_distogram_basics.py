"""Basic usage: sequence + MSA -> distogram.

The equivalent of the reference's README quick-start (alphafold2-pytorch
README "Usage": Alphafold2(dim=256, depth=2, heads=8, dim_head=64), a
128-residue sequence with a 5x64 MSA -> (1, 128, 128, 37) distogram) —
same call surface, grid-native TPU design underneath.

Run anywhere:  python examples/01_distogram_basics.py
(EX_TINY=1 shrinks dims for fast CI smoke.)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from alphafold2_tpu.models import Alphafold2

TINY = os.environ.get("EX_TINY") == "1"
DIM, N, M, NM = (32, 32, 2, 16) if TINY else (256, 128, 5, 64)

model = Alphafold2(
    dim=DIM,
    depth=2,
    heads=8 if not TINY else 2,
    dim_head=64 if not TINY else 16,
    max_seq_len=2 * N,
)

key = jax.random.key(0)
seq = jax.random.randint(jax.random.fold_in(key, 1), (1, N), 0, 21)
msa = jax.random.randint(jax.random.fold_in(key, 2), (1, M, NM), 0, 21)
mask = jnp.ones((1, N), dtype=bool)
msa_mask = jnp.ones((1, M, NM), dtype=bool)

params = model.init(key, seq, msa, mask=mask, msa_mask=msa_mask)
distogram = jax.jit(model.apply)(params, seq, msa, mask=mask, msa_mask=msa_mask)

print("distogram:", distogram.shape)  # (1, N, N, 37)
assert distogram.shape == (1, N, N, 37)
assert bool(jnp.all(jnp.isfinite(distogram)))
print("ok")
