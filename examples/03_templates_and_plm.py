"""Template conditioning and the PLM-embedding input path.

Reference README "Templates" (template sequences + coordinates, optional
sidechain SE(3) coloring) and the ESM/PLM ``embedds`` path (broken
upstream — SURVEY.md S2.5 — working here: the projected embedding
outer-sum becomes an (N, N) grid standing in for the MSA stream).

Run anywhere:  python examples/03_templates_and_plm.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from alphafold2_tpu import constants
from alphafold2_tpu.models import Alphafold2

TINY = os.environ.get("EX_TINY") == "1"
DIM, N, T = (32, 24, 2) if TINY else (64, 64, 2)

model = Alphafold2(
    dim=DIM, depth=1, heads=2, dim_head=16, max_seq_len=2 * N,
    template_attn_depth=1,
)

key = jax.random.key(0)
seq = jax.random.randint(jax.random.fold_in(key, 1), (1, N), 0, 21)
mask = jnp.ones((1, N), dtype=bool)

# templates: aligned sequences + CA coordinates (+ unit sidechain vectors
# for the SE(3) template embedder); the distogram is auto-bucketed from
# the coordinates when not given (reference alphafold2.py:508-509)
t_seq = jax.random.randint(jax.random.fold_in(key, 2), (1, T, N), 0, 21)
t_coors = jax.random.normal(jax.random.fold_in(key, 3), (1, T, N, 3)) * 10
t_side = jax.random.normal(jax.random.fold_in(key, 4), (1, T, N, 3))
t_side = t_side / jnp.linalg.norm(t_side, axis=-1, keepdims=True)
t_mask = jnp.ones((1, T, N), dtype=bool)

kw = dict(
    mask=mask,
    templates_seq=t_seq,
    templates_coors=t_coors,
    templates_mask=t_mask,
    templates_sidechains=t_side,
)
params = model.init(key, seq, **kw)
out = jax.jit(lambda p: model.apply(p, seq, **kw))(params)
print("templated distogram:", out.shape)

# PLM path: precomputed language-model residue embeddings instead of an MSA
plm = Alphafold2(dim=DIM, depth=1, heads=2, dim_head=16, max_seq_len=2 * N)
embedds = jax.random.normal(
    jax.random.fold_in(key, 5), (1, N, constants.NUM_EMBEDDS_TR)
)
p2 = plm.init(key, seq, mask=mask, embedds=embedds)
out2 = jax.jit(lambda p: plm.apply(p, seq, mask=mask, embedds=embedds))(p2)
print("plm-conditioned distogram:", out2.shape)
assert out.shape == out2.shape == (1, N, N, 37)
print("ok")
