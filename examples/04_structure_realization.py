"""Distogram -> 3D coordinates -> quality metrics.

The reference's structure-realization chain (README "Real Value Distance
Prediction" + utils.py): softmax the distogram, center it into distances
+ confidence weights, weighted-MDS into coordinates with a chirality fix,
then Kabsch-align and score (RMSD / GDT / TMscore / lDDT). One jnp
implementation here (the reference keeps torch+numpy twins of everything).

Run anywhere:  python examples/04_structure_realization.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from alphafold2_tpu.predict import realize_structure
from alphafold2_tpu.utils import Kabsch, RMSD, TMscore, get_bucketed_distance_matrix

TINY = os.environ.get("EX_TINY") == "1"
L = 16 if TINY else 48  # residues; the realization runs on 3L backbone atoms

key = jax.random.key(0)

# a synthetic "ground truth" backbone chain (3 atoms per residue)
steps = jax.random.normal(jax.random.fold_in(key, 1), (1, 3 * L, 3))
true = jnp.cumsum(1.2 * steps / jnp.linalg.norm(steps, axis=-1, keepdims=True), axis=1)

# a perfect distogram for it: one-hot bucketed true distances (stand-in for
# model output so the example is self-contained and deterministic)
mask = jnp.ones((1, 3 * L), dtype=bool)
buckets = get_bucketed_distance_matrix(true, mask)
logits = 10.0 * jax.nn.one_hot(jnp.maximum(buckets, 0), 37)

coords, distances, weights = realize_structure(
    logits, iters=50 if TINY else 200, key=jax.random.fold_in(key, 2),
    mask=mask,
)
print("realized coords:", coords.shape)  # (1, 3, 3L)

true_t = jnp.swapaxes(true, -1, -2)  # (1, 3, 3L)
aligned, target = Kabsch(coords, true_t)
print("RMSD after alignment:", float(RMSD(aligned, target)[0]))
print("TM-score:", float(TMscore(aligned, target)[0]))
assert bool(jnp.all(jnp.isfinite(aligned)))
print("ok")
