"""Long-context / memory levers, composed in one model.

The reference scales a single GPU with four mechanisms (sparse attention,
KV-compressed cross-attention, tied-row MSA attention, a reversible
trunk); this framework keeps all four — TPU-native — and adds fused flash
kernels, XLA rematerialization with checkpoint policies, and mesh
sharding (see 05_distributed_training.py).

Run anywhere:  python examples/02_memory_scaling.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from alphafold2_tpu.models import Alphafold2
from alphafold2_tpu.ops.sparse import BlockSparseConfig

TINY = os.environ.get("EX_TINY") == "1"
DIM, N, M = (32, 32, 2) if TINY else (128, 128, 8)

model = Alphafold2(
    dim=DIM,
    depth=2,
    heads=2,
    dim_head=16,
    max_seq_len=2 * N,
    # interleave block-sparse pair self-attention (reference README
    # "Sparse Attention": (True, False) per depth step, DeepSpeed block
    # sparsity -> here an in-repo Pallas kernel, a splash-attention
    # backend, and a jnp gather oracle, selected by config.backend)
    sparse_self_attn=(True, False),
    sparse_config=BlockSparseConfig(block_size=16, num_random_blocks=1),
    # compress cross-attention keys/values 2x (reference README
    # "Memory Compressed Attention"); composes with the flash kernel
    cross_attn_compress_ratio=2,
    # one shared attention matrix across MSA rows (reference README
    # "MSA Tied Row Attention") — with EXACT mask semantics (padded
    # entries abstain; the reference forbids masks here)
    msa_tie_row_attn=True,
    # O(1)-in-depth activation memory: XLA rematerialization...
    remat=True,
    # ...saving matmul outputs so the backward skips recomputing the
    # MXU-heavy ops (memory <-> MFU trade; "dots_no_batch" saves less)
    remat_policy="dots",
    # reversible=True instead gives the inversion-based engine — the
    # reference's reversible trunk, as a lax.scan + custom_vjp
)

key = jax.random.key(0)
seq = jax.random.randint(jax.random.fold_in(key, 1), (1, N), 0, 21)
msa = jax.random.randint(jax.random.fold_in(key, 2), (1, M, N), 0, 21)
mask = jnp.ones((1, N), dtype=bool)
msa_mask = jnp.ones((1, M, N), dtype=bool)

params = model.init(key, seq, msa, mask=mask, msa_mask=msa_mask)


def loss(p):
    return jnp.mean(
        model.apply(p, seq, msa, mask=mask, msa_mask=msa_mask) ** 2
    )


val, grads = jax.jit(jax.value_and_grad(loss))(params)
n_leaves = len(jax.tree.leaves(grads))
print(f"loss={float(val):.4f}, {n_leaves} gradient leaves, all finite:",
      all(bool(jnp.all(jnp.isfinite(g))) for g in jax.tree.leaves(grads)))
print("ok")
