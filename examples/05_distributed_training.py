"""Mesh-sharded training: one jitted step over a (dp, sp) device mesh.

The reference is strictly single-device (SURVEY.md S2.3); here the whole
train step (forward, loss, backward, optimizer) is one compiled program
laid out over a mesh — data-parallel batch sharding, sequence-parallel
pair-grid sharding, XLA collectives over ICI. This example builds a
4-device mesh from however many devices are present (works on the
8-virtual-device CPU mesh used by the test suite: run with
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/05_distributed_training.py
or on real chips unchanged).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

from alphafold2_tpu.config import Config, DataConfig, MeshConfig, ModelConfig, TrainConfig
from alphafold2_tpu.data.pipeline import SyntheticDataset
from alphafold2_tpu.parallel.sharding import make_mesh
from alphafold2_tpu.train.loop import (
    device_put_batch,
    build_model,
    make_train_step,
    tiny_init_state,
)

n_dev = jax.device_count()
n_sp = 2 if n_dev >= 4 else 1
n_dp = max(n_dev // n_sp, 1)
mesh = make_mesh(n_dp, n_sp, devices=jax.devices()[: n_dp * n_sp])
print(f"mesh: {n_dp} data-parallel x {n_sp} sequence-parallel "
      f"({jax.devices()[0].platform})")

cfg = Config(
    model=ModelConfig(
        dim=32, depth=1, heads=2, dim_head=16, max_seq_len=64,
        msa_tie_row_attn=True, remat=True, bfloat16=False,
        context_parallel="ring" if n_sp > 1 else None,
    ),
    mesh=MeshConfig(data_parallel=n_dp, seq_parallel=n_sp),
    data=DataConfig(crop_len=16, msa_depth=2, msa_len=16, batch_size=n_dp),
    train=TrainConfig(gradient_accumulate_every=1, warmup_steps=2),
)

batch = next(iter(SyntheticDataset(cfg.data, seed=0)))
model = build_model(cfg)
state = tiny_init_state(cfg, model, batch)
step = make_train_step(model, mesh)

sharded = device_put_batch(batch, mesh)
rng = jax.random.key(0)
for i in range(3):
    rng, r = jax.random.split(rng)
    state, metrics = step(state, sharded, r)
    print(f"step {i}: loss={float(metrics['loss']):.4f} "
          f"grad_norm={float(metrics['grad_norm']):.3f}")
print("ok")
