"""Trace continuity through the async scheduler (ISSUE 9 acceptance):
every request lifecycle — plain dispatch, cache hit, in-flight dedup
join, fault-injected retry, deadline miss, rejection — must reconstruct
from the trace events alone to a complete submit→…→resolve chain keyed
by the request's trace_id. Deterministic: fake clock, ``start=False``,
FakeEngine from test_scheduler.py's pattern, memory tracer."""

import numpy as np

from alphafold2_tpu.config import (
    Config,
    DataConfig,
    ModelConfig,
    ServeConfig,
)
from alphafold2_tpu.observe import EventCounters, Tracer
from alphafold2_tpu.observe.tracectx import (
    DEDUP_EVENT,
    RESOLVE_EVENT,
    SUBMIT_EVENT,
    reconstruct_traces,
    trace_completeness,
)
from alphafold2_tpu.serve import (
    AsyncServeFrontend,
    ServeRequest,
    ServeResult,
)


def _cfg(buckets=(8, 16), max_batch=2, **serve_kw):
    serve_kw.setdefault("mds_iters", 10)
    return Config(
        model=ModelConfig(dim=32, depth=1, heads=2, dim_head=16,
                          max_seq_len=3 * max(buckets), bfloat16=False),
        data=DataConfig(msa_depth=2),
        serve=ServeConfig(buckets=buckets, max_batch=max_batch, **serve_kw),
    )


class FakeClock:
    def __init__(self):
        self.now = 100.0

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


class TracingFakeEngine:
    """FakeEngine with the tracer ENABLED (memory mode) and trace_id
    stamped on dispatched results the way ServeEngine does."""

    def __init__(self, cfg, fail_first=0):
        self.cfg = cfg
        self.buckets = cfg.serve.buckets
        self.max_batch = cfg.serve.max_batch
        self.mesh_desc = None
        self.counters = EventCounters()
        self.tracer = Tracer(enabled=True)
        self.dispatched = []
        self._fail_remaining = fail_first

    def batch_for(self, bucket):
        return self.max_batch

    def dispatch_batch(self, bucket, reqs):
        self.dispatched.append((bucket, [r.seq for r in reqs]))
        if self._fail_remaining > 0:
            self._fail_remaining -= 1
            return [
                ServeResult(seq=r.seq, bucket=bucket, status="error",
                            error="InjectedFault: boom",
                            trace_id=r.trace.trace_id if r.trace else None)
                for r in reqs
            ]
        return [
            ServeResult(
                seq=r.seq, bucket=bucket,
                atom14=np.zeros((len(r.seq), 14, 3), np.float32),
                latency_s=1e-3,
                trace_id=r.trace.trace_id if r.trace else None,
            )
            for r in reqs
        ]

    def retry_bucket(self, bucket):
        i = self.buckets.index(bucket)
        return self.buckets[i + 1] if i + 1 < len(self.buckets) else None


def _frontend(fail_first=0, **serve_kw):
    serve_kw.setdefault("dwell_ms", 50.0)
    eng = TracingFakeEngine(_cfg(**serve_kw), fail_first=fail_first)
    clock = FakeClock()
    fe = AsyncServeFrontend(eng, clock=clock, start=False)
    return fe, eng, clock


def _complete(tracer, results):
    ids = [r.trace_id for r in results if r.status != "rejected"]
    assert all(ids), results  # every non-rejected result is trace-stamped
    return trace_completeness(tracer.events(), ids)


# ------------------------------------------------------------- lifecycles


def test_request_mints_trace_and_result_carries_it():
    fe, eng, clock = _frontend()
    req = ServeRequest("ACDEFG")
    assert req.trace is not None  # minted at creation
    h1, h2 = fe.submit(req), fe.submit("MKVLIT")
    fe.pump()
    r = h1.result(0)
    assert r.ok and r.trace_id == req.trace.trace_id
    summary = _complete(eng.tracer, [r, h2.result(0)])
    assert summary == {"total": 2, "complete": 2, "fraction": 1.0}


def test_dedup_join_links_follower_to_leader_trace():
    fe, eng, clock = _frontend()
    leader_req = ServeRequest("ACDEFG", seed=7)
    follower_req = ServeRequest("ACDEFG", seed=7)  # same key, OWN trace
    assert leader_req.trace.trace_id != follower_req.trace.trace_id
    h1, h2 = fe.submit(leader_req), fe.submit(follower_req)
    clock.advance(0.051)
    fe.pump()
    r1, r2 = h1.result(0), h2.result(0)
    assert r1.ok and r2.ok and r2.cache_hit
    # the shared result is re-stamped per requester: each trace resolves
    assert r1.trace_id == leader_req.trace.trace_id
    assert r2.trace_id == follower_req.trace.trace_id
    summary = _complete(eng.tracer, [r1, r2])
    assert summary["fraction"] == 1.0, summary
    # the follower's join event names the leader trace it rode
    joins = [e for e in eng.tracer.events() if e["name"] == DEDUP_EVENT]
    assert len(joins) == 1
    assert joins[0]["args"]["trace_id"] == follower_req.trace.trace_id
    assert joins[0]["args"]["leader_trace"] == leader_req.trace.trace_id


def test_cache_hit_lifecycle_reconstructs():
    fe, eng, clock = _frontend()
    first = ServeRequest("ACDEFG", seed=3)
    fe.submit(first)
    fe.submit("MKVLIT")
    fe.pump()
    repeat = ServeRequest("ACDEFG", seed=3)
    r = fe.submit(repeat).result(0)
    assert r.ok and r.cache_hit
    assert r.trace_id == repeat.trace.trace_id  # NOT the first request's
    summary = _complete(eng.tracer, [r])
    assert summary["fraction"] == 1.0, summary


def test_retry_lifecycle_reconstructs():
    fe, eng, clock = _frontend(fail_first=1)
    h1, h2 = fe.submit("ACDEFG"), fe.submit("MKVLIT")
    fe.pump()
    r1, r2 = h1.result(0), h2.result(0)
    assert r1.ok and r1.retried and r2.ok
    summary = _complete(eng.tracer, [r1, r2])
    assert summary["fraction"] == 1.0, summary
    # the retry span carries the member traces that rode it
    retries = [e for e in eng.tracer.events() if e["name"] == "sched.retry"]
    assert retries and set(retries[0]["args"]["trace_ids"]) == {
        r1.trace_id, r2.trace_id
    }


def test_deadline_miss_lifecycle_reconstructs():
    fe, eng, clock = _frontend(dwell_ms=10_000.0)
    req = ServeRequest("ACDEFG", deadline_s=0.2)
    h = fe.submit(req)
    clock.advance(0.3)
    fe.pump()
    r = h.result(0)
    assert r.status == "deadline_exceeded"
    assert r.trace_id == req.trace.trace_id
    summary = _complete(eng.tracer, [r])
    assert summary["fraction"] == 1.0, summary


def test_rejection_resolves_with_trace():
    fe, eng, clock = _frontend(
        queue_depth=1, dwell_ms=10_000.0, shed_watermark=0.0
    )
    fe.submit("ACDEFG")
    rej = ServeRequest("MKVLIT")
    r = fe.submit(rej).result(0)
    assert r.status == "rejected"
    assert r.trace_id == rej.trace.trace_id
    # rejected requests still emit a submit root + resolve terminal
    ids = [r.trace_id]
    summary = trace_completeness(eng.tracer.events(), ids)
    assert summary["fraction"] == 1.0, summary


def test_fault_injected_run_reconstructs_every_lifecycle():
    """The ISSUE's acceptance shape in miniature: mixed workload with an
    injected dispatch fault — every non-rejected lifecycle complete."""
    fe, eng, clock = _frontend(fail_first=1)
    handles = []
    reqs = ["ACDEFG", "MKVLIT", "ACDEFGHKLMNP", "WYTSAR", "GHKLMN"]
    for i, seq in enumerate(reqs):
        handles.append(fe.submit(ServeRequest(seq, seed=1, priority=i % 2)))
        clock.advance(0.01)
        fe.pump()
    clock.advance(0.06)
    fe.pump()
    results = [h.result(0) for h in handles]
    assert all(r.ok for r in results), [r.status for r in results]
    summary = _complete(eng.tracer, results)
    assert summary["fraction"] == 1.0, summary
    # spot-check the event plumbing the reconstruction relies on
    names = {e["name"] for e in eng.tracer.events()}
    assert {SUBMIT_EVENT, RESOLVE_EVENT, "sched.dispatch"} <= names


# -------------------------------------------------------------- observers


def test_observers_see_every_resolution_with_priority():
    fe, eng, clock = _frontend(
        queue_depth=1, dwell_ms=10_000.0, shed_watermark=0.0
    )
    seen = []
    fe.add_observer(lambda result, priority: seen.append(
        (result.status, priority)))
    fe.submit(ServeRequest("ACDEFG", priority=1), priority=1)
    fe.submit("MKVLIT")  # queue full: rejected
    clock.advance(11.0)
    fe.pump()
    statuses = sorted(seen)
    assert ("rejected", 0) in statuses
    assert ("ok", 1) in statuses
    assert len(seen) == 2


def test_observer_exception_does_not_break_resolution():
    fe, eng, clock = _frontend()

    def bad_observer(result, priority):
        raise RuntimeError("observer bug")

    fe.add_observer(bad_observer)
    h1, h2 = fe.submit("ACDEFG"), fe.submit("MKVLIT")
    fe.pump()
    assert h1.result(0).ok and h2.result(0).ok
