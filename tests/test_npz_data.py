"""Real-data ingestion tests: PDB directory -> npz shards -> training
batches -> one train step. The full local-data loop the reference delegates
to sidechainnet."""

import os
import subprocess
import sys

import numpy as np
import pytest

from alphafold2_tpu import constants
from alphafold2_tpu.config import Config, DataConfig, ModelConfig, TrainConfig
from alphafold2_tpu.data.pipeline import NpzShardDataset, make_dataset
from alphafold2_tpu.data.pipeline import _smooth_walk
from alphafold2_tpu.utils import pdb as pdbio

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_pdbs(d, n_files=3, length=20):
    rng = np.random.default_rng(0)
    os.makedirs(d, exist_ok=True)
    for i in range(n_files):
        ca = _smooth_walk(rng, length)
        dvec = np.diff(ca, axis=0, prepend=ca[:1] - (ca[1:2] - ca[:1]))
        dvec /= np.linalg.norm(dvec, axis=-1, keepdims=True) + 1e-9
        bb = np.stack([ca - 1.46 * dvec, ca, ca + 1.52 * dvec], axis=1)
        seq = "".join(
            constants.AA_ALPHABET[t]
            for t in rng.integers(0, 20, size=length)
        )
        pdbio.save_pdb(
            pdbio.backbone_to_pdb(seq, bb.astype(np.float32)),
            os.path.join(d, f"chain_{i}.pdb"),
        )


def test_import_pdbs_cli_and_train(tmp_path):
    pdb_dir = str(tmp_path / "pdbs")
    out_dir = str(tmp_path / "shards")
    _write_pdbs(pdb_dir)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "import_pdbs.py"),
         pdb_dir, out_dir],
        capture_output=True, text=True, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr
    assert "imported 3/3" in r.stdout

    cfg = Config(
        model=ModelConfig(dim=32, depth=1, heads=2, dim_head=16, max_seq_len=32,
                          bfloat16=False),
        data=DataConfig(crop_len=16, msa_depth=2, msa_len=16, batch_size=2,
                        min_len_filter=8, source="npz", data_dir=out_dir),
        train=TrainConfig(gradient_accumulate_every=1, warmup_steps=2),
    )
    ds = make_dataset(cfg.data, seed=0)
    assert isinstance(ds, NpzShardDataset)
    batch = next(iter(ds))
    assert batch["seq"].shape == (2, 16)
    assert batch["backbone"].shape == (2, 48, 3)
    # backbone slot 1 of each residue == the CA coords array
    bb = batch["backbone"].reshape(2, 16, 3, 3)
    w = batch["mask"][0].sum()
    assert np.allclose(bb[0, :w, 1], batch["coords"][0, :w], atol=1e-3)
    # consecutive CA distances are protein-like (came from real geometry)
    steps = np.linalg.norm(np.diff(batch["coords"][0][:w], axis=0), axis=-1)
    assert np.allclose(steps, 3.8, atol=0.3)

    import jax

    from alphafold2_tpu.train.loop import (
        build_model, device_put_batch, init_state, make_train_step,
    )

    model = build_model(cfg)
    state = init_state(cfg, model, batch)
    step = make_train_step(model)
    state, metrics = step(state, device_put_batch(batch), jax.random.key(0))
    assert np.isfinite(float(metrics["loss"]))
    assert bool(metrics["grads_ok"])


def test_npz_dataset_validates(tmp_path):
    cfg = DataConfig(source="npz", data_dir=str(tmp_path / "empty"))
    with pytest.raises(FileNotFoundError, match="no .npz shards"):
        NpzShardDataset(cfg)
    with pytest.raises(ValueError, match="data_dir"):
        NpzShardDataset(DataConfig(source="npz", data_dir=None))


def test_npz_dataset_length_filter_and_crop(tmp_path):
    d = str(tmp_path / "shards")
    os.makedirs(d)
    rng = np.random.default_rng(1)
    # one long chain (40) and one too-short chain (4, below min_len 8)
    np.savez(os.path.join(d, "long.npz"),
             seq=rng.integers(0, 20, 40).astype(np.int32),
             coords=rng.normal(size=(40, 3)).astype(np.float32))
    np.savez(os.path.join(d, "short.npz"),
             seq=rng.integers(0, 20, 4).astype(np.int32),
             coords=rng.normal(size=(4, 3)).astype(np.float32))
    cfg = DataConfig(crop_len=16, msa_depth=2, msa_len=8, batch_size=1,
                     min_len_filter=8, source="npz", data_dir=d)
    it = iter(NpzShardDataset(cfg, seed=0))
    for _ in range(4):
        batch = next(it)
        assert batch["mask"].sum() == 16  # long chain cropped to the window
        # CA-only shard: backbone synthesized, not left as zeros (the
        # end2end loss would otherwise train against garbage)
        assert np.abs(batch["backbone"][0, :48]).sum() > 0

    # nothing passes the filter -> loud error, not an infinite busy loop
    cfg_bad = DataConfig(crop_len=16, msa_depth=2, msa_len=8, batch_size=1,
                         min_len_filter=100, source="npz", data_dir=d)
    with pytest.raises(ValueError, match="length filter"):
        next(iter(NpzShardDataset(cfg_bad, seed=0)))
