"""Fleet frontend tests (serve/fleet.py + the scheduler/faults hooks).

The router logic is tested deterministically like the scheduler's: fake
engines, a fake clock, ``start=False`` (no replica dispatcher threads,
no health pump) and inline ``pump_replicas()`` / ``pump_health()``
passes. Covered: load-aware routing, work stealing with observer-driven
re-routing, replica-death draining with zero dropped accepted requests,
trace reconstruction across the traceparent hop, replica-scoped fault
plans, SLO fan-out aggregation, and the fleet's lock-order rule as a
static assertion over the layer-5 concurrency model."""

import numpy as np
import pytest

from alphafold2_tpu.config import (
    Config,
    DataConfig,
    ModelConfig,
    ServeConfig,
)
from alphafold2_tpu.observe import EventCounters, Tracer
from alphafold2_tpu.observe.slo import aggregate_slo_verdicts
from alphafold2_tpu.observe.tracectx import trace_completeness
from alphafold2_tpu.serve import FaultPlan, FleetFaultPlan, ServeResult
from alphafold2_tpu.serve.fleet import (
    STOLEN_ERROR,
    FleetFrontend,
    fleet_counter_zeros,
)


def _cfg(buckets=(8, 16), max_batch=2, **serve_kw):
    serve_kw.setdefault("mds_iters", 10)
    serve_kw.setdefault("dwell_ms", 50.0)
    return Config(
        model=ModelConfig(dim=32, depth=1, heads=2, dim_head=16,
                          max_seq_len=3 * max(buckets), bfloat16=False),
        data=DataConfig(msa_depth=2),
        serve=ServeConfig(buckets=buckets, max_batch=max_batch, **serve_kw),
    )


class FakeClock:
    def __init__(self):
        self.now = 100.0

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


class FakeEngine:
    """Engine stand-in mirroring tests/test_scheduler.py's: records every
    dispatch, never touches jax."""

    def __init__(self, cfg):
        self.cfg = cfg
        self.buckets = cfg.serve.buckets
        self.max_batch = cfg.serve.max_batch
        self.mesh_desc = None
        self.counters = EventCounters()
        self.tracer = Tracer(enabled=False)
        self.dispatched = []

    def batch_for(self, bucket):
        return self.max_batch

    def dispatch_batch(self, bucket, reqs):
        self.dispatched.append((bucket, [r.seq for r in reqs]))
        return [
            ServeResult(
                seq=r.seq, bucket=bucket,
                atom14=np.zeros((len(r.seq), 14, 3), np.float32),
                latency_s=1e-3,
            )
            for r in reqs
        ]

    def retry_bucket(self, bucket):
        i = self.buckets.index(bucket)
        return self.buckets[i + 1] if i + 1 < len(self.buckets) else None


def _fleet(replicas=2, tracer=None, **kw):
    cfg = _cfg()
    engines = [FakeEngine(cfg) for _ in range(replicas)]
    clock = FakeClock()
    fleet = FleetFrontend(
        engines, clock=clock, tracer=tracer, start=False, **kw
    )
    return fleet, engines, clock


def _seqs(n, length=6):
    alpha = "ACDEFGHIKLMNPQRSTVWY"
    return [
        "".join(alpha[(i + j) % len(alpha)] for j in range(length))
        for i in range(n)
    ]


def _drain(fleet, clock, rounds=10):
    # advance past the dwell window each round so partial batches dispatch
    for _ in range(rounds):
        clock.advance(1.0)
        if fleet.pump_replicas() == 0 and fleet.depth == 0:
            break


# ---------------------------------------------------------------- routing


def test_routing_stripes_idle_fleet():
    fleet, engines, clock = _fleet(replicas=2)
    handles = [fleet.submit(q, deadline_s=None) for q in _seqs(4)]
    # both replicas got work: an idle fleet stripes round-robin instead
    # of piling everything on replica 0
    depths = [c.frontend.depth for c in fleet.cells]
    assert depths == [2, 2]
    _drain(fleet, clock)
    results = [h.result(0) for h in handles]
    assert all(r.status == "ok" for r in results)
    assert fleet.stats()["fleet.routed"] == 4
    fleet.close()


def test_routing_prefers_less_loaded_replica():
    fleet, engines, _ = _fleet(replicas=2)
    # preload replica 0 via the router by pinning the pick, then restore
    orig = fleet._pick_replica
    fleet._pick_replica = lambda bucket, exclude: 0
    for q in _seqs(3):
        fleet.submit(q)
    fleet._pick_replica = orig
    fleet.submit("MKVLITAA")  # load-aware: must land on empty replica 1
    assert fleet.cells[1].frontend.depth == 1
    fleet.close()


def test_result_carries_router_trace_id():
    fleet, _, clock = _fleet(replicas=2)
    h = fleet.submit("ACDEFG")
    root_tid = h.request.trace.trace_id
    _drain(fleet, clock)
    assert h.result(0).trace_id == root_tid
    fleet.close()


# --------------------------------------------------------------- stealing


def test_steal_rebalances_and_reroutes():
    fleet, engines, clock = _fleet(replicas=2)
    orig = fleet._pick_replica
    fleet._pick_replica = lambda bucket, exclude: 0  # force imbalance
    handles = [fleet.submit(q) for q in _seqs(8)]
    fleet._pick_replica = orig
    assert [c.frontend.depth for c in fleet.cells] == [8, 0]
    # gap 8 > auto margin max(2, 2*max_batch)=4: steal half the gap
    summary = fleet.pump_health()
    assert summary["stolen"] == 4
    stats = fleet.stats()
    assert stats["fleet.steals"] == 4
    assert stats["fleet.rerouted"] == 4
    assert [c.frontend.depth for c in fleet.cells] == [4, 4]
    _drain(fleet, clock)
    results = [h.result(0) for h in handles]
    assert all(r.status == "ok" for r in results)
    # the steal is invisible to callers: no STOLEN_ERROR ever escapes
    assert not any(r.error == STOLEN_ERROR for r in results)
    fleet.close()


def test_steal_needs_margin():
    fleet, engines, _ = _fleet(replicas=2)
    orig = fleet._pick_replica
    fleet._pick_replica = lambda bucket, exclude: 0
    for q in _seqs(3):
        fleet.submit(q)
    fleet._pick_replica = orig
    assert fleet.pump_health()["stolen"] == 0  # gap 3 <= margin 4
    fleet.close()


# ------------------------------------------------------------ drain / kill


def test_kill_replica_drains_with_zero_drops():
    fleet, engines, clock = _fleet(replicas=2)
    handles = [fleet.submit(q) for q in _seqs(6)]
    killed = fleet.kill_replica(0)
    assert killed is True
    assert fleet.alive_replicas() == [1]
    # replica 0's queued work re-routed to the survivor, nothing dropped
    assert fleet.cells[1].frontend.depth == 6
    _drain(fleet, clock)
    results = [h.result(0) for h in handles]
    assert all(r.status == "ok" for r in results)
    stats = fleet.stats()
    assert stats["fleet.drains"] == 1
    assert stats["fleet.replica_deaths"] == 1
    assert stats["fleet.rerouted"] >= 3
    assert engines[0].dispatched == []  # nothing ran on the dead replica
    # idempotent: a second kill is a no-op
    assert fleet.kill_replica(0) is False
    fleet.close()


def test_route_racing_close_gets_structured_rejection_then_reroutes():
    fleet, engines, clock = _fleet(replicas=2)
    # a replica whose frontend already closed (drain race): the fleet's
    # route gets the structured "frontend closed" rejection and re-routes
    fleet.cells[0].frontend.close(timeout=0.1)
    orig = fleet._pick_replica
    fleet._pick_replica = lambda bucket, exclude: (
        0 if exclude is None else orig(bucket, exclude)
    )
    h = fleet.submit("ACDEFG")
    fleet._pick_replica = orig
    assert fleet.cells[1].frontend.depth == 1
    _drain(fleet, clock)
    assert h.result(0).status == "ok"
    assert fleet.stats()["fleet.rerouted"] == 1
    fleet.close()


def test_no_alive_replicas_rejects_structurally():
    fleet, engines, _ = _fleet(replicas=2)
    fleet.kill_replica(0)
    fleet.kill_replica(1)
    h = fleet.submit("ACDEFG")
    r = h.result(0)
    assert r.status == "rejected"
    assert r.error == "no alive replicas"
    assert fleet.stats()["fleet.no_replica"] == 1
    fleet.close()


def test_fleet_close_rejects_new_submits():
    fleet, _, _ = _fleet(replicas=2)
    fleet.close()
    r = fleet.submit("ACDEFG").result(0)
    assert r.status == "rejected"
    assert r.error == "fleet closed"


# ----------------------------------------------------------- trace the hop


def test_traceparent_hop_reconstructs_complete_traces():
    tracer = Tracer(enabled=True)
    fleet, engines, clock = _fleet(replicas=2, tracer=tracer)
    handles = [fleet.submit(q) for q in _seqs(6)]
    fleet.kill_replica(0)  # the drill must not orphan lifecycles either
    _drain(fleet, clock)
    results = [h.result(0) for h in handles]
    assert all(r.status == "ok" for r in results)
    summary = trace_completeness(
        tracer.events(), [r.trace_id for r in results]
    )
    assert summary["fraction"] == 1.0, summary
    # the router and replica halves share one trace: fleet.admit carries
    # the root span the replica lifecycle parents onto
    names_by_tid: dict = {}
    for e in tracer.events():
        args = e.get("args", e)
        tid = args.get("trace_id")
        if tid:
            names_by_tid.setdefault(tid, set()).add(e.get("name"))
    for r in results:
        names = names_by_tid[r.trace_id]
        assert "fleet.admit" in names
        assert "sched.submit" in names
    fleet.close()


# ------------------------------------------------------------ fault plans


def test_fleet_fault_plan_parses_kill_and_degrade():
    kill = FleetFaultPlan.from_spec("replica=1,at_s=2")
    assert (kill.replica, kill.at_s, kill.kind) == (1, 2.0, "kill")
    deg = FleetFaultPlan.from_spec("replica=0,at_s=1,degrade=0.05,times=3")
    assert deg.kind == "degrade"
    assert deg.degrade_s == 0.05 and deg.times == 3
    assert FleetFaultPlan.from_spec("") is None
    assert FleetFaultPlan.from_spec(None) is None
    with pytest.raises(ValueError):
        FleetFaultPlan.from_spec("replica=1,bogus=2")


def test_fleet_fault_take_is_one_shot():
    plan = FleetFaultPlan(replica=1, at_s=2.0)
    assert plan.take(1.0) is None  # not due yet
    assert plan.take(2.5) == "kill"
    assert plan.take(3.0) is None  # budget spent
    assert len(plan.fired) == 1


def test_degrade_plan_is_match_all_delay_only():
    deg = FleetFaultPlan(replica=0, degrade_s=0.01).degrade_plan()
    assert deg.match_all and not deg.fail and deg.times == 0
    assert deg._matches(7, 16) and deg._matches(1, 8)
    deg.on_dispatch(1, 8)  # must not raise
    assert deg.fired == [{"dispatch": 1, "bucket": 8}]


def test_pump_health_fires_kill_fault():
    fault = FleetFaultPlan(replica=1, at_s=5.0)
    fleet, engines, clock = _fleet(replicas=2, fault=fault)
    assert fleet.pump_health()["killed"] is None  # not due
    clock.advance(6.0)
    assert fleet.pump_health()["killed"] == 1
    assert fleet.alive_replicas() == [0]
    fleet.close()


def test_pump_health_fires_degrade_fault():
    fault = FleetFaultPlan(replica=0, at_s=0.0, degrade_s=0.01)
    fleet, engines, clock = _fleet(replicas=2, fault=fault)
    clock.advance(1.0)
    assert fleet.pump_health()["degraded"] == 0
    assert engines[0].faults.match_all and not engines[0].faults.fail
    assert fleet.alive_replicas() == [0, 1]  # degraded, not dead
    fleet.close()


# ------------------------------------------------------------- SLO fan-out


def test_slo_fanout_and_fleet_aggregation():
    from alphafold2_tpu.observe.slo import SLOSpec

    specs = [SLOSpec(name="availability", objective="availability",
                     target=0.9, min_events=1)]
    fleet, engines, clock = _fleet(replicas=2, slo_specs=specs)
    handles = [fleet.submit(q) for q in _seqs(4)]
    _drain(fleet, clock)
    assert all(h.result(0).status == "ok" for h in handles)
    summary = fleet.slo_summary()
    assert len(summary["replicas"]) == 2
    agg = summary["fleet"]
    assert len(agg) == 1 and agg[0]["spec"] == "availability"
    assert agg[0]["fast_events"] == 4  # summed across replicas
    assert agg[0]["replicas"] == 2
    assert agg[0]["alert"] is False
    fleet.close()


def test_aggregate_slo_verdicts_weights_burn_by_events():
    base = {"spec": "latency", "objective": "latency", "class": "all",
            "target": 0.99, "burn_threshold": 2.0}
    hot = dict(base, fast_burn=4.0, slow_burn=4.0,
               fast_events=10, slow_events=10, alert=True)
    idle = dict(base, fast_burn=0.0, slow_burn=0.0,
                fast_events=0, slow_events=0, alert=False)
    agg = aggregate_slo_verdicts([[hot], [idle]])
    assert agg[0]["fast_burn"] == 4.0  # the idle replica cannot dilute
    assert agg[0]["fast_events"] == 10
    assert agg[0]["alert"] is True


# ------------------------------------------------- counters and exposition


def test_snapshot_zero_seeds_every_fleet_counter():
    fleet, _, _ = _fleet(replicas=2)
    snap = fleet.snapshot()
    for key in fleet_counter_zeros(2):
        assert key in snap, key
    assert snap["fleet.steals"] == 0
    assert snap["fleet.replica0.alive"] == 1
    assert snap["fleet.replica1.depth"] == 0
    fleet.close()


# -------------------------------------------------- lock-order (layer 5)


def test_router_never_holds_its_lock_into_a_replica_lock():
    """The fleet's deadlock cliff, statically: the committed contract
    shape must contain no FleetFrontend._lock -> AsyncServeFrontend._lock
    edge (the gated defect in fleet.py is excluded from contracts by
    design and exists to prove the gate notices one)."""
    from alphafold2_tpu.analysis.concurrency import compute_contracts

    contracts = compute_contracts()
    forbidden = [
        edge for edge in contracts["lock_graph"]
        if edge.startswith("FleetFrontend._lock ->")
        and "AsyncServeFrontend._lock" in edge
    ]
    assert forbidden == [], forbidden
    # the router's own guarded state IS in the contract
    assert "FleetFrontend" in contracts["guards"]


def test_match_all_fault_plan_hits_any_dispatch():
    plan = FaultPlan(match_all=True, times=2)
    with pytest.raises(Exception):
        plan.on_dispatch(3, 16)
    with pytest.raises(Exception):
        plan.on_dispatch(9, 8)
    plan.on_dispatch(11, 8)  # budget of 2 spent: inert
    assert len(plan.fired) == 2
