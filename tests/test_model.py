"""Model-level tests: coverage model is reference tests/test_attention.py
(test_main, test_msa_tie_row_attn, test_templates, test_reversible), upgraded
with finite-ness and gradient checks; small dims for CPU speed."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu.models import Alphafold2


def _inputs(key, b=1, n=16, m=3, nm=16):
    k1, k2 = jax.random.split(key)
    seq = jax.random.randint(k1, (b, n), 0, 21)
    msa = jax.random.randint(k2, (b, m, nm), 0, 21)
    mask = jnp.ones((b, n), dtype=bool)
    msa_mask = jnp.ones((b, m, nm), dtype=bool)
    return seq, msa, mask, msa_mask


def test_main():
    model = Alphafold2(dim=32, depth=2, heads=2, dim_head=16, max_seq_len=64)
    seq, msa, mask, msa_mask = _inputs(jax.random.key(0))
    params = model.init(jax.random.key(1), seq, msa, mask=mask, msa_mask=msa_mask)
    out = model.apply(params, seq, msa, mask=mask, msa_mask=msa_mask)
    assert out.shape == (1, 16, 16, 37)
    assert np.all(np.isfinite(out))


def test_no_msa():
    # reference train_pre.py path: model(seq, mask=mask) with no MSA at all
    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16, max_seq_len=64)
    seq = jax.random.randint(jax.random.key(0), (2, 12), 0, 21)
    mask = jnp.ones((2, 12), dtype=bool)
    params = model.init(jax.random.key(1), seq, mask=mask)
    out = model.apply(params, seq, mask=mask)
    assert out.shape == (2, 12, 12, 37)


def test_msa_tie_row_attn():
    model = Alphafold2(
        dim=32, depth=2, heads=2, dim_head=16, max_seq_len=64, msa_tie_row_attn=True
    )
    seq, msa, mask, msa_mask = _inputs(jax.random.key(2))
    params = model.init(jax.random.key(3), seq, msa, mask=mask, msa_mask=msa_mask)
    out = model.apply(params, seq, msa, mask=mask, msa_mask=msa_mask)
    assert out.shape == (1, 16, 16, 37)
    assert np.all(np.isfinite(out))


def test_embedds_path():
    # the ESM/PLM path — broken in the reference (SURVEY.md S2.5), works here
    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16, max_seq_len=64, num_embedds=64)
    seq = jax.random.randint(jax.random.key(0), (1, 12), 0, 21)
    embedds = jax.random.normal(jax.random.key(1), (1, 12, 64))
    mask = jnp.ones((1, 12), dtype=bool)
    params = model.init(jax.random.key(2), seq, mask=mask, embedds=embedds)
    out = model.apply(params, seq, mask=mask, embedds=embedds)
    assert out.shape == (1, 12, 12, 37)
    assert np.all(np.isfinite(out))


def test_templates():
    b, n, T = 1, 12, 2
    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16, max_seq_len=64)
    key = jax.random.key(4)
    seq, msa, mask, msa_mask = _inputs(key, b=b, n=n, m=2, nm=n)
    templates_seq = jax.random.randint(jax.random.key(5), (b, T, n), 0, 21)
    templates_coors = jax.random.normal(jax.random.key(6), (b, T, n, 3)) * 5
    templates_mask = jnp.ones((b, T, n), dtype=bool)
    kwargs = dict(
        mask=mask,
        msa_mask=msa_mask,
        templates_seq=templates_seq,
        templates_coors=templates_coors,
        templates_mask=templates_mask,
    )
    params = model.init(jax.random.key(7), seq, msa, **kwargs)
    out = model.apply(params, seq, msa, **kwargs)
    assert out.shape == (b, n, n, 37)
    assert np.all(np.isfinite(out))


def test_templates_with_sidechains():
    b, n, T = 1, 8, 2
    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16, max_seq_len=64)
    seq, msa, mask, msa_mask = _inputs(jax.random.key(8), b=b, n=n, m=2, nm=n)
    kwargs = dict(
        mask=mask,
        msa_mask=msa_mask,
        templates_seq=jax.random.randint(jax.random.key(9), (b, T, n), 0, 21),
        templates_coors=jax.random.normal(jax.random.key(10), (b, T, n, 3)) * 5,
        templates_mask=jnp.ones((b, T, n), dtype=bool),
        templates_sidechains=jax.random.normal(jax.random.key(11), (b, T, n, 3)),
    )
    params = model.init(jax.random.key(12), seq, msa, **kwargs)
    out = model.apply(params, seq, msa, **kwargs)
    assert out.shape == (b, n, n, 37)
    assert np.all(np.isfinite(out))


def test_grad_flows():
    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16, max_seq_len=64)
    seq, msa, mask, msa_mask = _inputs(jax.random.key(13), n=8, nm=8)
    params = model.init(jax.random.key(14), seq, msa, mask=mask, msa_mask=msa_mask)

    def loss(p):
        return jnp.sum(model.apply(p, seq, msa, mask=mask, msa_mask=msa_mask))

    g = jax.grad(loss)(params)
    leaves = jax.tree.leaves(g)
    assert all(np.all(np.isfinite(l)) for l in leaves)
    assert any(np.any(l != 0) for l in leaves)


def test_cross_attn_compression():
    model = Alphafold2(
        dim=32, depth=1, heads=2, dim_head=16, max_seq_len=64,
        cross_attn_compress_ratio=2,
    )
    seq, msa, mask, msa_mask = _inputs(jax.random.key(15), n=10, m=3, nm=10)
    params = model.init(jax.random.key(16), seq, msa, mask=mask, msa_mask=msa_mask)
    out = model.apply(params, seq, msa, mask=mask, msa_mask=msa_mask)
    assert out.shape == (1, 10, 10, 37)
    assert np.all(np.isfinite(out))


def test_distogram_symmetric_under_symmetric_mask():
    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16, max_seq_len=64)
    seq, msa, mask, msa_mask = _inputs(jax.random.key(17), n=8, nm=8)
    params = model.init(jax.random.key(18), seq, msa, mask=mask, msa_mask=msa_mask)
    out = model.apply(params, seq, msa, mask=mask, msa_mask=msa_mask)
    assert np.allclose(out, np.swapaxes(out, 1, 2), atol=1e-4)


def test_axial_attention_broadcast_context():
    """AxialAttention's optional cross-attention context is broadcast to
    every row/column pass (reference alphafold2.py:270-276): runs, is
    finite, differentiable, and masked context changes nothing where the
    context is fully masked out vs absent-key baseline shapes."""
    from alphafold2_tpu.ops.attention import AxialAttention

    k = jax.random.key(31)
    x = jax.random.normal(jax.random.fold_in(k, 0), (2, 6, 6, 16))
    ctx = jax.random.normal(jax.random.fold_in(k, 1), (2, 5, 16))
    ctx_mask = jnp.ones((2, 5), bool).at[:, 3:].set(False)
    mod = AxialAttention(dim=16, heads=2, dim_head=8, use_flash=False)
    params = mod.init(jax.random.fold_in(k, 2), x, context=ctx,
                      context_mask=ctx_mask)
    out = mod.apply(params, x, context=ctx, context_mask=ctx_mask)
    assert out.shape == x.shape
    assert np.isfinite(np.asarray(out)).all()

    # masked-out context columns must not influence the output
    ctx2 = ctx.at[:, 3:].set(123.0)
    out2 = mod.apply(params, x, context=ctx2, context_mask=ctx_mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2), atol=1e-5)

    g = jax.grad(
        lambda c: jnp.sum(
            mod.apply(params, x, context=c, context_mask=ctx_mask) ** 2
        )
    )(ctx)
    assert np.isfinite(np.asarray(g)).all()


def test_templates_explicit_distogram():
    """User-supplied template distance buckets skip auto-binning (reference
    alphafold2.py:508-509) and produce the same result as pre-bucketing the
    coordinates manually."""
    from alphafold2_tpu.utils.structure import get_bucketed_distance_matrix

    b, n, T = 1, 8, 2
    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16, max_seq_len=32,
                       template_attn_depth=1, use_se3_template_embedder=False,
                       use_flash=False)
    k = jax.random.key(41)
    seq = jax.random.randint(jax.random.fold_in(k, 0), (b, n), 0, 21)
    msa = jax.random.randint(jax.random.fold_in(k, 1), (b, 2, n), 0, 21)
    t_seq = jax.random.randint(jax.random.fold_in(k, 2), (b, T, n), 0, 21)
    t_coors = jax.random.normal(jax.random.fold_in(k, 3), (b, T, n, 3)) * 5
    masks = dict(
        mask=jnp.ones((b, n), bool), msa_mask=jnp.ones((b, 2, n), bool),
        templates_mask=jnp.ones((b, T, n), bool),
    )
    params = model.init(k, seq, msa, templates_seq=t_seq,
                        templates_coors=t_coors, **masks)
    out_auto = model.apply(params, seq, msa, templates_seq=t_seq,
                           templates_coors=t_coors, **masks)
    t_dist = jnp.maximum(
        get_bucketed_distance_matrix(t_coors, masks["templates_mask"]), 0
    )
    out_explicit = model.apply(params, seq, msa, templates_seq=t_seq,
                               templates_coors=t_coors, templates_dist=t_dist,
                               **masks)
    np.testing.assert_allclose(np.asarray(out_auto), np.asarray(out_explicit),
                               atol=1e-5)
