"""Training-step tests: loss correctness, jitted step runs and learns,
checkpoint round-trip, config overrides. All single-compile, tiny shapes."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu.config import Config, DataConfig, MeshConfig, ModelConfig, TrainConfig
from alphafold2_tpu.data.pipeline import SyntheticDataset
from alphafold2_tpu.train.loop import (
    build_model,
    device_put_batch,
    distogram_cross_entropy,
    init_state,
    make_train_step,
)


def tiny_config(**model_kw):
    return Config(
        model=ModelConfig(
            dim=32, depth=1, heads=2, dim_head=16, max_seq_len=64,
            bfloat16=False, **model_kw,
        ),
        data=DataConfig(crop_len=16, msa_depth=2, msa_len=16, batch_size=2,
                        min_len_filter=8),
        train=TrainConfig(gradient_accumulate_every=1, warmup_steps=2),
    )


def test_distogram_cross_entropy_ignore_index():
    logits = jnp.zeros((1, 2, 2, 37))
    labels = jnp.array([[[0, -100], [-100, 5]]])
    loss = distogram_cross_entropy(logits, labels)
    # uniform logits -> CE = log(37) over the 2 valid entries
    assert np.isclose(float(loss), np.log(37), atol=1e-5)
    # all-ignored -> 0, not NaN
    assert float(distogram_cross_entropy(logits, jnp.full((1, 2, 2), -100))) == 0.0


def test_train_step_runs_and_learns():
    cfg = tiny_config()
    ds = iter(SyntheticDataset(cfg.data, seed=0))
    batch = next(ds)
    model = build_model(cfg)
    state = init_state(cfg, model, batch)
    step = make_train_step(model)
    dev = device_put_batch(batch)
    rng = jax.random.key(0)

    losses = []
    for i in range(8):
        rng, r = jax.random.split(rng)
        state, metrics = step(state, dev, r)
        losses.append(float(metrics["loss"]))
        assert bool(metrics["grads_ok"])
    # same batch repeated: loss must drop
    assert losses[-1] < losses[0], losses
    assert int(state.skipped) == 0


@pytest.fixture
def tiny_step_setup():
    """Everything the guarded tests below must NOT do inside the guard:
    data synthesis, param init (jax.random.key transfers its seed scalar),
    step construction and the explicit device_put of the batch."""
    cfg = tiny_config()
    batch = next(iter(SyntheticDataset(cfg.data, seed=0)))
    model = build_model(cfg)
    state = init_state(cfg, model, batch)
    step = make_train_step(model)
    return model, state, step, device_put_batch(batch), jax.random.key(0)


def test_train_step_transfer_guard_clean(
    tiny_step_setup, no_implicit_transfers
):
    """Compile + execute the train step under jax.transfer_guard
    ("disallow"): the jitted step must not depend on any implicit
    host->device transfer (flax's python-int TrainState.step was exactly
    such a leak until init_state pinned it on device)."""
    _, state, step, dev, rng = tiny_step_setup
    state, metrics = step(state, dev, rng)
    state, metrics = step(state, dev, rng)
    assert bool(metrics["grads_ok"])
    assert np.isfinite(float(metrics["loss"]))


@pytest.mark.slow
def test_train_grad_strict_promotion(tiny_step_setup, strict_promotion):
    """Forward + distogram loss + backward trace cleanly under strict
    dtype promotion — the first-party surface of the train step (the optax
    update is waived upstream: see analysis/targets.py train_step
    allow_reasons)."""
    from alphafold2_tpu.train.loop import distogram_cross_entropy
    from alphafold2_tpu.utils.structure import get_bucketed_distance_matrix

    model, state, _, dev, rng = tiny_step_setup

    def loss_fn(params):
        logits = model.apply(
            params, dev["seq"], dev.get("msa"), mask=dev["mask"],
            msa_mask=dev.get("msa_mask"), deterministic=False,
            rngs={"dropout": rng},
        )
        labels = get_bucketed_distance_matrix(dev["coords"], dev["mask"])
        return distogram_cross_entropy(logits, labels)

    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    assert np.isfinite(float(loss))
    assert all(
        bool(jnp.all(jnp.isfinite(g))) for g in jax.tree.leaves(grads)
    )


def test_train_step_skips_nonfinite():
    cfg = tiny_config()
    ds = iter(SyntheticDataset(cfg.data, seed=0))
    batch = next(ds)
    model = build_model(cfg)
    state = init_state(cfg, model, batch)
    step = make_train_step(model)
    # poison one parameter leaf -> non-finite forward -> non-finite grads
    flat = jax.tree.leaves(state.params)
    poisoned = jax.tree.unflatten(
        jax.tree.structure(state.params),
        [l.at[(0,) * l.ndim].set(np.nan) if i == 0 else l
         for i, l in enumerate(flat)],
    )
    # snapshot before the step: the step donates its input state, so the
    # poisoned device buffers are deleted after the call
    before = [np.asarray(l) for l in jax.tree.leaves(poisoned)]
    bad_state = state.replace(params=poisoned)
    state2, metrics = step(bad_state, device_put_batch(batch), jax.random.key(1))
    assert not bool(metrics["grads_ok"])
    assert int(state2.skipped) == 1
    # params unchanged on skip (grads zeroed; only opt-state counters move)
    for a, b in zip(before, jax.tree.leaves(state2.params)):
        assert np.allclose(a, b, equal_nan=True)


def test_checkpoint_roundtrip(tmp_path):
    from alphafold2_tpu.train.checkpoint import CheckpointManager

    cfg = tiny_config()
    ds = iter(SyntheticDataset(cfg.data, seed=0))
    batch = next(ds)
    model = build_model(cfg)
    state = init_state(cfg, model, batch)
    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=2)
    mgr.save(3, state)
    mgr.wait()
    restored, step = mgr.maybe_restore(state)
    assert step == 3
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(restored.params)):
        assert np.allclose(a, b)
    mgr.close()


def test_config_overrides_and_roundtrip():
    cfg = Config()
    cfg2 = cfg.apply_overrides(
        ["model.depth=12", "train.learning_rate=1e-4", "model.remat=true",
         "data.source=synthetic"]
    )
    assert cfg2.model.depth == 12
    assert cfg2.model.remat is True
    assert np.isclose(cfg2.train.learning_rate, 1e-4)
    cfg3 = Config.from_json(cfg2.to_json())
    assert cfg3.model.depth == 12


@pytest.mark.slow
def test_ingraph_multistep_matches_sequential():
    """bench.py's lax.scan-chained stepping == the same steps dispatched
    one jit call at a time (same rng schedule, same params)."""
    cfg = tiny_config()
    batch = next(iter(SyntheticDataset(cfg.data, seed=3)))
    model = build_model(cfg)
    raw_step = make_train_step(model, mesh=None, jit=False)
    dev_batch = device_put_batch(batch)
    rng = jax.random.key(11)
    keys = jax.random.split(rng, 3)

    state_a = init_state(cfg, model, batch)
    seq_step = jax.jit(raw_step)
    for r in keys:
        state_a, _ = seq_step(state_a, dev_batch, r)

    state_b = init_state(cfg, model, batch)

    def multi(state, batch, ks):
        def body(st, r):
            st, metrics = raw_step(st, batch, r)
            return st, metrics["loss"]

        return jax.lax.scan(body, state, ks)

    state_b, losses = jax.jit(multi)(state_b, dev_batch, keys)
    assert losses.shape == (3,)
    for a, b in zip(jax.tree.leaves(state_a.params), jax.tree.leaves(state_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_device_prefetch_order_and_exhaustion():
    from alphafold2_tpu.train.loop import device_prefetch

    batches = [{"seq": np.full((1, 4), i)} for i in range(5)]
    got = [int(b["seq"][0, 0]) for b in device_prefetch(iter(batches), size=2)]
    assert got == [0, 1, 2, 3, 4]
    # shorter than the prefetch depth
    got = [int(b["seq"][0, 0]) for b in device_prefetch(iter(batches[:1]), size=3)]
    assert got == [0]
    assert list(device_prefetch(iter([]), size=2)) == []


def test_bench_preflight_switches_compile_mode(monkeypatch):
    """bench.py's preflight: a dead remote-compile endpoint with a working
    client-compile mode must re-exec with PALLAS_AXON_REMOTE_COMPILE=0
    (the observed round-2 failure mode: init fine, first compile hangs)."""
    import subprocess
    import types

    import bench

    monkeypatch.setenv("PALLAS_AXON_REMOTE_COMPILE", "1")
    monkeypatch.delenv("AF2TPU_PLATFORM", raising=False)
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.delenv("AF2TPU_NO_PREFLIGHT", raising=False)

    calls = []

    def fake_run(cmd, env=None, timeout=None, capture_output=None):
        mode = (env or {}).get("PALLAS_AXON_REMOTE_COMPILE")
        calls.append(mode)
        # remote mode (1) broken; client mode (0) healthy
        return types.SimpleNamespace(returncode=0 if mode == "0" else 1)

    execs = []
    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setattr(
        bench.os, "execv", lambda *a: execs.append(a) or (_ for _ in ()).throw(SystemExit)
    )
    import pytest as _pytest

    with _pytest.raises(SystemExit):
        bench._preflight_compile_mode()
    assert calls == ["1", "0"]
    assert bench.os.environ["PALLAS_AXON_REMOTE_COMPILE"] == "0"
    assert execs  # re-exec'd

    # healthy remote mode: no re-exec
    calls.clear()
    execs.clear()
    monkeypatch.setenv("PALLAS_AXON_REMOTE_COMPILE", "1")
    monkeypatch.setattr(
        subprocess, "run",
        lambda *a, **kw: types.SimpleNamespace(returncode=0),
    )
    bench._preflight_compile_mode()
    assert not execs
