"""Chunked (online-softmax) attention tests: exactness vs the dense path,
mask semantics, gradients, the size-gated routing, and the SE(3) refiner's
streamed edge attention — the long-chain enablement layer (ops/chunked.py)
that keeps 512+ serve buckets out of dense-logits memory off-TPU."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import alphafold2_tpu.ops.chunked as chunked_mod
from alphafold2_tpu.ops.chunked import (
    chunked_attention,
    chunked_attn_fn,
    should_chunk,
)


def _dense(q, k, v, kv_mask, scale):
    dots = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if kv_mask is not None:
        dots = jnp.where(kv_mask[:, None, None, :], dots, -1e9)
    attn = jax.nn.softmax(dots, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", attn, v.astype(jnp.float32)).astype(
        q.dtype
    )


@pytest.fixture()
def qkv():
    rng = np.random.default_rng(0)
    b, h, nq, nk, d = 2, 3, 37, 53, 8
    q = jnp.asarray(rng.normal(size=(b, h, nq, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, nk, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, nk, d)), jnp.float32)
    kv_mask = jnp.asarray(rng.random((b, nk)) > 0.3)
    return q, k, v, kv_mask


@pytest.mark.parametrize("qc,kc", [(8, 16), (37, 53), (5, 7), (None, None)])
def test_chunked_matches_dense(qkv, qc, kc):
    """Exact to float reassociation across chunk geometries, including
    ragged final chunks and the auto-sized default."""
    q, k, v, kv_mask = qkv
    scale = q.shape[-1] ** -0.5
    ref = _dense(q, k, v, kv_mask, scale)
    out = chunked_attention(
        q, k, v, kv_mask=kv_mask, sm_scale=scale, q_chunk=qc, kv_chunk=kc
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-6)


def test_chunked_unmasked_and_query_mask(qkv):
    q, k, v, kv_mask = qkv
    scale = q.shape[-1] ** -0.5
    # no masks at all
    out = chunked_attention(q, k, v, sm_scale=scale, q_chunk=16, kv_chunk=8)
    ref = _dense(q, k, v, None, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-6)
    # masked queries emit zeros (the flash SegmentIds convention); valid
    # queries are untouched by the q_mask
    rng = np.random.default_rng(1)
    q_mask = jnp.asarray(rng.random(q.shape[0::2][:1] + (q.shape[2],)) > 0.4)
    q_mask = jnp.asarray(rng.random((q.shape[0], q.shape[2])) > 0.4)
    out = chunked_attention(
        q, k, v, q_mask=q_mask, kv_mask=kv_mask, sm_scale=scale,
        q_chunk=8, kv_chunk=8,
    )
    ref = _dense(q, k, v, kv_mask, scale)
    qm = np.asarray(q_mask)
    assert np.all(np.asarray(out)[~qm[:, None, :].repeat(q.shape[1], 1)] == 0)
    valid = np.broadcast_to(qm[:, None, :, None], ref.shape)
    np.testing.assert_allclose(
        np.asarray(out)[valid], np.asarray(ref)[valid], atol=2e-6
    )


def test_chunked_gradients_match_dense(qkv):
    q, k, v, kv_mask = qkv
    scale = q.shape[-1] ** -0.5

    g1 = jax.grad(
        lambda q: chunked_attention(
            q, k, v, kv_mask=kv_mask, sm_scale=scale, q_chunk=8, kv_chunk=8
        ).sum()
    )(q)
    g2 = jax.grad(lambda q: _dense(q, k, v, kv_mask, scale).sum())(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=5e-6)


def test_should_chunk_threshold_and_grid_hook(qkv, monkeypatch):
    """Routing: small shapes stay dense (the committed graph fingerprints
    depend on it); the grid attn_fn declines below threshold and computes
    above it."""
    q, k, v, kv_mask = qkv
    assert not should_chunk(4, 192, 192)  # single-device serve shapes
    assert should_chunk(1, 2_359_296, 1024)  # bucket-512 cross-attention
    fn = chunked_attn_fn(q.shape[-1] ** -0.5)
    assert fn(q, k[:, :, : q.shape[2]], v[:, :, : q.shape[2]], None) is None
    monkeypatch.setattr(chunked_mod, "CHUNK_THRESHOLD", 1)
    out = fn(q, q, q, kv_mask[:, : q.shape[2]])
    assert out is not None and out.shape == q.shape


def test_attention_module_chunked_branch_matches_dense(monkeypatch):
    """ops.attention.Attention routes through the chunked path above the
    threshold with identical results (same params, same inputs)."""
    from alphafold2_tpu.ops.attention import Attention

    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(2, 40, 16)), jnp.float32)
    ctx = jnp.asarray(rng.normal(size=(2, 23, 16)), jnp.float32)
    cmask = jnp.asarray(rng.random((2, 23)) > 0.3)
    mod = Attention(dim=16, heads=2, dim_head=8, use_flash=False)
    params = mod.init(jax.random.key(0), x, context=ctx, context_mask=cmask)
    dense = mod.apply(params, x, context=ctx, context_mask=cmask)
    monkeypatch.setattr(chunked_mod, "CHUNK_THRESHOLD", 1)
    streamed = mod.apply(params, x, context=ctx, context_mask=cmask)
    np.testing.assert_allclose(
        np.asarray(streamed), np.asarray(dense), atol=2e-5
    )


def test_grid_axial_chunked_matches_dense(monkeypatch):
    """The sharded axial passes' attn_fn hook: chunked per-device kernels
    inside grid_axial_attention equal the dense meshless result."""
    from alphafold2_tpu.ops.attention import AxialAttention

    rng = np.random.default_rng(3)
    n = 8
    x = jnp.asarray(rng.normal(size=(2, n, n, 16)), jnp.float32)
    mask = jnp.ones((2, n, n), bool).at[:, :, -2:].set(False)
    mod = AxialAttention(
        dim=16, heads=2, dim_head=8, grid_parallel=True, use_flash=False
    )
    params = mod.init(jax.random.key(1), x, mask=mask)
    dense = mod.apply(params, x, mask=mask)
    monkeypatch.setattr(chunked_mod, "CHUNK_THRESHOLD", 1)
    from alphafold2_tpu.parallel.grid_parallel import make_grid_mesh
    from alphafold2_tpu.parallel.sharding import use_mesh

    mesh = make_grid_mesh(2, 2, 2)
    with use_mesh(mesh):
        sharded = jax.jit(lambda x: mod.apply(params, x, mask=mask))(x)
    valid = np.asarray(mask)[..., None]
    np.testing.assert_allclose(
        np.asarray(sharded) * valid, np.asarray(dense) * valid, atol=2e-5
    )


def test_se3_streamed_matches_dense(monkeypatch):
    """The SE(3) refiner's streamed edge attention (rel/RBF/logits tiles +
    shared online softmax across all three aggregations) is exact vs the
    dense layer, with ragged edge blocks, and owns the identical parameter
    tree."""
    from alphafold2_tpu.models.se3 import EquivariantLayer

    rng = np.random.default_rng(4)
    b, n, ds, dv = 2, 50, 24, 4
    s = jnp.asarray(rng.normal(size=(b, n, ds)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, n, dv, 3)), jnp.float32)
    coords = jnp.asarray(rng.normal(size=(b, n, 3)), jnp.float32)
    mask = jnp.asarray(rng.random((b, n)) > 0.2)

    dense_mod = EquivariantLayer(dim=16, vec_dim=dv, heads=2)
    params = dense_mod.init(jax.random.key(2), s, v, coords, mask=mask)
    s_ref, v_ref = dense_mod.apply(params, s, v, coords, mask=mask)

    monkeypatch.setattr(chunked_mod, "CHUNK_THRESHOLD", 1)
    # edge_block 16 with n=50: ragged final tiles on both loop axes
    stream_mod = EquivariantLayer(dim=16, vec_dim=dv, heads=2, edge_block=16)
    p2 = stream_mod.init(jax.random.key(2), s, v, coords, mask=mask)
    assert jax.tree_util.tree_structure(params) == (
        jax.tree_util.tree_structure(p2)
    )
    s_out, v_out = stream_mod.apply(params, s, v, coords, mask=mask)
    # valid region exact; masked-query rows are garbage-by-contract in
    # BOTH paths (dense attends them uniformly over real keys, streamed
    # over padded keys) and every downstream read masks them out
    m = np.asarray(mask)
    sm = np.broadcast_to(m[:, :, None], s_ref.shape)
    vm = np.broadcast_to(m[:, :, None, None], v_ref.shape)
    np.testing.assert_allclose(
        np.asarray(s_out)[sm], np.asarray(s_ref)[sm], atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(v_out)[vm], np.asarray(v_ref)[vm], atol=1e-5
    )
    # unmasked: every row is valid -> full-tensor equality (ragged
    # padding rows are sliced off and padded keys masked internally)
    s_ref2, v_ref2 = dense_mod.apply(params, s, v, coords)
    s_out2, v_out2 = stream_mod.apply(params, s, v, coords)
    np.testing.assert_allclose(np.asarray(s_out2), np.asarray(s_ref2),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(v_out2), np.asarray(v_ref2),
                               atol=1e-5)
